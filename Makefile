.PHONY: test bench native dashboard golden clean run-mock

test:
	python -m pytest tests/ -q

bench: native
	python bench.py

native:
	$(MAKE) -C kube_gpu_stats_tpu/native

dashboard:
	cd deploy/grafana && python build_dashboard.py

golden:
	GOLDEN_UPDATE=1 python -m pytest tests/test_golden.py -q

run-mock: native
	python -m kube_gpu_stats_tpu --backend mock --listen-port 9400

clean:
	$(MAKE) -C kube_gpu_stats_tpu/native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
