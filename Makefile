.PHONY: test bench bench-quick profile-tick profile-ingest trace-tick native dashboard golden clean run-mock ci chaos lint fleet-sim federation-sim energy-sim host-sim chaos-sim partition-sim skew-sim local-sim cardinality-sim query-sim bench-diff

# The full gate .github/workflows/ci.yaml encodes, runnable offline:
# native build, suite (goldens diffed), zero-NVML grep, chart checks
# (helm render when the binary exists, the static chart tests always),
# wheel + console-script smoke in a scratch venv (no index needed).
ci: native lint bench-diff
	python -m pytest tests/ -q -m 'not chaos'
	python tools/fleet_sim.py
	python tools/federation_sim.py
	python tools/energy_sim.py
	python tools/host_sim.py
	python tools/chaos_sim.py
	python tools/partition_sim.py
	python tools/skew_sim.py
	python tools/localfault_sim.py
	python tools/cardinality_sim.py
	python tools/query_sim.py
	@if command -v helm >/dev/null 2>&1; then \
	    helm template deploy/helm/kube-tpu-stats >/dev/null && \
	    echo 'helm render: ok'; \
	else \
	    echo 'helm binary absent: chart pinned by tests/test_helm_chart.py'; \
	fi
	python bench.py | python -c "import json,sys; \
	    line = json.loads(sys.stdin.readline()); \
	    assert line['metric'] and line['value'] > 0, line"
	rm -rf build/ci-venv dist && \
	    python -m venv --system-site-packages build/ci-venv
	pip wheel --no-deps --no-build-isolation -w dist . >/dev/null
	build/ci-venv/bin/pip install --no-index --no-deps dist/*.whl >/dev/null
	build/ci-venv/bin/python -I -c "import kube_gpu_stats_tpu as m; \
	    assert 'ci-venv' in m.__file__, \
	    'wheel smoke resolved another copy, not the wheel: ' + m.__file__"
	build/ci-venv/bin/kube-tpu-stats --help >/dev/null
	@echo "make ci: all gates green"

test:
	python -m pytest tests/ -q

# Fault-injection / soak suite (the `chaos` pytest marker): libtpu
# restarts, kubelet socket loss, hung collectors, supervisor respawns.
# Runs everything `make ci` deliberately skips for speed.
chaos: native
	python -m pytest tests/ -q -m chaos

bench: native
	python bench.py

# Fleet-lens smoke, three scenarios, all inside `make ci`:
# straggler — N real daemons (fake libtpu + FakeKubelet attribution) +
# one hub; injects a straggler via a scripted RPC delay and asserts
# `doctor --fleet` names the guilty node with its phase and blamed
# port. link — degrades one shared ICI link from BOTH endpoint daemons'
# fake runtimes (+ NIC drops on both hosts) and asserts the doctor
# names the LINK host-counter-confirmed, accuses zero endpoint nodes,
# and replays the verdict retroactively via `--at` after recovery.
# waste — parks one pod's chips at duty ~0 and asserts
# `doctor --efficiency` names it (and only it) off the signed
# energy/waste attestation, the verdict clears with a journal event on
# recovery, and `--at` replays the incident from the history ring.
fleet-sim:
	python tools/fleet_sim.py --verbose

# Federation smoke (<30 s): N real daemons pushing deltas into two leaf
# hubs, leaves pushing rollups into one --federate root; injects a
# worker restart (generation resync) and a partitioned leaf (pull
# fallback), asserts the root rollup converges and `doctor --fleet`
# walks root -> leaf -> node to name the straggler. In `make ci` too.
federation-sim:
	python tools/federation_sim.py --verbose

# Energy/burst smoke (<30 s): a real daemon (TPU backend over the sysfs
# fixture + fake libtpu, FakeKubelet attribution) with the burst
# sampler continuous; injects a 50 ms power spike between ticks and
# asserts the burst histogram catches it while the 1 Hz gauge provably
# misses it, that per-pod joules survive a daemon restart (checkpoint
# replay), and that `doctor --energy` verifies the signed digest and
# refuses a wrong key. In `make ci` too.
energy-sim:
	python tools/energy_sim.py --verbose

# Fleet chaos smoke (<60 s, ISSUE 12): real daemons + synthesized
# session fleets over real HTTP against the root hub's survival layer.
# Injects a hub kill/restart (asserts warm resume off the WAL
# checkpoint: >= 95% of sessions continue delta chains with no FULL
# resync, zero drops, /readyz gates on replay), a 2x-budget publisher
# stampede (asserts shed-not-crash: 429 + Retry-After, recovery FULLs
# always admitted, no established session dropped), slow-loris sockets
# (cut at the ingest read deadline while healthy pushers land beside
# them), and a corrupt-frame flood (per-source quarantine + journal
# event; same-IP healthy pushers unharmed). In `make ci` too; the
# recovery-time/shed-fairness numbers are pinned in tests/test_latency.
chaos-sim:
	python tools/chaos_sim.py --verbose

# Partition chaos smoke (<60 s, ISSUE 13): the durable egress layer
# end to end — real daemons with disk spill queues through a hub
# blackout (late-but-complete drain: 0 lost, no 409 loop, live deltas
# resume), a beyond-bounds blackout (oldest-first loss, exactly
# accounted in kts_spill_dropped_total + journal), a rate-capped drain
# against an admission-controlled hub (sheds honored, 0 FULL
# amplification), and the durable sharded RemoteWriter through TSDB
# blackouts/flaps/slow links into a fake receiver (exactly-once,
# oldest-first, lag metered, WAL-bound loss accounted). In `make ci`;
# drain-throughput/catch-up numbers are CI-pinned in tests/test_latency
# (bench.measure_partition_drain).
partition-sim:
	python tools/partition_sim.py --verbose

# Host-correlation smoke (<30 s): N real daemons, each over a faked
# /proc + /sys + cgroup v2 host fixture, one hub; after the fleet
# lens's baselines warm, one node gets a simultaneous straggler tick
# (scripted RPC delay) AND a memory-pressure episode (PSI full avg10
# 0 -> 18%); asserts `doctor --fleet` names the node, its worst phase,
# and the PSI co-occurrence in one correlated verdict. In `make ci`.
host-sim:
	python tools/host_sim.py --verbose

# Local fault survival smoke (<60 s, ISSUE 15): a real daemon + hub
# driven through faultfs-injected ENOSPC (spill disk fills mid-drain),
# EIO on the energy checkpoint fsync, an EROFS "remount" under the
# hub's ingest checkpoint, a killed burst-sampler thread, and EMFILE
# on the hub's accept loop. Asserts zero process deaths, every lost
# record counted in kts_store_lost_records_total, every store
# auto-recovering when its fault clears (energy monotone, ingest
# exactly-once), and `doctor --stores` naming each degraded store and
# restarted thread. In `make ci` too.
local-sim:
	python tools/localfault_sim.py --verbose

# Version-skew chaos smoke (<60 s, ISSUE 14): the rolling-upgrade
# survival layer through a real mixed-version matrix — old publisher
# vs new hub (census lists the wire-v1 straggler), new publisher vs
# old/pre-negotiation hubs (hello-clamped / in-push encoding
# downgrade, zero data loss), a daemon upgrade restarting onto an
# old build's spill queue + checkpoints (re-encode, default-and-warn,
# future-major quarantined byte-identical), a hub upgrade under live
# pushers (checkpoint warm resume, 0 resyncs, <= 1 FULL per session,
# census flips without a FULL), and a census-gated 426 refusal that
# doctor --skew names. In `make ci` too.
skew-sim:
	python tools/skew_sim.py --verbose

# Cardinality-admission smoke (<60 s, ISSUE 16): a real hub under a
# 1M-unique-series label bomb from 2 of 16 pushers — over-budget FULLs
# clamped to their admitted prefix, ledger-growing frames refused 413
# at the hard cap before any parse, every dropped series accounted
# with the exported kts_cardinality_shed_total counters exactly equal
# to the in-process and /debug/cardinality ledgers, RSS growth under a
# pinned bound, the 14 healthy pushers byte-identical to a bomb-free
# control hub, and idle eviction re-admitting a 413'd late joiner once
# the bomb stops. In `make ci` too.
cardinality-sim:
	python tools/cardinality_sim.py --verbose

# Dashboard-stampede smoke (<30 s, ISSUE 18): 256 keep-alive readers
# polling /query against a LIVE-refreshing hub — p50/p99 pinned (the
# pre-rendered per-(family,window,generation) response cache is the
# mechanism), >= 50% 304s for conditional readers once the generation
# holds, a tightened per-client gate shedding 429 + Retry-After with
# the observed count exactly equal to the gate ledger and the exported
# kts_query_shed_total, and the history ring's slab bytes flat under
# the whole storm. In `make ci` too; the recorded figures live in
# BENCH_r*.json (bench.measure_query_serving) with CI pins in
# tests/test_latency.py.
query-sim:
	python tools/query_sim.py --verbose

# Compare the two newest BENCH_r*.json runs field by field, noise
# bands derived from the BENCH_r* history — CI-GATING (ISSUE 17): a
# PINNED field (ingest storm, scrape p99, poll max_hz, merge cold/p50,
# ingest CPU%) drifting past its band in the bad direction exits
# nonzero unless BENCH_WAIVERS.json names it. In `make ci`. Runbook:
# OPERATIONS.md "Performance ledger".
bench-diff:
	python tools/bench_diff.py --gate

# Perf smoke (<60 s): reduced-tick simulated harness + 64-worker hub
# merge, no real-chip probing. A quick number for iterating on a perf
# change; NOT part of `make ci` (ci runs the full bench) and never a
# BENCH artifact (the line carries quick: true).
bench-quick: native
	python bench.py --quick

# Static gates with no pytest run: the schema/docs sync check (a
# MetricSpec added without regenerating docs/METRICS.md fails here with
# the fix in the message) and the zero-NVML grep.
lint:
	python tools/check_metrics_docs.py
	python tools/check_no_nvml.py
	python tools/check_wal_versions.py
	python tools/check_supervised_threads.py

# Eyeball where tick time goes: 200 simulated ticks through the
# production loop with the flight recorder on, dumped as Chrome
# trace-event JSON (open in chrome://tracing / ui.perfetto.dev).
# profile-tick says WHICH FUNCTIONS; this shows WHEN, per tick phase.
trace-tick: native
	python tools/trace_dump.py --ticks 200 --out /tmp/kts-trace.json

# Localize a tick regression (<30 s): cProfile over a 200-tick
# simulated run (8 chips, in-process fake runtime, zero scripted RPC
# delay so exporter CPU dominates the rows), top-20 by cumulative time.
# bench-quick says THAT the tick moved; this says WHERE. Add --legacy
# for an A/B against the pre-plan builder path.
profile-tick: native
	python tools/profiler.py --ticks 200 --top 20

# Localize an INGEST regression (<30 s): cProfile of the hub's
# handler-thread delta apply path at 1k synthesized push sources
# (decode, session validation, native slot patch), top-20 by
# cumulative time. The bench's delta_ingest_* fields say THAT ingest
# moved; this says WHERE. Add --legacy for an A/B against the
# pure-Python per-slot oracle (--no-native-ingest).
profile-ingest: native
	python tools/profiler.py --ingest --sources 1000 --top 20

native:
	$(MAKE) -C kube_gpu_stats_tpu/native

dashboard:
	cd deploy/grafana && python build_dashboard.py

golden:
	GOLDEN_UPDATE=1 python -m pytest tests/test_golden.py -q

run-mock: native
	python -m kube_gpu_stats_tpu --backend mock --listen-port 9400

clean:
	$(MAKE) -C kube_gpu_stats_tpu/native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
