"""Exposition-contract validator: our own exporter must pass it, and it
must catch the violations it exists to catch."""

from kube_gpu_stats_tpu import validate
from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry


def render_ticks(n=1):
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=2), reg, deadline=5.0)
    texts = []
    for _ in range(n):
        loop.tick()
        texts.append(reg.snapshot().render())
    loop.stop()
    return texts


def test_own_exposition_conforms():
    (text,) = render_ticks()
    assert validate.check(text) == []


def test_monotone_counters_across_ticks():
    first, second = render_ticks(2)
    assert validate.check(second, previous=first) == []


def test_counter_regression_detected():
    first, second = render_ticks(2)
    # Feed the scrapes in reverse order: counters appear to go backwards.
    problems = validate.check(first, previous=second)
    assert any("went backwards" in p for p in problems)


def test_missing_label_detected():
    bad = 'accelerator_duty_cycle{chip="0"} 50\n'
    problems = validate.check(bad)
    assert any("missing labels" in p for p in problems)


def test_unknown_family_detected():
    (text,) = render_ticks()
    bad = text + (
        "accelerator_bogus_metric"
        '{accel_type="",chip="",device_path="",uuid="",pod="",namespace="",'
        'container="",slice="",worker="",topology=""} 1\n'
    )
    problems = validate.check(bad)
    assert any("not in the accelerator_* contract" in p for p in problems)


def test_out_of_range_detected():
    (text,) = render_ticks()
    bad = text.replace(
        "accelerator_duty_cycle{", "accelerator_duty_cycle{", 1
    )
    line = next(l for l in text.splitlines()
                if l.startswith("accelerator_duty_cycle{"))
    bad = text.replace(line, line.rsplit(" ", 1)[0] + " 150")
    problems = validate.check(bad)
    assert any("outside" in p for p in problems)


def test_out_of_range_bandwidth_util_detected():
    (text,) = render_ticks()
    line = next(l for l in text.splitlines()
                if l.startswith("accelerator_memory_bandwidth_utilization{"))
    bad = text.replace(line, line.rsplit(" ", 1)[0] + " 250")
    problems = validate.check(bad)
    assert any("outside" in p for p in problems)


def test_duplicate_series_detected():
    (text,) = render_ticks()
    line = next(l for l in text.splitlines()
                if l.startswith("accelerator_duty_cycle{"))
    problems = validate.check(text + line + "\n")
    assert any("duplicate series" in p for p in problems)


def test_malformed_line_is_a_violation():
    assert validate.check("accelerator_duty_cycle{chip=0} nope") != []


def test_cli_against_file(tmp_path, capsys):
    (text,) = render_ticks()
    path = tmp_path / "scrape.prom"
    path.write_text(text)
    assert validate.main([str(path)]) == 0
    assert "ok:" in capsys.readouterr().out
    path.write_text('accelerator_duty_cycle{chip="0"} 50\n')
    assert validate.main([str(path)]) == 1


def test_trailing_timestamp_accepted():
    line = ('accelerator_duty_cycle{accel_type="t",chip="0",device_path="d",'
            'uuid="",pod="",namespace="",container="",slice="",worker="",'
            'topology=""} 50 1722249600000\n')
    assert validate.check(line) == []


def test_histogram_buckets_checked_for_monotonicity():
    """_bucket/_count series are cumulative; going backwards between two
    scrapes is the counter-reset bug class and must be flagged."""
    from kube_gpu_stats_tpu import validate

    before = (
        'collector_poll_duration_seconds_bucket{le="0.05"} 10\n'
        'collector_poll_duration_seconds_count 12\n'
    )
    after = (
        'collector_poll_duration_seconds_bucket{le="0.05"} 4\n'
        'collector_poll_duration_seconds_count 12\n'
    )
    problems = validate.check(after, previous=before)
    assert any("went backwards" in p for p in problems), problems
    assert validate.check(before, previous=before) == []


def test_slice_rollups_checked_for_ranges_and_labels():
    from kube_gpu_stats_tpu.validate import check

    ok = ('slice_target_up{target="http://a:9400/metrics"} 1\n'
          'slice_duty_cycle_mean{slice="s"} 55.5\n'
          'slice_straggler_ratio{slice="s"} 0.9\n')
    assert check(ok) == []
    bad = ('slice_duty_cycle_mean{slice="s"} 250\n'
           'slice_straggler_ratio{slice="s"} 1.5\n'
           'slice_chips{slice="s",bogus="x"} 4\n'
           'slice_chips{slice="t"} 4\n'
           'slice_chips{slice="t"} 5\n')
    problems = check(bad)
    assert any("outside" in p and "slice_duty_cycle_mean" in p
               for p in problems)
    assert any("outside" in p and "slice_straggler_ratio" in p
               for p in problems)
    assert any("unexpected labels" in p and "bogus" in str(p)
               for p in problems)
    assert any("duplicate series" in p for p in problems)


def test_unknown_slice_family_flagged():
    from kube_gpu_stats_tpu.validate import check

    problems = check('slice_duty_cycle_avg{slice="s"} 50\n')
    assert problems and "not in the slice_* rollup contract" in problems[0]


def test_slice_rollup_missing_labels_flagged():
    from kube_gpu_stats_tpu.validate import check

    problems = check('slice_chips 4\n')
    assert problems and "missing labels" in problems[0]


def test_authed_fetch_refuses_redirects():
    import http.server
    import threading
    import urllib.error

    import pytest

    from kube_gpu_stats_tpu.validate import fetch_exposition

    class Redirector(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            self.send_response(302)
            self.send_header("Location", "http://127.0.0.1:1/steal")
            self.send_header("Content-Length", "0")
            self.end_headers()

    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Redirector)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}/metrics"
    try:
        # With a credential the redirect is refused (the Authorization
        # header must never chase a cross-origin Location).
        with pytest.raises(urllib.error.HTTPError):
            fetch_exposition(url, timeout=5,
                             headers={"Authorization": "Bearer secret"})
    finally:
        server.shutdown()


def test_auth_headers_helper(tmp_path):
    from kube_gpu_stats_tpu.validate import auth_headers

    token = tmp_path / "token"
    token.write_text("tok123\n")
    assert auth_headers(bearer_token_file=str(token)) == {
        "Authorization": "Bearer tok123"}
    pw = tmp_path / "pw"
    pw.write_text("hubpass\n")
    header = auth_headers(username="scraper", password_file=str(pw))
    import base64
    assert header["Authorization"] == "Basic " + base64.b64encode(
        b"scraper:hubpass").decode()
    # Unreadable file: {} and a warning, never a crash.
    assert auth_headers(bearer_token_file=str(tmp_path / "absent")) == {}


def test_auth_headers_survives_binary_credential_file(tmp_path):
    from kube_gpu_stats_tpu.validate import auth_headers

    bad = tmp_path / "token"
    bad.write_bytes(b"\xff\xfe\x00garbage")
    assert auth_headers(bearer_token_file=str(bad)) == {}


def test_validate_cli_authenticates(tmp_path, capsys):
    import hashlib

    from kube_gpu_stats_tpu.collectors.mock import MockCollector
    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.poll import PollLoop
    from kube_gpu_stats_tpu.registry import Registry
    from kube_gpu_stats_tpu.validate import main

    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, deadline=5.0)
    loop.tick()
    server = MetricsServer(
        reg, host="127.0.0.1", port=0, auth_username="ci",
        auth_password_sha256=hashlib.sha256(b"checkpass").hexdigest())
    server.start()
    url = f"http://127.0.0.1:{server.port}/metrics"
    pw = tmp_path / "pw"
    pw.write_text("checkpass")
    try:
        assert main([url, "--auth-username", "ci",
                     "--auth-password-file", str(pw)]) == 0
        capsys.readouterr()
        assert main([url]) == 2  # 401 without credentials
        capsys.readouterr()
        assert main([url, "--auth-username", "ci"]) == 2  # missing file
        capsys.readouterr()
    finally:
        loop.stop()
        server.stop()


def test_fetch_exposition_caps_response_size():
    import http.server
    import threading

    import pytest

    from kube_gpu_stats_tpu.validate import fetch_exposition

    class Firehose(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = b"x" * 4096
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Firehose)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}/metrics"
    try:
        # Under the cap: full body.
        assert len(fetch_exposition(url, timeout=5)) == 4096
        # Over the cap: a ValueError per target, never an OOM.
        with pytest.raises(ValueError, match="exceeds"):
            fetch_exposition(url, timeout=5, max_bytes=1024)
    finally:
        server.shutdown()
        server.server_close()


def test_lowercase_authorization_header_still_refuses_redirects():
    import http.server
    import threading
    import urllib.error

    import pytest

    from kube_gpu_stats_tpu.validate import fetch_exposition

    class Redirector(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            self.send_response(302)
            self.send_header("Location", "http://127.0.0.1:1/steal")
            self.send_header("Content-Length", "0")
            self.end_headers()

    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Redirector)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{server.server_address[1]}/metrics"
    try:
        with pytest.raises(urllib.error.HTTPError):
            fetch_exposition(url, timeout=5,
                             headers={"authorization": "Bearer secret"})
    finally:
        server.shutdown()
        server.server_close()


def test_parse_exposition_fuzz_never_crashes():
    """The hub/top feed REMOTE text into parse_exposition: any input must
    either parse or raise ValueError — never another exception type and
    never pathological time (the label regex is backtracking-safe)."""
    import random
    import time

    from kube_gpu_stats_tpu.validate import parse_exposition

    rng = random.Random(0xC0FFEE)
    start = time.monotonic()
    for _ in range(300):
        kind = rng.randrange(3)
        if kind == 0:  # raw bytes
            text = bytes(rng.randrange(256)
                         for _ in range(rng.randrange(0, 300))
                         ).decode("latin-1")
        elif kind == 1:  # structured-ish series lines with junk labels
            labels = "".join(rng.choice('a="b",\\"x{}=') for _ in range(40))
            text = f"metric_{rng.randrange(9)}{{{labels}}} {rng.random()}\n"
        else:  # pathological backslash runs (regex backtracking bait)
            text = 'm{a="' + "\\" * rng.randrange(1, 120) + '"} 1\n'
        try:
            parse_exposition(text)
        except ValueError:
            pass
    assert time.monotonic() - start < 10.0


def test_stale_label_allowed_on_gauges_only():
    """stale="true" (resilience degradation marker) is legal on
    per-device gauges, illegal on counters (a label flip mid-outage
    blinds increase()) and on accelerator_up (the health contract)."""
    base = ('accel_type="tpu",chip="0",device_path="/dev/accel0",uuid="",'
            'pod="",namespace="",container="",slice="",worker="",'
            'topology=""')
    ok = f'accelerator_power_watts{{{base},stale="true"}} 100\n'
    assert validate.check(ok) == []
    bad_counter = (f'accelerator_energy_joules_total{{{base},'
                   f'stale="true"}} 5\n')
    problems = validate.check(bad_counter)
    assert problems and "stale" in problems[0]
    bad_up = f'accelerator_up{{{base},stale="true"}} 0\n'
    problems = validate.check(bad_up)
    assert problems and "stale" in problems[0]


def test_retry_after_seconds_parses_and_bounds():
    """Shed responses carry Retry-After (ISSUE 12); the parser takes
    only the delta-seconds form, never raises, and caps how long one
    bad header can silence a publisher."""
    from kube_gpu_stats_tpu.validate import retry_after_seconds

    assert retry_after_seconds({"Retry-After": "2.5"}) == 2.5
    assert retry_after_seconds({"Retry-After": "0"}) == 0.0
    assert retry_after_seconds({}) == 1.0
    assert retry_after_seconds(None, default=3.0) == 3.0
    # HTTP-date form, garbage, negatives, NaN: the default, not a crash.
    assert retry_after_seconds(
        {"Retry-After": "Wed, 21 Oct 2015 07:28:00 GMT"}) == 1.0
    assert retry_after_seconds({"Retry-After": "-5"}) == 1.0
    assert retry_after_seconds({"Retry-After": "nan"}) == 1.0
    # One hostile header cannot demand an hour of silence.
    assert retry_after_seconds({"Retry-After": "99999"}) == 300.0
