"""check_wal_versions lint (ISSUE 14 satellite): every wal.py writer
call site must stamp a format version — SegmentRing(format_version=),
write_state state dicts with a 'version' key. The lint is the static
half; wal.write_state's runtime raise is the backstop."""

import pathlib
import subprocess
import sys
import textwrap

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "tools"))

import check_wal_versions  # noqa: E402


def _check(tmp_path, source: str) -> list[str]:
    path = tmp_path / "module.py"
    path.write_text(textwrap.dedent(source))
    return check_wal_versions.check_file(path)


def test_unstamped_segment_ring_flagged(tmp_path):
    problems = _check(tmp_path, """
        from .wal import SegmentRing
        ring = SegmentRing("/d", max_bytes=1)
    """)
    assert len(problems) == 1
    assert "format_version" in problems[0]


def test_stamped_segment_ring_passes(tmp_path):
    assert _check(tmp_path, """
        from .wal import SegmentRing
        ring = SegmentRing("/d", max_bytes=1, format_version=2)
    """) == []


def test_write_state_with_literal_stamp_passes(tmp_path):
    assert _check(tmp_path, """
        from . import wal
        wal.write_state("/p", {"version": 3, "seq": 1})
    """) == []


def test_write_state_unstamped_literal_flagged(tmp_path):
    problems = _check(tmp_path, """
        from . import wal
        wal.write_state("/p", {"seq": 1})
    """)
    assert len(problems) == 1
    assert "version" in problems[0]


def test_write_state_through_local_state_function_passes(tmp_path):
    """The energy.py shape: state built by a method whose returned
    dict literal carries the stamp."""
    assert _check(tmp_path, """
        from . import wal

        class Store:
            def _state(self):
                return {"version": 2, "data": []}

            def checkpoint(self):
                wal.write_state("/p", self._state())
    """) == []


def test_write_state_untraceable_without_any_stamp_flagged(tmp_path):
    problems = _check(tmp_path, """
        from . import wal

        def save(state):
            wal.write_state("/p", state)
    """)
    assert len(problems) == 1


def test_custom_version_key_respected(tmp_path):
    assert _check(tmp_path, """
        from . import wal
        wal.write_state("/p", {"fmt": 1}, version_key="fmt")
    """) == []
    assert len(_check(tmp_path, """
        from . import wal
        wal.write_state("/p", {"version": 1}, version_key="fmt")
    """)) == 1


def test_lint_green_on_the_real_package():
    """The shipped package must pass its own lint (the make lint
    gate); run the tool as the Makefile does."""
    result = subprocess.run(
        [sys.executable, str(ROOT / "tools" / "check_wal_versions.py")],
        capture_output=True, text=True)
    assert result.returncode == 0, result.stderr
