"""Single-node integration: real poll loop + real HTTP server + mock backend
(SURVEY.md §4 integration tier; BASELINE.json configs[0] end-to-end)."""

import urllib.request

from kube_gpu_stats_tpu.config import Config
from kube_gpu_stats_tpu.daemon import Daemon


def scrape(port):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
        return r.read().decode()


def test_mock_daemon_end_to_end(tmp_path):
    cfg = Config(
        backend="mock",
        mock_devices=4,
        interval=0.05,
        deadline=5.0,
        listen_host="127.0.0.1",
        listen_port=0,
        textfile_dir=str(tmp_path),
        attribution="off",
    )
    d = Daemon(cfg)
    d.start()
    try:
        assert d.registry.wait_for_publish(0, timeout=5)
        # Wait one more tick so ICI rates appear.
        assert d.registry.wait_for_publish(d.registry.generation, timeout=5)
        body = scrape(d.server.port)
        for family in (
            "accelerator_duty_cycle",
            "accelerator_memory_used_bytes",
            "accelerator_memory_total_bytes",
            "accelerator_power_watts",
            "accelerator_ici_link_bandwidth_bytes_per_second",
            "accelerator_up",
            "collector_poll_duration_seconds_bucket",
            "collector_build_info",
        ):
            assert family in body, family
        assert body.count('accelerator_up{') == 4
        # Textfile output mirrors the scrape contract.
        assert d.registry.wait_for_publish(d.registry.generation, timeout=5)
        prom = (tmp_path / "accelerator.prom").read_text()
        assert "accelerator_duty_cycle" in prom
    finally:
        d.stop()


def test_null_daemon_schema_valid(tmp_path):
    cfg = Config(
        backend="null",
        interval=0.05,
        listen_host="127.0.0.1",
        listen_port=0,
        attribution="off",
    )
    d = Daemon(cfg)
    d.start()
    try:
        assert d.registry.wait_for_publish(0, timeout=5)
        body = scrape(d.server.port)
        # No accelerator series, but self-metrics present and well-formed.
        assert "collector_devices 0" in body
        assert "accelerator_up" not in body
    finally:
        d.stop()


def test_auto_backend_upgrades_from_null_when_tpu_appears(tmp_path):
    """Round-2 advisor finding: the libtpu metric service only serves while
    a workload runs, so --backend auto on a sysfs-less TPU VM used to latch
    null for the process lifetime when the daemon started first. The
    upgrade watcher must re-probe and swap in the real backend once the
    service appears."""
    import time

    from kube_gpu_stats_tpu.testing.libtpu_server import FakeLibtpuServer

    server = FakeLibtpuServer(num_chips=2)  # port bound, NOT serving yet
    cfg = Config(
        backend="auto",
        interval=0.05,
        rediscovery_interval=0.1,  # re-probe cadence under test
        listen_host="127.0.0.1",
        listen_port=0,
        sysfs_root=str(tmp_path / "no-sysfs"),
        libtpu_ports=(server.port,),
        attribution="off",
    )
    d = Daemon(cfg)
    assert d.collector.name == "null"
    assert d.upgrade_watcher is not None
    d.start()
    try:
        assert d.registry.wait_for_publish(0, timeout=5)
        assert "accelerator_up" not in scrape(d.server.port)
        server.start()  # the TPU workload arrives
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            body = scrape(d.server.port)
            if body.count("accelerator_up{") == 2:
                break
            time.sleep(0.1)
        else:
            raise AssertionError("auto backend never upgraded from null")
        assert 'backend="tpu"' in body
        assert d.collector.name == "tpu"
    finally:
        d.stop()
        server.stop()
