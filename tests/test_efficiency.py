"""Fleet efficiency lens (ISSUE 20): per-pod waste scoring, idle-
reservation / low-goodput verdicts with warmup + hysteresis, the
UNKNOWN gate (a blind collector must never page a healthy tenant), the
signed federation energy/waste attestation, the hub's leaf-digest fold,
and the doctor's retroactive --at verdict."""

import pytest

from kube_gpu_stats_tpu import doctor, schema
from kube_gpu_stats_tpu.efficiency import (CLEAR_REFRESHES,
                                           EfficiencyLens,
                                           build_attestation)
from kube_gpu_stats_tpu.energy import verify_payload
from kube_gpu_stats_tpu.hub import Hub
from kube_gpu_stats_tpu.registry import SnapshotBuilder


def ev(duty, power=200.0, steps=None, chips=4, joules=None,
       coverage=1.0):
    """One pod's per-refresh evidence dict."""
    return {"duty": duty, "power": power, "steps": steps,
            "chips": chips, "joules": joules, "coverage": coverage}


def lens(**kwargs):
    kwargs.setdefault("warmup_refreshes", 3)
    kwargs.setdefault("idle_refreshes", 2)
    return EfficiencyLens(**kwargs)


def feed(engine, frames):
    """Drive observe() over a list of {key: evidence} frames with a
    deterministic clock; returns all journal events in order."""
    events = []
    now = 1_000_000.0
    for seq, frame in enumerate(frames, start=1):
        now += 10.0
        events.extend(engine.observe(seq, now, frame))
    return events


KEY = ("train-0", "ml")


# -- verdicts ----------------------------------------------------------------

def test_warmup_gate_blocks_early_verdict():
    """A pod idling from birth (model loading, compilation) is never
    accused inside the warmup grace; the verdict lands on the first
    warm refresh once the idle streak is satisfied."""
    engine = lens(warmup_refreshes=3, idle_refreshes=2)
    for seq in range(1, 4):
        events = engine.observe(seq, 1000.0 + seq, {KEY: ev(0.0)})
        assert events == [], f"accused during warmup at refresh {seq}"
    events = engine.observe(4, 1004.0, {KEY: ev(0.0)})
    assert [e[0] for e in events] == ["fleet_waste"]
    kind, detail, attrs = events[0]
    assert attrs["reason"] == "idle-reservation"
    assert attrs["pod"] == "train-0" and attrs["namespace"] == "ml"
    assert "ml/train-0" in detail and "4 chip(s)" in detail


def test_idle_reservation_raises_once_and_clears_with_event():
    engine = lens()
    events = feed(engine, [{KEY: ev(80.0)}] * 4 + [{KEY: ev(0.2)}] * 4)
    assert [e[0] for e in events] == ["fleet_waste"]
    assert "ml/train-0" in engine.suspects()
    # Healthy again: the clear needs CLEAR_REFRESHES consecutive busy
    # refreshes, then journals exactly once.
    events = feed(engine, [{KEY: ev(85.0)}] * (CLEAR_REFRESHES + 2))
    assert [e[0] for e in events] == ["fleet_waste_cleared"]
    assert "chips back in use" in events[0][1]
    assert engine.suspects() == {}
    # The identity keeps exporting a 0.0 tombstone for history reads.
    assert engine.rows() == [("train-0", "ml", "idle-reservation", 0.0)]


def test_one_busy_refresh_resets_the_idle_streak():
    engine = lens(warmup_refreshes=1, idle_refreshes=3)
    frames = ([{KEY: ev(80.0)}] * 2 + [{KEY: ev(0.0)}] * 2
              + [{KEY: ev(80.0)}] + [{KEY: ev(0.0)}] * 2)
    assert feed(engine, frames) == []
    assert engine.observe(99, 2000.0, {KEY: ev(0.0)})[0][0] == \
        "fleet_waste"


def test_low_goodput_needs_a_flat_step_counter():
    """Power drawn and duty up while the step counter is flat is
    low-goodput; an ABSENT counter is unknowable, never flat."""
    stuck = lens()
    events = feed(stuck, [{KEY: ev(80.0, steps=5.0)}] * 3
                  + [{KEY: ev(80.0, steps=0.0)}] * 3)
    assert [e[0] for e in events] == ["fleet_waste"]
    assert events[0][2]["reason"] == "low-goodput"

    no_counter = lens()
    assert feed(no_counter, [{KEY: ev(80.0, steps=None)}] * 10) == []
    assert no_counter.suspects() == {}


def test_departed_pod_clears_its_verdict():
    """Job ended, chips released: that IS the recovery — the verdict
    clears with a journal event and the tombstone rows stay."""
    engine = lens()
    feed(engine, [{KEY: ev(0.0)}] * 6)
    assert "ml/train-0" in engine.suspects()
    events = engine.observe(10, 3000.0, {})
    assert [e[0] for e in events] == ["fleet_waste_cleared"]
    assert "pod departed" in events[0][1]
    assert engine.suspects() == {}
    assert engine.rows() == [("train-0", "ml", "idle-reservation", 0.0)]


# -- the UNKNOWN gate (zero-coverage regression) -----------------------------

def test_blind_collector_scores_unknown_never_wasteful():
    """THE regression (ISSUE 20 bugfix): a pod with no duty evidence
    from any chip AND zero energy coverage must score UNKNOWN —
    counted, never ranked, never accused. A degraded telemetry store
    can never page a healthy tenant."""
    engine = lens(warmup_refreshes=1, idle_refreshes=2)
    blind = {"duty": None, "power": None, "steps": None, "chips": 8,
             "joules": None, "coverage": 0.0}
    events = feed(engine, [{KEY: dict(blind)}] * 20)
    assert events == []
    summary = engine.summary()
    assert summary["unknown_pods"] == 1
    assert summary["pods"]["ml/train-0"]["unknown"] is True
    assert summary["pods"]["ml/train-0"]["score"] is None
    assert summary["suspects"] == {}
    assert summary["top_waste"] == []
    builder = SnapshotBuilder()
    engine.contribute(builder)
    text = builder.build().render()
    assert "kts_fleet_efficiency_unknown_pods 1" in text
    assert "kts_fleet_waste_chips" not in text
    assert "kts_fleet_waste_suspect" not in text


def test_real_zero_duty_is_still_accusable():
    """Duty evidence present — even a hard 0.0 reading — is evidence
    of idleness, not blindness: the idle-reservation verdict must still
    fire (coverage may legitimately be ~0 when burst sampling is off)."""
    engine = lens(warmup_refreshes=1, idle_refreshes=2)
    events = feed(engine, [{KEY: ev(0.0, power=None, coverage=0.0)}] * 4)
    assert [e[0] for e in events] == ["fleet_waste"]


# -- scores ------------------------------------------------------------------

def test_score_scales_duty_by_step_progress():
    busy = lens()
    feed(busy, [{KEY: ev(80.0, steps=9.0)}] * 5)
    stuck = lens()
    feed(stuck, [{KEY: ev(80.0, steps=0.0)}] * 5)
    busy_score = busy.summary()["pods"]["ml/train-0"]["score"]
    stuck_score = stuck.summary()["pods"]["ml/train-0"]["score"]
    assert busy_score == pytest.approx(0.8 * 0.9, abs=1e-6)
    assert stuck_score == 0.0


def test_goodput_rates_steps_per_joule_and_chip_hour():
    engine = lens()
    feed(engine, [{KEY: ev(100.0, power=100.0, steps=10.0,
                           chips=4)}] * 6)
    pod = engine.summary()["pods"]["ml/train-0"]
    assert pod["steps_per_joule"] == pytest.approx(0.1, abs=1e-9)
    assert pod["steps_per_chip_hour"] == pytest.approx(9000.0)


def test_top_k_bounds_per_pod_exports_and_ranks_by_wasted_chips():
    engine = lens(warmup_refreshes=1, top_k=2)
    frame = {
        ("idle-big", "ml"): ev(0.0, chips=8),      # 8 wasted chips
        ("idle-small", "ml"): ev(0.0, chips=2),    # 2 wasted chips
        ("half", "ml"): ev(50.0, chips=2),         # 1 wasted chip
        ("busy", "ml"): ev(100.0, chips=4),        # ~0 wasted
    }
    feed(engine, [dict(frame) for _ in range(4)])
    ranking = engine.summary()["top_waste"]
    assert [r["pod"] for r in ranking] == ["idle-big", "idle-small"]
    assert ranking[0]["wasted_chips"] == pytest.approx(8.0)
    builder = SnapshotBuilder()
    engine.contribute(builder)
    text = builder.build().render()
    score_rows = [line for line in text.splitlines()
                  if line.startswith(schema.FLEET_EFFICIENCY_SCORE.name
                                     + "{")]
    assert len(score_rows) == 2  # top-K bound, not a census


def test_observe_is_deterministic():
    """Identical seeded input sequences produce byte-identical
    summaries and journal events — no wall-clock, no randomness."""
    frames = ([{KEY: ev(70.0, steps=5.0, joules=100.0)}] * 4
              + [{KEY: ev(0.3, steps=0.0, joules=140.0)}] * 4
              + [{KEY: ev(90.0, steps=7.0, joules=200.0)}] * 3)
    a, b = lens(), lens()
    assert feed(a, [dict(f) for f in frames]) == \
        feed(b, [dict(f) for f in frames])
    assert a.summary() == b.summary()
    assert a.rows() == b.rows()


def test_joules_counter_reset_skips_the_interval():
    engine = lens()
    feed(engine, [{KEY: ev(80.0, joules=1000.0)},
                  {KEY: ev(80.0, joules=1400.0)},   # 40 J/s
                  {KEY: ev(80.0, joules=5.0)}])     # reset: skipped
    state = engine._pods[KEY]
    assert state.joules_rate == pytest.approx(40.0)
    assert state.last_joules == 5.0


# -- the signed attestation --------------------------------------------------

LEAF_A = {"per_pod": [["train-0", "ml", 120.0], ["train-1", "ml", 30.0]],
          "coverage_ratio": 0.9, "signed": True, "hmac": "aa" * 32}
LEAF_B = {"per_pod": [["other", "infra", 50.0]],
          "coverage_ratio": 0.4, "signed": False}


def test_attestation_folds_leaves_and_verifies():
    engine = lens(warmup_refreshes=1)
    feed(engine, [{KEY: ev(0.0)}] * 4)
    payload = build_attestation(
        engine.summary(), {"http://a/metrics": dict(LEAF_A),
                           "http://b/metrics": dict(LEAF_B)},
        "fleet-key", node="hub-1", generated_at=123.0, targets_total=5)
    assert payload["totals"] == {
        "joules": pytest.approx(200.0), "pod_totals": 3, "leaves": 2,
        "leaves_signed": 1, "targets_total": 5,
        "coverage_min": pytest.approx(0.4)}
    assert "ml/train-0" in payload["waste"]["suspects"]
    # Leaf digests ride verbatim, their own HMACs intact.
    assert payload["leaves"]["http://a/metrics"]["hmac"] == "aa" * 32
    assert payload["signed"] is True
    assert verify_payload(payload, "fleet-key")
    assert not verify_payload(payload, "wrong-key")
    tampered = dict(payload)
    tampered["totals"] = dict(payload["totals"], joules=1.0)  # shaved
    assert not verify_payload(tampered, "fleet-key")


def test_attestation_unsigned_without_key_and_skips_error_stubs():
    payload = build_attestation(
        lens().summary(),
        {"http://a/metrics": dict(LEAF_A),
         "http://down/metrics": {"error": "connection refused"}}, "")
    assert payload["signed"] is False and "hmac" not in payload
    assert payload["totals"]["joules"] == pytest.approx(150.0)
    assert payload["totals"]["leaves_signed"] == 1
    # The unreachable leaf rides as a stub naming the gap.
    assert payload["leaves"]["http://down/metrics"]["error"]


# -- the hub's leaf fold -----------------------------------------------------

def test_hub_efficiency_payload_folds_leaves_with_stubs_and_caches():
    calls = []

    def fetcher(url):
        calls.append(url)
        if "9001" in url:
            raise OSError("connection refused")
        return dict(LEAF_A)

    hub = Hub(["http://127.0.0.1:9000/metrics",
               "http://127.0.0.1:9001/metrics"],
              interval=3600.0, energy_audit_key="fleet-key")
    try:
        hub._energy_fetcher = fetcher
        payload = hub.efficiency_payload()
        assert payload["totals"]["leaves"] == 2
        assert payload["totals"]["targets_total"] == 2
        assert payload["leaves"][
            "http://127.0.0.1:9001/metrics"]["error"]
        assert verify_payload(payload, "fleet-key")
        # Fetched URLs are the leaves' bases, /metrics stripped.
        assert "http://127.0.0.1:9000/debug/energy" in calls
        # TTL cache: a second scrape re-signs but does not re-fetch.
        before = len(calls)
        assert verify_payload(hub.efficiency_payload(), "fleet-key")
        assert len(calls) == before
    finally:
        hub.stop()


def test_hub_no_efficiency_answers_enabled_false():
    hub = Hub(["http://127.0.0.1:9000/metrics"], interval=3600.0,
              efficiency=False)
    try:
        assert hub.efficiency_payload() == {
            "enabled": False, "reason": "--no-efficiency"}
    finally:
        hub.stop()


# -- doctor: the retroactive --at verdict ------------------------------------

def test_efficiency_at_names_the_accused_pod():
    status, detail, data = doctor.efficiency_at_verdict(
        {"series": [
            {"labels": {"pod": "train-1", "namespace": "ml",
                        "reason": "idle-reservation"},
             "v": 1.0, "t": 1000.0},
            {"labels": {"pod": "train-0", "namespace": "ml",
                        "reason": "idle-reservation"},
             "v": 0.0, "t": 1000.0},   # tombstone: innocent
        ]}, 1000.0)
    assert status == doctor.WARN
    assert "ml/train-1 was wasting chips (idle-reservation" in detail
    assert [s["pod"] for s in data["waste_suspects"]] == ["train-1"]


def test_efficiency_at_all_tombstones_is_a_clean_ok():
    status, detail, _ = doctor.efficiency_at_verdict(
        {"series": [{"labels": {"pod": "train-0", "namespace": "ml",
                                "reason": "idle-reservation"},
                     "v": 0.0, "t": 1000.0}]}, 1000.0)
    assert status == doctor.OK
    assert "no pod was wasting chips" in detail


def test_efficiency_at_empty_ring_warns_about_boot_scope():
    status, detail, _ = doctor.efficiency_at_verdict({"series": []},
                                                     1000.0)
    assert status == doctor.WARN
    assert "no waste samples" in detail
