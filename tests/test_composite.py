"""Composite TPU backend: sysfs + libtpu merged, independent degradation;
plus daemon auto-detection against a fixture tree (configs[1] integration)."""

import pytest

from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.collectors import CollectorError
from kube_gpu_stats_tpu.collectors.composite import TpuCollector
from kube_gpu_stats_tpu.collectors.libtpu import LibtpuClient
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry

from kube_gpu_stats_tpu.testing.libtpu_server import FakeLibtpuServer
from kube_gpu_stats_tpu.testing.sysfs_fixture import make_sysfs


@pytest.fixture(params=["flat", "nested"])
def server(request):
    with FakeLibtpuServer(num_chips=2, dialect=request.param) as s:
        yield s


def make_tpu(tmp_path, server, **kw):
    make_sysfs(tmp_path, num_chips=2)
    return TpuCollector(
        sysfs_root=str(tmp_path),
        libtpu_client=LibtpuClient(ports=(server.port,), rpc_timeout=1.0),
        use_native=False,
        **kw,
    )


def test_merged_sample(tmp_path, server, monkeypatch):
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5p-16")
    col = make_tpu(tmp_path, server)
    devs = col.discover()
    assert len(devs) == 2
    assert devs[0].accel_type == "tpu-v5p"  # sysfs enumeration wins
    assert devs[0].uuid == "tpu-chip-0000"
    col.begin_tick()
    s = col.sample(devs[1])
    # Runtime counters AND sysfs environment in one sample.
    assert s.values[schema.DUTY_CYCLE.name] == 51.0
    assert s.values[schema.POWER.name] == pytest.approx(121.0)
    assert s.values[schema.TEMPERATURE.name] == pytest.approx(45.5)
    assert len(s.ici_counters) == 6
    col.close()


def test_libtpu_down_degrades_to_environment_only(tmp_path, server):
    col = make_tpu(tmp_path, server)
    devs = col.discover()
    server.fail = True
    col.begin_tick()
    s = col.sample(devs[0])
    assert schema.POWER.name in s.values
    assert schema.DUTY_CYCLE.name not in s.values
    col.close()


def test_both_sources_down_is_stale(tmp_path, server):
    col = make_tpu(tmp_path, server)
    devs = col.discover()
    server.fail = True
    import shutil

    shutil.rmtree(tmp_path / "class")
    col.begin_tick()
    with pytest.raises(CollectorError):
        col.sample(devs[0])
    col.close()


def test_through_poll_loop_full_families(tmp_path, server):
    import time

    col = make_tpu(tmp_path, server)
    reg = Registry()
    loop = PollLoop(col, reg, deadline=5.0)
    loop.tick()
    loop.tick()
    # Pipelined cadence: back-to-back manual ticks re-serve the first
    # completed fetch, and a rate needs two DISTINCT fetches — wait for
    # the second tick's fetch to land, then tick again to observe it.
    deadline = time.monotonic() + 5
    while col.runtime_fetch_seq < 2 and time.monotonic() < deadline:
        time.sleep(0.01)
    loop.tick()
    snap = reg.snapshot()
    families = {s.spec.name for s in snap.series}
    for family in (
        "accelerator_duty_cycle",
        "accelerator_memory_used_bytes",
        "accelerator_power_watts",
        "accelerator_temperature_celsius",
        "accelerator_ici_link_bandwidth_bytes_per_second",
        "accelerator_collective_ops_total",
        "accelerator_up",
    ):
        assert family in families, family
    ups = [s.value for s in snap.series if s.spec.name == "accelerator_up"]
    assert ups == [1.0, 1.0]
    loop.stop()


def test_daemon_auto_detects_tpu(tmp_path, server, monkeypatch):
    """--backend auto probes sysfs and builds the TPU backend (E1)."""
    from kube_gpu_stats_tpu.config import Config
    from kube_gpu_stats_tpu.daemon import build_collector

    make_sysfs(tmp_path, num_chips=2)
    monkeypatch.setenv("TPU_RUNTIME_METRICS_PORTS", str(server.port))
    cfg = Config(backend="auto", sysfs_root=str(tmp_path),
                 libtpu_ports=(server.port,), use_native=False)
    col = build_collector(cfg)
    assert col.name == "tpu"
    assert len(col.discover()) == 2
    col.close()


def test_libtpu_only_node_discovers_via_runtime(tmp_path, server):
    """TPU VM variants without /sys/class/accel fall back to runtime
    enumeration."""
    col = TpuCollector(
        sysfs_root=str(tmp_path),  # empty tree
        libtpu_client=LibtpuClient(ports=(server.port,), rpc_timeout=1.0),
        use_native=False,
    )
    devs = col.discover()
    assert len(devs) == 2
    col.begin_tick()
    s = col.sample(devs[0])
    assert schema.DUTY_CYCLE.name in s.values
    assert schema.POWER.name not in s.values
    col.close()


def test_daemon_auto_detects_tpu_without_sysfs(tmp_path, server):
    """Round-1 hole: on TPU VM variants without /sys/class/accel, --backend
    auto must still land on the tpu backend via the bounded libtpu probe —
    detect_tpu and TpuCollector.discover share one definition of "present"."""
    from kube_gpu_stats_tpu.config import Config
    from kube_gpu_stats_tpu.daemon import build_collector, detect_tpu

    cfg = Config(backend="auto", sysfs_root=str(tmp_path),  # empty tree
                 libtpu_ports=(server.port,), use_native=False)
    assert detect_tpu(cfg) is True
    col = build_collector(cfg)
    assert col.name == "tpu"
    assert len(col.discover()) == 2
    col.close()


def test_daemon_auto_falls_to_null_when_nothing_present(tmp_path):
    """No sysfs, no libtpu listener: auto must settle on null quickly
    (bounded probe), never hang or crash."""
    import time

    from kube_gpu_stats_tpu.config import Config
    from kube_gpu_stats_tpu.daemon import build_collector

    cfg = Config(backend="auto", sysfs_root=str(tmp_path),
                 libtpu_ports=(1,),  # nothing listens on port 1
                 use_native=False)
    t0 = time.monotonic()
    col = build_collector(cfg)
    assert col.name == "null"
    assert time.monotonic() - t0 < 5.0
    col.close()
