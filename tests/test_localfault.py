"""Local fault survival (ISSUE 15): the per-store durability state
machine, faultfs-driven store degradation/recovery, the supervisor's
restart-storm latch, the supervised-spawn helper, /debug/stores, and
the accept-loop fd-exhaustion fence."""

from __future__ import annotations

import errno
import json
import logging
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from kube_gpu_stats_tpu import wal
from kube_gpu_stats_tpu.spillq import SpillQueue
from kube_gpu_stats_tpu.testing.faultfs import FaultFS, fence_accepts
from kube_gpu_stats_tpu.wal import SegmentRing


class FakeTracer:
    enabled = True

    def __init__(self):
        self.events = []

    def event(self, kind, detail="", **attrs):
        self.events.append({"kind": kind, "detail": detail, **attrs})


@pytest.fixture
def fast_probe():
    """Every degraded op probes immediately (tests can't wait 5 s)."""
    wal.set_probe_interval(0.0)
    yield
    wal.set_probe_interval(5.0)


# -- StoreHealth unit ---------------------------------------------------------

def test_store_health_classifies_and_transitions():
    health = wal.StoreHealth("t", clock=lambda: 0.0)
    assert health.state == wal.STORE_HEALTHY
    reason = health.record_fault(OSError(errno.ENOSPC, "full"), lost=2)
    assert reason == "disk_full"
    assert health.state == wal.STORE_DEGRADED
    assert health.fault_counts == {"ENOSPC": 1}
    assert health.lost_records == 2
    assert health.episodes == 1
    # Same errno again: counted, but still ONE episode.
    health.record_fault(OSError(errno.ENOSPC, "full"))
    assert health.fault_counts == {"ENOSPC": 2}
    assert health.episodes == 1
    health.ok()
    assert health.state == wal.STORE_HEALTHY
    assert health.recoveries == 1


def test_store_health_probe_gating_uses_the_interval():
    now = [0.0]
    health = wal.StoreHealth("t", clock=lambda: now[0], probe_interval=10.0)
    assert health.allow_io()  # healthy: always
    health.record_fault(OSError(errno.EROFS, "ro"))
    assert not health.allow_io()  # inside the probe window
    now[0] = 10.5
    assert health.allow_io()   # the probe
    assert not health.allow_io()  # window re-armed by the probe
    now[0] = 21.0
    assert health.allow_io()


def test_store_health_logs_once_per_episode(caplog):
    health = wal.StoreHealth("quiet-store", clock=lambda: 0.0)
    with caplog.at_level(logging.WARNING):
        for _ in range(50):
            health.record_fault(OSError(errno.ENOSPC, "full"))
    lines = [r for r in caplog.records
             if "quiet-store degraded" in r.getMessage()]
    assert len(lines) == 1  # a full disk logs once per EPISODE, not per tick
    assert health.fault_counts["ENOSPC"] == 50  # the counter carries the rate


def test_store_health_journals_fault_and_recovery_edges():
    tracer = FakeTracer()
    wal.set_journal(tracer)
    health = wal.StoreHealth("j", clock=lambda: 0.0)
    for _ in range(3):
        health.record_fault(OSError(errno.EIO, "io"))
    health.ok()
    kinds = [e["kind"] for e in tracer.events]
    assert kinds == ["disk_fault", "store_recovered"]
    assert tracer.events[0]["store"] == "j"
    assert tracer.events[0]["errno"] == "EIO"


def test_classify_oserror_taxonomy():
    assert wal.classify_oserror(OSError(errno.ENOSPC, "x")) == \
        ("disk_full", "ENOSPC")
    assert wal.classify_oserror(OSError(errno.EDQUOT, "x")) == \
        ("disk_full", "EDQUOT")
    assert wal.classify_oserror(OSError(errno.EROFS, "x")) == \
        ("read_only", "EROFS")
    assert wal.classify_oserror(OSError(errno.EMFILE, "x")) == \
        ("fd_exhausted", "EMFILE")
    assert wal.classify_oserror(OSError(errno.ENOENT, "x")) == \
        ("io_fault", "ENOENT")
    # The accept fence's whole errno set classifies as fd_exhausted —
    # the /debug/stores reason must match the runbook's triage table.
    assert wal.classify_oserror(OSError(errno.ENOBUFS, "x"))[0] == \
        "fd_exhausted"
    assert wal.classify_oserror(OSError(errno.ENOMEM, "x"))[0] == \
        "fd_exhausted"


# -- write_state under faults -------------------------------------------------

def test_write_state_fault_degrades_instead_of_raising(tmp_path,
                                                       fast_probe):
    path = str(tmp_path / "ck.json")
    with FaultFS() as fs:
        fs.inject(str(tmp_path), "enospc", ops=("write", "fsync"))
        assert not wal.write_state(path, {"version": 1, "seq": 1},
                                   label="ck-test")
        health = wal.store_health("ck-test")
        assert health.state == wal.STORE_DEGRADED
        assert health.reason == "disk_full"
        fs.clear()
        # The fault cleared: the next attempt is the probe and re-arms.
        assert wal.write_state(path, {"version": 1, "seq": 2},
                               label="ck-test")
        assert health.state == wal.STORE_HEALTHY
    assert wal.load_newest(path, 1, label="ck-test")["seq"] == 2


def test_write_state_skips_disk_between_probes(tmp_path):
    """While degraded, write_state must not even touch the disk until
    the probe window — the degraded-mode overhead budget rides on it."""
    path = str(tmp_path / "ck.json")
    health = wal.store_health("gated")
    health.probe_interval = 3600.0
    health.record_fault(OSError(errno.ENOSPC, "full"))
    opens = []
    with FaultFS() as fs:
        rule = fs.inject(str(tmp_path), "enospc", ops=("open",))
        assert not wal.write_state(path, {"version": 1, "seq": 1},
                                   label="gated")
        opens.append(rule.hits)
    assert opens == [0]  # gated out before any open


# -- SegmentRing under faults -------------------------------------------------

def _ring(tmp_path, **kw):
    kw.setdefault("max_bytes", 1 << 20)
    kw.setdefault("segment_bytes", 256)
    kw.setdefault("label", "ring-test")
    kw.setdefault("format_version", 1)
    return SegmentRing(str(tmp_path / "ring"), **kw)


def test_ring_enospc_goes_memory_only_loss_counted(tmp_path, fast_probe):
    with FaultFS() as fs:
        fs.watch(str(tmp_path))
        ring = _ring(tmp_path)
        ring.append(1.0, b"before")  # healthy baseline
        fs.inject(str(tmp_path), "enospc",
                  ops=("open", "write", "fsync"))
        for i in range(5):
            ring.append(2.0 + i, b"during-%d" % i)
        assert ring.health.state == wal.STORE_DEGRADED
        assert ring.health.reason == "disk_full"
        # Telemetry continued in-memory: every record still drains.
        assert ring.records_pending() == 6
        # Durability loss exactly accounted: every degraded-window
        # record is in the ledger.
        assert ring.health.lost_records == 5
        fs.clear()
        ring.append(10.0, b"after")  # the probe: disk is back
        assert ring.health.state == wal.STORE_HEALTHY
        assert ring.health.recoveries == 1
        ring.close()
    # A restart sees exactly the durable set: baseline + post-recovery
    # (the 5 degraded-window records are the counted loss).
    recovered = _ring(tmp_path)
    payloads = []
    while True:
        record = recovered.peek()
        if record is None:
            break
        payloads.append(record[1])
        recovered.commit()
    assert b"before" in payloads
    assert b"after" in payloads
    assert not any(p.startswith(b"during") for p in payloads)
    recovered.close()


def test_ring_eio_quarantines_tail_and_recovers(tmp_path, fast_probe):
    with FaultFS() as fs:
        fs.watch(str(tmp_path))
        ring = _ring(tmp_path)
        ring.append(1.0, b"one")
        fs.inject(str(tmp_path / "ring"), "eio", ops=("write",), times=1)
        ring.append(2.0, b"two")  # EIO -> quarantine + fresh-tail retry
        assert ring.health.fault_counts.get("EIO") == 1
        # The retry landed durably on a fresh segment: recovered in-line.
        assert ring.health.state == wal.STORE_HEALTHY
        quarantined = [name for name in os.listdir(str(tmp_path / "ring"))
                       if ".eioq" in name]
        assert quarantined, "sick tail segment parked aside"
        assert ring.records_pending() == 2  # memory still serves both
        ring.close()


def test_ring_erofs_disables_durability_one_journal_event(tmp_path,
                                                          fast_probe):
    tracer = FakeTracer()
    wal.set_journal(tracer)
    with FaultFS() as fs:
        fs.watch(str(tmp_path))
        ring = _ring(tmp_path)
        ring.append(1.0, b"one")
        fs.inject(str(tmp_path), "erofs", ops=("open", "write", "fsync"))
        wal.set_probe_interval(3600.0)
        for i in range(10):
            ring.append(2.0 + i, b"x%d" % i)
        assert ring.health.reason == "read_only"
        faults = [e for e in tracer.events if e["kind"] == "disk_fault"]
        assert len(faults) == 1  # ONE event for the whole episode
        assert ring.records_pending() == 11
        ring.close()


def test_ring_enospc_sheds_oldest_segment_to_reclaim(tmp_path,
                                                     fast_probe):
    with FaultFS() as fs:
        fs.watch(str(tmp_path))
        # Small segments so several exist before the fault.
        ring = _ring(tmp_path, segment_bytes=64)
        for i in range(10):
            ring.append(float(i), b"p" * 40)
        segments_before = ring.status()["segments"]
        assert segments_before > 1
        fs.inject(str(tmp_path), "enospc", ops=("write", "fsync"))
        dropped = ring.append(99.0, b"p" * 40)
        # The shed is returned to the caller (journaled like an
        # eviction) and counted in both loss ledgers.
        assert dropped > 0
        assert ring.evicted_records == dropped
        assert ring.health.lost_records >= dropped
        assert ring.status()["segments"] < segments_before + 2
        ring.close()


def test_ring_recovery_write_rolls_past_a_gapped_tail(tmp_path,
                                                      fast_probe):
    """Review finding: a degraded window leaves memory-only records in
    the still-open tail segment; the recovery write must land on a
    FRESH segment, or disk and memory record indexes desynchronize and
    a post-crash recovery maps the drain cursor onto the wrong records
    — skipping a durable, undelivered one uncounted."""
    with FaultFS() as fs:
        fs.watch(str(tmp_path))
        ring = _ring(tmp_path)
        ring.append(1.0, b"A")  # durable in the open tail
        fs.inject(str(tmp_path), "erofs", ops=("write",))
        ring.append(2.0, b"B")  # write fails, handle open: memory-only
        assert ring.health.lost_records == 1
        fs.clear()
        ring.append(3.0, b"C")  # the probe: MUST roll to a fresh file
        assert ring.health.state == wal.STORE_HEALTHY
        # Drain A and B (in-memory continuity), persist the cursor —
        # the pre-crash state the finding's scenario needs.
        assert ring.peek()[1] == b"A"
        ring.commit()
        assert ring.peek()[1] == b"B"
        ring.commit()
        ring.close()
    # "Crash" + restart: the durable-but-undelivered C must still be
    # at the cursor (pre-fix, C shared A's file and the clamped cursor
    # skipped it forever, uncounted).
    recovered = _ring(tmp_path)
    record = recovered.peek()
    assert record is not None and record[1] == b"C"
    recovered.close()


def test_ring_torn_write_truncated_on_recovery(tmp_path):
    from kube_gpu_stats_tpu.testing.faultfs import TornWrite

    with FaultFS() as fs:
        fs.watch(str(tmp_path))
        ring = _ring(tmp_path)
        ring.append(1.0, b"good-record")
        fs.inject(str(tmp_path), "torn", ops=("write",), times=1)
        with pytest.raises(TornWrite):
            # The "crash": half the frame lands, the process dies.
            ring.append(2.0, b"torn-record-payload")
    recovered = _ring(tmp_path)
    assert recovered.torn_records >= 1
    record = recovered.peek()
    assert record is not None and record[1] == b"good-record"
    recovered.close()


def test_ring_constructor_survives_unwritable_dir(tmp_path, fast_probe):
    """The audited bug class (satellite): SegmentRing() runs on pool
    workers / handler threads — an EROFS from makedirs must degrade,
    never propagate and kill the constructing thread."""
    with FaultFS() as fs:
        fs.inject(str(tmp_path), "erofs", ops=("makedirs", "open",
                                               "write", "fsync"))
        ring = SegmentRing(str(tmp_path / "newdir"), max_bytes=1 << 20,
                           label="ctor-test", format_version=1)
        assert ring.health.state == wal.STORE_DEGRADED
        ring.append(1.0, b"x")  # still serves, memory-only
        assert ring.records_pending() == 1


def test_ring_recover_survives_unlistable_dir(tmp_path, fast_probe):
    os.makedirs(str(tmp_path / "ring"), exist_ok=True)
    with FaultFS() as fs:
        fs.inject(str(tmp_path), "eio", ops=("listdir",), times=1)
        ring = SegmentRing(str(tmp_path / "ring"), max_bytes=1 << 20,
                           label="recover-test", format_version=1)
    assert ring.health.fault_counts.get("EIO") == 1
    ring.close()


# -- store adoption: spillq + energy -----------------------------------------

def test_spillq_full_disk_survival_and_exact_accounting(tmp_path,
                                                        fast_probe):
    with FaultFS() as fs:
        fs.watch(str(tmp_path))
        spill = SpillQueue(str(tmp_path / "spill"), fsync=True)
        spill.spool(1.0, "body-before")
        fs.inject(str(tmp_path), "enospc",
                  ops=("open", "write", "fsync"))
        for i in range(4):
            spill.spool(2.0 + i, f"body-during-{i}")
        status = spill.status()
        assert status["health"]["state"] == wal.STORE_DEGRADED
        assert status["depth_frames"] == 5  # nothing silently dropped
        assert status["health"]["lost_records"] == 4
        fs.clear()
        spill.spool(10.0, "body-after")
        assert spill.status()["health"]["state"] == wal.STORE_HEALTHY
        # The drain still serves every frame oldest-first.
        drained = []
        while True:
            record = spill.peek()
            if record is None:
                break
            drained.append(record[1])
            spill.commit()
        assert drained[0] == "body-before"
        assert drained[-1] == "body-after"
        assert len(drained) == 6
        spill.close()


def test_energy_checkpoint_eio_defers_and_counters_stay_monotone(
        tmp_path, fast_probe):
    from kube_gpu_stats_tpu.energy import EnergyAccountant

    path = str(tmp_path / "energy.json")
    acct = EnergyAccountant(checkpoint_path=path, checkpoint_interval=0.0)
    acct.observe("dev0", "pod-a", "ns", 1.0, 100.0)
    acct.observe("dev0", "pod-a", "ns", 2.0, 100.0)
    assert acct.checkpoint(force=True)
    joules_before = acct._per_pod[("pod-a", "ns")]
    with FaultFS() as fs:
        fs.inject(str(tmp_path), "eio", ops=("fsync",))
        acct.observe("dev0", "pod-a", "ns", 3.0, 100.0)
        assert not acct.checkpoint(force=True)  # deferred, NOT raised
        assert wal.store_health("energy").state == wal.STORE_DEGRADED
        fs.clear()
        assert acct.checkpoint(force=True)  # probe: re-armed
        assert wal.store_health("energy").state == wal.STORE_HEALTHY
    fresh = EnergyAccountant(checkpoint_path=path)
    assert fresh._per_pod[("pod-a", "ns")] >= joules_before  # monotone


def test_store_metrics_contribution():
    from kube_gpu_stats_tpu import schema
    from kube_gpu_stats_tpu.registry import (SnapshotBuilder,
                                             contribute_store_metrics)

    health = wal.store_health("m-test")
    health.record_fault(OSError(errno.ENOSPC, "full"), lost=3)
    builder = SnapshotBuilder()
    contribute_store_metrics(builder)
    series = {(s.spec.name, tuple(s.labels)): s.value
              for s in builder.build().series}
    assert series[(schema.STORE_STATE.name,
                   (("store", "m-test"),))] == 0.0
    assert series[(schema.STORE_LOST.name,
                   (("store", "m-test"),))] == 3.0
    assert series[(schema.DISK_FAULTS.name,
                   (("store", "m-test"), ("errno", "ENOSPC")))] == 1.0


def test_store_metrics_quiet_publishes_skip_registry_walk(monkeypatch):
    """ISSUE 17 satellite: kts_store_* rows are edge-cached — a quiet
    100-publish run performs ZERO health-registry walks (the rows
    replay from the cache), and the next fault/loss edge invalidates
    the cache for exactly one fresh walk."""
    from kube_gpu_stats_tpu import schema
    from kube_gpu_stats_tpu.registry import (SnapshotBuilder,
                                             contribute_store_metrics)

    wal.reset_store_stats()
    health = wal.store_health("quiet-test")
    health.record_fault(OSError(errno.ENOSPC, "full"), lost=2)
    contribute_store_metrics(SnapshotBuilder())  # primes the cache

    walks: list[int] = []
    real_report = wal.store_report

    def counting_report():
        walks.append(1)
        return real_report()

    monkeypatch.setattr(wal, "store_report", counting_report)
    first = None
    for _ in range(100):
        builder = SnapshotBuilder()
        contribute_store_metrics(builder)
        got = {(s.spec.name, tuple(s.labels)): s.value
               for s in builder.build().series}
        if first is None:
            first = got
        assert got == first
    assert walks == []  # zero health-registry walks while quiet
    assert first[(schema.STORE_LOST.name,
                  (("store", "quiet-test"),))] == 2.0

    # A loss edge flips the generation: exactly one fresh walk, and the
    # new count lands in the very next publish.
    health.record_lost(3)
    builder = SnapshotBuilder()
    contribute_store_metrics(builder)
    assert len(walks) == 1
    got = {(s.spec.name, tuple(s.labels)): s.value
           for s in builder.build().series}
    assert got[(schema.STORE_LOST.name,
                (("store", "quiet-test"),))] == 5.0
    wal.reset_store_stats()


# -- supervisor: storm latch + spawn -----------------------------------------

def _dying_component(supervisor, clock):
    from kube_gpu_stats_tpu.resilience import BackoffPolicy

    supervisor.register(
        "dies", is_alive=lambda: False, restart=lambda: None,
        backoff=BackoffPolicy(base=1e-9, cap=1e-9, jitter=False))


def test_supervisor_latches_restart_storm_and_probes_after_hold():
    from kube_gpu_stats_tpu.supervisor import DEGRADED, Supervisor

    now = [0.0]
    supervisor = Supervisor(clock=lambda: now[0])
    _dying_component(supervisor, now)
    restarts = 0
    for _ in range(Supervisor.STORM_THRESHOLD):
        restarts += len(supervisor.check_once())
        now[0] += 1.0
    assert restarts == Supervisor.STORM_THRESHOLD
    report = supervisor.restart_report()[0]
    assert report["storms"] == 1 and report["storm_latched"]
    # Latched: no more respawns inside the hold...
    for _ in range(10):
        assert supervisor.check_once() == []
        now[0] += 1.0
    # ...and health reads DEGRADED with the storm named, not stale.
    row = supervisor.health()[0]
    assert row.state == DEGRADED and "restart storm" in row.reason
    # Hold over: ONE probe respawn...
    now[0] += Supervisor.STORM_HOLD
    assert supervisor.check_once() == ["dies"]
    # ...and a probe that dies again RE-LATCHES immediately — not
    # another five free respawns (the documented contract).
    now[0] += 1.0
    assert supervisor.check_once() == []
    report = supervisor.restart_report()[0]
    assert report["storms"] == 2 and report["storm_latched"]
    assert report["restarts"] == Supervisor.STORM_THRESHOLD + 1


def test_supervisor_storm_event_journaled():
    from kube_gpu_stats_tpu.supervisor import Supervisor

    now = [0.0]
    tracer = FakeTracer()
    supervisor = Supervisor(clock=lambda: now[0], tracer=tracer)
    _dying_component(supervisor, now)
    for _ in range(Supervisor.STORM_THRESHOLD):
        supervisor.check_once()
        now[0] += 1.0
    assert any(e["kind"] == "thread_restart_storm" for e in tracer.events)


def test_supervisor_contributes_storm_counter():
    from kube_gpu_stats_tpu import schema
    from kube_gpu_stats_tpu.registry import SnapshotBuilder
    from kube_gpu_stats_tpu.supervisor import Supervisor

    now = [0.0]
    supervisor = Supervisor(clock=lambda: now[0])
    _dying_component(supervisor, now)
    for _ in range(Supervisor.STORM_THRESHOLD):
        supervisor.check_once()
        now[0] += 1.0
    builder = SnapshotBuilder()
    supervisor.contribute(builder)
    series = {(s.spec.name, tuple(s.labels)): s.value
              for s in builder.build().series}
    assert series[(schema.THREAD_RESTART_STORMS.name,
                   (("component", "dies"),))] == 1.0


def test_publish_follower_respawn_retires_the_wedged_thread():
    """A hang-triggered respawn must ABANDON the wedged sender thread
    and the abandoned thread must retire at its next superseded()
    check — two run_forever loops draining one at-least-once cursor
    would race peek/commit and skip records (review finding)."""
    from kube_gpu_stats_tpu.registry import Registry, SnapshotBuilder
    from kube_gpu_stats_tpu.workers import PublishFollower

    wedge = threading.Event()
    pushed = []

    class Wedgy(PublishFollower):
        def push_once(self):
            pushed.append(threading.current_thread())
            wedge.wait(5.0)

    registry = Registry()
    follower = Wedgy(registry, 0.0, thread_name="pf-test")
    follower.start()
    try:
        registry.publish(SnapshotBuilder().build())
        deadline = time.monotonic() + 5.0
        while not pushed and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pushed, "follower never pushed"
        old = follower._thread
        follower.respawn()  # the supervisor's hang restart
        assert follower._thread is not old
        wedge.set()  # the wedge clears...
        old.join(3.0)
        assert not old.is_alive(), "superseded thread did not retire"
        assert len(pushed) == 1  # and it never pushed again
        # start() on a live thread stays a no-op (no triple-spawn).
        live = follower._thread
        follower.start()
        assert follower._thread is live
    finally:
        wedge.set()
        follower.stop()


def test_spawn_returns_named_daemon_thread():
    from kube_gpu_stats_tpu.supervisor import spawn

    ran = threading.Event()
    thread = spawn(ran.set, name="spawn-test")
    assert thread.daemon and thread.name == "spawn-test"
    assert not thread.is_alive()  # caller owns .start()
    thread.start()
    assert ran.wait(2.0)


def test_burst_sampler_start_respawns_a_dead_thread():
    """Pre-fix, a died-once sampler was unrestartable (`is not None`
    latch) — the supervisor's restart closure silently no-opped."""
    from kube_gpu_stats_tpu.burstsampler import BurstSampler

    sampler = BurstSampler(lambda: None, lambda: [], mode="continuous")
    sampler.start()
    assert sampler.thread_alive()
    first = sampler._thread
    sampler._stop.set()  # kill it the rude way
    sampler._wake.set()
    first.join(timeout=2.0)
    assert not sampler.thread_alive()
    sampler._stop.clear()
    sampler.start()  # the supervisor's restart closure
    assert sampler.thread_alive() and sampler._thread is not first
    sampler.stop()


# -- /debug/stores + accept fence --------------------------------------------

def _get(url, auth=None):
    request = urllib.request.Request(url)
    if auth:
        import base64

        request.add_header(
            "Authorization",
            "Basic " + base64.b64encode(auth.encode()).decode())
    with urllib.request.urlopen(request, timeout=5) as response:
        return response.status, response.read()


def test_debug_stores_endpoint_and_auth(tmp_path):
    import hashlib

    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.registry import Registry

    health = wal.store_health("endpoint-test")
    health.record_fault(OSError(errno.ENOSPC, "full"))

    def stores():
        return {"enabled": True, "stores": wal.store_report(),
                "threads": []}

    server = MetricsServer(
        Registry(), host="127.0.0.1", port=0,
        auth_username="ops",
        auth_password_sha256=hashlib.sha256(b"pw").hexdigest(),
        stores_provider=stores)
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(base + "/debug/stores")
        assert err.value.code == 401  # auth-gated like every /debug
        status, body = _get(base + "/debug/stores", auth="ops:pw")
        payload = json.loads(body)
        assert payload["stores"]["endpoint-test"]["state"] == "degraded"
        assert payload["stores"]["endpoint-test"]["reason"] == "disk_full"
    finally:
        server.stop()


def test_accept_loop_survives_fd_exhaustion(tmp_path):
    """EMFILE on accept: shed-with-backoff, counted, then full
    recovery — never an accept-loop death (the tentpole fence)."""
    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.registry import Registry, SnapshotBuilder

    registry = Registry()
    builder = SnapshotBuilder()
    registry.publish(builder.build())
    server = MetricsServer(registry, host="127.0.0.1", port=0)
    server.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        _get(base + "/healthz")  # warm: the loop accepts fine
        proxy = fence_accepts(server, times=4)
        deadline = time.monotonic() + 10.0
        status = None
        while time.monotonic() < deadline:
            try:
                status, _ = _get(base + "/healthz")
                break
            except (urllib.error.URLError, OSError):
                time.sleep(0.05)
        assert status == 200, "accept loop dead after EMFILE burst"
        assert proxy.faults_served == 4
        fence = server.accept_fence_status()
        assert fence["fenced_total"] == 4
        assert fence["episodes"] >= 1
        assert not fence["in_episode"]  # recovered
        health = wal.store_health("http-accept")
        assert health.fault_counts.get("EMFILE") == 4
        assert health.state == wal.STORE_HEALTHY
    finally:
        server.stop()


def test_fetch_pool_socket_emfile_sheds_not_crashes(monkeypatch):
    """EMFILE on the hub fetch pool's socket open path: the refresh
    counts a fetch failure (breaker discipline) and the pool thread
    survives — pinned shed-not-crash (satellite)."""
    import http.client

    from kube_gpu_stats_tpu.hub import Hub

    def exhausted(self):
        raise OSError(errno.EMFILE, "too many open files")

    monkeypatch.setattr(http.client.HTTPConnection, "connect", exhausted)
    hub = Hub(["http://127.0.0.1:9/metrics"], interval=10.0)
    try:
        frame = hub.refresh_once()
        assert frame.errors  # the failure is counted...
        hub.refresh_once()   # ...and the pool keeps refreshing
    finally:
        hub.stop()
