"""Native C++ batched sysfs reader: parity with the pure-Python path,
fallback behavior, and a speed sanity check. Skipped when the shared lib
can't be built/loaded (CI without g++)."""

import pytest

from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.collectors import CollectorError
from kube_gpu_stats_tpu.collectors.sysfs import SysfsCollector
from kube_gpu_stats_tpu.native import maybe_accelerate_sysfs
from kube_gpu_stats_tpu.testing.sysfs_fixture import make_sysfs

native = pytest.importorskip("kube_gpu_stats_tpu.native.binding")


@pytest.fixture
def tree(tmp_path):
    return make_sysfs(tmp_path, num_chips=4)


def test_wraps_when_library_present(tree):
    col = maybe_accelerate_sysfs(SysfsCollector(tree, accel_type="tpu"))
    assert col.name == "sysfs-native"


def test_parity_with_python_reader(tree):
    python = SysfsCollector(tree, accel_type="tpu")
    fast = native.NativeSysfsCollector(SysfsCollector(tree, accel_type="tpu"))
    devs = fast.discover()
    assert [d.index for d in devs] == [d.index for d in python.discover()]
    for dev in devs:
        assert fast.read_environment(dev) == python.read_environment(dev)


def test_missing_attributes_partial(tree):
    # Remove chip 1's power file; temp must still read natively.
    (tree / "class/accel/accel1/device/hwmon/hwmon0/power1_average").unlink()
    fast = native.NativeSysfsCollector(SysfsCollector(tree, accel_type="tpu"))
    devs = fast.discover()
    values = fast.read_environment(devs[1])
    assert schema.POWER.name not in values
    assert schema.TEMPERATURE.name in values


def test_vanished_device_raises(tree):
    fast = native.NativeSysfsCollector(SysfsCollector(tree, accel_type="tpu"))
    devs = fast.discover()
    fast.read_environment(devs[0])
    import shutil

    shutil.rmtree(tree / "class/accel/accel0")
    with pytest.raises(CollectorError):
        fast.read_environment(devs[0])


def test_garbage_value_skipped(tree):
    (tree / "class/accel/accel2/device/hwmon/hwmon0/temp1_input").write_text("zzz\n")
    fast = native.NativeSysfsCollector(SysfsCollector(tree, accel_type="tpu"))
    devs = fast.discover()
    values = fast.read_environment(devs[2])
    assert schema.TEMPERATURE.name not in values
    assert schema.POWER.name in values


def test_native_not_slower(tree):
    """Not a benchmark — just catches the case where the native path
    regresses to pathological (e.g. re-globbing per tick)."""
    import time

    python = SysfsCollector(tree, accel_type="tpu")
    fast = native.NativeSysfsCollector(SysfsCollector(tree, accel_type="tpu"))
    devs = fast.discover()
    for col in (python, fast):  # warm both
        for d in devs:
            col.read_environment(d)

    def clock(col, n=200):
        start = time.perf_counter()
        for _ in range(n):
            for d in devs:
                col.read_environment(d)
        return time.perf_counter() - start

    t_python, t_native = clock(python), clock(fast)
    assert t_native < t_python * 1.5, (t_python, t_native)
