"""Native C++ batched sysfs reader: parity with the pure-Python path,
fallback behavior, and a speed sanity check. Skipped when the shared lib
can't be built/loaded (CI without g++)."""

import pytest

from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.collectors import CollectorError
from kube_gpu_stats_tpu.collectors.sysfs import SysfsCollector
from kube_gpu_stats_tpu.native import maybe_accelerate_sysfs
from kube_gpu_stats_tpu.testing.sysfs_fixture import make_sysfs

native = pytest.importorskip("kube_gpu_stats_tpu.native.binding")


@pytest.fixture
def tree(tmp_path):
    return make_sysfs(tmp_path, num_chips=4)


def test_wraps_when_library_present(tree):
    col = maybe_accelerate_sysfs(SysfsCollector(tree, accel_type="tpu"))
    assert col.name == "sysfs-native"


def test_parity_with_python_reader(tree):
    python = SysfsCollector(tree, accel_type="tpu")
    fast = native.NativeSysfsCollector(SysfsCollector(tree, accel_type="tpu"))
    devs = fast.discover()
    assert [d.index for d in devs] == [d.index for d in python.discover()]
    for dev in devs:
        assert fast.read_environment(dev) == python.read_environment(dev)


def test_missing_attributes_partial(tree):
    # Remove chip 1's power file; temp must still read natively.
    (tree / "class/accel/accel1/device/hwmon/hwmon0/power1_average").unlink()
    fast = native.NativeSysfsCollector(SysfsCollector(tree, accel_type="tpu"))
    devs = fast.discover()
    values = fast.read_environment(devs[1])
    assert schema.POWER.name not in values
    assert schema.TEMPERATURE.name in values


def test_vanished_device_raises(tree):
    fast = native.NativeSysfsCollector(SysfsCollector(tree, accel_type="tpu"))
    devs = fast.discover()
    fast.read_environment(devs[0])
    import shutil

    shutil.rmtree(tree / "class/accel/accel0")
    with pytest.raises(CollectorError):
        fast.read_environment(devs[0])


def test_garbage_value_skipped(tree):
    (tree / "class/accel/accel2/device/hwmon/hwmon0/temp1_input").write_text("zzz\n")
    fast = native.NativeSysfsCollector(SysfsCollector(tree, accel_type="tpu"))
    devs = fast.discover()
    values = fast.read_environment(devs[2])
    assert schema.TEMPERATURE.name not in values
    assert schema.POWER.name in values


def test_native_not_slower(tree):
    """Not a benchmark — just catches the case where the native path
    regresses to pathological (e.g. re-globbing per tick)."""
    import time

    python = SysfsCollector(tree, accel_type="tpu")
    fast = native.NativeSysfsCollector(SysfsCollector(tree, accel_type="tpu"))
    devs = fast.discover()
    for col in (python, fast):  # warm both
        for d in devs:
            col.read_environment(d)

    def clock(col, n=200):
        start = time.perf_counter()
        for _ in range(n):
            for d in devs:
                col.read_environment(d)
        return time.perf_counter() - start

    t_python, t_native = clock(python), clock(fast)
    assert t_native < t_python * 1.5, (t_python, t_native)


def test_plan_skips_unparsable_hit_for_readable_fallback(tmp_path):
    """Review finding: the plan pinned the first glob hit even when it
    couldn't be read/parsed, losing the pure-Python fallback chain. An
    hwmon file serving garbage must yield to the flat fallback file."""
    from kube_gpu_stats_tpu.collectors.sysfs import SysfsCollector
    from kube_gpu_stats_tpu.native.binding import NativeSysfsCollector

    accel = tmp_path / "class" / "accel" / "accel0"
    hwmon = accel / "device" / "hwmon" / "hwmon0"
    hwmon.mkdir(parents=True)
    (hwmon / "power1_average").write_text("not-a-number\n")  # dead first hit
    (accel / "power_usage_uw").write_text("120000000\n")     # readable fallback
    col = NativeSysfsCollector(SysfsCollector(str(tmp_path)))
    (dev,) = col.discover()
    env = col.read_environment(dev)
    assert env["accelerator_power_watts"] == 120.0


def test_plan_heals_when_files_appear_later(tmp_path):
    """Boot race: accel dir exists before hwmon binds. The empty plan
    must not blind the collector until rediscovery — the next tick
    re-globs (review finding)."""
    from kube_gpu_stats_tpu.collectors.sysfs import SysfsCollector
    from kube_gpu_stats_tpu.native.binding import NativeSysfsCollector

    accel = tmp_path / "class" / "accel" / "accel0"
    accel.mkdir(parents=True)
    col = NativeSysfsCollector(SysfsCollector(str(tmp_path)))
    (dev,) = col.discover()
    assert col.read_environment(dev) == {}  # nothing there yet
    (accel / "power_usage_uw").write_text("90000000\n")  # driver binds
    env = col.read_environment(dev)  # next tick: plan rebuilt
    assert env["accelerator_power_watts"] == 90.0


def test_plan_reprobes_after_pinned_file_dies(tmp_path):
    """hwmon renumbering: the pinned path dying must trigger a re-probe
    next tick instead of a permanent metric loss (review finding)."""
    from kube_gpu_stats_tpu.collectors.sysfs import SysfsCollector
    from kube_gpu_stats_tpu.native.binding import NativeSysfsCollector

    accel = tmp_path / "class" / "accel" / "accel0"
    hwmon0 = accel / "device" / "hwmon" / "hwmon0"
    hwmon0.mkdir(parents=True)
    (hwmon0 / "power1_average").write_text("100000000\n")
    col = NativeSysfsCollector(SysfsCollector(str(tmp_path)))
    (dev,) = col.discover()
    assert col.read_environment(dev)["accelerator_power_watts"] == 100.0
    # Driver rebind renumbers hwmon0 -> hwmon1.
    hwmon1 = accel / "device" / "hwmon" / "hwmon1"
    hwmon1.mkdir()
    (hwmon1 / "power1_average").write_text("110000000\n")
    (hwmon0 / "power1_average").unlink()
    hwmon0.rmdir()
    col.read_environment(dev)  # degraded tick: pinned path gone
    env = col.read_environment(dev)  # re-probed plan
    assert env["accelerator_power_watts"] == 110.0


def test_wirefast_rejects_bad_prepopulated_cache():
    """Review finding: a non-dict or shape-less cache entry segfaulted
    the process; it must raise from Python instead."""
    import pytest

    from kube_gpu_stats_tpu import native
    from kube_gpu_stats_tpu.proto import tpumetrics

    wirefast = native.load_wirefast()
    if wirefast is None:
        pytest.skip("native extension not built")
    raw = tpumetrics.encode_response(
        [tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 0, 50.0)])
    with pytest.raises(TypeError):
        wirefast.ingest(raw, {0: "not-a-dict"})
    with pytest.raises(TypeError):
        wirefast.ingest(raw, {0: {}})  # dict but missing values/ici


def test_wirefast_failed_configure_leaves_state_intact():
    """Review finding: a failed configure() half-cleared the name table,
    silently misclassifying every later family. It must be atomic."""
    import pytest

    from kube_gpu_stats_tpu import native
    from kube_gpu_stats_tpu.proto import tpumetrics

    wirefast = native.load_wirefast()
    if wirefast is None:
        pytest.skip("native extension not built")
    with pytest.raises(ValueError):
        wirefast.configure({b"a.b": "x", b"bad": 3}, b"i", b"c")
    try:
        # Old configuration still classifies the pinned names.
        raw = tpumetrics.encode_response(
            [tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 0, 50.0)])
        cache = {}
        n, _dialect, unknown = wirefast.ingest(raw, cache)
        assert n == 1 and unknown == 0
        assert cache[0]["values"]  # classified, not dropped as unknown
    finally:
        native.load_wirefast()  # restore canonical configuration
