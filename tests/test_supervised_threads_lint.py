"""check_supervised_threads lint (ISSUE 15 satellite): every thread in
kube_gpu_stats_tpu/ must be born through supervisor.spawn() — bare
threading.Thread(...) call sites (and Thread subclasses) fail `make
lint`, with supervisor.py (the helper's home) and testing/ (test
doubles) allowlisted."""

import pathlib
import subprocess
import sys
import textwrap

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "tools"))

import check_supervised_threads  # noqa: E402


def _check(tmp_path, source: str, name: str = "module.py") -> list[str]:
    path = tmp_path / name
    path.write_text(textwrap.dedent(source))
    return check_supervised_threads.check_file(path)


def test_bare_threading_thread_flagged(tmp_path):
    problems = _check(tmp_path, """
        import threading
        t = threading.Thread(target=print, name="x", daemon=True)
    """)
    assert len(problems) == 1
    assert "supervisor.spawn" in problems[0]


def test_imported_thread_name_flagged(tmp_path):
    problems = _check(tmp_path, """
        from threading import Thread
        t = Thread(target=print)
    """)
    assert len(problems) == 1


def test_thread_subclass_flagged(tmp_path):
    problems = _check(tmp_path, """
        import threading

        class Worker(threading.Thread):
            pass
    """)
    assert len(problems) == 1
    assert "subclasses" in problems[0]


def test_spawn_helper_usage_passes(tmp_path):
    assert _check(tmp_path, """
        from .supervisor import spawn
        t = spawn(print, name="ok")
        t.start()
    """) == []


def test_unrelated_thread_attribute_passes(tmp_path):
    """Other .Thread attributes (a fake SDK's client.Thread) must not
    false-positive; only the threading module's constructor counts."""
    assert _check(tmp_path, """
        import sdk
        t = sdk.Thread(target=print)
    """) == []


def test_allowlist_covers_supervisor_and_testing():
    assert "supervisor.py" in check_supervised_threads.ALLOW_FILES
    assert "testing" in check_supervised_threads.ALLOW_DIRS


def test_lint_green_on_the_real_package():
    """The shipped package must pass its own lint (the make lint
    gate); run the tool as the Makefile does."""
    result = subprocess.run(
        [sys.executable,
         str(ROOT / "tools" / "check_supervised_threads.py")],
        capture_output=True, text=True)
    assert result.returncode == 0, result.stderr
