"""Sysfs discovery + attribute parsing against fixture trees
(SURVEY.md §4 unit tier)."""

import pytest

from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.collectors import CollectorError
from kube_gpu_stats_tpu.collectors.sysfs import SysfsCollector

from kube_gpu_stats_tpu.testing.sysfs_fixture import make_sysfs


def test_discovery(tmp_path):
    make_sysfs(tmp_path, num_chips=4)
    col = SysfsCollector(tmp_path, accel_type="tpu-v5p")
    devs = col.discover()
    assert [d.index for d in devs] == [0, 1, 2, 3]
    assert devs[2].device_path == "/dev/accel2"
    assert devs[2].uuid == "tpu-chip-0002"
    assert devs[2].accel_type == "tpu-v5p"


def test_discovery_empty_tree(tmp_path):
    assert SysfsCollector(tmp_path, accel_type="tpu").discover() == []


def test_environment_reads_hwmon_scaling(tmp_path):
    make_sysfs(tmp_path, num_chips=1, power_uw=150_000_000, temp_mc=52_500)
    col = SysfsCollector(tmp_path, accel_type="tpu")
    dev = col.discover()[0]
    sample = col.sample(dev)
    assert sample.values[schema.POWER.name] == pytest.approx(150.0)
    assert sample.values[schema.TEMPERATURE.name] == pytest.approx(52.5)


def test_flat_file_fallback(tmp_path):
    make_sysfs(tmp_path, num_chips=1, with_hwmon=False)
    accel = tmp_path / "class" / "accel" / "accel0"
    (accel / "power_usage_uw").write_text("99000000\n")
    (accel / "temperature_mc").write_text("41000\n")
    col = SysfsCollector(tmp_path, accel_type="tpu")
    sample = col.sample(col.discover()[0])
    assert sample.values[schema.POWER.name] == pytest.approx(99.0)
    assert sample.values[schema.TEMPERATURE.name] == pytest.approx(41.0)


def test_missing_attributes_are_omitted_not_fatal(tmp_path):
    make_sysfs(tmp_path, num_chips=1, with_hwmon=False, with_uuid=False)
    col = SysfsCollector(tmp_path, accel_type="tpu")
    dev = col.discover()[0]
    assert dev.uuid == ""
    assert col.sample(dev).values == {}


def test_garbage_attribute_skipped(tmp_path):
    make_sysfs(tmp_path, num_chips=1, with_hwmon=True)
    hwmon = tmp_path / "class/accel/accel0/device/hwmon/hwmon0"
    (hwmon / "power1_average").write_text("not-a-number\n")
    col = SysfsCollector(tmp_path, accel_type="tpu")
    values = col.sample(col.discover()[0]).values
    assert schema.POWER.name not in values
    assert schema.TEMPERATURE.name in values


def test_vanished_device_raises(tmp_path):
    make_sysfs(tmp_path, num_chips=1)
    col = SysfsCollector(tmp_path, accel_type="tpu")
    dev = col.discover()[0]
    import shutil

    shutil.rmtree(tmp_path / "class" / "accel" / "accel0")
    with pytest.raises(CollectorError):
        col.sample(dev)
