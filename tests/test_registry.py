import math
import threading

from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.registry import (
    HistogramState,
    Registry,
    SnapshotBuilder,
    format_value,
)


def test_format_value():
    assert format_value(1.0) == "1"
    assert format_value(0.5) == "0.5"
    assert format_value(-3.0) == "-3"
    assert format_value(float("nan")) == "NaN"
    assert format_value(float("inf")) == "+Inf"
    assert format_value(95 * 1024**3) == str(95 * 1024**3)


def test_histogram_observe_and_quantile():
    h = HistogramState.empty(schema.SELF_POLL_DURATION, (0.01, 0.05, 0.1))
    for v in (0.005, 0.005, 0.02, 0.2):
        h = h.observe(v)
    assert h.total == 4
    assert math.isclose(h.sum, 0.23)
    assert h.counts == (2, 1, 0, 1)
    assert h.quantile(0.5) == 0.01  # 2 of 4 obs fall in the first bucket
    assert h.quantile(0.99) == math.inf


def test_render_family_order_and_help():
    b = SnapshotBuilder()
    b.add(schema.POWER, 123.0, {"chip": "0"})
    b.add(schema.DUTY_CYCLE, 55.5, {"chip": "0"})
    text = b.build().render()
    # Families render in schema order: duty_cycle before power.
    assert text.index("accelerator_duty_cycle") < text.index("accelerator_power")
    assert "# HELP accelerator_power_watts" in text
    assert "# TYPE accelerator_power_watts gauge" in text
    assert 'accelerator_power_watts{chip="0"} 123' in text
    assert text.endswith("\n")


def test_histogram_render_cumulative():
    h = HistogramState.empty(schema.SELF_POLL_DURATION, (0.01, 0.05))
    h = h.observe(0.005)
    h = h.observe(0.02)
    b = SnapshotBuilder()
    b.add_histogram(h)
    text = b.build().render()
    assert 'collector_poll_duration_seconds_bucket{le="0.01"} 1' in text
    assert 'collector_poll_duration_seconds_bucket{le="0.05"} 2' in text
    assert 'collector_poll_duration_seconds_bucket{le="+Inf"} 2' in text
    assert "collector_poll_duration_seconds_count 2" in text


def test_registry_publish_wait():
    reg = Registry()
    gen = reg.generation
    done = threading.Event()

    def publisher():
        b = SnapshotBuilder()
        b.add(schema.SELF_DEVICES, 1.0)
        reg.publish(b.build())
        done.set()

    t = threading.Thread(target=publisher)
    t.start()
    assert reg.wait_for_publish(gen, timeout=5)
    t.join()
    assert reg.snapshot().series[0].value == 1.0
    # Waiting for a generation already surpassed returns immediately.
    assert reg.wait_for_publish(gen, timeout=0)
