import pytest

from kube_gpu_stats_tpu.config import Config, from_args, parse_libtpu_ports


def test_defaults():
    cfg = from_args([])
    assert cfg.backend == "auto"
    assert cfg.interval == 1.0
    assert cfg.deadline == 0.050
    assert cfg.listen_port == 9400
    assert cfg.libtpu_ports == (8431,)
    assert cfg.attribution == "auto"
    assert not cfg.textfile_enabled


def test_flags():
    cfg = from_args(
        [
            "--backend", "mock",
            "--mock-devices", "8",
            "--interval", "0.5",
            "--textfile-dir", "/tmp/tf",
            "--libtpu-ports", "8431,8432",
            "--attribution", "off",
            "--no-native",
        ]
    )
    assert cfg.backend == "mock"
    assert cfg.mock_devices == 8
    assert cfg.interval == 0.5
    assert cfg.textfile_enabled and cfg.textfile_dir == "/tmp/tf"
    assert cfg.libtpu_ports == (8431, 8432)
    assert cfg.attribution == "off"
    assert cfg.use_native is False


def test_env_overrides(monkeypatch):
    monkeypatch.setenv("KTS_BACKEND", "null")
    monkeypatch.setenv("KTS_LISTEN_PORT", "9999")
    monkeypatch.setenv("TPU_RUNTIME_METRICS_PORTS", "8440 8441")
    cfg = from_args([])
    assert cfg.backend == "null"
    assert cfg.listen_port == 9999
    assert cfg.libtpu_ports == (8440, 8441)
    # Explicit flag beats env.
    assert from_args(["--backend", "mock"]).backend == "mock"


def test_parse_libtpu_ports():
    assert parse_libtpu_ports("8431") == (8431,)
    assert parse_libtpu_ports("1, 2  3") == (1, 2, 3)
    assert parse_libtpu_ports("") == (8431,)


def test_config_dataclass_roundtrip():
    cfg = Config(backend="mock")
    assert cfg.textfile_enabled is False


def test_no_native_env_spellings(monkeypatch):
    for raw, expect_native in [
        ("False", True), ("FALSE", True), ("0", True), ("", True),
        ("no", True), ("off", True),
        ("1", False), ("true", False), ("YES", False), ("on", False),
    ]:
        monkeypatch.setenv("KTS_NO_NATIVE", raw)
        assert from_args([]).use_native is expect_native, raw


def test_drop_labels_parsing():
    assert from_args([]).drop_labels == ()
    cfg = from_args(["--drop-labels", "pod, namespace ,uuid"])
    assert cfg.drop_labels == ("pod", "namespace", "uuid")


def test_drop_labels_rejects_identity_keys(capsys):
    import pytest

    with pytest.raises(SystemExit):
        from_args(["--drop-labels", "chip,pod"])
    assert "device-identity" in capsys.readouterr().err


def test_config_file_layering(tmp_path, monkeypatch):
    cfg_file = tmp_path / "kts.yaml"
    cfg_file.write_text(
        "backend: mock\n"
        "mock-devices: 6\n"
        "interval: 0.25\n"
        "libtpu-ports: [8431, 8432]\n"
        "drop-labels: [pod, namespace]\n"
    )
    cfg = from_args(["--config", str(cfg_file)])
    assert cfg.backend == "mock"
    assert cfg.mock_devices == 6
    assert cfg.interval == 0.25
    assert cfg.libtpu_ports == (8431, 8432)
    assert cfg.drop_labels == ("pod", "namespace")
    # Flags beat file.
    assert from_args(["--config", str(cfg_file), "--backend", "null"]).backend == "null"
    # Env beats file.
    monkeypatch.setenv("KTS_BACKEND", "null")
    assert from_args(["--config", str(cfg_file)]).backend == "null"


def test_config_file_unknown_key(tmp_path, capsys):
    import pytest

    cfg_file = tmp_path / "bad.yaml"
    cfg_file.write_text("no-such-option: 1\n")
    with pytest.raises(SystemExit):
        from_args(["--config", str(cfg_file)])
    assert "unknown key" in capsys.readouterr().err


def test_config_file_missing(tmp_path, capsys):
    import pytest

    with pytest.raises(SystemExit):
        from_args(["--config", str(tmp_path / "nope.yaml")])


def test_config_file_validates_choices_and_types(tmp_path, capsys):
    import pytest

    bad = tmp_path / "bad.yaml"
    bad.write_text("backend: bogus\n")
    with pytest.raises(SystemExit):
        from_args(["--config", str(bad)])
    assert "must be one of" in capsys.readouterr().err

    bad.write_text("interval: {weird: 1}\n")
    with pytest.raises(SystemExit):
        from_args(["--config", str(bad)])
    assert "scalar" in capsys.readouterr().err

    bad.write_text("interval: notafloat\n")
    with pytest.raises(SystemExit):
        from_args(["--config", str(bad)])
    assert "invalid value" in capsys.readouterr().err

    bad.write_text("no-native: yes-please\n")
    with pytest.raises(SystemExit):
        from_args(["--config", str(bad)])


def test_tpu_runtime_metrics_ports_env_beats_config_file(tmp_path, monkeypatch):
    cfg_file = tmp_path / "kts.yaml"
    cfg_file.write_text("libtpu-ports: [9999]\n")
    monkeypatch.setenv("TPU_RUNTIME_METRICS_PORTS", "8431,8432")
    cfg = from_args(["--config", str(cfg_file)])
    assert cfg.libtpu_ports == (8431, 8432)
    monkeypatch.delenv("TPU_RUNTIME_METRICS_PORTS")
    assert from_args(["--config", str(cfg_file)]).libtpu_ports == (9999,)


def test_tls_flags_must_come_together():
    with pytest.raises(SystemExit):
        from_args(["--tls-cert-file", "/tmp/cert.pem"])


def test_auth_flags_must_come_together():
    with pytest.raises(SystemExit):
        from_args(["--auth-username", "prom"])


def test_auth_hash_must_be_sha256_hex():
    with pytest.raises(SystemExit):
        from_args(["--auth-username", "prom",
                   "--auth-password-sha256", "plaintext-password"])


def test_web_hardening_flags_parse():
    cfg = from_args([
        "--tls-cert-file", "/etc/tls/cert.pem",
        "--tls-key-file", "/etc/tls/key.pem",
        "--auth-username", "prom",
        "--auth-password-sha256", "a" * 64,
    ])
    assert cfg.tls_cert_file == "/etc/tls/cert.pem"
    assert cfg.auth_username == "prom"


def test_config_file_yaml11_on_off_booleans(tmp_path):
    """YAML 1.1 parses bare on/off as booleans; the documented spelling
    `device_processes: on` must still work unquoted."""
    cfg_file = tmp_path / "kts.yaml"
    cfg_file.write_text("device_processes: off\n")
    cfg = from_args(["--config", str(cfg_file)])
    assert cfg.device_processes == "off"
    cfg_file.write_text("device_processes: on\n")
    assert from_args(["--config", str(cfg_file)]).device_processes == "on"


def test_config_file_yaml11_off_for_non_on_choices(tmp_path):
    """`attribution: off` — choices without an 'on' member — must also
    survive the YAML 1.1 boolean parse."""
    cfg_file = tmp_path / "kts.yaml"
    cfg_file.write_text("attribution: off\n")
    assert from_args(["--config", str(cfg_file)]).attribution == "off"


def test_log_format_defaults_text_and_setup_logging_runs():
    """Regression: the daemon entrypoint calls setup_logging(cfg) before
    anything else; a Config missing log_format crash-looped the DaemonSet
    (round-1 advisor finding). Exercise the real entry path."""
    from kube_gpu_stats_tpu.daemon import setup_logging

    cfg = from_args([])
    assert cfg.log_format == "text"
    setup_logging(cfg)  # must not raise

    cfg = from_args(["--log-format", "json"])
    assert cfg.log_format == "json"
    setup_logging(cfg)  # must not raise


def test_log_format_rejects_unknown():
    with pytest.raises(SystemExit):
        from_args(["--log-format", "xml"])


def test_json_log_formatter_single_line():
    import json
    import logging

    from kube_gpu_stats_tpu.daemon import JsonLogFormatter

    rec = logging.LogRecord("kts", logging.WARNING, __file__, 1,
                            "tick overran by %dms", (7,), None)
    doc = json.loads(JsonLogFormatter().format(rec))
    assert doc["severity"] == "WARNING"
    assert doc["message"] == "tick overran by 7ms"
    assert "\n" not in JsonLogFormatter().format(rec)


def test_remote_write_extra_labels_parse_and_validate():
    from kube_gpu_stats_tpu.config import from_args, parse_extra_labels

    cfg = from_args(["--backend", "mock", "--remote-write-extra-labels",
                     "cluster=prod, region=us-east1"])
    assert cfg.remote_write_extra_labels == (
        ("cluster", "prod"), ("region", "us-east1"))
    import pytest
    for bad in ("cluster", "pod=x", "chip=0", "job=a", "1bad=x",
                "a=1,a=2"):
        with pytest.raises(SystemExit):
            from_args(["--backend", "mock",
                       "--remote-write-extra-labels", bad])
    assert parse_extra_labels("") == ()


def test_extra_labels_empty_value_rejected():
    import pytest

    from kube_gpu_stats_tpu.config import parse_extra_labels

    # The wire encoders drop empty-valued labels, so 'cluster=' would
    # silently no-op — it must fail at startup instead.
    with pytest.raises(ValueError, match="non-empty value"):
        parse_extra_labels("cluster=")


def test_host_stats_flags(monkeypatch):
    cfg = from_args([])
    assert cfg.host_stats is True
    assert cfg.cgroup_root == "/sys/fs/cgroup"
    cfg = from_args(["--no-host-stats", "--cgroup-root", "/mnt/cg"])
    assert cfg.host_stats is False
    assert cfg.cgroup_root == "/mnt/cg"
    monkeypatch.setenv("KTS_NO_HOST_STATS", "1")
    monkeypatch.setenv("KTS_CGROUP_ROOT", "/env/cg")
    cfg = from_args([])
    assert cfg.host_stats is False
    assert cfg.cgroup_root == "/env/cg"


def test_hub_proto_max_flag_reaches_config():
    """ISSUE 14 regression: the flag existed but wasn't mapped into
    Config, so --hub-proto-max silently did nothing — a canary wave
    'held at v1' would have negotiated up anyway."""
    assert from_args([]).hub_proto_max == 0
    assert from_args(["--hub-proto-max", "1"]).hub_proto_max == 1
