from kube_gpu_stats_tpu.proto import tpumetrics


def test_request_roundtrip():
    assert tpumetrics.decode_request(tpumetrics.encode_request("foo")) == "foo"
    assert tpumetrics.decode_request(tpumetrics.encode_request("")) == ""
    assert tpumetrics.decode_request(b"") == ""


def test_double_metric_roundtrip():
    s = tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 3, 72.5, timestamp_ns=123)
    out = tpumetrics.decode_response(tpumetrics.encode_response([s]))
    assert out == [s]


def test_int_metric_roundtrip():
    s = tpumetrics.MetricSample(tpumetrics.HBM_USED, 0, 7 * 1024**3)
    (decoded,) = tpumetrics.decode_response(tpumetrics.encode_response([s]))
    assert decoded.value == 7 * 1024**3
    assert isinstance(decoded.value, int)


def test_link_metric_roundtrip():
    s = tpumetrics.MetricSample(tpumetrics.ICI_TRAFFIC, 2, 999, link="y1")
    (decoded,) = tpumetrics.decode_response(tpumetrics.encode_response([s]))
    assert decoded.link == "y1"
    assert decoded.value == 999


def test_multiple_samples_preserve_order():
    samples = [
        tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, i, float(i)) for i in range(5)
    ]
    decoded = tpumetrics.decode_response(tpumetrics.encode_response(samples))
    assert [s.device_id for s in decoded] == [0, 1, 2, 3, 4]
