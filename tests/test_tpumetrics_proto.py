from kube_gpu_stats_tpu.proto import tpumetrics


def test_request_roundtrip():
    assert tpumetrics.decode_request(tpumetrics.encode_request("foo")) == "foo"
    assert tpumetrics.decode_request(tpumetrics.encode_request("")) == ""
    assert tpumetrics.decode_request(b"") == ""


def test_double_metric_roundtrip():
    s = tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 3, 72.5, timestamp_ns=123)
    out = tpumetrics.decode_response(tpumetrics.encode_response([s]))
    assert out == [s]


def test_int_metric_roundtrip():
    s = tpumetrics.MetricSample(tpumetrics.HBM_USED, 0, 7 * 1024**3)
    (decoded,) = tpumetrics.decode_response(tpumetrics.encode_response([s]))
    assert decoded.value == 7 * 1024**3
    assert isinstance(decoded.value, int)


def test_link_metric_roundtrip():
    s = tpumetrics.MetricSample(tpumetrics.ICI_TRAFFIC, 2, 999, link="y1")
    (decoded,) = tpumetrics.decode_response(tpumetrics.encode_response([s]))
    assert decoded.link == "y1"
    assert decoded.value == 999


def test_multiple_samples_preserve_order():
    samples = [
        tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, i, float(i)) for i in range(5)
    ]
    decoded = tpumetrics.decode_response(tpumetrics.encode_response(samples))
    assert [s.device_id for s in decoded] == [0, 1, 2, 3, 4]


def test_unknown_fields_skipped_all_wire_types():
    """Forward compat: a future runtime adding fields of ANY wire type must
    not break decode (review finding)."""
    import struct

    from kube_gpu_stats_tpu.proto import codec

    metric = (
        codec.field_string(1, "m")
        + codec.field_varint(2, 3)
        + codec.field_double(3, 1.5)
        + codec.field_varint(99, 7)                       # unknown varint
        + codec.tag(100, codec.FIXED64) + struct.pack("<d", 2.5)  # unknown f64
        + codec.tag(101, codec.FIXED32) + struct.pack("<f", 1.0)  # unknown f32
        + codec.field_bytes(102, b"xyz")                  # unknown bytes
    )
    (decoded,) = tpumetrics.decode_response(codec.field_bytes(1, metric))
    assert decoded.name == "m"
    assert decoded.device_id == 3
    assert decoded.value == 1.5


def test_varint_overrunning_window_is_valueerror():
    """A truncated varint at a submessage boundary must not silently eat
    the next message's bytes (review finding)."""
    from kube_gpu_stats_tpu.proto import codec

    good = tpumetrics.encode_metric(
        tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 1, 50.0)
    )
    # A metric whose window ends mid-varint: tag for field 2 + continuation
    # byte with MSB set, window cut right after.
    bad_metric = codec.field_string(1, "m") + codec.tag(2, codec.VARINT) + b"\xff"
    blob = codec.field_bytes(1, bad_metric) + codec.field_bytes(1, good)
    import pytest as _pytest

    with _pytest.raises(ValueError):
        tpumetrics.decode_response(blob)


def test_known_field_wrong_wire_type_raises():
    from kube_gpu_stats_tpu.proto import codec

    # double_value (field 3) as varint: schema mismatch, not silence.
    bad = codec.field_string(1, "m") + codec.field_varint(3, 7)
    import pytest as _pytest

    with _pytest.raises(ValueError):
        tpumetrics.decode_metric(bad)
