import pytest

from kube_gpu_stats_tpu.proto import codec, tpumetrics


def test_request_roundtrip():
    assert tpumetrics.decode_request(tpumetrics.encode_request("foo")) == "foo"
    assert tpumetrics.decode_request(tpumetrics.encode_request("")) == ""
    assert tpumetrics.decode_request(b"") == ""


def test_double_metric_roundtrip():
    s = tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 3, 72.5, timestamp_ns=123)
    out = tpumetrics.decode_response(tpumetrics.encode_response([s]))
    assert out == [s]


def test_int_metric_roundtrip():
    s = tpumetrics.MetricSample(tpumetrics.HBM_USED, 0, 7 * 1024**3)
    (decoded,) = tpumetrics.decode_response(tpumetrics.encode_response([s]))
    assert decoded.value == 7 * 1024**3
    assert isinstance(decoded.value, int)


def test_link_metric_roundtrip():
    s = tpumetrics.MetricSample(tpumetrics.ICI_TRAFFIC, 2, 999, link="y1")
    (decoded,) = tpumetrics.decode_response(tpumetrics.encode_response([s]))
    assert decoded.link == "y1"
    assert decoded.value == 999


def test_multiple_samples_preserve_order():
    samples = [
        tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, i, float(i)) for i in range(5)
    ]
    decoded = tpumetrics.decode_response(tpumetrics.encode_response(samples))
    assert [s.device_id for s in decoded] == [0, 1, 2, 3, 4]


def test_unknown_fields_skipped_all_wire_types():
    """Forward compat: a future runtime adding fields of ANY wire type must
    not break decode (review finding)."""
    import struct

    from kube_gpu_stats_tpu.proto import codec

    metric = (
        codec.field_string(1, "m")
        + codec.field_varint(2, 3)
        + codec.field_double(3, 1.5)
        + codec.field_varint(99, 7)                       # unknown varint
        + codec.tag(100, codec.FIXED64) + struct.pack("<d", 2.5)  # unknown f64
        + codec.tag(101, codec.FIXED32) + struct.pack("<f", 1.0)  # unknown f32
        + codec.field_bytes(102, b"xyz")                  # unknown bytes
    )
    (decoded,) = tpumetrics.decode_response(codec.field_bytes(1, metric))
    assert decoded.name == "m"
    assert decoded.device_id == 3
    assert decoded.value == 1.5


def test_varint_overrunning_window_is_valueerror():
    """A truncated varint at a submessage boundary must not silently eat
    the next message's bytes (review finding)."""
    from kube_gpu_stats_tpu.proto import codec

    good = tpumetrics.encode_metric(
        tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 1, 50.0)
    )
    # A metric whose window ends mid-varint: tag for field 2 + continuation
    # byte with MSB set, window cut right after.
    bad_metric = codec.field_string(1, "m") + codec.tag(2, codec.VARINT) + b"\xff"
    blob = codec.field_bytes(1, bad_metric) + codec.field_bytes(1, good)
    import pytest as _pytest

    with _pytest.raises(ValueError):
        tpumetrics.decode_response(blob)


def test_known_field_wrong_wire_type_raises():
    from kube_gpu_stats_tpu.proto import codec

    # double_value (field 3) as varint: schema mismatch, not silence.
    bad = codec.field_string(1, "m") + codec.field_varint(3, 7)
    import pytest as _pytest

    with _pytest.raises(ValueError):
        tpumetrics.decode_metric(bad)


# -- nested dialect (round-1 verdict item 1) ---------------------------------

# Golden bytes generated with protoc 3.21 + the google.protobuf runtime
# from the nested schema documented in the tpumetrics module docstring
# (AttrValue/Attribute/Gauge/Timestamp/Metric/TPUMetric/MetricResponse) —
# real-protobuf serializations, not our own encoder's output, so a
# symmetric misreading of the format cannot pass.
NESTED_GOLDEN_HBM = bytes.fromhex(
    "0a8b010a227470752e72756e74696d652e68626d2e6d656d6f72792e75736167"
    "652e6279746573121948424d206d656d6f727920757361676520696e20627974"
    "65731a240a0f0a096465766963655f69641202180012090880b79bb50610f403"
    "1a061080808080041a240a0f0a096465766963655f69641202180112090880b7"
    "9bb50610f4031a06108080808008"
)
NESTED_GOLDEN_ICI = bytes.fromhex(
    "0aba010a227470752e72756e74696d652e6963692e6c696e6b2e747261666669"
    "632e62797465731a230a0f0a096465766963655f6964120218000a0c0a046c69"
    "6e6b12040a0278301a0210021a230a0f0a096465766963655f6964120218000a"
    "0c0a046c696e6b12040a0279311a0210021a240a0f0a096465766963655f6964"
    "120218010a0c0a046c696e6b12040a0278301a0310ea071a240a0f0a09646576"
    "6963655f6964120218010a0c0a046c696e6b12040a0279311a0310ea07"
)
NESTED_GOLDEN_DUTY = bytes.fromhex(
    "0a460a287470752e72756e74696d652e74656e736f72636f72652e6475747963"
    "79636c652e70657263656e741a1a0a0d0a07636f72655f6964120218031a0909"
    "0000000000e05540"
)


def test_nested_golden_hbm_decodes():
    samples, dialect = tpumetrics.decode_response_ex(NESTED_GOLDEN_HBM)
    assert dialect == tpumetrics.NESTED
    assert samples == [
        tpumetrics.MetricSample(tpumetrics.HBM_USED, 0, 1024**3,
                                1722211200_000000500, ""),
        tpumetrics.MetricSample(tpumetrics.HBM_USED, 1, 2 * 1024**3,
                                1722211200_000000500, ""),
    ]


def test_nested_golden_ici_links_decode():
    samples, dialect = tpumetrics.decode_response_ex(NESTED_GOLDEN_ICI)
    assert dialect == tpumetrics.NESTED
    assert len(samples) == 4
    assert {(s.device_id, s.link) for s in samples} == {
        (0, "x0"), (0, "y1"), (1, "x0"), (1, "y1")
    }


def test_nested_golden_core_id_double_gauge():
    samples, dialect = tpumetrics.decode_response_ex(NESTED_GOLDEN_DUTY)
    assert dialect == tpumetrics.NESTED
    assert samples == [
        tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 3, 87.5, 0, "")
    ]


def test_nested_encoder_roundtrip():
    original = [
        tpumetrics.MetricSample(tpumetrics.ICI_TRAFFIC, c, 1000 * c + li,
                                link=link)
        for c in range(3) for li, link in enumerate(("x0", "x1"))
    ]
    raw = tpumetrics.encode_response_nested(tpumetrics.ICI_TRAFFIC, original)
    decoded, dialect = tpumetrics.decode_response_ex(raw)
    assert dialect == tpumetrics.NESTED
    assert decoded == original


def test_nested_encoder_rejects_mixed_families():
    with pytest.raises(ValueError):
        tpumetrics.encode_response_nested(
            tpumetrics.DUTY_CYCLE,
            [tpumetrics.MetricSample(tpumetrics.HBM_USED, 0, 1)],
        )


def test_flat_detects_flat():
    raw = tpumetrics.encode_response(
        [tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 0, 50.0)]
    )
    assert tpumetrics.detect_dialect(raw) == tpumetrics.FLAT
    assert tpumetrics.decode_response_ex(raw)[1] == tpumetrics.FLAT


def test_mixed_dialect_markers_rejected():
    flat_entry = codec.field_bytes(1, (
        codec.field_string(1, tpumetrics.DUTY_CYCLE)
        + codec.field_varint(2, 0) + codec.field_double(3, 1.0)
    ))
    nested_entry = codec.field_bytes(1, (
        codec.field_string(1, tpumetrics.DUTY_CYCLE)
        + codec.field_bytes(3, tpumetrics.encode_metric_nested(
            tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 0, 1.0)))
    ))
    with pytest.raises(ValueError):
        tpumetrics.detect_dialect(flat_entry + nested_entry)


def test_nested_with_unknown_extension_fields_stays_nested():
    """Round-2 advisor finding (medium): a newer nested runtime may extend
    TPUMetric with fields 4-6 (legal proto3 forward compat). Those wire
    shapes overlap flat Metric's int_value/timestamp/link, but they are
    only WEAK flat evidence — with hard nested markers present they must
    be skipped as unknown fields, not trip the mixed-markers error."""
    sample = tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 3, 87.5)
    body = (
        codec.field_string(1, tpumetrics.DUTY_CYCLE)
        + codec.field_bytes(3, tpumetrics.encode_metric_nested(sample))
        + codec.field_varint(4, 7)            # future varint extension
        + codec.field_varint(5, 123456789)    # future varint extension
        + codec.field_string(6, "v2-extra")   # future string extension
    )
    raw = codec.field_bytes(1, body)
    assert tpumetrics.detect_dialect(raw) == tpumetrics.NESTED
    samples, dialect = tpumetrics.decode_response_ex(raw)
    assert dialect == tpumetrics.NESTED
    assert samples == [sample]


def test_weak_flat_markers_alone_still_decode_flat():
    """Without any nested marker, fields 4-6 remain flat evidence: a flat
    runtime emitting only name+int_value (zero-omitting encoder, chip 0)
    must keep decoding as flat, exactly as before the weak/hard split."""
    raw = codec.field_bytes(1, (
        codec.field_string(1, tpumetrics.HBM_USED)
        + codec.field_varint(4, 2048)
    ))
    assert tpumetrics.detect_dialect(raw) == tpumetrics.FLAT
    samples, dialect = tpumetrics.decode_response_ex(raw)
    assert dialect == tpumetrics.FLAT
    assert samples == [tpumetrics.MetricSample(tpumetrics.HBM_USED, 0, 2048)]


def test_hard_flat_vs_nested_conflict_still_rejected():
    """The weak/hard split must not weaken garble detection: hard flat
    markers (field 2 varint / field 3 fixed64) alongside hard nested
    markers are still an error, in the same response AND in the same
    entry."""
    nested_entry = codec.field_bytes(1, (
        codec.field_string(1, tpumetrics.DUTY_CYCLE)
        + codec.field_bytes(3, tpumetrics.encode_metric_nested(
            tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 0, 1.0)))
    ))
    one_entry_both = codec.field_bytes(1, (
        codec.field_string(1, tpumetrics.DUTY_CYCLE)
        + codec.field_varint(2, 1)                       # flat device_id
        + codec.field_bytes(3, b"\x11" + b"\x00" * 8)    # nested-shaped metrics
    ))
    for raw in (
        codec.field_bytes(1, codec.field_string(1, "x")
                          + codec.field_double(3, 1.0)) + nested_entry,
        one_entry_both,
    ):
        with pytest.raises(ValueError):
            tpumetrics.detect_dialect(raw)


def test_alternate_attribute_key_spellings():
    for dkey in sorted(tpumetrics.DEVICE_ATTR_KEYS):
        metric = (
            codec.field_bytes(1, codec.field_string(1, dkey)
                              + codec.field_bytes(2, codec.field_varint(3, 7)))
            + codec.field_bytes(3, codec.field_varint(2, 42))
        )
        body = (codec.field_string(1, tpumetrics.HBM_USED)
                + codec.field_bytes(3, metric))
        samples, _ = tpumetrics.decode_response_ex(codec.field_bytes(1, body))
        assert samples[0].device_id == 7, dkey
    for lkey in sorted(tpumetrics.LINK_ATTR_KEYS):
        metric = (
            codec.field_bytes(1, codec.field_string(1, "device_id")
                              + codec.field_bytes(2, codec.field_varint(3, 0)))
            + codec.field_bytes(1, codec.field_string(1, lkey)
                                + codec.field_bytes(2, codec.field_string(1, "z1")))
            + codec.field_bytes(3, codec.field_varint(2, 9))
        )
        body = (codec.field_string(1, tpumetrics.ICI_TRAFFIC)
                + codec.field_bytes(3, metric))
        samples, _ = tpumetrics.decode_response_ex(codec.field_bytes(1, body))
        assert samples[0].link == "z1", lkey


def test_nested_varint_cannot_overrun_its_window():
    """Fuzz-found regression: a varint whose continuation bytes cross a
    sub-message window boundary must fail, not silently consume the next
    field's bytes (the round-1 decoder relied only on the outer check)."""
    # AttrValue window of length 2 containing `18 bd`: field 3 varint whose
    # payload byte has the continuation bit set — it would terminate only
    # past the window.
    attr = (codec.field_string(1, "device_id")
            + bytes([0x12, 0x02, 0x18, 0xBD]))
    metric = (codec.field_bytes(1, attr)
              + codec.field_bytes(3, codec.field_varint(2, 1)))
    body = (codec.field_string(1, tpumetrics.HBM_USED)
            + codec.field_bytes(3, metric))
    with pytest.raises(ValueError):
        tpumetrics.decode_response_ex(codec.field_bytes(1, body))


def test_name_only_response_is_ambiguous_and_empty():
    """Review finding: an empty nested answer (TPUMetric with a name and
    no metrics) must NOT decode as a flat chip-0/value-0 sample — that
    fabricated phantom devices (discover() would even materialize a
    Device 0 from an empty HBM_TOTAL answer)."""
    raw = tpumetrics.encode_response_nested(tpumetrics.HBM_TOTAL, [])
    assert tpumetrics.detect_dialect(raw) == tpumetrics.AMBIGUOUS
    samples, dialect = tpumetrics.decode_response_ex(raw)
    assert samples == [] and dialect == tpumetrics.AMBIGUOUS
    # Flat name-only (a zero-omitting proto3 encoder at chip 0 / value 0)
    # is the deliberate cost of that choice: also no samples.
    flat_name_only = codec.field_bytes(
        1, codec.field_string(1, tpumetrics.DUTY_CYCLE))
    assert tpumetrics.decode_response(flat_name_only) == []
    # Any second chip or nonzero value disambiguates back to flat.
    two_chips = flat_name_only + tpumetrics.encode_response(
        [tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 1, 2.0)])
    samples, dialect = tpumetrics.decode_response_ex(two_chips)
    assert dialect == tpumetrics.FLAT and len(samples) == 2


def test_direction_attribute_does_not_overwrite_link():
    """Review finding: 'direction' is a sibling dimension, not a link-id
    spelling — it must not collapse distinct links."""
    metric = (
        codec.field_bytes(1, codec.field_string(1, "device_id")
                          + codec.field_bytes(2, codec.field_varint(3, 0)))
        + codec.field_bytes(1, codec.field_string(1, "link_id")
                            + codec.field_bytes(2, codec.field_string(1, "x0")))
        + codec.field_bytes(1, codec.field_string(1, "direction")
                            + codec.field_bytes(2, codec.field_string(1, "tx")))
        + codec.field_bytes(3, codec.field_varint(2, 9))
    )
    body = (codec.field_string(1, tpumetrics.ICI_TRAFFIC)
            + codec.field_bytes(3, metric))
    samples, _ = tpumetrics.decode_response_ex(codec.field_bytes(1, body))
    assert samples[0].link == "x0"
