"""Latency regression harness (SURVEY.md §4: p50 poll-tick latency under
the 50 ms budget with 8 local chips and scripted RPC delays; BASELINE.md
north star). bench.py runs the same harness and reports the number."""

import statistics

from kube_gpu_stats_tpu.bench import run_latency_harness


def test_p50_under_budget_with_scripted_delay(tmp_path):
    result = run_latency_harness(
        tmp_path, num_chips=8, ticks=30, rpc_delay=0.010, warmup=3
    )
    assert result["p50_ms"] < 50.0, result
    # Sanity: the scripted 10 ms RPC delay is actually inside the measurement.
    assert result["p50_ms"] > 8.0, result


def test_latency_scales_sublinearly_with_chips(tmp_path):
    """Per-chip fan-out + batched libtpu fetch: 8 chips must not cost ~8x
    1 chip (the serialized-loop failure mode, SURVEY.md §7 hard part b)."""
    one = run_latency_harness(tmp_path / "a", num_chips=1, ticks=15,
                              rpc_delay=0.010, warmup=3)
    eight = run_latency_harness(tmp_path / "b", num_chips=8, ticks=15,
                                rpc_delay=0.010, warmup=3)
    assert eight["p50_ms"] < one["p50_ms"] * 4, (one, eight)


def test_harness_reports_full_distribution(tmp_path):
    result = run_latency_harness(tmp_path, num_chips=2, ticks=10,
                                 rpc_delay=0.0, warmup=2)
    for key in ("p50_ms", "p90_ms", "p99_ms", "mean_ms", "ticks", "chips"):
        assert key in result
    assert result["ticks"] == 10
    assert result["p50_ms"] <= result["p99_ms"]
    assert result["mean_ms"] == statistics.mean(result["durations_ms"])
