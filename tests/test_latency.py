"""Latency regression harness (SURVEY.md §4: p50 poll-tick latency under
the 50 ms budget with 8 local chips and scripted RPC delays; BASELINE.md
north star). bench.py runs the same harness and reports the number."""

import statistics

from flake import retry_once_on_box_noise

from kube_gpu_stats_tpu.bench import run_latency_harness


# Box-noise retry (the soak/multihost discipline): this harness drives a
# real server subprocess, real sockets and wall-clock pacing, and its
# scrape_p50 pin sits within 2x of the measured number — under full-suite
# load a scheduling burst can push one run over (the ROADMAP scrape-creep
# watch item's noise band) without any code having regressed. One loud
# retry; failing twice still fails the suite.
@retry_once_on_box_noise
def test_p50_under_budget_with_scripted_delay(tmp_path):
    result = run_latency_harness(
        tmp_path, num_chips=8, ticks=30, rpc_delay=0.010, warmup=3
    )
    assert result["p50_ms"] < 50.0, result
    # Pipelined tick (ISSUE 3): the scripted 10 ms RPC flight overlaps
    # the inter-tick gap instead of sitting inside the tick, so the p50
    # must land UNDER the RPC floor — while the RPCs demonstrably keep
    # flowing (the data-sanity half the old `p50 > 8` check carried).
    assert result["p50_ms"] < 8.0, result
    assert result["rpc_calls_per_tick"] > 0, result
    assert result["metrics_per_chip"] > 10, result
    # Scrape-path budget (ISSUE 7 satellite, BENCH_r06 regression pin):
    # with pipelined ticks the background fetch wave contends with an
    # inline render, which took scrape_p50 from ~1.5 ms to ~24 ms. The
    # render pre-warmer serves each scrape the per-generation
    # pre-gzipped bytes, so the measured end-to-end scrape (socket
    # included, under the live pipelined load) must stay sub-5 ms.
    assert result["scrape_p50_ms"] < 5.0, result


def test_blocking_mode_keeps_rpc_inside_the_tick(tmp_path):
    """pipeline_fetch=False (the escape hatch) restores the join-this-
    tick's-fetch contract: the scripted RPC delay is inside the
    measurement — the sanity floor that proves the harness measures the
    transport at all."""
    result = run_latency_harness(
        tmp_path, num_chips=8, ticks=10, rpc_delay=0.010, warmup=2,
        pipeline_fetch=False,
    )
    assert result["p50_ms"] > 8.0, result


def test_latency_scales_sublinearly_with_chips(tmp_path):
    """Per-chip fan-out + batched libtpu fetch: 8 chips must not cost ~8x
    1 chip (the serialized-loop failure mode, SURVEY.md §7 hard part b)."""
    one = run_latency_harness(tmp_path / "a", num_chips=1, ticks=15,
                              rpc_delay=0.010, warmup=3)
    eight = run_latency_harness(tmp_path / "b", num_chips=8, ticks=15,
                                rpc_delay=0.010, warmup=3)
    assert eight["p50_ms"] < one["p50_ms"] * 4, (one, eight)


def test_harness_reports_full_distribution(tmp_path):
    result = run_latency_harness(tmp_path, num_chips=2, ticks=10,
                                 rpc_delay=0.0, warmup=2)
    for key in ("p50_ms", "p90_ms", "p99_ms", "mean_ms", "ticks", "chips"):
        assert key in result
    assert result["ticks"] == 10
    assert result["p50_ms"] <= result["p99_ms"]
    assert result["mean_ms"] == statistics.mean(result["durations_ms"])
    # Flight-recorder pins ride the same harness (ISSUE 4): tracing is
    # ON in the measured loop — spans must actually be recorded — and
    # the per-span overhead ships as a bench field.
    assert result["tick_spans_per_tick"] > 0, result
    assert result["trace_overhead_ns_per_span"] > 0, result


def test_trace_overhead_within_hard_budget():
    """Tracing is on by default, so its per-span cost is a north-star
    input: the enter/exit of one enabled span must stay microseconds.
    Budget generous for CI jitter (measured ~1-2 µs on an idle box);
    the p50 pins above already prove the END-TO-END tick with tracing
    enabled stays under the PR 3 number."""
    from kube_gpu_stats_tpu.tracing import measure_overhead_ns

    ns = measure_overhead_ns()
    assert ns < 25_000, f"span overhead {ns:.0f} ns/span blows the budget"


def test_burst_fold_overhead_under_2pct_of_tick_budget():
    """ISSUE 8 acceptance pin: the burst sampler's cost ON THE TICK
    PATH — draining and folding one full 1 Hz interval's worth of
    100 Hz samples across 8 chips — stays under 2% of the 50 ms tick
    budget (measured ~0.3%). The sampling thread itself runs beside the
    loop (its CPU share ships as burst_thread_cpu_pct), never inside
    the tick. Best of 3 rounds, timeit.repeat style, so a co-tenant
    noise burst can't fail the pin for the code's cost."""
    from kube_gpu_stats_tpu.bench import measure_burst_overhead

    best = None
    for _ in range(3):
        result = measure_burst_overhead(ticks=60, thread_seconds=0.3)
        assert result is not None
        if best is None or result["burst_overhead_pct"] < \
                best["burst_overhead_pct"]:
            best = result
    assert best["burst_overhead_pct"] < 2.0, best
    # The thread achieved a usable fraction of the configured rate
    # (mock read path; a collapse here means the sampling loop itself
    # regressed, not the box).
    assert best["burst_samples_per_sec"] > 100.0, best


def test_hoststats_read_under_budget():
    """ISSUE 10 acceptance pin: one full HostStats.read() over a
    realistic fixture tree (PSI x3, stat, softirqs, NIC, thermal,
    throttle, 8 pod cgroups) stays cheap enough that a single pool
    worker absorbs it per tick with the whole idle window to spare —
    the read lives on the sampler pool (the procstats prefetch
    discipline), never inside the tick budget, and this pin keeps it
    from quietly growing into a pool hog. Best of 3 rounds so a
    co-tenant noise burst can't fail the pin for the code's cost."""
    from kube_gpu_stats_tpu.bench import measure_hoststats

    best = None
    for _ in range(3):
        result = measure_hoststats(reads=30)
        assert result is not None
        if best is None or result["hoststats_read_ms_per_tick"] < \
                best["hoststats_read_ms_per_tick"]:
            best = result
    assert best["hoststats_read_ms_per_tick"] < 10.0, best


def test_scrape_hot_path_p99_under_5ms():
    """ISSUE 7 satellite acceptance: scrape_p99 < 5 ms restored. The
    render pre-warmer fills the per-generation text+gzip cache right
    behind each publish, so a scrape's cost is semaphore + cache lookup
    + socket write. Measured end to end over HTTP against a published
    registry, timeit.repeat style (best round's p99) so a co-tenant
    noise burst can't fail the pin for the code's cost."""
    import time
    import urllib.request

    from kube_gpu_stats_tpu import schema
    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.registry import Registry, SnapshotBuilder

    builder = SnapshotBuilder()
    for chip in range(8):
        labels = (("accel_type", "tpu-v5p"), ("chip", str(chip)),
                  ("device_path", f"/dev/accel{chip}"), ("uuid", ""))
        for spec in schema.PER_DEVICE_METRICS:
            if spec.type is not schema.MetricType.HISTOGRAM:
                builder.add(spec, 42.0, labels)
    registry = Registry()
    registry.publish(builder.build())
    server = MetricsServer(registry, host="127.0.0.1", port=0)
    server.start()
    try:
        # Let the warmer fill the text + gzip entries for this
        # generation (a first-scrape miss would render inline — still
        # correct, just not the steady state this test prices).
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            _, hit = registry.rendered(gzip_level=3)
            if hit:
                break
            time.sleep(0.01)
        request = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/metrics",
            headers={"Accept-Encoding": "gzip"})
        best_p99 = float("inf")
        for _ in range(3):
            samples = []
            for _ in range(40):
                start = time.monotonic()
                urllib.request.urlopen(request, timeout=5).read()
                samples.append((time.monotonic() - start) * 1000.0)
            samples.sort()
            best_p99 = min(best_p99, samples[int(len(samples) * 0.99)])
        assert best_p99 < 5.0, f"warm scrape p99 {best_p99:.2f} ms"
    finally:
        server.stop()


@retry_once_on_box_noise
def test_federation_root_refresh_under_budget():
    """ISSUE 7 acceptance: 4096 simulated workers behind 64 leaf delta
    sessions, root-hub WARM refresh p50 under 10 ms (best spaced
    round's median — the bench's own statistic). ISSUE 11 adds the
    ingest pin: one full wave of leaf delta frames must apply in under
    9 ms (single-lane handler work — the r07→r09 drift class 12.0 →
    16.9 ms went behind the native batch store; the r13→r16 creep
    7.5 → 12.6 ms went behind the admission-hoist + native slot decode
    of ISSUE 17 — measured ~5 ms, ~8 ms under full-suite load; the
    box-noise retry covers the tail)."""
    from kube_gpu_stats_tpu.bench import measure_delta_federation

    result = measure_delta_federation()
    assert result is not None
    assert result["workers"] == 4096
    assert result["root_merge_p50_ms"] < 10.0, result
    assert result["delta_ingest_ms_per_refresh"] < 9.0, result


@retry_once_on_box_noise
def test_hub_merge_cold_refresh_under_budget():
    """ISSUE 17 satellite: the COLD first refresh (every body parsed,
    every merge plan compiled) over the 64-worker slice fixture must
    stay under 90 ms — the r13→r16 drift took it 51 → 73 ms; the
    shape-keyed plan/program memos claw it back (measured ~40-55 ms in
    a warm process) and this pin keeps plan compilation off the cold
    path for good."""
    from kube_gpu_stats_tpu.bench import measure_hub_merge

    result = measure_hub_merge()
    assert result is not None
    assert result["cold_ms"] < 90.0, result


def test_ingest_storm_10k_pushers_refresh_interval_bounded():
    """ISSUE 11 acceptance: 10k synthesized pushers against one hub.
    One full wave of per-pusher delta frames (the handler-thread work
    one refresh interval absorbs) must stay a small fraction of the
    10 s interval — measured ~120 ms native; the 2.5 s pin catches the
    drift class without flaking a loaded CI box — and a fleet-wide
    resync storm (every session re-POSTing a FULL at once, concurrent
    threads) must recover with ZERO dropped sessions inside one
    interval."""
    from kube_gpu_stats_tpu.bench import measure_ingest_storm

    result = measure_ingest_storm(pushers=10_000, waves=1)
    assert result is not None
    assert result["delta_ingest_10k_ms_per_refresh"] < 2_500.0, result
    assert result["ingest_cpu_pct"] < 25.0, result
    # Resync-storm survival: >= 256 simultaneous FULLs is the
    # acceptance floor; the storm here is the whole 10k fleet.
    assert result["resync_storm_sessions"] >= 10_000, result
    assert result["resync_storm_dropped"] == 0, result
    assert result["resync_storm_served"] == 10_000, result
    assert result["resync_storm_recovery_s"] < 10.0, result


def test_warm_restart_recovery_time_and_resume_fraction():
    """ISSUE 12 acceptance (recovery-time pin): a hub killed at its
    checkpoint state and restarted must resume >= 95% of 2k sessions'
    delta chains without a FULL resync — only the crash-window tail
    (sessions whose seq advanced after the last WAL write, 2% here)
    pays one — with zero sessions dropped and the whole fleet re-served
    by push inside a fraction of one refresh interval. Generous wall
    bounds for CI boxes; measured ~0.1 s replay at 2k on an idle one."""
    from kube_gpu_stats_tpu.bench import measure_warm_restart

    result = measure_warm_restart(pushers=2_000)
    assert result is not None
    assert result["resumed_fraction"] >= 0.95, result
    assert result["dropped"] == 0, result
    assert result["replay_s"] < 10.0, result
    assert result["recovery_s"] < 20.0, result


def test_overload_shed_priority_and_fairness():
    """ISSUE 12 acceptance (shed-fairness pin): a 4x-budget delta
    stampede over 256 established sessions must shed with 429 +
    Retry-After (the guard engages), never refuse a recovery FULL
    (shed priority: chatty deltas first, session recovery always
    admitted), never drop an established session (shed is load
    shaping, not eviction), keep the new-session memory fence closed
    at capacity, and spread the shed burden so every source still
    lands deltas (fairness — no source starved outright)."""
    from kube_gpu_stats_tpu.bench import measure_overload_shed

    result = measure_overload_shed()
    assert result is not None
    assert result["delta_shed"] > 0, result
    assert result["full_refused"] == 0, result
    assert result["fence_held"], result
    assert result["sessions_alive"] == result["pushers"], result
    assert result["sources_served_fraction"] >= 0.9, result


def test_partition_drain_throughput_and_spool_cost():
    """ISSUE 13 acceptance pins: the spill queue's fsynced spool write
    (the partition-mode per-tick hot path) must stay a rounding error
    next to the 1 Hz poll interval, the on-disk cost per spooled
    snapshot must stay in compressed-frame territory (the spool sizing
    table assumes ~KB/tick, not the raw exposition), and the drain must
    move a 200-frame backlog over real HTTP fast enough that the
    --hub-drain-rate knob — not the implementation — is the limiter.
    Best of 3 rounds, timeit.repeat style, so a co-tenant noise burst
    can't fail the pin for the code's cost."""
    from kube_gpu_stats_tpu.bench import measure_partition_drain

    best = None
    for _ in range(3):
        result = measure_partition_drain()
        assert result is not None
        if best is None or result["partition_drain_frames_per_s"] > \
                best["partition_drain_frames_per_s"]:
            best = result
    assert best["spill_spool_ms_per_frame"] < 50.0, best
    assert best["spill_bytes_per_tick"] < 16_384, best
    assert best["partition_drain_frames_per_s"] > 100.0, best
    assert best["partition_catchup_s"] < 10.0, best
    assert best["spill_dropped"] == 0, best


def test_degraded_store_overhead_under_10pct_of_tick_budget():
    """ISSUE 15 acceptance pin: while the disk-backed stores are
    DEGRADED (full disk latched, probes far away), the per-tick store
    ops must take the gated in-memory path — under 10% of the 50 ms
    tick budget, and in practice cheaper than the healthy fsync path.
    Guards a regression where degraded mode grows per-op retries,
    probing or logging. Best of 3 rounds (timeit.repeat style) so a
    co-tenant noise burst can't fail the pin."""
    from kube_gpu_stats_tpu.bench import measure_degraded_overhead

    best = None
    for _ in range(3):
        result = measure_degraded_overhead(ticks=100)
        assert result is not None
        if best is None or result["degraded_overhead_pct"] < \
                best["degraded_overhead_pct"]:
            best = result
    assert best["degraded_overhead_pct"] < 10.0, best
    # Every degraded-window spool is in the loss ledger — the exact
    # accounting the localfault sim asserts end to end.
    assert best["degraded_lost_counted"] == 100, best


def test_cardinality_admission_overhead_under_2pct_of_ingest():
    """ISSUE 16 acceptance pin: the cardinality accountant's hot-path
    bookkeeping (admit + install per FULL) must stay under 2% of the
    full ingest path's per-series cost (measured ~0.2% — two absolute
    measurements ratioed, not a noisy A/B difference). Guards a
    regression where admission grows per-series work (a per-label walk,
    a sort, an allocation) onto every frame of every healthy pusher.
    Best of 3 rounds so a co-tenant noise burst can't fail the pin."""
    from kube_gpu_stats_tpu.bench import measure_cardinality_admission

    best = None
    for _ in range(3):
        result = measure_cardinality_admission(
            pushers=128, frames=20, bomb_series=20_000, bomb_frames=2)
        assert result is not None
        if best is None or result["cardinality_admission_overhead_pct"] \
                < best["cardinality_admission_overhead_pct"]:
            best = result
    assert best["cardinality_admission_overhead_pct"] < 2.0, best
    # The bomb was clamped: the ledger holds the budget, not the bomb
    # (the RSS half of the claim is pinned in tools/cardinality_sim.py).
    assert best["bomb_live_series"] < 2_000, best


def test_render_cost_bounded_at_32_chip_full_label_scale():
    """Round-1 verdict item 7 (done round 3): series growth must not
    silently eat the scrape budget. Render a 32-chip snapshot with the
    full label surface (attribution, topology, 6 ICI links as the mock
    emits them, an 8-process holder table per device, self metrics) and
    assert the render cost stays a small fraction of the 50 ms budget.
    BASELINE.md records the measured number next to the poll numbers."""
    import time

    from kube_gpu_stats_tpu.collectors.mock import MockCollector
    from kube_gpu_stats_tpu.poll import PollLoop
    from kube_gpu_stats_tpu.registry import Registry

    class FakeAttribution:
        def lookup(self, device):
            return {"pod": f"train-{device.index}", "namespace": "ml",
                    "container": "worker"}

    holders = [(str(1000 + i), f"proc{i}", "", 1.0) for i in range(8)]
    reg = Registry()
    loop = PollLoop(
        MockCollector(num_devices=32, accel_type="tpu-v5p"),
        reg, deadline=5.0,
        attribution=FakeAttribution(),
        topology_labels={"slice": "v5p-256", "worker": "0",
                         "topology": "8x8x4"},
        process_openers=lambda path: holders,
    )
    loop.tick()
    loop.tick()  # second tick: ICI rates join the series set
    loop.stop()
    snapshot = reg.snapshot()
    series_count = len(snapshot.series)
    assert series_count > 700, series_count  # the scale this test claims

    renders = []
    for _ in range(20):
        start = time.perf_counter()
        text = snapshot.render()
        renders.append((time.perf_counter() - start) * 1000.0)
    renders.sort()
    p50 = renders[len(renders) // 2]
    # Budget share: a scrape render an order of magnitude under the 50 ms
    # collection budget leaves the budget to collection. Generous for CI
    # jitter; the measured number on an idle box is ~1-2 ms.
    assert p50 < 10.0, f"render p50 {p50:.2f} ms for {series_count} series"
    assert len(text) > 100_000  # the render actually carried the series


@retry_once_on_box_noise
def test_query_serving_stampede_pins():
    """ISSUE 18 acceptance pins: 256 keep-alive dashboard readers
    against a LIVE-refreshing hub see query p99 < 25 ms (the
    pre-rendered per-(family, window, generation) response cache is
    the mechanism — a reader never pays a render or a gzip), >= 50%
    of If-None-Match /metrics scrapes answer 304 once the generation
    holds, the ring's per-refresh write cost stays in microsecond
    territory (measured ~1 ms against a 10 ms pin for box headroom),
    and the ring's slab footprint stays a fixed few MB. Real sockets,
    wall-clock pacing and a 1-core-CI thread ballet — box-noise retry,
    same discipline as the harness pin above."""
    from kube_gpu_stats_tpu.bench import measure_query_serving

    result = measure_query_serving()
    assert result is not None
    assert result["query_p99_ms_256readers"] < 25.0, result
    assert result["query_p50_ms_256readers"] < 15.0, result
    assert result["scrape_304_ratio"] >= 0.5, result
    assert result["history_write_ns_per_refresh"] < 10e6, result
    assert result["history_rss_mb"] < 20.0, result
