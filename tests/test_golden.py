"""Golden exposition test (SURVEY.md §4: "metric-schema goldens ... compared
against golden .prom files"). Regenerate with:

    GOLDEN_UPDATE=1 python -m pytest tests/test_golden.py
"""

import itertools
import os
import pathlib

from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry
from kube_gpu_stats_tpu.tracing import Tracer

GOLDEN = pathlib.Path(__file__).parent / "golden" / "mock_2dev.prom"


class FakeAttribution:
    def lookup(self, device):
        if device.device_id == "0":
            return {"pod": "train-abc", "namespace": "ml", "container": "worker"}
        return {}


def render_two_ticks() -> str:
    reg = Registry()
    clock = itertools.count(100.0, 0.5).__next__  # deterministic monotonic
    loop = PollLoop(
        MockCollector(num_devices=2),
        reg,
        deadline=5.0,
        attribution=FakeAttribution(),
        topology_labels={"slice": "test-slice", "worker": "0", "topology": "2x2x1"},
        version="golden",
        process_metrics=False,  # /proc values are nondeterministic
        # Disabled recorder: the kts_tick_phase_seconds digest carries
        # real perf-counter durations, which are nondeterministic.
        tracer=Tracer(enabled=False),
        clock=clock,
    )
    loop.tick()
    loop.tick()
    loop.stop()
    text = reg.snapshot().render()
    # The poll-duration histogram depends on wall time via the fake clock
    # only, so the whole exposition is deterministic.
    return text


def test_matches_golden():
    text = render_two_ticks()
    if os.environ.get("GOLDEN_UPDATE"):
        GOLDEN.parent.mkdir(exist_ok=True)
        GOLDEN.write_text(text)
    assert GOLDEN.exists(), "golden missing; run with GOLDEN_UPDATE=1"
    assert text == GOLDEN.read_text()


def _populated_registry():
    reg = Registry()
    loop = PollLoop(
        MockCollector(num_devices=2), reg, deadline=5.0,
        attribution=FakeAttribution(),
        topology_labels={"slice": "test-slice", "worker": "0",
                         "topology": "2x2x1"},
        version="golden", process_metrics=False,
        clock=itertools.count(100.0, 0.5).__next__,
    )
    loop.tick()
    loop.stop()
    return reg


def test_cached_render_byte_identical_to_uncached():
    """The one-render-per-generation cache (Registry.rendered) must be
    invisible in the bytes: text and gzip, classic and OpenMetrics, all
    byte-identical to an uncached Snapshot.render() of the same
    snapshot. gzip is compared against mtime=0 compression — the pinned
    determinism contract of the cached path."""
    import gzip

    reg = _populated_registry()
    snapshot = reg.snapshot()
    for openmetrics in (False, True):
        uncached = snapshot.render(openmetrics=openmetrics).encode()
        body, hit = reg.rendered(openmetrics=openmetrics)
        assert not hit  # first read of this generation renders
        assert body == uncached
        body, hit = reg.rendered(openmetrics=openmetrics)
        assert hit  # second read is the memoized bytes
        assert body == uncached
        gz, _ = reg.rendered(openmetrics=openmetrics, gzip_level=3)
        assert gz == gzip.compress(uncached, compresslevel=3, mtime=0)
        assert gzip.decompress(gz) == uncached
        gz2, hit = reg.rendered(openmetrics=openmetrics, gzip_level=3)
        assert hit and gz2 == gz


def test_render_cache_invalidates_on_publish():
    from kube_gpu_stats_tpu.registry import SnapshotBuilder

    reg = _populated_registry()
    before, _ = reg.rendered()
    reg.publish(SnapshotBuilder().build())
    after, hit = reg.rendered()
    assert not hit  # new generation: the cache must not serve old bytes
    assert after != before
    assert after == reg.snapshot().render().encode()


def test_http_scrape_serves_cached_bytes_identical(tmp_path):
    """End to end through the production MetricsServer: a gzip scrape
    and a plain scrape both match the uncached render, and repeated
    scrapes (cache hits) keep serving the same bytes."""
    import gzip
    import urllib.request

    from kube_gpu_stats_tpu.exposition import MetricsServer

    reg = _populated_registry()
    uncached = reg.snapshot().render().encode()
    server = MetricsServer(reg, host="127.0.0.1", port=0)
    server.start()
    url = f"http://127.0.0.1:{server.port}/metrics"
    try:
        for _ in range(2):  # second pass is a guaranteed cache hit
            with urllib.request.urlopen(url, timeout=5) as resp:
                assert resp.read() == uncached
            request = urllib.request.Request(
                url, headers={"Accept-Encoding": "gzip"})
            with urllib.request.urlopen(request, timeout=5) as resp:
                assert resp.headers.get("Content-Encoding") == "gzip"
                assert gzip.decompress(resp.read()) == uncached
    finally:
        server.stop()
