"""Golden exposition test (SURVEY.md §4: "metric-schema goldens ... compared
against golden .prom files"). Regenerate with:

    GOLDEN_UPDATE=1 python -m pytest tests/test_golden.py
"""

import itertools
import os
import pathlib

from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry

GOLDEN = pathlib.Path(__file__).parent / "golden" / "mock_2dev.prom"


class FakeAttribution:
    def lookup(self, device):
        if device.device_id == "0":
            return {"pod": "train-abc", "namespace": "ml", "container": "worker"}
        return {}


def render_two_ticks() -> str:
    reg = Registry()
    clock = itertools.count(100.0, 0.5).__next__  # deterministic monotonic
    loop = PollLoop(
        MockCollector(num_devices=2),
        reg,
        deadline=5.0,
        attribution=FakeAttribution(),
        topology_labels={"slice": "test-slice", "worker": "0", "topology": "2x2x1"},
        version="golden",
        process_metrics=False,  # /proc values are nondeterministic
        clock=clock,
    )
    loop.tick()
    loop.tick()
    loop.stop()
    text = reg.snapshot().render()
    # The poll-duration histogram depends on wall time via the fake clock
    # only, so the whole exposition is deterministic.
    return text


def test_matches_golden():
    text = render_two_ticks()
    if os.environ.get("GOLDEN_UPDATE"):
        GOLDEN.parent.mkdir(exist_ok=True)
        GOLDEN.write_text(text)
    assert GOLDEN.exists(), "golden missing; run with GOLDEN_UPDATE=1"
    assert text == GOLDEN.read_text()
