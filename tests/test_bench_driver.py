"""The driver entry point (repo-root bench.py): JSON-line shape and the
round-end real-mode retry (round-4 verdict, weak 1 — a tunnel that
recovers while the simulated harness runs must still yield a real-mode
artifact, with both modes' fields in the same line)."""

import importlib.util
import json
import os
import pathlib

import pytest

from kube_gpu_stats_tpu import bench as bench_mod


class _Exit(Exception):
    pass


def run_main(capsys, monkeypatch) -> dict:
    """Execute bench.py main() with os._exit neutralized; returns the
    parsed JSON line."""
    path = pathlib.Path(__file__).parent.parent / "bench.py"
    spec = importlib.util.spec_from_file_location("bench_driver", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(os, "_exit", lambda code: (_ for _ in ()).throw(
        _Exit(str(code))))
    with pytest.raises(_Exit, match="0"):
        mod.main()
    out = capsys.readouterr().out.strip().splitlines()
    return json.loads(out[-1])


def _measurement(mode: str, p50: float) -> dict:
    return {
        "p50_ms": p50, "p90_ms": p50 * 1.2, "p99_ms": p50 * 1.5,
        "metrics_per_chip": 20.0, "max_hz": 1000.0 / p50,
        "scrape_p50_ms": 1.0, "scrape_p99_ms": 2.0,
        "mode": mode, "chips": 8,
        "path": "embedded" if mode == "real" else "fake-grpc",
    }


def test_round_end_retry_recovers_real_mode(capsys, monkeypatch):
    """Tunnel wedged at bench start, back by round end: the retry's real
    measurement becomes the headline and the simulated section ships
    alongside it — both modes in ONE artifact."""
    calls = {"real": 0}

    def fake_real(**kwargs):
        calls["real"] += 1
        if calls["real"] == 1:
            return None, {"jax_platform": None, "first": True}
        real = _measurement("real", 0.5)
        real["workload_mfu_pct_during_bench"] = 42.0
        real["mfu_sweep"] = [{"size": 4096, "tflops_per_s": 100.0}]
        return real, {"jax_platform": "tpu"}

    monkeypatch.setattr(bench_mod, "try_real_harness", fake_real)
    monkeypatch.setattr(bench_mod, "try_embedded_harness",
                        lambda probe, **kw: None)
    monkeypatch.setattr(bench_mod, "run_latency_harness",
                        lambda *a, **kw: _measurement("simulated", 11.0))
    monkeypatch.setattr(
        bench_mod, "measure_hub_merge",
        lambda workers=64, **kw: {
            "p50_ms": 22.0 if workers == 64 else 55.0,
            "cold_ms": 30.0 if workers == 64 else 80.0,
            "body_cache_hit_rate": 0.8, "parse_mb_per_s": 40.0,
            "render_cache_hits": 3})

    line = run_main(capsys, monkeypatch)
    assert calls["real"] == 2
    assert line["mode"] == "real"
    assert line["metric"].endswith("_real")
    assert line["value"] == 0.5
    assert line["workload_mfu_pct_during_bench"] == 42.0
    assert line["mfu_sweep"] == [{"size": 4096, "tflops_per_s": 100.0}]
    # The simulated run is not discarded: its figures ride along so the
    # regression pin survives a real round.
    assert line["simulated"]["p50_ms"] == 11.0
    assert line["simulated"]["chips"] == 8
    assert line["real_probe"]["first"] is True
    assert line["real_probe"]["round_end_retry"] == {"jax_platform": "tpu"}
    # Hub ingest/merge figures at both fan-in shapes, with the cache
    # evidence fields alongside the latency headline.
    assert line["hub_merge_64w_p50_ms"] == 22.0
    assert line["hub_merge_64w_cold_ms"] == 30.0
    assert line["hub_merge_256w_p50_ms"] == 55.0
    assert line["hub_body_cache_hit_rate"] == 0.8
    assert line["hub_parse_mb_per_s"] == 40.0
    assert line["hub_render_cache_hits"] == 3


def test_retry_failure_stays_simulated_with_probe_evidence(capsys,
                                                          monkeypatch):
    """Tunnel down the whole run: simulated headline, no simulated
    sub-section (it IS the headline), and BOTH probes recorded so the
    artifact explains itself."""
    monkeypatch.setattr(
        bench_mod, "try_real_harness",
        lambda **kw: (None, {"jax_platform": None}))
    monkeypatch.setattr(bench_mod, "try_embedded_harness",
                        lambda probe, **kw: None)
    monkeypatch.setattr(bench_mod, "run_latency_harness",
                        lambda *a, **kw: _measurement("simulated", 11.0))
    monkeypatch.setattr(bench_mod, "measure_hub_merge",
                        lambda *a, **kw: None)

    line = run_main(capsys, monkeypatch)
    assert line["mode"] == "simulated"
    assert line["value"] == 11.0
    assert "simulated" not in line  # no duplicate section
    assert line["real_probe"]["round_end_retry"] == {"jax_platform": None}
    assert "hub_merge_64w_p50_ms" not in line
    # vs_baseline: 50ms budget over the measured p50.
    assert line["vs_baseline"] == pytest.approx(50.0 / 11.0, abs=1e-3)
