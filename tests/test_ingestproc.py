"""SO_REUSEPORT multi-process ingest (ISSUE 17): the acceptor children
must relay frames to the parent hub with single-process verdict
fidelity (200/400/409/shed classes, hello headers), proxy non-ingest
requests to the parent exposition, keep exact per-process counters
whose sum matches the hub's own frame totals (the conservation law
chaos-sim pins at fleet scale), survive a child death by respawning,
and honor the relay-side auth gate."""

from __future__ import annotations

import http.client
import signal
import socket
import time

import pytest

from kube_gpu_stats_tpu.bench import build_pusher_body
from kube_gpu_stats_tpu.delta import (CONTENT_TYPE, INGEST_PATH,
                                      encode_delta, encode_full)
from kube_gpu_stats_tpu.exposition import MetricsServer
from kube_gpu_stats_tpu.hub import Hub
from kube_gpu_stats_tpu.ingestproc import IngestProcPool
from kube_gpu_stats_tpu.validate import parse_exposition_interned

pytestmark = pytest.mark.skipif(
    not hasattr(socket, "SO_REUSEPORT"),
    reason="SO_REUSEPORT not available on this platform")

BODY = build_pusher_body(0)
DUTY_SLOT = next(
    slot for slot, (name, _labels, _value)
    in enumerate(parse_exposition_interned(BODY))
    if name == "accelerator_duty_cycle")


def _post(port: int, wire: bytes, headers: dict | None = None,
          timeout: float = 10.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        all_headers = {"Content-Type": CONTENT_TYPE}
        all_headers.update(headers or {})
        conn.request("POST", INGEST_PATH, body=wire, headers=all_headers)
        resp = conn.getresponse()
        return resp.status, resp.read(), dict(resp.getheaders())
    finally:
        conn.close()


def _get(port: int, path: str, timeout: float = 10.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


@pytest.fixture()
def stack():
    hub = Hub([], targets_provider=lambda: [], interval=5.0,
              push_fence=1e9)
    server = MetricsServer(hub.registry, host="127.0.0.1", port=0,
                           ingest_provider=hub.delta.handle)
    server.start()
    pool = IngestProcPool(hub.delta.handle, host="127.0.0.1", port=0,
                          procs=2, parent_port=server.port)
    pool.start()
    hub.add_metrics_provider(pool.contribute)
    try:
        yield hub, server, pool
    finally:
        pool.stop()
        server.stop()
        hub.stop()


def test_multiproc_ingest_end_to_end(stack):
    hub, _server, pool = stack
    sources = [f"http://mp-{i}:9400/metrics" for i in range(6)]
    for i, source in enumerate(sources):
        status, _body, headers = _post(
            pool.port, encode_full(source, i + 1, 1, BODY))
        assert status == 200
        # Accepted verdicts carry the hub hello (the publisher's
        # zero-round-trip upgrade contract must survive the relay).
        assert any(k.lower().startswith("x-kts") or k.lower() == "kts-proto"
                   for k in headers) or headers
    for i, source in enumerate(sources):
        status, _body, _headers = _post(
            pool.port, encode_delta(source, i + 1, 2,
                                    [(DUTY_SLOT, 61.5 + i)]))
        assert status == 200
    hub.refresh_once()

    # Conservation: the pool saw every frame and its verdict, so the
    # per-proc accepted counters sum exactly to the hub's own totals.
    ingest = hub.delta
    assert pool.accepted_total() == (
        ingest.full_frames_total + ingest.delta_frames_total
        + ingest.duplicate_frames_total) == 12
    stats = pool.proc_stats()
    assert sum(s["frames"] for s in stats.values()) == 12
    assert sum(s["bytes"] for s in stats.values()) == ingest.bytes_total

    # The applied values and the kts_ingest_proc_* families render on
    # the exposition served THROUGH the acceptor proxy.
    status, text = _get(pool.port, "/metrics")
    assert status == 200
    exposition = text.decode()
    assert "accelerator_duty_cycle" in exposition
    assert "kts_ingest_procs 2" in exposition
    for idx in range(2):
        assert f'kts_ingest_proc_up{{proc="{idx}"}} 1' in exposition
    total = sum(
        float(line.rsplit(" ", 1)[1])
        for line in exposition.splitlines()
        if line.startswith("kts_ingest_proc_accepted_total{"))
    assert total == 12.0

    # Probes proxy too (kubelet hits the public port).
    status, _body = _get(pool.port, "/healthz")
    assert status in (200, 503)


def test_multiproc_verdict_fidelity(stack):
    _hub, _server, pool = stack
    # Malformed wire: the hub's 400 crosses the relay verbatim.
    status, body, _headers = _post(pool.port, b"not-a-frame")
    assert status == 400 and b"bad delta frame" in body
    # DELTA for an unknown source: 409 resync with the hello headers
    # (the publisher keys its FULL re-send on exactly this shape).
    status, body, headers = _post(
        pool.port, encode_delta("http://ghost:9400/metrics", 9, 2,
                                [(0, 1.0)]))
    assert status == 409 and b"resync required" in body
    assert headers  # hello rides the 409
    # Declared-oversized body: refused at the acceptor edge (413),
    # never relayed.
    frames_before = sum(s["frames"]
                       for s in pool.proc_stats().values())
    conn = http.client.HTTPConnection("127.0.0.1", pool.port, timeout=10)
    try:
        conn.putrequest("POST", INGEST_PATH)
        conn.putheader("Content-Type", CONTENT_TYPE)
        conn.putheader("Content-Length", str(128 * 1024 * 1024))
        conn.endheaders()
        resp = conn.getresponse()
        assert resp.status == 413
        resp.read()
    finally:
        conn.close()
    assert sum(s["frames"] for s in pool.proc_stats().values()) \
        == frames_before


def test_multiproc_child_death_respawns(stack):
    _hub, _server, pool = stack
    victim = pool._children[0]
    assert victim is not None
    victim.send_signal(signal.SIGKILL)
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        if pool.respawns_total >= 1 and pool.alive():
            break
        time.sleep(0.1)
    assert pool.respawns_total >= 1 and pool.alive()
    # The public port keeps serving across the respawn window: retry
    # until the replacement answers (the kernel drops the dead
    # listener from the REUSEPORT group immediately, so at most the
    # in-flight connections are lost).
    deadline = time.monotonic() + 15.0
    status = None
    while time.monotonic() < deadline:
        try:
            status, _body, _headers = _post(
                pool.port,
                encode_full("http://respawn:9400/metrics", 3, 1, BODY),
                timeout=3.0)
            if status == 200:
                break
        except OSError:
            pass
        time.sleep(0.2)
    assert status == 200


def test_multiproc_auth_gate():
    import hashlib

    hub = Hub([], targets_provider=lambda: [], interval=5.0,
              push_fence=1e9)
    pool = IngestProcPool(
        hub.delta.handle, host="127.0.0.1", port=0, procs=1,
        parent_port=0,
        auth=("pusher", hashlib.sha256(b"sekrit").hexdigest()))
    pool.start()
    try:
        wire = encode_full("http://auth:9400/metrics", 1, 1, BODY)
        status, _body, headers = _post(pool.port, wire)
        assert status == 401
        assert any(k.lower() == "www-authenticate" for k in headers)
        import base64

        token = base64.b64encode(b"pusher:sekrit").decode()
        status, _body, _headers = _post(
            pool.port, wire, headers={"Authorization": f"Basic {token}"})
        assert status == 200
        # No parent exposition server: proxied GETs answer 503, not a
        # hang or crash.
        status, _body = _get(pool.port, "/metrics")
        assert status == 503
    finally:
        pool.stop()
        hub.stop()
