"""Fused native wire decode+ingest (_wirefast): equivalence with the
pure-Python ingest path, error contract, fuzz parity. Skipped when the
extension isn't built."""

import pytest

wirefast = pytest.importorskip("kube_gpu_stats_tpu.native._wirefast",
                               reason="_wirefast.so not built")


@pytest.fixture
def loaded_wirefast():
    from kube_gpu_stats_tpu.native import load_wirefast

    wf = load_wirefast()
    assert wf is not None
    return wf


def _payload(**server_kw):
    from kube_gpu_stats_tpu.proto import tpumetrics
    from kube_gpu_stats_tpu.testing.libtpu_server import FakeLibtpuServer

    srv = FakeLibtpuServer(**server_kw)
    return srv._handle(tpumetrics.encode_request(""), None)


def _both(loaded_wirefast, raw):
    """Run fused and Python ingest on raw; return (fused_outcome,
    py_outcome) where outcome is ('ok', cache) or ('err', exc_type)."""
    from kube_gpu_stats_tpu.collectors.libtpu import ingest_response_py

    results = []
    for ingest in (loaded_wirefast.ingest, ingest_response_py):
        cache = {}
        try:
            ingest(raw, cache)
            results.append(("ok", cache))
        except (ValueError, OverflowError) as exc:
            results.append(("err", type(exc)))
    return results


def test_wirefast_matches_python_ingest(loaded_wirefast):
    for kw in ({"num_chips": 8}, {"num_chips": 1}, {"num_chips": 4,
                                                    "chip_offset": 4}):
        raw = _payload(**kw)
        fused, py = _both(loaded_wirefast, raw)
        assert fused[0] == "ok" and fused == py


def test_wirefast_unknown_metric_and_fields_skipped(loaded_wirefast):
    """Forward compat: unknown metric names and unknown fields must be
    ignored by both paths identically."""
    from kube_gpu_stats_tpu.proto import codec, tpumetrics

    metric = (codec.field_string(1, "tpu.runtime.future.metric") +
              codec.field_varint(2, 0) + codec.field_double(3, 1.5) +
              codec.field_varint(99, 7))   # unknown field too
    known = (codec.field_string(1, tpumetrics.DUTY_CYCLE) +
             codec.field_varint(2, 0) + codec.field_double(3, 42.0))
    raw = codec.field_bytes(1, metric) + codec.field_bytes(1, known)
    fused, py = _both(loaded_wirefast, raw)
    assert fused == py
    assert fused[0] == "ok"
    assert list(fused[1][0]["values"].values()) == [42.0]


def test_wirefast_wire_type_mismatch_is_valueerror(loaded_wirefast):
    from kube_gpu_stats_tpu.proto import codec

    bad_metric = codec.field_varint(1, 99) + codec.field_varint(2, 0)
    with pytest.raises(ValueError):
        loaded_wirefast.ingest(codec.field_bytes(1, bad_metric), {})
    with pytest.raises(ValueError):
        loaded_wirefast.ingest(codec.field_varint(1, 5), {})
    with pytest.raises(ValueError):
        loaded_wirefast.ingest(b"\xff\xff\xff\xff", {})


def test_wirefast_fuzz_equivalence(loaded_wirefast):
    """Mutated and random payloads must produce identical outcomes on the
    fused and Python paths: same cache, or both rejecting."""
    import random

    rng = random.Random(20260729)
    base = _payload(num_chips=4)
    for trial in range(400):
        blob = bytearray(base)
        for _ in range(rng.randrange(1, 6)):
            blob[rng.randrange(len(blob))] = rng.randrange(256)
        fused, py = _both(loaded_wirefast, bytes(blob))
        if fused[0] == "err" and py[0] == "err":
            continue  # both rejected; exact exception type may differ
        assert fused == py, (trial, bytes(blob))
    for trial in range(400):
        blob = bytes(rng.randrange(256) for _ in range(rng.randrange(80)))
        fused, py = _both(loaded_wirefast, blob)
        if fused[0] == "err" and py[0] == "err":
            continue
        assert fused == py, (trial, blob)


def test_collector_fused_ingest_is_all_or_nothing():
    """A corrupt tail must not publish the leading valid metrics (review
    finding: raw _wirefast.ingest mutates as it parses; the collector wraps
    it with staging)."""
    from kube_gpu_stats_tpu.collectors.libtpu import _load_wirefast
    from kube_gpu_stats_tpu.proto import codec, tpumetrics

    fused = _load_wirefast()
    assert fused is not None
    good = codec.field_bytes(1, (
        codec.field_string(1, tpumetrics.DUTY_CYCLE) +
        codec.field_varint(2, 0) + codec.field_double(3, 42.0)
    ))
    corrupt = good + codec.field_bytes(1, codec.field_varint(1, 99))
    cache = {}
    with pytest.raises(ValueError):
        fused(corrupt, cache)
    assert cache == {}
    fused(good, cache)
    assert cache[0]["values"]
