"""Fused native wire decode+ingest (_wirefast): equivalence with the
pure-Python ingest path, error contract, fuzz parity. Skipped when the
extension isn't built."""

import pytest

wirefast = pytest.importorskip("kube_gpu_stats_tpu.native._wirefast",
                               reason="_wirefast.so not built")


@pytest.fixture
def loaded_wirefast():
    from kube_gpu_stats_tpu.native import load_wirefast

    wf = load_wirefast()
    assert wf is not None
    return wf


def _payload(**server_kw):
    from kube_gpu_stats_tpu.proto import tpumetrics
    from kube_gpu_stats_tpu.testing.libtpu_server import FakeLibtpuServer

    srv = FakeLibtpuServer(**server_kw)
    return srv._handle(tpumetrics.encode_request(""), None)


def _both(loaded_wirefast, raw):
    """Run fused and Python ingest on raw; return (fused_outcome,
    py_outcome) where outcome is ('ok', cache) or ('err', exc_type)."""
    from kube_gpu_stats_tpu.collectors.libtpu import ingest_response_py

    results = []
    for ingest in (loaded_wirefast.ingest, ingest_response_py):
        cache = {}
        try:
            ingest(raw, cache)
            results.append(("ok", cache))
        except (ValueError, OverflowError) as exc:
            results.append(("err", type(exc)))
    return results


def test_wirefast_matches_python_ingest(loaded_wirefast):
    for kw in ({"num_chips": 8}, {"num_chips": 1}, {"num_chips": 4,
                                                    "chip_offset": 4}):
        raw = _payload(**kw)
        fused, py = _both(loaded_wirefast, raw)
        assert fused[0] == "ok" and fused == py


def test_wirefast_unknown_metric_and_fields_skipped(loaded_wirefast):
    """Forward compat: unknown metric names and unknown fields must be
    ignored by both paths identically."""
    from kube_gpu_stats_tpu.proto import codec, tpumetrics

    metric = (codec.field_string(1, "tpu.runtime.future.metric") +
              codec.field_varint(2, 0) + codec.field_double(3, 1.5) +
              codec.field_varint(99, 7))   # unknown field too
    known = (codec.field_string(1, tpumetrics.DUTY_CYCLE) +
             codec.field_varint(2, 0) + codec.field_double(3, 42.0))
    raw = codec.field_bytes(1, metric) + codec.field_bytes(1, known)
    fused, py = _both(loaded_wirefast, raw)
    assert fused == py
    assert fused[0] == "ok"
    assert list(fused[1][0]["values"].values()) == [42.0]


def test_wirefast_wire_type_mismatch_is_valueerror(loaded_wirefast):
    from kube_gpu_stats_tpu.proto import codec

    bad_metric = codec.field_varint(1, 99) + codec.field_varint(2, 0)
    with pytest.raises(ValueError):
        loaded_wirefast.ingest(codec.field_bytes(1, bad_metric), {})
    with pytest.raises(ValueError):
        loaded_wirefast.ingest(codec.field_varint(1, 5), {})
    with pytest.raises(ValueError):
        loaded_wirefast.ingest(b"\xff\xff\xff\xff", {})


def test_wirefast_fuzz_equivalence(loaded_wirefast):
    """Mutated and random payloads must produce identical outcomes on the
    fused and Python paths: same cache, or both rejecting."""
    import random

    rng = random.Random(20260729)
    base = _payload(num_chips=4)
    for trial in range(400):
        blob = bytearray(base)
        for _ in range(rng.randrange(1, 6)):
            blob[rng.randrange(len(blob))] = rng.randrange(256)
        fused, py = _both(loaded_wirefast, bytes(blob))
        if fused[0] == "err" and py[0] == "err":
            continue  # both rejected; exact exception type may differ
        assert fused == py, (trial, bytes(blob))
    for trial in range(400):
        blob = bytes(rng.randrange(256) for _ in range(rng.randrange(80)))
        fused, py = _both(loaded_wirefast, blob)
        if fused[0] == "err" and py[0] == "err":
            continue
        assert fused == py, (trial, blob)


def test_collector_fused_ingest_is_all_or_nothing():
    """A corrupt tail must not publish the leading valid metrics (review
    finding: raw _wirefast.ingest mutates as it parses; the collector wraps
    it with staging)."""
    from kube_gpu_stats_tpu.collectors.libtpu import _load_wirefast
    from kube_gpu_stats_tpu.proto import codec, tpumetrics

    fused = _load_wirefast()
    assert fused is not None
    good = codec.field_bytes(1, (
        codec.field_string(1, tpumetrics.DUTY_CYCLE) +
        codec.field_varint(2, 0) + codec.field_double(3, 42.0)
    ))
    corrupt = good + codec.field_bytes(1, codec.field_varint(1, 99))
    cache = {}
    with pytest.raises(ValueError):
        fused(corrupt, cache)
    assert cache == {}
    fused(good, cache)
    assert cache[0]["values"]


def _nested_payload(name, samples):
    from kube_gpu_stats_tpu.proto import tpumetrics

    return tpumetrics.encode_response_nested(name, samples)


def test_wirefast_nested_dialect_matches_python(loaded_wirefast):
    from kube_gpu_stats_tpu.proto import tpumetrics

    ici = [tpumetrics.MetricSample(tpumetrics.ICI_TRAFFIC, c, 1000 * c + li,
                                   link=link)
           for c in range(4) for li, link in enumerate(("x0", "x1", "y0"))]
    for raw in (
        _nested_payload(tpumetrics.ICI_TRAFFIC, ici),
        _nested_payload(tpumetrics.HBM_USED, [
            tpumetrics.MetricSample(tpumetrics.HBM_USED, c, (c + 1) * 1024**3)
            for c in range(4)
        ]),
        _nested_payload(tpumetrics.DUTY_CYCLE, [
            tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, c, 50.0 + c,
                                    timestamp_ns=123456789)
            for c in range(4)
        ]),
        _nested_payload(tpumetrics.COLLECTIVES, [
            tpumetrics.MetricSample(tpumetrics.COLLECTIVES, 0, 512)
        ]),
    ):
        fused, py = _both(loaded_wirefast, raw)
        assert fused[0] == "ok" and fused == py


def test_wirefast_nested_server_payload_equivalence(loaded_wirefast):
    """A full per-metric sweep from the nested fake server must ingest
    identically on both paths."""
    from kube_gpu_stats_tpu.proto import tpumetrics
    from kube_gpu_stats_tpu.testing.libtpu_server import FakeLibtpuServer

    srv = FakeLibtpuServer(num_chips=4, dialect="nested")

    class _Ctx:
        def abort(self, code, detail):
            raise AssertionError((code, detail))

    for name in tpumetrics.ALL_METRICS:
        raw = srv._handle(tpumetrics.encode_request(name), _Ctx())
        fused, py = _both(loaded_wirefast, raw)
        assert fused[0] == "ok" and fused == py, name


def test_wirefast_nested_attr_key_spellings(loaded_wirefast):
    """Every accepted device/link attribute spelling must behave the same
    in C and Python (the C table is a hand-synced copy)."""
    from kube_gpu_stats_tpu.proto import codec, tpumetrics

    for dkey in sorted(tpumetrics.DEVICE_ATTR_KEYS):
        for lkey in sorted(tpumetrics.LINK_ATTR_KEYS):
            metric = (
                codec.field_bytes(1, codec.field_string(1, dkey)
                                  + codec.field_bytes(2, codec.field_string(1, "5")))
                + codec.field_bytes(1, codec.field_string(1, lkey)
                                    + codec.field_bytes(2, codec.field_varint(3, 2)))
                + codec.field_bytes(3, codec.field_varint(2, 77))
            )
            body = (codec.field_string(1, tpumetrics.ICI_TRAFFIC)
                    + codec.field_bytes(3, metric))
            raw = codec.field_bytes(1, body)
            fused, py = _both(loaded_wirefast, raw)
            assert fused == py, (dkey, lkey)
            assert fused[0] == "ok"
            assert fused[1][5]["ici"] == {"2": 77}


def test_wirefast_nested_fuzz_equivalence(loaded_wirefast):
    import random

    from kube_gpu_stats_tpu.proto import tpumetrics

    rng = random.Random(20260730)
    ici = [tpumetrics.MetricSample(tpumetrics.ICI_TRAFFIC, c, 1000 * c + li,
                                   link=link)
           for c in range(4) for li, link in enumerate(("x0", "x1", "y0"))]
    base = _nested_payload(tpumetrics.ICI_TRAFFIC, ici)
    for trial in range(400):
        blob = bytearray(base)
        for _ in range(rng.randrange(1, 6)):
            blob[rng.randrange(len(blob))] = rng.randrange(256)
        fused, py = _both(loaded_wirefast, bytes(blob))
        if fused[0] == "err" and py[0] == "err":
            continue
        assert fused == py, (trial, bytes(blob))


def test_wirefast_nested_extension_fields_match_python(loaded_wirefast):
    """Round-2 advisor finding (medium), native side: a nested TPUMetric
    extended with fields 4-6 (legal proto3 forward compat) must decode as
    nested in C too — the old scan counted those as hard flat markers and
    failed the whole response with the mixed-markers error."""
    from kube_gpu_stats_tpu.proto import codec, tpumetrics

    sample = tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 3, 87.5)
    body = (
        codec.field_string(1, tpumetrics.DUTY_CYCLE)
        + codec.field_bytes(3, tpumetrics.encode_metric_nested(sample))
        + codec.field_varint(4, 7)
        + codec.field_varint(5, 123456789)
        + codec.field_string(6, "v2-extra")
    )
    raw = codec.field_bytes(1, body)
    fused, py = _both(loaded_wirefast, raw)
    assert fused[0] == "ok" and fused == py
    assert list(fused[1][3]["values"].values()) == [87.5]


def test_wirefast_ingest_reports_dialect(loaded_wirefast):
    from kube_gpu_stats_tpu.proto import codec, tpumetrics

    flat = tpumetrics.encode_response(
        [tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 0, 50.0)])
    nested = tpumetrics.encode_response_nested(
        tpumetrics.DUTY_CYCLE,
        [tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 0, 50.0)])
    name_only = codec.field_bytes(
        1, codec.field_string(1, tpumetrics.DUTY_CYCLE))
    assert loaded_wirefast.ingest(flat, {}) == (1, 0, 0)
    assert loaded_wirefast.ingest(nested, {}) == (1, 1, 0)
    assert loaded_wirefast.ingest(name_only, {}) == (0, 2, 0)
    assert loaded_wirefast.ingest(b"", {}) == (0, 2, 0)


def test_wirefast_counts_unknown_families_like_python(loaded_wirefast):
    """Unknown-family payloads are dropped by both paths, but the drop is
    COUNTED (round-2 verdict item 6): the native count must equal the
    Python path's unknown-name list length, flat and nested."""
    from kube_gpu_stats_tpu.collectors.libtpu import ingest_response_py
    from kube_gpu_stats_tpu.proto import codec, tpumetrics

    alien_flat = (
        codec.field_bytes(1, (
            codec.field_string(1, "tpu.runtime.novel.metric")
            + codec.field_varint(2, 0) + codec.field_double(3, 1.0)))
        + codec.field_bytes(1, (
            codec.field_string(1, tpumetrics.DUTY_CYCLE)
            + codec.field_varint(2, 0) + codec.field_double(3, 42.0)))
        + codec.field_bytes(1, (
            codec.field_string(1, "tpu.runtime.other.metric")
            + codec.field_varint(2, 1) + codec.field_double(3, 2.0)))
    )
    alien_nested = tpumetrics.encode_response_nested(
        "megascale.future.family",
        [tpumetrics.MetricSample("megascale.future.family", c, 1.0)
         for c in range(3)],
    )
    for raw, expect_unknown in ((alien_flat, 2), (alien_nested, 3)):
        c_native, c_py = {}, {}
        _n, _d, unknown = loaded_wirefast.ingest(raw, c_native)
        report = ingest_response_py(raw, c_py)
        assert unknown == expect_unknown
        assert report.unknown == expect_unknown
        assert len(report.unknown_names) == expect_unknown
        assert c_native == c_py  # caches stay clean + equal


def test_fused_wrapper_latched_dialect_resolution_matches_python():
    """The collector-facing fused wrapper must implement the same
    assume-resolution contract as ingest_response_py: same cache, same
    returned dialect, for every (response, assume) combination."""
    from kube_gpu_stats_tpu.collectors.libtpu import (_load_wirefast,
                                                      ingest_response_py)
    from kube_gpu_stats_tpu.proto import codec, tpumetrics

    fused = _load_wirefast()
    assert fused is not None
    name_only = codec.field_bytes(
        1, codec.field_string(1, tpumetrics.HBM_USED))
    flat = tpumetrics.encode_response(
        [tpumetrics.MetricSample(tpumetrics.HBM_USED, 1, 2048)])
    nested = tpumetrics.encode_response_nested(
        tpumetrics.HBM_USED,
        [tpumetrics.MetricSample(tpumetrics.HBM_USED, 1, 2048)])
    for raw in (name_only, flat, nested, b""):
        for assume in (None, tpumetrics.FLAT, tpumetrics.NESTED):
            c_native, c_py = {}, {}
            d_native = fused(raw, c_native, assume)
            d_py = ingest_response_py(raw, c_py, assume)
            assert d_native == d_py, (raw, assume)
            assert c_native == c_py, (raw, assume)
