"""Full-stack configs[2] integration: daemon with TPU backend + PodResources
attribution; scrape carries pod labels on the right chips, reallocation
flows through on refresh (SURVEY.md §4 integration tier)."""

import time
import urllib.request

import pytest

from kube_gpu_stats_tpu.config import Config
from kube_gpu_stats_tpu.daemon import Daemon

from kube_gpu_stats_tpu.testing.kubelet_server import FakeKubeletServer, tpu_pod
from kube_gpu_stats_tpu.testing.libtpu_server import FakeLibtpuServer
from kube_gpu_stats_tpu.testing.sysfs_fixture import make_sysfs


@pytest.fixture
def stack(tmp_path):
    make_sysfs(tmp_path / "sys", num_chips=4)
    socket = str(tmp_path / "kubelet.sock")
    pods = [tpu_pod("train-job", "ml", "worker", ["0", "1"])]
    with FakeLibtpuServer(num_chips=4) as libtpu, \
         FakeKubeletServer(socket, pods) as kubelet:
        cfg = Config(
            backend="tpu",
            sysfs_root=str(tmp_path / "sys"),
            libtpu_ports=(libtpu.port,),
            interval=0.05,
            deadline=1.0,
            listen_host="127.0.0.1",
            listen_port=0,
            attribution="podresources",
            kubelet_socket=socket,
            attribution_interval=0.05,
            use_native=False,
        )
        daemon = Daemon(cfg)
        daemon.start()
        yield daemon, kubelet
        daemon.stop()


def scrape(daemon):
    url = f"http://127.0.0.1:{daemon.server.port}/metrics"
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.read().decode()


def duty_lines(body):
    return {
        line.split('chip="')[1].split('"')[0]: line
        for line in body.splitlines()
        if line.startswith("accelerator_duty_cycle{")
    }


def test_pod_labels_on_allocated_chips(stack):
    daemon, _ = stack
    assert daemon.registry.wait_for_publish(0, timeout=5)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        body = scrape(daemon)
        lines = duty_lines(body)
        if len(lines) == 4 and 'pod="train-job"' in lines.get("0", ""):
            break
        time.sleep(0.05)
    else:
        raise AssertionError(f"attribution never appeared:\n{body}")
    assert 'namespace="ml"' in lines["0"]
    assert 'container="worker"' in lines["1"]
    assert 'pod=""' in lines["2"]
    assert 'pod=""' in lines["3"]


def test_reallocation_updates_labels(stack):
    daemon, kubelet = stack
    assert daemon.registry.wait_for_publish(0, timeout=5)
    kubelet.pods = [tpu_pod("second-job", "batch", "main", ["2"])]
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        lines = duty_lines(scrape(daemon))
        if 'pod="second-job"' in lines.get("2", "") and 'pod=""' in lines.get("0", ""):
            break
        time.sleep(0.05)
    else:
        raise AssertionError("reallocation never propagated")
