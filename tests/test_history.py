"""History ring + /query serving (ISSUE 18): tier downsampling pinned
against a brute-force oracle, the fixed-memory bound under identity
churn, the intentional non-survival across a warm restart (and the
boot-scoped ETag spaces that make it safe), the read-admission gate's
exact accounting, and the retroactive `doctor --fleet --at` verdict
replayed from ring payloads — a straggler visible in the past stays
named after it recovers."""

import gzip
import json
import math

from kube_gpu_stats_tpu.doctor import OK, WARN, fleet_at_verdict
from kube_gpu_stats_tpu.history import (DEFAULT_TIERS, HistoryStore,
                                        QueryGate, etag_match)
from kube_gpu_stats_tpu.registry import Registry

BASE = 1_700_000_000.0  # aligned-ish anchor; bucket math floors anyway


def feed(store, samples, family="slice_duty_cycle_mean",
         labels=(("slice", "s0"),)):
    """Record each (ts, value) as its own commit — one refresh per
    sample, the hub's cadence."""
    for generation, (ts, value) in enumerate(samples, start=1):
        store.record(family, labels, value)
        store.commit(ts, generation)


def query(store, **params):
    status, body, headers = store.handle_query(
        params, "10.0.0.1", gzip_ok=False, if_none_match="")
    return status, body, headers


class TestTierDownsampling:
    def test_every_tier_matches_the_bucket_mean_oracle(self):
        # 90 samples at the 10 s refresh cadence: one per finest
        # bucket, 3 per 5-min bucket (the 24h tier must average them),
        # all inside one 1 h bucket until the boundary crossing below.
        store = HistoryStore()
        samples = [(BASE + 10.0 * i, float(i * i % 97))
                   for i in range(90)]
        feed(store, samples)
        for window, step, _slots in DEFAULT_TIERS:
            oracle: dict[int, list[float]] = {}
            for ts, value in samples:
                oracle.setdefault(math.floor(ts / step), []).append(value)
            want = [[bucket * step, sum(vs) / len(vs)]
                    for bucket, vs in sorted(oracle.items())]
            status, body, _headers = query(
                store, family="slice_duty_cycle_mean", window=window)
            assert status == 200
            payload = json.loads(body)
            assert payload["step_s"] == step
            (series,) = payload["series"]
            got = series["samples"]
            assert len(got) == len(want)
            for (got_ts, got_v), (want_ts, want_v) in zip(got, want):
                assert got_ts == want_ts
                assert math.isfinite(got_v)
                assert abs(got_v - want_v) < 1e-9, (window, got_ts)

    def test_boundary_sample_opens_the_next_bucket(self):
        # A sample EXACTLY on a 5-min edge belongs to the bucket it
        # opens, not the one it closes — the oracle and the ring must
        # agree on half-open [start, start+step).
        store = HistoryStore()
        edge = (math.floor(BASE / 300.0) + 1) * 300.0
        feed(store, [(edge - 10.0, 1.0), (edge, 5.0), (edge + 10.0, 7.0)])
        status, body, _ = query(
            store, family="slice_duty_cycle_mean", window="24h")
        assert status == 200
        (series,) = json.loads(body)["series"]
        assert series["samples"] == [[edge - 300.0, 1.0], [edge, 6.0]]

    def test_ring_wrap_drops_only_aged_out_buckets(self):
        # 2x the finest window: the first hour's buckets are
        # overwritten in place; what remains is exactly the newest 360.
        store = HistoryStore()
        samples = [(BASE + 10.0 * i, float(i)) for i in range(720)]
        feed(store, samples)
        status, body, _ = query(
            store, family="slice_duty_cycle_mean", window="1h")
        (series,) = json.loads(body)["series"]
        assert len(series["samples"]) == 360
        assert series["samples"][0][0] == BASE + 10.0 * 360
        assert series["samples"][-1][1] == 719.0


class TestFixedMemory:
    def test_bytes_capped_and_shed_accounted_under_churn(self):
        # 30 cycles of fresh identities: the slab count never passes
        # max_series, and every sample that could not be admitted is
        # counted — offered = admitted + shed, exactly.
        store = HistoryStore(max_series=8)
        bound = 8 * store.series_bytes
        offered = 0
        for cycle in range(30):
            for i in range(4):
                store.record("slice_power_watts",
                             (("slice", f"c{cycle}-{i}"),), 1.0)
                offered += 1
            store.commit(BASE + 10.0 * cycle, cycle + 1)
            assert store.bytes() <= bound
        assert store.bytes() == bound
        assert store.samples_total == 8  # the first 8 identities' writes
        assert store.series_shed_total == offered - 8
        assert store.series_evicted_total == 0

    def test_reclaim_reuses_slabs_in_place(self):
        # reclaim_age=0: every new identity reclaims the stalest slab
        # instead of shedding — the slab count (and bytes) still never
        # grows past the cap.
        store = HistoryStore(max_series=8, reclaim_age=0.0)
        bound = 8 * store.series_bytes
        for cycle in range(30):
            for i in range(4):
                store.record("slice_power_watts",
                             (("slice", f"c{cycle}-{i}"),), 1.0)
            store.commit(BASE + 10.0 * cycle, cycle + 1)
            assert store.bytes() <= bound
        assert store.bytes() == bound
        assert store.series_shed_total == 0
        assert store.series_evicted_total == 30 * 4 - 8


class TestWarmRestart:
    def test_ring_does_not_survive_a_restart_by_design(self):
        # The ring is in-hub process state, deliberately: a restarted
        # hub answers /query with 404-unknown-family (and doctor --at
        # says so), never with silently-empty history.
        old = HistoryStore()
        feed(old, [(BASE, 1.0), (BASE + 10.0, 2.0)])
        status, _body, _ = query(
            old, family="slice_duty_cycle_mean", window="1h")
        assert status == 200
        reborn = HistoryStore()
        status, body, _ = query(
            reborn, family="slice_duty_cycle_mean", window="1h")
        assert status == 404
        assert b"unknown family" in body

    def test_boot_nonce_splits_the_etag_spaces(self):
        # Same data, same generation, two boots: a dashboard holding
        # the old boot's ETag must NOT draw a 304 from the new hub —
        # its cache would be a different process's history.
        def etag_of(store):
            _status, _body, headers = query(
                store, family="slice_duty_cycle_mean", window="1h")
            return headers["ETag"]

        first, second = HistoryStore(), HistoryStore()
        feed(first, [(BASE, 1.0)])
        feed(second, [(BASE, 1.0)])
        assert etag_of(first) != etag_of(second)
        status, _body, _headers = second.handle_query(
            {"family": "slice_duty_cycle_mean", "window": "1h"},
            "10.0.0.1", gzip_ok=False, if_none_match=etag_of(first))
        assert status == 200  # full body, not a stale 304

    def test_registry_metrics_etags_differ_across_boots(self):
        from kube_gpu_stats_tpu.exposition import _metrics_etag

        a, b = Registry(), Registry()
        assert a.boot_id != b.boot_id
        assert (_metrics_etag(a.boot_id, 1, False, False)
                != _metrics_etag(b.boot_id, 1, False, False))


class TestQueryServing:
    def test_etag_roundtrip_and_invalidation(self):
        store = HistoryStore()
        feed(store, [(BASE, 1.0)])
        status, body, headers = query(
            store, family="slice_duty_cycle_mean", window="1h")
        assert status == 200
        etag = headers["ETag"]
        status, body, headers = store.handle_query(
            {"family": "slice_duty_cycle_mean", "window": "1h"},
            "10.0.0.1", gzip_ok=False, if_none_match=etag)
        assert (status, body) == (304, b"")
        assert headers["ETag"] == etag
        # A new publish invalidates by generation mismatch — same
        # conditional now misses and the ETag moves.
        store.record("slice_duty_cycle_mean", (("slice", "s0"),), 9.0)
        store.commit(BASE + 10.0, 2)
        status, _body, headers = store.handle_query(
            {"family": "slice_duty_cycle_mean", "window": "1h"},
            "10.0.0.1", gzip_ok=False, if_none_match=etag)
        assert status == 200
        assert headers["ETag"] != etag

    def test_gzip_body_is_the_same_document(self):
        store = HistoryStore()
        feed(store, [(BASE + 10.0 * i, float(i)) for i in range(60)])
        _s, plain, _h = query(
            store, family="slice_duty_cycle_mean", window="1h")
        status, gz, headers = store.handle_query(
            {"family": "slice_duty_cycle_mean", "window": "1h"},
            "10.0.0.1", gzip_ok=True, if_none_match="")
        assert status == 200
        assert headers["Content-Encoding"] == "gzip"
        assert gzip.decompress(gz) == plain

    def test_parameter_validation(self):
        store = HistoryStore()
        feed(store, [(BASE, 1.0)])
        assert query(store)[0] == 400                       # no family
        status, body, _ = query(
            store, family="slice_duty_cycle_mean", window="3h")
        assert status == 400
        assert b"1h,24h,7d" in body
        status, body, _ = query(
            store, family="slice_duty_cycle_mean", window="1h",
            step="300")
        assert status == 400                                # wrong step
        assert query(store, family="slice_duty_cycle_mean",
                     window="1h", step="10s")[0] == 200
        status, body, _ = query(store, family="nope", window="1h")
        assert status == 404
        assert b"slice_duty_cycle_mean" in body

    def test_disabled_store_answers_enabled_false(self):
        store = HistoryStore(enabled=False)
        store.record("slice_chips", (), 1.0)
        store.commit(BASE, 1)
        assert store.samples_total == 0
        status, body, _ = query(store, family="slice_chips")
        assert status == 200
        payload = json.loads(body)
        assert payload["enabled"] is False
        assert "--no-history" in payload["hint"]


class TestQueryGate:
    def test_exact_shed_accounting(self):
        gate = QueryGate(rate=1.0, burst=2.0)
        verdicts = [gate.admit("1.2.3.4", now=100.0) for _ in range(20)]
        admitted = [v for v in verdicts if v[0]]
        shed = [v for v in verdicts if not v[0]]
        assert len(admitted) == 2           # the whole burst, no more
        assert len(shed) == 18
        assert gate.admitted_total == 2
        assert gate.shed_total == 18
        assert all(retry >= 1 for _ok, retry in shed)
        # Tokens refill at the configured rate — and the counters only
        # ever count, they never reset.
        ok, retry = gate.admit("1.2.3.4", now=101.5)
        assert ok
        assert gate.admitted_total == 3

    def test_clients_are_isolated(self):
        gate = QueryGate(rate=1.0, burst=1.0)
        assert gate.admit("1.2.3.4", now=100.0)[0]
        assert not gate.admit("1.2.3.4", now=100.0)[0]
        assert gate.admit("5.6.7.8", now=100.0)[0]

    def test_rate_zero_admits_everything(self):
        gate = QueryGate(rate=0.0, burst=1.0)
        assert all(gate.admit("1.2.3.4")[0] for _ in range(50))
        assert gate.shed_total == 0


class TestEtagMatch:
    def test_semantics(self):
        assert etag_match('"a-1"', '"a-1"')
        assert etag_match("*", '"anything"')
        assert etag_match('"x", "a-1"', '"a-1"')
        assert etag_match('W/"a-1"', '"a-1"')   # weak compare for 304s
        assert not etag_match("", '"a-1"')
        assert not etag_match('"a-2"', '"a-1"')


class TestDoctorAt:
    """`doctor --fleet --at` replays the verdict from ring payloads:
    drive a REAL store through a straggler episode and its recovery,
    and pin that the past still names the straggler."""

    STEPS = "slice_worker_steps_per_second"
    UP = "slice_target_up"

    def make_history(self):
        store = HistoryStore()
        t0 = BASE
        # t0: worker w2 straggling at 2 steps/s, target node-2 down.
        for worker, rate in (("w0", 10.0), ("w1", 10.0), ("w2", 2.0)):
            store.record(self.STEPS,
                         (("slice", "s0"), ("worker", worker)), rate)
        store.record(self.UP, (("target", "node-2:9400"),), 0.0)
        store.commit(t0, 1)
        # t0+600: fully recovered.
        for worker in ("w0", "w1", "w2"):
            store.record(self.STEPS,
                         (("slice", "s0"), ("worker", worker)), 10.0)
        store.record(self.UP, (("target", "node-2:9400"),), 1.0)
        store.commit(t0 + 600.0, 2)
        return store, t0

    def verdict_at(self, store, ts):
        return fleet_at_verdict(store.at_payload(self.STEPS, ts),
                                store.at_payload(self.UP, ts),
                                {"series": []}, ts)

    def test_straggler_ten_minutes_ago_stays_named_after_recovery(self):
        store, t0 = self.make_history()
        status, detail, data = self.verdict_at(store, t0)
        assert status == WARN
        assert "straggler worker w2" in detail
        assert "ratio 0.20" in detail
        assert "as of" in detail
        assert "node-2:9400 was down" in detail
        assert data["slices"]["s0"]["slowest_worker"] == "w2"
        assert data["targets_down"] == ["node-2:9400"]

    def test_now_is_healthy_after_recovery(self):
        store, t0 = self.make_history()
        status, detail, _data = self.verdict_at(store, t0 + 600.0)
        assert status == OK
        assert "fleet healthy" in detail

    def test_empty_ring_says_it_does_not_survive_restarts(self):
        status, detail, _data = fleet_at_verdict(
            {"series": []}, {"series": []}, {"series": []}, BASE)
        assert status == WARN
        assert "does not survive a restart" in detail
