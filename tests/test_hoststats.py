"""Host-signals collector tests (ISSUE 10): fixture-tree reads, rate
deltas, graceful degradation (missing PSI, cgroup v1-only, unreadable
thermal, hostile PSI lines), poll-loop wiring off the hot path, the
/debug/host payload, and the procstats boot-time retry satellite."""

from __future__ import annotations

import pathlib
import sys

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from kube_gpu_stats_tpu import schema  # noqa: E402
from kube_gpu_stats_tpu.hoststats import (HostStats,  # noqa: E402
                                          probe_runq_source)
from kube_gpu_stats_tpu.registry import SnapshotBuilder  # noqa: E402
from kube_gpu_stats_tpu.testing import host_fixture  # noqa: E402
from kube_gpu_stats_tpu.validate import parse_exposition  # noqa: E402

POD_UID = host_fixture.DEFAULT_POD_UID


class FakeClock:
    def __init__(self, now: float = 100.0) -> None:
        self.now = now

    def __call__(self) -> float:
        return self.now


def make_host(tmp_path, **kwargs) -> tuple[HostStats, dict]:
    roots = host_fixture.make_host_tree(tmp_path)
    host = HostStats(proc_root=str(roots["proc"]),
                     sysfs_root=str(roots["sysfs"]),
                     cgroup_root=str(roots["cgroup"]), **kwargs)
    return host, roots


def render_series(host, snap=None):
    builder = SnapshotBuilder()
    host.contribute(builder, snap)
    return list(parse_exposition(builder.build().render()))


def series_value(series, family, **want):
    out = [value for name, labels, value in series
           if name == family and all(labels.get(k) == v
                                     for k, v in want.items())]
    return out[0] if out else None


# -- full fixture read -------------------------------------------------------

def test_full_fixture_read(tmp_path):
    host, _ = make_host(tmp_path, clock=FakeClock())
    snap = host.read()
    assert snap.errors == ()
    # PSI: cpu has no full line; memory/io carry both kinds.
    assert snap.pressure[("cpu", "some", "avg10")] == 1.0
    assert ("cpu", "full", "avg10") not in snap.pressure
    assert snap.pressure[("memory", "full", "avg10")] == 0.0
    assert snap.pressure[("io", "some", "avg10")] == 0.5
    # Stall totals convert kernel microseconds to seconds.
    assert snap.pressure_stall[("memory", "full")] == pytest.approx(0.002)
    # /proc/stat totals present, rates absent on the first sample.
    assert snap.interrupts == {"hard": 1000.0, "soft": 500.0}
    assert snap.irq_rate == {}
    assert snap.nic_drop_rate is None
    # NIC counters (loopback excluded by construction of the fixture).
    assert snap.nic_errors[("eth0", "rx")] == 0.0
    assert snap.nic_drops[("eth0", "tx")] == 0.0
    # Thermal + throttle.
    assert snap.thermal[("0", "x86_pkg_temp")] == 45.0
    assert snap.throttle == {"core": 0.0, "package": 0.0}
    # Pod cgroup parsed; no pod_map -> empty pod/namespace labels.
    assert snap.pods[POD_UID]["cpu_seconds"] == pytest.approx(1.0)
    assert snap.pods[POD_UID]["memory_bytes"] == float(64 << 20)
    assert snap.pods[POD_UID]["pod"] == ""


def test_rates_appear_on_second_read(tmp_path):
    clock = FakeClock()
    host, roots = make_host(tmp_path, clock=clock)
    host.read()
    # Advance every counter by a known delta over 10 fake seconds.
    host_fixture.write_proc_stat(roots["proc"], intr_total=2000,
                                 softirq_total=1500)
    host_fixture.write_softirqs(roots["proc"],
                                {"TIMER": (150, 150), "NET_RX": (100, 75)})
    host_fixture.write_nic(roots["sysfs"], rx_dropped=50, tx_dropped=10)
    host_fixture.write_throttle(roots["sysfs"], core=5, package=1)
    clock.now += 10.0
    snap = host.read()
    assert snap.irq_rate["hard"] == pytest.approx(100.0)
    assert snap.irq_rate["soft"] == pytest.approx(100.0)
    assert snap.softirq_rate["TIMER"] == pytest.approx(10.0)
    assert snap.softirq_rate["NET_RX"] == pytest.approx(10.0)
    assert snap.nic_drop_rate == pytest.approx(6.0)  # 60 drops / 10 s
    assert snap.throttle_rate == pytest.approx(0.6)


def test_counter_reset_yields_no_rate(tmp_path):
    clock = FakeClock()
    host, roots = make_host(tmp_path, clock=clock)
    host.read()
    host_fixture.write_proc_stat(roots["proc"], intr_total=1)  # reboot
    clock.now += 10.0
    snap = host.read()
    assert "hard" not in snap.irq_rate


# -- graceful degradation ----------------------------------------------------

def test_missing_pressure_dir_is_absent_not_an_error(tmp_path):
    """Pre-4.20 kernels have no /proc/pressure: partial families,
    zero errors."""
    import shutil

    host, roots = make_host(tmp_path)
    shutil.rmtree(roots["proc"] / "pressure")
    snap = host.read()
    assert snap.errors == ()
    assert snap.pressure == {}
    assert snap.interrupts  # the other sources still served


def test_cgroup_v1_only_host_has_no_pod_families(tmp_path):
    """A v1-only host (no cgroup.controllers marker) degrades to no
    kts_host_pod_* families, silently."""
    host, roots = make_host(tmp_path)
    (roots["cgroup"] / "cgroup.controllers").unlink()
    snap = host.read()
    assert snap.errors == ()
    assert snap.pods == {}


def test_unreadable_thermal_zone_is_absent(tmp_path):
    host, roots = make_host(tmp_path)
    temp = roots["sysfs"] / "class" / "thermal" / "thermal_zone0" / "temp"
    temp.unlink()
    temp.mkdir()  # open() now fails with EISDIR — the unreadable case
    snap = host.read()
    assert snap.errors == ()
    assert snap.thermal == {}


def test_hostile_psi_line_is_partial_plus_counted_error(tmp_path):
    host, roots = make_host(tmp_path)
    (roots["proc"] / "pressure" / "memory").write_text(
        "some avg10=GARBAGE avg60=nope total=zzz\n"
        "full avg10=18.00 avg60=9.00 avg300=4.00 total=180000\n")
    snap = host.read()
    assert "hoststats_psi" in snap.errors
    # The parseable line of the same file still served...
    assert snap.pressure[("memory", "full", "avg10")] == 18.0
    # ...and so did every other resource.
    assert snap.pressure[("io", "some", "avg10")] == 0.5
    # Cumulative counts ride the debug payload.
    assert host.debug_payload()["errors"]["hoststats_psi"] == 1


def test_garbage_cgroup_and_nic_counted_not_raised(tmp_path):
    host, roots = make_host(tmp_path)
    pod_dir = (roots["cgroup"] / "kubepods.slice"
               / "kubepods-burstable.slice"
               / f"kubepods-burstable-pod{POD_UID.replace('-', '_')}.slice")
    (pod_dir / "memory.current").write_text("not-a-number\n")
    (roots["sysfs"] / "class" / "net" / "eth0" / "statistics"
     / "rx_dropped").write_text("garbage\n")
    snap = host.read()
    assert "hoststats_cgroup" in snap.errors
    assert "hoststats_nic" in snap.errors
    # Partial pod entry: cpu/io parsed even though memory didn't.
    assert snap.pods[POD_UID]["cpu_seconds"] == pytest.approx(1.0)
    assert "memory_bytes" not in snap.pods[POD_UID]


def test_everything_missing_yields_empty_snapshot(tmp_path):
    host = HostStats(proc_root=str(tmp_path / "nope"),
                     sysfs_root=str(tmp_path / "nope"),
                     cgroup_root=str(tmp_path / "nope"))
    snap = host.read()
    assert snap.errors == ()
    assert snap.pressure == {} and snap.pods == {} and snap.thermal == {}
    # Nothing read => contribute emits nothing (snapshot stamped, but
    # every family empty).
    assert [s for s in render_series(host, snap)] == []


# -- pod join + layouts ------------------------------------------------------

def test_pod_map_join_labels_pod_and_namespace(tmp_path):
    host, _ = make_host(
        tmp_path, pod_map=lambda: {POD_UID: ("train-0", "ml")})
    snap = host.read()
    assert snap.pods[POD_UID]["pod"] == "train-0"
    assert snap.pods[POD_UID]["namespace"] == "ml"
    series = render_series(host, snap)
    assert series_value(series, "kts_host_pod_cpu_seconds_total",
                        pod="train-0", namespace="ml",
                        pod_uid=POD_UID) == pytest.approx(1.0)


def test_pod_map_crash_degrades_to_unlabeled(tmp_path):
    def boom():
        raise RuntimeError("kubelet went away")

    host, _ = make_host(tmp_path, pod_map=boom)
    snap = host.read()
    assert "hoststats_pod_map" in snap.errors
    assert snap.pods[POD_UID]["pod"] == ""


def test_cgroupfs_layout_also_discovered(tmp_path):
    host, roots = make_host(tmp_path)
    other = "11112222-3333-4444-5555-666677778888"
    host_fixture.write_pod_cgroup(roots["cgroup"], other, layout="cgroupfs",
                                  cpu_usec=2_000_000)
    snap = host.read()
    assert snap.pods[other]["cpu_seconds"] == pytest.approx(2.0)
    assert POD_UID in snap.pods  # systemd layout still found too


# -- exposition / schema -----------------------------------------------------

def test_contribute_renders_schema_valid_families(tmp_path):
    clock = FakeClock()
    host, roots = make_host(tmp_path, clock=clock)
    host.read()
    host_fixture.write_nic(roots["sysfs"], rx_dropped=30)
    clock.now += 10.0
    snap = host.read()
    series = render_series(host, snap)
    names = {name for name, _labels, _value in series}
    assert "kts_host_pressure_share" in names
    assert "kts_host_pressure_stall_seconds_total" in names
    assert "kts_host_interrupts_total" in names
    assert "kts_host_irq_rate" in names
    assert "kts_host_nic_drops_total" in names
    assert "kts_host_nic_drop_rate" in names
    assert "kts_host_thermal_zone_celsius" in names
    assert "kts_host_cpu_throttle_events_total" in names
    assert "kts_host_pod_memory_bytes" in names
    assert series_value(series, "kts_host_pressure_share",
                        resource="cpu", kind="some",
                        window="avg10") == 1.0
    assert series_value(series, "kts_host_nic_drop_rate") == \
        pytest.approx(3.0)
    # Every emitted family is a schema family (golden contract).
    known = {spec.name for spec in schema.ALL_METRICS}
    assert names <= known


def test_disabled_collector_contributes_nothing(tmp_path):
    host, _ = make_host(tmp_path, enabled=False)
    snap = host.read()  # read still works (tools); contribute gates
    assert render_series(host, snap) == []
    assert host.debug_payload() == {"enabled": False}


def test_trace_note_carries_strongest_signals(tmp_path):
    host, roots = make_host(tmp_path)
    host_fixture.write_psi(roots["proc"], "memory", some_avg10=35.0,
                           full_avg10=18.0, some_total_us=5_000,
                           full_total_us=2_000)
    snap = host.read()
    note = host.trace_note(snap)
    assert note["mem_full_avg10"] == 18.0
    assert note["cpu_some_avg10"] == 1.0
    assert host.trace_note(None) is not None  # falls back to last read


def test_debug_payload_shape(tmp_path):
    import json

    host, _ = make_host(tmp_path,
                        pod_map=lambda: {POD_UID: ("train-0", "ml")})
    host.read()
    payload = host.debug_payload()
    assert payload["enabled"] is True
    assert payload["pressure"]["memory_full_avg10"] == 0.0
    assert payload["pods"][POD_UID]["pod"] == "train-0"
    assert payload["ebpf"] == {"available": False, "reason": "not probed"}
    json.dumps(payload, sort_keys=True)  # must be JSON-serializable


# -- eBPF gating -------------------------------------------------------------

def test_ebpf_probe_refuses_gracefully():
    source, reason = probe_runq_source()
    assert source is None
    assert reason  # names why, never raises


def test_injected_runq_source_emits_quantiles(tmp_path):
    class FakeRunq:
        def read(self):
            return {"p50": 0.0001, "p99": 0.004}

    host, _ = make_host(tmp_path, ebpf_source=FakeRunq())
    snap = host.read()
    assert snap.runq == {"p50": 0.0001, "p99": 0.004}
    series = render_series(host, snap)
    assert series_value(series, "kts_host_runq_latency_seconds",
                        quantile="p99") == pytest.approx(0.004)
    assert host.debug_payload()["ebpf"]["available"] is True


def test_crashing_runq_source_counts_not_raises(tmp_path):
    class Boom:
        def read(self):
            raise OSError("bpf prog detached")

    host, _ = make_host(tmp_path, ebpf_source=Boom())
    snap = host.read()
    assert "hoststats_ebpf" in snap.errors
    assert snap.runq == {}


# -- cardinality fences ------------------------------------------------------

def test_pod_cap_is_stable_deterministic_and_latched(tmp_path):
    from kube_gpu_stats_tpu import hoststats as hs

    host, roots = make_host(tmp_path)
    for i in range(hs.MAX_PODS + 5):
        uid = f"{i:08x}-0000-0000-0000-000000000000"
        host_fixture.write_pod_cgroup(roots["cgroup"], uid,
                                      layout="cgroupfs")
    snap = host.read()
    assert len(snap.pods) == hs.MAX_PODS
    assert "hoststats_pod_cap" in snap.errors
    # Deterministic selection: the sorted-first subset, identical on
    # the next read (flapping series would break rate() queries), and
    # the over-cap error is latched, not ramped per read.
    snap2 = host.read()
    assert set(snap2.pods) == set(snap.pods)
    assert "hoststats_pod_cap" not in snap2.errors


def test_nic_rate_survives_interface_churn_without_spiking(tmp_path):
    """A NIC entering the read set (veth churn / cap-window shift) must
    contribute NOTHING on first sight — its lifetime drop counter
    landing in one delta would export a bogus drop-rate spike and raise
    a false host_nic_drops fleet anomaly."""
    import shutil

    clock = FakeClock()
    host, roots = make_host(tmp_path, clock=clock)
    host.read()
    # A new interface appears carrying a large lifetime counter.
    host_fixture.write_nic(roots["sysfs"], "veth9", rx_dropped=100_000)
    clock.now += 10.0
    snap = host.read()
    assert snap.nic_drop_rate == pytest.approx(0.0)  # eth0 moved 0
    # From its second sample the newcomer rates normally...
    host_fixture.write_nic(roots["sysfs"], "veth9", rx_dropped=100_050)
    clock.now += 10.0
    snap = host.read()
    assert snap.nic_drop_rate == pytest.approx(5.0)
    # ...and a departed interface's baseline is pruned, not leaked.
    shutil.rmtree(roots["sysfs"] / "class" / "net" / "veth9")
    clock.now += 10.0
    host.read()
    assert "nic:drops:veth9" not in host._prev


def test_error_totals_swap_not_mutate_for_http_readers(tmp_path):
    """debug_payload() iterates _error_totals on HTTP threads; read()
    must swap in a new dict, never grow the one being iterated."""
    host, roots = make_host(tmp_path)
    before = host._error_totals
    (roots["proc"] / "pressure" / "memory").write_text("garbage\n")
    host.read()
    assert host._error_totals is not before
    assert host._error_totals["hoststats_psi"] == 1
    assert before == {}


# -- poll-loop wiring --------------------------------------------------------

def test_poll_loop_exports_host_families_off_hot_path(tmp_path):
    import time

    from kube_gpu_stats_tpu.collectors.mock import MockCollector
    from kube_gpu_stats_tpu.poll import PollLoop
    from kube_gpu_stats_tpu.registry import Registry

    host, roots = make_host(tmp_path)
    (roots["proc"] / "pressure" / "memory").write_text("garbage line\n")
    registry = Registry()
    loop = PollLoop(MockCollector(2), registry, host_stats=host)
    try:
        # First tick submits the pool read; families land once it
        # completes (absent-until-first-read contract).
        loop.tick()
        deadline = time.monotonic() + 5.0
        names: set = set()
        while time.monotonic() < deadline:
            loop.tick()
            names = {s.spec.name for s in registry.snapshot().series}
            if "kts_host_pressure_share" in names:
                break
            time.sleep(0.02)
        assert "kts_host_pressure_share" in names
        # The hostile PSI line surfaced on the counter operators are
        # told to alert on (same contract as the env path).
        errors = {
            labels[0][1]: value for spec, labels, value
            in registry.snapshot().series
            if spec.name == "collector_poll_errors_total"
        }
        assert errors.get("hoststats_psi", 0) >= 1
        # Tick meta carries the time-aligned host note on the ring.
        traces = [t for t in loop.tracer.traces() if "host" in t.meta]
        assert traces, "no tick trace carried the host aux annotation"
        assert "cpu_some_avg10" in traces[-1].meta["host"]
    finally:
        loop.stop()


def test_poll_loop_without_host_stats_unchanged():
    from kube_gpu_stats_tpu.collectors.mock import MockCollector
    from kube_gpu_stats_tpu.poll import PollLoop
    from kube_gpu_stats_tpu.registry import Registry

    registry = Registry()
    loop = PollLoop(MockCollector(1), registry)
    try:
        loop.tick()
        names = {s.spec.name for s in registry.snapshot().series}
        assert not any(name.startswith("kts_host_") for name in names)
    finally:
        loop.stop()


# -- doctor --host -----------------------------------------------------------

def test_doctor_check_host_summarizes_live_daemon(tmp_path):
    from kube_gpu_stats_tpu import doctor
    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.registry import Registry

    host, roots = make_host(tmp_path)
    host_fixture.write_psi(roots["proc"], "memory", some_avg10=35.0,
                           full_avg10=18.0, some_total_us=5_000,
                           full_total_us=2_000)
    host.read()
    server = MetricsServer(Registry(), host="127.0.0.1", port=0,
                           host_provider=host)
    server.start()
    try:
        result = doctor.check_host(f"http://127.0.0.1:{server.port}")
        assert result.status == doctor.WARN  # hot pressure share
        assert "memory_full_avg10=18%" in result.detail
        assert "1 pod cgroup(s)" in result.detail
        assert "eBPF runq source off" in result.detail
    finally:
        server.stop()


def test_doctor_check_host_classifies_absent_and_disabled(tmp_path):
    from kube_gpu_stats_tpu import doctor
    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.registry import Registry

    # No provider wired: classified WARN, not a crash.
    bare = MetricsServer(Registry(), host="127.0.0.1", port=0)
    bare.start()
    try:
        result = doctor.check_host(f"http://127.0.0.1:{bare.port}")
        assert result.status == doctor.WARN
        assert "/debug/host" in result.detail
    finally:
        bare.stop()
    # Disabled collector: names --no-host-stats.
    disabled = MetricsServer(Registry(), host="127.0.0.1", port=0,
                             host_provider=HostStats(enabled=False))
    disabled.start()
    try:
        result = doctor.check_host(f"http://127.0.0.1:{disabled.port}")
        assert result.status == doctor.WARN
        assert "--no-host-stats" in result.detail
    finally:
        disabled.stop()


# -- procstats satellite -----------------------------------------------------

def test_procstats_boot_time_retries_after_transient_failure(monkeypatch):
    """Satellite: a transiently unreadable /proc/stat at import must not
    blank process_start_time_seconds forever — the next read() retries
    the boot-time parse and caches the success."""
    from kube_gpu_stats_tpu import procstats

    monkeypatch.setattr(procstats, "_BOOT_TIME", None)
    monkeypatch.setattr(procstats, "_boot_time", lambda: 1_700_000_000.0)
    readings = procstats.read()
    assert "process_start_time_seconds" in readings
    assert readings["process_start_time_seconds"] > 1_700_000_000.0
    # The retry cached: later failures of the source don't regress it.
    assert procstats._BOOT_TIME == 1_700_000_000.0


def test_procstats_boot_time_still_absent_while_unreadable(monkeypatch):
    from kube_gpu_stats_tpu import procstats

    monkeypatch.setattr(procstats, "_BOOT_TIME", None)
    monkeypatch.setattr(procstats, "_boot_time", lambda: None)
    readings = procstats.read()
    assert "process_start_time_seconds" not in readings
    assert "process_cpu_seconds_total" in readings
