"""Metric family selection (--metrics-include/--metrics-exclude) — the
DCGM-exporter collectors-file analog (schema.resolve_metric_filter,
registry.FilteredSnapshotBuilder, wired through config + poll loop)."""

import pytest

from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.config import from_args
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import (FilteredSnapshotBuilder,
                                         HistogramState, Registry)


def families(text):
    return {line.split("{")[0].split(" ")[0]
            for line in text.splitlines() if not line.startswith("#")}


# -- resolve_metric_filter ---------------------------------------------------

def test_exclude_names_disable_exactly_those():
    disabled = schema.resolve_metric_filter(
        (), ("accelerator_power_watts", "accelerator_temperature_celsius"))
    assert disabled == {"accelerator_power_watts",
                        "accelerator_temperature_celsius"}


def test_include_list_disables_everything_else():
    disabled = schema.resolve_metric_filter(("accelerator_duty_cycle",), ())
    assert "accelerator_duty_cycle" not in disabled
    assert "accelerator_power_watts" in disabled
    # The health contract is never disabled even under a narrow include.
    assert "accelerator_up" not in disabled


def test_globs_expand_and_exclude_beats_include():
    disabled = schema.resolve_metric_filter(
        ("accelerator_memory_*", "accelerator_duty_cycle"),
        ("accelerator_memory_peak_bytes",))
    assert "accelerator_memory_used_bytes" not in disabled
    assert "accelerator_memory_total_bytes" not in disabled
    assert "accelerator_memory_peak_bytes" in disabled  # exclude wins
    assert "accelerator_ici_link_bandwidth_bytes_per_second" in disabled


def test_unknown_family_and_dead_glob_fail_loudly():
    with pytest.raises(ValueError, match="unknown metric family"):
        schema.resolve_metric_filter((), ("accelerator_duty_cylce",))
    with pytest.raises(ValueError, match="matches no filterable"):
        schema.resolve_metric_filter(("nvidia_*",), ())


def test_accelerator_up_is_not_filterable():
    with pytest.raises(ValueError, match="health contract"):
        schema.resolve_metric_filter((), ("accelerator_up",))
    with pytest.raises(ValueError, match="health contract"):
        schema.resolve_metric_filter(("accelerator_up",), ())


def test_self_metrics_are_not_filterable():
    with pytest.raises(ValueError, match="unknown metric family"):
        schema.resolve_metric_filter((), ("collector_poll_duration_seconds",))


# -- FilteredSnapshotBuilder -------------------------------------------------

def test_filtered_builder_drops_series_and_histograms():
    builder = FilteredSnapshotBuilder(
        frozenset({schema.POWER.name,
                   schema.WORKLOAD_STEP_DURATION.name}))
    builder.add(schema.POWER, 100.0)
    builder.add(schema.DUTY_CYCLE, 50.0)
    builder.add_histogram(HistogramState.empty(
        schema.WORKLOAD_STEP_DURATION, schema.STEP_DURATION_BUCKETS))
    builder.add_histogram(HistogramState.empty(
        schema.SELF_POLL_DURATION, schema.POLL_DURATION_BUCKETS))
    text = builder.build().render()
    got = families(text)
    assert schema.DUTY_CYCLE.name in got
    assert schema.POWER.name not in got
    assert "collector_poll_duration_seconds_count" in got
    assert not any(f.startswith(schema.WORKLOAD_STEP_DURATION.name)
                   for f in got)


# -- through the poll loop ---------------------------------------------------

def test_poll_loop_respects_disabled_metrics():
    reg = Registry()
    loop = PollLoop(
        MockCollector(num_devices=2), reg, deadline=5.0,
        disabled_metrics=schema.resolve_metric_filter(
            (), ("accelerator_power_watts", "accelerator_ici_*")),
    )
    loop.tick()
    loop.tick()
    loop.stop()
    got = families(reg.snapshot().render())
    assert "accelerator_power_watts" not in got
    assert "accelerator_ici_link_traffic_bytes_total" not in got
    assert "accelerator_ici_link_bandwidth_bytes_per_second" not in got
    assert "accelerator_duty_cycle" in got
    assert "accelerator_up" in got
    assert "collector_devices" in got


def test_poll_loop_include_mode_filters_memory_retention():
    # The stale-tick MEMORY_TOTAL retention re-emit must obey the filter
    # too — an include list without memory families exports no capacity
    # gauges even for a device that just went stale.
    from kube_gpu_stats_tpu.collectors import CollectorError

    class FlakyMock(MockCollector):
        failing = False

        def sample(self, device):
            if self.failing:
                raise CollectorError("injected")
            return super().sample(device)

    collector = FlakyMock(num_devices=1)
    reg = Registry()
    loop = PollLoop(
        collector, reg, deadline=5.0,
        disabled_metrics=schema.resolve_metric_filter(
            ("accelerator_duty_cycle",), ()),
    )
    loop.tick()  # healthy: seeds the retained-capacity map
    got = families(reg.snapshot().render())
    assert "accelerator_duty_cycle" in got
    assert "accelerator_memory_total_bytes" not in got
    assert "accelerator_memory_used_bytes" not in got
    collector.failing = True
    loop.tick()  # stale: the retention re-emit path runs
    loop.stop()
    text = reg.snapshot().render()
    got = families(text)
    assert "accelerator_memory_total_bytes" not in got
    # The device is reported down, proving the stale path actually ran.
    up_lines = [line for line in text.splitlines()
                if line.startswith("accelerator_up{")]
    assert up_lines and all(line.endswith(" 0") for line in up_lines)


# -- through config ----------------------------------------------------------

def test_config_resolves_and_validates_filter():
    cfg = from_args(["--metrics-exclude", "accelerator_process_open",
                     "--backend", "mock"])
    assert cfg.metrics_exclude == ("accelerator_process_open",)
    assert cfg.disabled_metrics == {"accelerator_process_open"}
    with pytest.raises(SystemExit):
        from_args(["--metrics-exclude", "not_a_family"])
    with pytest.raises(SystemExit):
        from_args(["--metrics-include", "accelerator_up"])


def test_config_default_is_everything_enabled():
    assert from_args(["--backend", "mock"]).disabled_metrics == frozenset()


def test_disabled_families_not_built_by_plan():
    """Disabled families are omitted from the compiled tick plan, not
    just dropped by the filtered builder at add time — otherwise every
    changing disabled gauge still constructs a Series per tick and the
    series_built/series_reused accounting goes negative."""
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=2), reg, deadline=5.0,
                    disabled_metrics=schema.FILTERABLE_METRICS)
    loop.tick()
    loop.tick()  # warm tick: unchanged slots replay their cached Series
    stats = loop.last_tick_stats
    assert stats["series_reused"] >= 0, stats
    assert stats["series_built"] <= stats["series"], stats
    loop.stop()
