"""Web-hardening surface of the exposition server: gzip negotiation, HTTP
basic auth (with kubelet-probe exemptions), and TLS. GPU exporters of this
genre usually punt these to exporter-toolkit/sidecars; here they're
built-in (docs/PARITY.md aux table)."""

import base64
import gzip
import hashlib
import ssl
import subprocess
import urllib.error
import urllib.request

import pytest

from kube_gpu_stats_tpu.exposition import MetricsServer, _gzip_accepted
from kube_gpu_stats_tpu.registry import Registry, SnapshotBuilder
from kube_gpu_stats_tpu import schema


def make_registry(series=300):
    registry = Registry()
    builder = SnapshotBuilder()
    for i in range(series):
        builder.add(schema.DUTY_CYCLE, float(i), [("chip", str(i))])
    registry.publish(builder.build())
    return registry


@pytest.fixture
def server():
    srv = MetricsServer(make_registry(), host="127.0.0.1", port=0)
    srv.start()
    yield srv
    srv.stop()


def fetch(port, path="/metrics", headers=None, scheme="http", context=None):
    request = urllib.request.Request(
        f"{scheme}://127.0.0.1:{port}{path}", headers=headers or {}
    )
    return urllib.request.urlopen(request, timeout=5, context=context)


# -- gzip --------------------------------------------------------------------

def test_gzip_when_accepted(server):
    resp = fetch(server.port, headers={"Accept-Encoding": "gzip"})
    assert resp.headers["Content-Encoding"] == "gzip"
    plain = fetch(server.port).read()
    assert gzip.decompress(resp.read()) == plain
    assert len(plain) > 1000  # compression actually mattered


def test_no_gzip_without_accept(server):
    resp = fetch(server.port)
    assert resp.headers.get("Content-Encoding") is None


def test_gzip_q0_is_refusal():
    assert _gzip_accepted("gzip")
    assert _gzip_accepted("deflate, gzip;q=0.5")
    assert _gzip_accepted("*")
    assert not _gzip_accepted("gzip;q=0")
    assert not _gzip_accepted("deflate")
    assert not _gzip_accepted("")


def test_small_bodies_not_compressed():
    srv = MetricsServer(Registry(), host="127.0.0.1", port=0)
    srv.start()
    try:
        resp = fetch(srv.port, headers={"Accept-Encoding": "gzip"})
        assert resp.headers.get("Content-Encoding") is None
    finally:
        srv.stop()


def test_gzip_composes_with_openmetrics(server):
    resp = fetch(server.port, headers={
        "Accept-Encoding": "gzip",
        "Accept": "application/openmetrics-text;version=1.0.0",
    })
    assert resp.headers["Content-Encoding"] == "gzip"
    text = gzip.decompress(resp.read()).decode()
    assert text.rstrip().endswith("# EOF")


# -- basic auth --------------------------------------------------------------

def auth_header(user, password):
    token = base64.b64encode(f"{user}:{password}".encode()).decode()
    return {"Authorization": f"Basic {token}"}


@pytest.fixture
def auth_server():
    srv = MetricsServer(
        make_registry(), host="127.0.0.1", port=0,
        auth_username="prom",
        auth_password_sha256=hashlib.sha256(b"s3cret").hexdigest(),
    )
    srv.start()
    yield srv
    srv.stop()


def test_auth_required(auth_server):
    with pytest.raises(urllib.error.HTTPError) as err:
        fetch(auth_server.port)
    assert err.value.code == 401
    assert err.value.headers["WWW-Authenticate"].startswith("Basic")


def test_auth_wrong_password(auth_server):
    with pytest.raises(urllib.error.HTTPError) as err:
        fetch(auth_server.port, headers=auth_header("prom", "wrong"))
    assert err.value.code == 401


def test_auth_garbage_header(auth_server):
    with pytest.raises(urllib.error.HTTPError) as err:
        fetch(auth_server.port, headers={"Authorization": "Basic !!!not-b64"})
    assert err.value.code == 401


def test_auth_ok(auth_server):
    resp = fetch(auth_server.port, headers=auth_header("prom", "s3cret"))
    assert resp.status == 200
    assert b"accelerator_duty_cycle" in resp.read()


def test_probes_exempt_from_auth(auth_server):
    assert fetch(auth_server.port, "/healthz").status == 200
    assert fetch(auth_server.port, "/readyz").status == 200
    # but the debug surface is protected
    with pytest.raises(urllib.error.HTTPError) as err:
        fetch(auth_server.port, "/debug/threads")
    assert err.value.code == 401


def test_every_debug_endpoint_401s_without_leaking_trace_payloads():
    """ISSUE 4 satellite: the whole /debug surface — flight recorder
    included — must refuse unauthenticated requests, and the 401 body
    must never carry trace payloads (span names, journal details)."""
    from kube_gpu_stats_tpu.tracing import Tracer

    tracer = Tracer()
    tracer.begin("tick", 1)
    with tracer.span("SECRET_PHASE", device="SECRET_DEVICE"):
        pass
    tracer.end()
    tracer.event("breaker", "SECRET_EVENT_DETAIL")
    from kube_gpu_stats_tpu.fleetlens import FleetLens

    from kube_gpu_stats_tpu.hoststats import HostStats

    srv = MetricsServer(
        make_registry(), host="127.0.0.1", port=0,
        auth_username="prom",
        auth_password_sha256=hashlib.sha256(b"s3cret").hexdigest(),
        trace_provider=tracer,
        fleet_provider=FleetLens(tracer=tracer),
        host_provider=HostStats(),
        egress_provider=lambda: {"enabled": True,
                                 "spill": {"SECRET": "SPOOL_DETAIL"}},
        stores_provider=lambda: {"enabled": True,
                                 "stores": {"SECRET_STORE": {}}},
        efficiency_provider=lambda: {
            "enabled": True,
            "waste": {"suspects": {"SECRET_NS/SECRET_POD": {}}}},
    )
    srv.start()
    try:
        for path in ("/debug/threads", "/debug/profile?seconds=0.1",
                     "/debug/ticks", "/debug/trace?last=5",
                     "/debug/events?since=0", "/debug/fleet",
                     "/debug/host", "/debug/egress", "/debug/stores",
                     "/debug/efficiency"):
            with pytest.raises(urllib.error.HTTPError) as err:
                fetch(srv.port, path)
            assert err.value.code == 401, path
            body = err.value.read()
            assert body == b"unauthorized\n", (path, body)
        # With credentials the recorder serves its data — the 401s above
        # weren't vacuous.
        ok = fetch(srv.port, "/debug/ticks",
                   headers=auth_header("prom", "s3cret")).read()
        assert b"SECRET_PHASE" in ok
    finally:
        srv.stop()


def test_debug_host_404_without_provider(server):
    """Servers with no host collector wired (hubs, bare registries)
    must 404 /debug/host, mirroring /debug/fleet."""
    with pytest.raises(urllib.error.HTTPError) as err:
        fetch(server.port, "/debug/host")
    assert err.value.code == 404


def test_debug_host_disabled_answers_enabled_false():
    """--no-host-stats keeps the endpoint up and says so (the --no-trace
    contract) rather than 404ing into 'exporter predates the feature'."""
    import json

    from kube_gpu_stats_tpu.hoststats import HostStats

    srv = MetricsServer(make_registry(), host="127.0.0.1", port=0,
                        host_provider=HostStats(enabled=False))
    srv.start()
    try:
        payload = json.loads(fetch(srv.port, "/debug/host").read())
        assert payload == {"enabled": False}
    finally:
        srv.stop()


def test_debug_host_served_with_auth(tmp_path):
    import json

    from kube_gpu_stats_tpu.hoststats import HostStats
    from kube_gpu_stats_tpu.testing import host_fixture

    roots = host_fixture.make_host_tree(tmp_path)
    host = HostStats(proc_root=str(roots["proc"]),
                     sysfs_root=str(roots["sysfs"]),
                     cgroup_root=str(roots["cgroup"]))
    host.read()
    srv = MetricsServer(
        make_registry(), host="127.0.0.1", port=0,
        auth_username="prom",
        auth_password_sha256=hashlib.sha256(b"s3cret").hexdigest(),
        host_provider=host)
    srv.start()
    try:
        payload = json.loads(fetch(
            srv.port, "/debug/host",
            headers=auth_header("prom", "s3cret")).read())
        assert payload["enabled"] is True
        assert "memory_full_avg10" in payload["pressure"]
        # Landing page lists the endpoint (inventory contract).
        landing = fetch(srv.port, "/",
                        headers=auth_header("prom", "s3cret")).read()
        assert b"/debug/host" in landing
    finally:
        srv.stop()


def test_debug_egress_404_without_provider(server):
    """Servers with no egress provider wired (bare registries) must
    404 /debug/egress, mirroring /debug/host."""
    with pytest.raises(urllib.error.HTTPError) as err:
        fetch(server.port, "/debug/egress")
    assert err.value.code == 404


def test_debug_egress_served_with_auth_and_disabled_contract():
    import json

    payload_state = {"enabled": False, "senders": {}}
    srv = MetricsServer(
        make_registry(), host="127.0.0.1", port=0,
        auth_username="prom",
        auth_password_sha256=hashlib.sha256(b"s3cret").hexdigest(),
        egress_provider=lambda: payload_state)
    srv.start()
    try:
        # Nothing configured: enabled:false (the --no-trace contract —
        # curl diagnoses config, not absence).
        payload = json.loads(fetch(
            srv.port, "/debug/egress",
            headers=auth_header("prom", "s3cret")).read())
        assert payload["enabled"] is False
        payload_state.update(
            enabled=True,
            spill={"depth_frames": 3, "dropped_total": 0})
        payload = json.loads(fetch(
            srv.port, "/debug/egress",
            headers=auth_header("prom", "s3cret")).read())
        assert payload["spill"]["depth_frames"] == 3
        landing = fetch(srv.port, "/",
                        headers=auth_header("prom", "s3cret")).read()
        assert b"/debug/egress" in landing
    finally:
        srv.stop()


def test_debug_efficiency_404_without_provider(server):
    """Servers with no efficiency provider wired (daemons, bare
    registries, --no-fleet-lens hubs) must 404 /debug/efficiency,
    mirroring /debug/fleet — the endpoint is a hub surface."""
    with pytest.raises(urllib.error.HTTPError) as err:
        fetch(server.port, "/debug/efficiency")
    assert err.value.code == 404


def test_debug_efficiency_disabled_answers_enabled_false():
    """--no-efficiency keeps the endpoint up and says so (the
    --no-trace contract): curl diagnoses config, not a hub that
    predates the efficiency lens."""
    import json

    payload_state = {"enabled": False, "reason": "--no-efficiency"}
    srv = MetricsServer(
        make_registry(), host="127.0.0.1", port=0,
        auth_username="prom",
        auth_password_sha256=hashlib.sha256(b"s3cret").hexdigest(),
        efficiency_provider=lambda: payload_state)
    srv.start()
    try:
        payload = json.loads(fetch(
            srv.port, "/debug/efficiency",
            headers=auth_header("prom", "s3cret")).read())
        assert payload["enabled"] is False
        assert payload["reason"] == "--no-efficiency"
        landing = fetch(srv.port, "/",
                        headers=auth_header("prom", "s3cret")).read()
        assert b"/debug/efficiency" in landing
    finally:
        srv.stop()


def test_debug_stores_404_without_provider(server):
    """Servers with no stores provider wired (bare registries) must
    404 /debug/stores, mirroring /debug/egress."""
    with pytest.raises(urllib.error.HTTPError) as err:
        fetch(server.port, "/debug/stores")
    assert err.value.code == 404


def test_debug_stores_daemon_end_to_end(tmp_path):
    """The daemon wires its real payload (ISSUE 15): store states,
    accept-fence status and the supervisor thread report, plus the
    landing-page inventory row."""
    import json

    from kube_gpu_stats_tpu.config import Config
    from kube_gpu_stats_tpu.daemon import Daemon

    d = Daemon(Config(backend="mock", attribution="off", listen_port=0,
                      hub_url="http://127.0.0.1:9",
                      hub_spill_dir=str(tmp_path / "spill")))
    try:
        d.server.start()
        payload = json.loads(fetch(d.server.port, "/debug/stores").read())
        assert payload["enabled"] is True
        assert payload["role"] == "daemon"
        assert "spill" in payload["stores"]
        assert "http-accept" in payload["stores"]
        assert "accept_fence" in payload
        assert isinstance(payload["threads"], list)
        landing = fetch(d.server.port, "/").read()
        assert b"/debug/stores" in landing
    finally:
        d.server.stop()
        d.collector.close()


def test_debug_egress_daemon_end_to_end(tmp_path):
    """The daemon wires its real payload: spill + senders visible."""
    import json

    from kube_gpu_stats_tpu.config import Config
    from kube_gpu_stats_tpu.daemon import Daemon

    d = Daemon(Config(backend="mock", attribution="off", listen_port=0,
                      hub_url="http://127.0.0.1:9",
                      hub_spill_dir=str(tmp_path / "spill")))
    try:
        d.server.start()
        payload = json.loads(fetch(d.server.port, "/debug/egress").read())
        assert payload["enabled"] is True
        assert "spill" in payload
        assert "delta" in payload["senders"]
    finally:
        d.server.stop()
        d.collector.close()


# -- TLS ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def cert_pair(tmp_path_factory):
    d = tmp_path_factory.mktemp("tls")
    cert, key = d / "cert.pem", d / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=127.0.0.1",
         "-addext", "subjectAltName=IP:127.0.0.1"],
        check=True, capture_output=True,
    )
    return cert, key


def test_tls_scrape(cert_pair):
    cert, key = cert_pair
    srv = MetricsServer(make_registry(), host="127.0.0.1", port=0,
                        tls_cert_file=str(cert), tls_key_file=str(key))
    srv.start()
    try:
        context = ssl.create_default_context(cafile=str(cert))
        resp = fetch(srv.port, scheme="https", context=context)
        assert b"accelerator_duty_cycle" in resp.read()
    finally:
        srv.stop()


def test_tls_requires_both_files(cert_pair):
    cert, _ = cert_pair
    with pytest.raises(ValueError):
        MetricsServer(Registry(), host="127.0.0.1", port=0,
                      tls_cert_file=str(cert))


def test_tls_plus_auth(cert_pair):
    cert, key = cert_pair
    srv = MetricsServer(
        make_registry(), host="127.0.0.1", port=0,
        tls_cert_file=str(cert), tls_key_file=str(key),
        auth_username="prom",
        auth_password_sha256=hashlib.sha256(b"pw").hexdigest(),
    )
    srv.start()
    try:
        context = ssl.create_default_context(cafile=str(cert))
        with pytest.raises(urllib.error.HTTPError) as err:
            fetch(srv.port, scheme="https", context=context)
        assert err.value.code == 401
        resp = fetch(srv.port, scheme="https", context=context,
                     headers=auth_header("prom", "pw"))
        assert resp.status == 200
    finally:
        srv.stop()


def test_auth_non_ascii_username_is_401(auth_server):
    """compare_digest on str raises TypeError for non-ASCII — a crafted
    username must produce a clean 401, not a dropped connection (review
    finding)."""
    with pytest.raises(urllib.error.HTTPError) as err:
        fetch(auth_server.port, headers=auth_header("pröm", "s3cret"))
    assert err.value.code == 401


def test_tls_idle_connection_does_not_block_probes(cert_pair):
    """A client that connects and never speaks must not wedge the accept
    loop (review finding: handshake-on-accept serialized all requests
    behind one silent TCP connection)."""
    import socket

    cert, key = cert_pair
    srv = MetricsServer(make_registry(), host="127.0.0.1", port=0,
                        tls_cert_file=str(cert), tls_key_file=str(key))
    srv.start()
    try:
        # Open a raw TCP connection and send nothing.
        idle = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        try:
            context = ssl.create_default_context(cafile=str(cert))
            resp = fetch(srv.port, "/healthz", scheme="https",
                         context=context)
            assert resp.status == 200
        finally:
            idle.close()
    finally:
        srv.stop()


def test_tls_minimum_version_is_modern(cert_pair):
    """The server context must refuse legacy TLS (create_default_context
    pins >= 1.2; a bare SSLContext would inherit the system floor)."""
    cert, key = cert_pair
    srv = MetricsServer(make_registry(), host="127.0.0.1", port=0,
                        tls_cert_file=str(cert), tls_key_file=str(key))
    srv.start()
    try:
        client = ssl.create_default_context(cafile=str(cert))
        client.minimum_version = ssl.TLSVersion.TLSv1_2
        resp = fetch(srv.port, scheme="https", context=client)
        assert resp.status == 200
    finally:
        srv.stop()


# -- mTLS (client-certificate verification) ----------------------------------

@pytest.fixture(scope="module")
def client_cert_pair(tmp_path_factory):
    """A second self-signed pair acting as the client identity AND the CA
    the server trusts (self-signed = its own chain)."""
    d = tmp_path_factory.mktemp("mtls")
    cert, key = d / "client.pem", d / "client-key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=prometheus-scraper"],
        check=True, capture_output=True,
    )
    return cert, key


def test_mtls_rejects_certless_client(cert_pair, client_cert_pair):
    cert, key = cert_pair
    client_ca, _ = client_cert_pair
    srv = MetricsServer(make_registry(), host="127.0.0.1", port=0,
                        tls_cert_file=str(cert), tls_key_file=str(key),
                        tls_client_ca_file=str(client_ca))
    srv.start()
    try:
        context = ssl.create_default_context(cafile=str(cert))
        with pytest.raises((ssl.SSLError, urllib.error.URLError,
                            ConnectionResetError, OSError)):
            fetch(srv.port, scheme="https", context=context)
    finally:
        srv.stop()


def test_mtls_accepts_client_with_cert(cert_pair, client_cert_pair):
    cert, key = cert_pair
    client_cert, client_key = client_cert_pair
    srv = MetricsServer(make_registry(), host="127.0.0.1", port=0,
                        tls_cert_file=str(cert), tls_key_file=str(key),
                        tls_client_ca_file=str(client_cert))
    srv.start()
    try:
        context = ssl.create_default_context(cafile=str(cert))
        context.load_cert_chain(str(client_cert), str(client_key))
        resp = fetch(srv.port, scheme="https", context=context)
        assert b"accelerator_duty_cycle" in resp.read()
    finally:
        srv.stop()


def test_mtls_requires_server_tls(client_cert_pair):
    client_ca, _ = client_cert_pair
    with pytest.raises(ValueError):
        MetricsServer(Registry(), host="127.0.0.1", port=0,
                      tls_client_ca_file=str(client_ca))


def test_mtls_flag_validation():
    from kube_gpu_stats_tpu.config import from_args

    with pytest.raises(SystemExit):
        from_args(["--backend", "mock", "--tls-client-ca-file", "/ca.pem"])


def test_unreadable_tls_files_do_not_leak_listener(cert_pair):
    """A bad cert path raises AFTER the socket binds — the constructor
    must close the listener on its way out (review finding)."""
    import socket

    cert, key = cert_pair
    # Dynamically pick a free port (a hardcoded one races parallel runs).
    with socket.socket() as probe_sock:
        probe_sock.bind(("127.0.0.1", 0))
        port = probe_sock.getsockname()[1]
    for _ in range(3):
        with pytest.raises(FileNotFoundError):
            MetricsServer(Registry(), host="127.0.0.1", port=port,
                          tls_cert_file=str(cert), tls_key_file=str(key),
                          tls_client_ca_file="/nonexistent/ca.pem")
    # Port must be immediately rebindable: nothing leaked.
    srv = MetricsServer(Registry(), host="127.0.0.1", port=port)
    srv.start()
    srv.stop()


# -- scrape-storm concurrency cap --------------------------------------------

def test_scrape_cap_503s_excess_concurrent_renders():
    """Renders beyond max_concurrent_scrapes answer 503 immediately;
    probes stay exempt; the slots recycle once the storm passes."""
    import concurrent.futures
    import threading as _threading
    import urllib.request

    class SlowSnapshot:
        timestamp = 1.0

        def __init__(self, gate):
            self._gate = gate

        def render(self, openmetrics=False):
            self._gate.wait(5)
            return "accelerator_up 1\n" * 20

    class SlowRegistry(Registry):
        def __init__(self, gate):
            super().__init__()
            self._gate = gate

        def snapshot(self):
            return SlowSnapshot(self._gate)

    gate = _threading.Event()
    started = _threading.Semaphore(0)  # released once per render begun
    srv = MetricsServer(SlowRegistry(gate), host="127.0.0.1", port=0,
                        max_concurrent_scrapes=2)
    # Signal render starts deterministically (no sleeps): wrap render.
    real_snapshot = srv._registry.snapshot

    def snapshot():
        snap = real_snapshot()
        real_render = snap.render

        def render(openmetrics=False):
            started.release()
            return real_render(openmetrics)

        snap.render = render
        return snap

    srv._registry.snapshot = snapshot
    srv.start()
    url = f"http://127.0.0.1:{srv.port}/metrics"

    def fetch_code():
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                return resp.status
        except urllib.error.HTTPError as exc:
            return exc.code

    try:
        with concurrent.futures.ThreadPoolExecutor(max_workers=6) as pool:
            first_two = [pool.submit(fetch_code) for _ in range(2)]
            # Both slots provably occupied (renders started, gated).
            assert started.acquire(timeout=10)
            assert started.acquire(timeout=10)
            # Every further scrape must bounce off the cap synchronously.
            rejected = [pool.submit(fetch_code).result(timeout=10)
                        for _ in range(4)]
            gate.set()
            held = sorted(f.result(timeout=10) for f in first_two)
        assert rejected == [503, 503, 503, 503], rejected
        assert held == [200, 200], held
        # Probes were never subject to the cap.
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=5) as r:
            assert r.status == 200
        # Slots recycled: a lone scrape succeeds now.
        assert fetch_code() == 200
    finally:
        srv.stop()


def test_scrape_cap_zero_disables():
    srv = MetricsServer(make_registry(), host="127.0.0.1", port=0,
                        max_concurrent_scrapes=0)
    srv.start()
    try:
        assert fetch(srv.port).status == 200
    finally:
        srv.stop()


def test_rejected_scrapes_surface_as_self_metric():
    from kube_gpu_stats_tpu.exposition import RenderStats
    from kube_gpu_stats_tpu.registry import SnapshotBuilder

    rs = RenderStats()
    builder = SnapshotBuilder()
    rs.contribute(builder)
    # Born at 0 (not absent): increase()-based alerting would miss a
    # burst entirely if the series first appeared already at N.
    (series,) = [s for s in builder.build().series
                 if s.spec.name == schema.SELF_SCRAPES_REJECTED.name]
    assert series.value == 0.0
    rs.reject()
    rs.reject()
    builder = SnapshotBuilder()
    rs.contribute(builder)
    (series,) = [s for s in builder.build().series
                 if s.spec.name == schema.SELF_SCRAPES_REJECTED.name]
    assert series.value == 2.0


# --- ingest hardening (ISSUE 12): slow-loris + Content-Length fences --------

def _ingest_server(read_deadline: float = 0.5):
    """Server with a live ingest provider and a tight body-read
    deadline (the slow-loris fence under test)."""
    from kube_gpu_stats_tpu.hub import Hub

    hub = Hub([], targets_provider=lambda: [], interval=10.0,
              push_fence=1e9)
    srv = MetricsServer(hub.registry, host="127.0.0.1", port=0,
                        ingest_provider=hub.delta.handle,
                        ingest_read_deadline=read_deadline)
    srv.start()
    return hub, srv


def test_slow_loris_post_body_cut_off_with_408():
    """A POST that declares a body and dribbles 2 bytes must be cut at
    the read deadline with 408 + connection close — not hold its
    handler thread for the default (infinite) socket timeout."""
    import socket
    import time

    hub, srv = _ingest_server(read_deadline=0.5)
    try:
        sock = socket.create_connection(("127.0.0.1", srv.port),
                                        timeout=10)
        start = time.monotonic()
        sock.sendall(b"POST /ingest/delta HTTP/1.1\r\n"
                     b"Host: t\r\n"
                     b"Content-Type: application/x-kts-delta\r\n"
                     b"Content-Length: 5000\r\n\r\nab")
        sock.settimeout(10)
        answer = sock.recv(256)
        took = time.monotonic() - start
        sock.close()
        assert b"408" in answer, answer
        assert took < 5.0, took  # the deadline fired, not TCP teardown
        # The server is fully live afterwards: a real frame lands.
        from kube_gpu_stats_tpu import delta as delta_mod
        from kube_gpu_stats_tpu.bench import build_pusher_body

        wire = delta_mod.encode_full("http://ok:9400/metrics", 1, 1,
                                     build_pusher_body(0))
        request = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/ingest/delta", data=wire,
            method="POST",
            headers={"Content-Type": delta_mod.CONTENT_TYPE})
        assert urllib.request.urlopen(request, timeout=5).status == 200
    finally:
        srv.stop()
        hub.stop()


def test_content_length_fence_refuses_before_reading():
    """Missing, garbage, zero, and absurd Content-Length all answer
    413 without the server ever reading a body byte."""
    import http.client

    hub, srv = _ingest_server()
    try:
        for headers in ({},
                        {"Content-Length": "banana"},
                        {"Content-Length": "0"},
                        {"Content-Length": str(65 * 1024 * 1024)}):
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=5)
            try:
                conn.putrequest("POST", "/ingest/delta")
                conn.putheader("Content-Type",
                               "application/x-kts-delta")
                for key, value in headers.items():
                    conn.putheader(key, value)
                conn.endheaders()
                resp = conn.getresponse()
                resp.read()
                assert resp.status == 413, (headers, resp.status)
            finally:
                conn.close()
    finally:
        srv.stop()
        hub.stop()


def test_truncated_post_body_is_400_not_a_stuck_thread():
    """A peer that closes mid-body yields a clean 400 (short read), not
    an exception-killed connection thread."""
    import socket

    hub, srv = _ingest_server(read_deadline=0.5)
    try:
        sock = socket.create_connection(("127.0.0.1", srv.port),
                                        timeout=10)
        sock.sendall(b"POST /ingest/delta HTTP/1.1\r\n"
                     b"Host: t\r\n"
                     b"Content-Length: 500\r\n\r\nshort")
        sock.shutdown(socket.SHUT_WR)
        sock.settimeout(10)
        answer = sock.recv(256)
        sock.close()
        assert b"400" in answer, answer
    finally:
        srv.stop()
        hub.stop()


# -- /query + conditional scrapes (ISSUE 18) ---------------------------------

def _history_store(enabled=True):
    from kube_gpu_stats_tpu.history import HistoryStore

    store = HistoryStore(enabled=enabled)
    store.record("slice_chips", (("slice", "s0"),), 4.0)
    store.commit(1_700_000_000.0, 1)
    return store


def test_query_is_auth_gated():
    """/query serves fleet telemetry history — it sits behind the same
    basic-auth gate as /metrics and the /debug surface."""
    store = _history_store()
    srv = MetricsServer(
        make_registry(), host="127.0.0.1", port=0,
        auth_username="prom",
        auth_password_sha256=hashlib.sha256(b"s3cret").hexdigest(),
        history_provider=store,
    )
    srv.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            fetch(srv.port, "/query?family=slice_chips&window=1h")
        assert err.value.code == 401
        assert b"slice_chips" not in err.value.read()  # no payload leak
        resp = fetch(srv.port, "/query?family=slice_chips&window=1h",
                     headers=auth_header("prom", "s3cret"))
        assert resp.status == 200
        payload = resp.read()
        assert b'"family": "slice_chips"' in payload
        assert resp.headers["ETag"].startswith('"h')
    finally:
        srv.stop()


def test_query_listed_on_landing_page_when_wired():
    srv = MetricsServer(make_registry(), host="127.0.0.1", port=0,
                        history_provider=_history_store())
    srv.start()
    try:
        assert b"/query" in fetch(srv.port, "/").read()
    finally:
        srv.stop()


def test_query_404_when_unwired(server):
    """Daemons and bare servers wire no history: /query is 404 and the
    landing page does not advertise it."""
    with pytest.raises(urllib.error.HTTPError) as err:
        fetch(server.port, "/query?family=slice_chips&window=1h")
    assert err.value.code == 404
    assert b"/query" not in fetch(server.port, "/").read()


def test_query_disabled_answers_enabled_false():
    """--no-history wires a disabled store so a dashboard gets a
    self-describing verdict, not an ambiguous 404."""
    import json

    srv = MetricsServer(make_registry(), host="127.0.0.1", port=0,
                        history_provider=_history_store(enabled=False))
    srv.start()
    try:
        payload = json.loads(
            fetch(srv.port, "/query?family=slice_chips").read())
        assert payload["enabled"] is False
        assert "--no-history" in payload["hint"]
    finally:
        srv.stop()


def test_metrics_conditional_scrape_304(server):
    """If-None-Match on an unchanged generation answers 304 with an
    empty body; urllib surfaces 304 as an HTTPError, which is exactly
    the zero-transfer contract."""
    resp = fetch(server.port, "/metrics")
    etag = resp.headers["ETag"]
    assert etag
    resp.read()
    with pytest.raises(urllib.error.HTTPError) as err:
        fetch(server.port, "/metrics", headers={"If-None-Match": etag})
    assert err.value.code == 304
    assert err.value.read() == b""
    # A different (older/foreign) tag misses: full body, current ETag.
    resp = fetch(server.port, "/metrics",
                 headers={"If-None-Match": '"stale-0-m00"'})
    assert resp.status == 200
    assert resp.headers["ETag"] == etag
    assert resp.read()
