"""Plan-compiled snapshot vs legacy SnapshotBuilder oracle (ISSUE 3).

The compiled-tick-plan path (`PollLoop._emit_device_plan`) must render
byte-identically to the pre-plan builder path (`_emit_device_legacy`,
kept exactly as the original `_build_snapshot` wrote series) under every
behavior the loop supports: device churn, failed/stale/degraded samples,
attribution transitions, drop-label and metric-filter reconfiguration,
passthrough families, percentile expansions, process holders. Mirrors
tests/test_parse_differential.py (fast parser vs
`parse_exposition_reference`): randomized sequences, byte-for-byte
comparison of the rendered exposition.

Both emitters are pure functions of `_update_tick_state`'s output, so
one state fold feeds both paths per step — state mutation (energy
integration, restart detection, rate baselines) happens once and the
comparison sees the exact records production saw.
"""

import random

import pytest

from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.collectors import (Collector, CollectorError, Device,
                                           Sample)
from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry


class ScriptedCollector(Collector):
    """Deterministic chaos: each (device, tick) draws its behavior from
    its own seeded RNG, so pool-thread interleaving can't perturb the
    sequence and a failing seed replays exactly."""

    name = "scripted"

    def __init__(self, seed: int, num_devices: int = 3) -> None:
        self.seed = seed
        self.num = num_devices
        self.tick_no = 0

    def discover(self):
        return [
            Device(i, str(i), f"/dev/accel{i}", "scripted", f"uuid-{i}")
            for i in range(self.num)
        ]

    def begin_tick(self) -> None:
        self.tick_no += 1

    def sample(self, device: Device) -> Sample:
        rng = random.Random(f"{self.seed}:{device.device_id}:{self.tick_no}")
        roll = rng.random()
        if roll < 0.10:
            raise CollectorError("scripted outage")
        values = {
            schema.DUTY_CYCLE.name: round(rng.uniform(0, 100), 1),
            schema.POWER.name: round(rng.uniform(50, 400), 1),
            schema.UPTIME.name: float(1000 + self.tick_no),
        }
        if roll > 0.25:
            # Degraded (runtime-not-ready) samples below the threshold
            # lack HBM capacity: exercises the retained-total path.
            values[schema.MEMORY_TOTAL.name] = 95.0e9
            values[schema.MEMORY_USED.name] = round(rng.uniform(0, 95e9), 0)
        if rng.random() < 0.5:
            for pct in ("p50", "p90", "p99"):
                values[schema.dcn_value_key(pct)] = round(
                    rng.uniform(0.001, 0.01), 6)
        if rng.random() < 0.2:
            # A value key outside the pinned schema AND the percentile
            # expansions: both paths must silently skip it.
            values["tpu_unknown_mystery_metric"] = 1.0
        ici = {}
        if rng.random() < 0.8:
            for link in ("x0", "x1", "y0"):
                ici[link] = (self.tick_no + 1) * 1_000_000 * (
                    device.index + 1) + rng.randrange(1000)
        raw = {}
        if rng.random() < 0.4:
            raw[("megacore.fusion", "")] = round(rng.uniform(0, 1), 3)
            raw[("hbm.ecc", f"ch{rng.randrange(2)}")] = float(
                rng.randrange(10))
        return Sample(
            device=device,
            values=values,
            ici_counters=ici,
            collective_ops=(self.tick_no * 10 if rng.random() < 0.7
                            else None),
            raw_values=raw,
            stale=rng.random() < 0.12,
        )


class MutableAttribution:
    def __init__(self):
        self.mapping = {}
        self.stale = False

    def lookup(self, device):
        return self.mapping.get(device.device_id, {})


def _attribution_for(rng: random.Random, num: int) -> dict:
    out = {}
    for i in range(num):
        roll = rng.random()
        if roll < 0.4:
            continue  # unattributed (empty mapping)
        out[str(i)] = {
            "pod": f"train-{rng.randrange(3)}",
            "namespace": "ml",
            "container": "main" if roll < 0.8 else "",
        }
    return out


def _holders_for(path: str):
    return (("1234", "python3", f"uid-{path[-1]}", 1.0),
            ("_overflow", "_overflow", "", 2.0))


DIFF_CASES = [
    # (seed, drop_labels, disabled_metrics)
    (0, (), frozenset()),
    (1, ("pod", "uuid"), frozenset()),
    (2, (), frozenset({schema.DUTY_CYCLE.name, schema.ICI_BANDWIDTH.name,
                       schema.PASSTHROUGH.name})),
    (3, ("namespace",), frozenset({schema.MEMORY_TOTAL.name})),
]


@pytest.mark.parametrize("seed,drop,disabled", DIFF_CASES)
def test_plan_matches_legacy_oracle_randomized(seed, drop, disabled):
    rng = random.Random(seed * 7919 + 13)
    collector = ScriptedCollector(seed)
    attribution = MutableAttribution()
    attribution.mapping = _attribution_for(rng, collector.num)
    loop = PollLoop(
        collector,
        Registry(),
        deadline=5.0,
        attribution=attribution,
        topology_labels={"slice": "diff-slice", "worker": "0",
                         "topology": "2x2x1"},
        process_metrics=False,
        drop_labels=drop,
        disabled_metrics=disabled,
        process_openers=_holders_for,
    )
    try:
        for step in range(40):
            event = rng.random()
            if event < 0.10:
                # Device churn: grow/shrink and re-enumerate — plans for
                # vanished devices must not leak into the emit, fresh
                # devices must compile correct plans.
                collector.num = rng.choice((1, 2, 3, 4))
                loop.rediscover()
            elif event < 0.25:
                # Attribution transitions (empty->populated->empty and
                # value changes for the same key set) on the C3 cadence.
                attribution.mapping = _attribution_for(rng, collector.num)
                attribution.stale = rng.random() < 0.2
            elif event < 0.30:
                # Live reconfig invalidates every compiled plan.
                loop.reconfigure(
                    drop_labels=rng.choice(((), ("pod",), drop)),
                    disabled_metrics=rng.choice((frozenset(), disabled)),
                )
            results = loop._sample_all()
            tick = loop._update_tick_state(results, now=100.0 + step)
            plan_snap = loop._emit_snapshot(tick, True)
            legacy_snap = loop._emit_snapshot(tick, False)
            assert plan_snap.render() == legacy_snap.render(), (
                f"seed={seed} step={step}: plan render diverged from the "
                f"legacy oracle")
            assert (plan_snap.render(openmetrics=True)
                    == legacy_snap.render(openmetrics=True))
    finally:
        loop.stop()


def test_plan_loop_matches_legacy_loop_end_to_end():
    """Two full production loops over identical deterministic backends —
    one plan-compiled, one forced legacy (use_tick_plan=False, the
    escape hatch) — publish byte-identical expositions tick after tick,
    including the value-unchanged re-emit path (mock gauges hold still
    across some consecutive ticks of the triangle wave)."""
    from kube_gpu_stats_tpu.tracing import Tracer

    frozen = lambda: 0.0  # noqa: E731 - identical tick durations/rates
    loops = []
    for use_plan in (True, False):
        loop = PollLoop(
            MockCollector(num_devices=2),
            Registry(),
            deadline=5.0,
            topology_labels={"slice": "s", "worker": "1", "topology": "2x1"},
            process_metrics=False,
            use_tick_plan=use_plan,
            # Disabled recorders: each loop's kts_tick_phase_seconds
            # digest would carry its own real span timings, which can
            # never be byte-identical across two loops.
            tracer=Tracer(enabled=False),
            clock=frozen,
        )
        loops.append(loop)
    plan_loop, legacy_loop = loops
    try:
        for tick in range(8):
            plan_loop.tick()
            legacy_loop.tick()
            plan_body = plan_loop._registry.snapshot().render()
            legacy_body = legacy_loop._registry.snapshot().render()
            # The self-metrics differ only where they must: the plan
            # cache counters exist on both (shared tail), with the same
            # values (both loops compile/hit identically).
            assert plan_body == legacy_body, f"tick {tick} diverged"
    finally:
        plan_loop.stop()
        legacy_loop.stop()


def test_plan_reuses_series_objects_for_unchanged_values():
    """The allocation contract the bench pins: an unchanged slot value
    re-emits the SAME Series object (zero per-tick garbage), a changed
    value builds exactly one."""

    class ConstantCollector(Collector):
        name = "const"

        def discover(self):
            return [Device(0, "0", "/dev/accel0", "const")]

        def sample(self, device):
            return Sample(device, {schema.DUTY_CYCLE.name: 42.0,
                                   schema.MEMORY_TOTAL.name: 8.0})

    loop = PollLoop(ConstantCollector(), Registry(), deadline=5.0,
                    process_metrics=False)
    try:
        loop.tick()
        first = {(s.spec.name, s.labels): s
                 for s in loop._registry.snapshot().series}
        loop.tick()
        stats = loop.last_tick_stats
        # Every device series was re-emitted from its plan slot.
        assert stats["series_reused"] > 0
        assert stats["series_built"] == stats["series"] - stats[
            "series_reused"]
        for s in loop._registry.snapshot().series:
            key = (s.spec.name, s.labels)
            if s.spec.name.startswith("accelerator_"):
                assert s is first[key], f"{key} was rebuilt, not reused"
    finally:
        loop.stop()
