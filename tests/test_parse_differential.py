"""Differential test: the split-based fast tokenizer (validate._parse_series
and the label fast path in _tokenize_labels) must agree with the regex
reference implementation (parse_exposition_reference) on EVERY input —
same triples or the same ValueError verdict. The fast path is allowed to
be fast only because any line it cannot prove equivalent falls back to
the reference regex; this suite is the oracle that pins that claim, over
a hand-built corpus (escapes, exponent floats, NaN/Inf, comments,
timestamps) plus a seeded random fuzz sweep."""

import math
import random

import pytest

from kube_gpu_stats_tpu.validate import (parse_exposition,
                                         parse_exposition_interned,
                                         parse_exposition_reference)


def agree(text: str):
    """Assert fast and reference parses agree; returns the parse (or None
    when both reject)."""
    try:
        expected = parse_exposition_reference(text)
    except ValueError:
        with pytest.raises(ValueError):
            parse_exposition(text)
        return None
    got = parse_exposition(text)
    assert _canon(got) == _canon(expected), text
    return got


def _canon(series):
    # NaN != NaN breaks naive equality; compare values by repr.
    return [(name, labels, repr(value)) for name, labels, value in series]


CORPUS = [
    # Plain series, empty/no labels, trailing whitespace.
    "m 1",
    "m{} 1",
    "m{a=\"b\"} 2.5",
    "  m{a=\"b\",c=\"d\"} 2.5  ",
    "m_total{a=\"b\"} 0",
    # Escaped label values: \" \\ \n stay RAW (neither parser unescapes —
    # the shared contract both sides must honor).
    'm{a="x\\"y"} 1',
    'm{a="back\\\\slash"} 1',
    'm{a="line\\nbreak"} 1',
    'm{a="\\\\",b="\\""} 1',
    # Exponent floats, signs, specials, underscores-in-floats.
    "m 1e3",
    "m -2.5e-7",
    "m +Inf",
    "m -Inf",
    "m NaN",
    "m inf",
    "m 1_0",
    # Timestamps (optional trailing ms integer).
    "m 1 1722249600000",
    "m{a=\"b\"} 1 -5",
    "m 1 12.5",     # fractional timestamp: both reject
    "m 1 2 3",      # too many fields: both reject
    "m 1 x",        # junk timestamp: both reject
    # Comments and blanks interleaved.
    "# HELP m help text\n# TYPE m gauge\nm 1\n\n   \nm2 2",
    "#",
    "",
    "\n\n",
    # Malformed lines: both must reject identically.
    "m",
    "m{a=\"b\"}",
    "m{a=\"b\"}1",          # missing space after labels
    "m{a=\"b\" 1",          # unclosed brace
    "m{a=b} 1",             # unquoted value
    "{a=\"b\"} 1",          # missing name
    "9metric 1",            # bad name start... reference: no match
    "m nope",
    # Label-grammar corners the fast scanner must flee to the regex on.
    'm{a="b",,c="d"} 1',    # double comma
    'm{a="b" ,c="d"} 1',    # space before comma
    'm{a="b", c="d"} 1',    # space after comma
    'm{a="b"junk,c="d"} 1',  # junk between pairs
    'm{a="b",} 1',          # trailing comma
    'm{a="b"="c"} 1',       # = inside value position
    'm{a="b",a="c"} 1',     # duplicate label name (last wins, both sides)
    'm{A_1=""} 1',          # empty value
    'm{le="+Inf"} 1',
    # Colons are legal in metric names, not label names.
    "job:rate:5m 1",
    'm{a:b="c"} 1',
]


def test_corpus_agreement():
    for text in CORPUS:
        agree(text)


def test_multiline_document_agreement():
    # A document containing any malformed line errors in both parsers;
    # build one from only the individually-parseable lines instead.
    good = []
    for line in CORPUS:
        if "\n" in line:
            continue
        try:
            parse_exposition_reference(line)
            good.append(line)
        except ValueError:
            pass
    doc = "\n".join(good)
    agree(doc)


def test_interned_view_matches_dict_view():
    """parse_exposition_interned returns the same series with tuple
    labels, pointer-shared across calls — the identity contract the
    hub's merge keys rely on."""
    text = ('m{a="b",c="d"} 1\n'
            'm{a="b",c="d"} 2\n'
            'n{a="b",c="d"} 3\n')
    interned = parse_exposition_interned(text)
    plain = parse_exposition(text)
    assert [(n, dict(l), v) for n, l, v in interned] == plain
    # Same raw label text -> the SAME tuple object, across series and
    # across calls (the shared pool).
    assert interned[0][1] is interned[1][1]
    assert interned[0][1] is interned[2][1]
    again = parse_exposition_interned('m{a="b",c="d"} 9\n')
    assert again[0][1] is interned[0][1]
    assert again[0][0] is interned[0][0]  # family names interned too


def test_special_values_parse_exactly():
    got = parse_exposition("a NaN\nb +Inf\nc -Inf\n")
    assert math.isnan(got[0][2])
    assert got[1][2] == math.inf
    assert got[2][2] == -math.inf


def test_fuzz_agreement_seeded():
    """Random structured-ish and raw-noise inputs: the two parsers must
    agree (triples or error) on every one. Seeded for reproducibility."""
    rng = random.Random(0xD1FF)
    atoms = ['a="b"', 'x="\\""', 'y="\\\\"', 'z="v\\nw"', 'le="0.5"',
             'a="b"', ',', ',,', ' ', '=', '"', '\\', 'name', '{', '}']
    for _ in range(400):
        kind = rng.randrange(4)
        if kind == 0:  # clean-ish series line
            labels = ",".join(
                f'{rng.choice("abcxyz")}{rng.randrange(9)}="v{rng.random()}"'
                for _ in range(rng.randrange(0, 5)))
            value = rng.choice(["1", "2.5", "-3e-2", "NaN", "+Inf", "-Inf",
                                str(rng.random())])
            ts = rng.choice(["", " 123", " -9", " 1.5", " x"])
            text = f"m{{{labels}}} {value}{ts}"
        elif kind == 1:  # label-grammar soup
            text = "m{" + "".join(rng.choice(atoms)
                                  for _ in range(rng.randrange(1, 8))) + "} 1"
        elif kind == 2:  # raw printable noise
            text = "".join(chr(rng.randrange(32, 127))
                           for _ in range(rng.randrange(0, 60)))
        else:  # multi-line mix with comments
            text = "\n".join(
                rng.choice(["# c", "", "m 1", 'm{a="b"} 2',
                            'm{a="\\""} 3 4', "m nope"])
                for _ in range(rng.randrange(1, 6)))
        agree(text)
