"""Embedded (workload-side) exporter: in-process JAX introspection
collector, full stack scrape, and the bench probe record (round-2 verdict
item 1 — the only real-chip telemetry path where no metric service is
served). Runs on the conftest-forced 8-device CPU mesh."""

import urllib.request

import pytest

jax = pytest.importorskip("jax")

from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.embedded import (EmbeddedExporter,
                                         JaxIntrospectCollector,
                                         _kind_capacity)


def test_collector_discovers_jax_devices():
    col = JaxIntrospectCollector()
    devices = col.discover()
    assert len(devices) == 8  # conftest CPU mesh
    assert devices[0].device_path.startswith("jax:cpu:")
    assert len({d.device_id for d in devices}) == 8


def test_sample_reports_live_array_memory_and_steps():
    import jax.numpy as jnp

    col = JaxIntrospectCollector()
    devices = col.discover()
    keepalive = jnp.ones((256, 256), jnp.float32)  # 256 KiB on device 0
    col.record_step()
    col.record_step(4)
    s = col.sample(devices[0])
    assert s.values[schema.MEMORY_USED.name] >= 256 * 1024
    assert s.values[schema.WORKLOAD_STEPS.name] == 5.0
    assert s.values[schema.UPTIME.name] >= 0.0
    # CPU devices have no capacity table entry: no fabricated total.
    assert schema.MEMORY_TOTAL.name not in s.values
    del keepalive


def test_step_timer_feeds_busy_counter_and_histogram():
    import time

    col = JaxIntrospectCollector()
    with col.step_timer():
        time.sleep(0.02)
    col.record_step(2, seconds=0.5)  # two steps, 0.25 s each
    devices = col.discover()
    s = col.sample(devices[0])
    assert s.values[schema.WORKLOAD_STEPS.name] == 3.0
    busy = s.values[schema.WORKLOAD_BUSY_SECONDS.name]
    assert 0.52 <= busy < 5.0
    (hist,) = col.extra_histograms()
    assert hist.total == 3
    assert abs(hist.sum - busy) < 1e-9
    # The two 0.25 s observations land in the (0.1, 0.25] bucket.
    assert hist.counts[schema.STEP_DURATION_BUCKETS.index(0.25)] == 2


def test_sample_reports_peak_memory_high_water_mark():
    import jax.numpy as jnp

    col = JaxIntrospectCollector()
    devices = col.discover()
    keepalive = jnp.ones((512, 512), jnp.float32)  # 1 MiB on device 0
    high = col.sample(devices[0])
    assert high.values[schema.MEMORY_PEAK.name] >= 1024 * 1024
    del keepalive
    jnp.zeros(()).block_until_ready()
    low = col.sample(devices[0])
    # Used drops with the allocation; the peak must not.
    assert low.values[schema.MEMORY_PEAK.name] >= \
        high.values[schema.MEMORY_PEAK.name]


def test_kind_capacity_table():
    assert _kind_capacity("TPU v5 lite") == 16 * 1024**3
    assert _kind_capacity("TPU v5p chip") == 95 * 1024**3
    assert _kind_capacity("TPU v4") == 32 * 1024**3
    assert _kind_capacity("Quantum Chip 9000") is None


def test_embedded_exporter_end_to_end():
    """start() -> workload steps -> scrape: the real-mode proof path, on
    the CPU mesh. Scrape surface and schema identical to the daemon's."""
    exporter = EmbeddedExporter(port=0, interval=0.05)
    exporter.start()
    try:
        exporter.record_step(3)
        assert exporter.registry.wait_for_publish(0, timeout=5)
        assert exporter.registry.wait_for_publish(
            exporter.registry.generation, timeout=5)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{exporter.port}/metrics", timeout=5
        ) as resp:
            body = resp.read().decode()
        assert body.count("accelerator_up{") == 8
        assert "accelerator_workload_steps_total{" in body
        assert "accelerator_workload_busy_seconds_total{" in body
        assert "accelerator_memory_used_bytes{" in body
        assert "accelerator_memory_peak_bytes{" in body
        assert "accelerator_workload_step_duration_seconds_bucket" in body
        assert 'backend="jax-embedded"' in body
        # The embedded output must pass the shipped schema validator
        # (review finding: histogram families once failed the contract).
        from kube_gpu_stats_tpu import validate
        assert validate.check(body) == []
        # Self-observability rides along like the daemon.
        assert "collector_poll_duration_seconds_bucket" in body
        with urllib.request.urlopen(
            f"http://127.0.0.1:{exporter.port}/healthz", timeout=5
        ) as resp:
            assert resp.status == 200
    finally:
        exporter.stop()


def test_burn_step_hook_feeds_counter():
    from kube_gpu_stats_tpu.loadgen.burn import run_burn

    col = JaxIntrospectCollector()
    result = {}
    steps = run_burn(seconds=0.2, size=128, report_every=1e9,
                     step_hook=col.record_step, depth=4, result=result)
    assert steps > 0 and col._steps == steps
    # The burn reports its matmul FLOPs across ALL devices: depth
    # chained matmuls of size^3 on each of the mesh's devices.
    n = result["devices"]
    assert n == len(jax.local_devices())
    assert col._flops == steps * 2 * 4 * n * 128**3
    # Steady-state measurement excludes compile: rate present once the
    # burn ran past its first materialization batch.
    assert result["size"] == 128 and result["depth"] == 4
    assert result["steps_per_s"] >= 0.0


def test_burn_drives_every_local_device():
    """Round-4 verdict item 2: every local device's FLOPs counter is
    nonzero and per-chip MFU is equal across chips — the burn shards
    over the whole 8-device CPU mesh, so the collector's SPMD split is
    exact (the old 'default device only' caveat is dead)."""
    import time as _time

    from kube_gpu_stats_tpu import embedded as embedded_mod
    from kube_gpu_stats_tpu.loadgen.burn import run_burn

    col = JaxIntrospectCollector()
    devices = col.discover()
    assert len(devices) == 8
    steps = run_burn(seconds=0.15, size=128, report_every=1e9,
                     step_hook=col.record_step, depth=4)
    assert steps > 0
    col.begin_tick()  # window start (FLOPs already nonzero)
    steps = run_burn(seconds=0.15, size=128, report_every=1e9,
                     step_hook=col.record_step, depth=4)
    assert steps > 0
    col.begin_tick()  # window end: delta > 0 -> per-device rate
    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(embedded_mod, "_kind_peak_flops", lambda kind: 1e9)
        samples = [col.sample(d) for d in devices]
    flops = [s.values[schema.WORKLOAD_FLOPS.name] for s in samples]
    assert all(f > 0 for f in flops)
    assert len(set(flops)) == 1  # equal split over the mesh
    mfus = [s.values[schema.WORKLOAD_MFU.name] for s in samples]
    assert all(m > 0 for m in mfus)
    assert len(set(mfus)) == 1  # equal per-chip MFU


def test_sweep_burn_rows_on_cpu_mesh():
    from kube_gpu_stats_tpu.loadgen.burn import sweep_burn

    rows = sweep_burn(sizes=(128, 256), seconds_per_size=0.2, depth=2)
    assert [r["size"] for r in rows] == [128, 256]
    for row in rows:
        assert row["devices"] == 8
        assert row["tflops_per_s"] >= 0.0
        # CPU kinds have no peak entry: no fabricated MFU column.
        assert "mfu_pct" not in row
    # The sweep deadline skips sizes it can't afford (compiles included).
    bounded = sweep_burn(sizes=(128, 256), seconds_per_size=0.2,
                         depth=2, deadline_seconds=0.0)
    assert bounded[0].get("skipped") or bounded[1].get("skipped")


def test_mixed_device_kinds_resolved_per_device():
    """Capacity, peak FLOPs, and accel_type come from EACH device's
    kind, never device 0's (round-4 verdict item 6)."""

    class FakeDev:
        def __init__(self, id, kind):
            self.id = id
            self.platform = "tpu"
            self.device_kind = kind

    col = JaxIntrospectCollector()
    col._devices = [FakeDev(0, "TPU v5p chip"), FakeDev(1, "TPU v5 lite")]
    col._has_memory_stats = False
    devices = col.discover()
    assert [d.accel_type for d in devices] == ["tpu-v5p-chip", "tpu-v5-lite"]
    col.record_step(1, flops=4e12)
    samples = {d.index: col.sample(d) for d in devices}
    # Per-device HBM capacity from each kind's row.
    assert samples[0].values[schema.MEMORY_TOTAL.name] == 95 * 1024**3
    assert samples[1].values[schema.MEMORY_TOTAL.name] == 16 * 1024**3
    # Per-device peak from each kind's row (the MFU denominator).
    assert samples[0].values[schema.PEAK_FLOPS.name] == 459e12
    assert samples[1].values[schema.PEAK_FLOPS.name] == 197e12


def test_v2_v3_tables_are_per_jax_device():
    """v2/v3 expose each TensorCore as its own JAX device, so those
    rows are per-core: half the public per-chip figure."""
    from kube_gpu_stats_tpu.embedded import _kind_peak_flops

    assert _kind_peak_flops("TPU v3") == 61.5e12
    assert _kind_peak_flops("TPU v2") == 22.5e12
    assert _kind_capacity("TPU v3") == 16 * 1024**3
    assert _kind_capacity("TPU v2") == 8 * 1024**3
    # v7/Ironwood: no published per-chip bf16 spec — must omit, never
    # guess.
    assert _kind_peak_flops("TPU v7") is None
    assert _kind_capacity("TPU7x") is None


def test_flops_counter_divides_over_local_devices():
    col = JaxIntrospectCollector()
    devices = col.discover()
    col.record_step(2, seconds=0.1, flops=16e9)
    s = col.sample(devices[0])
    assert s.values[schema.WORKLOAD_FLOPS.name] == 16e9 / len(devices)
    # CPU devices: no peak table entry -> no peak gauge, no MFU, never a
    # guess.
    assert schema.PEAK_FLOPS.name not in s.values
    assert schema.WORKLOAD_MFU.name not in s.values


def test_no_flops_reported_no_flops_series():
    col = JaxIntrospectCollector()
    col.record_step(3, seconds=0.1)
    s = col.sample(col.discover()[0])
    assert schema.WORKLOAD_FLOPS.name not in s.values


def test_mfu_gauge_from_tick_window(monkeypatch):
    import time as _time

    from kube_gpu_stats_tpu import embedded as embedded_mod

    # CPU device kinds have no table entry; pin a peak so the math is
    # checkable: 1 GFLOP/s peak per device.
    monkeypatch.setattr(embedded_mod, "_kind_peak_flops", lambda kind: 1e9)
    col = JaxIntrospectCollector()
    devices = col.discover()
    n = len(devices)
    col.record_step(1, flops=n * 100e9)
    col.begin_tick()  # first window point: no MFU yet
    assert col.sample(devices[0]).values.get(schema.WORKLOAD_MFU.name) is None
    _time.sleep(0.05)
    col.record_step(1, flops=n * 100e9)
    col.begin_tick()
    s = col.sample(devices[0])
    assert s.values[schema.PEAK_FLOPS.name] == 1e9
    mfu = s.values[schema.WORKLOAD_MFU.name]
    # 100e9 FLOPs/device at 1e9 peak: >100% for any window under 100 s —
    # proves the window math without a timing cliff, and that
    # over-reported FLOPs surface as >100 instead of being clamped into
    # plausibility.
    assert mfu > 100.0
    # A window with no new FLOPs drives MFU to ~0 (goodput gap visible).
    _time.sleep(0.01)
    col.begin_tick()
    assert col.sample(devices[0]).values[schema.WORKLOAD_MFU.name] < mfu


def test_real_probe_explains_fallback():
    """Round-1 verdict item 2: on a box with no TPU surface the harness
    must return a machine-checked record of WHY, not a bare None."""
    from kube_gpu_stats_tpu.bench import try_real_harness

    result, probe = try_real_harness(ticks=1, warmup=0, colaunch=False)
    assert result is None
    assert probe["ports"]
    assert all(v is False for v in probe["ports_open"].values())
    attempt = probe["external_attempt"]
    assert attempt["devices"] == 0 or attempt["error"]
    assert probe["burn_colaunch"]["skipped"] is True


def test_embedded_harness_refuses_cpu_as_real():
    """A CPU-only jax must never produce a mode:'real' bench result."""
    from kube_gpu_stats_tpu.bench import try_embedded_harness

    probe = {}
    result = try_embedded_harness(probe, ticks=1, warmup=0, burn_seconds=0.1)
    assert result is None
    assert probe["embedded_attempt"]["jax_platform"] == "cpu"
    assert "no accelerator platform" in probe["embedded_attempt"]["error"]


def test_colaunch_skipped_without_accelerator_platform(monkeypatch):
    """Review finding: a chip-less box must not pay a CPU burn before
    falling back to simulated mode — the platform probe short-circuits
    the co-launch. The probe runs in a subprocess (this sandbox's
    sitecustomize force-registers a real TPU plugin there, ignoring the
    conftest CPU pin), so it is stubbed for determinism."""
    from kube_gpu_stats_tpu import bench

    monkeypatch.setattr(bench, "_probe_jax_platform", lambda: "cpu")
    result, probe = bench.try_real_harness(ticks=1, warmup=0, colaunch=True)
    assert result is None
    assert probe["jax_platform"] == "cpu"
    assert probe["burn_colaunch"]["spawned"] is False
    assert "no accelerator platform" in str(probe["burn_colaunch"]["skipped"])


def test_embedded_exporter_metric_filter():
    import urllib.request

    exporter = EmbeddedExporter(metrics_exclude=("accelerator_uptime_seconds",))
    exporter.start()
    try:
        assert exporter.registry.wait_for_publish(0, timeout=5)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{exporter.port}/metrics", timeout=5
        ).read().decode()
    finally:
        exporter.stop()
    assert "accelerator_memory_used_bytes" in body
    assert "accelerator_uptime_seconds" not in body
    with pytest.raises(ValueError, match="unknown metric family"):
        EmbeddedExporter(metrics_exclude=("not_a_family",))
