"""Embedded (workload-side) exporter: in-process JAX introspection
collector, full stack scrape, and the bench probe record (round-2 verdict
item 1 — the only real-chip telemetry path where no metric service is
served). Runs on the conftest-forced 8-device CPU mesh."""

import urllib.request

import pytest

jax = pytest.importorskip("jax")

from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.embedded import (EmbeddedExporter,
                                         JaxIntrospectCollector,
                                         _kind_capacity)


def test_collector_discovers_jax_devices():
    col = JaxIntrospectCollector()
    devices = col.discover()
    assert len(devices) == 8  # conftest CPU mesh
    assert devices[0].device_path.startswith("jax:cpu:")
    assert len({d.device_id for d in devices}) == 8


def test_sample_reports_live_array_memory_and_steps():
    import jax.numpy as jnp

    col = JaxIntrospectCollector()
    devices = col.discover()
    keepalive = jnp.ones((256, 256), jnp.float32)  # 256 KiB on device 0
    col.record_step()
    col.record_step(4)
    s = col.sample(devices[0])
    assert s.values[schema.MEMORY_USED.name] >= 256 * 1024
    assert s.values[schema.WORKLOAD_STEPS.name] == 5.0
    assert s.values[schema.UPTIME.name] >= 0.0
    # CPU devices have no capacity table entry: no fabricated total.
    assert schema.MEMORY_TOTAL.name not in s.values
    del keepalive


def test_step_timer_feeds_busy_counter_and_histogram():
    import time

    col = JaxIntrospectCollector()
    with col.step_timer():
        time.sleep(0.02)
    col.record_step(2, seconds=0.5)  # two steps, 0.25 s each
    devices = col.discover()
    s = col.sample(devices[0])
    assert s.values[schema.WORKLOAD_STEPS.name] == 3.0
    busy = s.values[schema.WORKLOAD_BUSY_SECONDS.name]
    assert 0.52 <= busy < 5.0
    (hist,) = col.extra_histograms()
    assert hist.total == 3
    assert abs(hist.sum - busy) < 1e-9
    # The two 0.25 s observations land in the (0.1, 0.25] bucket.
    assert hist.counts[schema.STEP_DURATION_BUCKETS.index(0.25)] == 2


def test_sample_reports_peak_memory_high_water_mark():
    import jax.numpy as jnp

    col = JaxIntrospectCollector()
    devices = col.discover()
    keepalive = jnp.ones((512, 512), jnp.float32)  # 1 MiB on device 0
    high = col.sample(devices[0])
    assert high.values[schema.MEMORY_PEAK.name] >= 1024 * 1024
    del keepalive
    jnp.zeros(()).block_until_ready()
    low = col.sample(devices[0])
    # Used drops with the allocation; the peak must not.
    assert low.values[schema.MEMORY_PEAK.name] >= \
        high.values[schema.MEMORY_PEAK.name]


def test_kind_capacity_table():
    assert _kind_capacity("TPU v5 lite") == 16 * 1024**3
    assert _kind_capacity("TPU v5p chip") == 95 * 1024**3
    assert _kind_capacity("TPU v4") == 32 * 1024**3
    assert _kind_capacity("Quantum Chip 9000") is None


def test_embedded_exporter_end_to_end():
    """start() -> workload steps -> scrape: the real-mode proof path, on
    the CPU mesh. Scrape surface and schema identical to the daemon's."""
    exporter = EmbeddedExporter(port=0, interval=0.05)
    exporter.start()
    try:
        exporter.record_step(3)
        assert exporter.registry.wait_for_publish(0, timeout=5)
        assert exporter.registry.wait_for_publish(
            exporter.registry.generation, timeout=5)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{exporter.port}/metrics", timeout=5
        ) as resp:
            body = resp.read().decode()
        assert body.count("accelerator_up{") == 8
        assert "accelerator_workload_steps_total{" in body
        assert "accelerator_workload_busy_seconds_total{" in body
        assert "accelerator_memory_used_bytes{" in body
        assert "accelerator_memory_peak_bytes{" in body
        assert "accelerator_workload_step_duration_seconds_bucket" in body
        assert 'backend="jax-embedded"' in body
        # The embedded output must pass the shipped schema validator
        # (review finding: histogram families once failed the contract).
        from kube_gpu_stats_tpu import validate
        assert validate.check(body) == []
        # Self-observability rides along like the daemon.
        assert "collector_poll_duration_seconds_bucket" in body
        with urllib.request.urlopen(
            f"http://127.0.0.1:{exporter.port}/healthz", timeout=5
        ) as resp:
            assert resp.status == 200
    finally:
        exporter.stop()


def test_burn_step_hook_feeds_counter():
    from kube_gpu_stats_tpu.loadgen.burn import run_burn

    col = JaxIntrospectCollector()
    steps = run_burn(seconds=0.2, size=128, report_every=1e9,
                     step_hook=col.record_step)
    assert steps > 0 and col._steps == steps
    # The burn reports its matmul FLOPs (4 chained matmuls of size^3).
    assert col._flops == steps * 2 * 4 * 128**3


def test_flops_counter_divides_over_local_devices():
    col = JaxIntrospectCollector()
    devices = col.discover()
    col.record_step(2, seconds=0.1, flops=16e9)
    s = col.sample(devices[0])
    assert s.values[schema.WORKLOAD_FLOPS.name] == 16e9 / len(devices)
    # CPU devices: no peak table entry -> no peak gauge, no MFU, never a
    # guess.
    assert schema.PEAK_FLOPS.name not in s.values
    assert schema.WORKLOAD_MFU.name not in s.values


def test_no_flops_reported_no_flops_series():
    col = JaxIntrospectCollector()
    col.record_step(3, seconds=0.1)
    s = col.sample(col.discover()[0])
    assert schema.WORKLOAD_FLOPS.name not in s.values


def test_mfu_gauge_from_tick_window(monkeypatch):
    import time as _time

    from kube_gpu_stats_tpu import embedded as embedded_mod

    # CPU device kinds have no table entry; pin a peak so the math is
    # checkable: 1 GFLOP/s peak per device.
    monkeypatch.setattr(embedded_mod, "_kind_peak_flops", lambda kind: 1e9)
    col = JaxIntrospectCollector()
    devices = col.discover()
    n = len(devices)
    col.record_step(1, flops=n * 100e9)
    col.begin_tick()  # first window point: no MFU yet
    assert col.sample(devices[0]).values.get(schema.WORKLOAD_MFU.name) is None
    _time.sleep(0.05)
    col.record_step(1, flops=n * 100e9)
    col.begin_tick()
    s = col.sample(devices[0])
    assert s.values[schema.PEAK_FLOPS.name] == 1e9
    mfu = s.values[schema.WORKLOAD_MFU.name]
    # 100e9 FLOPs/device at 1e9 peak: >100% for any window under 100 s —
    # proves the window math without a timing cliff, and that
    # over-reported FLOPs surface as >100 instead of being clamped into
    # plausibility.
    assert mfu > 100.0
    # A window with no new FLOPs drives MFU to ~0 (goodput gap visible).
    _time.sleep(0.01)
    col.begin_tick()
    assert col.sample(devices[0]).values[schema.WORKLOAD_MFU.name] < mfu


def test_real_probe_explains_fallback():
    """Round-1 verdict item 2: on a box with no TPU surface the harness
    must return a machine-checked record of WHY, not a bare None."""
    from kube_gpu_stats_tpu.bench import try_real_harness

    result, probe = try_real_harness(ticks=1, warmup=0, colaunch=False)
    assert result is None
    assert probe["ports"]
    assert all(v is False for v in probe["ports_open"].values())
    attempt = probe["external_attempt"]
    assert attempt["devices"] == 0 or attempt["error"]
    assert probe["burn_colaunch"]["skipped"] is True


def test_embedded_harness_refuses_cpu_as_real():
    """A CPU-only jax must never produce a mode:'real' bench result."""
    from kube_gpu_stats_tpu.bench import try_embedded_harness

    probe = {}
    result = try_embedded_harness(probe, ticks=1, warmup=0, burn_seconds=0.1)
    assert result is None
    assert probe["embedded_attempt"]["jax_platform"] == "cpu"
    assert "no accelerator platform" in probe["embedded_attempt"]["error"]


def test_colaunch_skipped_without_accelerator_platform(monkeypatch):
    """Review finding: a chip-less box must not pay a CPU burn before
    falling back to simulated mode — the platform probe short-circuits
    the co-launch. The probe runs in a subprocess (this sandbox's
    sitecustomize force-registers a real TPU plugin there, ignoring the
    conftest CPU pin), so it is stubbed for determinism."""
    from kube_gpu_stats_tpu import bench

    monkeypatch.setattr(bench, "_probe_jax_platform", lambda: "cpu")
    result, probe = bench.try_real_harness(ticks=1, warmup=0, colaunch=True)
    assert result is None
    assert probe["jax_platform"] == "cpu"
    assert probe["burn_colaunch"]["spawned"] is False
    assert "no accelerator platform" in str(probe["burn_colaunch"]["skipped"])


def test_embedded_exporter_metric_filter():
    import urllib.request

    exporter = EmbeddedExporter(metrics_exclude=("accelerator_uptime_seconds",))
    exporter.start()
    try:
        assert exporter.registry.wait_for_publish(0, timeout=5)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{exporter.port}/metrics", timeout=5
        ).read().decode()
    finally:
        exporter.stop()
    assert "accelerator_memory_used_bytes" in body
    assert "accelerator_uptime_seconds" not in body
    with pytest.raises(ValueError, match="unknown metric family"):
        EmbeddedExporter(metrics_exclude=("not_a_family",))
