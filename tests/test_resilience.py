"""Unit tests for the shared resilience primitives (resilience.py):
backoff math, circuit-breaker state machine under a fake clock, and the
per-tick deadline budget. No sleeps — every time-dependent behavior is
driven through the injectable clock."""

import pytest

from kube_gpu_stats_tpu.resilience import (CLOSED, HALF_OPEN, OPEN,
                                           BackoffPolicy, BreakerOpenError,
                                           CircuitBreaker, DeadlineBudget)


class FakeClock:
    def __init__(self, now=0.0):
        self.now = now

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


# -- BackoffPolicy ------------------------------------------------------------

def test_backoff_interval_for_is_exponential_and_capped():
    policy = BackoffPolicy(base=1.0, cap=6.0)
    assert policy.interval_for(0) == 1.0
    assert policy.interval_for(1) == 2.0
    assert policy.interval_for(2) == 4.0
    assert policy.interval_for(3) == 6.0  # capped
    assert policy.interval_for(50) == 6.0  # no overflow at silly counts


def test_backoff_stateful_next_delay_and_reset():
    policy = BackoffPolicy(base=0.5, cap=4.0)
    assert policy.next_delay() == 0.5
    assert policy.next_delay() == 1.0
    assert policy.next_delay() == 2.0
    policy.reset()
    assert policy.attempts == 0
    assert policy.next_delay() == 0.5


def test_backoff_decorrelated_jitter_bounded():
    import random

    policy = BackoffPolicy(base=1.0, cap=10.0, jitter=True,
                           rng=random.Random(7))
    prev = 1.0
    for _ in range(50):
        delay = policy.next_delay()
        assert 1.0 <= delay <= min(10.0, prev * 3)
        prev = delay


def test_backoff_rejects_bad_config():
    with pytest.raises(ValueError):
        BackoffPolicy(base=0.0, cap=1.0)
    with pytest.raises(ValueError):
        BackoffPolicy(base=2.0, cap=1.0)


# -- CircuitBreaker -----------------------------------------------------------

def test_breaker_trips_on_consecutive_failures_and_recovers():
    clock = FakeClock()
    breaker = CircuitBreaker("edge", failure_threshold=3, recovery_time=5.0,
                             clock=clock)
    assert breaker.state == CLOSED
    for _ in range(2):
        assert breaker.allow()
        breaker.record_failure(RuntimeError("boom"))
    assert breaker.state == CLOSED  # below threshold
    assert breaker.allow()
    breaker.record_failure(RuntimeError("boom"))
    assert breaker.state == OPEN
    assert breaker.trips_total == 1
    assert not breaker.allow()  # open refuses
    clock.advance(4.9)
    assert not breaker.allow()  # recovery not elapsed
    clock.advance(0.2)
    assert breaker.allow()  # the single probe
    assert breaker.state == HALF_OPEN
    assert not breaker.allow()  # only ONE probe admitted
    breaker.record_success()
    assert breaker.state == CLOSED
    assert breaker.consecutive_failures == 0
    assert breaker.last_error is None


def test_breaker_half_open_probe_failure_reopens():
    clock = FakeClock()
    breaker = CircuitBreaker(failure_threshold=1, recovery_time=2.0,
                             clock=clock)
    breaker.record_failure("down")
    assert breaker.state == OPEN
    clock.advance(2.0)
    assert breaker.allow()
    breaker.record_failure("still down")
    assert breaker.state == OPEN
    assert breaker.trips_total == 2
    assert not breaker.allow()  # recovery clock restarted
    clock.advance(2.0)
    assert breaker.allow()


def test_breaker_failure_rate_condition():
    clock = FakeClock()
    breaker = CircuitBreaker(failure_threshold=100, window=10,
                             failure_rate_threshold=0.5, clock=clock)
    # Alternate: 50% failures, but under `window` outcomes -> no trip.
    for _ in range(4):
        breaker.record_failure("x")
        breaker.record_success()
    assert breaker.state == CLOSED
    # Fill the window at >= 50% failures. (Stop at the trip: an
    # unsolicited success while OPEN is read as recovery evidence and
    # closes the breaker again.)
    for _ in range(5):
        breaker.record_failure("x")
        if breaker.state == OPEN:
            break
        breaker.record_success()
    assert breaker.state == OPEN


def test_breaker_min_failure_span_requires_duration():
    # N rapid failures (doctor's back-to-back ticks) must NOT read as a
    # persistent outage; the same count spread over the span must.
    clock = FakeClock()
    breaker = CircuitBreaker(failure_threshold=3, min_failure_span=2.0,
                             clock=clock)
    for _ in range(5):
        breaker.record_failure("burst")
    assert breaker.state == CLOSED  # burst spanned 0s
    clock.advance(2.5)
    breaker.record_failure("still failing")
    assert breaker.state == OPEN  # streak now spans >= 2s


def test_breaker_guard_and_call():
    clock = FakeClock()
    breaker = CircuitBreaker("kubelet", failure_threshold=1, clock=clock)
    assert breaker.call(lambda: 42) == 42
    with pytest.raises(RuntimeError):
        breaker.call(lambda: (_ for _ in ()).throw(RuntimeError("die")))
    assert breaker.state == OPEN
    with pytest.raises(BreakerOpenError) as err:
        breaker.guard()
    assert "kubelet" in str(err.value)


def test_breaker_state_values_for_gauge():
    clock = FakeClock()
    breaker = CircuitBreaker(failure_threshold=1, recovery_time=1.0,
                             clock=clock)
    assert breaker.state_value() == 0.0
    breaker.record_failure("x")
    assert breaker.state_value() == 2.0
    clock.advance(1.0)
    assert breaker.allow()
    assert breaker.state_value() == 1.0


def test_breaker_success_resets_streak():
    clock = FakeClock()
    breaker = CircuitBreaker(failure_threshold=3, clock=clock)
    breaker.record_failure("a")
    breaker.record_failure("b")
    breaker.record_success()
    breaker.record_failure("c")
    breaker.record_failure("d")
    assert breaker.state == CLOSED  # streak was broken by the success


# -- DeadlineBudget -----------------------------------------------------------

def test_deadline_budget_draws_down():
    clock = FakeClock()
    budget = DeadlineBudget(0.050, clock=clock)
    assert budget.remaining() == pytest.approx(0.050)
    assert budget.take(0.010) == pytest.approx(0.010)  # capped at want
    clock.advance(0.030)
    assert budget.remaining() == pytest.approx(0.020)
    assert budget.take() == pytest.approx(0.020)
    clock.advance(0.030)
    assert budget.remaining() == 0.0
    assert budget.take(1.0) == 0.0
    assert budget.expired()
    assert budget.elapsed() == pytest.approx(0.060)


def test_breaker_reclaims_abandoned_half_open_probe():
    """An admitted probe whose outcome is never recorded (caller dropped
    the call before it ran) must not wedge the breaker in HALF_OPEN
    forever: the probe slot is reclaimed after a recovery window."""
    clock = FakeClock()
    breaker = CircuitBreaker(failure_threshold=1, recovery_time=2.0,
                             clock=clock)
    breaker.record_failure("down")
    clock.advance(2.0)
    assert breaker.allow()  # probe admitted... and then abandoned
    assert not breaker.allow()  # slot held
    clock.advance(2.0)
    assert breaker.allow()  # reclaimed: a fresh probe is admitted
    breaker.record_success()
    assert breaker.state == CLOSED
