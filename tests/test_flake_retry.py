"""The box-noise retry helper (tests/flake.py): exactly one retry, on
the noise-shaped exception classes only, with a fresh tmp_path so
fixture trees built by the first attempt don't fail the retry."""

import pytest
from flake import retry_once_on_box_noise


def test_retries_exactly_once_and_passes():
    calls = []

    @retry_once_on_box_noise
    def flaky():
        calls.append(1)
        if len(calls) == 1:
            raise AssertionError("box noise")
        return "ok"

    assert flaky() == "ok"
    assert len(calls) == 2


def test_second_failure_propagates():
    @retry_once_on_box_noise
    def broken():
        raise AssertionError("real regression")

    with pytest.raises(AssertionError, match="real regression"):
        broken()
    # ...and non-noise exception classes never retry at all.
    calls = []

    @retry_once_on_box_noise
    def buggy():
        calls.append(1)
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        buggy()
    assert len(calls) == 1


def test_retry_gets_a_fresh_tmp_path(tmp_path):
    """Review fix: the first attempt builds fixture trees (make_sysfs
    mkdirs without exist_ok); re-running into the same directory would
    fail deterministically and mask the flake being retried."""
    seen = []

    @retry_once_on_box_noise
    def builds_a_tree(tmp_path):
        seen.append(tmp_path)
        (tmp_path / "sys").mkdir()  # FileExistsError on a reused dir
        if len(seen) == 1:
            raise AssertionError("box noise")

    builds_a_tree(tmp_path=tmp_path)
    assert len(seen) == 2
    assert seen[0] != seen[1]
    assert seen[1] == tmp_path / "box-noise-retry"
