"""Deployment-asset schema validation (SURVEY.md §4 "e2e manifests:
dry-run/schema validation only; no TPU nodes in CI") + the zero-NVML
constraint from BASELINE.md, checked at the artifact level."""

import json
import pathlib
import re

import yaml

from kube_gpu_stats_tpu import schema

DEPLOY = pathlib.Path(__file__).parent.parent / "deploy"


def load_yaml_docs(name):
    return [d for d in yaml.safe_load_all((DEPLOY / name).read_text()) if d]


def test_daemonset_shape():
    (ds,) = load_yaml_docs("daemonset.yaml")
    assert ds["kind"] == "DaemonSet"
    spec = ds["spec"]["template"]["spec"]
    # TPU node pools: GKE sets the accelerator label VALUE to the type, so
    # scheduling must match on key existence (Exists), never a value.
    terms = spec["affinity"]["nodeAffinity"][
        "requiredDuringSchedulingIgnoredDuringExecution"]["nodeSelectorTerms"]
    exprs = [e for t in terms for e in t["matchExpressions"]]
    assert any(
        e["key"] == "cloud.google.com/gke-tpu-accelerator"
        and e["operator"] == "Exists"
        for e in exprs
    )
    assert "nodeSelector" not in spec  # exact-value match would never schedule
    assert any(t["key"] == "google.com/tpu" for t in spec["tolerations"])
    # Host surfaces the exporter needs (L0 sysfs + C3 attribution).
    mounts = {m["mountPath"]: m for m in spec["containers"][0]["volumeMounts"]}
    assert mounts["/sys"]["readOnly"] is True
    assert "/var/lib/kubelet/pod-resources" in mounts
    assert "/var/lib/kubelet/device-plugins" in mounts
    volumes = {v["name"]: v for v in spec["volumes"]}
    assert volumes["sys"]["hostPath"]["path"] == "/sys"
    # libtpu metric service is on the node loopback.
    assert spec["hostNetwork"] is True
    container = spec["containers"][0]
    assert container["ports"][0]["containerPort"] == 9400
    assert container["livenessProbe"]["httpGet"]["path"] == "/healthz"
    assert container["securityContext"]["readOnlyRootFilesystem"] is True


def test_rbac_and_service():
    docs = load_yaml_docs("rbac.yaml")
    kinds = [d["kind"] for d in docs]
    assert kinds == ["Namespace", "ServiceAccount", "Service"]
    service = docs[2]
    assert service["spec"]["clusterIP"] == "None"
    assert service["spec"]["ports"][0]["port"] == 9400


def test_zero_nvml_cuda_userspace():
    """BASELINE.md binary constraint, applied to the shipped artifacts: no
    NVML/CUDA anywhere in image or manifests. ('nvidia.com/gpu' is the k8s
    resource name used for unified attribution, not userspace.)"""
    for name in ("Dockerfile", "daemonset.yaml", "rbac.yaml"):
        functional = "\n".join(
            line for line in (DEPLOY / name).read_text().splitlines()
            if not line.lstrip().startswith("#")  # prose may *say* "no CUDA"
        ).lower()
        for needle in ("nvml", "cuda", "nvidia-smi", "libnvidia"):
            assert needle not in functional, (name, needle)


def test_dockerfile_entrypoint_and_user():
    text = (DEPLOY / "Dockerfile").read_text()
    assert '"python", "-m", "kube_gpu_stats_tpu"' in text
    assert "USER 65532" in text  # non-root
    assert "EXPOSE 9400" in text


METRIC_TOKEN = re.compile(r"\b(accelerator_[a-z_]+|collector_[a-z_]+)\b")


def known_exposition_names():
    names = set()
    for spec in schema.ALL_METRICS:
        names.add(spec.name)
        if spec.type is schema.MetricType.HISTOGRAM:
            names.update(
                {f"{spec.name}_bucket", f"{spec.name}_sum", f"{spec.name}_count"}
            )
    return names


def test_dashboard_json_matches_builder():
    """dashboard.json must be exactly what build_dashboard.py generates
    — hand-edits to the JSON get destroyed by the next `make dashboard`
    (round-5 finding: four round-4 panels lived only in the JSON and a
    rebuild silently deleted them). Edit the builder, regenerate,
    commit both."""
    import runpy
    import shutil
    import tempfile

    src = DEPLOY / "grafana" / "build_dashboard.py"
    committed = (DEPLOY / "grafana" / "dashboard.json").read_text()
    with tempfile.TemporaryDirectory() as tmp:
        build = pathlib.Path(tmp) / "build_dashboard.py"
        shutil.copy(src, build)
        runpy.run_path(str(build), run_name="__main__")
        rebuilt = (pathlib.Path(tmp) / "dashboard.json").read_text()
    assert rebuilt == committed, (
        "dashboard.json drifted from build_dashboard.py output; run "
        "`make dashboard` and commit, or port hand-edits into the builder")


def test_dashboard_references_only_real_metrics():
    board = json.loads((DEPLOY / "grafana" / "dashboard.json").read_text())
    known = known_exposition_names()
    exprs = [
        t["expr"]
        for panel in board["panels"]
        for t in panel.get("targets", [])
    ]
    assert exprs, "dashboard has no queries"
    for expr in exprs:
        for token in METRIC_TOKEN.findall(expr):
            assert token in known, f"dashboard references unknown metric {token}"


def test_dashboard_chip_colors_fixed_order_not_cycled():
    board = json.loads((DEPLOY / "grafana" / "dashboard.json").read_text())

    def color_overrides(panel):
        # Overrides also carry non-color properties now (right-hand
        # axis placement); only chip-color overrides are compared.
        return [
            o["properties"][0]["value"]["fixedColor"]
            for o in panel.get("fieldConfig", {}).get("overrides", [])
            if o["properties"][0]["id"] == "color"
        ]

    per_chip_panels = [p for p in board["panels"] if color_overrides(p)]
    assert per_chip_panels
    first = color_overrides(per_chip_panels[0])
    assert len(first) == len(set(first)) == 8
    for panel in per_chip_panels[1:]:
        # same chip -> same color on every panel
        assert color_overrides(panel) == first


def test_dashboard_template_vars():
    board = json.loads((DEPLOY / "grafana" / "dashboard.json").read_text())
    names = {v["name"] for v in board["templating"]["list"]}
    assert {"datasource", "slice", "worker", "accel_type"} <= names


def test_alert_rules_parse_and_reference_real_metrics():
    doc = yaml.safe_load((DEPLOY / "alerts.yaml").read_text())
    rules = [r for g in doc["groups"] for r in g["rules"]]
    assert len(rules) >= 4
    known = known_exposition_names()
    for rule in rules:
        assert "alert" in rule and "expr" in rule
        for token in METRIC_TOKEN.findall(rule["expr"]):
            assert token in known, f"alert references unknown metric {token}"
        assert rule.get("labels", {}).get("severity") in ("warning", "critical")


def test_recording_rules_parse_and_reference_real_metrics():
    doc = yaml.safe_load((DEPLOY / "recording_rules.yaml").read_text())
    rules = [r for g in doc["groups"] for r in g["rules"]]
    assert len(rules) >= 5
    known = known_exposition_names()
    for rule in rules:
        assert "record" in rule and "expr" in rule
        for token in METRIC_TOKEN.findall(rule["expr"]):
            assert token in known, f"recording rule references unknown {token}"


def test_systemd_unit_shape():
    """The TPU VM (non-Kubernetes) half of C8: unit parses as INI, restarts
    on failure, points at the real module, and hardening doesn't break the
    exporter's two filesystem needs (read /sys, write the textfile dir)."""
    import configparser

    parser = configparser.ConfigParser(strict=True)
    # systemd allows repeated keys; none are used in this unit, so strict
    # INI parsing doubles as a lint that we don't start relying on them.
    parser.read_string((DEPLOY / "systemd" / "kube-tpu-stats.service").read_text())
    service = parser["Service"]
    assert "kube_gpu_stats_tpu" in service["ExecStart"]
    assert service["Restart"] == "always"
    assert service["EnvironmentFile"].lstrip("-") == "/etc/default/kube-tpu-stats"
    # ProtectSystem=strict makes / read-only: the textfile dir must be
    # carved back out or the TextfileWriter would crash-loop the unit.
    assert service["ProtectSystem"] == "strict"
    assert "textfile_collector" in service["ReadWritePaths"]
    assert parser["Install"]["WantedBy"] == "multi-user.target"


def test_systemd_env_file_keys_are_real_flags():
    """Every KTS_* key in the sample env file must correspond to a real
    flag (config.py reads KTS_<dest-upper>); a typo here ships a silently
    ignored setting to every TPU VM install."""
    from kube_gpu_stats_tpu.config import build_parser

    dests = {
        "KTS_" + a.dest.upper()
        for a in build_parser()._actions
        if a.dest != "help"
    } | {"KTS_NO_NATIVE",
         # Read by topology.py (topology_labels/accel_type), not config.py.
         "KTS_SLICE", "KTS_WORKER", "KTS_TOPOLOGY", "KTS_ACCEL_TYPE"}
    text = (DEPLOY / "systemd" / "kube-tpu-stats.env").read_text()
    for line in text.splitlines():
        line = line.strip().lstrip("# ")
        if "=" in line and line.split("=")[0].startswith("KTS_"):
            key = line.split("=")[0]
            assert key in dests, f"env file sets unknown variable {key}"


def test_systemd_installer_references_shipped_files():
    text = (DEPLOY / "systemd" / "install.sh").read_text()
    assert "set -euo pipefail" in text
    for shipped in ("kube-tpu-stats.service", "kube-tpu-stats.env"):
        assert shipped in text
        assert (DEPLOY / "systemd" / shipped).exists()
    assert "doctor" in text  # preflight after install


def test_podmonitor_matches_daemonset():
    """The optional prometheus-operator PodMonitors must select the
    pods they claim (DaemonSet and hub) and scrape the port the
    container actually names."""
    docs = load_yaml_docs("podmonitor.yaml")
    assert [d["kind"] for d in docs] == ["PodMonitor", "PodMonitor"]
    by_name = {d["metadata"]["name"]: d for d in docs}
    pm = by_name["kube-tpu-stats"]
    (ds,) = [d for d in load_yaml_docs("daemonset.yaml") if d["kind"] == "DaemonSet"]
    pod_labels = ds["spec"]["template"]["metadata"]["labels"]
    for key, value in pm["spec"]["selector"]["matchLabels"].items():
        assert pod_labels.get(key) == value
    container = ds["spec"]["template"]["spec"]["containers"][0]
    port_names = {p["name"] for p in container["ports"]}
    for endpoint in pm["spec"]["podMetricsEndpoints"]:
        assert endpoint["port"] in port_names
        assert endpoint.get("path", "/metrics") == "/metrics"
    assert pm["metadata"]["namespace"] == ds["metadata"]["namespace"]

    # Hub PodMonitor: pod-direct scraping so the zero-target NotReady
    # hub stays visible to SliceHubNoTargets.
    hub_pm = by_name["kube-tpu-stats-hub"]
    (dep,) = [d for d in load_yaml_docs("hub.yaml")
              if d["kind"] == "Deployment"]
    hub_labels = dep["spec"]["template"]["metadata"]["labels"]
    for key, value in hub_pm["spec"]["selector"]["matchLabels"].items():
        assert hub_labels.get(key) == value
    hub_ports = {p["name"] for c in
                 dep["spec"]["template"]["spec"]["containers"]
                 for p in c["ports"]}
    for endpoint in hub_pm["spec"]["podMetricsEndpoints"]:
        assert endpoint["port"] in hub_ports
    assert hub_pm["metadata"]["namespace"] == dep["metadata"]["namespace"]


def test_hub_manifest_shape():
    """deploy/hub.yaml: the optional slice aggregation Deployment must run
    the hub subcommand against the mounted targets file, wire probes to
    the hub's stale-aware endpoints, and keep names consistent across the
    ConfigMap, volume, and Service selector."""
    docs = load_yaml_docs("hub.yaml")
    by_kind = {d["kind"]: d for d in docs}
    assert set(by_kind) == {"ConfigMap", "Deployment", "Service"}
    dep = by_kind["Deployment"]
    pod = dep["spec"]["template"]
    container = pod["spec"]["containers"][0]
    assert container["args"][0] == "hub"
    targets_idx = container["args"].index("--targets-file")
    targets_path = container["args"][targets_idx + 1]
    mount = container["volumeMounts"][0]
    assert targets_path.startswith(mount["mountPath"])
    volumes = {v["name"]: v for v in pod["spec"]["volumes"]}
    mounts = {m["name"]: m for m in container["volumeMounts"]}
    assert set(volumes) == set(mounts) == {"targets", "state"}
    assert volumes["targets"]["configMap"]["name"] == \
        by_kind["ConfigMap"]["metadata"]["name"]
    # Warm-restart state (ISSUE 12): the checkpoint path must land on
    # the writable emptyDir, which survives container restarts — the
    # liveness-probe case the checkpoint exists for.
    assert "emptyDir" in volumes["state"]
    ckpt_idx = container["args"].index("--ingest-checkpoint")
    assert container["args"][ckpt_idx + 1].startswith(
        mounts["state"]["mountPath"])
    filename = targets_path[len(mount["mountPath"]):].lstrip("/")
    assert filename in by_kind["ConfigMap"]["data"]
    assert container["livenessProbe"]["httpGet"]["path"] == "/healthz"
    assert container["readinessProbe"]["httpGet"]["path"] == "/readyz"
    port_names = {p["name"] for p in container["ports"]}
    assert container["livenessProbe"]["httpGet"]["port"] in port_names
    svc = by_kind["Service"]
    pod_labels = pod["metadata"]["labels"]
    for key, value in svc["spec"]["selector"].items():
        assert pod_labels.get(key) == value
    assert {d["metadata"]["namespace"] for d in docs} == {
        dep["metadata"]["namespace"]}


def test_kustomization_references_existing_manifests():
    """Every resource in deploy/kustomization.yaml must exist and parse
    as a k8s manifest (a rename breaks `kubectl apply -k` at deploy
    time, not CI, unless pinned here)."""
    doc = yaml.safe_load((DEPLOY / "kustomization.yaml").read_text())
    assert doc["kind"] == "Kustomization"
    assert doc["resources"], "kustomization lists no resources"
    for resource in doc["resources"]:
        path = DEPLOY / resource
        assert path.exists(), f"kustomization references missing {resource}"
        for manifest in load_yaml_docs(resource):
            assert "kind" in manifest and "apiVersion" in manifest
