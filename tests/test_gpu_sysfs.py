"""NVML-free GPU collector over /sys/class/drm fixtures (C12 single-binary
mixed clusters)."""

import pytest

from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.collectors import CollectorError
from kube_gpu_stats_tpu.collectors.gpu_sysfs import GpuSysfsCollector
from kube_gpu_stats_tpu.testing.sysfs_fixture import make_drm_sysfs


def test_discovery_skips_connector_nodes(tmp_path):
    make_drm_sysfs(tmp_path, num_cards=2)
    col = GpuSysfsCollector(tmp_path)
    devs = col.discover()
    assert [d.index for d in devs] == [0, 1]
    assert devs[0].accel_type == "gpu-amd"
    assert devs[0].device_path == "/dev/dri/card0"
    assert devs[1].uuid == "gpu-uid-0001"


def test_vendor_mapping(tmp_path):
    make_drm_sysfs(tmp_path, num_cards=1, vendor="0x10de")
    assert GpuSysfsCollector(tmp_path).discover()[0].accel_type == "gpu-nvidia"
    make_drm_sysfs(tmp_path / "intel", num_cards=1, vendor="0x8086")
    assert GpuSysfsCollector(tmp_path / "intel").discover()[0].accel_type == "gpu-intel"


def test_sample_values_and_scaling(tmp_path):
    make_drm_sysfs(tmp_path, num_cards=1, busy_percent=42,
                   power_uw=200_000_000, temp_mc=65_500)
    col = GpuSysfsCollector(tmp_path)
    s = col.sample(col.discover()[0])
    assert s.values[schema.DUTY_CYCLE.name] == 42.0
    assert s.values[schema.MEMORY_USED.name] == 4 * 1024**3
    assert s.values[schema.MEMORY_TOTAL.name] == 16 * 1024**3
    assert s.values[schema.POWER.name] == pytest.approx(200.0)
    assert s.values[schema.TEMPERATURE.name] == pytest.approx(65.5)


def test_partial_attributes(tmp_path):
    make_drm_sysfs(tmp_path, num_cards=1)
    (tmp_path / "class/drm/card0/device/gpu_busy_percent").unlink()
    col = GpuSysfsCollector(tmp_path)
    s = col.sample(col.discover()[0])
    assert schema.DUTY_CYCLE.name not in s.values
    assert schema.POWER.name in s.values


def test_vanished_card_raises(tmp_path):
    make_drm_sysfs(tmp_path, num_cards=1)
    col = GpuSysfsCollector(tmp_path)
    dev = col.discover()[0]
    import shutil

    shutil.rmtree(tmp_path / "class/drm/card0")
    with pytest.raises(CollectorError):
        col.sample(dev)


def test_daemon_auto_prefers_tpu_then_gpu(tmp_path):
    from kube_gpu_stats_tpu.config import Config
    from kube_gpu_stats_tpu.daemon import build_collector

    # GPU-only node: auto lands on gpu-sysfs.
    make_drm_sysfs(tmp_path, num_cards=2)
    cfg = Config(backend="auto", sysfs_root=str(tmp_path), use_native=False)
    col = build_collector(cfg)
    assert col.name == "gpu-sysfs"
    assert len(col.discover()) == 2


def test_gpu_through_poll_loop(tmp_path):
    from kube_gpu_stats_tpu.poll import PollLoop
    from kube_gpu_stats_tpu.registry import Registry

    make_drm_sysfs(tmp_path, num_cards=2)
    reg = Registry()
    loop = PollLoop(GpuSysfsCollector(tmp_path), reg, deadline=5.0)
    loop.tick()
    snap = reg.snapshot()
    duty = [s for s in snap.series if s.spec.name == schema.DUTY_CYCLE.name]
    assert len(duty) == 2
    assert dict(duty[0].labels)["accel_type"] == "gpu-amd"
    loop.stop()


def test_bmc_framebuffer_card_not_selected_by_auto(tmp_path):
    """A display-only card (BMC/integrated) has /sys/class/drm/cardN but no
    telemetry files; auto must fall back to null (review finding)."""
    from kube_gpu_stats_tpu.config import Config
    from kube_gpu_stats_tpu.daemon import build_collector

    device = tmp_path / "class" / "drm" / "card0" / "device"
    device.mkdir(parents=True)
    (device / "vendor").write_text("0x1a03\n")  # ASPEED BMC
    col = build_collector(Config(backend="auto", sysfs_root=str(tmp_path),
                                 use_native=False))
    assert col.name == "null"
    # Explicit --backend gpu still allows it (operator override).
    gpu = build_collector(Config(backend="gpu", sysfs_root=str(tmp_path)))
    assert gpu.name == "gpu-sysfs"


def test_telemetry_capable_requires_readable_values(tmp_path):
    """Review finding: existence-only capability check latched a backend
    that exports nothing when the attribute files can't be parsed."""
    from kube_gpu_stats_tpu.collectors.gpu_sysfs import GpuSysfsCollector

    card = tmp_path / "class" / "drm" / "card0" / "device"
    card.mkdir(parents=True)
    (card / "gpu_busy_percent").write_text("not a number\n")
    col = GpuSysfsCollector(sysfs_root=str(tmp_path))
    assert col.telemetry_capable() is False
    (card / "gpu_busy_percent").write_text("42\n")
    assert col.telemetry_capable() is True


# -- burst-path parity (ISSUE 8 satellite: the GPU backend grows the
# -- same burst hooks as the TPU sysfs path, prep for ROADMAP item 4) --------

def test_read_burst_matches_sample_power(tmp_path):
    make_drm_sysfs(tmp_path, num_cards=2, power_uw=180_000_000)
    col = GpuSysfsCollector(sysfs_root=str(tmp_path))
    for dev in col.discover():
        burst = col.read_burst(dev)
        gauge = col.sample(dev).values[schema.POWER.name]
        assert burst == pytest.approx(gauge)
    assert col.read_burst(col.discover()[0]) == pytest.approx(180.0)


def test_read_burst_caches_path_and_reresolves(tmp_path):
    make_drm_sysfs(tmp_path, num_cards=1, power_uw=180_000_000)
    col = GpuSysfsCollector(sysfs_root=str(tmp_path))
    dev = col.discover()[0]
    assert col.read_burst(dev) == pytest.approx(180.0)
    power = (tmp_path / "class" / "drm" / "card0" / "device" / "hwmon"
             / "hwmon1" / "power1_average")
    power.write_text("900000000\n")
    # Cached path serves the new value without a re-glob.
    assert col.read_burst(dev) == pytest.approx(900.0)
    power.unlink()
    assert col.read_burst(dev) is None
    # Attribute reappears (driver reload): re-resolved, not latched dead.
    power.write_text("200000000\n")
    assert col.read_burst(dev) == pytest.approx(200.0)


def test_read_burst_none_without_power_attribute(tmp_path):
    card = tmp_path / "class" / "drm" / "card0" / "device"
    card.mkdir(parents=True)
    (card / "gpu_busy_percent").write_text("42\n")
    col = GpuSysfsCollector(sysfs_root=str(tmp_path))
    assert col.read_burst(col.discover()[0]) is None


def test_burst_sampler_runs_over_gpu_backend(tmp_path):
    """The sampler composes with the GPU backend exactly as with the
    TPU one — one read per card per pass into the per-device ring."""
    from kube_gpu_stats_tpu.burstsampler import BurstSampler

    make_drm_sysfs(tmp_path, num_cards=2, power_uw=180_000_000)
    col = GpuSysfsCollector(sysfs_root=str(tmp_path))
    devices = col.discover()
    sampler = BurstSampler(lambda: col, lambda: devices)
    assert sampler._read_once() == 2
    assert sampler.drain("0")[0][1] == pytest.approx(180.0)
    assert sampler.drain("1")[0][1] == pytest.approx(185.0)
