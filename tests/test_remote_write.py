"""Remote-write client: WriteRequest correctness against a fake receiver
(snappy+prompb decoded), spec retry semantics (5xx retried, 4xx dropped),
bearer-token refresh, and daemon wiring."""

import http.server
import threading

import pytest

from kube_gpu_stats_tpu import schema, snappy
from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.proto import prompb, prompb2
from kube_gpu_stats_tpu.registry import Registry
from kube_gpu_stats_tpu.remote_write import (RemoteWriter,
                                             build_write_request,
                                             build_write_request_v2)


class FakeReceiver:
    """Minimal remote-write receiver: records decoded WriteRequests; can
    be scripted to answer with an HTTP error code."""

    def __init__(self):
        self.requests = []
        self.requests_v2 = []
        self.headers = []
        self.puts = []
        self.fail_codes = []  # pop-front script of status codes
        self.fail_headers = []  # optional parallel script of header dicts
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                body = self.rfile.read(int(self.headers["Content-Length"]))
                outer.headers.append(dict(self.headers))
                if outer.fail_codes:
                    self.send_response(outer.fail_codes.pop(0))
                    for key, value in (outer.fail_headers.pop(0)
                                       if outer.fail_headers else {}).items():
                        self.send_header(key, value)
                    self.end_headers()
                    return
                raw = snappy.decompress(body)
                if "io.prometheus.write.v2" in self.headers.get(
                        "Content-Type", ""):
                    outer.requests_v2.append(prompb2.decode_request(raw))
                else:
                    outer.requests.append(prompb.decode_write_request(raw))
                self.send_response(204)
                self.end_headers()

            def do_PUT(self):  # pushgateway-style target for mode tests
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                outer.puts.append(self.path)
                self.send_response(200)
                self.end_headers()

            def log_message(self, *args):
                pass

        self._httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}/api/v1/push"


@pytest.fixture
def registry():
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=2), reg, deadline=5.0)
    loop.tick()
    loop.stop()
    return reg


def test_write_request_carries_all_series(registry):
    snapshot = registry.snapshot()
    decoded = prompb.decode_write_request(
        build_write_request(snapshot, "kts", "node-1"))
    names = {labels["__name__"] for labels, _ in decoded}
    assert schema.DUTY_CYCLE.name in names
    assert schema.SELF_POLL_DURATION.name + "_bucket" in names
    assert schema.SELF_POLL_DURATION.name + "_count" in names
    for labels, samples in decoded:
        assert labels["job"] == "kts"
        assert labels["instance"] == "node-1"
        assert list(labels) == sorted(labels)  # spec: sorted by name
        assert "" not in labels.values()  # spec: no empty label values
        assert len(samples) == 1
        assert samples[0][1] == int(snapshot.timestamp * 1000)
    # Histogram le values must match the scrape path's text rendering.
    les = {labels["le"] for labels, _ in decoded if "le" in labels}
    assert "0.05" in les and "+Inf" in les


def test_push_end_to_end(registry):
    with FakeReceiver() as receiver:
        writer = RemoteWriter(registry, receiver.url, job="kts",
                              instance="n0", min_interval=0.0)
        writer.push_once()
        assert writer.consecutive_failures == 0
        (request,) = receiver.requests
        duty = [s for labels, s in request
                if labels["__name__"] == schema.DUTY_CYCLE.name
                and labels["chip"] == "0"]
        assert len(duty) == 1
        headers = receiver.headers[0]
        assert headers["Content-Encoding"] == "snappy"
        assert headers["Content-Type"] == "application/x-protobuf"
        assert headers["X-Prometheus-Remote-Write-Version"] == "0.1.0"


def test_5xx_counts_failure_4xx_drops(registry):
    with FakeReceiver() as receiver:
        writer = RemoteWriter(registry, receiver.url, min_interval=0.0)
        receiver.fail_codes.append(503)
        writer.push_once()
        assert writer.consecutive_failures == 1
        assert writer.dropped_total == 0
        receiver.fail_codes.append(400)
        writer.push_once()
        assert writer.consecutive_failures == 1  # not a retryable failure
        assert writer.dropped_total == 1
        writer.push_once()  # receiver healthy again
        assert writer.consecutive_failures == 0


def test_429_is_retryable(registry):
    with FakeReceiver() as receiver:
        writer = RemoteWriter(registry, receiver.url, min_interval=0.0)
        receiver.fail_codes.append(429)
        writer.push_once()
        assert writer.consecutive_failures == 1
        assert writer.dropped_total == 0


def test_bearer_token_reread_per_push(registry, tmp_path):
    token = tmp_path / "token"
    token.write_text("first\n")
    with FakeReceiver() as receiver:
        writer = RemoteWriter(registry, receiver.url, min_interval=0.0,
                              bearer_token_file=str(token))
        writer.push_once()
        token.write_text("second\n")  # rotation
        writer.push_once()
    assert receiver.headers[0]["Authorization"] == "Bearer first"
    assert receiver.headers[1]["Authorization"] == "Bearer second"


def test_unreadable_token_skips_push_and_backs_off(registry, tmp_path):
    """A missing/rotating token must not push unauthenticated (and then
    treat the 401 as a permanent drop) — it skips the push as a retryable
    failure."""
    with FakeReceiver() as receiver:
        writer = RemoteWriter(registry, receiver.url, min_interval=0.0,
                              bearer_token_file=str(tmp_path / "absent"))
        writer.push_once()
        assert receiver.requests == [] and receiver.headers == []
        assert writer.consecutive_failures == 1
        assert writer.dropped_total == 0
        (tmp_path / "absent").write_text("tok")  # token appears
        writer.push_once()
        assert writer.consecutive_failures == 0
        assert receiver.headers[0]["Authorization"] == "Bearer tok"


def test_empty_snapshot_not_pushed():
    with FakeReceiver() as receiver:
        writer = RemoteWriter(Registry(), receiver.url, min_interval=0.0)
        writer.push_once()
        assert receiver.requests == []


def test_follows_publishes(registry):
    with FakeReceiver() as receiver:
        writer = RemoteWriter(registry, receiver.url, min_interval=0.0)
        writer.start()
        loop = PollLoop(MockCollector(num_devices=1), registry, deadline=5.0)
        loop.tick()
        loop.stop()
        deadline = threading.Event()
        for _ in range(50):
            if receiver.requests:
                break
            deadline.wait(0.1)
        writer.stop()
    assert receiver.requests


def test_push_health_self_metrics(registry):
    """collector_push_* families surface shipping health on the scrape."""
    from kube_gpu_stats_tpu.config import Config
    from kube_gpu_stats_tpu.daemon import Daemon

    with FakeReceiver() as receiver:
        d = Daemon(Config(backend="mock", attribution="off",
                          remote_write_url=receiver.url,
                          pushgateway_url=f"http://127.0.0.1:{receiver.port}",
                          listen_port=0))
        try:
            d.poll.tick()  # non-empty snapshot so the push actually fires
            receiver.fail_codes.append(503)
            d.remote_writer.push_once()  # one failure on record
            d.pusher.push_once()  # one pushgateway success
            d.poll.tick()
            series = {
                (s.spec.name, dict(s.labels).get("mode")): s.value
                for s in d.registry.snapshot().series
                if s.spec.name.startswith("collector_push_")
            }
        finally:
            d.poll.stop()
            d.collector.close()
    assert series[("collector_push_failures_total", "remote_write")] == 1.0
    assert series[("collector_push_total", "remote_write")] == 0.0
    assert series[("collector_push_dropped_total", "remote_write")] == 0.0
    assert series[("collector_push_total", "pushgateway")] == 1.0
    assert series[("collector_push_failures_total", "pushgateway")] == 0.0
    assert receiver.puts  # the PUT actually landed


def test_daemon_wires_remote_writer():
    from kube_gpu_stats_tpu.config import Config
    from kube_gpu_stats_tpu.daemon import Daemon

    d = Daemon(Config(backend="mock", attribution="off",
                      remote_write_url="http://127.0.0.1:1/api/v1/push",
                      listen_port=0))
    try:
        assert d.remote_writer is not None
    finally:
        d.collector.close()
    d2 = Daemon(Config(backend="mock", attribution="off", listen_port=0))
    try:
        assert d2.remote_writer is None
    finally:
        d2.collector.close()


def test_prompb_known_answer_against_real_protobuf():
    """Round-1 advisor finding: the hand-rolled prompb encoder was only
    validated against its own decoder. This golden byte string was
    generated with protoc 3.21 + the google.protobuf runtime from the
    remote-write 1.0 WriteRequest schema (two timeseries, sorted labels,
    one sample each) — byte-for-byte what a real receiver parses."""
    from kube_gpu_stats_tpu.proto import prompb

    golden = bytes.fromhex(
        "0a580a220a085f5f6e616d655f5f1216616363656c657261746f725f64757479"
        "5f6379636c650a090a04636869701201300a150a036a6f62120e6b7562652d74"
        "70752d73746174731210090000000000c049401080d8a5de8f320a1e0a0e0a08"
        "5f5f6e616d655f5f12027570120c09000000000000f03f10e807"
    )
    got = prompb.encode_write_request([
        prompb.encode_series(
            "accelerator_duty_cycle",
            [("chip", "0"), ("job", "kube-tpu-stats")],
            51.5, 1722211200000,
        ),
        prompb.encode_series("up", [], 1.0, 1000),
    ])
    assert got == golden
    # And the test-side decoder reads the real-protobuf bytes too.
    decoded = prompb.decode_write_request(golden)
    assert decoded[0][0]["__name__"] == "accelerator_duty_cycle"
    assert decoded[0][1] == [(51.5, 1722211200000)]
    assert decoded[1][0] == {"__name__": "up"}
    assert decoded[1][1] == [(1.0, 1000)]


def test_labeled_histogram_states_carry_their_labels():
    """Scrape-duration histograms are dimensioned by output; every expanded
    remote-write series must carry that label next to le/job/instance."""
    from kube_gpu_stats_tpu import schema
    from kube_gpu_stats_tpu.registry import HistogramState, SnapshotBuilder

    builder = SnapshotBuilder()
    hist = HistogramState.empty(
        schema.SELF_SCRAPE_DURATION, schema.SCRAPE_DURATION_BUCKETS,
        labels=(("output", "http"),),
    ).observe(0.004)
    builder.add_histogram(hist)
    decoded = prompb.decode_write_request(
        build_write_request(builder.build(), "kts", "node-1"))
    hist_series = [
        (labels, samples) for labels, samples in decoded
        if labels["__name__"].startswith("collector_scrape_duration_seconds")
    ]
    assert hist_series
    for labels, _ in hist_series:
        assert labels["output"] == "http"
        assert labels["job"] == "kts"


# --- remote-write 2.0 (io.prometheus.write.v2.Request, proto/prompb2) -------

def test_prompb2_known_answer_against_real_protobuf():
    """Golden bytes generated with protoc + the google.protobuf runtime
    from the remote-write 2.0 Request schema (two timeseries, interned
    symbols, gauge metadata with help) — byte-for-byte what a real 2.0
    receiver parses."""
    golden = bytes.fromhex(
        "220022085f5f6e616d655f5f2216616363656c657261746f725f647574795f63"
        "79636c6522046368697022013022036a6f62220e6b7562652d7470752d737461"
        "74732205447574792e220275702a200a060102030405061210090000000000c0"
        "49401080d8a5de8f322a04080218072a120a020108120c09000000000000f03f"
        "10e807"
    )
    table = prompb2.SymbolTable()
    series = [
        prompb2.encode_series(
            table, "accelerator_duty_cycle",
            [("chip", "0"), ("job", "kube-tpu-stats")],
            51.5, 1722211200000, prompb2.TYPE_GAUGE, "Duty."),
        prompb2.encode_series(table, "up", [], 1.0, 1000),
    ]
    assert prompb2.encode_request(table, series) == golden
    decoded = prompb2.decode_request(golden)
    assert decoded[0][0] == {"__name__": "accelerator_duty_cycle",
                             "chip": "0", "job": "kube-tpu-stats"}
    assert decoded[0][1] == [(51.5, 1722211200000)]
    assert decoded[0][2] == {"type": prompb2.TYPE_GAUGE, "help": "Duty."}
    assert decoded[1][0] == {"__name__": "up"} and decoded[1][2] == {}


def test_v2_request_same_series_set_as_v1(registry):
    snapshot = registry.snapshot()
    v1 = prompb.decode_write_request(
        build_write_request(snapshot, "kts", "n0"))
    v2 = prompb2.decode_request(build_write_request_v2(snapshot, "kts", "n0"))
    assert [(labels, samples) for labels, samples, _ in v2] == v1
    # Typed metadata rides every v2 series.
    by_name = {labels["__name__"]: md for labels, _, md in v2}
    assert by_name[schema.DUTY_CYCLE.name]["type"] == prompb2.TYPE_GAUGE
    assert by_name[schema.ICI_TRAFFIC_TOTAL.name]["type"] == \
        prompb2.TYPE_COUNTER
    assert by_name[schema.SELF_POLL_DURATION.name + "_bucket"]["type"] == \
        prompb2.TYPE_HISTOGRAM
    assert by_name[schema.DUTY_CYCLE.name]["help"] == schema.DUTY_CYCLE.help


def test_v2_interning_shrinks_payload(registry):
    snapshot = registry.snapshot()
    v1 = build_write_request(snapshot, "kube-tpu-stats", "node-1")
    v2 = build_write_request_v2(snapshot, "kube-tpu-stats", "node-1")
    # v2 carries MORE information (help strings, types) yet must be
    # smaller uncompressed: label strings are sent once, not per series.
    assert len(v2) < len(v1)


def test_v2_push_end_to_end(registry):
    with FakeReceiver() as receiver:
        writer = RemoteWriter(registry, receiver.url, job="kts",
                              instance="n0", min_interval=0.0,
                              protocol="2.0")
        writer.push_once()
        assert writer.consecutive_failures == 0
        (request,) = receiver.requests_v2
        duty = [s for labels, s, _ in request
                if labels["__name__"] == schema.DUTY_CYCLE.name
                and labels["chip"] == "0"]
        assert len(duty) == 1
        headers = receiver.headers[0]
        assert headers["Content-Encoding"] == "snappy"
        assert headers["Content-Type"] == \
            "application/x-protobuf;proto=io.prometheus.write.v2.Request"
        assert headers["X-Prometheus-Remote-Write-Version"] == "2.0.0"


def test_v2_downgrades_to_v1_on_415(registry):
    with FakeReceiver() as receiver:
        writer = RemoteWriter(registry, receiver.url, min_interval=0.0,
                              protocol="2.0")
        receiver.fail_codes.append(415)
        writer.push_once()
        assert writer.protocol == "1.0"  # spec: downgrade, don't drop
        assert writer.dropped_total == 0
        writer.push_once()
        assert receiver.requests and not receiver.requests_v2[1:]
        assert receiver.headers[-1]["X-Prometheus-Remote-Write-Version"] == \
            "0.1.0"


def test_415_on_v1_is_a_plain_4xx_drop(registry):
    with FakeReceiver() as receiver:
        writer = RemoteWriter(registry, receiver.url, min_interval=0.0)
        receiver.fail_codes.append(415)
        writer.push_once()
        assert writer.protocol == "1.0"
        assert writer.dropped_total == 1


def test_protocol_flag_plumbs_to_writer():
    import pytest

    from kube_gpu_stats_tpu.config import from_args

    cfg = from_args(["--backend", "mock",
                     "--remote-write-protocol", "2.0"])
    assert cfg.remote_write_protocol == "2.0"
    with pytest.raises(ValueError):
        RemoteWriter(Registry(), "http://x/", protocol="3.0")


def test_bad_env_protocol_is_a_usage_error(monkeypatch, capsys):
    import pytest

    from kube_gpu_stats_tpu.config import from_args

    monkeypatch.setenv("KTS_REMOTE_WRITE_PROTOCOL", "2")
    with pytest.raises(SystemExit) as exc:
        from_args(["--backend", "mock"])
    assert exc.value.code == 2  # argparse usage error, not a traceback
    assert "remote-write-protocol" in capsys.readouterr().err


def test_doctor_probe_negotiates_configured_protocol():
    from kube_gpu_stats_tpu.remote_write import build_headers

    v2 = build_headers("", "2.0")
    assert v2["X-Prometheus-Remote-Write-Version"] == "2.0.0"
    assert "io.prometheus.write.v2" in v2["Content-Type"]
    v1 = build_headers("", "1.0")
    assert v1["X-Prometheus-Remote-Write-Version"] == "0.1.0"


def test_prompb2_decoder_fuzz_raises_only_valueerror():
    """Garbage and mutated-valid inputs must yield ValueError or a clean
    result — never IndexError/KeyError/hangs (the decoder backs the test
    receiver, and a symbol ref can point past the symbol table)."""
    import random

    rng = random.Random(20260729)
    table = prompb2.SymbolTable()
    valid = prompb2.encode_request(table, [
        prompb2.encode_series(table, "up", [("chip", "0")], 1.0, 1000,
                              prompb2.TYPE_GAUGE, "help text"),
    ])
    for trial in range(3000):
        if trial % 3 == 0:
            raw = bytes(rng.randrange(256)
                        for _ in range(rng.randrange(0, 80)))
        else:
            mutated = bytearray(valid)
            for _ in range(rng.randrange(1, 4)):
                mutated[rng.randrange(len(mutated))] = rng.randrange(256)
            raw = bytes(mutated[:rng.randrange(1, len(mutated) + 1)])
        try:
            prompb2.decode_request(raw)
        except ValueError:
            pass
        except IndexError as exc:  # noqa: PERF203
            raise AssertionError(f"IndexError on {raw.hex()}") from exc


def test_prompb2_out_of_range_symbol_ref_is_valueerror():
    from kube_gpu_stats_tpu.proto import codec

    body = codec.field_bytes(
        1, codec.encode_varint(5) + codec.encode_varint(6))
    raw = codec.field_string(4, "") + codec.field_bytes(5, body)
    with pytest.raises(ValueError, match="symbol ref"):
        prompb2.decode_request(raw)


def test_redirect_is_a_failure_not_a_silent_get(registry):
    """urllib's default redirect handler converts a redirected POST into
    a body-less GET — an auth proxy answering 302 would count total data
    loss as pushes_total. The no-redirect opener must surface 3xx as a
    retryable failure and never issue the GET."""
    import http.server

    events = []

    class Redirector(http.server.ThreadingHTTPServer):
        pass

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            events.append(self.command)
            self.send_response(302)
            self.send_header("Location", "/login")
            self.end_headers()

        do_PUT = do_POST  # pushgateway pushes PUT; same 302 trap

        def do_GET(self):
            events.append("GET")
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"login page")

        def log_message(self, *args):
            pass

    srv = Redirector(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        writer = RemoteWriter(
            registry, f"http://127.0.0.1:{srv.server_address[1]}/push",
            min_interval=0.0)
        writer.push_once()
        assert writer.pushes_total == 0
        assert writer.consecutive_failures == 1  # retryable, visible
        assert writer.dropped_total == 0
        assert events == ["POST"]  # no silent GET to /login

        from kube_gpu_stats_tpu.exposition import PushgatewayPusher

        pusher = PushgatewayPusher(
            registry, f"http://127.0.0.1:{srv.server_address[1]}",
            min_interval=0.0)
        pusher.push_once()
        assert pusher.pushes_total == 0
        assert pusher.consecutive_failures == 1
        assert events == ["POST", "PUT"]  # both redirected, neither GET
    finally:
        srv.shutdown()
        srv.server_close()


def test_extra_labels_stamped_on_every_series(registry):
    snapshot = registry.snapshot()
    decoded = prompb.decode_write_request(
        build_write_request(snapshot, "kts", "node-1",
                            (("cluster", "prod"), ("region", "us"))))
    assert decoded
    for labels, _ in decoded:
        assert labels["cluster"] == "prod"
        assert labels["region"] == "us"
        assert list(labels) == sorted(labels)  # spec still holds

    from kube_gpu_stats_tpu.remote_write import build_write_request_v2
    from kube_gpu_stats_tpu.proto import prompb2

    decoded_v2 = prompb2.decode_request(
        build_write_request_v2(snapshot, "kts", "node-1",
                               (("cluster", "prod"),)))
    for labels, _, _ in decoded_v2:
        assert labels["cluster"] == "prod"


# --- durable sharded mode (ISSUE 13): WAL-backed, backpressure-aware --------

def _durable(registry, receiver, tmp_path, **kw):
    kw.setdefault("min_interval", 0.0)
    kw.setdefault("wal_dir", str(tmp_path / "rw-wal"))
    return RemoteWriter(registry, receiver.url, job="kts", instance="n0",
                        **kw)


def _unblock(writer):
    """Collapse the shards' probe backoff (tests don't sleep)."""
    for shard in writer._shards:
        shard.retry_at = 0.0


def test_durable_single_shard_end_to_end(registry, tmp_path):
    with FakeReceiver() as receiver:
        writer = _durable(registry, receiver, tmp_path)
        writer.push_once()
        assert writer.pushes_total == 1
        assert writer.backlog_records() == 0
        (request,) = receiver.requests
        names = {labels["__name__"] for labels, _ in request}
        assert schema.DUTY_CYCLE.name in names
        # Same series set as the legacy whole-snapshot request.
        legacy = prompb.decode_write_request(
            build_write_request(registry.snapshot(), "kts", "n0"))
        assert sorted(str(l) for l, _ in request) == \
            sorted(str(l) for l, _ in legacy)
        writer.stop()


def test_durable_outage_is_late_delivery_not_loss(registry, tmp_path):
    """The tentpole contract: a receiver outage leaves requests in the
    WAL; recovery drains them oldest-first — zero loss, in order."""
    with FakeReceiver() as receiver:
        writer = _durable(registry, receiver, tmp_path)
        receiver.fail_codes.append(503)
        writer.push_once()
        assert writer.pushes_total == 0
        assert writer.failures_total == 1
        assert writer.backlog_records() == 1  # journaled, not dropped
        # Durable mode keeps publish cadence; the SHARD backs off.
        assert writer.consecutive_failures == 0
        assert writer._shards[0].retry_at > 0
        # Receiver recovers; a new snapshot publishes meanwhile.
        loop = PollLoop(MockCollector(num_devices=2), registry,
                        deadline=5.0)
        loop.tick()
        loop.stop()
        _unblock(writer)
        writer.push_once()
        assert writer.backlog_records() == 0
        assert writer.pushes_total == 2  # backlog + the new one, both
        ts = [request[0][1][0][1] for request in receiver.requests]
        assert ts == sorted(ts)  # oldest-first
        writer.stop()


def test_durable_poison_4xx_parks_and_drain_continues(registry, tmp_path):
    with FakeReceiver() as receiver:
        writer = _durable(registry, receiver, tmp_path)
        receiver.fail_codes.append(400)
        writer.push_once()
        shard = writer._shards[0]
        assert shard.parked_total == 1
        assert writer.dropped_total == 1
        assert writer.backlog_records() == 0  # the queue moved on
        assert shard.parked_ring.records_pending() == 1  # kept for triage
        # A poison response is NOT a backoff: the receiver is healthy
        # and the next snapshot sails through.
        loop = PollLoop(MockCollector(num_devices=1), registry,
                        deadline=5.0)
        loop.tick()
        loop.stop()
        writer.push_once()
        assert writer.pushes_total == 1
        writer.stop()


def test_durable_honors_retry_after(registry, tmp_path):
    with FakeReceiver() as receiver:
        writer = _durable(registry, receiver, tmp_path)
        receiver.fail_codes.append(429)
        receiver.fail_headers.append({"Retry-After": "7"})
        import time as time_mod

        before = time_mod.monotonic()
        writer.push_once()
        shard = writer._shards[0]
        assert shard.retry_at - before > 5.0  # the hint, not the base
        assert writer.backlog_records() == 1
        # Within the window the shard does not probe at all.
        requests_before = len(receiver.headers)
        writer.push_once()
        assert len(receiver.headers) == requests_before
        writer.stop()


def test_durable_wal_bounded_evicts_oldest_counted(registry, tmp_path):
    with FakeReceiver() as receiver:
        writer = _durable(registry, receiver, tmp_path,
                          wal_max_bytes=1 << 16)
        receiver.fail_codes.extend([503] * 100)
        loop = PollLoop(MockCollector(num_devices=2), registry,
                        deadline=5.0)
        for i in range(40):
            loop.tick()
            _unblock(writer)
            writer.push_once()
        loop.stop()
        shard = writer._shards[0]
        assert shard.dropped_total > 0  # the bound engaged, counted
        assert shard.ring.bytes_pending() <= (1 << 16) + (1 << 20)
        status = writer.egress_status()
        assert status["shards"][0]["dropped_total"] == shard.dropped_total
        writer.stop()


def test_durable_wal_survives_restart(registry, tmp_path):
    with FakeReceiver() as receiver:
        writer = _durable(registry, receiver, tmp_path)
        receiver.fail_codes.append(503)
        writer.push_once()
        assert writer.backlog_records() == 1
        writer.stop()  # closes rings, saves cursors
        writer2 = _durable(registry, receiver, tmp_path)
        assert writer2.backlog_records() == 1  # recovered from disk
        _unblock(writer2)
        writer2.push_once()
        assert writer2.backlog_records() == 0
        assert receiver.requests  # the pre-crash request landed
        writer2.stop()


def test_durable_sharding_partitions_series_stably(registry, tmp_path):
    from kube_gpu_stats_tpu.remote_write import shard_of

    with FakeReceiver() as receiver:
        writer = _durable(registry, receiver, tmp_path, shards=4)
        writer.push_once()
        assert 1 <= len(receiver.requests) <= 4
        # Union over shard requests == the legacy whole-snapshot set.
        got = sorted(str(labels) for request in receiver.requests
                     for labels, _ in request)
        legacy = prompb.decode_write_request(
            build_write_request(registry.snapshot(), "kts", "n0"))
        assert got == sorted(str(labels) for labels, _ in legacy)
        writer.stop()
    # Routing is stable and PYTHONHASHSEED-independent.
    labels = [("chip", "0"), ("job", "kts")]
    assert shard_of("accelerator_duty_cycle", labels, 4) == \
        shard_of("accelerator_duty_cycle", list(labels), 4)
    assert shard_of("x", [], 1) == 0


def test_durable_415_downgrades_and_parks_that_request(registry, tmp_path):
    with FakeReceiver() as receiver:
        writer = _durable(registry, receiver, tmp_path, protocol="2.0")
        receiver.fail_codes.append(415)
        writer.push_once()
        assert writer.protocol == "1.0"
        shard = writer._shards[0]
        assert shard.parked_total == 1  # 2.0 bytes can't be re-encoded
        assert writer.backlog_records() == 0
        # The next snapshot ships as 1.0 and lands.
        loop = PollLoop(MockCollector(num_devices=1), registry,
                        deadline=5.0)
        loop.tick()
        loop.stop()
        writer.push_once()
        assert receiver.requests and not receiver.requests_v2
        writer.stop()


def test_durable_lag_metering_and_egress_fold(registry, tmp_path):
    from kube_gpu_stats_tpu.registry import (SnapshotBuilder,
                                             contribute_egress_stats)

    with FakeReceiver() as receiver:
        writer = _durable(registry, receiver, tmp_path)
        writer.push_once()
        status = writer.egress_status()
        assert status["durable"] is True
        (shard,) = status["shards"]
        assert shard["lag_seconds"] >= 0.0
        assert shard["sent_total"] == 1
        builder = SnapshotBuilder()
        contribute_egress_stats(builder, {"remote_write": status})
        text = builder.build().render()
        assert "kts_remote_write_shards 1" in text
        assert 'kts_remote_write_wal_bytes{shard="0"} 0' in text
        assert 'kts_remote_write_lag_seconds{shard="0"}' in text
        assert 'kts_remote_write_parked_total{shard="0"} 0' in text
        assert 'kts_remote_write_dropped_total{shard="0"} 0' in text
        writer.stop()


def test_legacy_mode_has_no_egress_surface(registry):
    with FakeReceiver() as receiver:
        writer = RemoteWriter(registry, receiver.url, min_interval=0.0)
        assert writer.egress_status() is None
        assert not writer.durable
        assert writer.backlog_records() == 0
        writer.stop()


def test_durable_flags_wire_through_daemon(tmp_path):
    from kube_gpu_stats_tpu.config import Config, from_args
    from kube_gpu_stats_tpu.daemon import Daemon

    import pytest as pytest_mod

    cfg = from_args(["--backend", "mock",
                     "--remote-write-url", "http://127.0.0.1:9/push",
                     "--remote-write-wal-dir", str(tmp_path / "wal"),
                     "--remote-write-shards", "2"])
    assert cfg.remote_write_shards == 2
    with pytest_mod.raises(SystemExit):
        from_args(["--backend", "mock", "--remote-write-shards", "2"])
    d = Daemon(Config(backend="mock", attribution="off", listen_port=0,
                      remote_write_url="http://127.0.0.1:9/push",
                      remote_write_wal_dir=str(tmp_path / "wal2")))
    try:
        assert d.remote_writer.durable
        d.poll.tick()
        text = d.registry.snapshot().render()
        assert "kts_remote_write_shards 1" in text
    finally:
        d.poll.stop()
        d.collector.close()
