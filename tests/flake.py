"""Bounded retry for known box-noise flakes (ISSUE 12 satellite).

A handful of end-to-end tests (soak, multihost) drive real sockets,
real thread fleets, and wall-clock pacing; on a loaded CI box they fail
~1/10 runs on scheduling noise, not code. Those failures drown real
regressions from the chaos/robustness suites in rerun noise, so the
known-noisy tests get EXACTLY ONE retry — marked loudly in the test
log, so a test that starts failing twice in a row (a real regression)
still fails the suite, and a rising retry rate is itself visible
evidence.

Deliberately not a plugin dependency (the image is frozen) and
deliberately narrow: apply it only to tests whose flake is understood
and box-noise-shaped. A retry on a deterministic test is a bug
sponge — don't."""

from __future__ import annotations

import functools
import logging

log = logging.getLogger(__name__)


def retry_once_on_box_noise(test):
    """Re-run the test once if its first run raises AssertionError or
    OSError (the box-noise shapes: timing assertions and transient
    socket failures). Anything else — and a second failure — propagates
    unchanged."""

    @functools.wraps(test)
    def wrapper(*args, **kwargs):
        try:
            return test(*args, **kwargs)
        except (AssertionError, OSError) as exc:
            log.warning(
                "box-noise retry: %s failed once (%s: %s); retrying "
                "exactly once", test.__name__, type(exc).__name__, exc)
            if "tmp_path" in kwargs:
                # The retry gets a FRESH directory: the first attempt
                # already built fixture trees (make_sysfs mkdirs
                # without exist_ok), and re-running into the same
                # tmp_path would fail deterministically with
                # FileExistsError — masking the flake being retried.
                retry_dir = kwargs["tmp_path"] / "box-noise-retry"
                retry_dir.mkdir()
                kwargs = {**kwargs, "tmp_path": retry_dir}
            return test(*args, **kwargs)

    return wrapper
