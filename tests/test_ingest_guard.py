"""Ingest survival layer (ISSUE 12): admission control (token bucket,
in-flight budget, memory fence, shed priority), hostile-pusher
quarantine, and the warm-restart checkpoint/replay — including the
churn/restart races the satellites call out."""

from __future__ import annotations

import json
import threading
import time

from kube_gpu_stats_tpu import delta
from kube_gpu_stats_tpu.bench import build_pusher_body
from kube_gpu_stats_tpu.hub import Hub
from kube_gpu_stats_tpu.resilience import TokenBucket
from kube_gpu_stats_tpu.validate import parse_exposition_interned


def make_hub(**kwargs):
    return Hub([], targets_provider=lambda: [], interval=10.0,
               push_fence=1e9, **kwargs)


def churn_slots_of(body: str) -> list[int]:
    probe = parse_exposition_interned(body)
    by_name = {name: slot for slot, (name, _l, _v) in enumerate(probe)}
    return sorted((by_name["accelerator_duty_cycle"],
                   by_name["accelerator_power_watts"]))


def seed(hub, n: int, prefix: str = "node"):
    sources = [f"http://{prefix}-{i:03d}:9400/metrics" for i in range(n)]
    bodies = [build_pusher_body(i) for i in range(n)]
    for i, source in enumerate(sources):
        code, _resp, _hdrs = hub.delta.handle(
            delta.encode_full(source, i + 1, 1, bodies[i]))
        assert code == 200, code
    return sources, bodies


# --- TokenBucket (resilience.py) ---------------------------------------------

def test_token_bucket_rate_and_retry_after():
    clock = [0.0]
    bucket = TokenBucket(rate=10.0, burst=2.0, clock=lambda: clock[0])
    assert bucket.try_take()
    assert bucket.try_take()
    assert not bucket.try_take()  # burst drained, no time passed
    # Retry-After names the refill horizon: one token at 10/s = 0.1s.
    assert 0.0 < bucket.retry_after() <= 0.1
    clock[0] += 0.1
    assert bucket.try_take()
    # Refill never exceeds the burst ceiling.
    clock[0] += 100.0
    assert bucket.try_take()
    assert bucket.try_take()
    assert not bucket.try_take()


# --- admission: rate, in-flight, memory fence --------------------------------

def test_delta_rate_sheds_deltas_never_fulls():
    hub = make_hub(ingest_lanes=1, ingest_delta_rate=1e-6)
    try:
        sources, bodies = seed(hub, 2)
        slots = churn_slots_of(bodies[0])
        # The bucket starts at burst 2e-6: effectively zero tokens, so
        # the very first DELTA sheds with 429 + Retry-After...
        code, _resp, hdrs = hub.delta.handle(delta.encode_delta(
            sources[0], 1, 2, [(slots[0], 51.0)]))
        assert code == 429, code
        assert "Retry-After" in hdrs
        assert hub.delta.shed_total.get("delta_rate") == 1
        # ...while a recovery FULL for an established session sails
        # through (shed priority), and the shed session is still alive.
        code, _resp, _hdrs = hub.delta.handle(
            delta.encode_full(sources[0], 1_000_001, 1, bodies[0]))
        assert code == 200, code
        assert len(hub.delta.sources()) == 2
    finally:
        hub.stop()


def test_inflight_budget_reserves_headroom_for_fulls():
    # max_inflight=1 -> reserve=1 -> the DELTA admission limit is 0
    # while FULLs may still use the whole budget: the degenerate
    # configuration that makes the priority observable synchronously.
    hub = make_hub(ingest_max_inflight=1)
    try:
        sources, bodies = seed(hub, 1)
        slots = churn_slots_of(bodies[0])
        code, _resp, hdrs = hub.delta.handle(delta.encode_delta(
            sources[0], 1, 2, [(slots[0], 51.0)]))
        assert code == 429, code
        assert "Retry-After" in hdrs
        assert hub.delta.shed_total.get("inflight") == 1
        code, _resp, _hdrs = hub.delta.handle(
            delta.encode_full(sources[0], 1, 2, bodies[0]))
        assert code == 200, code
    finally:
        hub.stop()


def test_memory_fence_refuses_only_new_sessions():
    hub = make_hub(ingest_max_sessions=2)
    try:
        sources, bodies = seed(hub, 2)
        slots = churn_slots_of(bodies[0])
        # A third, NEW source is refused 503 + Retry-After at the fence
        # — before any session state is allocated for it.
        code, _resp, hdrs = hub.delta.handle(
            delta.encode_full("http://new:9400/metrics", 9, 1, bodies[0]))
        assert code == 503, code
        assert "Retry-After" in hdrs
        assert hub.delta.shed_total.get("memory") == 1
        assert len(hub.delta.sources()) == 2
        # Established sessions are never turned away: deltas land, and
        # a restart (new generation FULL) re-anchors fine at capacity.
        code, _resp, _hdrs = hub.delta.handle(delta.encode_delta(
            sources[1], 2, 2, [(slots[0], 51.0)]))
        assert code == 200, code
        code, _resp, _hdrs = hub.delta.handle(
            delta.encode_full(sources[0], 1_000_001, 1, bodies[0]))
        assert code == 200, code
    finally:
        hub.stop()


# --- quarantine --------------------------------------------------------------

def test_undecodable_flood_quarantines_peer_before_decode():
    hub = make_hub(ingest_quarantine_threshold=3,
                   ingest_quarantine_window=60.0)
    try:
        for _ in range(3):
            code, _resp, _hdrs = hub.delta.handle(b"garbage", peer="9.9.9.9")
            assert code == 400, code
        code, _resp, hdrs = hub.delta.handle(b"garbage", peer="9.9.9.9")
        assert code == 429, code
        assert "Retry-After" in hdrs
        assert hub.delta.quarantined == 1
        assert hub.delta.shed_total.get("quarantined") == 1
        # Even a VALID frame from the quarantined peer is refused at
        # the door — that's the point: no decode work for that address
        # until the window passes.
        code, _resp, _hdrs = hub.delta.handle(
            delta.encode_full("http://ok:9400/metrics", 1, 1,
                              build_pusher_body(0)), peer="9.9.9.9")
        assert code == 429, code
        # A different peer is untouched.
        code, _resp, _hdrs = hub.delta.handle(
            delta.encode_full("http://ok:9400/metrics", 1, 1,
                              build_pusher_body(0)), peer="8.8.8.8")
        assert code == 200, code
    finally:
        hub.stop()


def test_healthy_traffic_on_shared_ip_resets_the_peer_streak():
    """NAT safety: pushers behind one address must not be collateral —
    a clean frame between a bad actor's garbage bursts resets the
    consecutive-malformed streak, so the shared peer never trips."""
    hub = make_hub(ingest_quarantine_threshold=3)
    try:
        good = delta.encode_full("http://ok:9400/metrics", 1, 1,
                                 build_pusher_body(0))
        for round_no in range(4):
            for _ in range(2):  # threshold - 1 garbage frames
                code, _resp, _hdrs = hub.delta.handle(b"junk", peer="n.a.t")
                assert code == 400, code
            code, _resp, _hdrs = hub.delta.handle(
                delta.encode_full("http://ok:9400/metrics",
                                  round_no + 2, 1, build_pusher_body(0)),
                peer="n.a.t")
            assert code == 200, code
        assert hub.delta.quarantined == 0
        assert good  # the wire stayed valid throughout
    finally:
        hub.stop()


def test_bad_body_quarantines_source_not_peer():
    """A frame that DECODES carries a reliable source identity: the
    breaker keys on it, never on the shared client address (the
    chaos-sim regression: one bad source must not 429 every healthy
    pusher on the same IP)."""
    hub = make_hub(ingest_quarantine_threshold=3,
                   ingest_quarantine_window=0.2)
    try:
        sources, bodies = seed(hub, 1)
        slots = churn_slots_of(bodies[0])
        for i in range(3):
            code, _resp, _hdrs = hub.delta.handle(
                delta.encode_full("http://evil:9400/metrics", i + 2, 1,
                                  "{ not an exposition\n"),
                peer="127.0.0.1")
            assert code == 400, code
        code, _resp, hdrs = hub.delta.handle(
            delta.encode_full("http://evil:9400/metrics", 50, 1,
                              "{ still not\n"), peer="127.0.0.1")
        assert code == 429, code
        # The healthy session on the SAME peer address keeps landing.
        code, _resp, _hdrs = hub.delta.handle(delta.encode_delta(
            sources[0], 1, 2, [(slots[0], 51.0)]), peer="127.0.0.1")
        assert code == 200, code
        # After the window one probe is admitted; a clean FULL from the
        # once-evil source closes the quarantine.
        time.sleep(0.25)
        code, _resp, _hdrs = hub.delta.handle(
            delta.encode_full("http://evil:9400/metrics", 60, 1,
                              bodies[0]), peer="127.0.0.1")
        assert code == 200, code
        assert hub.delta.quarantined == 0
    finally:
        hub.stop()


# --- warm restart ------------------------------------------------------------

def test_checkpoint_between_full_and_first_delta_replays_consistent_seq(
        tmp_path):
    """ISSUE 12 satellite: a checkpoint written between a session's
    FULL and its first DELTA must replay to the post-FULL seq — the
    publisher's next DELTA (seq 2) lands, and the values patch onto
    the replayed entry exactly as they would have on the original."""
    path = str(tmp_path / "ckpt")
    hub = make_hub(ingest_checkpoint=path)
    sources, bodies = seed(hub, 3)
    slots = churn_slots_of(bodies[0])
    assert hub.delta.checkpoint(force=True)
    hub.stop()

    hub2 = make_hub(ingest_checkpoint=path)
    try:
        assert hub2.delta.checkpoint_loaded
        assert hub2.delta.replaying
        # /readyz holds NotReady on the replay gate (published but
        # still replaying), while /healthz liveness is untouched.
        from kube_gpu_stats_tpu.registry import SnapshotBuilder

        hub2.registry.publish(SnapshotBuilder().build())
        ok, reason = hub2.ready()
        assert not ok and "warm restart" in reason
        # The publisher's first post-restart DELTA replays the session
        # on demand and applies — no 409, no FULL.
        code, _resp, _hdrs = hub2.delta.handle(delta.encode_delta(
            sources[0], 1, 2, [(slots[0], 77.0), (slots[1], 307.0)]))
        assert code == 200, code
        assert hub2.delta.resyncs_total == 0
        # Background sweep restores the quiet sessions too.
        hub2.delta.start_replay()
        deadline = time.monotonic() + 5.0
        while hub2.delta.replaying and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not hub2.delta.replaying
        assert hub2.delta.warm_restart_sessions == 3
        hub2.refresh_once()
        assert hub2._push_served == 3
        # The on-demand delta's values are live in the merged view.
        text = hub2.registry.snapshot().render()
        assert "accelerator_duty_cycle" in text
        assert " 77" in text
    finally:
        hub2.stop()


def test_full_after_restart_supersedes_checkpoint(tmp_path):
    """A publisher that restarted during the hub's own downtime sends a
    FULL with a new generation: the checkpoint record must be
    discarded, not replayed over the fresher state."""
    path = str(tmp_path / "ckpt")
    hub = make_hub(ingest_checkpoint=path)
    sources, bodies = seed(hub, 1)
    assert hub.delta.checkpoint(force=True)
    hub.stop()

    hub2 = make_hub(ingest_checkpoint=path)
    try:
        code, _resp, _hdrs = hub2.delta.handle(
            delta.encode_full(sources[0], 999, 1, bodies[0]))
        assert code == 200, code
        assert not hub2.delta.replaying  # the pending record is gone
        # The session runs on the NEW generation, not the checkpointed.
        code, _resp, _hdrs = hub2.delta.handle(delta.encode_delta(
            sources[0], 999, 2, [(churn_slots_of(bodies[0])[0], 51.0)]))
        assert code == 200, code
    finally:
        hub2.stop()


def test_checkpoint_survives_weird_label_values(tmp_path):
    """The checkpoint serializes entries back to exposition text; label
    escaping must round-trip (backslash, quote, newline) or a replay
    would corrupt — or refuse — the session it claims to restore."""
    from kube_gpu_stats_tpu import schema
    from kube_gpu_stats_tpu.registry import SnapshotBuilder

    builder = SnapshotBuilder()
    builder.add(schema.DEVICE_UP, 1.0,
                (("accel_type", 'we"ird\\val\nue'), ("chip", "0"),
                 ("device_path", "/dev/accel0"), ("uuid", "")))
    body = builder.build().render()
    path = str(tmp_path / "ckpt")
    hub = make_hub(ingest_checkpoint=path)
    source = "http://weird:9400/metrics"
    code, _resp, _hdrs = hub.delta.handle(
        delta.encode_full(source, 1, 1, body))
    assert code == 200, code
    assert hub.delta.checkpoint(force=True)
    hub.stop()

    hub2 = make_hub(ingest_checkpoint=path)
    try:
        hub2.delta.start_replay()
        deadline = time.monotonic() + 5.0
        while hub2.delta.replaying and time.monotonic() < deadline:
            time.sleep(0.01)
        assert hub2.delta.warm_restart_sessions == 1
        code, _resp, _hdrs = hub2.delta.handle(delta.encode_delta(
            source, 1, 2, [(0, 0.0)]))
        assert code == 200, code
    finally:
        hub2.stop()


def test_checkpoint_mid_resync_storm_is_consistent(tmp_path):
    """Satellite race: a checkpoint capture racing a concurrent FULL
    resync storm must stay internally consistent (each record is
    captured under its lane lock) and replayable."""
    path = str(tmp_path / "ckpt")
    hub = make_hub(ingest_checkpoint=path, ingest_lanes=4)
    n = 64
    sources, bodies = seed(hub, n)
    stop = threading.Event()
    errors: list = []

    def storm() -> None:
        gen = 1_000
        while not stop.is_set():
            gen += 1
            for i in range(0, n, 7):
                code, _resp, _hdrs = hub.delta.handle(
                    delta.encode_full(sources[i], gen * n + i, 1,
                                      bodies[i]))
                if code != 200:
                    errors.append(code)

    thread = threading.Thread(target=storm)
    thread.start()
    try:
        for _ in range(10):
            assert hub.delta.checkpoint(force=True)
    finally:
        stop.set()
        thread.join(timeout=10)
    hub.stop()
    assert not errors
    state = json.loads((tmp_path / "ckpt").read_text())
    assert len(state["sessions"]) == n
    hub2 = make_hub(ingest_checkpoint=path)
    try:
        hub2.delta.start_replay()
        deadline = time.monotonic() + 5.0
        while hub2.delta.replaying and time.monotonic() < deadline:
            time.sleep(0.01)
        assert hub2.delta.warm_restart_sessions == n
        hub2.refresh_once()
        assert hub2._push_served == n
    finally:
        hub2.stop()


# --- churn races -------------------------------------------------------------

def test_eviction_and_expiry_racing_concurrent_ingest():
    """Satellite race: lane eviction (target churn) and expiry sweeps
    (sources()) racing live frame applies must neither crash nor
    strand a session — an evicted source's next delta draws a clean
    409 and its FULL re-admits it."""
    hub = make_hub(ingest_lanes=4)
    n = 32
    sources, bodies = seed(hub, n)
    slots = churn_slots_of(bodies[0])
    stop = threading.Event()
    crashes: list = []
    seqs = [1] * n

    def pusher() -> None:
        try:
            while not stop.is_set():
                for i in range(n):
                    code, _resp, _hdrs = hub.delta.handle(
                        delta.encode_delta(
                            sources[i], i + 1, seqs[i] + 1,
                            [(slots[0], 50.0 + i)]))
                    if code == 200:
                        seqs[i] += 1
                    elif code == 409:
                        # Evicted underneath us: re-anchor like a real
                        # publisher.
                        code, _resp, _hdrs = hub.delta.handle(
                            delta.encode_full(sources[i], i + 1,
                                              seqs[i] + 1, bodies[i]))
                        if code == 200:
                            seqs[i] += 1
                        else:
                            crashes.append(("full", code))
                    else:
                        crashes.append(("delta", code))
        except Exception as exc:  # noqa: BLE001 - the test's whole point
            crashes.append(exc)

    threads = [threading.Thread(target=pusher) for _ in range(2)]
    for thread in threads:
        thread.start()
    try:
        deadline = time.monotonic() + 1.5
        while time.monotonic() < deadline:
            # Churn: evict half the fleet, then everyone, then let the
            # expiry sweep (sources()) run against live applies.
            hub.delta.evict(set(sources[: n // 2]))
            hub.delta.sources()
            hub.delta.fresh_sources(1e9)
            hub.delta.evict(set())
            time.sleep(0.01)
    finally:
        stop.set()
        for thread in threads:
            thread.join(timeout=10)
    try:
        assert not crashes, crashes[:5]
        # The fleet converges back: every source re-admitted via FULL.
        for i in range(n):
            code, _resp, _hdrs = hub.delta.handle(
                delta.encode_full(sources[i], i + 1, seqs[i] + 1,
                                  bodies[i]))
            assert code == 200, code
        assert len(hub.delta.sources()) == n
    finally:
        hub.stop()


def test_checkpoint_mid_replay_preserves_pending_sessions(tmp_path):
    """Review fix: a checkpoint written while warm replay is still
    pending must carry the unreplayed records forward verbatim — a
    crash-loop (or clean stop) mid-replay must never shrink the fleet
    to the replayed-so-far fraction."""
    path = str(tmp_path / "ckpt")
    hub = make_hub(ingest_checkpoint=path)
    sources, bodies = seed(hub, 5)
    slots = churn_slots_of(bodies[0])
    assert hub.delta.checkpoint(force=True)
    hub.stop()

    hub2 = make_hub(ingest_checkpoint=path)
    # NO background replay: only one source replays (on demand), then
    # the hub checkpoints and dies — the other four are still pending.
    code, _resp, _hdrs = hub2.delta.handle(delta.encode_delta(
        sources[0], 1, 2, [(slots[0], 60.0)]))
    assert code == 200, code
    assert hub2.delta.warm_restart_pending == 4
    assert hub2.delta.checkpoint(force=True)
    state = json.loads((tmp_path / "ckpt").read_text())
    assert {record[0] for record in state["sessions"]} == set(sources)
    hub2.stop()

    hub3 = make_hub(ingest_checkpoint=path)
    try:
        hub3.delta.start_replay()
        deadline = time.monotonic() + 5.0
        while hub3.delta.replaying and time.monotonic() < deadline:
            time.sleep(0.01)
        assert hub3.delta.warm_restart_sessions == 5
        # The on-demand-replayed source resumes at its ADVANCED seq
        # (2, not the original checkpoint's 1)...
        code, _resp, _hdrs = hub3.delta.handle(delta.encode_delta(
            sources[0], 1, 3, [(slots[0], 61.0)]))
        assert code == 200, code
        # ...and a carried-forward pending source at its original seq.
        code, _resp, _hdrs = hub3.delta.handle(delta.encode_delta(
            sources[3], 4, 2, [(slots[0], 62.0)]))
        assert code == 200, code
    finally:
        hub3.stop()


def test_checkpoint_epoch_outranks_previous_lives(tmp_path):
    """Review fix: the WAL-vs-main 'newest wins' rule compares a
    PERSISTED monotone epoch, re-seeded on load — a fresh process's
    first write must out-rank a long-lived previous incarnation's
    main file, or a crash between fsync and rename would resurrect
    the stale state over the newer fsynced one."""
    path = str(tmp_path / "ckpt")
    hub = make_hub(ingest_checkpoint=path)
    sources, bodies = seed(hub, 2)
    for _ in range(5):  # a long first life: epoch climbs to 5
        assert hub.delta.checkpoint(force=True)
    hub.stop()  # forced final write: epoch 6
    first_life = json.loads((tmp_path / "ckpt").read_text())

    hub2 = make_hub(ingest_checkpoint=path)
    assert hub2.delta.checkpoint(force=True)
    hub2_state = json.loads((tmp_path / "ckpt").read_text())
    assert hub2_state["seq"] > first_life["seq"]
    hub2.stop()

    # Simulated crash between fsync and rename: the second life's
    # newest state stranded in the .wal behind the first life's main.
    (tmp_path / "ckpt.wal").write_text(json.dumps(hub2_state))
    (tmp_path / "ckpt").write_text(json.dumps(first_life))
    hub3 = make_hub(ingest_checkpoint=path)
    try:
        # The .wal wins on epoch, not on a per-process frame counter.
        assert hub3.delta.checkpoint_loaded
        assert hub3.delta._ckpt_seq == hub2_state["seq"]
    finally:
        hub3.stop()


def test_quarantine_eviction_never_drops_live_quarantines(monkeypatch):
    """Review fix: at the quarantine-table cap, room is made only from
    CLOSED breakers — a flood rotating >cap source names must not push
    a real (OPEN) offender back into full parse work, and the rotating
    names themselves never accumulate enough streak to trip."""
    monkeypatch.setattr(delta.DeltaIngest, "MAX_QUARANTINE_KEYS", 4)
    hub = make_hub(ingest_quarantine_threshold=3)
    try:
        # A real offender trips OPEN.
        for i in range(3):
            code, _resp, _hdrs = hub.delta.handle(
                delta.encode_full("http://evil:9400/metrics", i + 2, 1,
                                  "{ bad\n"))
            assert code == 400, code
        assert hub.delta.quarantined == 1
        # A rotating flood far past the cap: closed trackers churn,
        # the OPEN offender survives, the table stays bounded.
        for i in range(20):
            code, _resp, _hdrs = hub.delta.handle(
                delta.encode_full(f"http://rot-{i}:9400/metrics", 9, 1,
                                  "{ bad\n"))
            assert code == 400, code
        assert len(hub.delta._quarantine) <= 4
        assert hub.delta.quarantined == 1
        code, _resp, _hdrs = hub.delta.handle(
            delta.encode_full("http://evil:9400/metrics", 99, 1,
                              "{ bad\n"))
        assert code == 429, code  # still quarantined, not evicted
    finally:
        hub.stop()
