"""Pallas burn kernel numerics in interpreter mode on the CPU mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from kube_gpu_stats_tpu.loadgen.pallas_burn import (pallas_all_device_burn,
                                                    pallas_matmul)


def test_matches_reference_matmul():
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(256, 512), dtype=jnp.bfloat16)
    b = jnp.asarray(rng.randn(512, 384), dtype=jnp.bfloat16)
    got = pallas_matmul(a, b, tile_m=128, tile_n=128, tile_k=128,
                        interpret=True)
    want = jnp.dot(a, b, preferred_element_type=jnp.float32)
    assert got.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-2, atol=1e-2)


def test_k_accumulation_across_grid_steps():
    # K spans several grid steps; accumulation must not lose partials.
    rng = np.random.RandomState(1)
    a = jnp.asarray(rng.randn(128, 1024), dtype=jnp.bfloat16)
    b = jnp.asarray(rng.randn(1024, 128), dtype=jnp.bfloat16)
    got = pallas_matmul(a, b, tile_m=128, tile_n=128, tile_k=256,
                        interpret=True)
    want = jnp.dot(a, b, preferred_element_type=jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-2, atol=1e-2)


def test_shape_validation():
    a = jnp.zeros((128, 128), jnp.bfloat16)
    b = jnp.zeros((256, 128), jnp.bfloat16)
    with pytest.raises(ValueError):
        pallas_matmul(a, b, interpret=True)
    with pytest.raises(ValueError):
        pallas_matmul(
            jnp.zeros((100, 128), jnp.bfloat16),
            jnp.zeros((128, 128), jnp.bfloat16),
            tile_m=100, interpret=True,
        )


def test_all_device_burn_step_contract():
    step, x, w, n, flops = pallas_all_device_burn(size=256)
    out = step(x, w)
    out.block_until_ready()
    assert out.shape == x.shape == (n * 256, 256)
    assert out.dtype == jnp.bfloat16


def test_non_default_multiple_of_128_sizes():
    # 384 is a legal MXU size but not a multiple of the default tiles;
    # tiles must snap to a divisor.
    rng = np.random.RandomState(2)
    a = jnp.asarray(rng.randn(384, 384), dtype=jnp.bfloat16)
    b = jnp.asarray(rng.randn(384, 384), dtype=jnp.bfloat16)
    got = pallas_matmul(a, b, interpret=True)
    want = jnp.dot(a, b, preferred_element_type=jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-2, atol=1e-2)


def test_unknown_kernel_rejected():
    from kube_gpu_stats_tpu.loadgen.burn import run_burn

    with pytest.raises(ValueError, match="unknown kernel"):
        run_burn(seconds=0.1, size=128, kernel="Pallas")


def test_pallas_all_device_burn_drives_the_mesh():
    """The pallas kernel composed with shard_map covers every local
    device (parity with burn.make_all_device_burn): sharded input,
    donated buffer, per-device blocks, correct FLOPs accounting."""
    import jax
    import jax.numpy as jnp

    step, x, w, n, flops = pallas_all_device_burn(size=128)
    assert n == len(jax.local_devices()) == 8
    assert x.shape == (8 * 128, 128)
    assert flops == 2 * 8 * 128**3
    assert not x.sharding.is_fully_replicated
    out = step(x, w)
    assert out.shape == (8 * 128, 128)
    assert out.sharding.device_set == set(jax.local_devices())
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())


def test_run_burn_pallas_uses_all_devices():
    from kube_gpu_stats_tpu.loadgen.burn import run_burn

    result = {}
    steps = run_burn(seconds=0.2, size=128, report_every=1e9,
                     kernel="pallas", result=result)
    assert steps > 0
    assert result["devices"] == 8
