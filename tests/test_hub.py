"""`kube-tpu-stats hub` — the slice aggregation service (hub.py). Sources
are real exporter stacks (mock collector → poll loop → registry → HTTP
server) so the merge and rollups are pinned to the actual exposition, not
hand-written fixture text."""

import time
import urllib.request

import pytest

from kube_gpu_stats_tpu import hub as hub_mod
from kube_gpu_stats_tpu import schema, validate
from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.exposition import MetricsServer
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry
from kube_gpu_stats_tpu.validate import fetch_exposition, parse_exposition

DEAD_TARGET = "http://127.0.0.1:1/metrics"


@pytest.fixture
def node_stack():
    """Factory for real per-node exporter stacks serving on port 0."""
    stacks = []

    def make(worker, slice_name="v5p-16", devices=2):
        reg = Registry()
        loop = PollLoop(
            MockCollector(num_devices=devices, accel_type="tpu-v5p"),
            reg,
            deadline=5.0,
            topology_labels={"slice": slice_name, "worker": worker,
                             "topology": "2x2x4"},
        )
        loop.tick()
        loop.tick()  # second tick: ICI rates need a delta
        server = MetricsServer(reg, host="127.0.0.1", port=0)
        server.start()
        stacks.append((loop, server))
        return f"http://127.0.0.1:{server.port}/metrics"

    yield make
    for loop, server in stacks:
        loop.stop()
        server.stop()


def series_map(text):
    return {(name, tuple(sorted(labels.items()))): value
            for name, labels, value in parse_exposition(text)}


def values(text, family):
    return [value for name, labels, value in parse_exposition(text)
            if name == family]


def test_hub_merges_two_workers_and_rolls_up(node_stack):
    targets = [node_stack("0"), node_stack("1")]
    source_totals = sum(
        sum(values(fetch_exposition(t), "accelerator_memory_total_bytes"))
        for t in targets)

    hub = hub_mod.Hub(targets, expect_workers=2)
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()

    assert values(text, "slice_target_up") == [1.0, 1.0]
    assert values(text, "slice_workers_expected") == [2.0]
    assert values(text, "slice_chips") == [4.0]
    assert values(text, "slice_chips_up") == [4.0]
    assert values(text, "slice_workers") == [2.0]
    [mean] = values(text, "slice_duty_cycle_mean")
    [lo] = values(text, "slice_duty_cycle_min")
    [hi] = values(text, "slice_duty_cycle_max")
    assert 0.0 <= lo <= mean <= hi <= 100.0
    assert values(text, "slice_memory_total_bytes") == [source_totals]
    assert values(text, "slice_ici_bandwidth_bytes_per_second")[0] > 0
    # Per-chip series pass through with their worker identity intact.
    ups = [labels for name, labels, _ in parse_exposition(text)
           if name == "accelerator_up"]
    assert {lbl["worker"] for lbl in ups} == {"0", "1"}
    assert values(text, "hub_refresh_duration_seconds_count") == [1.0]
    # The merged exposition still honors the accelerator_* contract.
    assert validate.check(text) == []


def test_hub_rollup_labels_carry_slice(node_stack):
    hub = hub_mod.Hub([node_stack("0", slice_name="v5p-a"),
                       node_stack("0", slice_name="v5p-b")])
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    chips = {labels["slice"]: value
             for name, labels, value in parse_exposition(text)
             if name == "slice_chips"}
    assert chips == {"v5p-a": 2.0, "v5p-b": 2.0}


def test_hub_dead_target_degrades_not_crashes(node_stack):
    live = node_stack("0")
    hub = hub_mod.Hub([live, DEAD_TARGET])
    try:
        frame = hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    up = {labels["target"]: value
          for name, labels, value in parse_exposition(text)
          if name == "slice_target_up"}
    assert up == {live: 1.0, DEAD_TARGET: 0.0}
    assert values(text, "slice_chips") == [2.0]  # live worker still rolls up
    assert frame.errors  # the failure is reported, not swallowed


def test_hub_duplicate_chip_identity_folds(node_stack, tmp_path):
    # Two distinct targets claiming the same chip identity (topology
    # misconfig) = every per-chip series collides.
    text = fetch_exposition(node_stack("0"))
    (tmp_path / "a.prom").write_text(text)
    (tmp_path / "b.prom").write_text(text)
    hub = hub_mod.Hub([str(tmp_path / "a.prom"), str(tmp_path / "b.prom")])
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    [dups] = values(text, "slice_duplicate_series")
    assert dups > 0
    # Dedup is correctness: the merged exposition has no duplicate series.
    assert validate.check(text) == []
    # Rollups deliberately count the chimera twice — that IS the signal
    # (2 real chips, 4 claimed).
    assert values(text, "slice_chips") == [4.0]


def test_hub_same_target_listed_twice_is_deduped(node_stack):
    target = node_stack("0")
    hub = hub_mod.Hub([target, target])
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    # One slice_target_up series, one copy of each chip — a repeated URL
    # must not render an exposition Prometheus would reject.
    assert values(text, "slice_target_up") == [1.0]
    assert values(text, "slice_duplicate_series") == [0.0]
    assert validate.check(text) == []


def test_hub_dedup_is_label_order_insensitive(tmp_path):
    # A third-party exporter may render the same Prometheus series
    # identity with labels in a different order.
    (tmp_path / "a.prom").write_text(
        'accelerator_power_watts{chip="0",worker="3",slice="s"} 100\n')
    (tmp_path / "b.prom").write_text(
        'accelerator_power_watts{worker="3",slice="s",chip="0"} 100\n')
    hub = hub_mod.Hub([str(tmp_path / "a.prom"), str(tmp_path / "b.prom")])
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    assert len(values(text, "accelerator_power_watts")) == 1
    assert values(text, "slice_duplicate_series") == [1.0]


def test_hub_empty_worker_label_disambiguated_by_target(tmp_path):
    # Two dev-VM/embedded exporters with no topology labels both export
    # chip 0 — different hardware, must both survive the merge.
    line = 'accelerator_power_watts{chip="0",worker="",slice=""} {v}\n'
    a, b = tmp_path / "a.prom", tmp_path / "b.prom"
    a.write_text(line.replace("{v}", "100"))
    b.write_text(line.replace("{v}", "200"))
    hub = hub_mod.Hub([str(a), str(b)])
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    series = [(labels, value)
              for name, labels, value in parse_exposition(text)
              if name == "accelerator_power_watts"]
    assert sorted(value for _, value in series) == [100.0, 200.0]
    assert {labels["worker"] for labels, _ in series} == {str(a), str(b)}
    assert values(text, "slice_duplicate_series") == [0.0]
    assert values(text, "slice_power_watts") == [300.0]


def test_hub_step_rates_and_straggler_ratio(tmp_path):
    base = ('accelerator_workload_steps_total'
            '{chip="0",worker="{w}",slice="s"} {v}\n')

    def write(steps_a, steps_b):
        (tmp_path / "a.prom").write_text(
            base.replace("{w}", "0").replace("{v}", str(steps_a)))
        (tmp_path / "b.prom").write_text(
            base.replace("{w}", "1").replace("{v}", str(steps_b)))

    write(100, 200)
    hub = hub_mod.Hub([str(tmp_path / "a.prom"), str(tmp_path / "b.prom")])
    try:
        hub.refresh_once()
        first = hub.registry.snapshot().render()
        assert values(first, "slice_worker_steps_per_second") == []
        time.sleep(0.25)
        write(150, 300)  # worker 0 gains 50, worker 1 gains 100
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    rates = {labels["worker"]: value
             for name, labels, value in parse_exposition(text)
             if name == "slice_worker_steps_per_second"}
    assert set(rates) == {"0", "1"}
    assert rates["0"] > 0 and rates["1"] > rates["0"]
    [ratio] = values(text, "slice_straggler_ratio")
    # Deltas are 50 vs 100 over near-identical windows.
    assert 0.4 < ratio < 0.6


def test_hub_rollups_only_still_detects_duplicates(node_stack, tmp_path):
    # --rollups-only is exactly the mode where the per-chip series can't
    # reveal a chip-identity collision, so the detector must still run.
    text = fetch_exposition(node_stack("0"))
    (tmp_path / "a.prom").write_text(text)
    (tmp_path / "b.prom").write_text(text)
    hub = hub_mod.Hub([str(tmp_path / "a.prom"), str(tmp_path / "b.prom")],
                      rollups_only=True)
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    [dups] = values(text, "slice_duplicate_series")
    assert dups > 0


def test_hub_ici_rollup_zero_traffic_keeps_series(tmp_path):
    # An idle interconnect is a 0 reading; a source with no ICI series at
    # all gets no rollup. Conflating them would churn absent() alerts.
    ici = ('accelerator_ici_link_bandwidth_bytes_per_second'
           '{chip="0",worker="0",slice="s",link="0"} 0\n')
    bare = 'accelerator_power_watts{chip="0",worker="0",slice="s"} 5\n'
    (tmp_path / "ici.prom").write_text(ici)
    (tmp_path / "bare.prom").write_text(bare)

    hub = hub_mod.Hub([str(tmp_path / "ici.prom")])
    try:
        hub.refresh_once()
        with_ici = hub.registry.snapshot().render()
    finally:
        hub.stop()
    assert values(with_ici, "slice_ici_bandwidth_bytes_per_second") == [0.0]

    hub = hub_mod.Hub([str(tmp_path / "bare.prom")])
    try:
        hub.refresh_once()
        without = hub.registry.snapshot().render()
    finally:
        hub.stop()
    assert values(without, "slice_ici_bandwidth_bytes_per_second") == []


def test_hub_slow_drip_target_cannot_wedge_refresh():
    # A target that accepts the connection but never completes a response
    # within the refresh deadline must be marked down, not block forever
    # (urlopen's timeout is per socket op, so a slow drip evades it).
    import socket
    import threading

    listener = socket.socket()
    listener.bind(("127.0.0.1", 0))
    listener.listen(1)
    port = listener.getsockname()[1]
    release = threading.Event()
    conns = []

    def tarpit():
        # Drip one byte per 0.1s: every socket recv succeeds inside the
        # per-op timeout, but the response never completes — the evasion
        # a bare urlopen timeout cannot catch.
        try:
            conn, _ = listener.accept()
        except OSError:
            return
        conns.append(conn)
        while not release.is_set():
            try:
                conn.sendall(b"x")
            except OSError:
                return
            release.wait(0.1)

    thread = threading.Thread(target=tarpit, daemon=True)
    thread.start()
    hub = hub_mod.Hub([f"http://127.0.0.1:{port}/metrics"],
                      fetch_timeout=0.3)
    try:
        start = time.monotonic()
        frame = hub.refresh_once()
        assert time.monotonic() - start < 3.0
        assert frame.errors and "deadline" in frame.errors[0]
        text = hub.registry.snapshot().render()
        assert values(text, "slice_target_up") == [0.0]
        # The wedged fetch stays outstanding: the next refresh must not
        # stack another worker on the same target.
        frame2 = hub.refresh_once()
        assert frame2.errors and "still running" in frame2.errors[0]
    finally:
        release.set()
        listener.close()
        for conn in conns:
            conn.close()
        hub.stop()


def test_hub_rollups_only_drops_per_chip_series(node_stack):
    hub = hub_mod.Hub([node_stack("0")], rollups_only=True)
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    assert values(text, "slice_chips") == [2.0]
    assert not any(name.startswith("accelerator_")
                   for name, _, _ in parse_exposition(text))


def test_hub_serves_http_with_healthz_staleness(node_stack):
    hub = hub_mod.Hub([node_stack("0")])
    server = MetricsServer(hub.registry, host="127.0.0.1", port=0,
                           healthz_max_age=30.0)
    server.start()
    url = f"http://127.0.0.1:{server.port}"
    try:
        # No refresh yet -> no snapshot -> liveness fails loudly.
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(url + "/healthz", timeout=5)
        assert err.value.code == 503
        hub.refresh_once()
        with urllib.request.urlopen(url + "/healthz", timeout=5) as resp:
            assert resp.status == 200
        with urllib.request.urlopen(url + "/metrics", timeout=5) as resp:
            body = resp.read().decode()
        assert "slice_chips" in body
    finally:
        hub.stop()
        server.stop()


def test_hub_body_cache_reuses_parse_on_unchanged_body(tmp_path):
    """Zero-reparse ingest (ISSUE 2): a byte-identical response body
    reuses the previous cycle's parse + merge plan, counted in
    kts_hub_body_cache_hits_total — and the merged accelerator_*/slice_*
    output is identical either way."""
    body = ('accelerator_power_watts{chip="0",worker="0",slice="s"} 100\n'
            'accelerator_power_watts{chip="1",worker="0",slice="s"} 120\n')
    (tmp_path / "a.prom").write_text(body)
    hub = hub_mod.Hub([str(tmp_path / "a.prom")])
    try:
        hub.refresh_once()
        first = hub.registry.snapshot().render()
        entry = hub._parse_cache[str(tmp_path / "a.prom")]
        hub.refresh_once()
        second = hub.registry.snapshot().render()
        # Same entry object: nothing was re-parsed, the plan replayed.
        assert hub._parse_cache[str(tmp_path / "a.prom")] is entry
        assert values(second, "kts_hub_body_cache_hits_total") == [1.0]
        assert values(first, "kts_hub_body_cache_hits_total") == [0.0]

        def merged(text):
            return sorted(
                (name, tuple(sorted(labels.items())), value)
                for name, labels, value in parse_exposition(text)
                if name.startswith(("accelerator_", "slice_"))
                and name != "slice_target_fetch_seconds")  # timing varies

        assert merged(first) == merged(second)
        # A changed body drops the cache entry and re-parses.
        (tmp_path / "a.prom").write_text(body.replace("100", "140"))
        hub.refresh_once()
        third = hub.registry.snapshot().render()
        assert hub._parse_cache[str(tmp_path / "a.prom")] is not entry
        assert values(third, "kts_hub_body_cache_hits_total") == [1.0]
        assert 140.0 in values(third, "accelerator_power_watts")
    finally:
        hub.stop()


def test_hub_stat_sig_distrusts_open_mtime_granule(tmp_path):
    """Racily-clean rule: a file whose mtime granule is still open never
    earns a stat short-circuit (a coarse-mtime filesystem could take a
    same-size in-place rewrite the (mtime, size, inode) signature can't
    see), so a pinned-mtime rewrite is still picked up via the body-hash
    path; once the mtime is safely old, the signature is trusted."""
    import os

    path = tmp_path / "a.prom"
    target = str(path)
    path.write_text(
        'accelerator_power_watts{chip="0",worker="0",slice="s"} 100\n')
    hub = hub_mod.Hub([target])
    try:
        hub.refresh_once()
        entry = hub._parse_cache[target]
        assert entry.stat_sig is None  # mtime granule still open
        # Same-size in-place rewrite with the mtime PINNED to the old
        # value — what a whole-second-mtime filesystem shows when both
        # writes land in one granule. The hub must see the new value.
        st = path.stat()
        path.write_text(
            'accelerator_power_watts{chip="0",worker="0",slice="s"} 120\n')
        os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns))
        hub.refresh_once()
        assert 120.0 in values(hub.registry.snapshot().render(),
                               "accelerator_power_watts")
        # An old mtime closes the granule: the next refresh's body-hash
        # hit adopts a trusted signature for the stat fast path.
        old = time.time_ns() - 10 * hub_mod._STAT_SIG_SETTLE_NS
        os.utime(path, ns=(old, old))
        hub.refresh_once()
        assert hub._parse_cache[target].stat_sig is not None
    finally:
        hub.stop()


def test_hub_target_churn_evicts_all_per_target_caches(tmp_path):
    """_refresh_targets drops dead targets from _hist_cache; the
    body/parse caches must evict on the same path or a churning
    discovered target list leaks an entry (body + merge plan) per
    departed pod."""
    a, b = str(tmp_path / "a.prom"), str(tmp_path / "b.prom")
    for path in (a, b):
        (tmp_path / path.rsplit("/", 1)[1]).write_text(
            'accelerator_workload_steps_total'
            '{chip="0",worker="0",slice="s"} 5\n')
    targets = [[a, b]]
    hub = hub_mod.Hub([], targets_provider=lambda: list(targets[0]))
    try:
        hub.refresh_once()
        assert set(hub._parse_cache) == {a, b}
        assert set(hub._hist_cache) == {a, b}
        targets[0] = [a]  # pod b departs discovery
        hub.refresh_once()
        assert set(hub._parse_cache) == {a}
        assert set(hub._hist_cache) == {a}
        assert b not in hub._breakers
    finally:
        hub.stop()


def test_hub_push_modes_ship_merged_snapshot(node_stack):
    # The hub as slice-level egress: a PublishFollower sender attached to
    # the hub registry ships the merged exposition (rollups + per-chip).
    import http.server
    import threading

    from kube_gpu_stats_tpu.exposition import PushgatewayPusher

    received = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_PUT(self):
            length = int(self.headers.get("Content-Length", 0))
            received.append(self.rfile.read(length))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

    gateway = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=gateway.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{gateway.server_address[1]}"

    hub = hub_mod.Hub([node_stack("0")],
                      push_stats=lambda: {"pushgateway": {
                          "pushes": 1, "failures": 0, "dropped": 0}})
    pusher = PushgatewayPusher(hub.registry, url, job="hub-test")
    try:
        hub.refresh_once()
        pusher.push_once()
    finally:
        hub.stop()
        gateway.shutdown()
    assert pusher.pushes_total == 1
    body = received[0].decode()
    assert "slice_chips" in body and "accelerator_up" in body
    # Shipping health rides the hub's own exposition.
    text = hub.registry.snapshot().render()
    assert 'collector_push_total{mode="pushgateway"} 1' in text


def _step_hist_text(observations):
    """Exposition text with one step-duration histogram, like an embedded
    exporter renders it (through the real HistogramState/render path)."""
    from kube_gpu_stats_tpu.registry import HistogramState, SnapshotBuilder

    hist = HistogramState.empty(schema.WORKLOAD_STEP_DURATION,
                                schema.STEP_DURATION_BUCKETS)
    for value in observations:
        hist = hist.observe(value)
    builder = SnapshotBuilder()
    builder.add_histogram(hist)
    return builder.build().render()


def test_hub_merges_step_histograms_across_targets(tmp_path):
    (tmp_path / "a.prom").write_text(_step_hist_text([0.01, 0.01, 0.2]))
    (tmp_path / "b.prom").write_text(_step_hist_text([0.01, 3.0]))
    hub = hub_mod.Hub([str(tmp_path / "a.prom"), str(tmp_path / "b.prom")])
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    name = schema.WORKLOAD_STEP_DURATION.name
    assert values(text, f"{name}_count") == [5.0]
    assert values(text, f"{name}_sum") == [pytest.approx(0.01 * 3 + 0.2 + 3.0)]
    buckets = {labels["le"]: value
               for n, labels, value in parse_exposition(text)
               if n == f"{name}_bucket"}
    assert buckets["0.01"] == 3.0  # three 10 ms steps across both targets
    assert buckets["+Inf"] == 5.0
    assert validate.check(text) == []


def test_hub_mfu_rollup_mean_and_min(tmp_path):
    # Slice-level MFU: mean + min over the chips reporting the gauge
    # (embedded workloads) — the goodput analog of the duty rollups.
    line = ('accelerator_workload_model_flops_utilization'
            '{{chip="0",worker="{w}",slice="s"}} {v}\n')
    (tmp_path / "a.prom").write_text(
        line.format(w="0", v="40"))
    (tmp_path / "b.prom").write_text(
        line.format(w="1", v="20"))
    # A worker with no MFU (no embedded hook) must not poison the mean.
    (tmp_path / "c.prom").write_text(
        'accelerator_up{chip="0",worker="2",slice="s"} 1\n')
    hub = hub_mod.Hub([str(tmp_path / n) for n in
                       ("a.prom", "b.prom", "c.prom")])
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    assert values(text, "slice_workload_mfu_mean") == [30.0]
    assert values(text, "slice_workload_mfu_min") == [20.0]
    # (Fixture lines are minimal, not full-label contract expositions;
    # the new slice_* families themselves are contract-checked by the
    # validate slice branch in other hub tests.)


def test_hub_hung_file_target_cannot_wedge_refresh(tmp_path):
    """A .prom target whose read blocks forever (FIFO with no writer —
    the NFS/FUSE-stall stand-in) must cost only itself: the chunk's
    earlier members' results are salvaged, refresh_once returns within
    the deadline, the hung member is guarded (its blocked pool thread
    is never doubled), and the healthy target stays up on the NEXT
    refresh too (it re-chunks without the guarded one)."""
    import os

    good = tmp_path / "a_good.prom"
    good.write_text('accelerator_up{chip="0",worker="0",slice="s"} 1\n')
    fifo = tmp_path / "z_hung.prom"
    os.mkfifo(fifo)
    hub = hub_mod.Hub([str(good), str(fifo)], fetch_timeout=0.3)
    try:
        start = time.monotonic()
        hub.refresh_once()
        assert time.monotonic() - start < 5  # budget ~0.6s, not forever
        text = hub.registry.snapshot().render()
        ups = {labels["target"]: value
               for name, labels, value in parse_exposition(text)
               if name == "slice_target_up"}
        # good sorts before the fifo, so its outcome is salvaged from
        # the hung chunk's progress list.
        assert ups[str(good)] == 1.0
        assert ups[str(fifo)] == 0.0
        # Next refresh: the hung member is guarded ("still running"),
        # the healthy one re-chunks cleanly and stays up.
        start = time.monotonic()
        frame = hub.refresh_once()
        assert time.monotonic() - start < 5
        text = hub.registry.snapshot().render()
        ups = {labels["target"]: value
               for name, labels, value in parse_exposition(text)
               if name == "slice_target_up"}
        assert ups[str(good)] == 1.0
        assert ups[str(fifo)] == 0.0
        assert any("still running" in e for e in frame.errors)
    finally:
        hub.stop()


def test_hub_hung_stat_sweep_does_not_starve_other_sweeps(
        tmp_path, monkeypatch):
    """A stat hung on a dead mount must cost only its own sweep: the
    other sweeps' misses get their read chunks submitted the moment
    each sweep resolves — not after the hung sweep's deadline, which
    would time the reads out and mark healthy targets down (and feed
    their breakers) for sharing a refresh with the hang."""
    import os as os_mod
    import threading

    line = 'accelerator_up{{chip="0",worker="{w}",slice="s"}} 1\n'
    paths = []
    old = time.time_ns() - 10 * hub_mod._STAT_SIG_SETTLE_NS
    for worker in range(8):
        path = tmp_path / f"w{worker}.prom"
        path.write_text(line.format(w=worker))
        os_mod.utime(path, ns=(old, old))
        paths.append(path)
    hub = hub_mod.Hub([str(p) for p in paths], fetch_timeout=0.1)
    release = threading.Event()
    try:
        # First refresh caches every target with a trusted stat_sig
        # (mtimes are backdated past the settle window).
        hub.refresh_once()
        assert all(hub._parse_cache[str(p)].stat_sig is not None
                   for p in paths)
        # Rewrite one target per non-first sweep (8 targets / 4 ways =
        # sweeps of 2: w0-w1, w2-w3, ...) so those sweeps report misses
        # that need read chunks; backdate so the granule stays closed.
        chip1 = 'accelerator_up{{chip="1",worker="{w}",slice="s"}} 1\n'
        for worker in (3, 5, 7):
            paths[worker].write_text(line.format(w=worker)
                                     + chip1.format(w=worker))
            os_mod.utime(paths[worker], ns=(old, old))
        # w0's stat hangs (dead-NFS stand-in) — it leads sweep 0.
        real_stat = os_mod.stat

        def hanging_stat(path, *args, **kwargs):
            if str(path) == str(paths[0]):
                release.wait()
            return real_stat(path, *args, **kwargs)

        monkeypatch.setattr(hub_mod.os, "stat", hanging_stat)
        start = time.monotonic()
        frame = hub.refresh_once()
        assert time.monotonic() - start < 5
        monkeypatch.setattr(hub_mod.os, "stat", real_stat)
        text = hub.registry.snapshot().render()
        ups = {labels["target"]: value
               for name, labels, value in parse_exposition(text)
               if name == "slice_target_up"}
        # Hung member down (and only it is charged a stat stall); its
        # sweep-mate w1 was queued behind it and is down for this
        # refresh only. Every other sweep's targets — including the
        # rewritten ones whose reads chunked mid-wait — stay up.
        assert ups[str(paths[0])] == 0.0
        assert ups[str(paths[1])] == 0.0
        for worker in range(2, 8):
            assert ups[str(paths[worker])] == 1.0, f"w{worker} marked down"
        assert any("stat stalled" in e for e in frame.errors)
        # The rewritten bodies were actually re-read, not served stale:
        # reachable targets contribute w2,w4,w6 (1 chip) + w3,w5,w7
        # (2 chips after the rewrite).
        assert len(values(text, "accelerator_up")) == 3 * 1 + 3 * 2
    finally:
        release.set()
        hub.stop()


def test_hub_mid_sweep_hang_salvages_without_spurious_breaker_charge(
        tmp_path, monkeypatch):
    """A stat hung mid-sweep leaves complete outcomes in the progress
    list: salvaged HITS record as up, salvaged MISSES are marked down
    WITHOUT a breaker charge (reading them would need budget the
    expired deadline can't fund — chunking post-deadline used to time
    the read out and charge 'file read stalled' to a healthy target),
    and the next refresh re-reads the miss cleanly while only the hung
    member stays guarded."""
    import os as os_mod
    import threading

    line = 'accelerator_up{{chip="0",worker="{w}",slice="s"}} 1\n'
    chip1 = 'accelerator_up{{chip="1",worker="{w}",slice="s"}} 1\n'
    paths = []
    old = time.time_ns() - 10 * hub_mod._STAT_SIG_SETTLE_NS
    for worker in range(8):
        path = tmp_path / f"w{worker}.prom"
        path.write_text(line.format(w=worker))
        os_mod.utime(path, ns=(old, old))
        paths.append(path)
    hub = hub_mod.Hub([str(p) for p in paths], fetch_timeout=0.1)
    release = threading.Event()
    try:
        hub.refresh_once()
        # Sweep 0 is (w0, w1): w0 is rewritten (a statted miss sitting
        # in progress when the hang strikes at w1, sweep 0's SECOND
        # member — so the salvage sees one complete miss outcome).
        paths[0].write_text(line.format(w=0) + chip1.format(w=0))
        os_mod.utime(paths[0], ns=(old, old))
        real_stat = os_mod.stat

        def hanging_stat(path, *args, **kwargs):
            if str(path) == str(paths[1]):
                release.wait()
            return real_stat(path, *args, **kwargs)

        monkeypatch.setattr(hub_mod.os, "stat", hanging_stat)
        frame = hub.refresh_once()
        text = hub.registry.snapshot().render()
        ups = {labels["target"]: value
               for name, labels, value in parse_exposition(text)
               if name == "slice_target_up"}
        # w0's miss was salvaged down without a read attempt; w1 (the
        # hung member) is the only one charged. Everyone else is up.
        assert ups[str(paths[0])] == 0.0
        assert ups[str(paths[1])] == 0.0
        for worker in range(2, 8):
            assert ups[str(paths[worker])] == 1.0, f"w{worker} marked down"
        assert any("read skipped" in e and str(paths[0]) in e
                   for e in frame.errors)
        assert not any("file read stalled" in e for e in frame.errors)
        # No breaker charge for the salvaged miss: w1 still hangs (its
        # guarded fetch is outstanding), yet w0 re-reads cleanly and
        # serves its NEW body on the very next refresh — an open or
        # half-charged breaker would have kept it down.
        frame = hub.refresh_once()
        text = hub.registry.snapshot().render()
        ups = {labels["target"]: value
               for name, labels, value in parse_exposition(text)
               if name == "slice_target_up"}
        assert ups[str(paths[0])] == 1.0
        assert ups[str(paths[1])] == 0.0
        assert any("still running" in e for e in frame.errors)
        workers = {labels["worker"]
                   for name, labels, value in parse_exposition(text)
                   if name == "accelerator_up" and labels["chip"] == "1"}
        assert workers == {"0"}  # the rewritten body's new chip landed
    finally:
        release.set()
        hub.stop()


def test_hub_rollup_dip_policy_reflects_answered_targets(tmp_path):
    """The documented dip policy: summed gauges drop by a missing
    worker's share for exactly the refreshes it misses (truthful
    current view, slice_target_up names the cause), then recover; the
    cumulative step HISTOGRAM holds its cached contribution instead
    (a dipping counter would read as a reset). See _add_rollups."""
    line = ('accelerator_up{{chip="0",worker="{w}",slice="s"}} 1\n'
            'accelerator_power_watts{{chip="0",worker="{w}",slice="s"}} 100\n'
            'accelerator_memory_used_bytes'
            '{{chip="0",worker="{w}",slice="s"}} 1e9\n')
    paths = []
    for worker in range(3):
        path = tmp_path / f"w{worker}.prom"
        path.write_text(line.format(w=worker))
        paths.append(path)
    hub = hub_mod.Hub([str(p) for p in paths])
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
        assert values(text, "slice_power_watts") == [300.0]
        assert values(text, "slice_memory_used_bytes") == [3e9]
        assert values(text, "slice_chips") == [3.0]
        assert values(text, "slice_workers") == [3.0]
        # Worker 1 misses one refresh: sums dip by its share, the
        # flag names it, nothing is fabricated.
        paths[1].rename(tmp_path / "w1.gone")
        hub.refresh_once()
        text = hub.registry.snapshot().render()
        assert values(text, "slice_power_watts") == [200.0]
        assert values(text, "slice_memory_used_bytes") == [2e9]
        assert values(text, "slice_chips") == [2.0]
        assert values(text, "slice_workers") == [2.0]
        ups = {labels["target"]: value
               for name, labels, value in parse_exposition(text)
               if name == "slice_target_up"}
        assert ups[str(paths[1])] == 0.0
        assert sum(ups.values()) == 2.0
        # Recovery restores the full sums next refresh.
        (tmp_path / "w1.gone").rename(paths[1])
        hub.refresh_once()
        text = hub.registry.snapshot().render()
        assert values(text, "slice_power_watts") == [300.0]
        assert values(text, "slice_chips") == [3.0]
    finally:
        hub.stop()


def test_hub_histogram_empty_worker_disambiguated_by_target(tmp_path):
    # Same rule as _merge_chip_series: two embedded/dev targets whose
    # step histograms carry identical labels with a present-but-empty
    # worker are different hardware — their distributions must split
    # into worker=<target> series like their gauges do, not silently
    # sum into one worker="" series.
    from kube_gpu_stats_tpu.registry import HistogramState, SnapshotBuilder

    def hist_text(observations):
        hist = HistogramState.empty(
            schema.WORKLOAD_STEP_DURATION, schema.STEP_DURATION_BUCKETS,
            labels=(("chip", "0"), ("worker", ""), ("slice", "")))
        for value in observations:
            hist = hist.observe(value)
        builder = SnapshotBuilder()
        builder.add_histogram(hist)
        return builder.build().render()

    a, b = tmp_path / "a.prom", tmp_path / "b.prom"
    a.write_text(hist_text([0.01, 0.01]))
    b.write_text(hist_text([3.0]))
    hub = hub_mod.Hub([str(a), str(b)])
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    name = schema.WORKLOAD_STEP_DURATION.name
    counts = {labels["worker"]: value
              for n, labels, value in parse_exposition(text)
              if n == f"{name}_count"}
    assert counts == {str(a): 2.0, str(b): 1.0}
    # (No validate.check here: worker-labeled step histograms are
    # out-of-contract input the hub accepts leniently; in-contract
    # label-free histograms keep summing into the slice distribution —
    # pinned by test_hub_merges_step_histograms_across_targets.)


def test_hub_histogram_survives_target_outage_monotone(tmp_path):
    # A transient fetch failure must not dip the merged cumulative
    # counters (Prometheus would read a counter reset and rate() a
    # phantom spike on recovery): the failed target's last contribution
    # is carried until it answers again.
    name = schema.WORKLOAD_STEP_DURATION.name
    a, b = tmp_path / "a.prom", tmp_path / "b.prom"
    a.write_text(_step_hist_text([0.01, 0.01]))
    b.write_text(_step_hist_text([0.01, 0.2, 3.0]))
    hub = hub_mod.Hub([str(a), str(b)])
    try:
        hub.refresh_once()
        assert values(hub.registry.snapshot().render(),
                      f"{name}_count") == [5.0]
        b.rename(tmp_path / "b.gone")  # target b misses this refresh
        hub.refresh_once()
        text = hub.registry.snapshot().render()
        assert values(text, "slice_target_up") == [1.0, 0.0]
        assert values(text, f"{name}_count") == [5.0]  # no dip
        (tmp_path / "b.gone").rename(b)
        b.write_text(_step_hist_text([0.01, 0.2, 3.0, 3.0]))
        hub.refresh_once()
        assert values(hub.registry.snapshot().render(),
                      f"{name}_count") == [6.0]
    finally:
        hub.stop()


def test_hub_skips_histogram_with_mismatched_bounds(tmp_path):
    (tmp_path / "a.prom").write_text(_step_hist_text([0.01]))
    name = schema.WORKLOAD_STEP_DURATION.name
    (tmp_path / "b.prom").write_text(
        f'{name}_bucket{{le="0.5"}} 1\n'
        f'{name}_bucket{{le="+Inf"}} 1\n'
        f'{name}_sum 0.4\n'
        f'{name}_count 1\n')
    hub = hub_mod.Hub([str(tmp_path / "a.prom"), str(tmp_path / "b.prom")])
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    # Mixed exporter versions: never merged wrong, just absent.
    assert values(text, f"{name}_count") == []
    assert values(text, "slice_target_up") == [1.0, 1.0]


def test_hub_rollups_only_drops_histograms(tmp_path):
    (tmp_path / "a.prom").write_text(_step_hist_text([0.01]))
    hub = hub_mod.Hub([str(tmp_path / "a.prom")], rollups_only=True)
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    assert not any(n.startswith("accelerator_")
                   for n, _, _ in parse_exposition(text))


def test_hub_once_pushes_to_gateway(node_stack, capsys):
    # `hub --once --pushgateway-url` from cron must actually push.
    import http.server
    import threading

    received = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_PUT(self):
            length = int(self.headers.get("Content-Length", 0))
            received.append((self.path, self.rfile.read(length)))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

    gateway = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=gateway.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{gateway.server_address[1]}"
    try:
        rc = hub_mod.main([node_stack("0"), "--once",
                           "--pushgateway-url", url])
    finally:
        gateway.shutdown()
    assert rc == 0
    capsys.readouterr()
    (path, body), = received
    # Stable grouping key: the job name, never a per-pod hostname.
    assert path.endswith("/job/kube-tpu-stats-hub/instance/"
                         "kube-tpu-stats-hub")
    assert b"slice_chips" in body


def test_hub_once_push_failure_is_visible(node_stack, capsys):
    rc = hub_mod.main([node_stack("0"), "--once",
                       "--pushgateway-url", "http://127.0.0.1:1"])
    capsys.readouterr()
    assert rc == 1


def test_hub_slice_width_64_workers(tmp_path):
    # v5p-256 shape: 64 worker targets x 4 chips — the SAME fixture the
    # bench's hub_merge_64w_p50_ms measures (bench.build_slice_fixture),
    # so the published number and this CI pin describe one workload.
    # File targets keep this deterministic; 64 concurrent HTTP stacks
    # are proven by test_multihost — here the claim is merge/rollup
    # correctness and bounded refresh cost at slice width.
    from kube_gpu_stats_tpu.bench import build_slice_fixture

    targets = build_slice_fixture(tmp_path, workers=64, chips=4)

    hub = hub_mod.Hub(targets, expect_workers=64)
    try:
        start = time.monotonic()
        hub.refresh_once()
        wall = time.monotonic() - start
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    assert wall < 5.0, f"64-worker refresh took {wall:.2f}s"
    assert values(text, "slice_chips") == [256.0]
    assert values(text, "slice_chips_up") == [256.0]
    assert values(text, "slice_workers") == [64.0]
    assert values(text, "slice_memory_total_bytes") == [256 * 95.0e9]
    assert len([1 for name, _, _ in parse_exposition(text)
                if name == "accelerator_up"]) == 256
    assert values(text, "slice_duplicate_series") == [0.0]
    assert validate.check(text) == []


def test_hub_scrapes_auth_protected_targets(node_stack):
    import hashlib

    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, deadline=5.0)
    loop.tick()
    server = MetricsServer(
        reg, host="127.0.0.1", port=0, auth_username="scraper",
        auth_password_sha256=hashlib.sha256(b"hubpass").hexdigest())
    server.start()
    url = f"http://127.0.0.1:{server.port}/metrics"
    try:
        import base64

        token = base64.b64encode(b"scraper:hubpass").decode()
        hub = hub_mod.Hub(
            [url], headers_provider=lambda: {"Authorization":
                                             "Basic " + token})
        try:
            hub.refresh_once()
            text = hub.registry.snapshot().render()
        finally:
            hub.stop()
        assert values(text, "slice_target_up") == [1.0]
        assert values(text, "slice_chips") == [1.0]

        bare = hub_mod.Hub([url])
        try:
            frame = bare.refresh_once()
            text = bare.registry.snapshot().render()
        finally:
            bare.stop()
        assert values(text, "slice_target_up") == [0.0]
        assert "401" in frame.errors[0]
    finally:
        loop.stop()
        server.stop()


def test_hub_scrapes_tls_targets_with_private_ca(tmp_path):
    import subprocess

    cert, key = tmp_path / "cert.pem", tmp_path / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=127.0.0.1",
         "-addext", "subjectAltName=IP:127.0.0.1"],
        check=True, capture_output=True)
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, deadline=5.0)
    loop.tick()
    server = MetricsServer(reg, host="127.0.0.1", port=0,
                           tls_cert_file=str(cert), tls_key_file=str(key))
    server.start()
    url = f"https://127.0.0.1:{server.port}/metrics"
    try:
        hub = hub_mod.Hub([url], target_ca_file=str(cert))
        try:
            hub.refresh_once()
            text = hub.registry.snapshot().render()
        finally:
            hub.stop()
        assert values(text, "slice_target_up") == [1.0]

        # Without the CA the self-signed cert is rejected — visible, not
        # silently trusted.
        bare = hub_mod.Hub([url])
        try:
            bare.refresh_once()
            text = bare.registry.snapshot().render()
        finally:
            bare.stop()
        assert values(text, "slice_target_up") == [0.0]

        trusting = hub_mod.Hub([url], target_insecure_tls=True)
        try:
            trusting.refresh_once()
            text = trusting.registry.snapshot().render()
        finally:
            trusting.stop()
        assert values(text, "slice_target_up") == [1.0]
    finally:
        loop.stop()
        server.stop()


def test_hub_of_hubs_chains(node_stack):
    # Multi-slice rollouts can point a top-level hub at per-slice hubs:
    # merged per-chip series pass through; rollups recompute at each
    # level from the chips actually observed.
    inner = hub_mod.Hub([node_stack("0"), node_stack("1")])
    inner_server = MetricsServer(inner.registry, host="127.0.0.1", port=0)
    inner_server.start()
    try:
        inner.refresh_once()
        outer = hub_mod.Hub(
            [f"http://127.0.0.1:{inner_server.port}/metrics"])
        try:
            outer.refresh_once()
            text = outer.registry.snapshot().render()
        finally:
            outer.stop()
        assert values(text, "slice_chips") == [4.0]
        assert values(text, "slice_workers") == [2.0]
        assert len([1 for n, _, _ in parse_exposition(text)
                    if n == "accelerator_up"]) == 4
        assert validate.check(text) == []
    finally:
        inner.stop()
        inner_server.stop()


def test_hub_cli_auth_flags_validated(capsys):
    with pytest.raises(SystemExit):
        hub_mod.main(["http://x/metrics", "--once",
                      "--target-auth-username", "u"])
    capsys.readouterr()


def test_hub_once_cli(node_stack, capsys):
    assert hub_mod.main([node_stack("0"), "--once"]) == 0
    out = capsys.readouterr().out
    assert values(out, "slice_chips") == [2.0]


def test_hub_once_cli_all_targets_down(capsys):
    assert hub_mod.main([DEAD_TARGET, "--once"]) == 2
    out = capsys.readouterr().out
    assert values(out, "slice_target_up") == [0.0]


def test_hub_targets_file(node_stack, tmp_path, capsys):
    listing = tmp_path / "targets.txt"
    listing.write_text(f"# slice workers\n{node_stack('0')}\n")
    assert hub_mod.main(["--targets-file", str(listing), "--once"]) == 0
    assert "slice_chips" in capsys.readouterr().out


def test_hub_soak_flapping_targets(node_stack, tmp_path):
    """Short soak: many refreshes while one target flaps. Counters must
    stay monotone across the whole run (validate's two-scrape check), no
    thread growth, rollups always present."""
    import threading

    from kube_gpu_stats_tpu.validate import check

    stable = node_stack("0")
    flappy = tmp_path / "flappy.prom"
    flappy.write_text(_step_hist_text([0.01, 0.02]))

    hub = hub_mod.Hub([stable, str(flappy)], fetch_timeout=1.0)
    try:
        before_threads = threading.active_count()
        previous_text = None
        observations = [0.01, 0.02]
        for i in range(25):
            if i % 3 == 2:
                # Flap: the file target vanishes for one refresh.
                if flappy.exists():
                    flappy.rename(tmp_path / "gone")
            else:
                if not flappy.exists():
                    (tmp_path / "gone").rename(flappy)
                    observations.append(0.05)  # its counters advanced
                    flappy.write_text(_step_hist_text(observations))
            hub.refresh_once()
            text = hub.registry.snapshot().render()
            problems = check(text, previous=previous_text)
            assert problems == [], f"refresh {i}: {problems}"
            assert values(text, "slice_chips") == [2.0]  # stable's 2 chips
            previous_text = text
        # No per-refresh thread leak (the fetch pool is fixed-size).
        assert threading.active_count() <= before_threads + 1
    finally:
        hub.stop()


def test_hub_cli_tls_flags_mutually_exclusive(capsys):
    with pytest.raises(SystemExit):
        hub_mod.main(["http://x/metrics", "--once",
                      "--target-ca-file", "ca.pem",
                      "--target-insecure-tls"])
    capsys.readouterr()


def test_hub_exports_own_process_metrics(node_stack):
    hub = hub_mod.Hub([node_stack("0")])
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    assert values(text, "process_cpu_seconds_total")
    assert values(text, "process_resident_memory_bytes")


def test_hub_exports_per_target_fetch_seconds(node_stack):
    live = node_stack("0")
    hub = hub_mod.Hub([live, DEAD_TARGET])
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    fetches = {labels["target"]: value
               for name, labels, value in parse_exposition(text)
               if name == "slice_target_fetch_seconds"}
    # Only successful fetches report a duration; the dead target's
    # absence (paired with slice_target_up 0) is the signal.
    assert set(fetches) == {live}
    assert 0.0 <= fetches[live] < 5.0


def test_resolve_dns_targets_localhost():
    urls = hub_mod.resolve_dns_targets("localhost:19490")
    assert "http://127.0.0.1:19490/metrics" in urls \
        or "http://[::1]:19490/metrics" in urls
    assert urls == sorted(urls)
    with pytest.raises(ValueError, match="host:port"):
        hub_mod.resolve_dns_targets("no-port-here")
    https = hub_mod.resolve_dns_targets("localhost:443", scheme="https")
    assert all(u.startswith("https://") for u in https)


def test_hub_dynamic_targets_follow_provider(node_stack, tmp_path):
    a, b = node_stack("0"), node_stack("1")
    current = [a]
    hub = hub_mod.Hub([], targets_provider=lambda: list(current))
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
        assert values(text, "slice_target_up") == [1.0]
        assert values(text, "slice_workers") == [1.0]

        current.append(b)  # pod appears
        hub.refresh_once()
        text = hub.registry.snapshot().render()
        assert values(text, "slice_target_up") == [1.0, 1.0]
        assert values(text, "slice_workers") == [2.0]

        # Histogram cache prunes departed targets.
        hist = tmp_path / "h.prom"
        hist.write_text(_step_hist_text([0.01]))
        current.append(str(hist))
        hub.refresh_once()
        assert str(hist) in hub._hist_cache
        current.remove(str(hist))  # pod gone
        hub.refresh_once()
        assert str(hist) not in hub._hist_cache

        def boom():
            raise OSError("dns down")

        hub._targets_provider = boom  # discovery blip
        frame = hub.refresh_once()
        text = hub.registry.snapshot().render()
        # Previous list kept; refresh proceeded.
        assert values(text, "slice_target_up") == [1.0, 1.0]
        assert not frame.errors
    finally:
        hub.stop()


def test_hub_cli_dns_flag_validation(capsys):
    with pytest.raises(SystemExit):
        hub_mod.main(["http://x/metrics", "--targets-dns", "svc:9400",
                      "--once"])
    with pytest.raises(SystemExit):
        hub_mod.main(["--targets-dns", "not-a-host-port", "--once"])
    capsys.readouterr()


def test_parse_dns_endpoint_rejects_urls():
    # A pasted URL parses into host 'http://svc' and would fail DNS on
    # every refresh with only log evidence; it must fail at startup.
    for endpoint in ("http://svc:9400", "https://svc.ns:9400"):
        with pytest.raises(ValueError, match="bare host:port"):
            hub_mod.parse_dns_endpoint(endpoint)
    # A path suffix lands in the port half and fails the digit check.
    with pytest.raises(ValueError):
        hub_mod.parse_dns_endpoint("svc:9400/metrics")


def test_parse_dns_endpoint_ipv6_brackets():
    assert hub_mod.parse_dns_endpoint("[fd00::5]:9400") == ("fd00::5", "9400")
    assert hub_mod.parse_dns_endpoint("svc.ns.svc:9400") == (
        "svc.ns.svc", "9400")
    with pytest.raises(ValueError):
        hub_mod.parse_dns_endpoint("svc-only")


def test_refresh_targets_keeps_running_stuck_future():
    # A wedged fetch for a target that flaps out of DNS must stay
    # guarded, or every flap pins another pool worker.
    import concurrent.futures

    hub = hub_mod.Hub(["a"], targets_provider=lambda: ["b"])
    try:
        running = concurrent.futures.Future()  # PENDING: not done
        finished = concurrent.futures.Future()
        finished.set_result(None)
        hub._outstanding = {"a": running, "gone": finished}
        hub._refresh_targets()
        assert "a" in hub._outstanding  # still guarded
        assert "gone" not in hub._outstanding  # finished + departed: pruned
    finally:
        hub.stop()


def test_hub_refresh_deadline_scales_with_pool_waves(tmp_path):
    # More targets than pool workers run in waves; the deadline must
    # budget for queueing or healthy targets of a wide slice get marked
    # down every refresh. 40 file targets through a small pool must all
    # succeed.
    targets = []
    for i in range(40):
        path = tmp_path / f"w{i}.prom"
        path.write_text(
            f'accelerator_up{{chip="0",worker="{i}",slice="s"}} 1\n')
        targets.append(str(path))
    hub = hub_mod.Hub(targets, fetch_timeout=5.0)
    hub._pool_size = 4  # simulate heavy oversubscription
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    assert values(text, "slice_target_up") == [1.0] * 40
    assert values(text, "slice_workers") == [40.0]


def test_hub_unresolved_discovery_publishes_minimal_snapshot(capsys):
    def no_targets():
        raise OSError("dns down")

    hub = hub_mod.Hub([], targets_provider=no_targets, expect_workers=4)
    try:
        frame = hub.refresh_once()
        assert frame.errors and "discovery" in frame.errors[0]
        # A minimal snapshot IS published (slice_targets 0, config
        # gauges, refresh histogram): the shipped liveness probe hits
        # /healthz, and publishing nothing would restart-loop the pod
        # over a DNS outage a restart cannot fix. Zero targets stays
        # alertable as slice_targets == 0.
        text = hub.registry.snapshot().render()
        assert hub.registry.snapshot().timestamp > 0.0
        assert values(text, "slice_targets") == [0.0]
        assert values(text, "slice_workers_expected") == [4.0]
        # No slice data is fabricated.
        assert values(text, "slice_workers") == []
        assert not any(n.startswith("accelerator_")
                       for n, _, _ in parse_exposition(text))
        # Readiness still gates: a hub that has never seen a target must
        # not go Ready (a rollout with broken discovery would otherwise
        # replace a working hub with a blind one).
        ok, reason = hub.ready()
        assert not ok and "no targets" in reason
    finally:
        hub.stop()


def test_hub_minimal_snapshot_keeps_push_health_series():
    # Push senders keep shipping while the hub is decommissioned, so
    # their collector_push_* health counters must keep rendering in the
    # zero-targets snapshot (same publish tail as the normal path).
    def no_targets():
        return []

    hub = hub_mod.Hub([], targets_provider=no_targets,
                      push_stats=lambda: {"remote_write": {
                          "pushes": 3, "failures": 1, "dropped": 0}})
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
        assert values(text, "slice_targets") == [0.0]
        pushes = {labels.get("mode"): value
                  for name, labels, value in parse_exposition(text)
                  if name == "collector_push_failures_total"}
        assert pushes == {"remote_write": 1.0}
        # process_* self-health renders too.
        assert any(n.startswith("process_")
                   for n, _, _ in parse_exposition(text))
    finally:
        hub.stop()


def test_hub_ready_transitions_with_target_list(tmp_path):
    prom = tmp_path / "a.prom"
    prom.write_text('accelerator_up{chip="0",worker="0",slice="s"} 1\n')
    listing = tmp_path / "targets.txt"
    listing.write_text(f"{prom}\n")
    hub = hub_mod.Hub([], targets_provider=hub_mod.file_targets_provider(
        str(listing)))
    try:
        assert hub.ready() == (False, "no snapshot published yet")
        hub.refresh_once()
        assert hub.ready() == (True, "ready")
        listing.write_text("# decommissioned\n")
        hub.refresh_once()
        ok, reason = hub.ready()
        assert not ok and "decommissioned" in reason
    finally:
        hub.stop()


def test_hub_single_target_empty_worker_rewrite_is_stable(tmp_path):
    # Identity must not depend on the instantaneous target count (DNS
    # churn): even a single unlabeled target gets worker=<target>.
    prom = tmp_path / "dev.prom"
    prom.write_text('accelerator_up{chip="0",worker="",slice=""} 1\n')
    hub = hub_mod.Hub([str(prom)])
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    (labels,) = [labels for name, labels, _ in parse_exposition(text)
                 if name == "accelerator_up"]
    assert labels["worker"] == str(prom)


def test_hub_targets_file_reread_follows_edits(node_stack, tmp_path):
    # file_sd semantics (what `hub --targets-file` wires): edits to the
    # file apply at the next refresh, no restart.
    a, b = node_stack("0"), node_stack("1")
    listing = tmp_path / "targets.txt"
    listing.write_text(f"{a}\n")

    # The provider main() actually wires — the shipped closure is what
    # this test pins.
    provider = hub_mod.file_targets_provider(str(listing))

    hub = hub_mod.Hub([], targets_provider=provider)
    try:
        hub.refresh_once()
        assert values(hub.registry.snapshot().render(),
                      "slice_workers") == [1.0]
        listing.write_text(f"{a}\n# comment\n{b}\n")
        hub.refresh_once()
        text = hub.registry.snapshot().render()
        assert values(text, "slice_workers") == [2.0]
        listing.unlink()  # unreadable: previous list kept
        hub.refresh_once()
        assert values(hub.registry.snapshot().render(),
                      "slice_workers") == [2.0]
        # Deliberately EMPTY is a decommission, not a failure: the hub
        # stops scraping and publishes a minimal snapshot (slice_targets
        # 0, no slice data) so the liveness probe keeps passing while
        # the state stays alertable.
        listing.write_text("# decommissioned\n")
        generation = hub.registry.generation
        frame = hub.refresh_once()
        assert frame.errors and "no targets" in frame.errors[0]
        assert hub.registry.generation > generation  # minimal publish
        text = hub.registry.snapshot().render()
        assert values(text, "slice_targets") == [0.0]
        assert values(text, "slice_workers") == []
    finally:
        hub.stop()


def test_hub_cli_file_and_dns_mutually_exclusive(tmp_path, capsys):
    listing = tmp_path / "t.txt"
    listing.write_text("http://x/metrics\n")
    with pytest.raises(SystemExit):
        hub_mod.main(["--targets-file", str(listing),
                      "--targets-dns", "svc:9400", "--once"])
    capsys.readouterr()


def test_measure_hub_merge_returns_bounded_median():
    from kube_gpu_stats_tpu.bench import measure_hub_merge

    # Small shape keeps this fast; the bench runs the full 64x4.
    result = measure_hub_merge(workers=4, chips=2, refreshes=2)
    assert result is not None
    assert 0.0 < result["p50_ms"] < 5000.0
    assert 0.0 < result["cold_ms"] < 5000.0
    # Static fixture bodies: refresh 2 hits the body cache on all 4
    # targets -> 4 hits over 8 fetches.
    assert result["body_cache_hit_rate"] == 0.5
    assert result["parse_mb_per_s"] is None or result["parse_mb_per_s"] > 0
    # 4 back-to-back renders of one generation: 1 miss + 3 hits.
    assert result["render_cache_hits"] == 3


def test_hub_target_breaker_opens_then_recovers(tmp_path):
    """A target failing several refreshes running trips its circuit
    breaker: the hub stops burning fetch attempts on it (skipped with a
    'circuit open' reason, still slice_target_up 0, breaker state in
    the exposition) until the recovery probe re-admits one fetch."""
    good = tmp_path / "good.prom"
    good.write_text('accelerator_up{chip="0",worker="w0",slice="s"} 1\n')
    gone = tmp_path / "gone.prom"  # never exists at first
    hub = hub_mod.Hub([str(good), str(gone)], fetch_timeout=1.0)
    hub._breaker_recovery = 0.05  # fast probe for the test
    try:
        for _ in range(3):  # threshold: 3 consecutive failures
            hub.refresh_once()
        assert hub._breakers[str(gone)].state == "open"
        frame = hub.refresh_once()  # skipped, not fetched
        assert any("circuit open" in err for err in frame.errors)
        text = hub.registry.snapshot().render()
        assert values(text, "slice_target_up") == [0.0, 1.0] or \
            values(text, "slice_target_up") == [1.0, 0.0]
        assert any(
            n == "kts_breaker_state" and v == 2.0
            for n, _, v in parse_exposition(text))
        # Target comes back: the recovery probe readmits one fetch and
        # the breaker closes.
        gone.write_text(
            'accelerator_up{chip="0",worker="w1",slice="s"} 1\n')
        import time as _time

        deadline = _time.monotonic() + 5
        while _time.monotonic() < deadline and \
                hub._breakers[str(gone)].state != "closed":
            _time.sleep(0.06)
            hub.refresh_once()
        assert hub._breakers[str(gone)].state == "closed"
        text = hub.registry.snapshot().render()
        assert values(text, "slice_target_up") == [1.0, 1.0]
        assert values(text, "slice_workers") == [2.0]
    finally:
        hub.stop()


def test_hub_rolls_up_slice_energy_joules(tmp_path):
    """Per-slice joules (ISSUE 8): sum of the per-chip energy counters
    over answered chips; absent when no chip exports energy."""
    line = ('accelerator_energy_joules_total'
            '{chip="0",worker="{w}",slice="s"} {v}\n')
    (tmp_path / "a.prom").write_text(
        line.replace("{w}", "0").replace("{v}", "1200.5"))
    (tmp_path / "b.prom").write_text(
        line.replace("{w}", "1").replace("{v}", "800.0"))
    (tmp_path / "c.prom").write_text(
        'accelerator_power_watts{chip="0",worker="2",slice="s2"} 100\n')
    hub = hub_mod.Hub([str(tmp_path / "a.prom"), str(tmp_path / "b.prom"),
                       str(tmp_path / "c.prom")])
    try:
        hub.refresh_once()
        text = hub.registry.snapshot().render()
    finally:
        hub.stop()
    assert values(text, "slice_energy_joules") == [2000.5]
    rows = [labels for name, labels, _ in parse_exposition(text)
            if name == "slice_energy_joules"]
    assert rows == [{"slice": "s"}]
