"""Wire-format codec unit tests."""

import struct

import pytest

from kube_gpu_stats_tpu.proto import codec


def test_varint_roundtrip():
    for v in (0, 1, 127, 128, 300, 2**32, 2**63 - 1):
        data = codec.encode_varint(v)
        decoded, pos = codec.decode_varint(data, 0)
        assert (decoded, pos) == (v, len(data))


def test_negative_varint_int64():
    data = codec.encode_varint(-5)
    assert len(data) == 10  # two's-complement 64-bit always 10 bytes
    decoded, _ = codec.decode_varint(data, 0)
    assert codec.signed(decoded) == -5


def test_truncated_varint():
    with pytest.raises(ValueError):
        codec.decode_varint(b"\x80", 0)


def test_field_roundtrip_all_types():
    msg = (
        codec.field_varint(1, 42)
        + codec.field_double(2, 3.5)
        + codec.field_string(3, "héllo")
        + codec.field_bytes(4, b"\x00\x01")
    )
    fields = list(codec.iter_fields(msg))
    assert fields[0] == (1, codec.VARINT, 42)
    assert fields[1] == (2, codec.FIXED64, 3.5)
    assert fields[2][2].decode("utf-8") == "héllo"
    assert fields[3][2] == b"\x00\x01"


def test_unknown_fields_are_iterated_not_fatal():
    msg = codec.field_varint(99, 7) + codec.field_string(1, "x")
    fields = {f: v for f, _, v in codec.iter_fields(msg)}
    assert fields[1] == b"x"
    assert fields[99] == 7


def test_truncated_length_delimited():
    bad = codec.tag(1, codec.LENGTH) + codec.encode_varint(100) + b"short"
    with pytest.raises(ValueError):
        list(codec.iter_fields(bad))


def test_truncated_fixed64():
    bad = codec.tag(1, codec.FIXED64) + struct.pack("<I", 1)
    with pytest.raises(ValueError):
        list(codec.iter_fields(bad))


def test_unsupported_wire_type():
    with pytest.raises(ValueError):
        list(codec.iter_fields(codec.encode_varint((1 << 3) | 3)))  # start-group


def test_fuzz_decoders_raise_only_valueerror():
    """Arbitrary bytes from a mismatched runtime must surface as ValueError
    (the catchable contract), never AttributeError/TypeError/IndexError."""
    import random

    from kube_gpu_stats_tpu.proto import podresources, tpumetrics

    rng = random.Random(1234)
    decoders = (
        tpumetrics.decode_response,
        tpumetrics.decode_request,
        tpumetrics.decode_metric,
        podresources.decode_list_response,
        podresources.decode_allocatable_response,
        podresources.decode_pod,
        podresources.decode_container_devices,
    )
    for _ in range(500):
        blob = bytes(rng.randrange(256) for _ in range(rng.randrange(64)))
        for decode in decoders:
            try:
                decode(blob)
            except ValueError:
                pass  # the only allowed failure type
