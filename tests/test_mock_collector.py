import pytest

from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.collectors import CollectorError
from kube_gpu_stats_tpu.collectors.mock import MockCollector, NullCollector


def test_discover_shape():
    c = MockCollector(num_devices=8)
    devs = c.discover()
    assert len(devs) == 8
    assert devs[3].device_path == "/dev/accel3"
    assert devs[3].device_id == "3"
    assert devs[3].uuid == "mock-0003"


def test_sample_schema_valid_and_deterministic():
    a = MockCollector(num_devices=2)
    b = MockCollector(num_devices=2)
    dev = a.discover()[1]
    sa, sb = a.sample(dev), b.sample(dev)
    assert sa.values == sb.values
    assert sa.ici_counters == sb.ici_counters
    allowed = {m.name for m in schema.PER_DEVICE_METRICS} | set(
        schema.PERCENTILE_VALUE_KEYS
    )
    assert set(sa.values) <= allowed
    assert 0.0 <= sa.values[schema.DUTY_CYCLE.name] <= 100.0
    assert sa.values[schema.MEMORY_USED.name] <= sa.values[schema.MEMORY_TOTAL.name]


def test_counters_monotonic_across_ticks():
    c = MockCollector(num_devices=1)
    dev = c.discover()[0]
    s1, s2 = c.sample(dev), c.sample(dev)
    for link in s1.ici_counters:
        assert s2.ici_counters[link] > s1.ici_counters[link]
    assert s2.collective_ops > s1.collective_ops


def test_fault_injection():
    c = MockCollector(num_devices=2, fail_devices=[1])
    devs = c.discover()
    c.sample(devs[0])
    with pytest.raises(CollectorError):
        c.sample(devs[1])


def test_null_collector_empty():
    assert NullCollector().discover() == []
