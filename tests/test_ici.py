"""ICI counter delta/rate math (SURVEY.md §4 unit tier, §7 hard part d)."""

from kube_gpu_stats_tpu.ici import RateTracker


def test_first_sample_has_no_rate():
    rt = RateTracker()
    assert rt.rate("0", "x0", 1000, now=1.0) is None


def test_steady_rate():
    rt = RateTracker()
    rt.rate("0", "x0", 1000, now=1.0)
    assert rt.rate("0", "x0", 3000, now=2.0) == 2000.0
    assert rt.rate("0", "x0", 3000, now=3.0) == 0.0


def test_reset_drops_interval_then_recovers():
    rt = RateTracker()
    rt.rate("0", "x0", 10_000, now=1.0)
    # Counter went backwards: libtpu restarted. No rate this interval.
    assert rt.rate("0", "x0", 500, now=2.0) is None
    # Baseline re-established from the post-reset value.
    assert rt.rate("0", "x0", 1500, now=3.0) == 1000.0


def test_zero_dt_guard():
    rt = RateTracker()
    rt.rate("0", "x0", 100, now=5.0)
    assert rt.rate("0", "x0", 200, now=5.0) is None


def test_links_and_devices_independent():
    rt = RateTracker()
    rt.rate("0", "x0", 100, now=1.0)
    rt.rate("0", "x1", 100, now=1.0)
    rt.rate("1", "x0", 100, now=1.0)
    assert rt.rate("0", "x0", 200, now=2.0) == 100.0
    assert rt.rate("0", "x1", 400, now=2.0) == 300.0
    assert rt.rate("1", "x0", 150, now=2.0) == 50.0


def test_forget_device():
    rt = RateTracker()
    rt.rate("0", "x0", 100, now=1.0)
    rt.forget_device("0")
    assert rt.rate("0", "x0", 200, now=2.0) is None


def test_link_name_churn_bounded():
    """Review finding: unique link names per tick grew the tracker
    unboundedly; past the per-device budget new links get no state."""
    from kube_gpu_stats_tpu.ici import RateTracker

    tracker = RateTracker()
    for i in range(RateTracker.MAX_LINKS_PER_DEVICE * 3):
        tracker.rate("dev0", f"churn{i}", i, float(i))
    assert len(tracker._last) == RateTracker.MAX_LINKS_PER_DEVICE
    # Known links keep producing rates.
    tracker.rate("dev0", "churn0", 100, 1000.0)
    assert tracker.rate("dev0", "churn0", 200, 1001.0) == 100.0
    tracker.forget_device("dev0")
    assert tracker._last == {} and tracker._per_device == {}
