"""ICI counter delta/rate math (SURVEY.md §4 unit tier, §7 hard part d)
and the per-link baseline engine (ISSUE 19)."""

from kube_gpu_stats_tpu.ici import LinkBaselineEngine, RateTracker


def test_first_sample_has_no_rate():
    rt = RateTracker()
    assert rt.rate("0", "x0", 1000, now=1.0) is None


def test_steady_rate():
    rt = RateTracker()
    rt.rate("0", "x0", 1000, now=1.0)
    assert rt.rate("0", "x0", 3000, now=2.0) == 2000.0
    assert rt.rate("0", "x0", 3000, now=3.0) == 0.0


def test_reset_drops_interval_then_recovers():
    rt = RateTracker()
    rt.rate("0", "x0", 10_000, now=1.0)
    # Counter went backwards: libtpu restarted. No rate this interval.
    assert rt.rate("0", "x0", 500, now=2.0) is None
    # Baseline re-established from the post-reset value.
    assert rt.rate("0", "x0", 1500, now=3.0) == 1000.0


def test_zero_dt_guard():
    rt = RateTracker()
    rt.rate("0", "x0", 100, now=5.0)
    assert rt.rate("0", "x0", 200, now=5.0) is None


def test_links_and_devices_independent():
    rt = RateTracker()
    rt.rate("0", "x0", 100, now=1.0)
    rt.rate("0", "x1", 100, now=1.0)
    rt.rate("1", "x0", 100, now=1.0)
    assert rt.rate("0", "x0", 200, now=2.0) == 100.0
    assert rt.rate("0", "x1", 400, now=2.0) == 300.0
    assert rt.rate("1", "x0", 150, now=2.0) == 50.0


def test_forget_device():
    rt = RateTracker()
    rt.rate("0", "x0", 100, now=1.0)
    rt.forget_device("0")
    assert rt.rate("0", "x0", 200, now=2.0) is None


def test_link_name_churn_bounded():
    """Review finding: unique link names per tick grew the tracker
    unboundedly; past the per-device budget new links get no state."""
    from kube_gpu_stats_tpu.ici import RateTracker

    tracker = RateTracker()
    for i in range(RateTracker.MAX_LINKS_PER_DEVICE * 3):
        tracker.rate("dev0", f"churn{i}", i, float(i))
    assert len(tracker._last) == RateTracker.MAX_LINKS_PER_DEVICE
    # Known links keep producing rates.
    tracker.rate("dev0", "churn0", 100, 1000.0)
    assert tracker.rate("dev0", "churn0", 200, 1001.0) == 100.0
    tracker.forget_device("dev0")
    assert tracker._last == {} and tracker._per_device == {}


# -- counter wrap/restart pins (ISSUE 19 satellite 1) -----------------------


def test_wraparound_never_emits_negative_or_spike_rate():
    """A 64-bit counter wrapping appears as a smaller value — exactly
    like a restart. The interval must be dropped: no negative rate, no
    absurd positive spike from treating the wrap as a huge delta."""
    rt = RateTracker()
    near_max = 2**64 - 1000
    rt.rate("0", "x0", near_max, now=1.0)
    # Wrapped past zero: the raw value is now tiny.
    assert rt.rate("0", "x0", 500, now=2.0) is None
    # The post-wrap value is the new baseline; normal rates resume.
    assert rt.rate("0", "x0", 1500, now=3.0) == 1000.0


def test_restart_mid_stream_drops_exactly_one_interval():
    rt = RateTracker()
    rt.rate("0", "x0", 1_000_000, now=1.0)
    assert rt.rate("0", "x0", 2_000_000, now=2.0) == 1_000_000.0
    # Runtime restarted: counter rebased near zero.
    assert rt.rate("0", "x0", 10_000, now=3.0) is None
    assert rt.rate("0", "x0", 20_000, now=4.0) == 10_000.0


def test_stale_device_forget_then_fresh_baseline():
    """forget_device must clear ALL of the device's links; the next
    observation of each is a first sample, never a rate against the
    pre-departure counter."""
    rt = RateTracker()
    rt.rate("0", "x0", 100, now=1.0)
    rt.rate("0", "y1", 5_000, now=1.0)
    rt.rate("1", "x0", 100, now=1.0)
    rt.forget_device("0")
    assert rt.rate("0", "x0", 200, now=2.0) is None
    assert rt.rate("0", "y1", 6_000, now=2.0) is None
    # The other device's state is untouched.
    assert rt.rate("1", "x0", 200, now=2.0) == 100.0


# -- per-link baseline engine (ISSUE 19 tentpole) ---------------------------


def _warm(engine, key, rate=3e7, samples=10, start=0.0):
    now = start
    for _ in range(samples):
        now += 1.0
        engine.observe(key, rate, now)
    return now


def test_engine_warmup_gates_flagging():
    """A cold baseline degrades nothing — even a 90% drop inside the
    warmup window stays unflagged."""
    eng = LinkBaselineEngine(warmup=6)
    eng.observe("0-1", 3e7, 1.0)
    a = eng.observe("0-1", 3e6, 2.0)  # 90% drop, but only 2 samples
    assert a is not None and not a.degraded


def test_engine_degrades_and_hysteresis_clears():
    eng = LinkBaselineEngine()
    now = _warm(eng, "0-1")
    a = eng.observe("0-1", 3e6, now + 1.0)
    assert a.degraded and a.drop > 0.8
    # Still degraded while the rate stays in the hole.
    assert eng.observe("0-1", 3e6, now + 2.0).degraded
    assert eng.degraded("0-1")
    # Recovery to the reference clears (rate >= mean - gap/2).
    a = eng.observe("0-1", 3e7, now + 3.0)
    assert not a.degraded and not eng.degraded("0-1")


def test_engine_degraded_baseline_does_not_self_clear():
    """While degraded the reference folds 16x slower and the MAD
    window freezes: a sick link sitting at 10% for many refreshes must
    not drag its own baseline down to the sick rate and self-clear."""
    eng = LinkBaselineEngine()
    now = _warm(eng, "0-1")
    last = None
    for i in range(30):
        last = eng.observe("0-1", 3e6, now + 1.0 + i)
    assert last.degraded
    assert last.mean > 1.5e7  # baseline still far above the sick rate


def test_engine_counter_reset_is_a_noop_not_a_zero():
    """RateTracker answers None for a reset interval; the engine must
    treat that as 'no observation' — baseline intact, nothing flagged,
    not a zero-rate reading (which WOULD look like total loss)."""
    eng = LinkBaselineEngine()
    now = _warm(eng, "0-1")
    snap_before = eng.snapshot()["0-1"]
    assert eng.observe("0-1", None, now + 1.0) is None
    snap_after = eng.snapshot()["0-1"]
    assert snap_after["mean"] == snap_before["mean"]
    assert snap_after["samples"] == snap_before["samples"]
    assert not snap_after["degraded"]
    # The next real rate scores against the preserved baseline.
    assert eng.observe("0-1", 3e7, now + 2.0).degraded is False


def test_engine_mad_band_absorbs_jitter():
    """Operational jitter around the reference (within the MAD band /
    drop-fraction floor) never flags; only a real collapse does."""
    eng = LinkBaselineEngine()
    rates = [3e7 * (1.0 + 0.02 * ((i % 5) - 2)) for i in range(20)]
    now = 0.0
    for rate in rates:
        now += 1.0
        a = eng.observe("0-1", rate, now)
        assert not a.degraded
    assert eng.observe("0-1", 3e7 * 0.5, now + 1.0).degraded


def test_engine_link_budget_capped():
    eng = LinkBaselineEngine()
    eng.MAX_LINKS = 8
    for i in range(20):
        eng.observe(f"link{i}", 1.0, float(i + 1))
    assert len(eng._links) == 8
    assert eng.observe("link19", 2.0, 100.0) is None


def test_engine_sweep_forgets_stale_links():
    eng = LinkBaselineEngine()
    _warm(eng, "0-1", start=0.0)
    _warm(eng, "2-3", start=500.0)
    removed = eng.sweep(now=600.0, max_age=300.0)
    assert removed == ["0-1"]
    assert "0-1" not in eng.snapshot() and "2-3" in eng.snapshot()
    # A swept link re-seeds from scratch (fresh warmup).
    a = eng.observe("0-1", 3e6, 601.0)
    assert a.samples == 1 and not a.degraded
