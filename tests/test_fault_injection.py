"""Failure-detection / elastic-recovery integration tests (SURVEY.md §5:
"survive libtpu restart / kubelet socket loss: retry with backoff, mark
device gauges stale, never crash the DaemonSet pod"; fault injection via the
fake servers)."""

import threading
import time
import urllib.request

import pytest

from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.collectors.composite import TpuCollector
from kube_gpu_stats_tpu.collectors.libtpu import LibtpuClient
from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.exposition import MetricsServer
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry
from kube_gpu_stats_tpu.testing.libtpu_server import FakeLibtpuServer
from kube_gpu_stats_tpu.testing.sysfs_fixture import make_sysfs

# Fault-injection suite: `make chaos` territory, excluded from `make ci`
# (still green — excluded for speed, not flakiness).
pytestmark = pytest.mark.chaos


def up_values(snapshot):
    return [s.value for s in snapshot.series if s.spec.name == "accelerator_up"]


def test_libtpu_restart_counters_reset_then_recover(tmp_path):
    """Kill the runtime mid-run, restart it on the SAME port with reset
    counters: chips degrade (env-only), then recover, and the ICI rate math
    never emits a negative/spiked rate from the reset."""
    make_sysfs(tmp_path, num_chips=2)
    server = FakeLibtpuServer(num_chips=2).start()
    port = server.port
    col = TpuCollector(
        sysfs_root=str(tmp_path),
        libtpu_client=LibtpuClient(ports=(port,), rpc_timeout=0.5),
        use_native=False,
    )
    reg = Registry()
    loop = PollLoop(col, reg, deadline=5.0,
                    pipeline_fetch=False)  # blocking contract: each tick joins its own fetch
    loop.tick()
    loop.tick()
    assert up_values(reg.snapshot()) == [1.0, 1.0]

    server.stop()  # runtime dies
    loop.tick()
    snap = reg.snapshot()
    # sysfs still answers: chips stay up with environment-only samples.
    assert up_values(snap) == [1.0, 1.0]
    names = {s.spec.name for s in snap.series}
    assert schema.DUTY_CYCLE.name not in names
    assert schema.POWER.name in names

    # Pre-restart: the derived restart counter exists, born at 0.
    restart_values = [
        s.value for s in reg.snapshot().series
        if s.spec.name == schema.RUNTIME_RESTARTS.name
    ]
    assert restart_values == [0.0, 0.0]

    # Runtime restarts: counters restart near zero (reset semantics). The
    # channel reconnect + reset-interval drop may take a couple of ticks;
    # the invariant is that NO tick ever emits a negative/spiked rate and
    # rates return within a few ticks.
    server2 = FakeLibtpuServer(num_chips=2, port=port).start()
    server2.uptime_base = 3.0  # fresh runtime: uptime moved backwards
    try:
        bandwidths = []
        for attempt in range(10):
            loop.tick()
            time.sleep(0.2)  # let the channel finish reconnecting
            bandwidths = [
                s.value for s in reg.snapshot().series
                if s.spec.name == schema.ICI_BANDWIDTH.name
            ]
            assert all(b >= 0 for b in bandwidths), bandwidths
            if bandwidths:
                break
        assert len(bandwidths) == 12, f"rates never recovered: {bandwidths}"
        snap = reg.snapshot()
        assert schema.DUTY_CYCLE.name in {s.spec.name for s in snap.series}
        # The uptime drop (7200 -> 3) was observed exactly once per chip:
        # accelerator_runtime_restarts_total makes the bounce alertable
        # with increase() instead of a magic uptime threshold.
        restart_values = [
            s.value for s in snap.series
            if s.spec.name == schema.RUNTIME_RESTARTS.name
        ]
        assert restart_values == [1.0, 1.0]
    finally:
        server2.stop()
        loop.stop()


def test_scrape_storm_does_not_perturb_poll_latency():
    """E3 lock-light contract: a scrape storm renders snapshots and must not
    stretch tick latency (snapshot swap is the only shared state)."""
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=8), reg, deadline=5.0)
    server = MetricsServer(reg, host="127.0.0.1", port=0)
    server.start()
    loop.tick()

    def quiet_p50(n=30):
        xs = sorted(loop.tick() for _ in range(n))
        return xs[n // 2]

    baseline = quiet_p50()

    stop = threading.Event()

    def storm():
        while not stop.is_set():
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/metrics", timeout=2
                ).read()
            except Exception:
                pass

    threads = [threading.Thread(target=storm, daemon=True) for _ in range(8)]
    for t in threads:
        t.start()
    try:
        stormy = quiet_p50()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=2)
        server.stop()
        loop.stop()
    # Generous bound: GIL contention exists, but nothing should serialize a
    # tick behind 8 scrapers. Catches accidental lock coupling.
    assert stormy < max(baseline * 5, baseline + 0.010), (baseline, stormy)


def test_hotplug_rediscovery_picks_up_new_chip():
    class GrowingCollector(MockCollector):
        def __init__(self):
            super().__init__(num_devices=2)
            self.grown = False

        def discover(self):
            if self.grown:
                bigger = MockCollector(num_devices=3)
                return bigger.discover()
            return super().discover()

        def sample(self, device):
            if device.index >= 2:
                return MockCollector(num_devices=3, start_tick=5).sample(device)
            return super().sample(device)

    col = GrowingCollector()
    reg = Registry()
    loop = PollLoop(col, reg, interval=0.01, deadline=5.0,
                    rediscovery_interval=0.05)
    loop.start()
    try:
        assert reg.wait_for_publish(0, timeout=5)
        assert len(up_values(reg.snapshot())) == 2
        col.grown = True
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if len(up_values(reg.snapshot())) == 3:
                break
            assert reg.wait_for_publish(reg.generation, timeout=5)
        assert len(up_values(reg.snapshot())) == 3
    finally:
        loop.stop()


def test_failing_rediscovery_keeps_serving():
    class FlakyDiscovery(MockCollector):
        def __init__(self):
            super().__init__(num_devices=2)
            self.discover_calls = 0

        def discover(self):
            self.discover_calls += 1
            if self.discover_calls > 1:
                raise RuntimeError("sysfs went away")
            return super().discover()

    col = FlakyDiscovery()
    reg = Registry()
    loop = PollLoop(col, reg, deadline=5.0)
    loop.tick()
    loop.rediscover()  # raises internally, must be swallowed
    loop.tick()
    snap = reg.snapshot()
    assert up_values(snap) == [1.0, 1.0]
    errors = [
        s.value for s in snap.series
        if s.spec.name == "collector_poll_errors_total"
        and dict(s.labels).get("reason") == "rediscover"
    ]
    assert errors == [1.0]
    loop.stop()


def test_tick_crash_does_not_kill_loop():
    """An unexpected (non-CollectorError) exception inside a tick must not
    kill the run_forever thread (review finding: silent permanent metrics
    loss behind a passing healthz)."""
    class ExplodingCollector(MockCollector):
        def __init__(self):
            super().__init__(num_devices=1)
            self.calls = 0

        def begin_tick(self):
            self.calls += 1
            if self.calls == 2:
                raise TypeError("unexpected proto shape")  # not CollectorError

    col = ExplodingCollector()
    reg = Registry()
    loop = PollLoop(col, reg, interval=0.01, deadline=5.0)
    loop.start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and reg.generation < 5:
            time.sleep(0.01)
        assert reg.generation >= 5  # kept publishing after the crash tick
        crash = [
            s.value for s in reg.snapshot().series
            if s.spec.name == "collector_poll_errors_total"
            and dict(s.labels).get("reason") == "tick_crash"
        ]
        assert crash == [1.0]
    finally:
        loop.stop()


def test_healthz_goes_unhealthy_when_poll_dies():
    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.registry import SnapshotBuilder

    reg = Registry()
    server = MetricsServer(reg, host="127.0.0.1", port=0, healthz_max_age=0.2)
    server.start()
    url = f"http://127.0.0.1:{server.port}/healthz"
    try:
        # No snapshot yet: stale.
        try:
            urllib.request.urlopen(url, timeout=2)
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
        reg.publish(SnapshotBuilder().build())
        assert urllib.request.urlopen(url, timeout=2).status == 200
        time.sleep(0.4)  # poll "died": no publishes for > max_age
        try:
            urllib.request.urlopen(url, timeout=2)
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
    finally:
        server.stop()


def test_slow_runtime_degrades_fresh_not_stale(tmp_path):
    """Runtime slower than the tick deadline: chips must degrade to this
    tick's sysfs-only values — the split fast path must NOT peek the
    previous tick's runtime cache and serve stale duty/HBM as fresh."""
    make_sysfs(tmp_path, num_chips=2)
    server = FakeLibtpuServer(num_chips=2).start()
    col = TpuCollector(
        sysfs_root=str(tmp_path),
        libtpu_client=LibtpuClient(ports=(server.port,), rpc_timeout=5.0),
        use_native=False,
    )
    reg = Registry()
    loop = PollLoop(col, reg, deadline=0.4,
                    pipeline_fetch=False)  # blocking contract: each tick joins its own fetch
    try:
        loop.tick()  # healthy tick primes the runtime cache
        names = {s.spec.name for s in reg.snapshot().series}
        assert schema.DUTY_CYCLE.name in names

        server.delay = 2.0  # now slower than the 0.4s deadline
        loop.tick()
        snapshot = reg.snapshot()
        names = {s.spec.name for s in snapshot.series}
        # Fresh environmental values still export; runtime families must
        # vanish rather than repeat the previous tick's cache.
        assert schema.POWER.name in names
        assert schema.DUTY_CYCLE.name not in names
        assert up_values(snapshot) == [1.0, 1.0]  # degraded, not stale
        # Retained capacity: used/total ratios must not flap on slow ticks.
        assert schema.MEMORY_TOTAL.name in names

        server.delay = 0.0
        # The wedged 2s fetch from the slow tick must drain before a new
        # one can land; wait it out, then confirm recovery.
        col.wait_ready(5.0)
        loop.tick()
        loop.tick()
        assert schema.DUTY_CYCLE.name in {
            s.spec.name for s in reg.snapshot().series
        }
    finally:
        loop.stop()
        server.stop()
        col.close()


def test_libtpu_breaker_opens_stale_labels_then_recovers(tmp_path):
    """Persistent runtime outage (not a blink): after the per-port
    breaker trips, chips flip accelerator_up to 0 and the surviving
    env-only gauges carry stale="true" — rather than fabricating
    runtime values or quietly looking merely runtime-metrics-free.
    When the runtime returns, the recovery probe re-admits the fetch
    and chips recover within two ticks. Breaker state self-metrics
    ride the snapshot throughout."""
    from kube_gpu_stats_tpu.supervisor import Supervisor

    make_sysfs(tmp_path, num_chips=2)
    server = FakeLibtpuServer(num_chips=2).start()
    port = server.port
    col = TpuCollector(
        sysfs_root=str(tmp_path),
        libtpu_client=LibtpuClient(
            ports=(port,), rpc_timeout=0.5,
            breaker_recovery_time=0.05, breaker_min_span=0.0),
        use_native=False,
    )
    sup = Supervisor()
    sup.register_breaker_provider(col.breakers)
    reg = Registry()
    loop = PollLoop(col, reg, deadline=5.0, health_stats=sup.contribute,
                    pipeline_fetch=False)  # blocking contract: each tick joins its own fetch
    try:
        loop.tick()
        assert up_values(reg.snapshot()) == [1.0, 1.0]

        server.stop()  # runtime persistently down, not a blink
        for _ in range(3):  # breaker threshold: 3 consecutive failures
            loop.tick()
        assert col.breakers()[f"libtpu:{port}"].state == "open"
        loop.tick()  # first tick under the open breaker
        snap = reg.snapshot()
        assert up_values(snap) == [0.0, 0.0]
        names = {s.spec.name for s in snap.series}
        assert schema.DUTY_CYCLE.name not in names  # nothing fabricated
        power = [s for s in snap.series if s.spec.name == schema.POWER.name]
        assert power and all(
            ("stale", "true") in s.labels for s in power)
        # accelerator_up keeps its base identity (the health contract).
        ups = [s for s in snap.series
               if s.spec.name == schema.DEVICE_UP.name]
        assert all("stale" not in dict(s.labels) for s in ups)
        # Breaker self-metrics ride the snapshot (and thus /metrics).
        states = [s.value for s in snap.series
                  if s.spec.name == schema.BREAKER_STATE.name]
        assert states == [2.0]
        trips = [s.value for s in snap.series
                 if s.spec.name == schema.BREAKER_TRIPS.name]
        assert trips == [1.0]

        # Runtime returns on the same port: the recovery probe re-admits
        # the fetch; chips must be fresh within two ticks of a
        # successful reconnect, with no negative ICI rate ever.
        server2 = FakeLibtpuServer(num_chips=2, port=port).start()
        try:
            time.sleep(0.06)  # recovery_time elapses -> probe admitted
            recovered_at = None
            for attempt in range(10):
                loop.tick()
                snap = reg.snapshot()
                rates = [s.value for s in snap.series
                         if s.spec.name == schema.ICI_BANDWIDTH.name]
                assert all(r >= 0 for r in rates), rates
                if up_values(snap) == [1.0, 1.0]:
                    recovered_at = attempt
                    break
                time.sleep(0.2)  # gRPC channel reconnect backoff
            assert recovered_at is not None, "chips never recovered"
            snap = reg.snapshot()
            assert schema.DUTY_CYCLE.name in {
                s.spec.name for s in snap.series}
            assert all("stale" not in dict(s.labels) for s in snap.series)
            states = [s.value for s in snap.series
                      if s.spec.name == schema.BREAKER_STATE.name]
            assert states == [0.0]  # closed again
        finally:
            server2.stop()
    finally:
        loop.stop()
        col.close()


def test_hung_tick_respawned_by_supervisor_watchdog():
    """A collector hang no timeout covers (begin_tick blocks): the
    supervisor watchdog notices the missing heartbeat, abandons the
    wedged thread (crash-only), respawns the loop, and
    kts_component_restarts_total increments — while the metrics
    endpoint keeps serving the last snapshot throughout."""
    from kube_gpu_stats_tpu.supervisor import Supervisor

    class HangingCollector(MockCollector):
        def __init__(self):
            super().__init__(num_devices=1)
            self.hang = threading.Event()     # arm: next begin_tick blocks
            self.hung = threading.Event()     # signal: we are blocked
            self.release = threading.Event()  # cleanup: unblock

        def begin_tick(self):
            if self.hang.is_set():
                self.hang.clear()  # one-shot: the respawned loop proceeds
                self.hung.set()
                self.release.wait(30)

    col = HangingCollector()
    sup = Supervisor(check_interval=0.05)
    reg = Registry()
    loop = PollLoop(col, reg, interval=0.05, deadline=5.0,
                    heartbeat=sup.beater("poll"),
                    health_stats=sup.contribute)
    sup.register("poll", is_alive=loop.thread_alive, restart=loop.respawn,
                 heartbeat_timeout=0.5)
    server = MetricsServer(reg, host="127.0.0.1", port=0)
    server.start()
    loop.start()
    sup.start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and reg.generation < 2:
            time.sleep(0.01)
        assert reg.generation >= 2

        col.hang.set()
        assert col.hung.wait(5)  # loop thread is now wedged in the tick
        wedged_at = reg.generation
        # The endpoint keeps serving while the loop is wedged.
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=2).read()
        assert b"accelerator_up" in body

        # Watchdog detects the missing heartbeat and respawns the loop:
        # publishes resume without any external kick.
        deadline = time.monotonic() + 10
        while (time.monotonic() < deadline
               and reg.generation < wedged_at + 3):
            time.sleep(0.02)
        assert reg.generation >= wedged_at + 3, "loop never respawned"
        restarts = [
            s.value for s in reg.snapshot().series
            if s.spec.name == "kts_component_restarts_total"
            and dict(s.labels).get("component") == "poll"
        ]
        assert restarts and restarts[0] >= 1.0
        healthy = [
            s.value for s in reg.snapshot().series
            if s.spec.name == "kts_component_healthy"
            and dict(s.labels).get("component") == "poll"
        ]
        assert healthy  # health state machine exports alongside
    finally:
        col.release.set()
        sup.stop()
        loop.stop()
        server.stop()


def test_kubelet_socket_loss_last_good_mapping_stale_then_fresh(tmp_path):
    """Hard kubelet socket loss: attribution keeps serving the last-good
    pod-device mapping, labeled stale="true" once the kubelet breaker
    opens, then recovers and re-labels fresh after the socket returns —
    picking up the new allocation, not the cached one."""
    from kube_gpu_stats_tpu.attribution import CachedAttribution
    from kube_gpu_stats_tpu.attribution.podresources import PodResourcesSource
    from kube_gpu_stats_tpu.resilience import CircuitBreaker
    from kube_gpu_stats_tpu.testing.kubelet_server import (FakeKubeletServer,
                                                           tpu_pod)

    socket_path = str(tmp_path / "kubelet.sock")
    server = FakeKubeletServer(
        socket_path, [tpu_pod("train", "ml", "worker", ["0", "1"])]).start()
    source = PodResourcesSource(
        socket_path, rpc_timeout=2.0,
        breaker=CircuitBreaker("kubelet", failure_threshold=2,
                               recovery_time=0.05))
    cached = CachedAttribution(source, refresh_interval=60.0)
    col = MockCollector(num_devices=2)
    reg = Registry()
    loop = PollLoop(col, reg, deadline=5.0, attribution=cached)
    try:
        cached.refresh_once()
        assert not cached.stale
        loop.tick()
        snap = reg.snapshot()
        power = [s for s in snap.series
                 if s.spec.name == schema.POWER.name]
        assert [dict(s.labels)["pod"] for s in power] == ["train", "train"]
        assert all("stale" not in dict(s.labels) for s in snap.series)

        server.close_socket()  # hard socket loss: stopped AND unlinked
        cached.refresh_once()  # failure 1
        cached.refresh_once()  # failure 2 -> kubelet breaker opens
        assert cached.breaker.state == "open"
        assert cached.stale
        loop.tick()
        snap = reg.snapshot()
        # Collection itself is healthy: chips stay up...
        assert up_values(snap) == [1.0, 1.0]
        power = [s for s in snap.series
                 if s.spec.name == schema.POWER.name]
        for s in power:
            labels = dict(s.labels)
            # ...serving the LAST-GOOD mapping, labeled stale.
            assert labels["pod"] == "train"
            assert labels.get("stale") == "true"

        # Socket returns with a NEW allocation on the same path.
        server2 = FakeKubeletServer(
            socket_path,
            [tpu_pod("serve", "ml", "worker", ["0", "1"])]).start()
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and cached.stale:
                time.sleep(0.06)  # let the breaker's recovery window pass
                cached.refresh_once()
            assert not cached.stale, "attribution never recovered"
            loop.tick()
            snap = reg.snapshot()
            power = [s for s in snap.series
                     if s.spec.name == schema.POWER.name]
            for s in power:
                labels = dict(s.labels)
                assert labels["pod"] == "serve"  # fresh, not cached
                assert "stale" not in labels
        finally:
            server2.stop()
    finally:
        loop.stop()


def test_multiport_partial_outage_stales_only_that_ports_chips(tmp_path):
    """Multi-process runtime, one process dies permanently: only ITS
    chips go stale (up 0, stale-labeled env gauges) — the healthy
    port's chips stay fresh. The per-device escalation must use the
    port->device mapping, not all-ports-open."""
    make_sysfs(tmp_path, num_chips=4)
    server_a = FakeLibtpuServer(num_chips=2).start()
    server_b = FakeLibtpuServer(num_chips=2, chip_offset=2).start()
    col = TpuCollector(
        sysfs_root=str(tmp_path),
        libtpu_client=LibtpuClient(
            ports=(server_a.port, server_b.port), rpc_timeout=0.5,
            breaker_recovery_time=30.0, breaker_min_span=0.0),
        use_native=False,
    )
    reg = Registry()
    loop = PollLoop(col, reg, deadline=5.0,
                    pipeline_fetch=False)  # blocking contract: each tick joins its own fetch
    try:
        loop.tick()
        assert up_values(reg.snapshot()) == [1.0, 1.0, 1.0, 1.0]

        server_b.stop()  # one process dies; the other keeps serving
        for _ in range(3):  # trip port B's breaker
            loop.tick()
        loop.tick()
        snap = reg.snapshot()
        assert up_values(snap) == [1.0, 1.0, 0.0, 0.0]
        for s in snap.series:
            labels = dict(s.labels)
            if s.spec.name == schema.DUTY_CYCLE.name:
                # Runtime values only from the live port's chips.
                assert labels["chip"] in ("0", "1")
            if s.spec.name == schema.POWER.name:
                stale = labels.get("stale")
                assert stale == ("true" if labels["chip"] in ("2", "3")
                                 else None)
    finally:
        loop.stop()
        server_a.stop()
        col.close()


def test_probe_tick_stays_stale_not_flapping(tmp_path):
    """During a persistent outage, the half-open recovery probe blocks
    ~0.5s — far past the 50 ms tick budget — so the overlapping tick
    degrades with 'fetch not ready' rather than a breaker error. That
    tick must STILL be stale: flapping accelerator_up back to 1 once
    per recovery window would defeat the contract and churn series
    identity at the probe cadence for the whole outage."""
    make_sysfs(tmp_path, num_chips=1)
    col = TpuCollector(
        sysfs_root=str(tmp_path),
        libtpu_client=LibtpuClient(
            ports=(1,), rpc_timeout=0.1,  # nothing listens on port 1
            breaker_min_span=0.0, breaker_recovery_time=30.0),
        use_native=False,
    )
    try:
        (dev,) = col.discover()
        for _ in range(3):  # trip the breaker
            col.begin_tick()
            col.wait_ready(5.0)
        assert col.breakers()["libtpu:1"].state == "open"
        env = col.read_environment(dev)
        # The probe-overrun tick: runtime_ready=False, breaker open.
        sample = col.assemble(dev, env, None, runtime_ready=False)
        assert sample.stale
        # And the ordinary open-breaker tick agrees (peek escalation).
        sample = col.assemble(dev, env, None, runtime_ready=True)
        assert sample.stale
    finally:
        col.close()


def test_pipelined_tick_detects_runtime_death(tmp_path):
    """The DEFAULT (pipelined) tick serves the last completed fetch, so
    a runtime death is observed one fetch cadence later, not in the
    same tick — but it must surface within a couple of ticks (the dead
    port answers connection-refused fast, and that failed refresh IS a
    completed outcome), never be masked indefinitely by the cache."""
    make_sysfs(tmp_path, num_chips=2)
    server = FakeLibtpuServer(num_chips=2).start()
    col = TpuCollector(
        sysfs_root=str(tmp_path),
        libtpu_client=LibtpuClient(ports=(server.port,), rpc_timeout=0.5),
        use_native=False,
    )
    reg = Registry()
    loop = PollLoop(col, reg, interval=0.05, deadline=5.0)  # fence 0.1 s
    try:
        loop.tick()  # blocking cold tick primes fetch + environment
        loop.tick()  # first pipelined tick
        assert schema.DUTY_CYCLE.name in {
            s.spec.name for s in reg.snapshot().series}

        server.stop()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            loop.tick()
            names = {s.spec.name for s in reg.snapshot().series}
            if schema.DUTY_CYCLE.name not in names:
                break
            time.sleep(0.05)
        names = {s.spec.name for s in reg.snapshot().series}
        # Runtime families are gone; fresh environment still exports
        # (independent degradation, same contract as blocking mode).
        assert schema.DUTY_CYCLE.name not in names
        assert schema.POWER.name in names
    finally:
        loop.stop()
        server.stop()
        col.close()
