"""Failure-detection / elastic-recovery integration tests (SURVEY.md §5:
"survive libtpu restart / kubelet socket loss: retry with backoff, mark
device gauges stale, never crash the DaemonSet pod"; fault injection via the
fake servers)."""

import threading
import time
import urllib.request

from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.collectors.composite import TpuCollector
from kube_gpu_stats_tpu.collectors.libtpu import LibtpuClient
from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.exposition import MetricsServer
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry
from kube_gpu_stats_tpu.testing.libtpu_server import FakeLibtpuServer
from kube_gpu_stats_tpu.testing.sysfs_fixture import make_sysfs


def up_values(snapshot):
    return [s.value for s in snapshot.series if s.spec.name == "accelerator_up"]


def test_libtpu_restart_counters_reset_then_recover(tmp_path):
    """Kill the runtime mid-run, restart it on the SAME port with reset
    counters: chips degrade (env-only), then recover, and the ICI rate math
    never emits a negative/spiked rate from the reset."""
    make_sysfs(tmp_path, num_chips=2)
    server = FakeLibtpuServer(num_chips=2).start()
    port = server.port
    col = TpuCollector(
        sysfs_root=str(tmp_path),
        libtpu_client=LibtpuClient(ports=(port,), rpc_timeout=0.5),
        use_native=False,
    )
    reg = Registry()
    loop = PollLoop(col, reg, deadline=5.0)
    loop.tick()
    loop.tick()
    assert up_values(reg.snapshot()) == [1.0, 1.0]

    server.stop()  # runtime dies
    loop.tick()
    snap = reg.snapshot()
    # sysfs still answers: chips stay up with environment-only samples.
    assert up_values(snap) == [1.0, 1.0]
    names = {s.spec.name for s in snap.series}
    assert schema.DUTY_CYCLE.name not in names
    assert schema.POWER.name in names

    # Pre-restart: the derived restart counter exists, born at 0.
    restart_values = [
        s.value for s in reg.snapshot().series
        if s.spec.name == schema.RUNTIME_RESTARTS.name
    ]
    assert restart_values == [0.0, 0.0]

    # Runtime restarts: counters restart near zero (reset semantics). The
    # channel reconnect + reset-interval drop may take a couple of ticks;
    # the invariant is that NO tick ever emits a negative/spiked rate and
    # rates return within a few ticks.
    server2 = FakeLibtpuServer(num_chips=2, port=port).start()
    server2.uptime_base = 3.0  # fresh runtime: uptime moved backwards
    try:
        bandwidths = []
        for attempt in range(10):
            loop.tick()
            time.sleep(0.2)  # let the channel finish reconnecting
            bandwidths = [
                s.value for s in reg.snapshot().series
                if s.spec.name == schema.ICI_BANDWIDTH.name
            ]
            assert all(b >= 0 for b in bandwidths), bandwidths
            if bandwidths:
                break
        assert len(bandwidths) == 12, f"rates never recovered: {bandwidths}"
        snap = reg.snapshot()
        assert schema.DUTY_CYCLE.name in {s.spec.name for s in snap.series}
        # The uptime drop (7200 -> 3) was observed exactly once per chip:
        # accelerator_runtime_restarts_total makes the bounce alertable
        # with increase() instead of a magic uptime threshold.
        restart_values = [
            s.value for s in snap.series
            if s.spec.name == schema.RUNTIME_RESTARTS.name
        ]
        assert restart_values == [1.0, 1.0]
    finally:
        server2.stop()
        loop.stop()


def test_scrape_storm_does_not_perturb_poll_latency():
    """E3 lock-light contract: a scrape storm renders snapshots and must not
    stretch tick latency (snapshot swap is the only shared state)."""
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=8), reg, deadline=5.0)
    server = MetricsServer(reg, host="127.0.0.1", port=0)
    server.start()
    loop.tick()

    def quiet_p50(n=30):
        xs = sorted(loop.tick() for _ in range(n))
        return xs[n // 2]

    baseline = quiet_p50()

    stop = threading.Event()

    def storm():
        while not stop.is_set():
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/metrics", timeout=2
                ).read()
            except Exception:
                pass

    threads = [threading.Thread(target=storm, daemon=True) for _ in range(8)]
    for t in threads:
        t.start()
    try:
        stormy = quiet_p50()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=2)
        server.stop()
        loop.stop()
    # Generous bound: GIL contention exists, but nothing should serialize a
    # tick behind 8 scrapers. Catches accidental lock coupling.
    assert stormy < max(baseline * 5, baseline + 0.010), (baseline, stormy)


def test_hotplug_rediscovery_picks_up_new_chip():
    class GrowingCollector(MockCollector):
        def __init__(self):
            super().__init__(num_devices=2)
            self.grown = False

        def discover(self):
            if self.grown:
                bigger = MockCollector(num_devices=3)
                return bigger.discover()
            return super().discover()

        def sample(self, device):
            if device.index >= 2:
                return MockCollector(num_devices=3, start_tick=5).sample(device)
            return super().sample(device)

    col = GrowingCollector()
    reg = Registry()
    loop = PollLoop(col, reg, interval=0.01, deadline=5.0,
                    rediscovery_interval=0.05)
    loop.start()
    try:
        assert reg.wait_for_publish(0, timeout=5)
        assert len(up_values(reg.snapshot())) == 2
        col.grown = True
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if len(up_values(reg.snapshot())) == 3:
                break
            assert reg.wait_for_publish(reg.generation, timeout=5)
        assert len(up_values(reg.snapshot())) == 3
    finally:
        loop.stop()


def test_failing_rediscovery_keeps_serving():
    class FlakyDiscovery(MockCollector):
        def __init__(self):
            super().__init__(num_devices=2)
            self.discover_calls = 0

        def discover(self):
            self.discover_calls += 1
            if self.discover_calls > 1:
                raise RuntimeError("sysfs went away")
            return super().discover()

    col = FlakyDiscovery()
    reg = Registry()
    loop = PollLoop(col, reg, deadline=5.0)
    loop.tick()
    loop.rediscover()  # raises internally, must be swallowed
    loop.tick()
    snap = reg.snapshot()
    assert up_values(snap) == [1.0, 1.0]
    errors = [
        s.value for s in snap.series
        if s.spec.name == "collector_poll_errors_total"
        and dict(s.labels).get("reason") == "rediscover"
    ]
    assert errors == [1.0]
    loop.stop()


def test_tick_crash_does_not_kill_loop():
    """An unexpected (non-CollectorError) exception inside a tick must not
    kill the run_forever thread (review finding: silent permanent metrics
    loss behind a passing healthz)."""
    class ExplodingCollector(MockCollector):
        def __init__(self):
            super().__init__(num_devices=1)
            self.calls = 0

        def begin_tick(self):
            self.calls += 1
            if self.calls == 2:
                raise TypeError("unexpected proto shape")  # not CollectorError

    col = ExplodingCollector()
    reg = Registry()
    loop = PollLoop(col, reg, interval=0.01, deadline=5.0)
    loop.start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and reg.generation < 5:
            time.sleep(0.01)
        assert reg.generation >= 5  # kept publishing after the crash tick
        crash = [
            s.value for s in reg.snapshot().series
            if s.spec.name == "collector_poll_errors_total"
            and dict(s.labels).get("reason") == "tick_crash"
        ]
        assert crash == [1.0]
    finally:
        loop.stop()


def test_healthz_goes_unhealthy_when_poll_dies():
    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.registry import SnapshotBuilder

    reg = Registry()
    server = MetricsServer(reg, host="127.0.0.1", port=0, healthz_max_age=0.2)
    server.start()
    url = f"http://127.0.0.1:{server.port}/healthz"
    try:
        # No snapshot yet: stale.
        try:
            urllib.request.urlopen(url, timeout=2)
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
        reg.publish(SnapshotBuilder().build())
        assert urllib.request.urlopen(url, timeout=2).status == 200
        time.sleep(0.4)  # poll "died": no publishes for > max_age
        try:
            urllib.request.urlopen(url, timeout=2)
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
    finally:
        server.stop()


def test_slow_runtime_degrades_fresh_not_stale(tmp_path):
    """Runtime slower than the tick deadline: chips must degrade to this
    tick's sysfs-only values — the split fast path must NOT peek the
    previous tick's runtime cache and serve stale duty/HBM as fresh."""
    make_sysfs(tmp_path, num_chips=2)
    server = FakeLibtpuServer(num_chips=2).start()
    col = TpuCollector(
        sysfs_root=str(tmp_path),
        libtpu_client=LibtpuClient(ports=(server.port,), rpc_timeout=5.0),
        use_native=False,
    )
    reg = Registry()
    loop = PollLoop(col, reg, deadline=0.4)
    try:
        loop.tick()  # healthy tick primes the runtime cache
        names = {s.spec.name for s in reg.snapshot().series}
        assert schema.DUTY_CYCLE.name in names

        server.delay = 2.0  # now slower than the 0.4s deadline
        loop.tick()
        snapshot = reg.snapshot()
        names = {s.spec.name for s in snapshot.series}
        # Fresh environmental values still export; runtime families must
        # vanish rather than repeat the previous tick's cache.
        assert schema.POWER.name in names
        assert schema.DUTY_CYCLE.name not in names
        assert up_values(snapshot) == [1.0, 1.0]  # degraded, not stale
        # Retained capacity: used/total ratios must not flap on slow ticks.
        assert schema.MEMORY_TOTAL.name in names

        server.delay = 0.0
        # The wedged 2s fetch from the slow tick must drain before a new
        # one can land; wait it out, then confirm recovery.
        col.wait_ready(5.0)
        loop.tick()
        loop.tick()
        assert schema.DUTY_CYCLE.name in {
            s.spec.name for s in reg.snapshot().series
        }
    finally:
        loop.stop()
        server.stop()
        col.close()
