"""Pushgateway exposition mode against a fake gateway HTTP server."""

import http.server
import threading
import time

import pytest

from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.exposition import CONTENT_TYPE, PushgatewayPusher
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry


class FakeGateway:
    def __init__(self):
        self.requests = []
        self.fail = False
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_PUT(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                outer.requests.append(
                    (self.path, self.headers.get("Content-Type"), body)
                )
                self.send_response(500 if outer.fail else 200)
                self.send_header("Content-Length", "0")
                self.end_headers()

        self._server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self._server.server_address[1]}"
        threading.Thread(target=self._server.serve_forever, daemon=True).start()

    def stop(self):
        self._server.shutdown()


@pytest.fixture
def gateway():
    g = FakeGateway()
    yield g
    g.stop()


def test_push_once_target_and_body(gateway):
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, deadline=5.0)
    loop.tick()
    pusher = PushgatewayPusher(reg, gateway.url, job="tpu job",
                               instance="node-1")
    pusher.push_once()
    loop.stop()
    (path, content_type, body) = gateway.requests[0]
    assert path == "/metrics/job/tpu%20job/instance/node-1"
    assert content_type == CONTENT_TYPE
    assert b"accelerator_duty_cycle" in body
    assert pusher.consecutive_failures == 0


def test_push_failure_counted_not_fatal(gateway):
    reg = Registry()
    gateway.fail = True
    pusher = PushgatewayPusher(reg, gateway.url, instance="n")
    pusher.push_once()
    assert pusher.consecutive_failures == 1
    gateway.fail = False
    pusher.push_once()
    assert pusher.consecutive_failures == 0


def test_follows_publishes(gateway):
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, interval=0.03,
                    deadline=5.0)
    pusher = PushgatewayPusher(reg, gateway.url, instance="n",
                               min_interval=0.0)
    pusher.start()
    loop.start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and len(gateway.requests) < 3:
            time.sleep(0.02)
        assert len(gateway.requests) >= 3
    finally:
        loop.stop()
        pusher.stop()


def test_daemon_wiring(gateway, monkeypatch):
    from kube_gpu_stats_tpu.config import Config, from_args
    from kube_gpu_stats_tpu.daemon import Daemon

    cfg = from_args(["--backend", "mock", "--listen-port", "0",
                     "--pushgateway-url", gateway.url,
                     "--attribution", "off", "--interval", "0.05"])
    assert cfg.pushgateway_url == gateway.url
    d = Daemon(cfg)
    d.start()
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not gateway.requests:
            time.sleep(0.02)
        assert gateway.requests
        assert gateway.requests[0][0].startswith("/metrics/job/kube-tpu-stats/")
    finally:
        d.stop()
