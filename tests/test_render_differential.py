"""Differential pins for the ISSUE 17 native hot paths: the wirefast
exposition render + gzip (``render_exposition``/``gzip_compress``) and
the hub frame-fold loop (``fold_rows``) must be indistinguishable from
their pure-Python oracles — ``Snapshot.render().encode()``,
``gzip.compress(..., mtime=0)`` and ``ChipRow.clone_at`` — over
randomized registries (histograms, staleness NaNs, federation
re-export families), randomized fold churn, and both exposition
formats. Same discipline as tests/test_ingest_differential.py: drive
both implementations with identical inputs, require identical bytes /
identical objects, and pin that the native path is actually exercised
(not silently oracled away)."""

from __future__ import annotations

import gzip as gzip_mod
import random

import pytest

from kube_gpu_stats_tpu import registry as registry_mod
from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.native import load_fold, load_render
from kube_gpu_stats_tpu.registry import (HistogramState, Registry, Series,
                                         Snapshot)
from kube_gpu_stats_tpu.top import ChipRow

NATIVE = load_render()
NATIVE_FOLD = load_fold()

needs_native = pytest.mark.skipif(
    NATIVE is None, reason="wirefast extension not built")
needs_native_fold = pytest.mark.skipif(
    NATIVE_FOLD is None, reason="wirefast extension not built")

_PLAIN_SPECS = [s for s in schema.ALL_METRICS
                if s.type is not schema.MetricType.HISTOGRAM]
_HIST_SPECS = [s for s in schema.ALL_METRICS
               if s.type is schema.MetricType.HISTOGRAM]

# Every divergence class the formatter has: NaN (staleness markers),
# infinities, int-collapse edges around 1e15, shortest-repr floats.
_VALUES = (0.0, -0.0, 1.0, -1.5, float("nan"), float("inf"),
           float("-inf"), 1e15, -1e15, 999999999999999.0, 2.0**53 + 2.0,
           123456789.25, 0.1, 1e-9, 1e300, 3.0)

_LABEL_VALUES = ("", "a", "train-0", 'quo"te', "back\\slash", "new\nline",
                 "unicode-é", "tpu-v5p")


def _random_snapshot(rng: random.Random) -> Snapshot:
    """A randomized registry snapshot: per-chip families, slice_*
    federation re-export rollups, self-metrics — any non-histogram
    family the schema knows — plus dimensioned histogram states."""
    series = []
    for _ in range(rng.randrange(0, 60)):
        spec = rng.choice(_PLAIN_SPECS)
        labels = tuple(
            (f"l{i}", rng.choice(_LABEL_VALUES))
            for i in range(rng.randrange(0, 4)))
        series.append(Series(spec, labels, rng.choice(_VALUES)))
    hists = []
    for _ in range(rng.randrange(0, 5)):
        spec = rng.choice(_HIST_SPECS)
        labels = ()
        if rng.random() < 0.6:
            labels = (("output", rng.choice(("http", "textfile"))),)
        state = HistogramState.empty(
            spec, (0.001, 0.01, 0.1, 1.0, 10.0), labels=labels)
        for _ in range(rng.randrange(0, 12)):
            state = state.observe(rng.uniform(0.0, 20.0),
                                  rng.randrange(1, 4))
        hists.append(state)
    return Snapshot(tuple(series), tuple(hists), 0.0)


@needs_native
def test_native_render_matches_python_oracle_randomized():
    """The acceptance pin: native render bytes == Snapshot.render()
    bytes over randomized registries, both exposition formats."""
    rng = random.Random(0x17E17)
    for _ in range(300):
        snap = _random_snapshot(rng)
        for openmetrics in (False, True):
            oracle = snap.render(openmetrics=openmetrics).encode()
            native = NATIVE.render_exposition(
                snap.series, snap.histograms, openmetrics)
            assert native == oracle


@needs_native
def test_native_render_empty_and_eof_edges():
    empty = Snapshot((), (), 0.0)
    assert NATIVE.render_exposition((), (), False) == b""
    assert (NATIVE.render_exposition((), (), True)
            == empty.render(openmetrics=True).encode() == b"# EOF\n")


@needs_native
def test_native_gzip_matches_python_gzip():
    """gzip_compress must be byte-identical to gzip.compress(mtime=0)
    at every level the render cache can ask for — the compressed
    artifact is part of the golden contract, not just the plaintext."""
    rng = random.Random(7)
    payloads = [b"", b"x", bytes(rng.randrange(256) for _ in range(4096)),
                b"accelerator_duty_cycle 42\n" * 4096]
    for level in (1, 2, 5, 6, 9):
        for data in payloads:
            assert (NATIVE.gzip_compress(data, level)
                    == gzip_mod.compress(data, compresslevel=level,
                                         mtime=0))


@needs_native
def test_registry_rendered_native_vs_oracle_registry():
    """End-to-end through Registry.rendered: a native registry and a
    native=False oracle registry publish identical snapshots and must
    serve identical bytes for every (format, gzip) shape."""
    rng = random.Random(0xD1FF)
    fast, oracle = Registry(), Registry(native=False)
    for _ in range(20):
        snap = _random_snapshot(rng)
        fast.publish(snap)
        oracle.publish(snap)
        for openmetrics in (False, True):
            for level in (0, 6, 9):
                got, _ = fast.rendered(openmetrics, level)
                want, _ = oracle.rendered(openmetrics, level)
                assert got == want
    # The fast registry must still be on the native path — a silent
    # mid-run fallback (native render raising) would have flipped it.
    assert fast._native_render


@needs_native
def test_native_render_exercised_not_silently_oracled(monkeypatch):
    """The differential suite is meaningless if Registry.rendered never
    actually reaches the native module — count the calls."""
    calls = {"render": 0, "gzip": 0}

    class Shim:
        @staticmethod
        def render_exposition(series, hists, openmetrics):
            calls["render"] += 1
            return NATIVE.render_exposition(series, hists, openmetrics)

        @staticmethod
        def gzip_compress(data, level):
            calls["gzip"] += 1
            return NATIVE.gzip_compress(data, level)

    monkeypatch.setattr(registry_mod, "_NATIVE_RENDER", Shim())
    monkeypatch.setattr(registry_mod, "_NATIVE_RENDER_LOADED", True)
    reg = Registry()
    reg.publish(_random_snapshot(random.Random(1)))
    body, hit = reg.rendered(False, 6)
    assert not hit and body
    assert calls == {"render": 1, "gzip": 1}


def _random_rows(rng: random.Random, n: int) -> dict:
    rows = {}
    for i in range(n):
        key = (f"http://t{i}", f"s{rng.randrange(3)}",
               str(rng.randrange(8)), str(i))
        row = ChipRow(key, at=rng.uniform(0, 100))
        row.duty = rng.choice((None, rng.uniform(0, 100)))
        row.mem_used = rng.choice((None, 1e9 * rng.random()))
        row.ici_bps = rng.uniform(0, 1e9)
        row.holders = rng.randrange(4)
        row.steps_total = rng.choice((None, float(rng.randrange(10**6))))
        rows[key] = row
    return rows


@needs_native_fold
def test_frame_fold_parity_under_randomized_churn():
    """fold_rows(dst, src, at) must produce rows field-identical to the
    clone_at oracle, with clone independence (mutating a frame row
    never touches the cached fold, and vice versa) — across rounds of
    randomized churn of the cached fold between folds."""
    rng = random.Random(0xF01D)
    src = _random_rows(rng, 40)
    for round_no in range(20):
        at = rng.uniform(0, 1e6)
        native_dst: dict = {}
        oracle_dst = {}
        NATIVE_FOLD.fold_rows(native_dst, src, at)
        for key, row in src.items():
            oracle_dst[key] = row.clone_at(at)
        assert native_dst.keys() == oracle_dst.keys()
        for key in oracle_dst:
            assert native_dst[key].__dict__ == oracle_dst[key].__dict__
            assert native_dst[key] is not src[key]
            assert type(native_dst[key]) is ChipRow
        #

        # Clone independence both ways: frame mutation (rates()) must
        # not leak into the cached fold; fold churn must not reach the
        # already-built frame.
        sample = rng.choice(list(src))
        native_dst[sample].duty = -1.0
        assert src[sample].duty != -1.0
        src[sample].ici_bps += 7.0
        assert native_dst[sample].ici_bps != src[sample].ici_bps
        # Randomized churn: add, drop, and restamp rows.
        for key in rng.sample(list(src), k=min(4, len(src))):
            del src[key]
        src.update(_random_rows(rng, rng.randrange(1, 6)))


@needs_native_fold
def test_hub_refresh_uses_native_fold():
    """Not-silently-oracled pin for the fold: a default hub loads the
    fold module; --no-native-ingest style hubs must not."""
    from kube_gpu_stats_tpu.hub import Hub

    fast = Hub([], targets_provider=lambda: [], interval=10.0,
               push_fence=1e9)
    oracle = Hub([], targets_provider=lambda: [], interval=10.0,
                 push_fence=1e9, native_ingest=False)
    assert fast._fold_native is not None
    assert oracle._fold_native is None
