"""Poll-loop behavior: fan-out, staleness, attribution join, self-metrics
(SURVEY.md §3 E2/E5, §5 failure detection)."""

import time

from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.collectors import Collector, CollectorError, Device, Sample
from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry


def series_map(snapshot):
    return {
        (s.spec.name, s.labels): s.value for s in snapshot.series
    }


def get(snapshot, name, **want_labels):
    out = []
    for s in snapshot.series:
        if s.spec.name != name:
            continue
        labels = dict(s.labels)
        if all(labels.get(k) == v for k, v in want_labels.items()):
            out.append((labels, s.value))
    return out


def test_tick_publishes_all_families():
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=2), reg, deadline=5.0)
    loop.tick()
    snap = reg.snapshot()
    assert len(get(snap, "accelerator_up")) == 2
    assert all(v == 1.0 for _, v in get(snap, "accelerator_up"))
    assert len(get(snap, "accelerator_duty_cycle")) == 2
    # 6 links per chip
    assert len(get(snap, "accelerator_ici_link_traffic_bytes_total", chip="0")) == 6
    # First tick: no bandwidth rates yet (no prior counter observation).
    assert get(snap, "accelerator_ici_link_bandwidth_bytes_per_second") == []
    loop.tick()
    snap = reg.snapshot()
    rates = get(snap, "accelerator_ici_link_bandwidth_bytes_per_second", chip="1")
    assert len(rates) == 6
    assert all(v > 0 for _, v in rates)
    assert get(snap, "collector_devices")[0][1] == 2.0
    assert snap.histograms[0].total == 2
    loop.stop()


def test_failed_device_marked_stale_not_fatal():
    reg = Registry()
    loop = PollLoop(
        MockCollector(num_devices=3, fail_devices=[1]), reg, deadline=5.0
    )
    loop.tick()
    loop.tick()
    snap = reg.snapshot()
    ups = {dict(l)["chip"]: v for l, v in get(snap, "accelerator_up")}
    assert ups == {"0": 1.0, "1": 0.0, "2": 1.0}
    errors = get(snap, "collector_poll_errors_total", reason="CollectorError")
    assert errors[0][1] == 2.0
    # Healthy chips still export values.
    assert len(get(snap, "accelerator_duty_cycle")) == 2
    loop.stop()


class SlowCollector(Collector):
    name = "slow"

    def __init__(self, delay):
        self.delay = delay

    def discover(self):
        return [Device(0, "0", "/dev/accel0", "mock")]

    def sample(self, device):
        time.sleep(self.delay)
        return Sample(device, {schema.POWER.name: 1.0})


def test_deadline_marks_device_stale():
    reg = Registry()
    loop = PollLoop(SlowCollector(0.5), reg, deadline=0.02)
    loop.tick()
    snap = reg.snapshot()
    assert get(snap, "accelerator_up")[0][1] == 0.0
    assert get(snap, "collector_poll_errors_total", reason="deadline")[0][1] == 1.0
    loop.stop()


def test_memory_total_retained_when_stale():
    class FlakyCollector(Collector):
        name = "flaky"

        def __init__(self):
            self.calls = 0

        def discover(self):
            return [Device(0, "0", "/dev/accel0", "mock")]

        def sample(self, device):
            self.calls += 1
            if self.calls > 1:
                raise CollectorError("down")
            return Sample(device, {schema.MEMORY_TOTAL.name: 1024.0})

    reg = Registry()
    loop = PollLoop(FlakyCollector(), reg, deadline=5.0)
    loop.tick()
    loop.tick()
    snap = reg.snapshot()
    assert get(snap, "accelerator_up")[0][1] == 0.0
    assert get(snap, "accelerator_memory_total_bytes")[0][1] == 1024.0
    # The restart counter stays emitted through the outage too: a
    # vanishing counter series would blind increase() exactly across a
    # crash-then-restart window (see _build_snapshot).
    assert get(snap, "accelerator_runtime_restarts_total")[0][1] == 0.0
    loop.stop()


def test_energy_integrates_power_over_ticks():
    import time

    class PowerCollector(Collector):
        name = "p"

        def discover(self):
            return [Device(0, "0", "/dev/accel0", "mock")]

        def sample(self, device):
            return Sample(device, {schema.POWER.name: 100.0})

    reg = Registry()
    loop = PollLoop(PowerCollector(), reg, deadline=5.0)
    loop.tick()
    # Born at 0 on the first power observation (no fabricated back-fill).
    assert get(reg.snapshot(), "accelerator_energy_joules_total")[0][1] == 0.0
    time.sleep(0.05)
    loop.tick()
    time.sleep(0.05)
    loop.tick()
    [(labels, joules)] = get(reg.snapshot(),
                             "accelerator_energy_joules_total")
    # 100 W over two observed gaps of >= 0.05 s each: energy is the
    # rectangle-rule integral, monotone and in a sane band.
    assert 100 * 0.08 <= joules <= 100 * 5.0
    loop.stop()


def test_energy_gap_capped_after_outage():
    import time

    class OutageCollector(Collector):
        name = "o"
        fail = False

        def discover(self):
            return [Device(0, "0", "/dev/accel0", "mock")]

        def sample(self, device):
            if self.fail:
                raise CollectorError("down")
            return Sample(device, {schema.POWER.name: 100.0})

    col = OutageCollector()
    reg = Registry()
    loop = PollLoop(col, reg, interval=0.01, deadline=5.0)
    loop.tick()  # baseline timestamp
    col.fail = True
    loop.tick()
    time.sleep(0.3)  # outage much longer than 10 intervals (0.1 s cap)
    col.fail = False
    loop.tick()
    [(labels, joules)] = get(reg.snapshot(),
                             "accelerator_energy_joules_total")
    # Integrating the whole 0.3 s gap at 100 W would be 30 J of energy
    # the chip may never have drawn; the 10-interval cap bounds it.
    assert joules <= 100 * (10 * 0.01) * 1.5  # cap + generous slack
    assert joules > 0.0
    loop.stop()


def test_energy_survives_garbage_power_samples():
    import time

    readings = iter([100.0, float("nan"), -5.0, float("inf"), 100.0])

    class GarbageCollector(Collector):
        name = "g"

        def discover(self):
            return [Device(0, "0", "/dev/accel0", "mock")]

        def sample(self, device):
            return Sample(device, {schema.POWER.name: next(readings)})

    reg = Registry()
    loop = PollLoop(GarbageCollector(), reg, deadline=5.0)
    for _ in range(5):
        loop.tick()
        time.sleep(0.02)
    [(labels, joules)] = get(reg.snapshot(),
                             "accelerator_energy_joules_total")
    # NaN must not poison the sum forever, a negative sample must not
    # un-monotone the counter, inf must not make it inf: only the two
    # valid 100 W observations integrate.
    assert joules == joules  # not NaN
    assert 0.0 <= joules < 100 * 5.0
    loop.stop()


class StaticAttribution:
    def __init__(self, mapping):
        self.mapping = mapping

    def lookup(self, device):
        return self.mapping.get(device.device_id, {})


def test_attribution_and_topology_labels_joined():
    reg = Registry()
    attr = StaticAttribution(
        {"0": {"pod": "train-0", "namespace": "ml", "container": "main"}}
    )
    loop = PollLoop(
        MockCollector(num_devices=2),
        reg,
        deadline=5.0,
        attribution=attr,
        topology_labels={"slice": "v5p-16", "worker": "3", "topology": "2x2x2"},
    )
    loop.tick()
    snap = reg.snapshot()
    labels0 = get(snap, "accelerator_duty_cycle", chip="0")[0][0]
    assert labels0["pod"] == "train-0"
    assert labels0["namespace"] == "ml"
    assert labels0["worker"] == "3"
    labels1 = get(snap, "accelerator_duty_cycle", chip="1")[0][0]
    # Unallocated chip keeps the label keys with empty values.
    assert labels1["pod"] == ""
    assert labels1["slice"] == "v5p-16"
    loop.stop()


def test_attribution_value_change_recompiles_plan():
    """ISSUE 3 satellite: a changed attribution VALUE for the same key
    set must recompile the device's tick plan — covering the
    empty→populated→empty pod transitions a rescheduled workload makes.
    A plan keyed only on key NAMES would keep exporting the dead pod."""
    reg = Registry()
    attr = StaticAttribution({})
    loop = PollLoop(MockCollector(num_devices=1), reg, deadline=5.0,
                    attribution=attr)
    loop.tick()
    assert get(reg.snapshot(), "accelerator_duty_cycle")[0][0]["pod"] == ""
    # empty -> populated
    attr.mapping = {"0": {"pod": "train-7", "namespace": "ml",
                          "container": "main"}}
    loop.tick()
    labels = get(reg.snapshot(), "accelerator_duty_cycle")[0][0]
    assert labels["pod"] == "train-7" and labels["namespace"] == "ml"
    # populated -> populated with a DIFFERENT value for the same keys
    # (pod rescheduled onto the chip under a new name)
    attr.mapping = {"0": {"pod": "train-8", "namespace": "ml",
                          "container": "main"}}
    loop.tick()
    assert get(reg.snapshot(), "accelerator_duty_cycle")[0][0]["pod"] == \
        "train-8"
    # populated -> empty
    attr.mapping = {}
    loop.tick()
    # Steady tick: the recompiled plan now serves from cache.
    loop.tick()
    snap = reg.snapshot()
    assert get(snap, "accelerator_duty_cycle")[0][0]["pod"] == ""
    # Three recompiles beyond the initial device compile, each counted
    # under its reason; the steady tick was a cache hit.
    assert get(snap, "kts_tick_plan_compiles_total",
               reason="attribution")[0][1] == 3.0
    assert get(snap, "kts_tick_plan_compiles_total",
               reason="device")[0][1] == 1.0
    assert get(snap, "kts_tick_plan_cache_hits_total")[0][1] >= 1.0
    loop.stop()


def test_reconfigure_drop_labels_invalidates_every_plan():
    """Drop-label reconfig must invalidate compiled plans (they embed
    the drop set in their pre-joined tuples) — without it the old labels
    would keep flowing from the cached slots forever."""
    reg = Registry()
    loop = PollLoop(
        MockCollector(num_devices=2), reg, deadline=5.0,
        attribution=StaticAttribution(
            {"0": {"pod": "secret", "namespace": "ml", "container": "c"}}),
    )
    loop.tick()
    assert get(reg.snapshot(), "accelerator_duty_cycle",
               chip="0")[0][0]["pod"] == "secret"
    loop.reconfigure(drop_labels=("pod",))
    loop.tick()
    snap = reg.snapshot()
    labels = get(snap, "accelerator_duty_cycle", chip="0")[0][0]
    assert labels["pod"] == "" and labels["container"] == "c"
    # Each device recompiled once under the 'reconfig' reason (the
    # compile burst is attributed to its true cause, not device churn);
    # 'device' keeps only the initial discovery compiles.
    assert get(snap, "kts_tick_plan_compiles_total",
               reason="reconfig")[0][1] == 2.0
    assert get(snap, "kts_tick_plan_compiles_total",
               reason="device")[0][1] == 2.0
    # Un-drop: plans recompile again and the value returns.
    loop.reconfigure(drop_labels=())
    loop.tick()
    assert get(reg.snapshot(), "accelerator_duty_cycle",
               chip="0")[0][0]["pod"] == "secret"
    loop.stop()


def test_reconfigure_metric_filter_applies_next_tick():
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, deadline=5.0)
    loop.tick()
    assert get(reg.snapshot(), "accelerator_duty_cycle")
    loop.reconfigure(
        disabled_metrics=frozenset({"accelerator_duty_cycle"}))
    loop.tick()
    snap = reg.snapshot()
    assert not get(snap, "accelerator_duty_cycle")
    assert get(snap, "accelerator_up")  # everything else still flows
    loop.stop()


def test_run_forever_ticks_at_interval():
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, interval=0.02, deadline=5.0)
    loop.start()
    gen = reg.generation
    assert reg.wait_for_publish(gen, timeout=2)
    assert reg.wait_for_publish(reg.generation, timeout=2)
    loop.stop()
    assert loop.poll_histogram.total >= 2


def test_hung_sample_does_not_leak_workers():
    """A backend call that blocks past the deadline must not stack one pool
    worker per tick (future.cancel can't stop a running call)."""
    import threading

    class HungCollector(Collector):
        name = "hung"

        def __init__(self):
            self.release = threading.Event()
            self.active = 0
            self.peak = 0
            self.lock = threading.Lock()

        def discover(self):
            return [Device(0, "0", "/dev/accel0", "mock")]

        def sample(self, device):
            with self.lock:
                self.active += 1
                self.peak = max(self.peak, self.active)
            try:
                self.release.wait(timeout=10)
            finally:
                with self.lock:
                    self.active -= 1
            return Sample(device, {schema.POWER.name: 1.0})

    col = HungCollector()
    reg = Registry()
    loop = PollLoop(col, reg, deadline=0.01)
    for _ in range(5):
        loop.tick()
    snap = reg.snapshot()
    assert get(snap, "accelerator_up")[0][1] == 0.0
    # Only ONE sampler thread ever entered the backend.
    assert col.peak == 1
    stuck = get(snap, "collector_poll_errors_total", reason="stuck")
    assert stuck and stuck[0][1] == 4.0
    col.release.set()
    loop.stop()


def test_rediscover_purges_vanished_device_state():
    class ShrinkingCollector(Collector):
        name = "shrink"

        def __init__(self):
            self.n = 2

        def discover(self):
            return [
                Device(i, str(i), f"/dev/accel{i}", "mock") for i in range(self.n)
            ]

        def sample(self, device):
            # Power WITHOUT MEMORY_TOTAL for device 1: a degraded-for-
            # life chip carries energy state but no retained total —
            # the purge must key on the union of state dicts, or a
            # renumbered chip inherits the dead one's energy baseline.
            values = {schema.POWER.name: 100.0}
            if device.device_id == "0":
                values[schema.MEMORY_TOTAL.name] = 7.0
            return Sample(device, values, ici_counters={"x0": 100})

    col = ShrinkingCollector()
    reg = Registry()
    loop = PollLoop(col, reg, deadline=5.0)
    loop.tick()
    assert "0" in loop._last_totals and "1" not in loop._last_totals
    assert "1" in loop._last_power_at
    col.n = 1
    loop.rediscover()
    assert "1" not in loop._last_power_at
    assert "1" not in loop._energy
    assert ("1", "x0") not in loop._rates._last
    assert ("0", "x0") in loop._rates._last
    loop.stop()


def test_process_self_metrics_exported():
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, deadline=5.0)
    loop.tick()
    names = {s.spec.name for s in reg.snapshot().series}
    assert "process_cpu_seconds_total" in names
    assert "process_resident_memory_bytes" in names
    assert "process_virtual_memory_bytes" in names
    assert "process_open_fds" in names
    values = {s.spec.name: s.value for s in reg.snapshot().series}
    assert values["process_resident_memory_bytes"] > 1024 * 1024
    assert values["process_virtual_memory_bytes"] >= \
        values["process_resident_memory_bytes"]
    assert values["process_open_fds"] > 0
    # Deliberately absent when the soft limit is RLIM_INFINITY.
    if "process_max_fds" in values:
        assert values["process_open_fds"] <= values["process_max_fds"]
    loop.stop()


def test_drop_labels_blank_but_keep_keys():
    reg = Registry()
    loop = PollLoop(
        MockCollector(num_devices=1),
        reg,
        deadline=5.0,
        attribution=StaticAttribution(
            {"0": {"pod": "secret-job", "namespace": "ml", "container": "c"}}
        ),
        drop_labels=("pod", "namespace", "uuid"),
    )
    loop.tick()
    labels = get(reg.snapshot(), "accelerator_duty_cycle")[0][0]
    assert labels["pod"] == "" and labels["namespace"] == ""
    assert labels["uuid"] == ""
    assert labels["container"] == "c"  # not dropped
    assert set(labels) >= {"pod", "namespace", "uuid"}  # keys retained
    loop.stop()


def test_wedged_env_read_not_served_frozen_by_pipelined_tick():
    """A device whose environment read wedges is demoted to the
    outstanding guard AND loses its completed-round entry: later
    pipelined ticks must keep it visibly down (up 0, counted stuck every
    tick — the blocking path's contract) instead of serving the frozen
    pre-wedge values as fresh forever, while healthy devices keep
    pipelining; once the read unwedges, the device recovers."""
    import concurrent.futures
    import threading

    class WedgeableSplit(Collector):
        name = "wedge"
        pipelined_wait = True

        def __init__(self):
            self.block = {}  # device_id -> Event the read parks on

        def discover(self):
            return [Device(i, str(i), f"/dev/accel{i}", "stub")
                    for i in range(2)]

        def begin_tick(self):
            pass

        def wait_ready(self, timeout=None, max_age=None):
            pass

        def read_environment(self, device):
            gate = self.block.get(device.device_id)
            if gate is not None:
                gate.wait(timeout=5)
            return {schema.POWER.name: 50.0}

        def assemble(self, device, env, env_err, runtime_ready=True):
            values = {schema.DUTY_CYCLE.name: 42.0}
            values.update(env)
            return Sample(device, values)

        def sample(self, device):
            return self.assemble(device, self.read_environment(device), None)

    t = [100.0]
    col = WedgeableSplit()
    reg = Registry()
    loop = PollLoop(col, reg, interval=0.05, deadline=0.3,
                    clock=lambda: t[0])  # fence = 2 * interval = 0.1
    gate = threading.Event()
    try:
        loop.tick()  # blocking cold tick: env completes for both chips
        assert get(reg.snapshot(), schema.POWER.name, chip="0") != []

        col.block["0"] = gate  # chip 0's next read wedges
        t[0] += 0.05
        loop.tick()  # pipelined: serves last round, kicks one that wedges
        # Let chip 1's round read land (only chip 0's is wedged) so the
        # fence-expiry demotion below hits exactly the wedged device.
        concurrent.futures.wait([loop._env_round["1"]], timeout=2)
        t[0] += 0.20  # age > fence: the wedged read gets demoted
        loop.tick()  # blocking fallback: chip 0 is stuck -> stale
        snap = reg.snapshot()
        assert get(snap, "accelerator_up", chip="0")[0][1] == 0.0
        assert get(snap, "accelerator_up", chip="1")[0][1] == 1.0

        t[0] += 0.05
        loop.tick()  # pipelined again (chip 1's read refreshed the fence)
        snap = reg.snapshot()
        # Chip 0's read is still wedged: visibly down and counted, never
        # the frozen power=50 from before the wedge.
        assert get(snap, "accelerator_up", chip="0")[0][1] == 0.0
        assert get(snap, schema.POWER.name, chip="0") == []
        assert loop._errors.get("stuck", 0) >= 2
        assert get(snap, schema.POWER.name, chip="1") != []

        gate.set()  # backend unwedges; the parked read completes
        col.block.clear()
        for _ in range(3):  # reap -> re-included round -> harvested
            t[0] += 0.05
            time.sleep(0.05)
            loop.tick()
        assert get(reg.snapshot(), schema.POWER.name, chip="0") != []
    finally:
        gate.set()
        loop.stop()


def test_full_env_timeout_does_not_rearm_pipelined_fast_path():
    """A blocking tick where EVERY environment read missed the deadline
    must not refresh the pipelined freshness fence: the next tick has to
    block (and mark the devices stale) again, not assemble 'fresh'
    runtime-only samples around reads that never completed."""
    import threading

    class AlwaysWedged(Collector):
        name = "wedged"
        pipelined_wait = True

        def __init__(self):
            self.gate = threading.Event()

        def discover(self):
            return [Device(i, str(i), f"/dev/accel{i}", "stub")
                    for i in range(2)]

        def begin_tick(self):
            pass

        def wait_ready(self, timeout=None, max_age=None):
            pass

        def read_environment(self, device):
            self.gate.wait(timeout=5)
            return {schema.POWER.name: 50.0}

        def assemble(self, device, env, env_err, runtime_ready=True):
            values = {schema.DUTY_CYCLE.name: 42.0}
            values.update(env)
            return Sample(device, values)

        def sample(self, device):
            return self.assemble(device, self.read_environment(device), None)

    t = [100.0]
    col = AlwaysWedged()
    reg = Registry()
    loop = PollLoop(col, reg, interval=0.05, deadline=0.05,
                    clock=lambda: t[0])
    try:
        loop.tick()  # cold blocking tick: both reads time out
        assert [v for _, v in get(reg.snapshot(), "accelerator_up")] == \
            [0.0, 0.0]
        for _ in range(3):
            t[0] += 0.05
            loop.tick()
            # Every subsequent tick must also be a blocking one that
            # reports the outage — a re-armed pipelined fast path would
            # flip the chips to up=1 runtime-only around the dead reads.
            assert [v for _, v in get(reg.snapshot(), "accelerator_up")] == \
                [0.0, 0.0]
    finally:
        col.gate.set()
        loop.stop()


def test_unchanged_fetch_generation_replays_ici_rates():
    """Pipelined regression: a tick re-serving the SAME completed fetch
    (generation unchanged) must replay the previous rates, not feed the
    tracker a duplicate observation — which would emit a bogus zero rate
    and reset the baseline under the genuinely-new counters after it."""

    class SeqCollector(Collector):
        name = "seq"

        def __init__(self):
            self.counter = 1000
            self.runtime_fetch_seq = 1

        def discover(self):
            return [Device(0, "0", "/dev/accel0", "stub")]

        def sample(self, device):
            return Sample(device, {schema.DUTY_CYCLE.name: 1.0},
                          ici_counters={"x_plus": self.counter})

    t = [50.0]
    col = SeqCollector()
    reg = Registry()
    loop = PollLoop(col, reg, deadline=5.0, clock=lambda: t[0])

    def bandwidths():
        return [v for _, v in
                get(reg.snapshot(), schema.ICI_BANDWIDTH.name)]

    loop.tick()
    assert bandwidths() == []  # first observation: no rate yet
    t[0] = 51.0
    col.counter, col.runtime_fetch_seq = 2000, 2
    loop.tick()
    assert bandwidths() == [1000.0]
    t[0] = 52.0  # same generation re-served: replay, not 0
    loop.tick()
    assert bandwidths() == [1000.0]
    t[0] = 53.0
    col.counter, col.runtime_fetch_seq = 4000, 3
    loop.tick()
    # Baseline untouched by the duplicate: (4000-2000)/(53-51), not /1.
    assert bandwidths() == [1000.0]
    loop.stop()
