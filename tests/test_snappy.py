"""Pure-Python snappy block codec: spec vectors, roundtrips (including
fuzz), strict decoder error handling, and compression effectiveness on
exposition-shaped payloads."""

import random

import pytest

from kube_gpu_stats_tpu import snappy


def test_spec_literal_vector():
    # Handcrafted per format_description.txt: len=5, literal tag (5-1)<<2.
    assert snappy.decompress(b"\x05\x10Hello") == b"Hello"


def test_spec_copy_vector():
    # "abababab...": literal "ab" then an overlapping RLE-style copy.
    # len=10; literal len2 tag = (2-1)<<2 = 0x04; copy-2 tag len=8 offset=2:
    # (8-1)<<2 | 0b10 = 0x1e, offset little-endian 0x0002.
    assert snappy.decompress(b"\x0a\x04ab\x1e\x02\x00") == b"ab" * 5


def test_empty_roundtrip():
    assert snappy.decompress(snappy.compress(b"")) == b""


@pytest.mark.parametrize("payload", [
    b"x",
    b"Hello, Hello, Hello!",
    b"ab" * 1000,
    bytes(range(256)) * 300,
    b"accelerator_duty_cycle{chip=\"0\"} 50\n" * 500,
])
def test_roundtrip(payload):
    assert snappy.decompress(snappy.compress(payload)) == payload


def test_fuzz_roundtrip():
    rng = random.Random(1234)
    for trial in range(50):
        n = rng.randrange(0, 5000)
        # Mix of random bytes and repetitive runs to exercise both paths.
        payload = bytes(
            rng.randrange(256) if rng.random() < 0.5 else 65
            for _ in range(n)
        )
        assert snappy.decompress(snappy.compress(payload)) == payload, trial


def test_compresses_repetitive_exposition():
    payload = (b'accelerator_memory_used_bytes{accel_type="tpu-v5p",'
               b'chip="%d",pod="train"} 1073741824\n')
    body = b"".join(payload % i for i in range(256))
    compressed = snappy.compress(body)
    assert len(compressed) < len(body) // 3  # actual LZ, not literal-only


def test_decoder_rejects_garbage():
    for bad in (
        b"",                      # no preamble
        b"\x05\x10He",            # truncated literal
        b"\x0a\x04ab\x1e",        # truncated copy offset
        b"\x05\x04ab\x06\x09\x00",  # copy offset beyond output
        b"\x03\x10Hello",         # length mismatch
        b"\xff\xff\xff\xff\xff\xff",  # runaway length varint
    ):
        with pytest.raises(ValueError):
            snappy.decompress(bad)
