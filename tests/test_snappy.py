"""Pure-Python snappy block codec: spec vectors, roundtrips (including
fuzz), strict decoder error handling, and compression effectiveness on
exposition-shaped payloads."""

import random

import pytest

from kube_gpu_stats_tpu import snappy


def test_spec_literal_vector():
    # Handcrafted per format_description.txt: len=5, literal tag (5-1)<<2.
    assert snappy.decompress(b"\x05\x10Hello") == b"Hello"


def test_spec_copy_vector():
    # "abababab...": literal "ab" then an overlapping RLE-style copy.
    # len=10; literal len2 tag = (2-1)<<2 = 0x04; copy-2 tag len=8 offset=2:
    # (8-1)<<2 | 0b10 = 0x1e, offset little-endian 0x0002.
    assert snappy.decompress(b"\x0a\x04ab\x1e\x02\x00") == b"ab" * 5


def test_empty_roundtrip():
    assert snappy.decompress(snappy.compress(b"")) == b""


@pytest.mark.parametrize("payload", [
    b"x",
    b"Hello, Hello, Hello!",
    b"ab" * 1000,
    bytes(range(256)) * 300,
    b"accelerator_duty_cycle{chip=\"0\"} 50\n" * 500,
])
def test_roundtrip(payload):
    assert snappy.decompress(snappy.compress(payload)) == payload


def test_fuzz_roundtrip():
    rng = random.Random(1234)
    for trial in range(50):
        n = rng.randrange(0, 5000)
        # Mix of random bytes and repetitive runs to exercise both paths.
        payload = bytes(
            rng.randrange(256) if rng.random() < 0.5 else 65
            for _ in range(n)
        )
        assert snappy.decompress(snappy.compress(payload)) == payload, trial


def test_compresses_repetitive_exposition():
    payload = (b'accelerator_memory_used_bytes{accel_type="tpu-v5p",'
               b'chip="%d",pod="train"} 1073741824\n')
    body = b"".join(payload % i for i in range(256))
    compressed = snappy.compress(body)
    assert len(compressed) < len(body) // 3  # actual LZ, not literal-only


def test_decoder_rejects_garbage():
    for bad in (
        b"",                      # no preamble
        b"\x05\x10He",            # truncated literal
        b"\x0a\x04ab\x1e",        # truncated copy offset
        b"\x05\x04ab\x06\x09\x00",  # copy offset beyond output
        b"\x03\x10Hello",         # length mismatch
        b"\xff\xff\xff\xff\xff\xff",  # runaway length varint
    ):
        with pytest.raises(ValueError):
            snappy.decompress(bad)


# -- Known-answer compressor vectors (round-1 advisor finding: roundtrip
# -- tests alone can't catch a symmetric misreading of the format). Each
# -- expected byte string is derived BY HAND from format_description.txt,
# -- independent of the module under test.

def test_compress_known_answer_empty():
    # Spec: a compressed stream is the uvarint uncompressed length followed
    # by elements; empty input = uvarint 0 and nothing else.
    assert snappy.compress(b"") == b"\x00"


def test_compress_known_answer_single_literal():
    # uvarint 1, literal tag (1-1)<<2|00 = 0x00, payload.
    assert snappy.compress(b"a") == b"\x01\x00a"


def test_compress_known_answer_short_string():
    # uvarint 5, literal tag (5-1)<<2 = 0x10 — the same stream the spec's
    # worked example produces; any conformant decoder accepts it.
    assert snappy.compress(b"Hello") == b"\x05\x10Hello"


def _walk_spec_elements(blob: bytes) -> tuple[int, int]:
    """Independent minimal verifier written straight from the snappy
    format grammar (NOT the module's decoder): returns (claimed, produced)
    decompressed lengths — the preamble's uvarint and the length implied
    by walking the element stream — raising on any malformed tag."""
    # uvarint preamble
    shift = claimed = i = 0
    while True:
        byte = blob[i]
        claimed |= (byte & 0x7F) << shift
        i += 1
        if not byte & 0x80:
            break
        shift += 7
    produced = 0
    while i < len(blob):
        tag = blob[i]
        kind = tag & 0b11
        if kind == 0b00:  # literal
            length = (tag >> 2) + 1
            i += 1
            if length > 60:
                extra = length - 60
                length = int.from_bytes(blob[i:i + extra], "little") + 1
                i += extra
            assert i + length <= len(blob), "literal overruns stream"
            i += length
        elif kind == 0b01:  # copy, 1-byte offset, len 4..11
            length = ((tag >> 2) & 0x7) + 4
            offset = ((tag >> 5) << 8) | blob[i + 1]
            i += 2
            assert 0 < offset <= produced, "copy-1 offset out of window"
        elif kind == 0b10:  # copy, 2-byte little-endian offset
            length = (tag >> 2) + 1
            offset = int.from_bytes(blob[i + 1:i + 3], "little")
            i += 3
            assert 0 < offset <= produced, "copy-2 offset out of window"
        else:  # copy, 4-byte offset (never needed at our sizes)
            length = (tag >> 2) + 1
            offset = int.from_bytes(blob[i + 1:i + 5], "little")
            i += 5
            assert 0 < offset <= produced, "copy-4 offset out of window"
        produced += length
    assert i == len(blob), "trailing garbage after final element"
    return claimed, produced


@pytest.mark.parametrize("payload", [
    b"ab" * 50,
    b"accelerator_duty_cycle{chip=\"0\"} 51.5\n" * 40,
    bytes(range(256)) * 3,
])
def test_compressor_output_conforms_to_spec_grammar(payload):
    claimed, produced = _walk_spec_elements(snappy.compress(payload))
    assert claimed == len(payload)
    assert produced == len(payload)


def test_decompression_bomb_bounded():
    """Review finding: a tiny stream of RLE copies claiming a small
    preamble materialized gigabytes before the final length check. The
    bound now trips at the declared length."""
    import pytest

    from kube_gpu_stats_tpu import snappy as s

    # preamble: 100 bytes; body: literal "ab" then RLE copy-2 elements
    # (len 64, offset 1) repeated far past the declared length.
    body = bytearray()
    body += bytes([100])            # varint preamble = 100
    body += bytes([(2 - 1) << 2])   # literal, length 2
    body += b"ab"
    for _ in range(5000):
        body += bytes([((64 - 1) << 2) | 0b10, 1, 0])  # copy-2 len 64 off 1
    with pytest.raises(ValueError, match="exceeds declared length"):
        s.decompress(bytes(body))
