"""Fleet lens (ISSUE 5): per-target anomaly baselines, slow-node
attribution from the daemons' flight-recorder digests, SLO burn
windows, /debug/fleet, and doctor --fleet. The acceptance harness
injects a slow port on one real node and frozen (failing) env reads on
another and pins that BOTH are flagged with the right phase/kind."""

import json
import pathlib
import tempfile
import types
import urllib.error
import urllib.request

import pytest

from kube_gpu_stats_tpu import doctor, fleetlens, schema
from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.exposition import MetricsServer
from kube_gpu_stats_tpu.fleetlens import (EwmaBaseline, FleetLens,
                                          _SloTracker, digest_from_series)
from kube_gpu_stats_tpu.hub import Hub
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry, SnapshotBuilder
from kube_gpu_stats_tpu.top import ChipRow
from kube_gpu_stats_tpu.tracing import Tracer
from kube_gpu_stats_tpu.validate import parse_exposition


def values(text, family):
    return [value for name, labels, value in parse_exposition(text)
            if name == family]


def labeled(text, family):
    return {tuple(sorted(labels.items())): value
            for name, labels, value in parse_exposition(text)
            if name == family}


# -- baselines ---------------------------------------------------------------

def _step(baseline, value):
    """score-then-fold, the exact sequence FleetLens._score drives."""
    z = baseline.score(value)
    baseline.fold(value)
    return z


def test_ewma_baseline_is_deterministic_and_scores_pre_fold():
    readings = [50.0, 51.0, 49.5, 50.5, 50.0, 12.0]
    a, b = EwmaBaseline(), EwmaBaseline()
    zs_a = [_step(a, x) for x in readings]
    zs_b = [_step(b, x) for x in readings]
    assert zs_a == zs_b  # exact arithmetic, no clocks
    assert (a.mean, a.var, a.count) == (b.mean, b.var, b.count)
    assert zs_a[0] == 0.0  # first reading seeds, never scores
    # The collapse to 12 is scored against the ~50 baseline BEFORE it
    # folds in — large negative z.
    assert zs_a[-1] < -4.0
    assert a.count == len(readings)


def test_ewma_flat_signal_does_not_zscore_jitter_to_infinity():
    baseline = EwmaBaseline()
    for _ in range(20):
        _step(baseline, 300.0)
    # 1% jitter on a dead-flat 300 W signal: under the 2%-of-mean
    # variance floor, well below any sane threshold.
    z = baseline.score(303.0)
    assert abs(z) < 1.0


# -- SLO burn windows --------------------------------------------------------

def test_slo_tracker_multiwindow_burn_rates():
    windows = ((300.0, "5m"), (3600.0, "1h"))
    tracker = _SloTracker(0.99, windows)  # 1% error budget
    # 50 min of clean refreshes at 10 s cadence, then a 2-minute
    # incident with 25% of chips stale.
    at = 0.0
    for _ in range(300):
        tracker.update(at, 0.0, 4.0)
        at += 10.0
    for _ in range(24):
        tracker.update(at, 1.0, 4.0)
        at += 10.0
    state = tracker.window_state(at, windows)
    # 5m window: 30 refreshes, 24 bad chips / 120 = 20% >> 1% budget.
    assert state["5m"]["bad_ratio"] == pytest.approx(0.2)
    assert state["5m"]["burn_rate"] == pytest.approx(20.0)
    # 1h window dilutes but still burns over budget.
    assert 0.0 < state["1h"]["bad_ratio"] < state["5m"]["bad_ratio"]
    assert state["1h"]["burn_rate"] > 1.0
    # Events past the horizon are pruned: advance 2h and the windows
    # drain back to zero.
    tracker.update(at + 7200.0, 0.0, 4.0)
    state = tracker.window_state(at + 7200.0, windows)
    assert state["1h"]["bad_ratio"] == 0.0
    assert state["1h"]["events"] == 4  # one refresh x 4 chips survives


# -- digest harvest ----------------------------------------------------------

def test_digest_from_series_extracts_phases_and_slowest():
    series = [
        ("kts_tick_phase_seconds",
         {"phase": "fetch_wait", "quantile": "p99"}, 0.01),
        ("kts_tick_phase_seconds",
         {"phase": "fetch_wait", "quantile": "max"}, 0.5),
        ("kts_slowest_tick_seconds",
         {"phase": "fetch_wait", "blame": "port=8431"}, 0.6),
        ("accelerator_up", {"chip": "0"}, 1.0),
    ]
    digest = digest_from_series(series)
    assert digest["phases"]["fetch_wait"] == {"p99": 0.01, "max": 0.5}
    assert digest["slowest"] == {"seconds": 0.6, "phase": "fetch_wait",
                                 "blame": "port=8431"}
    assert digest_from_series([("accelerator_up", {}, 1.0)]) == {}


def test_digest_from_series_extracts_host_signals():
    """ISSUE 10: the strongest kts_host_* signals ride the digest so
    the lens can baseline them and doctor can print the joined verdict."""
    series = [
        ("kts_host_pressure_share",
         {"resource": "memory", "kind": "full", "window": "avg10"}, 18.0),
        ("kts_host_pressure_share",
         {"resource": "memory", "kind": "full", "window": "avg60"}, 9.0),
        ("kts_host_pressure_share",
         {"resource": "cpu", "kind": "some", "window": "avg10"}, 2.0),
        ("kts_host_pressure_share",
         {"resource": "io", "kind": "full", "window": "avg10"}, 0.5),
        ("kts_host_nic_drop_rate", {}, 12.5),
        ("kts_host_cpu_throttle_rate", {}, 1.5),
        ("accelerator_up", {"chip": "0"}, 1.0),
    ]
    digest = digest_from_series(series)
    assert digest["host"] == {
        "mem_full_avg10": 18.0,   # avg60 deliberately not harvested
        "cpu_some_avg10": 2.0,
        "io_full_avg10": 0.5,
        "nic_drop_rate": 12.5,
        "throttle_rate": 1.5,
    }


# -- scripted scoring --------------------------------------------------------

def _row(target, duty=50.0, up=1.0, steps=None, worker="0"):
    return ChipRow(key=(target, "s", worker, "0"), up=up, duty=duty,
                   mem_used=1e9, power=300.0, steps_per_s=steps)


def _frame(rows):
    return types.SimpleNamespace(
        rows={(r.key + (i,)): r for i, r in enumerate(rows)})


def _observe(lens, seq, now, targets, rows, reachable=None,
             fetch=None, digests=None):
    lens.observe(seq, now, targets,
                 reachable if reachable is not None
                 else {t: True for t in targets},
                 fetch or {}, _frame(rows), digests or {})


def test_anomaly_raises_once_journals_and_recovers():
    tracer = Tracer()
    lens = FleetLens(tracer=tracer, min_samples=3)
    target = "http://w0/metrics"
    now = 1000.0
    for seq in range(1, 7):
        _observe(lens, seq, now + seq * 10, [target],
                 [_row(target, duty=50.0)])
    # Duty collapses: anomaly raises exactly once over 3 bad refreshes.
    for seq in range(7, 10):
        _observe(lens, seq, now + seq * 10, [target],
                 [_row(target, duty=2.0)])
    events = tracer.events()["events"]
    raises = [e for e in events if e["kind"] == "fleet_anomaly"]
    assert len(raises) == 1
    assert raises[0]["attrs"]["target"] == target
    assert raises[0]["attrs"]["anomaly"] == "duty"
    rollup = lens.rollup()
    assert "duty" in rollup["targets"][target]["anomalous"]
    assert rollup["anomalies"][0]["kind"] == "duty"
    # Back to baseline: the EWMA re-centers and the anomaly clears with
    # a recovery event.
    for seq in range(10, 40):
        _observe(lens, seq, now + seq * 10, [target],
                 [_row(target, duty=2.0)])
    assert not lens.rollup()["targets"][target]["anomalous"]
    kinds = [e["kind"] for e in tracer.events()["events"]]
    assert "fleet_recovered" in kinds


def test_freshness_anomaly_for_target_missing_refreshes():
    tracer = Tracer()
    lens = FleetLens(tracer=tracer, miss_threshold=3)
    target = "w0.prom"
    _observe(lens, 1, 0.0, [target], [_row(target)])
    for seq in range(2, 6):
        _observe(lens, seq, seq * 10.0, [target], [],
                 reachable={target: False})
    rollup = lens.rollup()
    assert "freshness" in rollup["targets"][target]["anomalous"]
    raises = [e for e in tracer.events()["events"]
              if e["kind"] == "fleet_anomaly"]
    assert len(raises) == 1  # edge-detected, not per refresh
    assert raises[0]["attrs"]["anomaly"] == "freshness"
    # The unreachable target's last-known chips burn the freshness
    # budget: 1 chip bad for 4 of 5 refreshes.
    fresh = rollup["slo"]["freshness"]["windows"]["5m"]
    assert fresh["bad_ratio"] == pytest.approx(0.8)
    assert fresh["burn_rate"] > 1.0
    # It answers again: freshness clears.
    _observe(lens, 6, 60.0, [target], [_row(target)])
    assert "freshness" not in lens.rollup()["targets"][target]["anomalous"]


def test_straggler_objective_burns_on_low_ratio():
    lens = FleetLens(straggler_ratio=0.75)
    targets = ["a", "b"]
    for seq in range(1, 5):
        rows = [_row("a", steps=10.0, worker="0"),
                _row("b", steps=9.5, worker="1")]
        _observe(lens, seq, seq * 10.0, targets, rows)
    state = lens.rollup()["slo"]["straggler"]["windows"]["5m"]
    assert state["bad_ratio"] == 0.0
    # Worker b collapses to 20% of a's rate: every refresh burns.
    for seq in range(5, 9):
        rows = [_row("a", steps=10.0, worker="0"),
                _row("b", steps=2.0, worker="1")]
        _observe(lens, seq, seq * 10.0, targets, rows)
    state = lens.rollup()["slo"]["straggler"]["windows"]["5m"]
    assert state["bad_ratio"] == pytest.approx(0.5)  # 4 of 8 refreshes
    assert state["burn_rate"] == pytest.approx(10.0)  # 5% budget


def test_host_pressure_anomaly_raises_from_flat_zero():
    """ISSUE 10: host_* signals are exempt from the first-activity
    re-seed (like stale_fraction) — a memory full-stall share going
    0 -> 18 IS the anomaly, not a new operating point — and the raise
    journals a host_pressure-kind fleet_anomaly event."""
    tracer = Tracer()
    lens = FleetLens(tracer=tracer, min_samples=3)
    target = "http://w0/metrics"
    host = {"mem_full_avg10": 0.0, "cpu_some_avg10": 1.0,
            "io_full_avg10": 0.0, "nic_drop_rate": 0.0,
            "throttle_rate": 0.0}
    for seq in range(1, 9):
        _observe(lens, seq, seq * 10.0, [target], [_row(target)],
                 digests={target: {"host": dict(host)}})
    stalled = dict(host, mem_full_avg10=18.0)
    _observe(lens, 9, 90.0, [target], [_row(target)],
             digests={target: {"host": stalled}})
    rollup = lens.rollup()
    assert "host_mem_stall" in rollup["targets"][target]["anomalous"]
    raises = [e for e in tracer.events()["events"]
              if e["kind"] == "fleet_anomaly"]
    assert len(raises) == 1
    assert raises[0]["attrs"]["anomaly"] == "host_mem_stall"
    # The digest (with its host values) rides the rollup for doctor's
    # joined verdict.
    assert rollup["targets"][target]["digest"]["host"][
        "mem_full_avg10"] == 18.0
    # Counter series carries the host kind.
    builder = SnapshotBuilder()
    lens.contribute(builder)
    text = builder.build().render()
    anomalies = labeled(text, "kts_fleet_anomalies_total")
    assert anomalies[(("kind", "host_mem_stall"),
                      ("target", target))] == 1.0


def test_host_anomaly_does_not_trigger_burst_arm_hook():
    """The burst auto-arm hook is power/duty-shaped only: a host
    pressure anomaly must not arm the power sampler."""
    armed = []
    lens = FleetLens(min_samples=2)
    lens.arm_hook = lambda target, kind, z: armed.append(kind)
    target = "t"
    host = {"mem_full_avg10": 0.0}
    for seq in range(1, 6):
        _observe(lens, seq, seq * 10.0, [target], [_row(target)],
                 digests={target: {"host": dict(host)}})
    _observe(lens, 6, 60.0, [target], [_row(target)],
             digests={target: {"host": {"mem_full_avg10": 25.0}}})
    assert "host_mem_stall" in lens.rollup()["targets"][target]["anomalous"]
    assert armed == []


def test_host_signal_vanishing_clears_latched_anomaly():
    """A daemon restarted with --no-host-stats stops exporting host
    signals; its latched host anomaly must clear with the data."""
    lens = FleetLens(min_samples=2)
    target = "t"
    for seq in range(1, 5):
        _observe(lens, seq, seq * 10.0, [target], [_row(target)],
                 digests={target: {"host": {"nic_drop_rate": 0.0}}})
    _observe(lens, 5, 50.0, [target], [_row(target)],
             digests={target: {"host": {"nic_drop_rate": 500.0}}})
    assert "host_nic_drops" in lens.rollup()["targets"][target]["anomalous"]
    # Empty digest replaces (the restart case): signal gone, kind clears.
    _observe(lens, 6, 60.0, [target], [_row(target)],
             digests={target: {}})
    assert not lens.rollup()["targets"][target]["anomalous"]


def test_slow_node_attribution_picks_worst_digest():
    lens = FleetLens()
    digests = {
        "a": {"slowest": {"seconds": 0.02, "phase": "env_round",
                          "blame": "device=1"}},
        "b": {"slowest": {"seconds": 0.9, "phase": "fetch_wait",
                          "blame": "port=8431"}},
    }
    _observe(lens, 1, 0.0, ["a", "b"], [_row("a"), _row("b")],
             digests=digests)
    worst = lens.rollup()["attribution"]
    assert worst["target"] == "b"
    assert worst["phase"] == "fetch_wait"
    assert worst["blame"] == "port=8431"
    # Contributed as the kts_fleet_worst_tick_seconds gauge.
    builder = SnapshotBuilder()
    lens.contribute(builder)
    text = builder.build().render()
    gauges = labeled(text, "kts_fleet_worst_tick_seconds")
    assert gauges == {(("phase", "fetch_wait"), ("target", "b")): 0.9}


def test_scoring_is_deterministic_under_seeded_inputs():
    """Acceptance: identical scripted inputs produce identical baselines,
    anomalies and burn state — no wall clock, no randomness."""
    import random

    def run():
        rng = random.Random(42)
        lens = FleetLens(min_samples=4)
        targets = ["a", "b"]
        for seq in range(1, 30):
            rows = [_row("a", duty=50 + rng.uniform(-1, 1),
                         steps=10 + rng.uniform(-0.1, 0.1), worker="0"),
                    _row("b", duty=(50 if seq < 20 else 5.0),
                         steps=10.0, worker="1")]
            _observe(lens, seq, seq * 10.0, targets, rows,
                     fetch={"a": 0.01 + rng.uniform(0, 0.001),
                            "b": 0.01})
        return lens.rollup()

    first, second = run(), run()
    assert first == second
    # b's duty collapse was flagged (the live flag adapts and clears
    # over sustained shifts; the anomaly ring keeps the incident).
    assert any(r["target"] == "b" and r["kind"] == "duty"
               for r in first["anomalies"])


def test_anomaly_raise_clear_has_hysteresis():
    """Review fix: clearing requires z to fall below HALF the raise
    threshold — a signal oscillating just around the threshold latches
    one incident instead of flapping raise/clear pairs into the journal
    and inflating the edge-counted incident counter."""
    tracer = Tracer()
    lens = FleetLens(tracer=tracer, min_samples=3)
    target = "w0"
    for seq in range(1, 7):
        _observe(lens, seq, seq * 10.0, [target], [_row(target, duty=50.0)])
    state = lens._targets[target]
    baseline = state.baselines["duty"]
    # Oscillate the reading so |z| alternates just above and just below
    # the threshold (4), but never under the clear threshold (2).
    for seq in range(7, 17):
        sd = max((baseline.var ** 0.5), 0.02 * abs(baseline.mean), 1.0)
        offset = (4.5 if seq % 2 else 3.5) * sd
        _observe(lens, seq, seq * 10.0, [target],
                 [_row(target, duty=baseline.mean - offset)])
    events = tracer.events()["events"]
    assert sum(1 for e in events if e["kind"] == "fleet_anomaly") == 1
    assert not any(e["kind"] == "fleet_recovered" for e in events)
    assert "duty" in lens.rollup()["targets"][target]["anomalous"]


def test_anomaly_clears_when_its_signal_stops_being_reported():
    """Review fix: a 'steps' anomaly raised during job teardown must
    clear once the step-rate series vanish from the exposition — a
    latched anomaly on data that no longer exists would page forever."""
    tracer = Tracer()
    lens = FleetLens(tracer=tracer, min_samples=3)
    target = "w0"
    for seq in range(1, 7):
        _observe(lens, seq, seq * 10.0, [target],
                 [_row(target, steps=100.0)])
    _observe(lens, 7, 70.0, [target], [_row(target, steps=1.0)])
    assert "steps" in lens.rollup()["targets"][target]["anomalous"]
    # The job is gone: no step series at all this refresh.
    _observe(lens, 8, 80.0, [target], [_row(target, steps=None)])
    assert "steps" not in lens.rollup()["targets"][target]["anomalous"]
    kinds = [e["kind"] for e in tracer.events()["events"]]
    assert kinds.count("fleet_recovered") == 1


def test_flat_at_zero_baselines_do_not_flag_job_start():
    """Review fix: an idle slice (duty/power/HBM flat at exactly zero
    through warmup) must not flag every target the moment a job starts.
    Bounded-scale signals get absolute sd floors; unbounded ones
    re-seed on first activity WITH the warmup gate re-armed, so the
    production min_samples window covers the post-launch ramp.
    stale_fraction keeps firing from zero — nonzero-from-zero IS its
    anomaly."""
    lens = FleetLens()  # production defaults: the claim under test
    target = "w0"
    for seq in range(1, 8):
        _observe(lens, seq, seq * 10.0, [target],
                 [ChipRow(key=(target, "s", "0", "0"), up=1.0, duty=0.0,
                          mem_used=0.0, power=0.0, steps_per_s=0.0)])
    # Job starts and RAMPS over several refreshes (model loading: HBM
    # doubling refresh to refresh, duty climbing) — the re-seed resets
    # the warmup gate, so the ramp must not z-explode against the
    # re-seeded zero-variance point either.
    ramp = [(20.0, 1e10, 100.0, 2.0), (45.0, 2e10, 180.0, 5.0),
            (70.0, 4e10, 250.0, 8.0), (95.0, 8e10, 300.0, 10.0)]
    for i, (duty, hbm, power, steps) in enumerate(ramp):
        _observe(lens, 8 + i, 80.0 + i * 10, [target],
                 [ChipRow(key=(target, "s", "0", "0"), up=1.0, duty=duty,
                          mem_used=hbm, power=power, steps_per_s=steps)])
        assert lens.rollup()["targets"][target]["anomalous"] == {}, \
            f"ramp step {i} falsely flagged"
    # ...while a chip going stale from the same flat-zero history still
    # fires (the floored signal's anomaly is exactly zero -> nonzero).
    _observe(lens, 12, 120.0, [target],
             [ChipRow(key=(target, "s", "0", "0"), up=0.0, duty=95.0,
                      mem_used=8e10, power=300.0, steps_per_s=10.0),
              ChipRow(key=(target, "s", "0", "1"), up=1.0, duty=95.0,
                      mem_used=8e10, power=300.0, steps_per_s=10.0)])
    assert "stale_fraction" in lens.rollup()["targets"][target]["anomalous"]


def test_attribution_drops_dead_targets_stale_digest():
    """Review fix: a crashed node's frozen pre-crash digest must not
    pin worst-node attribution forever while live nodes' rings age
    their own maxima out."""
    lens = FleetLens(miss_threshold=3)
    digests = {
        "dead": {"slowest": {"seconds": 9.9, "phase": "fetch_wait",
                             "blame": "port=1"}},
        "live": {"slowest": {"seconds": 0.1, "phase": "env_round",
                             "blame": "device=0"}},
    }
    _observe(lens, 1, 0.0, ["dead", "live"],
             [_row("dead"), _row("live")], digests=digests)
    assert lens.rollup()["attribution"]["target"] == "dead"
    for seq in range(2, 6):
        _observe(lens, seq, seq * 10.0, ["dead", "live"],
                 [_row("live")], reachable={"dead": False, "live": True},
                 digests={"live": digests["live"]})
    worst = lens.rollup()["attribution"]
    assert worst["target"] == "live"
    # An answered target with NO digest (restarted under --no-trace)
    # replaces its stale one instead of retaining it.
    _observe(lens, 6, 60.0, ["dead", "live"],
             [_row("dead"), _row("live")],
             digests={"dead": {}, "live": digests["live"]})
    assert lens.rollup()["attribution"]["target"] == "live"


def test_evict_drops_departed_target_state():
    lens = FleetLens(min_samples=2, miss_threshold=1)
    _observe(lens, 1, 0.0, ["a", "b"], [_row("a"), _row("b")])
    _observe(lens, 2, 10.0, ["a", "b"], [_row("a")],
             reachable={"a": True, "b": False})
    assert "b" in lens.rollup()["targets"]
    assert any(k[0] == "b" for k in lens._anomalies_total)
    lens.evict({"a"})
    rollup = lens.rollup()
    assert set(rollup["targets"]) == {"a"}
    assert not any(k[0] == "b" for k in lens._anomalies_total)


# -- daemon-side digest export ----------------------------------------------

def test_poll_exports_flight_recorder_digest():
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=2), reg, deadline=5.0)
    loop.tick()
    loop.tick()  # tick 2's snapshot carries tick 1's fold
    loop.stop()
    text = reg.snapshot().render()
    phases = labeled(text, schema.TICK_PHASE_SECONDS.name)
    assert phases, "digest absent with tracing enabled"
    recorded = {dict(k)["phase"] for k in phases}
    assert {"fold", "plan_write", "publish"} <= recorded
    assert {dict(k)["quantile"] for k in phases} == {"p50", "p99", "max"}
    slowest = labeled(text, schema.SLOWEST_TICK_SECONDS.name)
    (labels,) = slowest
    assert dict(labels)["phase"]  # a worst phase is always named


def test_poll_digest_absent_under_no_trace():
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, deadline=5.0,
                    tracer=Tracer(enabled=False))
    loop.tick()
    loop.tick()
    loop.stop()
    text = reg.snapshot().render()
    assert values(text, schema.TICK_PHASE_SECONDS.name) == []
    assert values(text, schema.SLOWEST_TICK_SECONDS.name) == []


# -- hub integration ---------------------------------------------------------

def _digest_target(tmp_path, name, slowest_phase="fetch_wait",
                   slowest_s=0.5, blame="port=8431"):
    builder = SnapshotBuilder()
    builder.add(schema.DEVICE_UP, 1.0,
                [("chip", "0"), ("worker", name), ("slice", "s")])
    builder.add(schema.POWER, 100.0,
                [("chip", "0"), ("worker", name), ("slice", "s")])
    builder.add(schema.TICK_PHASE_SECONDS, slowest_s,
                [("phase", slowest_phase), ("quantile", "p99")])
    builder.add(schema.SLOWEST_TICK_SECONDS, slowest_s,
                [("phase", slowest_phase), ("blame", blame)])
    path = tmp_path / f"{name}.prom"
    path.write_text(builder.build().render())
    return str(path)


def test_hub_serves_debug_fleet_and_gauges(tmp_path):
    slow = _digest_target(tmp_path, "slow", slowest_s=0.8)
    quick = _digest_target(tmp_path, "quick", slowest_phase="env_round",
                           slowest_s=0.002, blame="device=0")
    hub = Hub([slow, quick])
    server = MetricsServer(hub.registry, host="127.0.0.1", port=0,
                           trace_provider=hub.tracer,
                           fleet_provider=hub.fleet)
    server.start()
    try:
        hub.refresh_once()
        hub.refresh_once()
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/debug/fleet",
            timeout=5).read()
        payload = json.loads(body)
        assert payload["enabled"] is True
        assert set(payload["targets"]) == {slow, quick}
        assert payload["attribution"]["target"] == slow
        assert payload["attribution"]["blame"] == "port=8431"
        # The digest cached on the ingest entry survives the body-cache
        # hit on refresh 2 (series_dicts were dropped after refresh 1).
        assert payload["targets"][slow]["digest"]["slowest"][
            "phase"] == "fetch_wait"
        text = hub.registry.snapshot().render()
        assert values(text, "kts_fleet_targets_anomalous") == [0.0]
        burns = labeled(text, "kts_fleet_slo_burn_rate")
        assert {dict(k)["objective"] for k in burns} == \
            {"freshness", "straggler"}
        assert {dict(k)["window"] for k in burns} == {"5m", "1h"}
        worst = labeled(text, "kts_fleet_worst_tick_seconds")
        assert worst == {(("phase", "fetch_wait"),
                          ("target", slow)): 0.8}
        # The landing page advertises the endpoint.
        landing = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/", timeout=5).read().decode()
        assert "/debug/fleet" in landing
    finally:
        server.stop()
        hub.stop()


def test_hub_no_fleet_lens_disables_endpoint_and_gauges(tmp_path):
    target = _digest_target(tmp_path, "w0")
    hub = Hub([target], fleet_lens=False)
    server = MetricsServer(hub.registry, host="127.0.0.1", port=0,
                           trace_provider=hub.tracer,
                           fleet_provider=hub.fleet)
    server.start()
    try:
        hub.refresh_once()
        assert hub.fleet is None
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/debug/fleet", timeout=5)
        assert err.value.code == 404
        text = hub.registry.snapshot().render()
        assert values(text, "kts_fleet_slo_burn_rate") == []
        landing = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/", timeout=5).read().decode()
        assert "/debug/fleet" not in landing
    finally:
        server.stop()
        hub.stop()


def test_hub_fleet_state_evicts_with_target_churn(tmp_path):
    a = _digest_target(tmp_path, "a")
    b = _digest_target(tmp_path, "b")
    current = [[a, b]]
    hub = Hub([], targets_provider=lambda: list(current[0]))
    try:
        hub.refresh_once()
        assert set(hub.fleet.rollup()["targets"]) == {a, b}
        current[0] = [a]
        hub.refresh_once()
        assert set(hub.fleet.rollup()["targets"]) == {a}
    finally:
        hub.stop()


def test_hub_cli_rejects_bad_slo_flags(capsys):
    with pytest.raises(SystemExit):
        from kube_gpu_stats_tpu import hub as hub_mod

        hub_mod.main(["http://x/metrics", "--once",
                      "--slo-freshness-target", "1.5"])
    capsys.readouterr()


# -- doctor --fleet ----------------------------------------------------------

def _canned_rollup():
    return {
        "enabled": True,
        "seq": 42,
        "targets": {
            "http://w0:9400/metrics": {
                "anomalous": {},
                "signals": {},
            },
            "http://w3:9400/metrics": {
                "anomalous": {"stale_fraction": 9.5, "freshness": 3.0},
                "signals": {},
                "digest": {"slowest": {"seconds": 0.4,
                                       "phase": "env_round",
                                       "blame": "device=0"}},
            },
        },
        "anomalies": [],
        "slo": {
            "freshness": {"target": 0.99, "windows": {
                "5m": {"bad_ratio": 0.25, "burn_rate": 25.0,
                       "events": 30},
                "1h": {"bad_ratio": 0.02, "burn_rate": 2.0,
                       "events": 360},
            }},
            "straggler": {"target": 0.95, "ratio_min": 0.75, "windows": {
                "5m": {"bad_ratio": 0.0, "burn_rate": 0.0, "events": 30},
                "1h": {"bad_ratio": 0.0, "burn_rate": 0.0, "events": 360},
            }},
        },
        "attribution": {"target": "http://w7:9400/metrics",
                        "seconds": 1.2, "phase": "fetch_wait",
                        "blame": "port=8431"},
    }


def test_fleet_post_mortem_names_worst_node_anomalies_and_burn():
    status, detail, data = doctor.fleet_post_mortem(_canned_rollup())
    assert status == "warn"  # anomalies active + burn over budget
    assert "worst node: http://w7:9400/metrics" in detail
    assert "phase fetch_wait" in detail and "port=8431" in detail
    assert "http://w3:9400/metrics: freshness (3 refreshes missed), " \
           "stale_fraction (z=9.5) [worst phase env_round, device=0]" \
           in detail
    assert "freshness 1h=2x!/5m=25x!" in detail
    assert data["anomalous"] == {
        "http://w3:9400/metrics": {"stale_fraction": 9.5,
                                   "freshness": 3.0}}


def test_fleet_post_mortem_prints_joined_host_verdict():
    """ISSUE 10 acceptance shape: a target whose device-side anomaly
    co-occurs with host_* anomalies in the same refresh window gets
    the correlated sentence with CURRENT host values from its digest."""
    payload = _canned_rollup()
    target = "http://w7:9400/metrics"
    payload["targets"][target] = {
        "anomalous": {"fetch": 6.2, "host_mem_stall": 9.0,
                      "host_throttle": 4.5},
        "signals": {},
        "digest": {
            "slowest": {"seconds": 1.2, "phase": "fetch_wait",
                        "blame": "port=8431"},
            "host": {"mem_full_avg10": 18.0, "throttle_rate": 2.0},
        },
    }
    status, detail, data = doctor.fleet_post_mortem(payload)
    assert status == "warn"
    assert (f"{target}: fetch_wait spike co-occurs with "
            f"PSI memory full-stall 18.0% + "
            f"CPU thermal throttle 2.0 events/s") in detail
    assert data["correlated"][target]["phase"] == "fetch_wait"
    assert data["correlated"][target]["host_values"][
        "mem_full_avg10"] == 18.0


def test_fleet_post_mortem_host_only_anomaly_not_correlated():
    """Host pressure alone (no device-side anomaly, not the worst
    node) is listed but NOT claimed as the straggler's cause — the
    joined verdict requires co-occurrence."""
    payload = _canned_rollup()
    target = "http://w1:9400/metrics"
    payload["targets"][target] = {
        "anomalous": {"host_io_stall": 5.0},
        "signals": {},
        "digest": {"host": {"io_full_avg10": 7.0}},
    }
    status, detail, data = doctor.fleet_post_mortem(payload)
    assert status == "warn"
    assert "host_io_stall" in detail
    assert target not in data["correlated"]
    assert "co-occurs" not in [part for part in detail.split("; ")
                               if part.startswith(f"{target}: host")][0]


def test_fleet_post_mortem_worst_node_with_host_anomaly_correlates():
    """The attribution worst node needs no separate z-anomaly: its
    slow-phase attribution + a host anomaly is the co-occurrence."""
    payload = _canned_rollup()
    target = "http://w7:9400/metrics"
    payload["targets"][target] = {
        "anomalous": {"host_mem_stall": 12.0},
        "signals": {},
        "digest": {"host": {"mem_full_avg10": 22.5}},
    }
    status, detail, data = doctor.fleet_post_mortem(payload)
    assert f"{target}: fetch_wait spike co-occurs with " \
           f"PSI memory full-stall 22.5%" in detail
    assert data["correlated"][target]["phase"] == "fetch_wait"


def test_fleet_post_mortem_clean_fleet_is_ok():
    payload = _canned_rollup()
    payload["targets"]["http://w3:9400/metrics"]["anomalous"] = {}
    for objective in payload["slo"].values():
        for window in objective["windows"].values():
            window["burn_rate"] = 0.5
    status, detail, _ = doctor.fleet_post_mortem(payload)
    assert status == "ok"
    assert "worst node" in detail


def test_check_fleet_classifies_missing_and_unreachable():
    # No fleet provider wired: 404 classified, not a crash.
    server = MetricsServer(Registry(), host="127.0.0.1", port=0)
    server.start()
    try:
        result = doctor.check_fleet(f"http://127.0.0.1:{server.port}")
        assert result.status == "warn"
        assert "/debug/fleet" in result.detail
    finally:
        server.stop()
    import socket

    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    result = doctor.check_fleet(f"http://127.0.0.1:{port}")
    assert result.status == "fail"


def test_doctor_main_accepts_fleet_flag(tmp_path, capsys):
    import socket

    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    rc = doctor.main([
        "--fleet", "--url", f"http://127.0.0.1:{port}/metrics", "--json",
        "--backend", "mock", "--attribution", "off",
        "--sysfs-root", str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    rows = {c["name"]: c for c in out["checks"]}
    assert rows["fleet"]["status"] == "fail"
    assert rc == 1


# -- acceptance: fault injection across a multi-target hub -------------------

def test_fleet_lens_flags_slow_port_and_frozen_env_nodes(tmp_path):
    """Acceptance (ISSUE 5): one daemon with an injected slow libtpu
    port and one whose env reads freeze (device sampling fails) are
    BOTH flagged — doctor --fleet names the slow node with its worst
    phase (fetch_wait/rpc_port + blamed port) and the frozen node with
    its anomaly kind (stale_fraction), and the freshness burn gauges
    trip."""
    from kube_gpu_stats_tpu.collectors.composite import TpuCollector
    from kube_gpu_stats_tpu.collectors.libtpu import LibtpuClient
    from kube_gpu_stats_tpu.testing import FakeLibtpuServer, make_sysfs

    fake = FakeLibtpuServer(num_chips=2)
    fake.delay = 0.06  # the injected slow port
    fake.start()
    stacks = []
    try:
        with tempfile.TemporaryDirectory() as tmp:
            sysroot = pathlib.Path(tmp) / "sys"
            make_sysfs(sysroot, num_chips=2)
            tracer_a = Tracer()
            collector_a = TpuCollector(
                sysfs_root=str(sysroot),
                libtpu_client=LibtpuClient(ports=(fake.port,),
                                           rpc_timeout=5.0))
            collector_a.set_tracer(tracer_a)
            reg_a = Registry()
            loop_a = PollLoop(collector_a, reg_a, deadline=2.0,
                              pipeline_fetch=False, tracer=tracer_a)
            server_a = MetricsServer(reg_a, host="127.0.0.1", port=0)
            server_a.start()
            stacks.append((loop_a, server_a, collector_a))

            mock = MockCollector(num_devices=2)
            reg_b = Registry()
            loop_b = PollLoop(mock, reg_b, deadline=2.0)
            server_b = MetricsServer(reg_b, host="127.0.0.1", port=0)
            server_b.start()
            stacks.append((loop_b, server_b, None))

            url_a = f"http://127.0.0.1:{server_a.port}/metrics"
            url_b = f"http://127.0.0.1:{server_b.port}/metrics"
            hub = Hub([url_a, url_b], interval=60.0)
            hub.fleet.min_samples = 3  # short warmup for the test
            hub_server = MetricsServer(hub.registry, host="127.0.0.1",
                                       port=0, trace_provider=hub.tracer,
                                       fleet_provider=hub.fleet)
            hub_server.start()
            try:
                # Healthy baseline: both nodes ticking, six refreshes.
                for _ in range(6):
                    loop_a.tick()
                    loop_b.tick()
                    hub.refresh_once()
                assert not hub.fleet.rollup()["targets"][url_b][
                    "anomalous"]

                # Freeze node B's env reads: device 0's sample fails
                # from here on — the daemon marks it stale (up 0).
                real_sample = mock.sample

                def frozen_sample(device):
                    if device.device_id == "0":
                        raise RuntimeError("env read frozen")
                    return real_sample(device)

                mock.sample = frozen_sample
                for _ in range(3):
                    loop_a.tick()
                    loop_b.tick()
                    hub.refresh_once()

                rollup = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{hub_server.port}/debug/fleet",
                    timeout=5).read())
                # The frozen-env node is flagged with the right kind...
                assert "stale_fraction" in \
                    rollup["targets"][url_b]["anomalous"]
                # ...and the slow-port node is the fleet's worst node,
                # with the runtime-fetch phase and the blamed port.
                worst = rollup["attribution"]
                assert worst["target"] == url_a
                assert worst["phase"] in ("fetch_wait", "rpc_port")
                assert worst["blame"] == f"port={fake.port}"

                # The corresponding burn gauges trip: stale chips are
                # burning the freshness error budget.
                text = hub.registry.snapshot().render()
                burns = labeled(text, "kts_fleet_slo_burn_rate")
                fresh_5m = burns[(("objective", "freshness"),
                                  ("window", "5m"))]
                assert fresh_5m > 1.0, burns
                # At least the frozen node is anomalous (real timing
                # jitter on the slow node's fetch latency may flag it
                # too — that is working as intended, not noise).
                assert values(text,
                              "kts_fleet_targets_anomalous")[0] >= 1.0
                anomalies = labeled(text, "kts_fleet_anomalies_total")
                assert anomalies[(("kind", "stale_fraction"),
                                  ("target", url_b))] == 1.0

                # doctor --fleet names each target with phase/kind.
                result = doctor.check_fleet(
                    f"http://127.0.0.1:{hub_server.port}")
                assert result.status == "warn", result
                assert f"worst node: {url_a}" in result.detail
                assert worst["phase"] in result.detail
                assert f"port={fake.port}" in result.detail
                assert url_b in result.detail
                assert "stale_fraction" in result.detail
                # The anomaly landed in the shared journal with the
                # causing target and refresh seq.
                raises = [e for e in hub.tracer.events()["events"]
                          if e["kind"] == "fleet_anomaly"]
                assert any(e["attrs"]["target"] == url_b
                           and e["tick_seq"] > 6 for e in raises)
            finally:
                hub_server.stop()
                hub.stop()
    finally:
        for loop, server, collector in stacks:
            server.stop()
            loop.stop()
            if collector is not None:
                collector.close()
        fake.stop()


# -- refresh-cost budget (bench pin) ----------------------------------------

def test_fleet_score_cost_under_budget():
    """Acceptance: fleet_score_ms_per_refresh stays under its pinned
    budget with tracing enabled (the production configuration). The
    bench publishes the 64-worker figure; this pins an 8-worker shape
    with a hard ceiling generous enough for CI noise yet far below the
    refresh budget."""
    from kube_gpu_stats_tpu.bench import measure_hub_merge

    result = measure_hub_merge(workers=8, chips=2, refreshes=4)
    assert result is not None
    score = result["fleet_score_ms_per_refresh"]
    assert score is not None and score >= 0.0
    assert score < 25.0, f"fleet scoring {score} ms/refresh blows budget"


# -- burst-aware power baseline + auto-arm hook (ISSUE 8) --------------------

def test_digest_harvests_burst_max():
    series = [
        ("kts_power_burst_watts", {"chip": "0", "stat": "max"}, 450.0),
        ("kts_power_burst_watts", {"chip": "1", "stat": "max"}, 610.0),
        ("kts_power_burst_watts", {"chip": "0", "stat": "mean"}, 9999.0),
        ("accelerator_up", {"chip": "0"}, 1.0),
    ]
    digest = digest_from_series(series)
    # Max over chips, stat="max" rows only.
    assert digest["burst_max_watts"] == 610.0


def test_power_burst_signal_scored_and_raises():
    """A target whose sub-tick burst peak shifts regime raises a
    power_burst anomaly even while the tick-sampled power stays flat."""
    tracer = Tracer()
    lens = FleetLens(tracer=tracer, min_samples=3)
    target = "http://w0/metrics"
    for seq in range(1, 8):
        _observe(lens, seq, seq * 10.0, [target], [_row(target)],
                 digests={target: {"burst_max_watts": 310.0}})
    # The 1 Hz power stays 300 W (the _row default) but the sub-tick
    # peak triples: only the burst signal can see it.
    for seq in range(8, 11):
        _observe(lens, seq, seq * 10.0, [target], [_row(target)],
                 digests={target: {"burst_max_watts": 950.0}})
    rollup = lens.rollup()
    assert "power_burst" in rollup["targets"][target]["anomalous"]
    assert "power" not in rollup["targets"][target]["anomalous"]
    raises = [e for e in tracer.events()["events"]
              if e["kind"] == "fleet_anomaly"]
    assert [e["attrs"]["anomaly"] for e in raises] == ["power_burst"]


def test_arm_hook_fires_on_power_shaped_anomalies_only():
    armed = []
    lens = FleetLens(min_samples=3)
    lens.arm_hook = lambda target, kind, z: armed.append((target, kind))
    target = "w0"
    for seq in range(1, 8):
        _observe(lens, seq, seq * 10.0, [target], [_row(target, duty=50.0)])
    for seq in range(8, 11):
        _observe(lens, seq, seq * 10.0, [target], [_row(target, duty=2.0)])
    assert armed == [(target, "duty")]
    # An hbm-shaped anomaly must NOT arm (burst sampling answers power/
    # duty questions only).
    armed.clear()
    lens2 = FleetLens(min_samples=3)
    lens2.arm_hook = lambda target, kind, z: armed.append((target, kind))
    for seq in range(1, 8):
        _observe(lens2, seq, seq * 10.0, [target], [_row(target)])

    def hbm_row(used):
        row = _row(target)
        row.mem_used = used
        return row

    for seq in range(8, 11):
        _observe(lens2, seq, seq * 10.0, [target], [hbm_row(9e10)])
    assert lens2.rollup()["targets"][target]["anomalous"]
    assert armed == []


def test_arm_hook_crash_does_not_kill_observe():
    lens = FleetLens(min_samples=3)
    lens.arm_hook = lambda *a: (_ for _ in ()).throw(RuntimeError("boom"))
    target = "w0"
    for seq in range(1, 8):
        _observe(lens, seq, seq * 10.0, [target], [_row(target, duty=50.0)])
    for seq in range(8, 11):
        _observe(lens, seq, seq * 10.0, [target], [_row(target, duty=2.0)])
    assert "duty" in lens.rollup()["targets"][target]["anomalous"]


# -- interconnect localization (ISSUE 19) ------------------------------------

def _ici_digest(worker, rates, topology="4x1"):
    return {"ici": {"links": dict(rates), "worker": worker,
                    "topology": topology}}


def _ring4_digests(targets, sick=(), sick_rate=3e6, rate=3e7):
    """4 workers on a 4x1 torus, every local link at ``rate`` except
    the (worker, label) views named in ``sick``."""
    digests = {}
    for i, target in enumerate(targets):
        worker = str(i)
        links = {
            label: (sick_rate if (worker, label) in sick else rate)
            for label in ("x0", "x1")
        }
        digests[target] = _ici_digest(worker, links)
    return digests


def test_digest_from_series_extracts_ici_links():
    """Per-link ICI rates sum over the node's chips (chips share the
    physical links) and carry the worker/topology graph identity."""
    series = [
        (schema.ICI_BANDWIDTH.name,
         {"chip": "0", "link": "x0", "worker": "2",
          "topology": "4x1"}, 1e6),
        (schema.ICI_BANDWIDTH.name,
         {"chip": "1", "link": "x0", "worker": "2",
          "topology": "4x1"}, 2e6),
        (schema.ICI_BANDWIDTH.name,
         {"chip": "0", "link": "x1", "worker": "2",
          "topology": "4x1"}, 5e6),
        ("accelerator_up", {"chip": "0"}, 1.0),
    ]
    digest = digest_from_series(series)
    assert digest["ici"] == {
        "links": {"x0": 3e6, "x1": 5e6},
        "worker": "2",
        "topology": "4x1",
    }


def test_link_localizer_names_shared_link_not_endpoints():
    """Tentpole acceptance shape, unit-scale: both endpoint views of
    one edge collapse -> that edge (and only that edge) becomes the
    suspect, with journal events on the raise."""
    tracer = Tracer()
    lens = FleetLens(tracer=tracer)
    targets = [f"http://w{i}/metrics" for i in range(4)]
    rows = [_row(t, worker=str(i)) for i, t in enumerate(targets)]
    now = 0.0
    for seq in range(1, 10):
        now = seq * 10.0
        _observe(lens, seq, now, targets, rows,
                 digests=_ring4_digests(targets))
    assert lens.rollup()["links"]["suspects"] == {}
    # Link 1-2 degrades: worker 1 sees it as x1, worker 2 as x0.
    sick = (("1", "x1"), ("2", "x0"))
    for seq in range(10, 14):
        now = seq * 10.0
        _observe(lens, seq, now, targets, rows,
                 digests=_ring4_digests(targets, sick=sick))
    links = lens.rollup()["links"]
    assert list(links["suspects"]) == ["1-2"]
    verdict = links["suspects"]["1-2"]
    assert verdict["reason"].startswith("ici-rate")
    assert verdict["endpoints"] == ["1", "2"]
    assert verdict["drop"] > 0.8
    assert links["graph"] == {"kind": "torus", "topology": "4x1",
                              "nodes": 4, "links": 4}
    kinds = [e["kind"] for e in tracer.events()["events"]]
    assert "fleet_link_suspect" in kinds
    # The verdict's endpoints are explained targets for doctor's
    # suppression pass.
    assert lens.links.explained_targets() == {
        targets[1]: "1-2", targets[2]: "1-2"}
    # Gauges: suspect row at 1.0, the per-link baselines, link count.
    builder = SnapshotBuilder()
    lens.contribute(builder)
    text = builder.build().render()
    suspect = labeled(text, schema.FLEET_LINK_SUSPECT.name)
    key = (("link", "1-2"), ("reason", verdict["reason"]))
    assert suspect[key] == 1.0
    assert values(text, schema.FLEET_LINKS.name) == [4.0]
    baselines = labeled(text, schema.FLEET_LINK_BASELINE_BPS.name)
    # Edge stats average the two endpoint views of the same wire.
    assert baselines[(("link", "0-1"),)] == pytest.approx(3e7, rel=0.05)
    # Recovery: verdict clears with a journal event and the suspect
    # series drops to a 0.0 tombstone (history continuity).
    for seq in range(14, 20):
        now = seq * 10.0
        _observe(lens, seq, now, targets, rows,
                 digests=_ring4_digests(targets))
    assert lens.rollup()["links"]["suspects"] == {}
    kinds = [e["kind"] for e in tracer.events()["events"]]
    assert "fleet_link_cleared" in kinds
    rows_after = lens.link_history_rows()
    assert ("1-2", verdict["reason"], 0.0) in rows_after
    assert all(value == 0.0 for _l, _r, value in rows_after)


def test_link_localizer_one_sided_view_never_accuses():
    """Only ONE endpoint's view of the edge collapses (a local NIC/DMA
    problem, not the shared link): no candidate, no suspect."""
    lens = FleetLens()
    targets = [f"http://w{i}/metrics" for i in range(4)]
    rows = [_row(t, worker=str(i)) for i, t in enumerate(targets)]
    for seq in range(1, 10):
        _observe(lens, seq, seq * 10.0, targets, rows,
                 digests=_ring4_digests(targets))
    for seq in range(10, 16):
        _observe(lens, seq, seq * 10.0, targets, rows,
                 digests=_ring4_digests(targets, sick=(("1", "x1"),)))
    assert lens.rollup()["links"]["suspects"] == {}


def test_link_localizer_node_fault_blames_no_link():
    """Every link incident to worker 1 collapses from both ends: the
    common factor is the NODE, so accusing any single link would be
    wrong — the disambiguation pass drops all of its candidate edges."""
    lens = FleetLens()
    targets = [f"http://w{i}/metrics" for i in range(4)]
    rows = [_row(t, worker=str(i)) for i, t in enumerate(targets)]
    for seq in range(1, 10):
        _observe(lens, seq, seq * 10.0, targets, rows,
                 digests=_ring4_digests(targets))
    # Worker 1's whole interconnect is sick: its own two views AND the
    # matching far-end views (0-1 seen from 0, 1-2 seen from 2).
    sick = (("1", "x0"), ("1", "x1"), ("0", "x1"), ("2", "x0"))
    for seq in range(10, 16):
        _observe(lens, seq, seq * 10.0, targets, rows,
                 digests=_ring4_digests(targets, sick=sick))
    assert lens.rollup()["links"]["suspects"] == {}
