"""DaemonSamplerPool: futures semantics and the exit-hang regression — a
sample wedged inside a sick backend must never make the process unkillable
(ThreadPoolExecutor's atexit hook would join the stuck worker forever)."""

import concurrent.futures
import subprocess
import sys
import threading

import pytest

from kube_gpu_stats_tpu.workers import DaemonSamplerPool


def test_submit_result_roundtrip():
    pool = DaemonSamplerPool(2)
    try:
        futures = [pool.submit(lambda x: x * x, i) for i in range(10)]
        assert [f.result(timeout=5) for f in futures] == [i * i for i in range(10)]
    finally:
        pool.shutdown(wait=True)


def test_exceptions_delivered_to_waiter():
    pool = DaemonSamplerPool(1)
    try:
        future = pool.submit(lambda: 1 / 0)
        with pytest.raises(ZeroDivisionError):
            future.result(timeout=5)
    finally:
        pool.shutdown(wait=True)


def test_timeout_and_late_completion():
    release = threading.Event()
    pool = DaemonSamplerPool(1)
    try:
        future = pool.submit(release.wait, 10)
        with pytest.raises(concurrent.futures.TimeoutError):
            future.result(timeout=0.05)
        assert not future.cancel()  # already running
        release.set()
        assert future.result(timeout=5) is True
    finally:
        pool.shutdown(wait=True)


def test_cancel_queued_work_on_shutdown():
    started = threading.Event()
    block = threading.Event()

    def task():
        started.set()
        return block.wait(10)

    pool = DaemonSamplerPool(1)
    first = pool.submit(task)
    assert started.wait(5)  # running, so shutdown cannot cancel it
    queued = pool.submit(lambda: "never")
    pool.shutdown(wait=False, cancel_futures=True)
    assert queued.cancelled()
    block.set()
    assert first.result(timeout=5) is True
    with pytest.raises(RuntimeError):
        pool.submit(lambda: None)


def test_shutdown_idempotent_with_wedged_worker():
    """Second shutdown() must not trip over the first one's sentinel left
    unconsumed by a wedged worker (Daemon.stop is 'idempotent-ish')."""
    block = threading.Event()
    started = threading.Event()
    pool = DaemonSamplerPool(1)

    def wedge():
        started.set()
        block.wait(30)

    pool.submit(wedge)
    assert started.wait(5)
    pool.shutdown(wait=False, cancel_futures=True)
    pool.shutdown(wait=False, cancel_futures=True)  # must not raise
    block.set()


def test_process_exits_with_wedged_sampler():
    """A PollLoop whose backend wedges forever: the tick deadline abandons
    the sample, and interpreter exit must not join the stuck worker."""
    script = """
import time
from kube_gpu_stats_tpu.collectors import Collector, Device
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry

class Wedged(Collector):
    name = "wedged"
    def discover(self):
        return [Device(index=0, device_id="0", device_path="/dev/accel0",
                       accel_type="tpu")]
    def sample(self, device):
        time.sleep(3600)

loop = PollLoop(Wedged(), Registry(), deadline=0.05)
loop.tick()
loop.stop()
print("CLEAN-EXIT", flush=True)
"""
    proc = subprocess.run(
        [sys.executable, "-c", script],
        capture_output=True, text=True, timeout=30,
    )
    assert "CLEAN-EXIT" in proc.stdout
    assert proc.returncode == 0


def test_shutdown_default_never_blocks_on_wedged_worker():
    """Advisor finding: the old wait=True default joined without timeout —
    the exact hang the pool exists to avoid. The default must return even
    while a worker is wedged; wait=True must honor its join timeout."""
    import threading
    import time

    from kube_gpu_stats_tpu.workers import DaemonSamplerPool

    release = threading.Event()
    pool = DaemonSamplerPool(max_workers=1, thread_name_prefix="wedge")
    pool.submit(release.wait)  # wedges the single worker
    t0 = time.monotonic()
    pool.shutdown()  # default: no join at all
    assert time.monotonic() - t0 < 1.0

    pool2 = DaemonSamplerPool(max_workers=1, thread_name_prefix="wedge2")
    pool2.submit(release.wait)
    t0 = time.monotonic()
    pool2.shutdown(wait=True, timeout=0.2)
    assert time.monotonic() - t0 < 2.0
    release.set()


def test_shutdown_reports_clean_vs_wedged_drain(caplog):
    """wait=True returns True on a clean drain, False (with a warning) when
    the deadline expires with a worker still wedged (round-2 advisor
    finding: callers couldn't tell the two apart)."""
    import logging
    import threading

    from kube_gpu_stats_tpu.workers import DaemonSamplerPool

    pool = DaemonSamplerPool(max_workers=1)
    pool.submit(lambda: None).result(timeout=5)
    assert pool.shutdown(wait=True, timeout=5.0) is True

    wedge = threading.Event()
    pool2 = DaemonSamplerPool(max_workers=1)
    pool2.submit(wedge.wait)
    with caplog.at_level(logging.WARNING, logger="kube_gpu_stats_tpu.workers"):
        assert pool2.shutdown(wait=True, timeout=0.2) is False
    assert any("wedged" in r.message for r in caplog.records)
    wedge.set()  # let the worker exit

    pool3 = DaemonSamplerPool(max_workers=1)
    assert pool3.shutdown(wait=False) is False  # asked not to know


def test_periodic_refresher_survives_raising_subclass():
    """Review finding: an exception escaping refresh_once killed the
    watcher thread silently; containment now lives in the scaffold."""
    import threading
    import time

    from kube_gpu_stats_tpu.workers import PeriodicRefresher

    calls = []

    class Raising(PeriodicRefresher):
        def refresh_once(self):
            calls.append(1)
            raise RuntimeError("subclass bug")

    watcher = Raising(0.01, "raising-test")
    watcher.start()
    deadline = time.monotonic() + 5
    while len(calls) < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    watcher.stop()
    assert len(calls) >= 3  # kept refreshing after each crash
    assert watcher.consecutive_failures >= 3
