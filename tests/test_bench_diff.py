"""The perf ledger's CI gate (ISSUE 17): bench_diff must derive
per-field noise bands from the BENCH_r* history, fail --gate runs only
for PINNED fields drifting past their band in the bad direction, honor
run-scoped waivers in BENCH_WAIVERS.json, and stay report-only for
everything else — a perf regression should fail CI exactly like a
correctness regression, and an intentional one must be named in-tree."""

from __future__ import annotations

import json
import pathlib
import sys

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "tools"))

import bench_diff  # noqa: E402


def write_runs(tmp_path, histories: dict[str, list[float]]):
    """Lay down BENCH_r1..rN from per-field value sequences."""
    n = max(len(vals) for vals in histories.values())
    for i in range(n):
        line = {field: vals[i] for field, vals in histories.items()
                if i < len(vals)}
        (tmp_path / f"BENCH_r{i + 1}.json").write_text(json.dumps(line))


def test_bands_come_from_history_not_just_class_floors(tmp_path):
    # A field that historically steps ~40% run-to-run must get a ~40%
    # band (the median step), not the 25% class floor; a flat field
    # keeps the floor.
    write_runs(tmp_path, {
        "jittery_ms": [100.0, 140.0, 100.0, 140.0, 100.0],
        "steady_ms": [50.0, 50.5, 50.0, 50.5, 50.0],
    })
    history = [bench_diff.load_numeric(p)
               for _n, p in bench_diff.all_runs(tmp_path)[:-1]]
    bands = bench_diff.history_bands(history)
    assert bands["jittery_ms"] == pytest.approx(0.4, rel=0.2)
    assert bands["steady_ms"] == 0.25  # class floor


def test_gate_fails_pinned_regression_without_waiver(tmp_path):
    write_runs(tmp_path, {
        "delta_ingest_10k_ms_per_refresh": [150.0, 155.0, 150.0, 152.0,
                                            400.0],
        "unpinned_thing_ms": [10.0, 10.0, 10.0, 10.0, 99.0],
    })
    lines, failures = bench_diff.diff(tmp_path, gate=True)
    assert len(failures) == 1
    assert "delta_ingest_10k_ms_per_refresh" in failures[0]
    assert "no waiver" in failures[0]
    # The unpinned field is flagged in the report but never gates.
    assert any("unpinned_thing_ms" in line and "noise band" in line
               for line in lines)
    # Report-only mode sees the same drift but fails nothing.
    _lines, failures = bench_diff.diff(tmp_path, gate=False)
    assert failures == []


def test_gate_honors_run_scoped_waiver(tmp_path):
    write_runs(tmp_path, {
        "scrape_p99_ms": [3.0, 3.1, 3.0, 3.2, 9.0],
    })
    (tmp_path / bench_diff.WAIVERS).write_text(json.dumps({"waivers": [
        {"field": "scrape_p99_ms", "run": "r5",
         "reason": "new TLS handshake benchmarked in; accepted"},
    ]}))
    lines, failures = bench_diff.diff(tmp_path, gate=True)
    assert failures == []
    assert any("WAIVED" in line for line in lines)
    # The same waiver pointed at a DIFFERENT run does not apply (and is
    # reported stale).
    (tmp_path / bench_diff.WAIVERS).write_text(json.dumps({"waivers": [
        {"field": "scrape_p99_ms", "run": "r4", "reason": "stale"},
    ]}))
    lines, failures = bench_diff.diff(tmp_path, gate=True)
    assert len(failures) == 1
    assert any("stale waiver" in line for line in lines)


def test_pinned_improvement_never_fails(tmp_path):
    # Ingest getting faster and max_hz rising are improvements —
    # outside the band, flagged in the report, never a gate failure.
    write_runs(tmp_path, {
        "delta_ingest_10k_ms_per_refresh": [300.0, 310.0, 305.0, 311.0,
                                            132.0],
        "max_hz": [8000.0, 8100.0, 8050.0, 8200.0, 16000.0],
    })
    _lines, failures = bench_diff.diff(tmp_path, gate=True)
    assert failures == []


def test_max_hz_gates_on_falls_not_rises(tmp_path):
    write_runs(tmp_path, {
        "max_hz": [8000.0, 8100.0, 8050.0, 8200.0, 2000.0],
    })
    _lines, failures = bench_diff.diff(tmp_path, gate=True)
    assert len(failures) == 1 and "max_hz" in failures[0]


def test_malformed_waiver_is_an_error_not_a_skip(tmp_path):
    write_runs(tmp_path, {"scrape_p99_ms": [3.0, 3.0, 3.0, 3.0, 9.0]})
    (tmp_path / bench_diff.WAIVERS).write_text(
        json.dumps({"waivers": [{"field": "scrape_p99_ms"}]}))
    with pytest.raises(ValueError):
        bench_diff.diff(tmp_path, gate=True)


def test_main_exit_codes(tmp_path, capsys):
    write_runs(tmp_path, {
        "hub_merge_64w_cold_ms": [60.0, 62.0, 61.0, 60.0, 300.0],
    })
    assert bench_diff.main(["--root", str(tmp_path)]) == 0
    assert bench_diff.main(["--root", str(tmp_path), "--gate"]) == 1
    err = capsys.readouterr().err
    assert "GATE FAILURE" in err and bench_diff.WAIVERS in err


def test_repo_history_gate_is_green():
    """The checked-in BENCH_r* sequence must pass its own gate — `make
    ci` runs exactly this (a PR landing a regressing BENCH file must
    also land its waiver)."""
    _lines, failures = bench_diff.diff(ROOT, gate=True)
    assert failures == [], failures
