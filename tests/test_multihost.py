"""Multi-host slice coverage without a cluster (SURVEY.md §4: N exporter
instances, distinct worker/topology labels; the union of scrapes covers
every chip exactly once — BASELINE.json configs[3]).

Per-node DaemonSet pods are independent — that independence is what makes
the design testable: worker identity comes only from labels, so N local
exporters model N hosts faithfully.
"""

import re
import time
import urllib.request

import pytest
from flake import retry_once_on_box_noise

from kube_gpu_stats_tpu.collectors.composite import TpuCollector
from kube_gpu_stats_tpu.collectors.libtpu import LibtpuClient
from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.exposition import MetricsServer
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry

from kube_gpu_stats_tpu.testing.libtpu_server import FakeLibtpuServer
from kube_gpu_stats_tpu.testing.sysfs_fixture import make_sysfs

_SERIES_RE = re.compile(r'^accelerator_up\{(.*)\} 1$', re.M)


def parse_up_series(text):
    out = []
    for match in _SERIES_RE.finditer(text):
        labels = dict(
            part.split("=", 1) for part in re.findall(r'(\w+="[^"]*")', match.group(1))
            for part in [part.replace('"', "")]
        )
        out.append(labels)
    return out


def worker_chip_pairs(text):
    pairs = []
    for line in text.splitlines():
        if line.startswith("accelerator_up{") and line.endswith(" 1"):
            worker = re.search(r'worker="([^"]*)"', line).group(1)
            chip = re.search(r'chip="([^"]*)"', line).group(1)
            slice_ = re.search(r'slice="([^"]*)"', line).group(1)
            pairs.append((slice_, worker, chip))
    return pairs


def test_v5p_256_slice_union_mock():
    """64 workers x 4 chips = 256: every (worker, chip) exactly once across
    the union of all per-node exports."""
    chips_per_host, hosts = 4, 64
    union = []
    for worker in range(hosts):
        reg = Registry()
        loop = PollLoop(
            MockCollector(num_devices=chips_per_host, accel_type="tpu-v5p"),
            reg,
            deadline=5.0,
            topology_labels={
                "slice": "v5p-256-slice",
                "worker": str(worker),
                "topology": "8x8x4",
            },
        )
        loop.tick()
        union.extend(worker_chip_pairs(reg.snapshot().render()))
        loop.stop()
    assert len(union) == 256
    assert len(set(union)) == 256  # exactly once
    assert {p[0] for p in union} == {"v5p-256-slice"}


def test_multihost_real_stack_http(tmp_path):
    """4 workers with real gRPC fake-libtpu backends + real HTTP scrapes."""
    hosts = 4
    servers, daemonish = [], []
    union = []
    try:
        for worker in range(hosts):
            libtpu = FakeLibtpuServer(num_chips=4).start()
            servers.append(libtpu)
            sysroot = tmp_path / f"worker{worker}"
            make_sysfs(sysroot, num_chips=4)
            reg = Registry()
            col = TpuCollector(
                sysfs_root=str(sysroot),
                libtpu_client=LibtpuClient(ports=(libtpu.port,), rpc_timeout=1.0),
                use_native=False,
            )
            loop = PollLoop(
                col, reg, deadline=5.0,
                topology_labels={"slice": "v5p-16", "worker": str(worker),
                                 "topology": "2x2x4"},
            )
            server = MetricsServer(reg, host="127.0.0.1", port=0)
            server.start()
            daemonish.append((loop, server))
            loop.tick()
            loop.tick()
            # Pipelined cadence: a rate needs two DISTINCT completed
            # fetches; wait for the second tick's fetch, then observe it.
            deadline = time.monotonic() + 5
            while (col.runtime_fetch_seq < 2
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            loop.tick()
        for loop, server in daemonish:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=5
            ) as resp:
                body = resp.read().decode()
            union.extend(worker_chip_pairs(body))
            # Each node exports ICI bandwidth for its local chips.
            assert body.count("accelerator_ici_link_bandwidth_bytes_per_second{") == 24
        assert len(union) == 16
        assert len(set(union)) == 16
    finally:
        for loop, server in daemonish:
            loop.stop()
            server.stop()
        for s in servers:
            s.stop()


# Known ~1/10 box-noise flake (ISSUE 12 satellite): the concurrent
# 64-stack budget assertion scales with CPU oversubscription but a
# co-tenant burst can still blow the scaled bound. One marked retry;
# a real regression fails twice and still fails the suite.
@retry_once_on_box_noise
def test_v5p_256_slice_real_stack_concurrent(tmp_path):
    """Round-1 verdict item 6 (done round 3): the 256-chip union claim at
    REAL stack depth — 64 exporter instances (real gRPC fake-libtpu
    backend, real sysfs fixture, real poll loop, real HTTP server) all
    running concurrently in one process. Asserts the union covers all
    64x4 = 256 (worker, chip) pairs exactly once AND every exporter's
    tick p50 stays under the 50 ms budget while the whole slice's worth
    of stacks contends. Ticks are phase-staggered at a short interval so
    contention resembles 64 independent 1 Hz loops, not a GIL stampede
    artifact; the whole test is wall-bounded well under 60 s.

    Budget realism: in production each exporter owns a whole host; here
    64 of them share this machine's cores. The hard 50 ms claim is
    asserted on a solo stack in this same process, and the concurrent
    bound is the budget scaled by CPU oversubscription (64 stacks / N
    usable cores) — so on a >=64-core box it degenerates to the true
    budget, while a 1-core CI box doesn't fail on physics."""
    import os
    import statistics
    import threading
    import time

    hosts, chips_per_host = 64, 4
    budget_ms = 50.0
    cpus = len(os.sched_getaffinity(0)) or 1
    concurrent_budget_ms = budget_ms * max(1.0, hosts / cpus)
    stacks = []  # (libtpu, loop, http, registry)
    try:
        for worker in range(hosts):
            libtpu = FakeLibtpuServer(num_chips=chips_per_host).start()
            sysroot = tmp_path / f"w{worker}"
            make_sysfs(sysroot, num_chips=chips_per_host)
            reg = Registry()
            col = TpuCollector(
                sysfs_root=str(sysroot),
                libtpu_client=LibtpuClient(ports=(libtpu.port,),
                                           rpc_timeout=2.0),
                use_native=True,
            )
            loop = PollLoop(
                col, reg, deadline=5.0,
                topology_labels={"slice": "v5p-256-slice",
                                 "worker": str(worker),
                                 "topology": "8x8x4"},
            )
            http = MetricsServer(reg, host="127.0.0.1", port=0)
            http.start()
            stacks.append((libtpu, loop, http, reg))

        p50s: dict[int, float] = {}
        errors: list[BaseException] = []
        barrier = threading.Barrier(hosts)

        def drive(worker: int) -> None:
            loop = stacks[worker][1]
            try:
                barrier.wait(timeout=30)
                loop.tick()  # warmup: first fetch + label-cache build
                durations = []
                interval = 0.20
                next_fire = time.monotonic() + (worker % 8) * 0.025
                for _ in range(6):
                    delay = next_fire - time.monotonic()
                    if delay > 0:
                        time.sleep(delay)
                    durations.append(loop.tick() * 1000.0)
                    next_fire += interval
                p50s[worker] = statistics.median(durations)
            except BaseException as exc:  # noqa: BLE001 - surfaced below
                errors.append(exc)

        start = time.monotonic()
        threads = [threading.Thread(target=drive, args=(w,), daemon=True)
                   for w in range(hosts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=45)
        assert not errors, errors[:3]
        assert len(p50s) == hosts, "some exporters never finished ticking"

        union = []
        for _, _, http, _ in stacks:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}/metrics", timeout=10
            ) as resp:
                union.extend(worker_chip_pairs(resp.read().decode()))
        elapsed = time.monotonic() - start
        assert len(union) == 256
        assert len(set(union)) == 256  # exactly once across the slice
        assert {p[0] for p in union} == {"v5p-256-slice"}
        worst = max(p50s.values())
        assert worst < concurrent_budget_ms, (
            f"worst per-exporter p50 {worst:.1f} ms over the "
            f"{concurrent_budget_ms:.0f} ms oversubscription-scaled budget "
            f"({hosts} stacks on {cpus} cores)")
        assert elapsed < 60, f"not wall-bounded: {elapsed:.0f}s"

        # The un-scaled 50 ms production claim, asserted where it is
        # physically meaningful: one stack ticking alone (per-host view).
        solo_loop = stacks[0][1]
        solo = statistics.median(solo_loop.tick() * 1000.0 for _ in range(7))
        assert solo < budget_ms, (
            f"solo per-host p50 {solo:.1f} ms over the {budget_ms} ms budget")
    finally:
        for libtpu, loop, http, _ in stacks:
            loop.stop()
            http.stop()
            libtpu.stop()


# Same box-noise class: 64 real HTTP servers + a deadlined hub fetch
# wave occasionally trip the refresh deadline on a starved box.
@retry_once_on_box_noise
def test_hub_aggregates_64_real_http_exporters():
    """The hub at slice width over REAL HTTP (the deterministic
    file-target variant lives in test_hub): 64 in-process exporter
    stacks, one hub refresh through the real concurrent fetch path,
    256-chip union exactly once with full rollups."""
    import time

    from kube_gpu_stats_tpu.hub import Hub

    hosts, chips_per_host = 64, 4
    stacks = []
    try:
        for worker in range(hosts):
            reg = Registry()
            loop = PollLoop(
                MockCollector(num_devices=chips_per_host,
                              accel_type="tpu-v5p"),
                reg, deadline=5.0,
                topology_labels={"slice": "v5p-256-slice",
                                 "worker": str(worker),
                                 "topology": "8x8x4"},
            )
            loop.tick()
            http = MetricsServer(reg, host="127.0.0.1", port=0)
            http.start()
            stacks.append((loop, http))
        targets = [f"http://127.0.0.1:{http.port}/metrics"
                   for _, http in stacks]
        hub = Hub(targets, fetch_timeout=10.0)
        try:
            start = time.monotonic()
            hub.refresh_once()
            wall = time.monotonic() - start
            text = hub.registry.snapshot().render()
        finally:
            hub.stop()
        pairs = worker_chip_pairs(text)
        assert len(pairs) == 256 and len(set(pairs)) == 256
        assert 'slice_chips{slice="v5p-256-slice"} 256' in text
        assert 'slice_workers{slice="v5p-256-slice"} 64' in text
        up_lines = [line for line in text.splitlines()
                    if line.startswith("slice_target_up")]
        assert len(up_lines) == 64
        assert all(line.endswith(" 1") for line in up_lines)
        # Generous wall bound: one refresh of a whole slice's HTTP
        # fetches must not approach the default 10 s cadence even on an
        # oversubscribed CI box.
        assert wall < 30, f"64-target HTTP refresh took {wall:.1f}s"
    finally:
        for loop, http in stacks:
            loop.stop()
            http.stop()


def test_embedded_to_hub_chain_on_virtual_mesh(tmp_path):
    """Round-4 verdict item 4: the FULL embedded->hub chain on >=8
    virtual devices — two child processes each run the sharded train
    step (data x model parallel over a forced-8-device CPU mesh) under
    an embedded exporter; a hub merges both into one slice view.
    Asserts: 8 per-device series sets per worker, the SPMD FLOPs split
    (global counter / device count) exact per chip, step histograms
    populated and summed across workers, 16 chips exactly once."""
    import os
    import select
    import subprocess
    import sys
    import time

    from kube_gpu_stats_tpu.hub import Hub
    from kube_gpu_stats_tpu.validate import parse_exposition

    child_src = (tmp_path / "embedded_worker.py")
    child_src.write_text(
        "import sys, time\n"
        "import jax\n"
        # sitecustomize force-registers the TPU plugin and ignores env;
        # the config update is what actually pins CPU (conftest rule).
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from kube_gpu_stats_tpu import embedded\n"
        "from kube_gpu_stats_tpu.loadgen.burn import make_sharded_train_step\n"
        "exporter = embedded.start(port=0, interval=0.1)\n"
        "print(exporter.port, flush=True)\n"
        "mesh, step, params, x = make_sharded_train_step(8)\n"
        "for _ in range(40):\n"
        "    t0 = time.perf_counter()\n"
        "    params, loss = step(params, x)\n"
        "    jax.block_until_ready(loss)\n"
        "    exporter.record_step(1, seconds=time.perf_counter() - t0,\n"
        "                         flops=8e9)\n"
        "print('DONE', flush=True)\n"
        "time.sleep(600)\n"
    )
    procs = []
    ports = []
    try:
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for worker in range(2):
            env = dict(os.environ, KTS_SLICE="v5p-16", KTS_WORKER=str(worker),
                       KTS_TOPOLOGY="2x2x4",
                       # Pin the child mesh explicitly: other tests
                       # (dryrun_multichip(16)) mutate the inherited
                       # XLA_FLAGS device count in-process.
                       XLA_FLAGS="--xla_force_host_platform_device_count=8",
                       # A plain `python file.py` child doesn't get
                       # pytest's rootdir on sys.path.
                       PYTHONPATH=repo_root + os.pathsep
                       + os.environ.get("PYTHONPATH", ""))
            procs.append(subprocess.Popen(
                [sys.executable, str(child_src)], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True))

        def read_line(proc, timeout):
            ready, _, _ = select.select([proc.stdout], [], [], timeout)
            assert ready, "embedded worker never answered (jax init hang?)"
            return proc.stdout.readline().strip()

        for proc in procs:
            ports.append(int(read_line(proc, 120.0)))
        for proc in procs:
            assert read_line(proc, 180.0) == "DONE"
        time.sleep(0.4)  # one more poll tick folds the final counters

        targets = [f"http://127.0.0.1:{p}/metrics" for p in ports]
        per_worker = []
        import urllib.request

        for url in targets:
            text = urllib.request.urlopen(url, timeout=10).read().decode()
            per_worker.append(text)
        for text in per_worker:
            series = parse_exposition(text)
            ups = [(l["chip"], l["worker"]) for n, l, v in series
                   if n == "accelerator_up"]
            assert len(ups) == 8  # 8 per-device series sets
            flops = [v for n, l, v in series
                     if n == "accelerator_workload_flops_total"]
            # SPMD split: 40 steps x 8e9 FLOPs / 8 devices, per chip.
            assert flops == [pytest.approx(40 * 8e9 / 8)] * 8
            (count,) = [v for n, l, v in series
                        if n ==
                        "accelerator_workload_step_duration_seconds_count"]
            assert count == 40.0

        hub = Hub(targets, fetch_timeout=10.0)
        try:
            hub.refresh_once()
            merged = hub.registry.snapshot().render()
        finally:
            hub.stop()
        pairs = worker_chip_pairs(merged)
        assert len(pairs) == 16 and len(set(pairs)) == 16
        assert 'slice_chips{slice="v5p-16"} 16' in merged
        assert 'slice_workers{slice="v5p-16"} 2' in merged
        assert "slice_duplicate_series 0" in merged
        (total,) = [v for n, l, v in parse_exposition(merged)
                    if n == "accelerator_workload_step_duration_seconds_count"]
        assert total == 80.0  # both workers' histograms summed
    finally:
        for proc in procs:
            proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
