"""`kube-tpu-stats doctor` — the preflight diagnosis subcommand: per-probe
statuses against fake backends, JSON shape, exit codes, CLI dispatch."""

import json

import pytest

from kube_gpu_stats_tpu import doctor
from kube_gpu_stats_tpu.cli import main as cli_main
from kube_gpu_stats_tpu.config import Config
from kube_gpu_stats_tpu.testing.kubelet_server import FakeKubeletServer, tpu_pod
from kube_gpu_stats_tpu.testing.libtpu_server import FakeLibtpuServer
from kube_gpu_stats_tpu.testing.sysfs_fixture import make_sysfs


def by_name(results):
    out = {}
    for r in results:
        out[r.name] = r
    return out


@pytest.fixture
def tpu_node(tmp_path):
    """A healthy fake TPU node: sysfs tree + libtpu server + kubelet."""
    make_sysfs(tmp_path / "sys", num_chips=4)
    socket = str(tmp_path / "kubelet.sock")
    pods = [tpu_pod("train", "ml", "worker", ["0", "1"])]
    with FakeLibtpuServer(num_chips=4) as libtpu, \
         FakeKubeletServer(socket, pods) as kubelet:
        yield Config(
            backend="tpu",
            sysfs_root=str(tmp_path / "sys"),
            libtpu_ports=(libtpu.port,),
            kubelet_socket=socket,
            attribution="podresources",
            deadline=5.0,
        )


def test_healthy_tpu_node_all_ok(tpu_node):
    results = by_name(doctor.run_checks(tpu_node))
    libtpu_name = f"libtpu:{tpu_node.libtpu_ports[0]}"
    assert results["sysfs"].status == "ok"
    assert "4 chip(s)" in results["sysfs"].detail
    assert results[libtpu_name].status == "ok"
    assert "batched fetch" in results[libtpu_name].detail
    assert results["attribution"].status == "ok"
    assert "2 allocated" in results["attribution"].detail
    assert results["poll"].status == "ok"
    assert "4 up" in results["poll"].detail
    assert not any(r.status == "fail" for r in results.values())


def test_libtpu_down_is_warn_not_fail(tmp_path):
    make_sysfs(tmp_path / "sys", num_chips=2)
    cfg = Config(backend="tpu", sysfs_root=str(tmp_path / "sys"),
                 libtpu_ports=(1,), attribution="off", deadline=5.0)
    results = by_name(doctor.run_checks(cfg))
    assert results["libtpu:1"].status == "warn"
    assert "TPU_RUNTIME_METRICS_PORTS" in results["libtpu:1"].detail
    # Node still collects environmental metrics: poll must pass.
    assert results["poll"].status == "ok"
    assert "2 up" in results["poll"].detail


def test_per_metric_only_runtime_diagnoses_ok(tmp_path):
    make_sysfs(tmp_path / "sys", num_chips=2)
    with FakeLibtpuServer(num_chips=2) as libtpu:
        libtpu.reject_batch = True
        cfg = Config(backend="tpu", sysfs_root=str(tmp_path / "sys"),
                     libtpu_ports=(libtpu.port,), attribution="off",
                     deadline=5.0)
        results = by_name(doctor.run_checks(cfg))
    name = f"libtpu:{cfg.libtpu_ports[0]}"
    assert results[name].status == "ok"
    assert "per-metric" in results[name].detail


def test_garbled_runtime_is_fail(tmp_path):
    make_sysfs(tmp_path / "sys", num_chips=2)
    with FakeLibtpuServer(num_chips=2) as libtpu:
        libtpu.garble = True
        cfg = Config(backend="tpu", sysfs_root=str(tmp_path / "sys"),
                     libtpu_ports=(libtpu.port,), attribution="off",
                     deadline=5.0)
        results = by_name(doctor.run_checks(cfg))
        assert results[f"libtpu:{libtpu.port}"].status == "fail"
        assert doctor.main(["--backend", "tpu", "--sysfs-root",
                            str(tmp_path / "sys"), "--libtpu-ports",
                            str(libtpu.port), "--attribution", "off"]) == 1


def test_cpu_only_node_mock_backend_ready(tmp_path, capsys):
    rc = cli_main(["doctor", "--backend", "mock", "--attribution", "off",
                   "--sysfs-root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "READY" in out
    assert "[warn] sysfs" in out


def test_json_output_shape(tmp_path, capsys):
    rc = cli_main(["doctor", "--json", "--backend", "mock",
                   "--attribution", "off", "--sysfs-root", str(tmp_path)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["ready"] is True
    statuses = {c["name"]: c["status"] for c in doc["checks"]}
    assert statuses["poll"] == "ok"
    assert all(set(c.keys()) == {"name", "status", "detail", "data"}
               for c in doc["checks"])


def test_scrape_check_against_prom_file(tmp_path, capsys):
    good = tmp_path / "good.prom"
    # A contract-conformant exposition from the real stack: mock backend
    # through the production renderer.
    from kube_gpu_stats_tpu.collectors.mock import MockCollector
    from kube_gpu_stats_tpu.poll import PollLoop
    from kube_gpu_stats_tpu.registry import Registry

    registry = Registry()
    loop = PollLoop(MockCollector(num_devices=2), registry, deadline=5.0)
    loop.tick()
    loop.stop()
    good.write_text(registry.snapshot().render())
    result = doctor.check_scrape(str(good))
    assert result.status == "ok"

    bad = tmp_path / "bad.prom"
    bad.write_text('accelerator_duty_cycle{chip="0"} 12\n')
    result = doctor.check_scrape(str(bad))
    assert result.status == "fail"
    assert "missing labels" in result.detail


def test_scrape_hardened_endpoints_warn_not_fail(tmp_path):
    """The exporter's own shipped hardening must not read as broken: basic
    auth (doctor only holds the password hash) and self-signed TLS both
    prove the endpoint is alive — WARN, never FAIL."""
    import hashlib
    import subprocess

    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.registry import Registry

    auth_srv = MetricsServer(
        Registry(), host="127.0.0.1", port=0, auth_username="prom",
        auth_password_sha256=hashlib.sha256(b"pw").hexdigest(),
    )
    auth_srv.start()
    try:
        result = doctor.check_scrape(f"http://127.0.0.1:{auth_srv.port}/metrics")
        assert result.status == "warn"
        assert "requires authentication" in result.detail
    finally:
        auth_srv.stop()

    cert, key = tmp_path / "cert.pem", tmp_path / "key.pem"
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=127.0.0.1", "-addext", "subjectAltName=IP:127.0.0.1"],
        check=True, capture_output=True,
    )
    tls_srv = MetricsServer(Registry(), host="127.0.0.1", port=0,
                            tls_cert_file=str(cert), tls_key_file=str(key))
    tls_srv.start()
    try:
        result = doctor.check_scrape(f"https://127.0.0.1:{tls_srv.port}/metrics")
        assert result.status == "warn"
        assert "TLS handshake failed" in result.detail
    finally:
        tls_srv.stop()


def test_remote_write_probe(tmp_path):
    """Empty-WriteRequest probe: 2xx/400 = ok, 401 with creds = fail,
    receiver down = warn (exporter retries), 5xx = warn."""
    import http.server
    import threading

    codes = {"next": 204}
    bodies = []

    class H(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            bodies.append(self.rfile.read(
                int(self.headers["Content-Length"])))
            self.send_response(codes["next"])
            self.end_headers()

        def log_message(self, *a):
            pass

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}/push"
    try:
        cfg = Config(remote_write_url=url)
        assert doctor.check_remote_write(cfg).status == "ok"
        from kube_gpu_stats_tpu import snappy
        assert snappy.decompress(bodies[0]) == b""  # nothing written
        codes["next"] = 400
        result = doctor.check_remote_write(cfg)
        assert result.status == "ok" and "endpoint + auth OK" in result.detail
        codes["next"] = 401
        assert doctor.check_remote_write(cfg).status == "fail"
        codes["next"] = 503
        assert doctor.check_remote_write(cfg).status == "warn"
    finally:
        httpd.shutdown()
        httpd.server_close()
    assert doctor.check_remote_write(
        Config(remote_write_url="http://127.0.0.1:1/push")).status == "warn"
    # Malformed URL (no scheme) is a config error, not a transient blip.
    assert doctor.check_remote_write(
        Config(remote_write_url="localhost:9009/push")).status == "fail"
    assert doctor.check_remote_write(Config(
        remote_write_url=url,
        remote_write_bearer_token_file=str(tmp_path / "gone"),
    )).status == "fail"


def test_url_flag_requires_target():
    assert doctor.main(["--url"]) == 2
    assert doctor.main(["--url="]) == 2
    assert doctor.main(["--url", "--json"]) == 2


def test_url_equals_form(tmp_path, capsys):
    bad = tmp_path / "bad.prom"
    bad.write_text('accelerator_duty_cycle{chip="0"} 12\n')
    rc = cli_main(["doctor", f"--url={bad}", "--backend", "mock",
                   "--attribution", "off", "--sysfs-root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "[fail] scrape" in out


def test_hung_probe_is_bounded_fail():
    import time

    results = doctor._bounded("wedged", lambda: time.sleep(60), timeout=0.2)
    assert len(results) == 1
    assert results[0].status == "fail"
    assert "hung" in results[0].detail


def test_crashing_probe_is_fail_row_not_traceback():
    def boom():
        raise RuntimeError("kaput")

    results = doctor._bounded("broken", boom)
    assert results[0].status == "fail"
    assert "kaput" in results[0].detail


def test_doctor_reports_wire_dialect_per_port(tmp_path):
    """Round-1 verdict item 1: doctor must say which dialect each metric
    port speaks — the first question when a node exports nothing."""
    from kube_gpu_stats_tpu.doctor import check_libtpu_port

    with FakeLibtpuServer(num_chips=2, dialect="flat") as flat_srv, \
         FakeLibtpuServer(num_chips=2, dialect="nested") as nested_srv:
        cfg = Config(backend="tpu",
                     libtpu_ports=(flat_srv.port, nested_srv.port))
        flat_res = check_libtpu_port(cfg, flat_srv.port)
        nested_res = check_libtpu_port(cfg, nested_srv.port)
    assert flat_res.status == "ok"
    assert "flat dialect" in flat_res.detail
    assert "batched fetch" in flat_res.detail
    assert nested_res.status == "ok"
    assert "nested dialect" in nested_res.detail
    assert "per-metric fetch" in nested_res.detail


def test_doctor_reports_name_only_port_as_answering_not_unreachable():
    """Review finding: an idle zero-omitting flat runtime answers with
    name-only (AMBIGUOUS) payloads; doctor used to fall through to
    'unreachable (empty response)' — wrong on both counts. It must say the
    port answers but carries no dialect evidence yet."""
    from kube_gpu_stats_tpu.doctor import check_libtpu_port
    from kube_gpu_stats_tpu.proto import tpumetrics

    with FakeLibtpuServer(num_chips=1, dialect="flat") as srv:
        srv.zero_omit = True
        srv.drop_metrics.add(tpumetrics.ICI_TRAFFIC)  # counters never zero
        for m in tpumetrics.ALL_METRICS:
            srv.scripted[(m, 0)] = 0.0
        cfg = Config(backend="tpu", libtpu_ports=(srv.port,))
        res = check_libtpu_port(cfg, srv.port)
    assert res.status == "warn"
    assert "name-only" in res.detail
    assert "unreachable" not in res.detail


def test_doctor_names_alien_families():
    """Round-2 verdict item 6 done-criterion: doctor against a fake
    server speaking alien names must report them. Mixed surface -> OK
    with an ignore note; alien-only surface -> FAIL naming every family
    (the green-and-empty exporter now diagnoses itself)."""
    from kube_gpu_stats_tpu.doctor import check_libtpu_port
    from kube_gpu_stats_tpu.proto import tpumetrics

    with FakeLibtpuServer(num_chips=2) as mixed:
        mixed.extra_metrics["tpu.runtime.novel.metric"] = 1.0
        cfg = Config(backend="tpu", libtpu_ports=(mixed.port,))
        res = check_libtpu_port(cfg, mixed.port)
    assert res.status == "ok"
    assert "ignoring 1 unrecognized family" in res.detail
    assert "tpu.runtime.novel.metric" in res.detail
    # Structured payload for the capture runbook (--json harvest):
    # no prose parsing needed.
    assert res.data["unknown_families"] == ["tpu.runtime.novel.metric"]
    assert "accelerator_duty_cycle" in res.data["served_families"]
    assert res.data["dialect"]

    with FakeLibtpuServer(num_chips=2) as alien:
        alien.drop_metrics.update(tpumetrics.ALL_METRICS)
        alien.extra_metrics.update({
            "tpu.v7.dutycycle": 50.0, "tpu.v7.hbm.used": 1.0})
        cfg = Config(backend="tpu", libtpu_ports=(alien.port,))
        res = check_libtpu_port(cfg, alien.port)
    assert res.status == "fail"
    assert "tpu.v7.dutycycle" in res.detail and "tpu.v7.hbm.used" in res.detail
    assert "different metric-name surface" in res.detail
    assert res.data["unknown_families"] == [
        "tpu.v7.dutycycle", "tpu.v7.hbm.used"]


def test_embedded_viability_hint(tmp_path, monkeypatch):
    """When nothing external is collectable but in-process JAX would see
    a chip, doctor points at the embedded exporter; on a truly chip-less
    box the row is a skip. Healthy nodes never run the probe."""
    from kube_gpu_stats_tpu import doctor as doc

    cfg = Config(backend="tpu", sysfs_root=str(tmp_path / "nosys"),
                 libtpu_ports=(1,))  # closed port

    monkeypatch.setattr("kube_gpu_stats_tpu.bench._probe_jax_platform",
                        lambda timeout=60.0: "tpu")
    results = doc.run_checks(cfg)
    row = next(r for r in results if r.name == "embedded")
    assert row.status == doc.WARN
    assert "embedded.start" in row.detail

    monkeypatch.setattr("kube_gpu_stats_tpu.bench._probe_jax_platform",
                        lambda timeout=60.0: "cpu")
    results = doc.run_checks(cfg)
    row = next(r for r in results if r.name == "embedded")
    assert row.status == doc.SKIP


def test_embedded_hint_absent_on_healthy_node(tmp_path, monkeypatch):
    from kube_gpu_stats_tpu import doctor as doc
    from kube_gpu_stats_tpu.testing.sysfs_fixture import make_sysfs

    def boom(timeout=60.0):
        raise AssertionError("probe must not run when sysfs is healthy")

    monkeypatch.setattr("kube_gpu_stats_tpu.bench._probe_jax_platform", boom)
    make_sysfs(tmp_path / "sys", num_chips=2)
    with FakeLibtpuServer(num_chips=2) as server:
        cfg = Config(backend="tpu", sysfs_root=str(tmp_path / "sys"),
                     libtpu_ports=(server.port,))
        results = doc.run_checks(cfg)
    assert not any(r.name == "embedded" for r in results)


def test_embedded_hint_inconclusive_probe_is_not_an_all_clear(tmp_path,
                                                              monkeypatch):
    from kube_gpu_stats_tpu import doctor as doc

    monkeypatch.setattr("kube_gpu_stats_tpu.bench._probe_jax_platform",
                        lambda timeout=60.0: None)
    cfg = Config(backend="tpu", sysfs_root=str(tmp_path / "nosys"),
                 libtpu_ports=(1,))
    results = doc.run_checks(cfg)
    row = next(r for r in results if r.name == "embedded")
    assert row.status == doc.SKIP
    assert "inconclusive" in row.detail
    assert "nothing to export" not in row.detail


def test_embedded_hint_absent_when_sysfs_discovers_despite_warn(tmp_path,
                                                                monkeypatch):
    """Chips enumerable but attributes unreadable (privilege problem):
    that's an external surface needing mounts, not embedded mode — the
    probe must not run (review finding)."""
    from kube_gpu_stats_tpu import doctor as doc

    def boom(timeout=60.0):
        raise AssertionError("probe must not run when sysfs enumerates")

    monkeypatch.setattr("kube_gpu_stats_tpu.bench._probe_jax_platform", boom)
    # Bare accel dirs: discovery succeeds, attribute reads don't.
    for i in range(2):
        (tmp_path / "sys" / "class" / "accel" / f"accel{i}").mkdir(
            parents=True)
    cfg = Config(backend="tpu", sysfs_root=str(tmp_path / "sys"),
                 libtpu_ports=(1,))
    results = doc.run_checks(cfg)
    assert not any(r.name == "embedded" for r in results)


def test_port_scan_finds_runtime_on_nonstandard_port(tmp_path, monkeypatch):
    """Configured port down + a fake runtime on a neighbor port: doctor
    names the open port and the env var to point at it."""
    from kube_gpu_stats_tpu import doctor as doc

    monkeypatch.setattr("kube_gpu_stats_tpu.bench._probe_jax_platform",
                        lambda timeout=60.0: "cpu")
    with FakeLibtpuServer(num_chips=1) as server:
        # Configure a dead port whose +8 neighborhood contains the live one.
        base = server.port - 3
        cfg = Config(backend="tpu", sysfs_root=str(tmp_path / "nosys"),
                     libtpu_ports=(base,))
        results = doc.run_checks(cfg)
    row = next(r for r in results if r.name == "port-scan")
    assert row.status == doc.WARN
    assert str(server.port) in row.detail
    assert "TPU_RUNTIME_METRICS_PORTS" in row.detail


def test_port_scan_skip_when_neighborhood_quiet(tmp_path, monkeypatch):
    from kube_gpu_stats_tpu import doctor as doc

    monkeypatch.setattr("kube_gpu_stats_tpu.bench._probe_jax_platform",
                        lambda timeout=60.0: "cpu")
    cfg = Config(backend="tpu", sysfs_root=str(tmp_path / "nosys"),
                 libtpu_ports=(1,))
    results = doc.run_checks(cfg)
    row = next(r for r in results if r.name == "port-scan")
    assert row.status == doc.SKIP


def test_flag_value_validation():
    import pytest

    from kube_gpu_stats_tpu.config import from_args

    for bad in (["--interval", "0"], ["--deadline", "-1"],
                ["--max-concurrent-scrapes", "-1"],
                ["--remote-write-interval", "0"]):
        with pytest.raises(SystemExit):
            from_args(["--backend", "mock"] + bad)


def test_port_scan_skips_cleanly_when_config_covers_neighborhood(
        tmp_path, monkeypatch):
    """8 consecutive configured ports (the multi-process layout) must not
    crash the advisory scan (review finding: empty candidate set)."""
    from kube_gpu_stats_tpu import doctor as doc

    monkeypatch.setattr("kube_gpu_stats_tpu.bench._probe_jax_platform",
                        lambda timeout=60.0: "cpu")
    cfg = Config(backend="tpu", sysfs_root=str(tmp_path / "nosys"),
                 libtpu_ports=tuple(range(8431, 8439)))
    results = doc.run_checks(cfg)
    row = next(r for r in results if r.name == "port-scan")
    assert row.status == doc.SKIP
    assert "crash" not in row.detail


def test_resilience_row_on_healthy_node(tpu_node):
    results = by_name(doctor.run_checks(tpu_node))
    row = results["resilience"]
    assert row.status == "ok"
    assert f"libtpu:{tpu_node.libtpu_ports[0]}" in row.detail
    assert "closed" in row.detail
    assert row.data["breakers"]


def test_resilience_row_skip_on_breakerless_backend(tmp_path):
    cfg = Config(backend="mock", attribution="off",
                 sysfs_root=str(tmp_path), deadline=5.0)
    results = by_name(doctor.run_checks(cfg))
    assert results["resilience"].status == "skip"


def test_resilience_open_breaker_is_fail_and_exit_nonzero():
    """An OPEN breaker means collection through that edge is down right
    now: the resilience row FAILs, which makes doctor exit non-zero."""
    from kube_gpu_stats_tpu.resilience import CircuitBreaker

    breaker = CircuitBreaker("libtpu:8431", failure_threshold=1)
    breaker.record_failure(RuntimeError("connection refused"))

    class Stub:
        def breakers(self):
            return {"libtpu:8431": breaker}

    row = doctor.resilience_result(Stub())
    assert row.status == "fail"
    assert "open" in row.detail
    assert "connection refused" in row.detail
    assert row.data["breakers"]["libtpu:8431"]["state"] == "open"
    # Sorted with fails first + nonzero exit via the normal machinery.
    assert doctor._ORDER[row.status] == 0


def test_resilience_rapid_doctor_ticks_do_not_fake_an_outage(tmp_path):
    """doctor's 5 back-to-back ticks against a down-but-sysfs-backed
    node rack up failures in milliseconds; the breaker's min-span
    condition must keep that from reading as a persistent outage (the
    node still collects environmental metrics, poll stays ok)."""
    make_sysfs(tmp_path / "sys", num_chips=2)
    cfg = Config(backend="tpu", sysfs_root=str(tmp_path / "sys"),
                 libtpu_ports=(1,), attribution="off", deadline=5.0)
    results = by_name(doctor.run_checks(cfg))
    assert results["poll"].status == "ok"
    assert "2 up" in results["poll"].detail
    assert results["resilience"].status == "ok"
    assert "closed" in results["resilience"].detail


def test_live_resilience_reads_running_exporters_breakers(tmp_path):
    """doctor --url reads the RUNNING daemon's kts_breaker_state (a
    fresh probe's breakers start closed by design — min span): open on
    the live exposition is FAIL, all-closed OK, absent SKIP."""
    live = tmp_path / "live.prom"
    live.write_text('kts_breaker_state{component="libtpu:8431"} 2\n'
                    'kts_breaker_state{component="kubelet"} 0\n')
    row = doctor.check_live_resilience(str(live))
    assert row.status == "fail"
    assert "libtpu:8431: open" in row.detail
    assert row.data["breakers"]["libtpu:8431"] == "open"

    live.write_text('kts_breaker_state{component="kubelet"} 0\n')
    row = doctor.check_live_resilience(str(live))
    assert row.status == "ok"

    live.write_text('accelerator_up{chip="0"} 1\n')
    assert doctor.check_live_resilience(str(live)).status == "skip"


# -- doctor --skew (ISSUE 14) ------------------------------------------------

def test_skew_verdict_healthy_single_version():
    from kube_gpu_stats_tpu.doctor import OK, skew_verdict

    status, detail = skew_verdict({
        "role": "hub", "build": "0.5.0",
        "proto_min": 1, "proto_max": 2,
        "ingest": {"proto_min": 1, "proto_max": 2,
                   "fleet_versions": {"0.5.0": 12},
                   "skew_refused_total": 0, "refused_peers": {},
                   "downgraded_sessions": []},
        "publisher": None, "wal_quarantined": {},
    })
    assert status == OK
    assert "fleet census: 0.5.0=12" in detail


def test_skew_verdict_names_refused_and_downgraded_peers():
    from kube_gpu_stats_tpu.doctor import WARN, skew_verdict

    status, detail = skew_verdict({
        "role": "hub", "build": "0.5.0",
        "proto_min": 1, "proto_max": 2,
        "ingest": {
            "proto_min": 2, "proto_max": 2,
            "fleet_versions": {"0.5.0": 3, "wire-v1": 1},
            "skew_refused_total": 40,
            "refused_peers": {
                "http://node-9:9400/metrics": {"version": 1,
                                               "count": 40}},
            "downgraded_sessions": [
                {"source": "http://node-3:9400/metrics", "proto": 1,
                 "build": "0.4.0"}],
            "downgraded_sessions_truncated": 0,
        },
        "publisher": None, "wal_quarantined": {},
    })
    assert status == WARN
    assert "http://node-9:9400/metrics offered v1" in detail
    assert "http://node-3:9400/metrics (v1, 0.4.0)" in detail
    assert "MIXED fleet" in detail


def test_skew_verdict_publisher_and_quarantine_sides():
    from kube_gpu_stats_tpu.doctor import WARN, skew_verdict

    status, detail = skew_verdict({
        "role": "daemon", "build": "0.5.0",
        "proto_min": 1, "proto_max": 2,
        "publisher": {
            "negotiated_proto": 1,
            "hub": {"build": "0.6.0", "proto_min": 2, "proto_max": 3},
            "skew_refused_total": 7, "proto_downgrades_total": 0,
        },
        "wal_quarantined": {"energy": 1},
    })
    assert status == WARN
    assert "REFUSED 7 push(es)" in detail
    assert "QUARANTINED" in detail and "energy=1" in detail


def test_doctor_egress_undecodable_spool_points_at_skew():
    """ISSUE 14 satellite: spillq.undecodable_total finally has an
    operator surface — doctor --egress explains it and routes to
    doctor --skew."""
    from kube_gpu_stats_tpu.doctor import WARN, check_egress
    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.registry import Registry

    payload = {
        "enabled": True,
        "spill": {"depth_frames": 0, "bytes": 0, "max_bytes": 1 << 20,
                  "oldest_age_seconds": 0, "dropped_total": 0,
                  "undecodable_total": 3, "reencoded_total": 2,
                  "link_failures": 0},
        "senders": {},
    }
    server = MetricsServer(Registry(), host="127.0.0.1", port=0,
                           egress_provider=lambda: payload)
    server.start()
    try:
        result = check_egress(f"http://127.0.0.1:{server.port}")
    finally:
        server.stop()
    assert result.status == WARN
    assert "3 spooled frame(s) undecodable" in result.detail
    assert "doctor --skew" in result.detail
    assert "2 old-format spooled frame(s) recovered" in result.detail


def test_doctor_skew_cli_flag_runs_the_row(capsys):
    from kube_gpu_stats_tpu import doctor
    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.registry import Registry

    server = MetricsServer(Registry(), host="127.0.0.1", port=0,
                           skew_provider=lambda: {
                               "role": "daemon", "build": "0.5.0",
                               "proto_min": 1, "proto_max": 2,
                               "publisher": None,
                               "wal_quarantined": {}})
    server.start()
    try:
        code = doctor.main(["--backend", "mock", "--skew",
                            "--listen-port", str(server.port)])
        out = capsys.readouterr().out
        assert "skew" in out
        assert "build 0.5.0 speaks wire v1..v2" in out
        assert code == 0
    finally:
        server.stop()


def test_doctor_skew_classifies_missing_surface():
    from kube_gpu_stats_tpu.doctor import FAIL, WARN, check_skew
    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.registry import Registry

    server = MetricsServer(Registry(), host="127.0.0.1", port=0)
    server.start()  # no skew provider: 404s
    try:
        result = check_skew(f"http://127.0.0.1:{server.port}")
    finally:
        server.stop()
    assert result.status == WARN
    assert "predates the version-skew layer" in result.detail
    assert check_skew("http://127.0.0.1:9").status == FAIL


# -- --at time parsing + the history-backed fleet row (ISSUE 18) -------------

def test_parse_at_forms():
    from kube_gpu_stats_tpu.doctor import parse_at

    now = 2_000_000_000.0
    assert parse_at("600", now) == now - 600.0
    assert parse_at("10m", now) == now - 600.0
    assert parse_at("2h", now) == now - 7200.0
    assert parse_at("-2h", now) == now - 7200.0       # '-ago' spelling
    assert parse_at("1722470400", now) == 1722470400.0  # absolute
    for garbage in ("abc", "", "10d", "h"):
        with pytest.raises(ValueError) as err:
            parse_at(garbage, now)
        assert "10m" in str(err.value)  # the error teaches the forms


def test_at_flag_requires_fleet(capsys):
    from kube_gpu_stats_tpu.doctor import main as doctor_main

    assert doctor_main(["--at", "10m"]) == 2
    assert "--fleet" in capsys.readouterr().err


def test_check_fleet_at_against_a_live_hub_ring():
    """End to end: a hub's history ring holds a straggler episode 10
    minutes back; `doctor --fleet --at` replays it over real HTTP even
    though the fleet has since recovered."""
    import time as time_mod

    from kube_gpu_stats_tpu.doctor import WARN, check_fleet_at
    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.history import HistoryStore
    from kube_gpu_stats_tpu.registry import Registry

    store = HistoryStore()
    now = time_mod.time()
    t0 = now - 600.0
    for worker, rate in (("w0", 10.0), ("w1", 10.0), ("w2", 2.0)):
        store.record("slice_worker_steps_per_second",
                     (("slice", "s0"), ("worker", worker)), rate)
    store.record("slice_target_up", (("target", "node-2:9400"),), 0.0)
    store.commit(t0, 1)
    for worker in ("w0", "w1", "w2"):
        store.record("slice_worker_steps_per_second",
                     (("slice", "s0"), ("worker", worker)), 10.0)
    store.record("slice_target_up", (("target", "node-2:9400"),), 1.0)
    store.commit(now, 2)

    server = MetricsServer(Registry(), host="127.0.0.1", port=0,
                           history_provider=store)
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        past = check_fleet_at(base, t0)
        assert past.status == WARN
        assert "straggler worker w2" in past.detail
        assert "node-2:9400 was down" in past.detail
        present = check_fleet_at(base, now)
        assert "fleet healthy" in present.detail
    finally:
        server.stop()


def test_check_fleet_at_on_a_history_less_hub():
    """A hub without the ring (--no-history, or predating it) draws a
    self-describing WARN, not a crash or a fake all-clear."""
    from kube_gpu_stats_tpu.doctor import WARN, check_fleet_at
    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.history import HistoryStore
    from kube_gpu_stats_tpu.registry import Registry

    bare = MetricsServer(Registry(), host="127.0.0.1", port=0)
    bare.start()
    disabled = MetricsServer(Registry(), host="127.0.0.1", port=0,
                             history_provider=HistoryStore(enabled=False))
    disabled.start()
    try:
        for server in (bare, disabled):
            result = check_fleet_at(
                f"http://127.0.0.1:{server.port}", 1_700_000_000.0)
            assert result.status == WARN, result
    finally:
        bare.stop()
        disabled.stop()


# -- interconnect link verdicts (ISSUE 19) -----------------------------------

def _link_rollup():
    """A rollup mid link-incident: link 1-2 accused, its two endpoint
    nodes showing exactly the symptoms the link explains."""
    return {
        "enabled": True,
        "links": {
            "graph": {"kind": "torus", "topology": "4x1",
                      "nodes": 4, "links": 4},
            "suspects": {
                "1-2": {
                    "reason": "ici-rate+anomaly-correlated"
                              "+host-counter-confirmed",
                    "endpoints": ["1", "2"],
                    "targets": ["http://w1:9400/metrics",
                                "http://w2:9400/metrics"],
                    "since": 1000.0,
                    "drop": 0.89,
                    "observed_bps": 3.3e6,
                    "baseline_bps": 3e7,
                },
            },
            "baselines": {},
        },
        "targets": {
            "http://w1:9400/metrics": {
                "anomalous": {"ici": -7.2, "host_nic_drops": 9.0},
                "signals": {},
            },
            "http://w2:9400/metrics": {
                "anomalous": {"ici": -6.8, "steps": -4.1},
                "signals": {},
            },
            "http://w0:9400/metrics": {"anomalous": {}, "signals": {}},
        },
        "anomalies": [],
        "slo": {},
    }


def test_fleet_post_mortem_names_link_and_spares_neighbors():
    """Tentpole acceptance sentence: the verdict names the shared LINK
    (host-counter-confirmed, with the drop) and does NOT accuse the
    endpoint nodes whose anomalies the link fully explains."""
    status, detail, data = doctor.fleet_post_mortem(_link_rollup())
    assert status == doctor.WARN
    assert ("nodes 1,2 slow; shared ICI link 1-2 suspect, "
            "host-counter-confirmed (89% below baseline)") in detail
    assert "1-2" in data["link_suspects"]
    # The innocent neighbors: explained, not accused.
    assert data["anomalous"] == {}
    assert data["link_explained"] == {
        "http://w1:9400/metrics": "1-2",
        "http://w2:9400/metrics": "1-2",
    }
    assert "http://w1:9400/metrics: ici" not in detail


def test_fleet_post_mortem_link_does_not_absorb_unrelated_anomaly():
    """An endpoint with an anomaly the link CANNOT explain (power) is
    still accused — suppression is symptom-scoped, not node-scoped."""
    payload = _link_rollup()
    payload["targets"]["http://w1:9400/metrics"]["anomalous"] = {
        "ici": -7.2, "power": 8.5}
    status, detail, data = doctor.fleet_post_mortem(payload)
    assert status == doctor.WARN
    assert "shared ICI link 1-2 suspect" in detail
    assert "http://w1:9400/metrics" in data["anomalous"]
    assert "http://w1:9400/metrics" not in data["link_explained"]
    # The other endpoint's symptoms are all link-shaped: still spared.
    assert data["link_explained"] == {"http://w2:9400/metrics": "1-2"}


def test_fleet_post_mortem_anomaly_correlated_without_host():
    payload = _link_rollup()
    payload["links"]["suspects"]["1-2"]["reason"] = \
        "ici-rate+anomaly-correlated"
    _status, detail, _data = doctor.fleet_post_mortem(payload)
    assert "shared ICI link 1-2 suspect, anomaly-correlated" in detail
    assert "host-counter-confirmed" not in detail


def test_fleet_at_verdict_reads_link_suspect_from_ring_payload():
    from kube_gpu_stats_tpu.doctor import OK, WARN, fleet_at_verdict

    at = 1_700_000_000.0
    links = {"series": [
        {"labels": {"link": "1-2",
                    "reason": "ici-rate+host-counter-confirmed"},
         "v": 1.0, "t": at - 3.0},
        # A cleared identity's tombstone must stay silent.
        {"labels": {"link": "0-3", "reason": "ici-rate"},
         "v": 0.0, "t": at - 3.0},
    ]}
    status, detail, data = fleet_at_verdict({}, {}, {}, at,
                                            links_payload=links)
    assert status == WARN
    assert ("ICI link 1-2 was suspect "
            "(ici-rate+host-counter-confirmed, as of") in detail
    assert "0-3" not in detail
    assert [e["link"] for e in data["links_suspect"]] == ["1-2"]
    # Ring buckets hold the MEAN of their samples: a bucket where the
    # suspect raised mid-bucket reads fractional, and still counts.
    partial = {"series": [
        {"labels": {"link": "1-2", "reason": "ici-rate"},
         "v": 0.4, "t": at - 3.0}]}
    status, detail, _data = fleet_at_verdict({}, {}, {}, at,
                                             links_payload=partial)
    assert status == WARN and "ICI link 1-2 was suspect" in detail
    # All tombstones: clean verdict, not "no samples".
    clean = {"series": [
        {"labels": {"link": "1-2", "reason": "ici-rate"},
         "v": 0.0, "t": at - 3.0}]}
    status, detail, _data = fleet_at_verdict({}, {}, {}, at,
                                             links_payload=clean)
    assert status == OK and "fleet healthy" in detail


def test_check_fleet_at_retroactive_link_suspect():
    """Satellite 3: an already-cleared link fault is still localized
    retroactively — `doctor --fleet --at <incident>` reads the suspect
    row from the hub's history ring over real HTTP, while `--at now`
    reads the recovery's tombstone as healthy."""
    import time as time_mod

    from kube_gpu_stats_tpu.doctor import WARN, check_fleet_at
    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.history import HistoryStore
    from kube_gpu_stats_tpu.registry import Registry

    store = HistoryStore()
    now = time_mod.time()
    t0 = now - 600.0
    reason = "ici-rate+anomaly-correlated+host-counter-confirmed"
    store.record("kts_fleet_link_suspect",
                 (("link", "1-2"), ("reason", reason)), 1.0)
    store.commit(t0, 1)
    # Incident over: the localizer's tombstone row.
    store.record("kts_fleet_link_suspect",
                 (("link", "1-2"), ("reason", reason)), 0.0)
    store.commit(now, 2)

    server = MetricsServer(Registry(), host="127.0.0.1", port=0,
                           history_provider=store)
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        past = check_fleet_at(base, t0)
        assert past.status == WARN
        assert f"ICI link 1-2 was suspect ({reason}" in past.detail
        present = check_fleet_at(base, now)
        assert "fleet healthy" in present.detail
    finally:
        server.stop()
