"""Differential pin for the native ingest fast paths (ISSUE 11): the
wirefast batch apply (``apply_slots``) and the native snappy decoder
must be indistinguishable from their pure-Python oracles —
``_TargetCache.apply_patch``'s per-slot loop (kept behind
``--no-native-ingest``) and ``snappy._decompress_py`` — under
randomized value churn, shape changes, worker restarts, duplicate
deliveries and forced resyncs, including the histogram-fold and
fleet-digest invalidation edges (the two caches a delta drops instead
of patching). The pattern of tests/test_parse_differential.py: drive
both implementations with identical inputs, require identical outputs
or identical error verdicts."""

from __future__ import annotations

import random

import pytest

from kube_gpu_stats_tpu import delta, snappy
from kube_gpu_stats_tpu.hub import Hub
from kube_gpu_stats_tpu.native import load_ingest

from tests.test_delta import _EXCLUDED_FAMILIES, make_body

NATIVE = load_ingest()

needs_native = pytest.mark.skipif(
    NATIVE is None, reason="wirefast extension not built")


def _push_hub(native: bool) -> Hub:
    return Hub([], targets_provider=lambda: [], interval=10.0,
               push_fence=1e9, ingest_lanes=2, native_ingest=native)


def _data_lines(hub: Hub) -> list[str]:
    out = []
    for line in hub.registry.snapshot().render().splitlines():
        if (line.startswith(("accelerator_", "slice_"))
                and not line.startswith(_EXCLUDED_FAMILIES)):
            out.append(line)
    return out


def _feed_both(hubs, encoders, body: str) -> None:
    """One frame per hub from its own encoder — the encoders march in
    lockstep (same bodies), so both hubs see the same frame KINDS and
    the same change-sets."""
    for hub, encoder in zip(hubs, encoders):
        wire, _kind = encoder.encode_next(body)
        code, _resp, _hdrs = hub.delta.handle(wire)
        if code == 200:
            encoder.ack()
        else:
            encoder.nack()
            wire, _kind = encoder.encode_next(body)
            assert hub.delta.handle(wire)[0] == 200
            encoder.ack()


@needs_native
def test_native_apply_matches_python_oracle_under_randomized_churn():
    """The acceptance pin: after randomized churn/restart/reorder
    sequences, a native-ingest hub's rendered data series are
    byte-identical to the Python-oracle hub fed the exact same frame
    stream — histograms (WORKLOAD_STEP_DURATION riding make_body) and
    the digest family (TICK_PHASE_SECONDS) included, so the
    hist/digest invalidation edges run under both paths."""
    rng = random.Random(0xA11C)
    workers = 4
    hubs = [_push_hub(native=True), _push_hub(native=False)]
    try:
        assert hubs[0].delta.native_active
        assert not hubs[1].delta.native_active
        duties = [10.0 * (i + 1) for i in range(workers)]
        steps = [float(i) for i in range(workers)]
        extra = [False] * workers
        phase = [0.001] * workers
        generations = [i + 1 for i in range(workers)]
        encoders = [
            [delta.DeltaEncoder(f"w{i}", generation=generations[i])
             for i in range(workers)] for _hub in hubs]

        def body(i: int) -> str:
            return make_body(i, duties[i], steps=steps[i],
                             extra_chip=extra[i], phase_p50=phase[i])

        for i in range(workers):
            _feed_both(hubs, [enc[i] for enc in encoders], body(i))
        for hub in hubs:
            hub.refresh_once()
        assert _data_lines(hubs[0]) == _data_lines(hubs[1])

        for round_no in range(10):
            for i in range(workers):
                event = rng.random()
                if event < 0.45:
                    duties[i] += rng.choice([0.0, 1.0, 2.5])
                    steps[i] += rng.randint(0, 3)  # histogram fold edge
                elif event < 0.6:
                    phase[i] += 0.0005  # fleet-digest invalidation edge
                elif event < 0.75:
                    extra[i] = not extra[i]  # shape change -> FULL
                elif event < 0.85:
                    # Worker restart: new generation, counters reset.
                    generations[i] += 100
                    steps[i] = 0.0
                    for enc in encoders:
                        enc[i] = delta.DeltaEncoder(
                            f"w{i}", generation=generations[i])
                fault = rng.random()
                if fault < 0.15:
                    # Duplicate delivery against BOTH hubs: a repeated
                    # DELTA must 409 on each without corrupting state;
                    # a repeated FULL is accepted idempotently (a FULL
                    # always replaces the session wholesale).
                    for hub, enc in zip(hubs, encoders):
                        wire, kind = enc[i].encode_next(body(i))
                        code, _resp, _hdrs = hub.delta.handle(wire)
                        if code == 200:
                            enc[i].ack()
                            dup_code, _resp, _hdrs = hub.delta.handle(wire)
                            assert dup_code == (
                                200 if kind == delta.KIND_FULL else 409)
                        else:
                            enc[i].nack()
                            wire, _kind = enc[i].encode_next(body(i))
                            assert hub.delta.handle(wire)[0] == 200
                            enc[i].ack()
                else:
                    _feed_both(hubs, [enc[i] for enc in encoders],
                               body(i))
            for hub in hubs:
                hub.refresh_once()
            native_lines = _data_lines(hubs[0])
            python_lines = _data_lines(hubs[1])
            assert native_lines == python_lines, (
                f"round {round_no}: native apply diverged from the "
                f"Python oracle:\n" + "\n".join(
                    l for l in python_lines
                    if l not in native_lines)[:2000])
            # The per-entry float slab stays byte-exact with the series
            # views it fronts (the ICI-delta old-value source).
            for source in hubs[0].delta.sources():
                entry = hubs[0]._parse_cache.get(source)
                if entry is not None and entry.value_slab is not None:
                    for slot, (_n, _l, value) in enumerate(entry.series):
                        assert entry.value_slab[slot] == value
    finally:
        for hub in hubs:
            hub.stop()


@needs_native
def test_native_apply_exercised_not_silently_oracled():
    """The differential above is vacuous if the native hub quietly ran
    the Python loop: force one delta through and require the compiled
    program + slab to exist on the entry afterwards."""
    hub = _push_hub(native=True)
    try:
        encoder = delta.DeltaEncoder("w0", generation=1)
        wire, _ = encoder.encode_next(make_body(0, 10.0))
        assert hub.delta.handle(wire)[0] == 200
        encoder.ack()
        hub.refresh_once()  # builds the merge plans (the compile gate)
        entry = hub._parse_cache.get("w0")
        assert entry.patch_program is None  # lazy: no delta yet
        wire, kind = encoder.encode_next(make_body(0, 12.0))
        assert kind == delta.KIND_DELTA
        assert hub.delta.handle(wire)[0] == 200
        encoder.ack()
        assert entry.patch_program is not None
        assert entry.value_slab is not None
        # Kind constants are mirrored in C (wirefast.cc kPatch*): the
        # program's kind bytes must stay inside the Python enum range.
        kinds = entry.patch_program[0]
        assert set(kinds) <= {0, 1, 2, 3, 4, 5}
    finally:
        hub.stop()


def test_profile_ingest_reports_both_paths():
    """`make profile-ingest` must produce a usable report in both the
    native and --legacy (Python oracle) modes — the one-command
    diagnosability satellite."""
    from kube_gpu_stats_tpu.profiler import profile_ingest

    for native in (True, False):
        report, summary = profile_ingest(sources=16, waves=2,
                                         native=native, top=5)
        assert "handle" in report
        assert summary["sources"] == 16
        assert summary["ingest"]["delta_frames"] == 3 * 16  # warmup + 2
        if NATIVE is not None and native:
            assert summary["path"] == "native"
        if not native:
            assert summary["path"] == "python"
        assert summary["ms_per_wave"] > 0


@needs_native
def test_native_snappy_matches_python_decoder():
    """snappy.decompress dispatches to the native decoder; both sides
    must agree on every input — round-trips, hand-built streams, and
    seeded random mutations (same triples-or-error contract as the
    parser differential)."""
    rng = random.Random(0x5A17)
    native = snappy._native_uncompress
    assert native is not None

    corpus = [
        snappy.compress(b""),
        snappy.compress(b"Hello"),
        snappy.compress(b"ab" * 500),
        snappy.compress(bytes(rng.randrange(256) for _ in range(4096))),
        b"\x05\x10Hello",
        b"\x0a\x04ab\x1e\x02\x00",
        b"",                      # truncated preamble
        b"\xff\xff\xff\xff\xff\xff",  # runaway length varint
        b"\x05\x10Hel",           # truncated literal body
        b"\x05\x10Hello\x00",     # trailing garbage tag
        b"\x02\x00a\x05\x01\x00",  # copy reaching past declared length
    ]
    for _ in range(300):
        base = bytearray(snappy.compress(
            bytes(rng.randrange(4) for _ in range(rng.randrange(0, 64)))))
        for _ in range(rng.randrange(0, 3)):
            if base:
                base[rng.randrange(len(base))] = rng.randrange(256)
        corpus.append(bytes(base))

    for wire in corpus:
        try:
            expected = snappy._decompress_py(wire)
        except ValueError as exc:
            with pytest.raises(ValueError) as err:
                native(wire)
            assert str(err.value) == str(exc), wire.hex()
        else:
            assert native(wire) == expected, wire.hex()
