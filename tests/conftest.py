"""Test env: force JAX onto a virtual 8-device CPU mesh before any jax
import (only the loadgen/graft tests use JAX — the exporter itself has no
JAX dependency, SURVEY.md §7 non-goals)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
