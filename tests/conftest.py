"""Test env: force JAX onto a virtual 8-device CPU mesh (only the
loadgen/graft tests use JAX — the exporter has no JAX dependency,
SURVEY.md §7 non-goals).

The sandbox's sitecustomize force-registers a single-chip TPU PJRT plugin
("axon") and overrides JAX_PLATFORMS, so env vars alone don't stick; the
jax.config update below wins because backends initialize lazily, after
conftest import."""

import os
import pathlib
import subprocess

# Build the native fast paths once per session so a fresh checkout is
# green without a manual `make` step — and an EDITED .cc never tests
# against a stale .so (make's own mtime check makes this a no-op when
# current). Best-effort: if the toolchain is missing, the native tests
# fail loudly with their own ImportError.
_NATIVE = pathlib.Path(__file__).resolve().parent.parent / "kube_gpu_stats_tpu" / "native"
try:
    subprocess.run(["make", "-C", str(_NATIVE)], check=False,
                   capture_output=True, timeout=120)
except (OSError, subprocess.TimeoutExpired):
    pass  # no make / slow box: the native tests explain themselves

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # pragma: no cover - jax is baked into this image
    pass

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_store_health():
    """The per-store durability registry (wal.StoreHealth, ISSUE 15) is
    process-global like the quarantine counts: a fault-injection test
    degrading 'spill' must not leave the NEXT test's spill queue
    probe-gated off the disk."""
    from kube_gpu_stats_tpu import wal

    wal.reset_store_stats()
    yield
    wal.reset_store_stats()
    wal.set_journal(None)
