"""Schema contract tests (SURVEY.md §4 unit tier)."""

from kube_gpu_stats_tpu import schema


def test_schema_validates():
    schema.validate()


def test_all_north_star_metrics_present():
    # BASELINE.json north star: MXU duty cycle, HBM used/total, ICI link
    # bandwidth, chip power — all as accelerator_* families.
    names = {m.name for m in schema.PER_DEVICE_METRICS}
    assert "accelerator_duty_cycle" in names
    assert "accelerator_memory_used_bytes" in names
    assert "accelerator_memory_total_bytes" in names
    assert "accelerator_ici_link_bandwidth_bytes_per_second" in names
    assert "accelerator_power_watts" in names


def test_label_sets_stable():
    assert schema.DEVICE_LABELS == ("accel_type", "chip", "device_path", "uuid")
    assert schema.ATTRIBUTION_LABELS == ("pod", "namespace", "container")
    assert schema.TOPOLOGY_LABELS == ("slice", "worker", "topology")


def test_label_escaping():
    assert schema.escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    assert schema.render_labels([("pod", 'x"y')]) == '{pod="x\\"y"}'
    assert schema.render_labels([]) == ""


def test_metrics_doc_in_sync():
    import pathlib

    doc = pathlib.Path(__file__).parent.parent / "docs" / "METRICS.md"
    assert doc.read_text() == schema.render_docs(), (
        "docs/METRICS.md is stale; run: python -m kube_gpu_stats_tpu.schema"
    )
