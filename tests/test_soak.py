"""Short soak: full daemon under backend flapping + scrape load. Catches
slow structural failures unit tests can't — thread leaks, generation
stalls, crash-on-flap (SURVEY.md §5 "never crash the DaemonSet pod")."""

import http.server
import threading
import time
import urllib.request

import pytest
from flake import retry_once_on_box_noise

from kube_gpu_stats_tpu.config import Config
from kube_gpu_stats_tpu.daemon import Daemon
from kube_gpu_stats_tpu.testing.libtpu_server import FakeLibtpuServer
from kube_gpu_stats_tpu.testing.sysfs_fixture import make_sysfs

# Soak suite: `make chaos` territory, excluded from `make ci` for speed.
pytestmark = pytest.mark.chaos


class FlakyReceiver(http.server.ThreadingHTTPServer):
    """Remote-write/pushgateway sink that fails half the time — the soak
    must show the senders neither leak nor wedge under receiver flap."""

    def __init__(self):
        outer = self
        self.hits = {"POST": 0, "PUT": 0}

        class Handler(http.server.BaseHTTPRequestHandler):
            def _serve(self):
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                # Per-method parity, first attempt succeeds: deterministic
                # for each sender regardless of how their streams interleave
                # (the pushgateway pusher only gets a few backoff-spaced
                # attempts in the soak window — attempt #1 must not 503).
                outer.hits[self.command] += 1
                self.send_response(204 if outer.hits[self.command] % 2 else 503)
                self.end_headers()

            do_POST = do_PUT = _serve

            def log_message(self, *args):
                pass

        super().__init__(("127.0.0.1", 0), Handler)
        threading.Thread(target=self.serve_forever, daemon=True).start()


# Known ~1/10 box-noise flake (ISSUE 12 satellite): the soak's pacing
# assertions ride real wall-clock sleeps under real scrape load, and a
# loaded CI box occasionally starves a sender past its window. One
# marked retry bounds the noise so chaos/robustness-suite failures stay
# visible; two failures in a row still fail the suite.
@retry_once_on_box_noise
def test_soak_flapping_backend(tmp_path):
    make_sysfs(tmp_path / "sys", num_chips=4)
    server = FakeLibtpuServer(num_chips=4).start()
    receiver = FlakyReceiver()
    cfg = Config(
        backend="tpu",
        sysfs_root=str(tmp_path / "sys"),
        libtpu_ports=(server.port,),
        interval=0.03,
        deadline=0.5,
        listen_host="127.0.0.1",
        listen_port=0,
        attribution="off",
        rediscovery_interval=0.5,
        use_native=True,
        textfile_dir=str(tmp_path / "tf"),
        remote_write_url=(
            f"http://127.0.0.1:{receiver.server_address[1]}/push"),
        remote_write_interval=0.1,
        # 2.0 in the soak: the symbol-interning encoder takes the same
        # retry/flap beating as 1.0 (the receiver never 415s, so no
        # downgrade — every push exercises the v2 path).
        remote_write_protocol="2.0",
        pushgateway_url=f"http://127.0.0.1:{receiver.server_address[1]}",
    )
    daemon = Daemon(cfg)
    daemon.start()
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{daemon.server.port}/metrics", timeout=2
                ).read()
            except Exception:
                pass
            time.sleep(0.01)

    scrape_threads = [threading.Thread(target=scraper, daemon=True) for _ in range(3)]
    for t in scrape_threads:
        t.start()

    try:
        assert daemon.registry.wait_for_publish(0, timeout=5)
        settle = threading.active_count()
        from kube_gpu_stats_tpu import procstats

        rss_start = procstats.read().get("process_resident_memory_bytes", 0)
        start_gen = daemon.registry.generation
        deadline = time.monotonic() + 6.0
        flip = True
        while time.monotonic() < deadline:
            server.fail = flip  # flap the runtime every 500 ms
            flip = not flip
            time.sleep(0.5)
        server.fail = False

        # Liveness: the loop kept publishing through the whole soak.
        gens = daemon.registry.generation - start_gen
        assert gens > 100, f"only {gens} publishes in 6s soak"
        # No thread leak: a leaking sampler pool would add ~1 thread/tick
        # (hundreds over the soak); transient per-request HTTP handler
        # threads legitimately fluctuate by a few.
        assert threading.active_count() <= settle + 8, (
            settle, threading.active_count()
        )
        # No unbounded memory growth across ~200 ticks of flapping.
        rss_end = procstats.read().get("process_resident_memory_bytes", 0)
        if rss_start and rss_end:
            assert rss_end - rss_start < 30 * 1024 * 1024, (rss_start, rss_end)
        # Recovery: runtime healthy again -> full metrics return.
        time.sleep(0.5)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{daemon.server.port}/metrics", timeout=2
        ).read().decode()
        assert body.count("accelerator_up{") == 4
        assert "accelerator_duty_cycle{" in body
        # Both senders survived the flaky receiver and kept shipping:
        # successes and failures both recorded, threads accounted above.
        assert daemon.remote_writer.pushes_total > 0
        assert daemon.remote_writer.failures_total > 0
        assert daemon.pusher.pushes_total > 0
        assert 'collector_push_total{mode="remote_write"}' in body
    finally:
        stop.set()
        for t in scrape_threads:
            t.join(timeout=2)
        daemon.stop()
        server.stop()
        receiver.shutdown()
        receiver.server_close()
