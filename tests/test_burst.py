"""Burst sampler (burstsampler.py, ISSUE 8): ring/fold mechanics, arm
modes + journal events, poll-tick integration, the /debug/burst control
endpoint, and the headline fault-injection acceptance: a scripted 50 ms
power spike between ticks is invisible in accelerator_power_watts but
appears in the kts_power_burst_* max/histogram series."""

import json
import urllib.error
import urllib.request

import pytest

from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.burstsampler import BurstSampler
from kube_gpu_stats_tpu.collectors import Collector, Device, Sample
from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.collectors.sysfs import SysfsCollector
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry
from kube_gpu_stats_tpu.testing.sysfs_fixture import make_sysfs
from kube_gpu_stats_tpu.tracing import Tracer


def get(snapshot, name, **want_labels):
    out = []
    for s in snapshot.series:
        if s.spec.name != name:
            continue
        labels = dict(s.labels)
        if all(labels.get(k) == v for k, v in want_labels.items()):
            out.append((labels, s.value))
    return out


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def make_sampler(collector=None, devices=None, clock=None, **kwargs):
    collector = collector if collector is not None else MockCollector(2)
    devices = devices if devices is not None else collector.discover()
    return BurstSampler(lambda: collector, lambda: devices,
                        clock=clock or FakeClock(), **kwargs)


class SteadyPowerCollector(Collector):
    """120 W at every tick instant — the 1 Hz view of a chip whose
    spikes land between ticks."""

    name = "steady"

    def discover(self):
        return [Device(0, "0", "/dev/accel0", "mock")]

    def sample(self, device):
        return Sample(device, {schema.POWER.name: 120.0})


# -- ring + fold mechanics ---------------------------------------------------

def test_drain_returns_and_clears():
    sampler = make_sampler()
    sampler.inject("0", 0.1, 100.0)
    sampler.inject("0", 0.2, 200.0)
    assert sampler.drain("0") == ((0.1, 100.0), (0.2, 200.0))
    assert sampler.drain("0") == ()
    assert sampler.drain("never-seen") == ()


def test_ring_caps_buffered_samples():
    sampler = make_sampler(ring=16)
    for i in range(64):
        sampler.inject("0", i * 0.01, float(i))
    samples = sampler.drain("0")
    assert len(samples) == 16
    assert samples[-1][1] == 63.0  # newest kept, oldest dropped


def test_fold_stats_and_histogram():
    sampler = make_sampler()
    sampler.fold("0", ((0.0, 90.0), (0.01, 900.0), (0.02, 120.0)))
    stats = sampler.last_fold["0"]
    assert stats["min"] == 90.0
    assert stats["max"] == 900.0
    assert stats["n"] == 3
    assert sampler.samples_total["0"] == 3
    # An empty fold must hold, not clear, the last stats.
    sampler.fold("0", ())
    assert sampler.last_fold["0"]["max"] == 900.0


def test_forget_device_purges_state():
    sampler = make_sampler()
    sampler.inject("0", 0.0, 100.0)
    sampler.fold("0", ((0.0, 100.0),))
    sampler.forget_device("0")
    assert sampler.drain("0") == ()
    assert "0" not in sampler.samples_total


def test_read_once_uses_collector_read_burst():
    mock = MockCollector(2)
    mock.burst_power_fn = lambda dev, t: 150.0 + dev.index
    sampler = make_sampler(collector=mock)
    assert sampler._read_once() == 2
    assert sampler.drain("0") == ((0.0, 150.0),)
    assert sampler.drain("1") == ((0.0, 151.0),)


def test_read_once_tolerates_backends_without_read_burst():
    class Bare(Collector):
        name = "bare"

        def discover(self):
            return [Device(0, "0", "/dev/accel0", "mock")]

        def sample(self, device):  # pragma: no cover
            raise NotImplementedError

    bare = Bare()
    sampler = make_sampler(collector=bare, devices=bare.discover())
    assert sampler._read_once() == 0


def test_sysfs_read_burst_matches_sample_and_caches_path(tmp_path):
    make_sysfs(tmp_path, num_chips=2, power_uw=120_000_000)
    collector = SysfsCollector(tmp_path)
    dev = collector.discover()[0]
    assert collector.read_burst(dev) == pytest.approx(120.0)
    # Parity with the 1 Hz environment read.
    assert collector.read_environment(dev)[schema.POWER.name] == \
        pytest.approx(120.0)
    # Cached path serves a changed value without re-globbing.
    power_file = (tmp_path / "class" / "accel" / "accel0" / "device"
                  / "hwmon" / "hwmon0" / "power1_average")
    power_file.write_text("900000000\n")
    assert collector.read_burst(dev) == pytest.approx(900.0)
    # A vanished attribute re-resolves (returns None, no crash).
    power_file.unlink()
    assert collector.read_burst(dev) is None


# -- arming ------------------------------------------------------------------

def test_arm_modes_and_journal_events():
    clock = FakeClock()
    tracer = Tracer()
    sampler = make_sampler(clock=clock, tracer=tracer, hold=30.0)
    assert not sampler.armed
    sampler.arm()
    assert sampler.armed
    clock.t = 29.0
    assert sampler.armed
    clock.t = 31.0
    assert not sampler.armed
    sampler.arm(5.0, reason="anomaly")
    sampler.disarm()
    assert not sampler.armed
    kinds = [e["kind"] for e in tracer.events()["events"]]
    assert kinds == ["burst_arm", "burst_arm", "burst_disarm"]
    assert sampler.arms_total == {"demand": 1, "anomaly": 1}


def test_continuous_mode_always_armed():
    clock = FakeClock()
    sampler = make_sampler(clock=clock, mode="continuous")
    clock.t = 1e9
    assert sampler.armed
    sampler.disarm()
    assert sampler.armed  # continuous has no disarmed state


def test_scan_journal_auto_arms_on_power_anomaly():
    tracer = Tracer()
    sampler = make_sampler(tracer=tracer)
    tracer.event("fleet_anomaly", "node-3: duty breached", anomaly="duty",
                 target="node-3")
    sampler.scan_journal()
    assert sampler.armed
    assert sampler.arms_total == {"anomaly": 1}


def test_scan_journal_ignores_unrelated_anomalies():
    tracer = Tracer()
    sampler = make_sampler(tracer=tracer)
    tracer.event("fleet_anomaly", "node-3: hbm breached", anomaly="hbm",
                 target="node-3")
    tracer.event("breaker", "libtpu:8431: closed -> open")
    sampler.scan_journal()
    assert not sampler.armed
    # Scans advance past consumed events — a later power anomaly is a
    # fresh trigger, earlier ones are never re-scanned.
    tracer.event("fleet_anomaly", "node-4: power breached",
                 anomaly="power", target="node-4")
    sampler.scan_journal()
    assert sampler.armed


# -- poll integration + the fault-injection acceptance ------------------------

def test_spike_between_ticks_invisible_at_1hz_visible_in_burst():
    """The headline: a 50 ms 900 W spike strictly between ticks never
    moves accelerator_power_watts (which reads 120 W at every tick
    instant) but lands in the burst max + histogram at full height."""
    reg = Registry()
    clock = FakeClock()
    sampler = make_sampler(collector=SteadyPowerCollector(), clock=clock)
    loop = PollLoop(SteadyPowerCollector(), reg, deadline=5.0,
                    burst_sampler=sampler, clock=clock)
    clock.t = 1.0
    loop.tick()
    # The spike: 50 ms at 900 W between the t=1 and t=2 ticks, sampled
    # at 100 Hz by the (test-driven) sampler thread.
    for i in range(5):
        sampler.inject("0", 1.5 + i * 0.01, 900.0)
    clock.t = 2.0
    loop.tick()
    snap = reg.snapshot()
    # 1 Hz gauge: flat 120 W — the spike is invisible by construction.
    assert get(snap, schema.POWER.name)[0][1] == 120.0
    # Burst series: the spike at its true height.
    assert get(snap, schema.BURST_WATTS.name, stat="max")[0][1] == 900.0
    assert get(snap, schema.BURST_WATTS.name, stat="mean")[0][1] == 900.0
    assert get(snap, schema.BURST_SAMPLES.name, chip="0")[0][1] == 5.0
    hist = [h for h in snap.histograms
            if h.spec.name == schema.BURST_HIST.name]
    assert len(hist) == 1
    # 900 W lands in the (750, 1000] bucket.
    bucket = schema.BURST_WATTS_BUCKETS.index(1000.0)
    assert hist[0].counts[bucket] == 5
    assert hist[0].total == 5
    loop.stop()


def test_burst_families_absent_without_sampler():
    reg = Registry()
    loop = PollLoop(MockCollector(1), reg, deadline=5.0)
    loop.tick()
    snap = reg.snapshot()
    assert get(snap, schema.BURST_ARMED.name) == []
    assert get(snap, schema.BURST_WATTS.name) == []
    loop.stop()


def test_armed_gauge_and_arms_counter_exported():
    reg = Registry()
    clock = FakeClock()
    sampler = make_sampler(collector=SteadyPowerCollector(), clock=clock)
    loop = PollLoop(SteadyPowerCollector(), reg, deadline=5.0,
                    burst_sampler=sampler, clock=clock)
    loop.tick()
    snap = reg.snapshot()
    assert get(snap, schema.BURST_ARMED.name)[0][1] == 0.0
    sampler.arm(10.0)
    loop.tick()
    snap = reg.snapshot()
    assert get(snap, schema.BURST_ARMED.name)[0][1] == 1.0
    assert get(snap, schema.BURST_ARMS.name, reason="demand")[0][1] == 1.0
    loop.stop()


def test_rediscover_purges_departed_device_burst_state():
    reg = Registry()
    clock = FakeClock()
    mock = MockCollector(2)
    sampler = make_sampler(collector=mock, clock=clock)
    loop = PollLoop(mock, reg, deadline=5.0, burst_sampler=sampler,
                    clock=clock)
    sampler.inject("1", 0.5, 500.0)
    clock.t = 1.0
    loop.tick()
    assert "1" in sampler.samples_total
    loop.replace_collector(MockCollector(1))
    clock.t = 2.0
    loop.tick()
    assert "1" not in sampler.samples_total
    loop.stop()


def test_poll_scan_journal_auto_arm_end_to_end():
    """A fleet_anomaly landing in the daemon's journal arms the sampler
    on the next tick (the anomaly -> sub-tick-evidence loop)."""
    reg = Registry()
    clock = FakeClock()
    tracer = Tracer()
    sampler = make_sampler(collector=SteadyPowerCollector(), clock=clock,
                           tracer=tracer)
    loop = PollLoop(SteadyPowerCollector(), reg, deadline=5.0,
                    burst_sampler=sampler, tracer=tracer, clock=clock)
    loop.tick()
    assert not sampler.armed
    tracer.event("fleet_anomaly", "self: power breached", anomaly="power",
                 target="self")
    clock.t = 1.0
    loop.tick()
    assert sampler.armed
    loop.stop()


# -- /debug/burst ------------------------------------------------------------

@pytest.fixture
def burst_server():
    from kube_gpu_stats_tpu.exposition import MetricsServer

    clock = FakeClock()
    sampler = make_sampler(clock=clock)
    server = MetricsServer(Registry(), host="127.0.0.1", port=0,
                           burst_provider=sampler)
    server.start()
    yield server, sampler
    server.stop()


def _get_json(url):
    with urllib.request.urlopen(url, timeout=5) as resp:
        return json.loads(resp.read().decode())


def test_debug_burst_status_arm_disarm(burst_server):
    server, sampler = burst_server
    base = f"http://127.0.0.1:{server.port}"
    payload = _get_json(base + "/debug/burst")
    assert payload["enabled"] and not payload["armed"]
    payload = _get_json(base + "/debug/burst?arm=12.5")
    assert payload["armed"] and payload["armed_for_s"] == 12.5
    assert sampler.armed
    payload = _get_json(base + "/debug/burst?disarm=1")
    assert payload["disarmed"] and not sampler.armed
    # Bare arm uses the default hold.
    payload = _get_json(base + "/debug/burst?arm=")
    assert payload["armed_for_s"] == sampler.hold


def test_debug_burst_404_without_provider():
    from kube_gpu_stats_tpu.exposition import MetricsServer

    server = MetricsServer(Registry(), host="127.0.0.1", port=0)
    server.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/debug/burst", timeout=5)
        assert err.value.code == 404
    finally:
        server.stop()


def test_debug_burst_behind_auth():
    import base64

    from kube_gpu_stats_tpu.exposition import MetricsServer

    sampler = make_sampler(clock=FakeClock())
    server = MetricsServer(
        Registry(), host="127.0.0.1", port=0,
        auth_username="ops",
        # sha256("secret")
        auth_password_sha256="2bb80d537b1da3e38bd30361aa855686bde0eacd"
                             "7162fef6a25fe97bf527a25b",
        burst_provider=sampler)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/debug/burst?arm=5"
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(url, timeout=5)
        assert err.value.code == 401
        assert not sampler.armed  # the 401 must short-circuit the arm
        request = urllib.request.Request(url, headers={
            "Authorization": "Basic "
            + base64.b64encode(b"ops:secret").decode()})
        with urllib.request.urlopen(request, timeout=5) as resp:
            assert resp.status == 200
        assert sampler.armed
    finally:
        server.stop()


# -- review-fix regressions --------------------------------------------------

def test_inject_rejects_nonfinite_and_negative_samples():
    """A garbage hwmon read parsing to inf/NaN/negative must not poison
    the cumulative histogram sum or the joules integral downstream."""
    sampler = make_sampler()
    sampler.inject("0", 0.1, float("inf"))
    sampler.inject("0", 0.2, float("nan"))
    sampler.inject("0", 0.3, -5.0)
    sampler.inject("0", 0.4, 100.0)
    assert sampler.drain("0") == ((0.4, 100.0),)


def test_arms_total_counts_transitions_not_extensions():
    clock = FakeClock()
    sampler = make_sampler(clock=clock, hold=30.0)
    sampler.arm()
    sampler.arm(60.0)           # extension of an open window: no count
    sampler.arm(5.0, reason="anomaly")  # still armed: no count
    assert sampler.arms_total == {"demand": 1}
    clock.t = 100.0             # window lapsed
    sampler.arm(reason="anomaly")
    assert sampler.arms_total == {"demand": 1, "anomaly": 1}
