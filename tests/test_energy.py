"""Energy accountant (energy.py, ISSUE 8): trapezoid-over-burst vs
tick-rectangle integration, per-pod attribution, checkpoint persistence
(monotone across restarts, torn-file recovery), the signed governance
digest + tamper detection, and `doctor --energy` verification."""

import json

import pytest

from kube_gpu_stats_tpu import doctor, schema
from kube_gpu_stats_tpu.energy import (EnergyAccountant, sign_payload,
                                       verify_payload)
from kube_gpu_stats_tpu.registry import Registry, SnapshotBuilder


def get(snapshot, name, **want_labels):
    out = []
    for s in snapshot.series:
        if s.spec.name != name:
            continue
        labels = dict(s.labels)
        if all(labels.get(k) == v for k, v in want_labels.items()):
            out.append((labels, s.value))
    return out


# -- integration math --------------------------------------------------------

def test_rectangle_between_tick_gauges():
    acct = EnergyAccountant()
    assert acct.observe("0", "p", "ns", 1.0, 100.0) == 0.0  # anchor only
    # Trapezoid between two equal 100 W points over 1 s = 100 J.
    assert acct.observe("0", "p", "ns", 2.0, 100.0) == pytest.approx(100.0)
    # Ramp 100 -> 200 W over 1 s = 150 J.
    assert acct.observe("0", "p", "ns", 3.0, 200.0) == pytest.approx(150.0)


def test_trapezoid_over_burst_samples_catches_spike_area():
    """A 50 ms 900 W spike between 120 W ticks: rectangle integration
    sees ~120 J; the burst-aware integral adds the spike's true area."""
    flat = EnergyAccountant()
    flat.observe("0", "p", "ns", 1.0, 120.0)
    flat_j = flat.observe("0", "p", "ns", 2.0, 120.0)
    bursty = EnergyAccountant()
    bursty.observe("0", "p", "ns", 1.0, 120.0)
    spike = tuple((1.5 + i * 0.01, 900.0) for i in range(6))
    burst_j = bursty.observe("0", "p", "ns", 2.0, 120.0, spike)
    assert flat_j == pytest.approx(120.0)
    # The spike plateau alone carries 900 W * 0.05 s = 45 J where the
    # flat integral had 120 W * 0.05 = 6 J; edges add transition area.
    assert burst_j > flat_j + 30.0


def test_gap_capped_after_outage():
    acct = EnergyAccountant(max_gap=10.0)
    acct.observe("0", "p", "ns", 0.0, 100.0)
    # A 1000 s outage must integrate at most max_gap's worth.
    assert acct.observe("0", "p", "ns", 1000.0, 100.0) == \
        pytest.approx(1000.0)  # 100 W * 10 s cap


def test_stale_tick_integrates_burst_only():
    acct = EnergyAccountant()
    acct.observe("0", "p", "ns", 1.0, 100.0)
    # No gauge reading, burst samples only: the samples integrate, no
    # endpoint is fabricated at `now`.
    joules = acct.observe("0", "p", "ns", 2.0, None,
                          ((1.1, 100.0), (1.2, 100.0)))
    assert joules == pytest.approx(0.2 * 100.0)


def test_garbage_samples_ignored():
    acct = EnergyAccountant()
    acct.observe("0", "p", "ns", 1.0, 100.0)
    joules = acct.observe(
        "0", "p", "ns", 2.0, 100.0,
        ((1.5, -5.0), (0.5, 100.0), (3.0, 100.0)))  # negative/old/future
    assert joules == pytest.approx(100.0)


def test_per_pod_attribution_follows_reschedule():
    acct = EnergyAccountant()
    acct.observe("0", "train-a", "ml", 1.0, 100.0)
    acct.observe("0", "train-a", "ml", 2.0, 100.0)
    # Pod rescheduled: the next tick's joules land on the new owner.
    acct.observe("0", "train-b", "ml", 3.0, 100.0)
    acct.observe("0", "", "", 4.0, 100.0)  # unattributed
    builder = SnapshotBuilder()
    acct.contribute(builder)
    snap = builder.build()
    assert get(snap, schema.ENERGY_POD.name, pod="train-a")[0][1] == \
        pytest.approx(100.0)
    assert get(snap, schema.ENERGY_POD.name, pod="train-b")[0][1] == \
        pytest.approx(100.0)
    assert get(snap, schema.ENERGY_POD.name, pod="")[0][1] == \
        pytest.approx(100.0)


def test_coverage_ratio_tracks_burst_share():
    acct = EnergyAccountant(cover_gap=0.1)
    acct.observe("0", "p", "ns", 0.0, 100.0)
    acct.observe("0", "p", "ns", 1.0, 100.0)  # 1 s uncovered
    acct.observe("0", "p", "ns", 2.0, 100.0,
                 tuple((1.0 + i * 0.05, 100.0) for i in range(1, 20)))
    assert 0.3 < acct.coverage_ratio < 0.6  # ~1 of ~2 s covered


# -- checkpoint persistence ---------------------------------------------------

def test_checkpoint_replay_keeps_counters_monotone(tmp_path):
    path = str(tmp_path / "energy.json")
    acct = EnergyAccountant(checkpoint_path=path)
    acct.observe("0", "train", "ml", 1.0, 100.0)
    acct.observe("0", "train", "ml", 2.0, 100.0)
    assert acct.checkpoint(force=True)
    # "Restart": a fresh accountant over the same path resumes totals.
    reborn = EnergyAccountant(checkpoint_path=path)
    assert reborn.checkpoint_loaded
    builder = SnapshotBuilder()
    reborn.contribute(builder)
    assert get(builder.build(), schema.ENERGY_POD.name,
               pod="train")[0][1] == pytest.approx(100.0)
    # And keeps counting up from there — monotone across the restart.
    reborn.observe("0", "train", "ml", 3.0, 100.0)
    reborn.observe("0", "train", "ml", 4.0, 100.0)
    builder = SnapshotBuilder()
    reborn.contribute(builder)
    assert get(builder.build(), schema.ENERGY_POD.name,
               pod="train")[0][1] == pytest.approx(200.0)


def test_checkpoint_rate_limited_and_forced(tmp_path):
    path = str(tmp_path / "energy.json")
    acct = EnergyAccountant(checkpoint_path=path, checkpoint_interval=3600)
    acct.observe("0", "p", "ns", 1.0, 100.0)
    acct.observe("0", "p", "ns", 2.0, 100.0)
    assert acct.checkpoint()          # first write always lands
    assert not acct.checkpoint()      # within the interval: skipped
    acct.observe("0", "p", "ns", 3.0, 100.0)
    assert acct.checkpoint(force=True)
    assert acct.checkpoint_writes == 2


def test_torn_main_file_recovers_from_wal(tmp_path):
    path = str(tmp_path / "energy.json")
    acct = EnergyAccountant(checkpoint_path=path)
    acct.observe("0", "train", "ml", 1.0, 100.0)
    acct.observe("0", "train", "ml", 2.0, 100.0)
    acct.checkpoint(force=True)
    # Simulate a crash mid-rename: main torn, wal intact.
    wal_state = (tmp_path / "energy.json").read_text()
    (tmp_path / "energy.json.wal").write_text(wal_state)
    (tmp_path / "energy.json").write_text("{torn")
    reborn = EnergyAccountant(checkpoint_path=path)
    assert reborn.checkpoint_loaded
    assert reborn.status()["pods"] == 1


def test_unreadable_checkpoint_starts_at_zero(tmp_path):
    path = str(tmp_path / "energy.json")
    (tmp_path / "energy.json").write_text("not json")
    acct = EnergyAccountant(checkpoint_path=path)
    assert not acct.checkpoint_loaded
    assert acct.status()["pods"] == 0


# -- governance digest --------------------------------------------------------

def test_signed_digest_verifies_and_tamper_fails():
    acct = EnergyAccountant(audit_key="attest-key", node="node-1")
    acct.observe("0", "train", "ml", 1.0, 100.0)
    acct.observe("0", "train", "ml", 2.0, 100.0)
    digest = acct.digest()
    assert digest["signed"] and digest["node"] == "node-1"
    assert verify_payload(digest, "attest-key")
    assert not verify_payload(digest, "wrong-key")
    tampered = dict(digest)
    tampered["per_pod"] = [["train", "ml", 1.0]]  # bill shaved
    assert not verify_payload(tampered, "attest-key")
    # Round-trips through JSON (the wire format) unchanged.
    wired = json.loads(json.dumps(digest))
    assert verify_payload(wired, "attest-key")


def test_unsigned_digest_never_verifies():
    acct = EnergyAccountant()
    digest = acct.digest()
    assert not digest["signed"] and "hmac" not in digest
    assert not verify_payload(digest, "any-key")
    assert not verify_payload({**digest, "hmac": ""}, "any-key")


def test_sign_payload_ignores_existing_hmac_field():
    payload = {"a": 1, "hmac": "junk"}
    assert sign_payload(payload, "k") == sign_payload({"a": 1}, "k")


# -- /debug/energy + doctor --energy ------------------------------------------

@pytest.fixture
def energy_server():
    from kube_gpu_stats_tpu.exposition import MetricsServer

    acct = EnergyAccountant(audit_key="attest-key", node="node-1")
    acct.observe("0", "train", "ml", 1.0, 100.0)
    acct.observe("0", "train", "ml", 2.0, 100.0)
    server = MetricsServer(Registry(), host="127.0.0.1", port=0,
                           energy_provider=acct)
    server.start()
    yield server, acct
    server.stop()


def test_doctor_energy_verifies_live_digest(energy_server):
    server, _ = energy_server
    result = doctor.check_energy(f"http://127.0.0.1:{server.port}",
                                 "attest-key")
    assert result.status == doctor.OK
    assert "signature verified" in result.detail
    assert "100.0 J" in result.detail


def test_doctor_energy_fails_on_wrong_key(energy_server):
    server, _ = energy_server
    result = doctor.check_energy(f"http://127.0.0.1:{server.port}",
                                 "other-key")
    assert result.status == doctor.FAIL
    assert "DOES NOT VERIFY" in result.detail


def test_doctor_energy_warns_without_local_key(energy_server):
    server, _ = energy_server
    result = doctor.check_energy(f"http://127.0.0.1:{server.port}", "")
    assert result.status == doctor.WARN
    assert "NOT verified" in result.detail


def test_doctor_energy_fails_on_unsigned_daemon_with_local_key():
    from kube_gpu_stats_tpu.exposition import MetricsServer

    acct = EnergyAccountant()  # daemon side unsigned
    server = MetricsServer(Registry(), host="127.0.0.1", port=0,
                           energy_provider=acct)
    server.start()
    try:
        result = doctor.check_energy(f"http://127.0.0.1:{server.port}",
                                     "attest-key")
        assert result.status == doctor.FAIL
        assert "UNSIGNED" in result.detail
    finally:
        server.stop()


def test_doctor_energy_warns_on_missing_endpoint():
    from kube_gpu_stats_tpu.exposition import MetricsServer

    server = MetricsServer(Registry(), host="127.0.0.1", port=0)
    server.start()
    try:
        result = doctor.check_energy(f"http://127.0.0.1:{server.port}",
                                     "attest-key")
        assert result.status == doctor.WARN
        assert "no /debug/energy" in result.detail
    finally:
        server.stop()


def test_poll_wires_attribution_into_energy():
    """End-to-end through the poll loop: per-pod joules ride the
    kubelet attribution the tick plan already holds."""
    from kube_gpu_stats_tpu.collectors import Collector, Device, Sample
    from kube_gpu_stats_tpu.poll import PollLoop

    class PowerCollector(Collector):
        name = "power"

        def discover(self):
            return [Device(0, "0", "/dev/accel0", "mock")]

        def sample(self, device):
            return Sample(device, {schema.POWER.name: 200.0})

    class StaticAttribution:
        def lookup(self, device):
            return {"pod": "train-7", "namespace": "ml",
                    "container": "worker"}

    class FakeClock:
        t = 0.0

        def __call__(self):
            return self.t

    clock = FakeClock()
    acct = EnergyAccountant()
    reg = Registry()
    loop = PollLoop(PowerCollector(), reg, deadline=5.0,
                    attribution=StaticAttribution(), energy=acct,
                    clock=clock)
    clock.t = 1.0
    loop.tick()
    clock.t = 2.0
    loop.tick()
    snap = reg.snapshot()
    rows = get(snap, schema.ENERGY_POD.name, pod="train-7", namespace="ml")
    assert rows and rows[0][1] == pytest.approx(200.0)
    assert get(snap, schema.ENERGY_COVERAGE.name)[0][1] == 0.0
    loop.stop()


def test_inf_gauge_and_samples_rejected():
    """Review fix: an inf integrand (garbage sysfs text parses to
    float('inf')) must not make the per-pod counter — and the JSON
    checkpoint — permanently non-finite."""
    acct = EnergyAccountant()
    acct.observe("0", "p", "ns", 1.0, 100.0)
    joules = acct.observe("0", "p", "ns", 2.0, float("inf"),
                          ((1.5, float("inf")),))
    assert joules == 0.0
    assert acct.observe("0", "p", "ns", 3.0, 100.0) == \
        pytest.approx(200.0)  # 2 s gap from the t=1 anchor


def test_concurrent_checkpoints_serialize(tmp_path):
    """Review fix: the pool-submitted checkpoint and Daemon.stop's
    forced one must serialize on the io lock — concurrent writers on
    one .wal could publish a torn main file."""
    import threading

    path = str(tmp_path / "energy.json")
    acct = EnergyAccountant(checkpoint_path=path)
    for i in range(50):
        acct.observe("0", "p", "ns", float(i), 100.0)
    threads = [threading.Thread(target=acct.checkpoint, args=(True,))
               for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    reborn = EnergyAccountant(checkpoint_path=path)
    assert reborn.checkpoint_loaded  # main file parseable, never torn


def test_crash_between_fsync_and_rename_recovers_newer_wal(tmp_path):
    """Review fix: a .wal newer than main (crash after fsync, before
    rename) must win the load — main alone would restart counters below
    already-scraped values."""
    path = str(tmp_path / "energy.json")
    acct = EnergyAccountant(checkpoint_path=path)
    acct.observe("0", "train", "ml", 1.0, 100.0)
    acct.observe("0", "train", "ml", 2.0, 100.0)
    acct.checkpoint(force=True)  # main at seq 2
    acct.observe("0", "train", "ml", 3.0, 100.0)
    # Simulate the torn second checkpoint: newer state fsynced to .wal,
    # crash before the rename.
    import json as json_mod
    with acct._lock:
        newer = acct._state()
    (tmp_path / "energy.json.wal").write_text(json_mod.dumps(newer))
    reborn = EnergyAccountant(checkpoint_path=path)
    assert reborn.checkpoint_loaded
    # The wal's newer state won (main stopped at seq 1: the first
    # observe was anchor-only and never counted).
    assert reborn.status()["seq"] == 2
    assert reborn.status()["seq"] > 1


def test_first_checkpoint_crash_recovers_from_wal_alone(tmp_path):
    """Review fix: no main file at all (crash during the FIRST
    checkpoint's rename) must still load the fsynced .wal, not start
    at zero via the missing-main short-circuit."""
    import json as json_mod

    path = str(tmp_path / "energy.json")
    acct = EnergyAccountant()
    acct.observe("0", "train", "ml", 1.0, 100.0)
    acct.observe("0", "train", "ml", 2.0, 100.0)
    with acct._lock:
        state = acct._state()
    (tmp_path / "energy.json.wal").write_text(json_mod.dumps(state))
    reborn = EnergyAccountant(checkpoint_path=path)
    assert reborn.checkpoint_loaded
    assert reborn.status()["pods"] == 1


def test_daemon_derives_cover_gap_from_burst_hz():
    """Review fix: coverage must follow --burst-hz — a 5 Hz sampler's
    honest 0.2 s inter-sample gap counts as covered."""
    from kube_gpu_stats_tpu.config import Config
    from kube_gpu_stats_tpu.daemon import Daemon

    daemon = Daemon(Config(backend="null", listen_port=0, burst_hz=5.0,
                           attribution="off"))
    try:
        assert daemon.energy._cover_gap == pytest.approx(0.8)  # 4/hz
    finally:
        daemon.start()  # stop() on a never-started HTTP server hangs
        daemon.stop()


# -- cross-version checkpoint tolerance (ISSUE 14 satellite) -----------------

def test_checkpoint_pruned_keys_default_and_warn(tmp_path, caplog):
    """An older build wrote fewer keys: the loader defaults the missing
    ones with a warning instead of KeyError-ing the restart path, and
    the pod totals it DID write survive."""
    import json
    import logging

    path = tmp_path / "energy.json"
    path.write_text(json.dumps({
        "version": 1,
        "per_pod": [["train-pod", "ml", 123.5],
                    ["short-record"]],  # tolerated: skipped
        # covered_seconds/total_seconds/seq absent (older build)
    }))
    with caplog.at_level(logging.WARNING):
        acct = EnergyAccountant(checkpoint_path=str(path))
    assert acct.checkpoint_loaded
    assert acct._per_pod[("train-pod", "ml")] == 123.5
    assert acct.covered_seconds == 0.0 and acct.total_seconds == 0.0
    assert any("missing" in r.message for r in caplog.records)


def test_checkpoint_future_major_quarantined_byte_identical(tmp_path):
    """Refuse-don't-corrupt: a checkpoint from a newer build parks
    aside intact (a downgrade replays it later); the accountant starts
    degraded from empty — and NEVER truncates what it cannot read."""
    import json

    from kube_gpu_stats_tpu import wal

    wal.reset_quarantine_stats()
    path = tmp_path / "energy.json"
    raw = json.dumps({"version": 99, "per_pod": [["p", "ns", 1.0]],
                      "new_field": True}).encode()
    path.write_bytes(raw)
    acct = EnergyAccountant(checkpoint_path=str(path))
    assert not acct.checkpoint_loaded and not acct._per_pod
    assert not path.exists()
    aside = tmp_path / "energy.json.skew-v99"
    assert aside.read_bytes() == raw
    assert wal.quarantine_counts() == {"energy": 1}
    # The degraded accountant's own writes go to the MAIN path — the
    # parked file is never overwritten.
    acct.observe("dev0", "p2", "ns", 1.0, 100.0)
    acct.observe("dev0", "p2", "ns", 2.0, 100.0)
    assert acct.checkpoint(force=True)
    assert aside.read_bytes() == raw
    wal.reset_quarantine_stats()


# -- /debug/efficiency + doctor --efficiency (ISSUE 20) ------------------------
# The federation rollup carries the same attestation contract as the
# per-node /debug/energy digest above; this matrix mirrors the
# doctor --energy one: OK verified, FAIL on tamper or a wrong key,
# WARN unsigned-without-a-local-key.

@pytest.fixture
def efficiency_server():
    from kube_gpu_stats_tpu.efficiency import (EfficiencyLens,
                                               build_attestation)
    from kube_gpu_stats_tpu.exposition import MetricsServer

    engine = EfficiencyLens(warmup_refreshes=1, idle_refreshes=2)
    for seq in range(1, 5):
        engine.observe(seq, 1000.0 + seq,
                       {("train-1", "ml"): {
                           "duty": 0.0, "power": 10.0, "steps": None,
                           "chips": 4, "joules": None, "coverage": 1.0}})
    leaf = {"per_pod": [["train-1", "ml", 250.0]],
            "coverage_ratio": 0.8, "signed": True, "hmac": "bb" * 32}
    state = {"payload": build_attestation(
        engine.summary(), {"http://leaf-a/metrics": leaf},
        "attest-key", node="hub-1", generated_at=777.0)}
    server = MetricsServer(Registry(), host="127.0.0.1", port=0,
                           efficiency_provider=lambda: state["payload"])
    server.start()
    yield server, state
    server.stop()


def test_doctor_efficiency_verifies_live_attestation(efficiency_server):
    server, _ = efficiency_server
    result = doctor.check_efficiency(
        f"http://127.0.0.1:{server.port}", "attest-key")
    # The (real) idle pod rides the verified attestation as a WARN.
    assert result.status == doctor.WARN
    assert "signature verified" in result.detail
    assert "ml/train-1: idle-reservation" in result.detail
    assert "250.0 J attributed" in result.detail
    assert "1 leaf energy digest(s) (1 signed)" in result.detail


def test_doctor_efficiency_fails_on_wrong_key(efficiency_server):
    server, _ = efficiency_server
    result = doctor.check_efficiency(
        f"http://127.0.0.1:{server.port}", "other-key")
    assert result.status == doctor.FAIL
    assert "DOES NOT VERIFY" in result.detail


def test_doctor_efficiency_fails_on_bit_flipped_digest(efficiency_server):
    """Tamper in flight: shave one leaf's joule bill inside the signed
    payload — the hub-level HMAC must catch it even though the leaf
    digest carries its own (stale) HMAC."""
    server, state = efficiency_server
    tampered = json.loads(json.dumps(state["payload"]))
    tampered["leaves"]["http://leaf-a/metrics"]["per_pod"] = [
        ["train-1", "ml", 1.0]]
    state["payload"] = tampered
    result = doctor.check_efficiency(
        f"http://127.0.0.1:{server.port}", "attest-key")
    assert result.status == doctor.FAIL
    assert "DOES NOT VERIFY" in result.detail


def test_doctor_efficiency_warns_without_local_key(efficiency_server):
    server, _ = efficiency_server
    result = doctor.check_efficiency(
        f"http://127.0.0.1:{server.port}", "")
    assert result.status == doctor.WARN
    assert "NOT verified" in result.detail


def test_doctor_efficiency_fails_on_unsigned_hub_with_local_key(
        efficiency_server):
    from kube_gpu_stats_tpu.efficiency import build_attestation

    server, state = efficiency_server
    state["payload"] = build_attestation({}, {}, "")  # hub unsigned
    result = doctor.check_efficiency(
        f"http://127.0.0.1:{server.port}", "attest-key")
    assert result.status == doctor.FAIL
    assert "UNSIGNED" in result.detail


def test_doctor_efficiency_warns_on_disabled_hub(efficiency_server):
    server, state = efficiency_server
    state["payload"] = {"enabled": False, "reason": "--no-efficiency"}
    result = doctor.check_efficiency(
        f"http://127.0.0.1:{server.port}", "attest-key")
    assert result.status == doctor.WARN
    assert "--no-efficiency" in result.detail


def test_doctor_efficiency_warns_on_missing_endpoint():
    from kube_gpu_stats_tpu.exposition import MetricsServer

    server = MetricsServer(Registry(), host="127.0.0.1", port=0)
    server.start()
    try:
        result = doctor.check_efficiency(
            f"http://127.0.0.1:{server.port}", "attest-key")
        assert result.status == doctor.WARN
        assert "no /debug/efficiency" in result.detail
    finally:
        server.stop()
