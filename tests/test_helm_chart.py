"""Helm chart sanity (component C8 — deployment assets).

`helm` itself is not available in CI, so these tests pin what is checkable
statically: chart metadata, values parseability, that every `.Values.*`
path referenced by a template exists in values.yaml (the drift that breaks
charts in practice), and that the chart's DaemonSet keeps parity with the
raw-manifest deployment's host surfaces.
"""

import pathlib
import re

import yaml

CHART = pathlib.Path(__file__).parent.parent / "deploy" / "helm" / "kube-tpu-stats"

_VALUES_REF = re.compile(r"\.Values\.([A-Za-z0-9_.]+)")


def template_texts():
    return {p.name: p.read_text() for p in (CHART / "templates").glob("*")}


def test_chart_metadata():
    chart = yaml.safe_load((CHART / "Chart.yaml").read_text())
    assert chart["apiVersion"] == "v2"
    assert chart["name"] == "kube-tpu-stats"
    assert chart["version"]
    assert chart["appVersion"]


def test_values_parse():
    values = yaml.safe_load((CHART / "values.yaml").read_text())
    assert values["listenPort"] == 9400
    assert values["backend"] == "auto"


def test_every_values_reference_exists():
    values = yaml.safe_load((CHART / "values.yaml").read_text())
    missing = []
    for name, text in template_texts().items():
        for ref in _VALUES_REF.findall(text):
            node = values
            for part in ref.split("."):
                if isinstance(node, dict) and part in node:
                    node = node[part]
                else:
                    missing.append(f"{name}: .Values.{ref}")
                    break
    assert missing == [], missing


def test_template_braces_balanced():
    for name, text in template_texts().items():
        assert text.count("{{") == text.count("}}"), name


def test_daemonset_parity_with_raw_manifest():
    """The chart's DaemonSet must keep the raw manifest's host surfaces:
    sysfs, PodResources socket, device-plugin checkpoint dir, hostNetwork,
    TPU toleration, and both health probes."""
    ds = template_texts()["daemonset.yaml"]
    for needle in (
        "mountPath: /sys",
        "mountPath: /var/lib/kubelet/pod-resources",
        "mountPath: /var/lib/kubelet/device-plugins",
        "path: /healthz",
        "path: /readyz",
        "readOnlyRootFilesystem: true",
        "hostNetwork:",
    ):
        assert needle in ds, needle
    raw = (CHART.parent.parent / "daemonset.yaml").read_text()
    raw_mounts = set(re.findall(r"mountPath: (\S+)", raw))
    chart_mounts = set(re.findall(r"mountPath: (\S+)", ds))
    assert raw_mounts <= chart_mounts


def test_conditional_templates_are_gated():
    texts = template_texts()
    assert texts["servicemonitor.yaml"].startswith(
        "{{- if .Values.serviceMonitor.enabled }}"
    )
    assert texts["serviceaccount.yaml"].startswith(
        "{{- if .Values.serviceAccount.create }}"
    )
    assert texts["service.yaml"].startswith("{{- if .Values.service.enabled }}")


def test_hub_servicemonitor_gated_and_selector_matches_service():
    """The hub ServiceMonitor block in templates/hub.yaml must be gated
    on BOTH hub.enabled and serviceMonitor.enabled, and its selector
    must match the hub Service's labels — with no helm binary in CI, a
    renamed -hub label suffix would otherwise ship a ServiceMonitor
    that selects nothing and silently kills hub scraping."""
    text = template_texts()["hub.yaml"]
    assert ("{{- if and .Values.hub.enabled .Values.serviceMonitor.enabled }}"
            in text)
    sm_block = text.split("kind: ServiceMonitor", 1)[1]
    svc_block = text.split("kind: Service\n", 1)[1].split("---", 1)[0]
    lines = sm_block.splitlines()
    start = next(i for i, l in enumerate(lines)
                 if l.strip() == "matchLabels:")
    indent = len(lines[start]) - len(lines[start].lstrip())
    selector = []
    for line in lines[start + 1:]:
        if not line.strip() or len(line) - len(line.lstrip()) <= indent:
            break
        selector.append(line.strip())
    assert selector, "ServiceMonitor has no matchLabels entries"
    for entry in selector:
        # Every matchLabels line must appear verbatim in the Service's
        # label set (same templated name/instance expressions).
        assert entry in svc_block, entry


def test_template_control_structures_balance():
    """No helm binary in CI: at least pin that every {{ if }}/{{ range }}
    has a matching {{ end }} per template (the typo class that makes
    `helm template` fail at install time)."""
    for name, text in template_texts().items():
        opens = len(re.findall(r"\{\{-?\s*(?:if|range|with|define|block)\b",
                               text))
        ends = len(re.findall(r"\{\{-?\s*end\s*-?\}\}", text))
        assert opens == ends, (
            f"{name}: {opens} if/range/with vs {ends} end")


def test_daemonset_probe_scheme_follows_tls():
    """TLS wraps the one listener that also serves the probes: the chart
    must switch httpGet probes to HTTPS under TLS and to tcpSocket under
    mTLS (kubelet presents no client cert) — review finding."""
    text = template_texts()["daemonset.yaml"]
    assert "scheme: HTTPS" in text
    assert "tcpSocket:" in text
    # mTLS branch must come first (clientCaFile implies certFile).
    assert text.index(".Values.tls.clientCaFile") < text.index("scheme: HTTPS")


def test_hub_template_shape():
    """The optional hub component must run the hub subcommand against the
    mounted targets file, carry both probes, and be fully gated on
    hub.enabled (disabled by default)."""
    text = template_texts()["hub.yaml"]
    assert text.startswith("{{- if .Values.hub.enabled }}")
    assert '- "hub"' in text
    assert '"--targets-file"' in text
    assert "/healthz" in text and "/readyz" in text
    # No checksum-roll annotation: the hub re-reads the mounted targets
    # file every refresh, so ConfigMap edits apply without a restart.
    assert "checksum/targets" not in text
    values = yaml.safe_load((CHART / "values.yaml").read_text())
    assert values["hub"]["enabled"] is False
    assert values["hub"]["targets"] == []
