"""Node-side spill queue (spillq.py + DeltaPublisher integration,
ISSUE 13 tentpole): offline publishers spool every published snapshot
to a bounded on-disk ring, drain oldest-first rate-limited on
reconnect, honor hub sheds without FULL amplification, and account
every dropped frame."""

import time

from kube_gpu_stats_tpu import delta, schema
from kube_gpu_stats_tpu.exposition import MetricsServer
from kube_gpu_stats_tpu.hub import Hub
from kube_gpu_stats_tpu.registry import Registry, SnapshotBuilder
from kube_gpu_stats_tpu.resilience import TokenBucket
from kube_gpu_stats_tpu.spillq import SpillQueue
from kube_gpu_stats_tpu.tracing import Tracer


def _worker_registry():
    worker = Registry()

    def publish(duty: float) -> None:
        builder = SnapshotBuilder()
        labels = (("accel_type", "tpu-v5p"), ("chip", "0"),
                  ("device_path", "/dev/accel0"), ("uuid", ""))
        builder.add(schema.DEVICE_UP, 1.0, labels)
        builder.add(schema.DUTY_CYCLE, duty, labels)
        worker.publish(builder.build())

    return worker, publish


def _push_hub(**kw):
    kw.setdefault("push_fence", 1e9)
    return Hub([], targets_provider=lambda: [], interval=10.0, **kw)


def _hub_duty(hub) -> str:
    hub.refresh_once()
    return next(l for l in hub.registry.snapshot().render().splitlines()
                if l.startswith("accelerator_duty_cycle"))


def test_spill_queue_roundtrip_and_status(tmp_path):
    q = SpillQueue(str(tmp_path / "spill"), fsync=False)
    q.spool(100.0, "metric_a 1\n")
    q.spool(101.0, "metric_a 2\n")
    assert q.depth() == 2
    assert q.status()["depth_frames"] == 2
    ts, body = q.peek()
    assert ts == 100.0 and body == "metric_a 1\n"
    q.commit()
    assert q.depth() == 1 and q.drained_total == 1
    assert q.peek()[1] == "metric_a 2\n"
    q.close()


def test_spill_queue_bounded_drops_oldest_and_journals(tmp_path):
    tracer = Tracer(enabled=True)
    q = SpillQueue(str(tmp_path / "spill"), max_bytes=2048,
                   fsync=False, tracer=tracer)
    import random

    rng = random.Random(13)
    for i in range(200):
        # Incompressible-ish bodies so the byte bound actually engages.
        q.spool(float(i), "m %d # %s\n" % (
            i, "".join(rng.choice("abcdefgh") for _ in range(80))))
    assert q.dropped_total > 0
    # Oldest-first: the head of the surviving queue is NOT frame 0.
    ts, _body = q.peek()
    assert ts > 0.0
    assert q.spooled_total == 200
    assert q.depth() + q.dropped_total == 200
    events = tracer.events(0)["events"]
    assert any(e.get("kind") == "spill_drop" for e in events)
    q.close()


def test_offline_publisher_spools_at_publish_cadence(tmp_path):
    """A down hub no longer costs a tick per backoff window: every
    push_once while offline spools (local disk — no backoff), and
    consecutive_failures stays 0 so the follower keeps publish cadence;
    the network PROBE alone backs off."""
    worker, publish = _worker_registry()
    spill = SpillQueue(str(tmp_path / "spill"), fsync=False)
    publisher = delta.DeltaPublisher(
        worker, "http://127.0.0.1:9", source="node-a",  # port 9: discard
        timeout=0.2, spill=spill, drain_rate=1000.0)
    try:
        for i in range(4):
            publish(10.0 + i)
            publisher.push_once()
        assert spill.depth() == 4
        assert publisher.consecutive_failures == 0
        # One real probe (the first push); the rest spooled behind the
        # probe backoff without hammering the dead link.
        assert publisher.failures_total >= 1
        assert spill.spooled_total == 4
    finally:
        publisher.stop()


def test_drain_after_partition_zero_loss_one_full_no_409_loop(tmp_path):
    """The tentpole acceptance shape at unit scale: a partition's whole
    backlog lands late-but-complete — ONE session FULL, the rest
    deltas, zero resyncs, zero drops — then live deltas resume."""
    worker, publish = _worker_registry()
    hub = _push_hub()
    server = MetricsServer(hub.registry, host="127.0.0.1", port=0,
                           ingest_provider=hub.delta.handle)
    server.start()
    port = server.port
    server.stop()  # partition: nothing listening
    spill = SpillQueue(str(tmp_path / "spill"), fsync=False)
    publisher = delta.DeltaPublisher(
        worker, f"http://127.0.0.1:{port}", source="node-a",
        timeout=0.5, spill=spill, drain_rate=10_000.0)
    try:
        for i in range(6):
            publish(10.0 + i)
            publisher.push_once()
        assert spill.depth() == 6
        # Link restored.
        server2 = MetricsServer(hub.registry, host="127.0.0.1", port=port,
                                ingest_provider=hub.delta.handle)
        server2.start()
        try:
            publisher._probe_at = 0.0  # the probe window elapsed
            publish(99.0)
            publisher.push_once()  # spools the live frame, drains all 7
            assert spill.depth() == 0
            assert spill.drained_total == 7
            assert spill.dropped_total == 0
            stats = hub.delta.stats()
            assert stats["full_frames"] == 1  # exactly one session FULL
            assert stats["delta_frames"] == 6
            assert stats["resyncs"] == 0     # never a 409 loop
            assert stats["duplicate_frames"] == 0
            assert _hub_duty(hub).endswith(" 99")
            # Live mode resumed: the next publish goes straight through.
            publish(123.0)
            publisher.push_once()
            assert spill.depth() == 0
            assert _hub_duty(hub).endswith(" 123")
        finally:
            server2.stop()
    finally:
        publisher.stop()
        hub.stop()


def test_drain_rate_is_token_bucket_limited(tmp_path):
    """Drain never stampedes a recovering hub: one push_once sends at
    most the bucket's burst, and the amortized rate is the knob."""
    worker, publish = _worker_registry()
    hub = _push_hub()
    server = MetricsServer(hub.registry, host="127.0.0.1", port=0,
                           ingest_provider=hub.delta.handle)
    server.start()
    spill = SpillQueue(str(tmp_path / "spill"), fsync=False)
    publisher = delta.DeltaPublisher(
        worker, f"http://127.0.0.1:{server.port}", source="node-a",
        spill=spill, drain_rate=4.0)
    clock = [0.0]
    publisher._drain_bucket = TokenBucket(4.0, 2.0,
                                          clock=lambda: clock[0])
    try:
        for i in range(10):
            publish(float(i))
            spill.spool(time.time(), worker.rendered()[0].decode())
        depth = spill.depth()
        publish(50.0)
        publisher.push_once()  # spools 1 more, drains at most burst=2
        assert depth + 1 - spill.depth() <= 2
        clock[0] += 1.0  # one second refills 4 tokens
        publisher.push_once()
        assert spill.drained_total <= 2 + 4 + 1
    finally:
        publisher.stop()
        server.stop()
        hub.stop()


def test_drain_honors_shed_without_full_amplification(tmp_path):
    """A recovering hub shedding 429+Retry-After pauses the drain; the
    shed frame stays spooled (known-unapplied, re-sent later) and is
    NEVER promoted to a FULL — 0 FULL amplification."""
    worker, publish = _worker_registry()
    hub = _push_hub(ingest_lanes=1, ingest_delta_rate=1e-6)
    server = MetricsServer(hub.registry, host="127.0.0.1", port=0,
                           ingest_provider=hub.delta.handle)
    server.start()
    spill = SpillQueue(str(tmp_path / "spill"), fsync=False)
    publisher = delta.DeltaPublisher(
        worker, f"http://127.0.0.1:{server.port}", source="node-a",
        spill=spill, drain_rate=10_000.0)
    try:
        for i in range(3):
            publish(10.0 + i)
            spill.spool(time.time(), worker.rendered()[0].decode())
        publish(40.0)
        publisher.push_once()
        # Frame 1 went as the session FULL (never rate-shed); frame 2
        # was a DELTA the empty bucket refused.
        assert publisher.shed_honored_total == 1
        assert spill.depth() == 3  # shed frame + frame 3 + the live one
        stats = hub.delta.stats()
        assert stats["full_frames"] == 1
        # Pressure lifts: drain completes as DELTAS off the acked state.
        for lane in hub.delta._lanes:
            lane.bucket = None
        publisher._shed_until = 0.0
        publisher.push_once()
        stats = hub.delta.stats()
        assert stats["full_frames"] == 1  # STILL one: no amplification
        assert stats["resyncs"] == 0
        assert spill.depth() == 0
    finally:
        publisher.stop()
        server.stop()
        hub.stop()


def test_undecodable_frame_skipped_and_counted(tmp_path):
    """A CRC-valid record that fails snappy/utf-8 decode (version skew)
    is consumed rather than wedging the drain — and COUNTED, so the
    spooled == drained + dropped + undecodable + depth accounting
    never silently leaks."""
    q = SpillQueue(str(tmp_path / "spill"), fsync=False)
    q.spool(1.0, "metric_a 1\n")
    q._ring.append(2.0, b"\xff\xff\xff\xffgarbage")  # not snappy
    q.spool(3.0, "metric_a 3\n")
    assert q.peek()[1] == "metric_a 1\n"
    q.commit()
    assert q.peek()[1] == "metric_a 3\n"  # skipped PAST the bad record
    assert q.undecodable_total == 1
    assert q.status()["undecodable_total"] == 1
    q.close()


def test_drain_cursor_persists_mid_drain(tmp_path):
    """Every _drain_backlog exit persists the cursor (dirty-gated), not
    just the backlog-cleared one: a crash mid-way through a rate-paced
    drain replays at most the current cycle's window, never the whole
    already-drained prefix."""
    worker, publish = _worker_registry()
    hub = _push_hub()
    server = MetricsServer(hub.registry, host="127.0.0.1", port=0,
                           ingest_provider=hub.delta.handle)
    server.start()
    spill = SpillQueue(str(tmp_path / "spill"), fsync=False)
    publisher = delta.DeltaPublisher(
        worker, f"http://127.0.0.1:{server.port}", source="node-a",
        spill=spill, drain_rate=2.0)
    clock = [0.0]
    publisher._drain_bucket = TokenBucket(2.0, 2.0,
                                          clock=lambda: clock[0])
    try:
        for i in range(8):
            publish(float(i))
            spill.spool(time.time(), worker.rendered()[0].decode())
        publish(50.0)
        publisher.push_once()  # spools 1 more, drains at most burst=2
        drained = spill.drained_total
        assert 0 < drained < 9
        # Crash: NO stop()/close()/save — the fresh queue must resume
        # past the committed prefix off the per-cycle persisted cursor.
        spill2 = SpillQueue(str(tmp_path / "spill"), fsync=False)
        assert spill2.depth() == 9 - drained
    finally:
        publisher.stop()
        server.stop()
        hub.stop()


def test_spill_backlog_survives_publisher_restart(tmp_path):
    """Crash mid-partition: the next publisher process resumes the
    drain from disk (the at-least-once cursor window may re-send; the
    hub's retransmit dedup absorbs that)."""
    worker, publish = _worker_registry()
    spill = SpillQueue(str(tmp_path / "spill"), fsync=False)
    publisher = delta.DeltaPublisher(
        worker, "http://127.0.0.1:9", source="node-a",
        timeout=0.2, spill=spill, drain_rate=1000.0)
    for i in range(3):
        publish(10.0 + i)
        publisher.push_once()
    publisher.stop()  # close() saves the cursor
    hub = _push_hub()
    server = MetricsServer(hub.registry, host="127.0.0.1", port=0,
                           ingest_provider=hub.delta.handle)
    server.start()
    spill2 = SpillQueue(str(tmp_path / "spill"), fsync=False)
    assert spill2.depth() == 3
    publisher2 = delta.DeltaPublisher(
        worker, f"http://127.0.0.1:{server.port}", source="node-a",
        spill=spill2, drain_rate=1000.0)
    try:
        publish(77.0)
        publisher2.push_once()
        assert spill2.depth() == 0
        assert _hub_duty(hub).endswith(" 77")
    finally:
        publisher2.stop()
        server.stop()
        hub.stop()


def test_spill_status_and_metrics_fold(tmp_path):
    from kube_gpu_stats_tpu.registry import contribute_egress_stats

    worker, publish = _worker_registry()
    spill = SpillQueue(str(tmp_path / "spill"), fsync=False)
    publisher = delta.DeltaPublisher(
        worker, "http://127.0.0.1:9", source="node-a",
        timeout=0.2, spill=spill)
    try:
        publish(10.0)
        publisher.push_once()
        status = publisher.spill_status()
        assert status["depth_frames"] == 1
        assert status["drain_rate"] == 50.0
        assert status["draining"] is True
        builder = SnapshotBuilder()
        contribute_egress_stats(builder, {"spill": status})
        text = builder.build().render()
        assert 'kts_spill_frames_total{state="spooled"} 1' in text
        assert "kts_spill_depth_frames 1" in text
        assert "kts_spill_dropped_total 0" in text
        assert "kts_spill_oldest_seconds" in text
    finally:
        publisher.stop()


def test_publisher_without_spill_keeps_legacy_behavior():
    """No spill configured: failures back off the push cadence exactly
    as before (the tier-1 contract)."""
    worker, publish = _worker_registry()
    publisher = delta.DeltaPublisher(
        worker, "http://127.0.0.1:9", source="node-a", timeout=0.2)
    try:
        publish(10.0)
        publisher.push_once()
        assert publisher.consecutive_failures == 1
        assert publisher.failures_total == 1
        assert publisher.spill_status() is None
        assert publisher.backlog_depth == 0
    finally:
        publisher.stop()


def test_daemon_wires_spill_queue(tmp_path):
    from kube_gpu_stats_tpu.config import Config
    from kube_gpu_stats_tpu.daemon import Daemon

    d = Daemon(Config(backend="mock", attribution="off", listen_port=0,
                      hub_url="http://127.0.0.1:9",
                      hub_spill_dir=str(tmp_path / "spill"),
                      hub_spill_max_bytes=1 << 20,
                      hub_drain_rate=25.0))
    try:
        assert d.delta_pusher is not None
        assert d.delta_pusher._spill is not None
        assert d.delta_pusher.drain_rate == 25.0
        # The egress fold reaches the daemon's own exposition.
        d.poll.tick()
        text = d.registry.snapshot().render()
        assert "kts_spill_depth_frames" in text
    finally:
        d.poll.stop()
        d.collector.close()


def test_spill_flags_parse_and_validate(capsys):
    import pytest

    from kube_gpu_stats_tpu.config import from_args

    cfg = from_args(["--backend", "mock", "--hub-url", "http://h:9401",
                     "--hub-spill-dir", "/var/spool/kts",
                     "--hub-spill-max-bytes", str(1 << 20),
                     "--hub-drain-rate", "10"])
    assert cfg.hub_spill_dir == "/var/spool/kts"
    assert cfg.hub_spill_max_bytes == 1 << 20
    assert cfg.hub_drain_rate == 10.0
    with pytest.raises(SystemExit):
        from_args(["--backend", "mock", "--hub-drain-rate", "0"])
    assert "--hub-drain-rate" in capsys.readouterr().err
    with pytest.raises(SystemExit):
        from_args(["--backend", "mock", "--hub-spill-max-bytes", "10"])


# --- doctor --egress --------------------------------------------------------

def _egress_server(payload):
    srv = MetricsServer(Registry(), host="127.0.0.1", port=0,
                        egress_provider=lambda: payload)
    srv.start()
    return srv


def test_doctor_egress_summarizes_healthy_spill():
    from kube_gpu_stats_tpu import doctor

    srv = _egress_server({
        "enabled": True,
        "spill": {"depth_frames": 2, "bytes": 512, "max_bytes": 1 << 20,
                  "oldest_age_seconds": 4.0, "dropped_total": 0},
        "remote_write": {"durable": True, "shards": [
            {"shard": 0, "wal_bytes": 128, "lag_seconds": 1.5,
             "parked_total": 0, "dropped_total": 0}]},
        "senders": {"delta": {"consecutive_failures": 0}},
    })
    try:
        result = doctor.check_egress(f"http://127.0.0.1:{srv.port}")
        assert result.status == doctor.OK
        assert "spill: 2 frame(s)" in result.detail
        assert "remote-write: 1 shard(s)" in result.detail
        assert result.data["egress"]["enabled"] is True
    finally:
        srv.stop()


def test_doctor_egress_warns_on_loss_parked_and_down_link():
    from kube_gpu_stats_tpu import doctor

    srv = _egress_server({
        "enabled": True,
        "spill": {"depth_frames": 9, "bytes": 900_000,
                  "max_bytes": 1_000_000, "oldest_age_seconds": 300.0,
                  "dropped_total": 17},
        "remote_write": {"durable": True, "shards": [
            {"shard": 0, "wal_bytes": 4096, "lag_seconds": 250.0,
             "parked_total": 3, "dropped_total": 2}]},
        "senders": {"delta": {"consecutive_failures": 5}},
    })
    try:
        result = doctor.check_egress(f"http://127.0.0.1:{srv.port}")
        assert result.status == doctor.WARN
        assert "DROPPED 17" in result.detail
        assert "near its byte bound" in result.detail
        assert "3 poison request(s) parked" in result.detail
        assert "DROPPED 2 request(s)" in result.detail
        assert "link down: delta" in result.detail
    finally:
        srv.stop()


def test_doctor_egress_down_link_despite_pinned_zero_failures():
    """The durable senders pin consecutive_failures to 0 by design (the
    backoff belongs to the probe/shard loop, not the publish cadence) —
    the down-link WARN must come from the spill queue's link_failures
    and the shards' own failure counts."""
    from kube_gpu_stats_tpu import doctor

    srv = _egress_server({
        "enabled": True,
        "spill": {"depth_frames": 4, "bytes": 4096, "max_bytes": 1 << 20,
                  "oldest_age_seconds": 30.0, "dropped_total": 0,
                  "link_failures": 3},
        "remote_write": {"durable": True, "shards": [
            {"shard": 0, "wal_bytes": 2048, "lag_seconds": 30.0,
             "parked_total": 0, "dropped_total": 0,
             "consecutive_failures": 2}]},
        "senders": {"delta": {"consecutive_failures": 0}},
    })
    try:
        result = doctor.check_egress(f"http://127.0.0.1:{srv.port}")
        assert result.status == doctor.WARN
        assert "link down: delta, remote_write" in result.detail
    finally:
        srv.stop()


def test_doctor_egress_classifies_absent_disabled_unreachable():
    from kube_gpu_stats_tpu import doctor

    bare = MetricsServer(Registry(), host="127.0.0.1", port=0)
    bare.start()
    try:
        result = doctor.check_egress(f"http://127.0.0.1:{bare.port}")
        assert result.status == doctor.WARN
        assert "no /debug/egress" in result.detail
    finally:
        bare.stop()
    disabled = _egress_server({"enabled": False, "senders": {}})
    try:
        result = doctor.check_egress(f"http://127.0.0.1:{disabled.port}")
        assert result.status == doctor.WARN
        assert "no egress durability configured" in result.detail
    finally:
        disabled.stop()
    result = doctor.check_egress("http://127.0.0.1:9")
    assert result.status == doctor.FAIL


def test_doctor_egress_cli_flag_runs_the_row(capsys):
    from kube_gpu_stats_tpu import doctor

    srv = _egress_server({"enabled": False, "senders": {}})
    try:
        code = doctor.main(["--backend", "mock", "--egress",
                            "--listen-port", str(srv.port)])
        out = capsys.readouterr().out
        assert "egress" in out
        assert "no egress durability configured" in out
        assert code == 0  # WARN rows don't fail the doctor
    finally:
        srv.stop()


def test_spooled_wire_frame_recovered_by_reencode(tmp_path):
    """ISSUE 14: an old build spooled ENCODED wire frames, not bodies.
    A FULL frame's body is recovered (the drain re-encodes it at the
    negotiated wire version); a standalone DELTA has no base and stays
    undecodable — counted, never wedging."""
    q = SpillQueue(str(tmp_path / "spill"), fsync=False)
    q._ring.append(1.0, delta.encode_full("src", 9, 0, "metric_a 7\n"))
    q._ring.append(2.0, delta.encode_delta("src", 9, 1, [(0, 8.0)]))
    q.spool(3.0, "metric_a 9\n")
    assert q.peek() == (1.0, "metric_a 7\n")  # body out of the frame
    q.commit()
    assert q.reencoded_total == 1
    assert q.peek() == (3.0, "metric_a 9\n")  # DELTA skipped + counted
    assert q.undecodable_total == 1
    status = q.status()
    assert status["reencoded_total"] == 1
    assert status["format_version"] >= 1
    q.close()


def test_spill_segments_stamp_format_version(tmp_path):
    """New spill segments carry the KTSG header; a restart reads its
    own stamp back with zero skew/legacy segments."""
    q = SpillQueue(str(tmp_path / "spill"), fsync=False)
    q.spool(1.0, "metric_a 1\n")
    q.close()
    segs = sorted((tmp_path / "spill").glob("*.seg"))
    assert segs and segs[0].read_bytes()[:4] == b"KTSG"
    q2 = SpillQueue(str(tmp_path / "spill"), fsync=False)
    assert q2.depth() == 1
    status = q2.status()
    assert status["skew_segments_total"] == 0
    assert status["legacy_segments"] == 0
    q2.close()
