"""Topology label sourcing (component C9)."""

from kube_gpu_stats_tpu.topology import accel_type, topology_labels


def test_explicit_kts_env_wins():
    env = {
        "KTS_SLICE": "my-slice",
        "KTS_WORKER": "7",
        "KTS_TOPOLOGY": "4x4x8",
        "TPU_NAME": "ignored",
        "TPU_WORKER_ID": "0",
    }
    assert topology_labels(env) == {
        "slice": "my-slice", "worker": "7", "topology": "4x4x8"
    }


def test_gke_tpu_env_fallback():
    env = {
        "TPU_NAME": "v5p-slice-a",
        "TPU_WORKER_ID": "12",
        "TPU_TOPOLOGY": "8x8x4",
    }
    labels = topology_labels(env)
    assert labels == {"slice": "v5p-slice-a", "worker": "12", "topology": "8x8x4"}


def test_empty_env_keeps_keys():
    assert topology_labels({}) == {"slice": "", "worker": "", "topology": ""}


def test_accel_type_from_accelerator_type():
    assert accel_type({"TPU_ACCELERATOR_TYPE": "v5p-128"}) == "tpu-v5p"
    assert accel_type({"TPU_ACCELERATOR_TYPE": "v5litepod-16"}) == "tpu-v5litepod"
    assert accel_type({"KTS_ACCEL_TYPE": "v4-8"}) == "tpu-v4"
    assert accel_type({}) == "tpu"


def test_gce_metadata_fallback(monkeypatch):
    """Topology from a (fake) metadata server when env vars are absent —
    the exporter pod never carries TPU_* env (review finding)."""
    import http.server
    import threading

    from kube_gpu_stats_tpu.topology import from_gce_metadata, topology_labels

    attrs = {
        "/computeMetadata/v1/instance/attributes/agent-worker-number": "3",
        "/computeMetadata/v1/instance/attributes/accelerator-type": "v5p-128",
        "/computeMetadata/v1/instance/attributes/tpu-env":
            "ACCELERATOR_TYPE: 'v5p-128'\nTPU_TOPOLOGY: '4x4x8'\n"
            "TPU_NAME: 'my-slice'\nWORKER_ID: '3'\n",
    }

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.headers.get("Metadata-Flavor") != "Google":
                self.send_response(403)
                self.end_headers()
                return
            body = attrs.get(self.path)
            if body is None:
                self.send_response(404)
                self.end_headers()
                return
            data = body.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}/computeMetadata/v1"
    try:
        got = from_gce_metadata(base_url=base)
        assert got == {"worker": "3", "topology": "4x4x8", "slice": "my-slice"}
        monkeypatch.setenv("KTS_METADATA_URL", base)
        for var in ("TPU_NAME", "TPU_WORKER_ID", "TPU_TOPOLOGY",
                    "TPU_ACCELERATOR_TYPE", "KTS_SLICE", "KTS_WORKER",
                    "KTS_TOPOLOGY", "MEGASCALE_SLICE_ID", "CLOUD_TPU_TASK_ID"):
            monkeypatch.delenv(var, raising=False)
        import os
        labels = topology_labels(os.environ, use_metadata=True)
        assert labels == {"slice": "my-slice", "worker": "3", "topology": "4x4x8"}
        # Env still wins over metadata.
        monkeypatch.setenv("KTS_WORKER", "9")
        labels = topology_labels(os.environ, use_metadata=True)
        assert labels["worker"] == "9"
    finally:
        server.shutdown()


def test_metadata_disabled_off_gce(monkeypatch):
    from kube_gpu_stats_tpu import topology

    monkeypatch.delenv("KTS_METADATA_URL", raising=False)
    monkeypatch.setattr(topology, "_on_gce", lambda: False)
    assert topology.from_gce_metadata() == {}


def test_accel_type_final_labels_pass_through():
    """Review finding: an explicit final label was truncated to its
    family ("tpu-v5p" -> "tpu"); final forms now pass through while
    capacity forms still derive."""
    assert accel_type({"KTS_ACCEL_TYPE": "tpu-v5p"}) == "tpu-v5p"
    assert accel_type({"KTS_ACCEL_TYPE": "gpu-h100"}) == "gpu-h100"
    assert accel_type({"TPU_ACCELERATOR_TYPE": "tpu-v5litepod"}) == \
        "tpu-v5litepod"
    # Capacity forms unchanged (pinned above too).
    assert accel_type({"KTS_ACCEL_TYPE": "v4-8"}) == "tpu-v4"


def test_metadata_empty_worker_attribute_falls_back_to_tpu_env(tmp_path):
    """Review finding: a present-but-empty agent-worker-number blocked
    the tpu-env WORKER_ID fallback via setdefault."""
    import http.server
    import threading

    from kube_gpu_stats_tpu.topology import from_gce_metadata

    class Meta(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            answers = {
                "/instance/attributes/agent-worker-number": "",
                "/instance/attributes/accelerator-type": "v5p-128",
                "/instance/attributes/tpu-env":
                    "WORKER_ID: '3'\nTPU_TOPOLOGY: '8x8x4'\n",
            }
            body = answers.get(self.path)
            if body is None:
                self.send_response(404)
                self.end_headers()
                return
            data = body.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *args):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Meta)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        out = from_gce_metadata(
            base_url=f"http://127.0.0.1:{srv.server_address[1]}")
    finally:
        srv.shutdown()
        srv.server_close()
    assert out["worker"] == "3"
    assert out["topology"] == "8x8x4"
