"""Topology label sourcing (component C9)."""

from kube_gpu_stats_tpu.topology import accel_type, topology_labels


def test_explicit_kts_env_wins():
    env = {
        "KTS_SLICE": "my-slice",
        "KTS_WORKER": "7",
        "KTS_TOPOLOGY": "4x4x8",
        "TPU_NAME": "ignored",
        "TPU_WORKER_ID": "0",
    }
    assert topology_labels(env) == {
        "slice": "my-slice", "worker": "7", "topology": "4x4x8"
    }


def test_gke_tpu_env_fallback():
    env = {
        "TPU_NAME": "v5p-slice-a",
        "TPU_WORKER_ID": "12",
        "TPU_TOPOLOGY": "8x8x4",
    }
    labels = topology_labels(env)
    assert labels == {"slice": "v5p-slice-a", "worker": "12", "topology": "8x8x4"}


def test_empty_env_keeps_keys():
    assert topology_labels({}) == {"slice": "", "worker": "", "topology": ""}


def test_accel_type_from_accelerator_type():
    assert accel_type({"TPU_ACCELERATOR_TYPE": "v5p-128"}) == "tpu-v5p"
    assert accel_type({"TPU_ACCELERATOR_TYPE": "v5litepod-16"}) == "tpu-v5litepod"
    assert accel_type({"KTS_ACCEL_TYPE": "v4-8"}) == "tpu-v4"
    assert accel_type({}) == "tpu"
