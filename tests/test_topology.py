"""Topology label sourcing (component C9)."""

from kube_gpu_stats_tpu.topology import accel_type, topology_labels


def test_explicit_kts_env_wins():
    env = {
        "KTS_SLICE": "my-slice",
        "KTS_WORKER": "7",
        "KTS_TOPOLOGY": "4x4x8",
        "TPU_NAME": "ignored",
        "TPU_WORKER_ID": "0",
    }
    assert topology_labels(env) == {
        "slice": "my-slice", "worker": "7", "topology": "4x4x8"
    }


def test_gke_tpu_env_fallback():
    env = {
        "TPU_NAME": "v5p-slice-a",
        "TPU_WORKER_ID": "12",
        "TPU_TOPOLOGY": "8x8x4",
    }
    labels = topology_labels(env)
    assert labels == {"slice": "v5p-slice-a", "worker": "12", "topology": "8x8x4"}


def test_empty_env_keeps_keys():
    assert topology_labels({}) == {"slice": "", "worker": "", "topology": ""}


def test_accel_type_from_accelerator_type():
    assert accel_type({"TPU_ACCELERATOR_TYPE": "v5p-128"}) == "tpu-v5p"
    assert accel_type({"TPU_ACCELERATOR_TYPE": "v5litepod-16"}) == "tpu-v5litepod"
    assert accel_type({"KTS_ACCEL_TYPE": "v4-8"}) == "tpu-v4"
    assert accel_type({}) == "tpu"


def test_gce_metadata_fallback(monkeypatch):
    """Topology from a (fake) metadata server when env vars are absent —
    the exporter pod never carries TPU_* env (review finding)."""
    import http.server
    import threading

    from kube_gpu_stats_tpu.topology import from_gce_metadata, topology_labels

    attrs = {
        "/computeMetadata/v1/instance/attributes/agent-worker-number": "3",
        "/computeMetadata/v1/instance/attributes/accelerator-type": "v5p-128",
        "/computeMetadata/v1/instance/attributes/tpu-env":
            "ACCELERATOR_TYPE: 'v5p-128'\nTPU_TOPOLOGY: '4x4x8'\n"
            "TPU_NAME: 'my-slice'\nWORKER_ID: '3'\n",
    }

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.headers.get("Metadata-Flavor") != "Google":
                self.send_response(403)
                self.end_headers()
                return
            body = attrs.get(self.path)
            if body is None:
                self.send_response(404)
                self.end_headers()
                return
            data = body.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{server.server_address[1]}/computeMetadata/v1"
    try:
        got = from_gce_metadata(base_url=base)
        assert got == {"worker": "3", "topology": "4x4x8", "slice": "my-slice"}
        monkeypatch.setenv("KTS_METADATA_URL", base)
        for var in ("TPU_NAME", "TPU_WORKER_ID", "TPU_TOPOLOGY",
                    "TPU_ACCELERATOR_TYPE", "KTS_SLICE", "KTS_WORKER",
                    "KTS_TOPOLOGY", "MEGASCALE_SLICE_ID", "CLOUD_TPU_TASK_ID"):
            monkeypatch.delenv(var, raising=False)
        import os
        labels = topology_labels(os.environ, use_metadata=True)
        assert labels == {"slice": "my-slice", "worker": "3", "topology": "4x4x8"}
        # Env still wins over metadata.
        monkeypatch.setenv("KTS_WORKER", "9")
        labels = topology_labels(os.environ, use_metadata=True)
        assert labels["worker"] == "9"
    finally:
        server.shutdown()


def test_metadata_disabled_off_gce(monkeypatch):
    from kube_gpu_stats_tpu import topology

    monkeypatch.delenv("KTS_METADATA_URL", raising=False)
    monkeypatch.setattr(topology, "_on_gce", lambda: False)
    assert topology.from_gce_metadata() == {}


def test_accel_type_final_labels_pass_through():
    """Review finding: an explicit final label was truncated to its
    family ("tpu-v5p" -> "tpu"); final forms now pass through while
    capacity forms still derive."""
    assert accel_type({"KTS_ACCEL_TYPE": "tpu-v5p"}) == "tpu-v5p"
    assert accel_type({"KTS_ACCEL_TYPE": "gpu-h100"}) == "gpu-h100"
    assert accel_type({"TPU_ACCELERATOR_TYPE": "tpu-v5litepod"}) == \
        "tpu-v5litepod"
    # Capacity forms unchanged (pinned above too).
    assert accel_type({"KTS_ACCEL_TYPE": "v4-8"}) == "tpu-v4"


def test_metadata_empty_worker_attribute_falls_back_to_tpu_env(tmp_path):
    """Review finding: a present-but-empty agent-worker-number blocked
    the tpu-env WORKER_ID fallback via setdefault."""
    import http.server
    import threading

    from kube_gpu_stats_tpu.topology import from_gce_metadata

    class Meta(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            answers = {
                "/instance/attributes/agent-worker-number": "",
                "/instance/attributes/accelerator-type": "v5p-128",
                "/instance/attributes/tpu-env":
                    "WORKER_ID: '3'\nTPU_TOPOLOGY: '8x8x4'\n",
            }
            body = answers.get(self.path)
            if body is None:
                self.send_response(404)
                self.end_headers()
                return
            data = body.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *args):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Meta)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        out = from_gce_metadata(
            base_url=f"http://127.0.0.1:{srv.server_address[1]}")
    finally:
        srv.shutdown()
        srv.server_close()
    assert out["worker"] == "3"
    assert out["topology"] == "8x8x4"


# -- interconnect graph (ISSUE 19) ------------------------------------------


def test_parse_topology():
    from kube_gpu_stats_tpu.topology import parse_topology

    assert parse_topology("4x4x4") == (4, 4, 4)
    assert parse_topology("2x2") == (2, 2)
    assert parse_topology("16x16") == (16, 16)
    # Accelerator types, empties, malformed strings: None (ring
    # fallback), never an exception.
    assert parse_topology("v5p-128") is None
    assert parse_topology("") is None
    assert parse_topology("8") is None
    assert parse_topology("4x0") is None
    assert parse_topology("4x-2") is None


def test_link_name_is_numeric_aware():
    from kube_gpu_stats_tpu.topology import link_name

    assert link_name("2", "10") == "2-10"
    assert link_name("10", "2") == "2-10"
    assert link_name("b", "a") == "a-b"


def test_torus_graph_adjacency():
    from kube_gpu_stats_tpu.topology import InterconnectGraph

    g = InterconnectGraph([str(i) for i in range(8)], "2x2x2")
    assert g.kind == "torus"
    # 2x2x2: every axis has size 2 — wrap links would duplicate the
    # direct pair, so each node has exactly 3 neighbors (12 edges).
    assert len(g.links()) == 12
    assert g.neighbors("0") == ["1", "2", "4"]
    assert g.endpoints("0-4") == ("0", "4")


def test_torus_wraparound_only_above_size_two():
    from kube_gpu_stats_tpu.topology import InterconnectGraph

    g = InterconnectGraph([str(i) for i in range(4)], "4x1")
    assert g.kind == "torus"
    # Size-4 axis wraps: ring 0-1-2-3-0.
    assert g.links() == ["0-1", "0-3", "1-2", "2-3"]


def test_ring_fallback_without_parseable_topology():
    from kube_gpu_stats_tpu.topology import InterconnectGraph

    g = InterconnectGraph(["0", "1", "2", "3"], "v5p-128")
    assert g.kind == "ring"
    assert g.links() == ["0-1", "0-3", "1-2", "2-3"]


def test_sparse_or_nonnumeric_workers_go_edgeless():
    from kube_gpu_stats_tpu.topology import InterconnectGraph

    # Sparse ids (worker 2 missing): guessing adjacency would accuse
    # the wrong pair — the graph goes inert instead.
    assert InterconnectGraph(["0", "1", "3"], "").links() == []
    assert InterconnectGraph(["a", "b"], "").links() == []
    assert InterconnectGraph([], "").links() == []
    assert InterconnectGraph(["0"], "").links() == []


def test_edge_for_maps_local_labels_to_shared_edges():
    from kube_gpu_stats_tpu.topology import InterconnectGraph

    g = InterconnectGraph([str(i) for i in range(4)], "4x1")
    # Worker 1's +x neighbor and worker 2's -x neighbor are the SAME
    # physical link — both local labels map to one canonical edge.
    assert g.edge_for("1", "x1") == "1-2"
    assert g.edge_for("2", "x0") == "1-2"
    # Wraparound edge.
    assert g.edge_for("0", "x0") == "0-3"
    # Labels off the grid or outside the axis convention: no edge.
    assert g.edge_for("0", "y0") is None   # axis 1 has size 1
    assert g.edge_for("0", "z1") is None
    assert g.edge_for("0", "bogus") is None
    assert g.edge_for("9", "x0") is None   # unknown worker


def test_describe_shape():
    from kube_gpu_stats_tpu.topology import InterconnectGraph

    g = InterconnectGraph([str(i) for i in range(4)], "2x2")
    assert g.describe() == {"kind": "torus", "topology": "2x2",
                            "nodes": 4, "links": 4}
