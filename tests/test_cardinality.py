"""Cardinality & memory admission tests (ISSUE 16): the series
accountant's budget/hard-cap/eviction arithmetic, the label fence, the
ingest-path integration (clamped FULLs that keep their delta chains,
413 at the hard cap with publisher defer — never a resync loop), the
pull-parse install, idle eviction through the hub's one churn path,
the exported self-metering, doctor's verdict, and the long-churn
object-count regression pin (satellite: no unbounded survivor maps)."""

from __future__ import annotations

import json
import time
import urllib.request

import pytest

from kube_gpu_stats_tpu import delta, schema
from kube_gpu_stats_tpu.cardinality import (CardinalityShed, LabelFence,
                                            SeriesAccountant, clamp_series)
from kube_gpu_stats_tpu.hub import Hub
from kube_gpu_stats_tpu.registry import Registry, SnapshotBuilder


def _body(worker: int, duty: float, chips: int = 2) -> str:
    builder = SnapshotBuilder()
    for chip in range(chips):
        labels = (
            ("accel_type", "tpu-v5p"), ("chip", str(chip)),
            ("device_path", f"/dev/accel{chip}"), ("uuid", ""),
            ("slice", f"s{worker % 2}"), ("worker", str(worker)),
            ("topology", "2x2"))
        builder.add(schema.DEVICE_UP, 1.0, labels)
        builder.add(schema.DUTY_CYCLE, duty + chip, labels)
        builder.add(schema.POWER, 200.0 + duty, labels)
    return builder.build().render()


def _push_hub(**kwargs) -> Hub:
    kwargs.setdefault("targets_provider", lambda: [])
    kwargs.setdefault("interval", 10.0)
    kwargs.setdefault("push_fence", 1e9)
    return Hub([], **kwargs)


def _feed(hub: Hub, encoder: delta.DeltaEncoder, body: str) -> int:
    wire, _kind = encoder.encode_next(body)
    code, _resp, _hdrs = hub.delta.handle(wire)
    if code == 200:
        encoder.ack()
    else:
        encoder.nack()
    return code


# --- accountant arithmetic --------------------------------------------------

def test_accountant_disabled_is_accounting_only():
    acc = SeriesAccountant()
    assert not acc.enabled
    assert acc.admit("a", 10_000) == 10_000
    acc.install("a", 10_000, 500)
    assert acc.live_series() == 10_000
    assert acc.shed_totals() == {}


def test_budget_clamps_counts_and_unclamps_on_raise():
    acc = SeriesAccountant(budget_per_source=5)
    assert acc.admit("a", 8) == 5
    acc.install("a", 5, 100, clamped=True)
    assert acc.is_clamped("a")
    assert acc.shed_totals() == {("a", "source_budget"): 3}
    # Every over-budget FULL counts again — the counter is series
    # DROPPED, not sources clamped.
    assert acc.admit("a", 8) == 5
    assert acc.shed_totals() == {("a", "source_budget"): 6}
    # A budget raise re-admits the whole set on the next FULL.
    acc.budget_per_source = 10
    assert acc.admit("a", 8) == 8
    acc.install("a", 8, 100, clamped=False)
    assert not acc.is_clamped("a")
    assert acc.live_series() == 8


def test_hard_cap_refuses_new_source_but_clamps_established():
    acc = SeriesAccountant(hard_cap=10)
    assert acc.admit("a", 6) == 6
    acc.install("a", 6, 100)
    # Established source replacing its set: clamped to headroom, never
    # refused (existing series must keep updating).
    assert acc.admit("b", 6) == 4
    acc.install("b", 4, 100, clamped=True)
    assert acc.live_series() == 10
    assert acc.at_hard_cap()
    # A brand-new source with zero headroom: refused outright.
    with pytest.raises(CardinalityShed) as exc:
        acc.admit("c", 1)
    assert exc.value.retry_after > 0
    assert acc.shed_totals()[("c", "hard_cap")] == 1
    # An established source never draws the exception — its replace is
    # floored at its own current footprint.
    assert acc.admit("a", 8) == 6


def test_evict_idle_prefers_biggest_source_at_seq_tie():
    """A whole cohort going idle in one refresh must cost one label
    bomb, not every small healthy source whose dict insertion order
    happened to be older."""
    acc = SeriesAccountant(high_watermark=100, low_watermark=90)
    for i in range(10):
        acc.install(f"small-{i}", 6, 60)
    acc.install("bomb", 80, 800)
    for _ in range(acc.idle_refreshes + 1):
        acc.tick()
    evicted = acc.evict_idle()
    assert evicted == ["bomb"]
    assert acc.live_series() == 60
    assert acc.evicted_totals() == {"idle": 80}


def test_evict_idle_skips_active_sources():
    acc = SeriesAccountant(high_watermark=10, low_watermark=1,
                           idle_refreshes=2)
    acc.install("busy", 8, 80)
    acc.install("quiet", 8, 80)
    for _ in range(3):
        acc.tick()
        acc.touch("busy")
    assert acc.evict_idle() == ["quiet"]
    # Still above low watermark but nothing else is idle: a source
    # that is still updating is never evicted for pressure.
    assert acc.live_series() == 8
    assert "busy" in acc.ledger_sources()


def test_shed_ledger_aggregates_past_64_sources():
    acc = SeriesAccountant(budget_per_source=1)
    for i in range(80):
        acc.count_shed(f"s-{i:03d}", "source_budget")
    totals = acc.shed_totals()
    distinct = {source for source, _ in totals}
    assert len(distinct) <= 65  # 64 named + "other"
    assert totals[("other", "source_budget")] == 16
    assert sum(totals.values()) == 80


def test_forget_releases_footprint():
    acc = SeriesAccountant()
    acc.install("a", 7, 70)
    acc.forget("a")
    assert acc.live_series() == 0
    assert acc.live_bytes() == 0
    assert acc.source_count() == 0


def test_debug_payload_shape():
    acc = SeriesAccountant(budget_per_source=3, hard_cap=100,
                           high_watermark=50)
    assert acc.admit("a", 5) == 3
    acc.install("a", 3, 30, clamped=True)
    payload = acc.debug_payload()
    assert payload["live_series"] == 3
    assert payload["limits"]["hard_cap"] == 100
    assert payload["limits"]["low_watermark"] == 45  # 90% default
    assert payload["clamped_sources"] == ["a"]
    assert payload["top_sources"][0]["source"] == "a"
    assert payload["shed"] == [
        {"source": "a", "reasons": {"source_budget": 2}}]
    json.dumps(payload)  # must be wire-clean


def test_clamp_series_prefix():
    series = [("m", (), 1.0), ("m", (), 2.0), ("m", (), 3.0)]
    assert clamp_series(series, 2) == series[:2]
    assert clamp_series(series, 3) is series
    assert clamp_series(series, 99) is series


# --- label fence ------------------------------------------------------------

def test_label_fence_caps_distinct_values_with_stable_identity():
    fence = LabelFence(value_cap=2)
    assert fence.fence({"pod": "a"}) == {"pod": "a"}
    assert fence.fence({"pod": "b"}) == {"pod": "b"}
    assert fence.fence({"pod": "c"}) == {"pod": "overflow"}
    # Known values keep passing — series identity for admitted values
    # is stable, only NEW values degrade.
    assert fence.fence({"pod": "a"}) == {"pod": "a"}
    assert fence.fence({"pod": "d"}) == {"pod": "overflow"}
    assert fence.fenced_totals() == {"pod": 2}
    assert fence.admitted_values("pod") == 2


def test_label_fence_disabled_returns_input_untouched():
    fence = LabelFence(value_cap=0)
    labels = {"pod": "a"}
    assert fence.fence(labels) is labels
    assert not fence.enabled


# --- ingest integration -----------------------------------------------------

def test_full_clamped_to_prefix_delta_chain_survives():
    """Over-budget FULL: the admitted PREFIX is installed (series are
    born in body order, so slot indexing stays stable), the source's
    deltas keep applying to admitted slots, overflow slots are
    dropped-and-counted — NEVER a resync (a resync would re-parse the
    bomb forever)."""
    hub = _push_hub(series_budget_per_source=4)
    try:
        encoder = delta.DeltaEncoder("w0", generation=1)
        assert _feed(hub, encoder, _body(0, 10.0)) == 200
        assert hub.cardinality.live_series() == 4
        assert hub.cardinality.is_clamped("w0")
        # The encoder diffs against the FULL body it sent (6 series);
        # a value change on chip 0 (slot < 4) and chip 1 (slots >= 4
        # for POWER) rides one delta: admitted slots apply, overflow
        # slots are tolerated.
        assert _feed(hub, encoder, _body(0, 11.0)) == 200
        assert hub.delta.resyncs_total == 0
        hub.refresh_once()
        text = hub.registry.snapshot().render()
        line = next(l for l in text.splitlines()
                    if l.startswith("accelerator_duty_cycle")
                    and 'chip="0"' in l)
        assert line.endswith(" 11"), line
        shed = hub.cardinality.shed_totals()
        assert shed[("w0", "source_budget")] >= 2
    finally:
        hub.stop()


def test_hard_cap_pre_parse_413_and_established_survives():
    hub = _push_hub(series_hard_cap=6)
    try:
        first = delta.DeltaEncoder("w0", generation=1)
        assert _feed(hub, first, _body(0, 10.0)) == 200
        assert hub.cardinality.at_hard_cap()
        # New source at the cap: refused 413 + Retry-After BEFORE any
        # parse (the pre-parse fence), publisher-classified as shed.
        wire = delta.encode_full("w1", 2, 1, _body(1, 20.0))
        code, resp, hdrs = hub.delta.handle(wire)
        assert code == 413, (code, resp)
        assert "Retry-After" in hdrs
        # The established source keeps pushing FULLs (a restart) —
        # clamped to its own footprint, never refused.
        restart = delta.DeltaEncoder("w0", generation=2)
        assert _feed(hub, restart, _body(0, 30.0)) == 200
    finally:
        hub.stop()


def test_publisher_defers_413_like_429_then_lands_on_budget_raise():
    """Satellite 3: a 413 is the shed retry class — no FULL promotion,
    no failure/backoff, no resync — and once the operator raises the
    cap (or eviction frees room), the SAME deferred series land on the
    next push with zero resyncs."""
    import random

    from kube_gpu_stats_tpu.exposition import MetricsServer

    hub = _push_hub(series_hard_cap=6, ingest_lanes=1)
    server = MetricsServer(hub.registry, host="127.0.0.1", port=0,
                           ingest_provider=hub.delta.handle)
    server.start()
    filler = delta.DeltaEncoder("filler", generation=1)
    code, _resp, _hdrs = hub.delta.handle(
        delta.encode_full("filler", 1, 1, _body(0, 5.0)))
    assert code == 200

    worker = Registry()

    def publish(duty: float) -> None:
        builder = SnapshotBuilder()
        labels = (("accel_type", "tpu-v5p"), ("chip", "0"),
                  ("device_path", "/dev/accel0"), ("uuid", ""))
        builder.add(schema.DEVICE_UP, 1.0, labels)
        builder.add(schema.DUTY_CYCLE, duty, labels)
        worker.publish(builder.build())

    publish(10.0)
    publisher = delta.DeltaPublisher(
        worker, f"http://127.0.0.1:{server.port}", source="node-new",
        rng=random.Random(7))
    try:
        publisher.push_once()  # session FULL refused 413 at the cap
        assert publisher.shed_honored_total == 1
        assert publisher.failures_total == 0
        assert publisher.resyncs_total == 0
        assert publisher.consecutive_failures == 0
        assert publisher._shed_until > time.monotonic()
        # Deferring: no POST at all while the window holds.
        frames = hub.delta.stats()["full_frames"]
        publisher.push_once()
        assert hub.delta.stats()["full_frames"] == frames
        # The operator raises the cap; the deferral window passes; the
        # very next push lands the full series set. No resync anywhere.
        hub.cardinality.hard_cap = 100
        publisher._shed_until = 0.0
        publisher.push_once()
        assert publisher.pushes_total == 1
        assert publisher.shed_honored_total == 1
        assert publisher.resyncs_total == 0
        assert hub.delta.resyncs_total == 0
        assert "node-new" in hub.cardinality.ledger_sources()
    finally:
        publisher.stop()
        server.stop()
        hub.stop()


def test_budget_raise_readmits_clamped_series_on_next_full():
    """A clamped source's dropped series are DEFERRED, not lost: raise
    the budget and the next FULL (here: a shape change, the encoder's
    natural FULL trigger) lands every series — no resync, no manual
    kick."""
    hub = _push_hub(series_budget_per_source=4)
    try:
        encoder = delta.DeltaEncoder("w0", generation=1)
        assert _feed(hub, encoder, _body(0, 10.0)) == 200
        assert hub.cardinality.live_series() == 4
        hub.cardinality.budget_per_source = 0  # raise (off)
        assert _feed(hub, encoder, _body(0, 10.0, chips=3)) == 200
        assert hub.cardinality.live_series() == 9
        assert not hub.cardinality.is_clamped("w0")
        assert hub.delta.resyncs_total == 0
    finally:
        hub.stop()


def test_pull_parse_install_clamped_and_accounted(tmp_path):
    """The pull path births series through the same gate: a configured
    target's parse is clamped to its admitted prefix and the ledger
    carries it as kind=pull; the target STAYS configured (only its
    cached state is bounded, the operator chose the target)."""
    target = tmp_path / "w0.prom"
    target.write_text(_body(0, 42.0))
    hub = Hub([str(target)], interval=10.0,
              series_budget_per_source=4)
    try:
        hub.refresh_once()
        assert hub.cardinality.live_series() == 4
        assert hub.cardinality.is_clamped(str(target))
        payload = hub.cardinality.debug_payload()
        (entry,) = [row for row in payload["top_sources"]
                    if row["source"] == str(target)]
        assert entry["kind"] == "pull"
        assert str(target) in hub._targets
        assert hub.cardinality.shed_totals()[
            (str(target), "source_budget")] == 2
    finally:
        hub.stop()


def test_idle_eviction_sweeps_push_state_through_churn_path():
    """Above the high watermark, an idle push source is evicted through
    the refresh's ONE churn path: ledger, target list, parse cache and
    delta session all go together, the eviction is counted, and the
    evicted worker's comeback is a clean 409 -> FULL re-admission."""
    hub = _push_hub(series_budget_per_source=0, series_hard_cap=0,
                    series_high_watermark=8, series_low_watermark=7,
                    series_idle_refreshes=2)
    try:
        quiet = delta.DeltaEncoder("quiet", generation=1)
        busy = delta.DeltaEncoder("busy", generation=1)
        assert _feed(hub, quiet, _body(0, 10.0)) == 200
        assert _feed(hub, busy, _body(1, 20.0)) == 200
        assert hub.cardinality.live_series() == 12
        for duty in (21.0, 22.0, 23.0):
            assert _feed(hub, busy, _body(1, duty)) == 200
            hub.refresh_once()
        assert "quiet" not in hub.cardinality.ledger_sources()
        assert "quiet" not in hub._targets
        assert "quiet" not in hub._parse_cache
        assert "quiet" not in hub.delta.sources()
        assert "busy" in hub.delta.sources()
        assert hub.cardinality.evicted_totals() == {"idle": 6}
        text = hub.registry.snapshot().render()
        assert 'kts_cardinality_evicted_total{reason="idle"} 6' in text
        # Comeback: the evicted session's next delta draws a resync,
        # the FULL re-admits — standard churn recovery, nothing new.
        wire, _kind = quiet.encode_next(_body(0, 11.0))
        assert hub.delta.handle(wire)[0] == 409
        quiet.nack()
        assert _feed(hub, quiet, _body(0, 11.0)) == 200
    finally:
        hub.stop()


def test_self_metering_exported_with_born_at_zero_reasons():
    hub = _push_hub(series_budget_per_source=100)
    try:
        encoder = delta.DeltaEncoder("w0", generation=1)
        assert _feed(hub, encoder, _body(0, 10.0)) == 200
        hub.refresh_once()
        # The exposition-size gauge reports the PREVIOUS publish (the
        # tick N-1 convention), so it appears from the second refresh.
        hub.refresh_once()
        text = hub.registry.snapshot().render()
        assert 'kts_series_live{component="entries"} 6' in text
        assert 'kts_series_live{component="exposition"}' in text
        assert 'kts_source_series{source="w0"} 6' in text
        # Reasons born at 0 under source="other": increase()-based
        # alerting sees the FIRST real shed.
        for reason in ("source_budget", "hard_cap"):
            assert (f'kts_cardinality_shed_total{{source="other",'
                    f'reason="{reason}"}} 0') in text
        assert 'kts_cardinality_evicted_total{reason="idle"} 0' in text
    finally:
        hub.stop()


# --- /debug/cardinality + doctor -------------------------------------------

def test_debug_cardinality_endpoint_and_doctor_check():
    from kube_gpu_stats_tpu.doctor import check_cardinality
    from kube_gpu_stats_tpu.exposition import MetricsServer

    hub = _push_hub(series_budget_per_source=4)
    server = MetricsServer(
        hub.registry, host="127.0.0.1", port=0,
        ingest_provider=hub.delta.handle,
        cardinality_provider=lambda: dict(
            hub.cardinality.debug_payload(),
            enabled=hub.cardinality.enabled))
    server.start()
    try:
        encoder = delta.DeltaEncoder("w0", generation=1)
        assert _feed(hub, encoder, _body(0, 10.0)) == 200
        base = f"http://127.0.0.1:{server.port}"
        payload = json.loads(urllib.request.urlopen(
            base + "/debug/cardinality", timeout=10).read())
        assert payload["enabled"] is True
        assert payload["live_series"] == 4
        assert payload["clamped_sources"] == ["w0"]
        result = check_cardinality(base)
        assert result.status == "warn"  # clamped source named
        assert "w0" in result.detail
    finally:
        server.stop()
        hub.stop()


def test_doctor_cardinality_verdict_texts():
    from kube_gpu_stats_tpu.doctor import cardinality_verdict

    status, detail = cardinality_verdict(
        {"live_series": 12, "sources": 2, "limits": {"hard_cap": 100},
         "enabled": True})
    assert status == "ok" and "12 series live" in detail
    status, detail = cardinality_verdict(
        {"live_series": 100, "sources": 3,
         "limits": {"hard_cap": 100}, "enabled": True,
         "clamped_sources": ["bomb"], "shed_total": 50,
         "shed": [{"source": "bomb", "reasons": {"hard_cap": 50}}],
         "top_sources": [{"source": "bomb", "series": 90}]})
    assert status == "warn"
    assert "AT HARD CAP" in detail and "bomb" in detail


# --- config flags -----------------------------------------------------------

def test_cardinality_flag_validation():
    import argparse

    from kube_gpu_stats_tpu.config import (add_cardinality_flags,
                                           validate_cardinality_args)

    parser = argparse.ArgumentParser()
    add_cardinality_flags(parser)
    good = parser.parse_args(["--series-hard-cap", "1000",
                              "--series-high-watermark", "900"])
    assert validate_cardinality_args(good) is None
    bad = parser.parse_args(["--series-hard-cap", "100",
                             "--series-high-watermark", "200"])
    assert "high-watermark" in validate_cardinality_args(bad)
    orphan = parser.parse_args(["--series-low-watermark", "10"])
    assert "low-watermark" in validate_cardinality_args(orphan)


# --- poll-loop label fence --------------------------------------------------

def test_poll_loop_fences_churning_pod_label(tmp_path):
    """A workload churning its pod label every tick (the classic
    per-job pod explosion) degrades to pod="overflow" past the cap:
    the plan cache and the series set stop growing, and the fence's
    hit counter rides the exposition."""
    from kube_gpu_stats_tpu.collectors.mock import MockCollector
    from kube_gpu_stats_tpu.poll import PollLoop

    class ChurningAttribution:
        def __init__(self) -> None:
            self.n = 0

        def lookup(self, dev):
            self.n += 1
            return {"pod": f"job-{self.n}", "namespace": "ml",
                    "container": "w"}

    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, deadline=5.0,
                    attribution=ChurningAttribution(),
                    label_value_cap=3)
    try:
        for _ in range(10):
            loop.tick()
        series = reg.snapshot().series
        pods = {dict(s.labels).get("pod") for s in series
                if "pod" in dict(s.labels)}
        # 3 admitted values + the overflow aggregate, never 10.
        assert "overflow" in pods
        assert len(pods) <= 4, pods
        fenced = loop._label_fence.fenced_totals()
        assert fenced.get("pod", 0) >= 6
        text = reg.snapshot().render()
        assert 'kts_cardinality_fenced_total{label="pod"}' in text
    finally:
        loop.stop()


# --- long-churn object-count regression (satellite 1) ----------------------

def test_long_churn_keeps_hub_and_intern_pools_flat():
    """30 churn cycles of come-and-go push sources: every per-target
    survivor map (parse cache, hist cache, breakers, fleet baselines,
    delta sessions, cardinality ledger) must track the LIVE set, and
    the validate.py intern pools must stay under their wholesale-clear
    bound — sizes at cycle 10 equal sizes at cycle 30."""
    from kube_gpu_stats_tpu import validate

    hub = _push_hub(push_fence=1e9)
    hub.delta._expiry = 0.04

    def sizes() -> dict:
        return {
            "parse_cache": len(hub._parse_cache),
            "hist_cache": len(hub._hist_cache),
            "breakers": len(hub._breakers),
            "fleet": len(hub.fleet._targets) if hub.fleet else 0,
            "sessions": len(hub.delta.sources()),
            "ledger": hub.cardinality.source_count(),
        }

    try:
        snap10 = None
        for cycle in range(30):
            for k in range(4):
                encoder = delta.DeltaEncoder(
                    f"churn-{cycle:03d}-{k}", generation=cycle + 1)
                wire, _kind = encoder.encode_next(_body(k, 10.0 + cycle))
                assert hub.delta.handle(wire)[0] == 200
            hub.refresh_once()
            time.sleep(0.05)  # past expiry: this cycle's sources die
            if cycle == 10:
                hub.refresh_once()  # sweep before measuring
                snap10 = sizes()
        hub.refresh_once()
        snap30 = sizes()
        assert snap30 == snap10, (snap10, snap30)
        # The dead generations left nothing behind anywhere.
        assert snap30["sessions"] == 0
        assert snap30["ledger"] == 0
        assert snap30["parse_cache"] == 0
        # Intern pools are bounded memos with wholesale clear.
        assert len(validate._NAME_POOL) <= validate.BOUNDED_MEMO_MAX
        assert len(validate._LABEL_CACHE) <= validate.BOUNDED_MEMO_MAX
    finally:
        hub.stop()
