"""Driver-contract checks on the virtual 8-device CPU mesh (conftest sets
JAX_PLATFORMS=cpu + xla_force_host_platform_device_count=8)."""

import jax
import pytest

import __graft_entry__


def test_entry_jits_single_device():
    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    out.block_until_ready()
    assert out.shape == args[0].shape


@pytest.mark.parametrize("n", [1, 2, 4, 8])
def test_dryrun_multichip(n):
    __graft_entry__.dryrun_multichip(n)


def test_sharded_step_actually_shards():
    from kube_gpu_stats_tpu.loadgen.burn import make_sharded_train_step

    mesh, train_step, params, x = make_sharded_train_step(
        8, d_model=64, d_hidden=128, batch=32
    )
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("data", "model")
    # w1 column-sharded over "model", batch sharded over "data".
    assert len(params["w1"].sharding.device_set) == 8
    assert not x.sharding.is_fully_replicated
    with mesh:
        new_params, loss = train_step(params, x)
    assert new_params["w1"].sharding == params["w1"].sharding
    assert float(loss) > 0


def test_loadgen_burn_runs_briefly():
    from kube_gpu_stats_tpu.loadgen.burn import run_burn

    steps = run_burn(seconds=0.5, size=128, report_every=10.0)
    assert steps >= 1


def test_ici_ring_burn_numerics():
    """Ring rotation on the 8-device CPU mesh: after `steps` hops each
    shard holds the shard from `steps` positions back, plus `steps`."""
    import numpy as np

    from kube_gpu_stats_tpu.loadgen.ici_burn import make_ici_burn

    n, steps = 8, 3
    fn, x = make_ici_burn(n, shard_mb=0.001, steps=steps)
    original = np.asarray(x).reshape(n, -1)  # before fn donates x
    out = np.asarray(fn(x))
    rotated = np.roll(original, steps, axis=0) + steps
    np.testing.assert_allclose(out.reshape(n, -1), rotated)


def test_ici_burn_runs_briefly():
    from kube_gpu_stats_tpu.loadgen.ici_burn import run_ici_burn

    assert run_ici_burn(0.3, n_devices=4, shard_mb=0.001, steps=2) >= 1


def test_with_device_count_rewrites_flags():
    from __graft_entry__ import _with_device_count

    assert _with_device_count("", 8).endswith("device_count=8")
    assert "device_count=16" in _with_device_count(
        "--xla_force_host_platform_device_count=8", 16)
    # Larger existing value retained.
    assert "device_count=32" in _with_device_count(
        "--xla_force_host_platform_device_count=32", 8)
    assert "--other_flag" in _with_device_count(
        "--other_flag --xla_force_host_platform_device_count=4", 8)


def test_dryrun_16_exceeds_test_mesh_uses_subprocess():
    """conftest pins 8 CPU devices; dryrun(16) must self-provision a larger
    mesh via the subprocess fallback (rewriting the existing flag)."""
    import __graft_entry__ as graft

    graft.dryrun_multichip(16)


def test_dryrun_handles_non_power_of_two_device_counts():
    """The driver chooses n_devices; dp=3 (6 devices) must not crash on
    indivisible default shapes — make_sharded_train_step rounds the
    sharded dims up to the mesh factors."""
    import __graft_entry__ as g

    g.dryrun_multichip(6)
