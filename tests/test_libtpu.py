"""libtpu client/collector against the fake runtime-metrics server
(SURVEY.md §4 fake backend #2; BASELINE.json configs[1])."""

import pytest

from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.collectors import CollectorError
from kube_gpu_stats_tpu.collectors.libtpu import LibtpuClient, LibtpuCollector
from kube_gpu_stats_tpu.proto import tpumetrics

from kube_gpu_stats_tpu.testing.libtpu_server import HBM_TOTAL, LINKS, FakeLibtpuServer


@pytest.fixture(params=["flat", "nested"])
def server(request):
    """Every client/collector test runs under BOTH wire dialects (round-1
    verdict item 1): the flat round-1 shape and the nested tpu-info-style
    shape, which also rejects the batched "" selector."""
    with FakeLibtpuServer(num_chips=4, dialect=request.param) as s:
        yield s


def make_collector(server, **kw):
    client = LibtpuClient(ports=(server.port,), rpc_timeout=kw.pop("rpc_timeout", 1.0))
    return LibtpuCollector(client, accel_type="tpu-test", **kw)


def test_client_get_metric(server):
    client = LibtpuClient(ports=(server.port,), rpc_timeout=1.0)
    samples = client.get_metric(tpumetrics.DUTY_CYCLE)
    assert len(samples) == 4
    assert samples[0].value == 50.0
    client.close()


def test_discover_via_hbm_total(server):
    col = make_collector(server)
    devs = col.discover()
    assert [d.index for d in devs] == [0, 1, 2, 3]
    assert devs[0].accel_type == "tpu-test"
    col.close()


def test_begin_tick_then_sample(server):
    col = make_collector(server)
    devs = col.discover()
    col.begin_tick()
    s = col.sample(devs[2])
    assert s.values[schema.DUTY_CYCLE.name] == 52.0
    assert s.values[schema.MEMORY_USED.name] == 3 * 1024**3
    assert s.values[schema.MEMORY_TOTAL.name] == HBM_TOTAL
    assert set(s.ici_counters) == set(LINKS)
    assert s.collective_ops == 300
    col.close()


def test_bandwidth_uptime_and_dcn_families(server):
    col = make_collector(server)
    devs = col.discover()
    col.begin_tick()
    s = col.sample(devs[1])
    assert s.values[schema.MEMORY_BANDWIDTH_UTIL.name] == 31.0
    assert s.values[schema.UPTIME.name] == 7201.0
    assert s.values[schema.dcn_value_key("p50")] == 0.002
    assert s.values[schema.dcn_value_key("p90")] == 0.006
    assert s.values[schema.dcn_value_key("p99")] == 0.016
    col.close()


def test_per_metric_mode_stops_polling_unsupported_families(server):
    """In per-metric fallback mode a family the runtime rejects with a
    capability status (UNIMPLEMENTED) is latched and never requested again —
    an old runtime costs the failing RPCs once, not every tick."""
    server.reject_batch = True
    server.drop_metrics.update(
        {tpumetrics.DCN_LATENCY_P50, tpumetrics.DCN_LATENCY_P90,
         tpumetrics.DCN_LATENCY_P99}
    )
    col = make_collector(server)
    devs = col.discover()
    server.requests.clear()
    for _ in range(3):
        col.begin_tick()
        col.wait_ready()
    dropped_requests = [r for r in server.requests
                        if r in server.drop_metrics]
    assert len(dropped_requests) == 3  # one probe per family, first tick only
    s = col.sample(devs[0])
    assert schema.DUTY_CYCLE.name in s.values
    col.close()


def server_requested_count(server, name):
    return sum(1 for r in server.requests if r == name)


def test_mixed_port_statuses_do_not_latch_unsupported():
    """One port answering UNIMPLEMENTED while another port is down is NOT a
    capability answer — the family must be re-requested once the dead port
    returns (it may be the one that serves megascale metrics)."""
    with FakeLibtpuServer(num_chips=2) as live:
        dead = FakeLibtpuServer(num_chips=2, chip_offset=2)
        dead_port = dead.port  # grabs a port but never starts: UNAVAILABLE
        live.reject_batch = True
        live.drop_metrics.add(tpumetrics.DCN_LATENCY_P50)
        col = LibtpuCollector(
            LibtpuClient(ports=(live.port, dead_port), rpc_timeout=0.5),
            accel_type="tpu-test",
        )
        try:
            for _ in range(2):
                col.begin_tick()
                col.wait_ready()
            assert server_requested_count(live, tpumetrics.DCN_LATENCY_P50) == 2
        finally:
            col.close()
            dead.stop()


def test_mixed_batch_support_serves_both_ports():
    """Mixed runtime versions: one port serves the batched "" selector,
    the other rejects it. The rejecting port's chips must still be sampled
    (via per-metric top-up) — every tick, with nothing latched."""
    with FakeLibtpuServer(num_chips=2) as new_rt, \
            FakeLibtpuServer(num_chips=2, chip_offset=2) as old_rt:
        old_rt.reject_batch = True
        col = LibtpuCollector(
            LibtpuClient(ports=(new_rt.port, old_rt.port), rpc_timeout=0.5),
            accel_type="tpu-test",
        )
        try:
            for _ in range(2):
                col.begin_tick()
                col.wait_ready()
                for chip in range(4):  # chips 0-1 new_rt, 2-3 old_rt
                    s = col.sample(type("D", (), {"index": chip}))
                    assert s.values[schema.DUTY_CYCLE.name] == 50.0 + chip
        finally:
            col.close()


def test_rejecting_every_family_does_not_latch():
    """A half-initialized runtime that briefly answers UNIMPLEMENTED for
    every family must not be latched off permanently: once it recovers, the
    next tick polls and samples normally."""
    with FakeLibtpuServer(num_chips=2) as server:
        server.reject_batch = True
        server.drop_metrics.update(tpumetrics.ALL_METRICS)
        col = make_collector(server)
        col.begin_tick()
        col.wait_ready()
        dev_stub = type("D", (), {"index": 0})
        with pytest.raises(CollectorError):
            col.sample(dev_stub)
        server.drop_metrics.clear()  # runtime finished initializing
        col.begin_tick()
        col.wait_ready()
        s = col.sample(col.discover()[0])
        assert schema.DUTY_CYCLE.name in s.values
        assert s.values[schema.dcn_value_key("p50")] == 0.001
        col.close()


def test_single_slice_runtime_omits_dcn(server):
    """A runtime without megascale metrics (single-slice) drops the DCN
    families; everything else still samples and no percentile keys appear."""
    for name in (tpumetrics.DCN_LATENCY_P50, tpumetrics.DCN_LATENCY_P90,
                 tpumetrics.DCN_LATENCY_P99):
        server.drop_metrics.add(name)
    col = make_collector(server)
    devs = col.discover()
    col.begin_tick()
    s = col.sample(devs[0])
    assert not any(key in s.values for key in schema.PERCENTILE_VALUE_KEYS)
    assert schema.DUTY_CYCLE.name in s.values
    col.close()


def test_sample_before_any_tick_raises(server):
    col = make_collector(server)
    devs = col.discover()
    with pytest.raises(CollectorError):
        col.sample(devs[0])
    col.close()


def test_server_down_poisons_tick(server):
    col = make_collector(server)
    devs = col.discover()
    server.fail = True
    col.begin_tick()
    with pytest.raises(CollectorError):
        col.sample(devs[0])
    server.fail = False
    col.begin_tick()
    assert col.sample(devs[0]).values
    col.close()


def test_partial_metric_failure_keeps_rest(server):
    server.drop_metrics.add(tpumetrics.ICI_TRAFFIC)
    col = make_collector(server)
    devs = col.discover()
    col.begin_tick()
    s = col.sample(devs[0])
    assert s.ici_counters == {}
    assert schema.DUTY_CYCLE.name in s.values
    col.close()


def test_rpc_timeout_is_a_collector_error(server):
    server.delay = 0.5
    col = make_collector(server, rpc_timeout=0.05)
    col.begin_tick()
    dev_stub = type("D", (), {"index": 0})
    with pytest.raises(CollectorError):
        col.sample(dev_stub)
    col.close()


def test_garbled_response_is_collector_error(server):
    col = make_collector(server)
    devs = col.discover()
    server.garble = True
    col.begin_tick()
    with pytest.raises(CollectorError):
        col.sample(devs[0])
    col.close()


def test_multi_port_merge():
    """Multi-process runtimes serve different chips on different ports
    (TPU_RUNTIME_METRICS_PORTS lists several); the client merges them."""
    with FakeLibtpuServer(num_chips=2, chip_offset=0) as s1, \
         FakeLibtpuServer(num_chips=2, chip_offset=2) as s2:
        client = LibtpuClient(ports=(s1.port, s2.port), rpc_timeout=1.0)
        col = LibtpuCollector(client, accel_type="tpu-test")
        devs = col.discover()
        assert [d.index for d in devs] == [0, 1, 2, 3]
        col.begin_tick()
        assert col.sample(devs[3]).values[schema.DUTY_CYCLE.name] == 53.0
        col.close()


def test_one_port_down_still_serves_other():
    with FakeLibtpuServer(num_chips=2) as s1:
        client = LibtpuClient(ports=(s1.port, 1), rpc_timeout=0.3)  # port 1: dead
        col = LibtpuCollector(client, accel_type="tpu-test")
        devs = col.discover()
        assert len(devs) == 2
        col.begin_tick()
        assert col.sample(devs[1]).values
        col.close()


def test_batched_fetch_is_single_rpc():
    # Flat-only: the batched "" selector is a flat-dialect capability
    # (nested runtimes answer one family per RPC by construction).
    with FakeLibtpuServer(num_chips=4, dialect="flat") as server:
        col = make_collector(server)
        devs = col.discover()
        server.requests.clear()
        col.begin_tick()
        col.wait_ready()  # begin_tick only dispatches; join before asserting
        assert server.requests == [""]  # one RPC covers all metric families
        assert col.sample(devs[0]).values
        col.close()


def test_legacy_runtime_falls_back_to_per_metric(server):
    server.reject_batch = True
    col = make_collector(server)
    devs = col.discover()
    server.requests.clear()
    col.begin_tick()
    col.wait_ready()
    assert "" in server.requests  # probed once...
    assert set(server.requests) - {""} == set(tpumetrics.ALL_METRICS)
    server.requests.clear()
    col.begin_tick()
    col.wait_ready()
    assert "" not in server.requests  # ...then remembered the answer
    assert col.sample(devs[0]).values
    col.close()


def test_transient_outage_does_not_latch_per_metric_mode():
    """Runtime not up at pod start (UNAVAILABLE) must NOT permanently
    disable the batched fetch (review finding). Flat-only: asserts on the
    batched selector's retry behavior."""
    with FakeLibtpuServer(num_chips=4, dialect="flat") as server:
        server.fail = True
        col = make_collector(server)
        col.begin_tick()  # outage while probing
        col.wait_ready()
        server.fail = False
        server.requests.clear()
        col.begin_tick()
        col.wait_ready()
        assert server.requests == [""]  # batched path retried and won
        col.close()


def test_wire_type_mismatch_is_collector_error(server):
    """A response whose fields use wrong wire types must become
    CollectorError, not AttributeError (review finding)."""
    from kube_gpu_stats_tpu.proto import codec

    # Metric message with name (field 1) encoded as varint.
    bad_metric = codec.field_varint(1, 99) + codec.field_varint(2, 0)
    bad_response = codec.field_bytes(1, bad_metric)
    with pytest.raises(ValueError):
        tpumetrics.decode_response(bad_response)
    # And field "metrics" itself as varint:
    with pytest.raises(ValueError):
        tpumetrics.decode_response(codec.field_varint(1, 5))


def _metric_bytes(name, chip, *, double=None, varint=None, link=None):
    from kube_gpu_stats_tpu.proto import codec

    out = codec.field_string(1, name) + codec.field_varint(2, chip)
    if double is not None:
        out += codec.field_double(3, double)
    if varint is not None:
        out += codec.field_varint(4, varint)
    if link is not None:
        out += codec.field_string(6, link)
    return codec.field_bytes(1, out)


def test_python_ingest_is_all_or_nothing():
    """int(NaN)/int(inf) mid-response must leave the cache untouched on the
    pure-Python path too (review finding: it used to publish the leading
    metrics before raising)."""
    from kube_gpu_stats_tpu.collectors.libtpu import ingest_response_py

    raw = (_metric_bytes(tpumetrics.DUTY_CYCLE, 0, double=42.0) +
           _metric_bytes(tpumetrics.ICI_TRAFFIC, 0, double=float("nan"),
                         link="x0"))
    cache = {}
    with pytest.raises(ValueError):
        ingest_response_py(raw, cache)
    assert cache == {}


def test_bad_port_value_contained_to_that_port():
    """A port emitting inf for a counter metric (OverflowError on int())
    must not poison data from healthy ports (review finding: OverflowError
    escaped _refresh and failed the whole tick)."""
    good = _metric_bytes(tpumetrics.DUTY_CYCLE, 0, double=42.0)
    bad = _metric_bytes(tpumetrics.ICI_TRAFFIC, 1, double=float("inf"),
                        link="x0")

    class StubClient:
        port_dialects: dict[int, str] = {}

        def get_raw_with_errors(self, metric_name):
            return [(8431, good), (8432, bad)], []

        def note_dialect(self, port, dialect, raw):
            pass

        def close(self):
            pass

    col = LibtpuCollector(StubClient(), accel_type="tpu-test")
    col.begin_tick()
    col.wait_ready()
    dev = type("D", (), {"index": 0})
    assert col.sample(dev).values[schema.DUTY_CYCLE.name] == 42.0
    col.close()


def test_bad_value_in_per_metric_mode_contained():
    """Same inf-containment contract in the legacy per-metric path: one bad
    family must not take down the collector (review finding)."""
    good = _metric_bytes(tpumetrics.DUTY_CYCLE, 0, double=42.0)
    bad = _metric_bytes(tpumetrics.ICI_TRAFFIC, 0, double=float("inf"),
                        link="x0")

    class StubClient:
        def get_metric(self, metric_name):
            raw = bad if metric_name == tpumetrics.ICI_TRAFFIC else good
            return tpumetrics.decode_response(raw)

        def close(self):
            pass

    col = LibtpuCollector(StubClient(), accel_type="tpu-test")
    col._batched = False  # legacy runtime: per-metric requests
    col.begin_tick()
    col.wait_ready()
    dev = type("D", (), {"index": 0})
    s = col.sample(dev)
    assert s.values[schema.DUTY_CYCLE.name] == 42.0
    assert s.ici_counters == {}
    col.close()


def test_mixed_dialect_multi_port_merge():
    """Round-1 verdict item 1 done-criterion: a node whose runtime
    processes speak DIFFERENT wire dialects on different ports (e.g. a
    mid-upgrade multi-process runtime) must still merge every chip, and
    the client must report each port's dialect for diagnosis."""
    with FakeLibtpuServer(num_chips=2, chip_offset=0, dialect="flat") as s1, \
         FakeLibtpuServer(num_chips=2, chip_offset=2, dialect="nested") as s2:
        client = LibtpuClient(ports=(s1.port, s2.port), rpc_timeout=1.0)
        col = LibtpuCollector(client, accel_type="tpu-test")
        devs = col.discover()
        assert [d.index for d in devs] == [0, 1, 2, 3]
        col.begin_tick()
        # Chips behind the flat port and the nested port in one tick.
        assert col.sample(devs[0]).values[schema.DUTY_CYCLE.name] == 50.0
        assert col.sample(devs[3]).values[schema.DUTY_CYCLE.name] == 53.0
        assert set(col.sample(devs[1]).ici_counters) == set(LINKS)
        assert set(col.sample(devs[2]).ici_counters) == set(LINKS)
        assert client.port_dialects == {s1.port: "flat", s2.port: "nested"}
        col.close()


def test_client_latches_port_dialect(server):
    client = LibtpuClient(ports=(server.port,), rpc_timeout=1.0)
    client.get_metric(tpumetrics.DUTY_CYCLE)
    assert client.port_dialects == {server.port: server.dialect}
    client.close()


def test_overflow_in_one_port_decode_contained():
    """Review finding: a nested port whose device attribute is
    double_attr=inf raises OverflowError from int(); that must count as
    ONE failed port, not abort the multi-port merge."""
    from kube_gpu_stats_tpu.proto import codec

    inf_attr = (codec.field_string(1, "device_id")
                + codec.field_bytes(2, codec.field_double(4, float("inf"))))
    metric = (codec.field_bytes(1, inf_attr)
              + codec.field_bytes(3, codec.field_varint(2, 1)))
    poisoned = codec.field_bytes(1, (
        codec.field_string(1, tpumetrics.DUTY_CYCLE)
        + codec.field_bytes(3, metric)
    ))
    with pytest.raises(OverflowError):
        tpumetrics.decode_response(poisoned)
    good = tpumetrics.encode_response(
        [tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 0, 50.0)]
    )
    client = LibtpuClient(ports=(1, 2), rpc_timeout=0.1)
    client._fan_out = lambda req: [(good, None), (poisoned, None)]
    samples = client.get_metric(tpumetrics.DUTY_CYCLE)
    assert len(samples) == 1 and samples[0].value == 50.0
    client.close()


def test_latched_dialect_resolves_zero_omitted_idle_readings(caplog):
    """Round-2 advisor finding: a zero-omitting flat runtime serializes an
    idle chip 0 as a name-only Metric (the AMBIGUOUS wire shape). Before
    any dialect evidence the reading is dropped (with one warning per
    port); once a nonzero value latches the port as flat, subsequent
    ambiguous responses must resolve to the chip-0/value-0.0 reading
    instead of silently losing it every tick."""
    import logging

    from kube_gpu_stats_tpu.collectors import Device

    dev = Device(index=0, device_id="0", device_path="/dev/accel0",
                 accel_type="tpu-test")
    with FakeLibtpuServer(num_chips=1, dialect="flat") as server:
        server.zero_omit = True
        # ICI counters advance per fetch (never zero) — drop the family so
        # the all-idle response really is name-only throughout.
        server.drop_metrics.add(tpumetrics.ICI_TRAFFIC)
        for m in tpumetrics.ALL_METRICS:
            server.scripted[(m, 0)] = 0.0
        col = make_collector(server)
        with caplog.at_level(logging.WARNING,
                             logger="kube_gpu_stats_tpu.collectors.libtpu"):
            for _ in range(2):  # two ambiguous ticks, ONE warning
                col.begin_tick()
                col.wait_ready()
        with pytest.raises(CollectorError):
            col.peek(dev)  # unlatched: idle reading dropped
        drops = [r for r in caplog.records if "name-only" in r.message]
        assert len(drops) == 1

        server.scripted[(tpumetrics.DUTY_CYCLE, 0)] = 12.5
        col.begin_tick()
        col.wait_ready()
        assert col.peek(dev).values[schema.DUTY_CYCLE.name] == 12.5
        assert col._client.port_dialects == {server.port: tpumetrics.FLAT}

        server.scripted[(tpumetrics.DUTY_CYCLE, 0)] = 0.0
        col.begin_tick()
        col.wait_ready()
        # Latched flat: the ambiguous response now yields the idle zeros.
        s = col.peek(dev)
        assert s.values[schema.DUTY_CYCLE.name] == 0.0
        assert s.values[schema.MEMORY_TOTAL.name] == 0.0
        col.close()


def test_decode_response_ex_assume_resolves_only_ambiguous():
    from kube_gpu_stats_tpu.proto import codec

    name_only = codec.field_bytes(
        1, codec.field_string(1, tpumetrics.DUTY_CYCLE))
    # assume=FLAT recovers the zero-omitted reading
    samples, dialect = tpumetrics.decode_response_ex(
        name_only, tpumetrics.FLAT)
    assert dialect == tpumetrics.FLAT
    assert samples == [tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 0, 0.0)]
    # assume=NESTED reads it as an empty nested answer
    samples, dialect = tpumetrics.decode_response_ex(
        name_only, tpumetrics.NESTED)
    assert dialect == tpumetrics.NESTED and samples == []
    # assume must NOT override real structural evidence
    nested = tpumetrics.encode_response_nested(
        tpumetrics.DUTY_CYCLE,
        [tpumetrics.MetricSample(tpumetrics.DUTY_CYCLE, 2, 7.0)])
    samples, dialect = tpumetrics.decode_response_ex(nested, tpumetrics.FLAT)
    assert dialect == tpumetrics.NESTED
    assert samples[0].device_id == 2 and samples[0].value == 7.0


def test_dialect_relatches_when_runtime_restart_switches_builds():
    """Review finding: the latch must track contradicting structural
    evidence — a restarted workload can bring a different runtime build to
    the same port, and a stale FLAT latch would make ambiguous resolution
    fabricate chip-0 zeros from empty nested answers."""
    with FakeLibtpuServer(num_chips=1, dialect="flat") as server:
        client = LibtpuClient(ports=(server.port,), rpc_timeout=1.0)
        client.get_metric(tpumetrics.DUTY_CYCLE)
        assert client.port_dialects == {server.port: tpumetrics.FLAT}
        server.dialect = tpumetrics.NESTED  # "restart" with another build
        samples = client.get_metric(tpumetrics.DUTY_CYCLE)
        assert client.port_dialects == {server.port: tpumetrics.NESTED}
        assert samples and samples[0].value == 50.0  # still decodes right
        client.close()


def test_unknown_families_counted_and_warned_once(caplog):
    """Round-2 verdict item 6: a runtime serving families outside the
    pinned name surface must not present as a silently-empty collector —
    the drop is counted, warned once per port, and the known families
    still ingest cleanly (no phantom cache entries from alien names)."""
    import logging

    with FakeLibtpuServer(num_chips=2) as server:
        server.extra_metrics["tpu.runtime.novel.percentile"] = 7.0
        col = make_collector(server)
        devs = col.discover()
        with caplog.at_level(logging.WARNING,
                             logger="kube_gpu_stats_tpu.collectors.libtpu"):
            for _ in range(3):
                col.begin_tick()
                col.wait_ready()
        s = col.sample(devs[0])
        assert s.values[schema.DUTY_CYCLE.name] == 50.0
        assert not any("novel" in k for k in s.values)
        port = server.port
        # 2 chips x 1 alien family x 3 ticks
        assert col.unknown_family_samples[port] == 6
        warns = [r for r in caplog.records if "name surface" in r.message]
        assert len(warns) == 1  # once per port, not per tick
        assert "novel" in warns[0].message or "doctor" in warns[0].message
        col.close()


def test_multiport_rpc_call_count_exact():
    """rpc_calls_total is summed on the calling thread after the port
    fan-out gathers (the per-port closures run on pool workers, where an
    unlocked increment can lose counts): two live ports must count
    exactly 2 per fan-out, and breaker-refused ports must not count."""
    with FakeLibtpuServer(num_chips=1) as a, \
            FakeLibtpuServer(num_chips=1, chip_offset=1) as b:
        client = LibtpuClient(ports=(a.port, b.port), rpc_timeout=0.5)
        try:
            assert client.rpc_calls_total == 0
            client.get_metric(tpumetrics.HBM_TOTAL)
            assert client.rpc_calls_total == 2
            client.get_raw_with_errors("")
            assert client.rpc_calls_total == 4
            # Force port b's breaker open: refused calls issue no RPC
            # and must not count.
            client.breakers[b.port]._trip()
            client.get_metric(tpumetrics.HBM_TOTAL)
            assert client.rpc_calls_total == 5
        finally:
            client.close()


def test_rpc_stats_tolerates_ducktyped_client():
    """rpc_stats must use the same getattr guard as _refresh for clients
    without the counter (duck-typed transports are explicitly supported
    by _fetch_per_metric) — an AttributeError here would crash every
    tick inside the poll loop's self-metrics contribution."""
    class MiniClient:
        def get_metric(self, name):
            return []

        def close(self):
            pass

    col = LibtpuCollector(MiniClient(), accel_type="tpu-test")
    try:
        stats = col.rpc_stats()
        assert stats["rpc_calls_total"] == 0
        assert stats["batched_families"] == 0
    finally:
        col.close()
