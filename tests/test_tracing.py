"""Flight recorder (ISSUE 4): span recording, the anomaly event
journal, the /debug introspection endpoints, and doctor's --trace
post-mortem. The Chrome trace-event JSON shape is golden-pinned
(regenerate with GOLDEN_UPDATE=1, like tests/test_golden.py)."""

import itertools
import json
import os
import pathlib
import urllib.error
import urllib.request

import pytest

from kube_gpu_stats_tpu import doctor
from kube_gpu_stats_tpu.exposition import MetricsServer
from kube_gpu_stats_tpu.registry import Registry
from kube_gpu_stats_tpu.resilience import CircuitBreaker
from kube_gpu_stats_tpu.supervisor import Supervisor
from kube_gpu_stats_tpu.tracing import (Tracer, log_every,
                                        measure_overhead_ns,
                                        reset_log_marks)

TRACE_GOLDEN = pathlib.Path(__file__).parent / "golden" / "trace_3tick.json"


# -- span recording ----------------------------------------------------------

def test_span_records_into_the_ring():
    tracer = Tracer()
    tracer.begin("tick", 1)
    with tracer.span("fetch_wait"):
        pass
    with tracer.span("fold", device="3"):
        pass
    trace = tracer.end(devices=2)
    assert trace is not None
    assert trace.kind == "tick" and trace.seq == 1
    names = [s[0] for s in trace.spans]
    assert names == ["fetch_wait", "fold"]
    assert trace.spans[1][3] == {"device": "3"}
    assert trace.meta == {"devices": 2}
    assert tracer.traces() == [trace]


def test_disabled_tracer_is_a_noop():
    tracer = Tracer(enabled=False)
    tracer.begin("tick", 1)
    with tracer.span("x"):
        pass
    tracer.add_span("y", tracer.mark())
    tracer.aux_span("z", 123, dur_ns=1)
    tracer.event("breaker", "nope")
    assert tracer.end() is None
    assert tracer.traces() == []
    assert tracer.events()["events"] == []
    assert tracer.mark() == 0


def test_span_outside_a_trace_is_a_noop():
    tracer = Tracer()
    with tracer.span("orphan"):
        pass
    assert tracer.mark() == 0
    tracer.begin("tick", 1)
    assert tracer.end().spans == ()


def test_span_cap_counts_dropped_spans():
    tracer = Tracer(max_spans=4)
    tracer.begin("tick", 1)
    for _ in range(10):
        with tracer.span("s"):
            pass
    trace = tracer.end()
    assert len(trace.spans) == 4
    assert tracer.dropped_spans_total == 6


def test_aux_spans_drain_into_the_finishing_trace():
    tracer = Tracer()
    tracer.begin("tick", 7)
    tracer.aux_span("rpc_port", tracer.clock_ns(), dur_ns=5_000_000,
                    port=8431)
    trace = tracer.end()
    assert [s[0] for s in trace.spans] == ["rpc_port"]
    assert trace.spans[0][3] == {"port": 8431}
    # Drained: the next trace must not see it again.
    tracer.begin("tick", 8)
    assert tracer.end().spans == ()


def test_ring_is_bounded():
    tracer = Tracer(capacity=3)
    for seq in range(10):
        tracer.begin("tick", seq)
        tracer.end()
    assert [t.seq for t in tracer.traces()] == [7, 8, 9]
    assert [t.seq for t in tracer.traces(last=2)] == [8, 9]


# -- summaries ---------------------------------------------------------------

def test_ticks_summary_phases_and_blame():
    clock = itertools.count(0, 1_000_000).__next__  # 1 ms per clock read
    tracer = Tracer(clock_ns=clock, wall=lambda: 0.0)
    tracer.begin("tick", 1)
    with tracer.span("fetch_wait"):
        pass
    # start_ns=0 means "tracing was off at mark time" — use 1.
    tracer.aux_span("rpc_port", 1, dur_ns=50_000_000, port=8431)
    tracer.end()
    summary = tracer.ticks_summary()
    assert summary["ticks_recorded"] == 1
    assert summary["current_seq"] == 1
    assert "fetch_wait" in summary["phases"]
    assert summary["phases"]["rpc_port"]["max_ms"] == 50.0
    (slowest,) = summary["slowest"]
    assert slowest["seq"] == 1
    # The 50 ms aux span is both the worst phase and the blame carrier.
    assert slowest["worst_phase"] == "rpc_port"
    assert slowest["blame"]["attrs"] == {"port": 8431}


def test_overflow_bucket_quantile_stays_finite_json():
    """A >1 s observation (past the top phase bucket) must report the
    observed max, not float('inf') — json.dumps turns inf into the bare
    token Infinity, which is invalid JSON, exactly when a wedged tick
    makes /debug/ticks worth reading (review finding)."""
    tracer = Tracer()
    tracer.begin("tick", 1)
    tracer.aux_span("fetch_wait", 1, dur_ns=2_500_000_000)  # 2.5 s
    tracer.end()
    summary = tracer.ticks_summary()
    phase = summary["phases"]["fetch_wait"]
    assert phase["p50_ms"] == 2500.0
    assert phase["p99_ms"] == 2500.0
    json.loads(json.dumps(summary, allow_nan=False))  # strict-parseable


def test_aux_drain_respects_the_per_trace_span_cap():
    tracer = Tracer(max_spans=4)
    tracer.begin("tick", 1)
    with tracer.span("loop"):
        pass
    for i in range(10):
        tracer.aux_span("aux", 1, dur_ns=1, i=i)
    trace = tracer.end()
    assert len(trace.spans) == 4  # 1 loop + 3 aux — the documented cap
    assert tracer.dropped_spans_total == 7


# -- event journal -----------------------------------------------------------

def test_breaker_transition_journals_with_the_causing_tick_seq():
    tracer = Tracer()
    breaker = CircuitBreaker("libtpu:8431", failure_threshold=1,
                             min_failure_span=0.0)
    breaker.on_transition = tracer.breaker_listener
    tracer.begin("tick", 5)
    breaker.record_failure(RuntimeError("connection refused"))
    tracer.end()
    events = tracer.events()["events"]
    (opened,) = [e for e in events if e["kind"] == "breaker"]
    assert opened["tick_seq"] == 5
    assert opened["attrs"]["component"] == "libtpu:8431"
    assert opened["attrs"]["state"] == "open"
    assert "closed -> open" in opened["detail"]
    assert "connection refused" in opened["detail"]
    # Recovery probe + close journal too, with the then-current seq.
    tracer.begin("tick", 6)
    breaker._opened_at -= 10.0  # recovery window elapsed
    assert breaker.allow()
    breaker.record_success()
    tracer.end()
    states = [e["attrs"]["state"] for e in tracer.events()["events"]
              if e["kind"] == "breaker"]
    assert states == ["open", "half_open", "closed"]
    assert all(e["tick_seq"] == 6 for e in tracer.events(since=1)["events"])


def test_events_since_filter_and_last_id():
    tracer = Tracer()
    for i in range(5):
        tracer.event("plan_compile", f"device {i}", device=str(i))
    payload = tracer.events()
    assert payload["last_id"] == 5
    assert [e["id"] for e in payload["events"]] == [1, 2, 3, 4, 5]
    tail = tracer.events(since=3)
    assert [e["id"] for e in tail["events"]] == [4, 5]


def test_journal_is_bounded():
    tracer = Tracer(journal_capacity=3)
    for i in range(10):
        tracer.event("k", str(i))
    assert [e["detail"] for e in tracer.events()["events"]] == \
        ["7", "8", "9"]


def test_supervisor_attaches_listener_and_journals_health_flips():
    tracer = Tracer()
    supervisor = Supervisor(check_interval=0.01, tracer=tracer)
    breaker = CircuitBreaker("kubelet", failure_threshold=1,
                             min_failure_span=0.0)
    supervisor.register_breaker("kubelet", breaker)
    alive = [True]
    supervisor.register("poll", is_alive=lambda: alive[0], restart=None)
    supervisor.check_once()  # attaches the listener, baselines health
    assert breaker.on_transition is not None
    breaker.record_failure("socket gone")
    alive[0] = False
    supervisor.check_once()
    kinds = {(e["kind"], e["attrs"].get("component"))
             for e in tracer.events()["events"]}
    assert ("breaker", "kubelet") in kinds
    assert ("component", "poll") in kinds
    (flip,) = [e for e in tracer.events()["events"]
               if e["kind"] == "component"]
    assert "healthy -> stale" in flip["detail"]


# -- poll-loop integration ---------------------------------------------------

def test_poll_tick_records_phases_and_plan_compile_events():
    from kube_gpu_stats_tpu.collectors.mock import MockCollector
    from kube_gpu_stats_tpu.poll import PollLoop

    tracer = Tracer()
    loop = PollLoop(MockCollector(num_devices=2), Registry(),
                    deadline=5.0, tracer=tracer)
    loop.tick()
    loop.tick()
    loop.stop()
    assert [t.seq for t in tracer.traces()] == [1, 2]
    first = tracer.traces()[0]
    names = {s[0] for s in first.spans}
    assert {"env_round", "fold", "plan_write", "publish"} <= names
    # Generic (non-split) backends record per-device sample aux spans.
    devices = {s[3]["device"] for s in first.spans if s[0] == "sample"}
    assert devices == {"0", "1"}
    assert first.meta["devices"] == 2
    compiles = [e for e in tracer.events()["events"]
                if e["kind"] == "plan_compile"]
    assert len(compiles) == 2  # one per device, tick 1 only
    assert all(e["tick_seq"] == 1 for e in compiles)
    # The dropped-spans self-metric rides every snapshot, born at 0.
    from kube_gpu_stats_tpu import schema
    loop2 = PollLoop(MockCollector(num_devices=1), Registry(), deadline=5.0)
    registry = loop2._registry
    loop2.tick()
    loop2.stop()
    (series,) = [s for s in registry.snapshot().series
                 if s.spec.name == schema.TRACE_DROPPED_SPANS.name]
    assert series.value == 0.0


def test_breaker_open_event_has_the_right_tick_seq_via_http():
    """Acceptance: /debug/events shows the breaker transition with the
    tick seq that caused it. A dead libtpu port fails once per blocking
    tick; with failure_threshold=2 the breaker must open DURING tick 2
    and the journal entry must carry seq 2."""
    import socket

    from kube_gpu_stats_tpu.collectors.composite import TpuCollector
    from kube_gpu_stats_tpu.collectors.libtpu import LibtpuClient
    from kube_gpu_stats_tpu.poll import PollLoop
    from kube_gpu_stats_tpu.testing import make_sysfs

    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        dead_port = probe.getsockname()[1]
    import tempfile

    tracer = Tracer()
    with tempfile.TemporaryDirectory() as tmp:
        sysroot = pathlib.Path(tmp) / "sys"
        make_sysfs(sysroot, num_chips=2)
        collector = TpuCollector(
            sysfs_root=str(sysroot),
            libtpu_client=LibtpuClient(
                ports=(dead_port,), rpc_timeout=0.5,
                breaker_failure_threshold=2, breaker_min_span=0.0,
                breaker_recovery_time=60.0))
        # The daemon's supervisor normally attaches this on its first
        # watchdog pass; wire it directly here.
        for breaker in collector.breakers().values():
            breaker.on_transition = tracer.breaker_listener
        registry = Registry()
        loop = PollLoop(collector, registry, deadline=2.0,
                        pipeline_fetch=False, tracer=tracer)
        server = MetricsServer(registry, host="127.0.0.1", port=0,
                               trace_provider=tracer)
        server.start()
        try:
            for _ in range(3):
                loop.tick()
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/debug/events?since=0",
                timeout=5).read()
            events = json.loads(body)["events"]
            opened = [e for e in events if e["kind"] == "breaker"
                      and e["attrs"].get("state") == "open"]
            assert opened, events
            assert opened[0]["tick_seq"] == 2, opened
            assert opened[0]["attrs"]["component"] == f"libtpu:{dead_port}"
        finally:
            server.stop()
            loop.stop()
            collector.close()


def test_hub_cycle_records_phases_and_target_spans(tmp_path):
    from kube_gpu_stats_tpu import schema
    from kube_gpu_stats_tpu.hub import Hub
    from kube_gpu_stats_tpu.registry import SnapshotBuilder

    builder = SnapshotBuilder()
    builder.add(schema.DEVICE_UP, 1.0, [("chip", "0")])
    target = tmp_path / "w0.prom"
    target.write_text(builder.build().render())
    hub = Hub([str(target)], interval=60.0)
    try:
        hub.refresh_once()
        hub.refresh_once()
    finally:
        hub.stop()
    traces = hub.tracer.traces()
    assert [t.seq for t in traces] == [1, 2]
    assert traces[0].kind == "cycle"
    names = {s[0] for s in traces[0].spans}
    assert {"fetch", "frame_fold", "merge", "publish"} <= names
    # The cold cycle parsed the body; its target-attributed spans carry
    # the "which target" blame evidence.
    attrs = [s[3] for s in traces[0].spans
             if s[0] in ("target_fetch", "parse")]
    assert any(a and a.get("target") == str(target) for a in attrs)
    assert traces[0].meta["answered"] == 1


def test_hub_debug_trace_and_events_under_rollups_only_with_churn(tmp_path):
    """ISSUE 5 satellite: the hub's /debug/trace and /debug/events must
    stay coherent in --rollups-only mode AND across a target churning
    mid-window (the PR 2 cache-eviction path): cycle traces keep their
    per-target spans, eviction doesn't wedge the endpoints, and the
    payloads stay strict JSON."""
    from kube_gpu_stats_tpu.hub import Hub

    a = tmp_path / "a.prom"
    b = tmp_path / "b.prom"
    for path, worker in ((a, "0"), (b, "1")):
        path.write_text(
            f'accelerator_up{{chip="0",worker="{worker}",slice="s"}} 1\n')
    current = [[str(a), str(b)]]
    hub = Hub([], targets_provider=lambda: list(current[0]),
              rollups_only=True)
    srv = MetricsServer(hub.registry, host="127.0.0.1", port=0,
                        trace_provider=hub.tracer,
                        fleet_provider=hub.fleet)
    srv.start()
    try:
        hub.refresh_once()
        hub.refresh_once()
        current[0] = [str(a)]  # target b churns out mid-window
        hub.refresh_once()
        assert str(b) not in hub._parse_cache  # eviction path exercised
        trace = _get_json(srv.port, "/debug/trace?last=10")
        assert trace["enabled"] is True
        kinds = [e["name"] for e in trace["traceEvents"]]
        assert kinds.count("cycle") == 3
        # Pre-churn cycles carried target-attributed spans for BOTH
        # targets; rollups-only drops per-chip series, never the trace.
        targets = {e["args"].get("target")
                   for e in trace["traceEvents"]
                   if e["name"] in ("target_fetch", "parse")}
        assert {str(a), str(b)} <= targets
        ticks = _get_json(srv.port, "/debug/ticks")
        assert ticks["ticks_recorded"] == 3
        events = _get_json(srv.port, "/debug/events")
        assert events["enabled"] is True
        json.dumps(events, allow_nan=False)  # strict-parseable
        # The departed target's cached spans survive in the recorded
        # window; a refresh AFTER eviction still serves everything.
        hub.refresh_once()
        assert _get_json(srv.port, "/debug/trace?last=1")["traceEvents"]
    finally:
        srv.stop()
        hub.stop()


def test_hub_slowest_cycle_blames_timed_out_target(tmp_path):
    """ISSUE 5 satellite: a fetch that blows the refresh deadline is
    exactly the one that made the cycle slow — the slowest-cycle table
    must carry its target in the blame span (parity with the daemon's
    device/port blame), not just the successful fetches'."""
    import os

    from kube_gpu_stats_tpu.hub import Hub

    good = tmp_path / "a_good.prom"
    good.write_text('accelerator_up{chip="0",worker="0",slice="s"} 1\n')
    fifo = tmp_path / "z_hung.prom"
    os.mkfifo(fifo)  # read blocks forever: the NFS/FUSE-stall stand-in
    hub = Hub([str(good), str(fifo)], fetch_timeout=0.2)
    try:
        hub.refresh_once()
        summary = hub.tracer.ticks_summary()
        (slowest,) = [row for row in summary["slowest"]
                      if row["kind"] == "cycle"][:1]
        assert slowest["blame"]["attrs"]["target"] == str(fifo)
        assert slowest["blame"]["attrs"]["error"]
        assert slowest["blame"]["span"] == "target_fetch"
    finally:
        hub.stop()


# -- /debug endpoints --------------------------------------------------------

@pytest.fixture
def traced_server():
    tracer = Tracer()
    tracer.begin("tick", 1)
    with tracer.span("fetch_wait"):
        pass
    tracer.end(devices=1)
    tracer.event("plan_compile", "device 0: tick plan compiled (device)",
                 device="0", reason="device")
    srv = MetricsServer(Registry(), host="127.0.0.1", port=0,
                        trace_provider=tracer)
    srv.start()
    yield srv
    srv.stop()


def _get_json(port, path):
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5).read()
    return json.loads(body)


def test_debug_ticks_endpoint(traced_server):
    payload = _get_json(traced_server.port, "/debug/ticks")
    assert payload["enabled"] is True
    assert payload["ticks_recorded"] == 1
    assert "fetch_wait" in payload["phases"]
    assert payload["slowest"][0]["seq"] == 1


def test_debug_trace_endpoint_is_chrome_loadable(traced_server):
    payload = _get_json(traced_server.port, "/debug/trace?last=5")
    assert payload["displayTimeUnit"] == "ms"
    names = [e["name"] for e in payload["traceEvents"]]
    assert names == ["tick", "fetch_wait"]
    for event in payload["traceEvents"]:
        assert event["ph"] == "X"
        assert event["ts"] >= 0


def test_debug_events_endpoint_and_since(traced_server):
    payload = _get_json(traced_server.port, "/debug/events")
    assert [e["kind"] for e in payload["events"]] == ["plan_compile"]
    last = payload["last_id"]
    assert _get_json(traced_server.port,
                     f"/debug/events?since={last}")["events"] == []


def test_debug_trace_endpoints_404_without_a_tracer():
    srv = MetricsServer(Registry(), host="127.0.0.1", port=0)
    srv.start()
    try:
        for path in ("/debug/ticks", "/debug/trace", "/debug/events"):
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}{path}", timeout=5)
            assert err.value.code == 404
    finally:
        srv.stop()


def test_landing_page_lists_every_served_endpoint(traced_server):
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{traced_server.port}/", timeout=5).read().decode()
    for path in ("/metrics", "/healthz", "/readyz", "/debug/threads",
                 "/debug/profile", "/debug/ticks", "/debug/trace",
                 "/debug/events"):
        assert path in body, path
    # ...and a server without a tracer doesn't advertise trace endpoints.
    bare = MetricsServer(Registry(), host="127.0.0.1", port=0)
    bare.start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{bare.port}/", timeout=5).read().decode()
        assert "/debug/ticks" not in body
        assert "/readyz" in body
    finally:
        bare.stop()


# -- Chrome trace golden -----------------------------------------------------

def scripted_3tick_tracer() -> Tracer:
    """Deterministic 3-tick run: a counting clock (1 ms per read) and a
    counting wall clock, so the trace-event JSON is byte-stable."""
    clock = itertools.count(1_000_000, 1_000_000).__next__
    wall = itertools.count(1_700_000_000, 1).__next__
    tracer = Tracer(clock_ns=clock, wall=wall)
    for seq in (1, 2, 3):
        tracer.begin("tick", seq)
        with tracer.span("fetch_wait"):
            pass
        with tracer.span("env_round"):
            pass
        with tracer.span("fold", device="0"):
            pass
        tracer.aux_span("rpc_port", tracer.clock_ns(), dur_ns=2_000_000,
                        port=8431)
        tracer.end(devices=2, series=40)
    return tracer


def test_chrome_trace_golden():
    tracer = scripted_3tick_tracer()
    text = json.dumps(tracer.chrome_trace(), indent=2, sort_keys=True) + "\n"
    if os.environ.get("GOLDEN_UPDATE"):
        TRACE_GOLDEN.parent.mkdir(exist_ok=True)
        TRACE_GOLDEN.write_text(text)
    assert TRACE_GOLDEN.exists(), "golden missing; run with GOLDEN_UPDATE=1"
    assert text == TRACE_GOLDEN.read_text()


# -- doctor --trace ----------------------------------------------------------

def test_doctor_trace_postmortem_names_slow_phase_and_port():
    """Acceptance (fault injection): against a live daemon with an
    injected slow port, `doctor --trace` must name the slow phase
    (fetch_wait — blocking ticks join the delayed RPC) and the
    responsible port in its post-mortem."""
    import tempfile

    from kube_gpu_stats_tpu.collectors.composite import TpuCollector
    from kube_gpu_stats_tpu.collectors.libtpu import LibtpuClient
    from kube_gpu_stats_tpu.poll import PollLoop
    from kube_gpu_stats_tpu.testing import FakeLibtpuServer, make_sysfs

    fake = FakeLibtpuServer(num_chips=2)
    fake.delay = 0.1  # the injected slow port
    fake.start()
    tracer = Tracer()
    try:
        with tempfile.TemporaryDirectory() as tmp:
            sysroot = pathlib.Path(tmp) / "sys"
            make_sysfs(sysroot, num_chips=2)
            collector = TpuCollector(
                sysfs_root=str(sysroot),
                libtpu_client=LibtpuClient(ports=(fake.port,),
                                           rpc_timeout=5.0))
            collector.set_tracer(tracer)
            registry = Registry()
            loop = PollLoop(collector, registry, deadline=2.0,
                            pipeline_fetch=False, tracer=tracer)
            server = MetricsServer(registry, host="127.0.0.1", port=0,
                                   trace_provider=tracer)
            server.start()
            try:
                for _ in range(3):
                    loop.tick()
                result = doctor.check_trace(
                    f"http://127.0.0.1:{server.port}")
            finally:
                server.stop()
                loop.stop()
                collector.close()
    finally:
        fake.stop()
    assert result.status == "ok", result
    # The slow phase is the runtime fetch either way it's named: the
    # loop-side join (fetch_wait) and the transport-side per-port span
    # (rpc_port, which includes connection setup and can outlast the
    # join by a hair) race for "worst" — both are the right answer.
    assert ("fetch_wait" in result.detail or "rpc_port" in result.detail), \
        result.detail
    slowest = result.data["slowest"]
    fetch_phases = {"fetch_wait", "rpc_port"}
    assert slowest["worst_phase"] in fetch_phases, slowest
    # ...and the responsible PORT is named unambiguously via the blame
    # span, which always carries the port attr.
    assert str(fake.port) in result.detail, result.detail
    assert slowest["blame"]["attrs"]["port"] == fake.port


def test_doctor_trace_classifies_disabled_and_missing():
    # Disabled tracer: endpoints answer, doctor says so.
    tracer = Tracer(enabled=False)
    srv = MetricsServer(Registry(), host="127.0.0.1", port=0,
                        trace_provider=tracer)
    srv.start()
    try:
        result = doctor.check_trace(f"http://127.0.0.1:{srv.port}")
        assert result.status == "warn"
        assert "disabled" in result.detail
    finally:
        srv.stop()
    # No tracer wired: 404 classified as predates-the-recorder.
    bare = MetricsServer(Registry(), host="127.0.0.1", port=0)
    bare.start()
    try:
        result = doctor.check_trace(f"http://127.0.0.1:{bare.port}")
        assert result.status == "warn"
        assert "/debug/ticks" in result.detail
    finally:
        bare.stop()


def test_doctor_trace_base_derivation():
    assert doctor.trace_base("http://h:9400/metrics") == "http://h:9400"
    assert doctor.trace_base("http://h:9400") == "http://h:9400"
    assert doctor.trace_base("http://h:9400/") == "http://h:9400"


def test_doctor_main_accepts_trace_flag(tmp_path, capsys):
    """--trace rides the normal doctor pass as one more row (FAIL when
    the daemon is unreachable — nothing is listening on the target)."""
    import socket

    with socket.socket() as probe:
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
    rc = doctor.main([
        "--trace", "--url", f"http://127.0.0.1:{port}/metrics", "--json",
        "--backend", "mock", "--attribution", "off",
        "--sysfs-root", str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    rows = {c["name"]: c for c in out["checks"]}
    assert "trace" in rows
    assert rows["trace"]["status"] == "fail"
    assert rc == 1


# -- overhead + log rate limiting --------------------------------------------

def test_span_overhead_is_measurable_and_sane():
    ns = measure_overhead_ns(spans=2000)
    assert ns > 0
    # The hard budget lives in tests/test_latency.py; this is the
    # smoke check that the measurement itself works.
    assert ns < 1_000_000, ns


def test_log_every_rate_limits_per_key():
    reset_log_marks()
    clock = itertools.count(0.0, 1.0).__next__  # 1 s per call
    assert log_every("k", 10.0, clock=clock)      # t=0: granted
    assert not log_every("k", 10.0, clock=clock)  # t=1: suppressed
    assert log_every("other", 10.0, clock=clock)  # t=2: new key granted
    for _ in range(7):
        assert not log_every("k", 10.0, clock=clock)  # t=3..9
    assert log_every("k", 10.0, clock=clock)      # t=10: window elapsed
    reset_log_marks()
