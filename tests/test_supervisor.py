"""Supervisor unit tests (supervisor.py): watchdog detection of dead and
hung components, restart pacing, the healthy/degraded/stale state
machine, and the kts_* self-metric contribution. Clock-driven — no
thread sleeps except where a real thread is the thing under test."""

import threading

from kube_gpu_stats_tpu import schema
from kube_gpu_stats_tpu.registry import SnapshotBuilder
from kube_gpu_stats_tpu.resilience import BackoffPolicy, CircuitBreaker
from kube_gpu_stats_tpu.supervisor import (DEGRADED, HEALTHY, STALE,
                                           Supervisor)


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


def series(builder: SnapshotBuilder) -> dict:
    snap = builder.build()
    return {(s.spec.name, tuple(s.labels)): s.value for s in snap.series}


def test_dead_component_is_restarted_with_backoff():
    clock = FakeClock()
    sup = Supervisor(clock=clock)
    alive = {"up": False}
    restarts = []

    def restart():
        restarts.append(clock.now)

    sup.register("worker", is_alive=lambda: alive["up"], restart=restart,
                 backoff=BackoffPolicy(base=2.0, cap=8.0))
    assert sup.check_once() == ["worker"]
    # Still dead immediately after: backoff pacing refuses a hot loop.
    assert sup.check_once() == []
    clock.advance(2.0)
    assert sup.check_once() == ["worker"]
    assert len(restarts) == 2
    # Component comes back: healthy, restart count retained.
    alive["up"] = True
    assert sup.check_once() == []
    (row,) = sup.health()
    assert row.state == DEGRADED  # restarted recently
    assert row.restarts == 2
    clock.advance(Supervisor.DEGRADED_HOLD + 1)
    (row,) = sup.health()
    assert row.state == HEALTHY


def test_hung_component_detected_via_heartbeat():
    clock = FakeClock()
    sup = Supervisor(clock=clock)
    restarts = []
    sup.register("poll", is_alive=lambda: True,
                 restart=lambda: restarts.append(clock.now),
                 heartbeat_timeout=5.0)
    sup.beat("poll")
    clock.advance(4.0)
    assert sup.check_once() == []  # beating recently enough
    clock.advance(2.0)  # 6s since last beat > 5s timeout
    (row,) = sup.health()
    assert row.state == STALE
    assert "no heartbeat" in row.reason
    assert sup.check_once() == ["poll"]
    assert restarts == [6.0]
    # The restart granted heartbeat grace: not immediately re-restarted.
    assert sup.check_once() == []


def test_breaker_makes_component_degraded_and_reports():
    clock = FakeClock()
    sup = Supervisor(clock=clock)
    sup.register("attribution", is_alive=lambda: True)
    breaker = CircuitBreaker("kubelet", failure_threshold=1, clock=clock)
    sup.register_breaker("attribution:kubelet", breaker)
    (row,) = sup.health()
    assert row.state == HEALTHY
    breaker.record_failure("socket gone")
    (row,) = sup.health()
    assert row.state == DEGRADED
    assert "attribution:kubelet" in row.reason
    # health_report carries per-component reasons for /healthz.
    report = dict(
        (name, (state, reason)) for name, state, reason in sup.health_report())
    assert report["attribution"][0] == DEGRADED


def test_breaker_provider_is_late_bound():
    sup = Supervisor(clock=FakeClock())
    holder = {}
    sup.register_breaker_provider(lambda: holder)
    assert sup.breakers() == {}
    breaker = CircuitBreaker("libtpu:8431")
    holder["libtpu:8431"] = breaker
    assert sup.breakers() == {"libtpu:8431": breaker}


def test_contribute_exports_kts_families():
    clock = FakeClock()
    sup = Supervisor(clock=clock)
    sup.register("poll", is_alive=lambda: True, heartbeat_timeout=5.0)
    breaker = CircuitBreaker("libtpu:8431", failure_threshold=1, clock=clock)
    sup.register_breaker("libtpu:8431", breaker)
    breaker.record_failure("down")
    builder = SnapshotBuilder()
    sup.contribute(builder)
    values = series(builder)
    poll = (("component", "poll"),)
    port = (("component", "libtpu:8431"),)
    assert values[(schema.COMPONENT_HEALTHY.name, poll)] == 1.0
    assert values[(schema.COMPONENT_RESTARTS.name, poll)] == 0.0
    assert values[(schema.BREAKER_STATE.name, port)] == 2.0  # open
    assert values[(schema.BREAKER_TRIPS.name, port)] == 1.0


def test_unowned_breaker_gets_its_own_health_row():
    sup = Supervisor(clock=FakeClock())
    breaker = CircuitBreaker("target:http://w0:9400/metrics",
                             failure_threshold=1)
    sup.register_breaker("target:http://w0:9400/metrics", breaker)
    breaker.record_failure("conn refused")
    report = {name: (state, reason)
              for name, state, reason in sup.health_report()}
    state, reason = report["target:http://w0:9400/metrics"]
    assert state == DEGRADED
    assert "open" in reason


def test_watchdog_thread_restarts_real_dead_thread():
    # End-to-end with a real thread: die once, get respawned, stay up.
    sup = Supervisor(check_interval=0.02)
    spawned = []

    def spawn():
        thread = threading.Thread(target=lambda: None, daemon=True)
        thread.start()
        thread.join()  # dies immediately -> watchdog sees a dead thread
        spawned.append(thread)

    spawn()
    sup.register("flaky", is_alive=lambda: spawned[-1].is_alive(),
                 restart=spawn,
                 backoff=BackoffPolicy(base=0.01, cap=0.05))
    sup.start()
    try:
        import time

        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and len(spawned) < 3:
            time.sleep(0.01)
        assert len(spawned) >= 3
        (row,) = sup.health()
        assert row.restarts >= 2
    finally:
        sup.stop()


def test_crashing_restart_is_not_counted():
    """restart() raising means nothing was respawned: no restart count,
    no heartbeat grace — only the backoff advances."""
    clock = FakeClock()
    sup = Supervisor(clock=clock)
    attempts = []

    def bad_restart():
        attempts.append(clock.now)
        raise RuntimeError("start() is broken")

    sup.register("worker", is_alive=lambda: False, restart=bad_restart,
                 backoff=BackoffPolicy(base=2.0, cap=8.0))
    assert sup.check_once() == []  # attempted, crashed, not counted
    assert attempts == [0.0]
    (row,) = sup.health()
    assert row.restarts == 0
    assert row.state == STALE  # still dead, no fake grace
    # Backoff still paces the next attempt.
    assert sup.check_once() == []
    assert attempts == [0.0]
    clock.advance(2.0)
    sup.check_once()
    assert attempts == [0.0, 2.0]


def test_breaker_prefixes_map_production_names():
    """The shipped wiring: component 'poll' owns 'libtpu:<port>',
    'attribution' owns 'kubelet' — an open breaker degrades its owner
    and does not get a duplicate standalone row."""
    clock = FakeClock()
    sup = Supervisor(clock=clock)
    sup.register("poll", is_alive=lambda: True,
                 breaker_prefixes=("libtpu",))
    sup.register("attribution", is_alive=lambda: True,
                 breaker_prefixes=("kubelet",))
    libtpu = CircuitBreaker("libtpu:8431", failure_threshold=1, clock=clock)
    kubelet = CircuitBreaker("kubelet", failure_threshold=1, clock=clock)
    sup.register_breaker("libtpu:8431", libtpu)
    sup.register_breaker("kubelet", kubelet)
    assert all(h.state == HEALTHY for h in sup.health())
    libtpu.record_failure("runtime gone")
    kubelet.record_failure("socket gone")
    states = {h.name: (h.state, h.reason) for h in sup.health()}
    assert states["poll"][0] == DEGRADED
    assert "libtpu:8431" in states["poll"][1]
    assert states["attribution"][0] == DEGRADED
    assert "kubelet" in states["attribution"][1]
    # No duplicate standalone rows for owned breakers.
    assert [name for name, _, _ in sup.health_report()] == [
        "poll", "attribution"]
