"""Self-metric/docs consistency gate (ISSUE 4 satellite): every family
the schema emits must appear in docs/METRICS.md and vice versa — the
pytest face of `make lint`'s tools/check_metrics_docs.py."""

import importlib.util
import pathlib
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
TOOL = ROOT / "tools" / "check_metrics_docs.py"


def _load_tool():
    spec = importlib.util.spec_from_file_location("check_metrics_docs", TOOL)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_metrics_docs_in_sync():
    tool = _load_tool()
    assert tool.check() == [], (
        "docs/METRICS.md out of sync with the schema; regenerate with "
        "`python -m kube_gpu_stats_tpu.schema`")


def test_tool_exits_nonzero_on_drift(tmp_path, monkeypatch):
    """The lint must actually catch drift, both directions."""
    tool = _load_tool()
    doc = tmp_path / "METRICS.md"
    text = TOOL.parent.parent.joinpath("docs", "METRICS.md").read_text()
    doc.write_text(
        text.replace("| `kts_trace_dropped_spans_total` |", "| `gone` |", 1))
    monkeypatch.setattr(tool, "DOC", doc)
    problems = tool.check()
    assert any("kts_trace_dropped_spans_total" in p and "missing" in p
               for p in problems), problems
    assert any("gone" in p and "not emitted" in p for p in problems), problems


def test_cli_entrypoint_green():
    proc = subprocess.run([sys.executable, str(TOOL)],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
