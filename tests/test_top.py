"""`kube-tpu-stats top` — the live per-chip operator view (cli.py). Frames
are built from real rendered snapshots (mock collector through the real
poll loop + registry) so the view is pinned to the actual exposition, not
hand-written fixture text."""

import json

from kube_gpu_stats_tpu import schema, top
from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry


def rendered(worker="0", ticks=2):
    reg = Registry()
    loop = PollLoop(
        MockCollector(num_devices=2, accel_type="tpu-v5p"),
        reg,
        deadline=5.0,
        topology_labels={"slice": "v5p-16", "worker": worker,
                         "topology": "2x2x4"},
    )
    for _ in range(ticks):
        loop.tick()
    loop.stop()
    return reg.snapshot().render()


def test_build_frame_folds_targets_into_chip_rows():
    frame = top.build_frame([rendered("0"), rendered("1")], [], ats=[0.0, 0.0])
    assert len(frame.rows) == 4  # 2 workers x 2 chips
    row = frame.rows[(0, "v5p-16", "0", "0")]
    assert row.accel_type == "tpu-v5p"
    assert row.up == 1.0
    assert row.duty is not None and 0.0 <= row.duty <= 100.0
    assert row.mem_total and row.mem_used is not None
    assert row.ici_bps > 0  # mock exports per-link rates from tick 2


def test_rates_need_two_frames():
    text_a = (
        'accelerator_workload_steps_total{chip="0",worker="0",slice="s"} 100\n'
        'accelerator_workload_busy_seconds_total{chip="0",worker="0",slice="s"} 5\n'
    )
    text_b = (
        'accelerator_workload_steps_total{chip="0",worker="0",slice="s"} 150\n'
        'accelerator_workload_busy_seconds_total{chip="0",worker="0",slice="s"} 9\n'
    )
    first = top.build_frame([text_a], [], ats=[100.0])
    first.rates(None)
    row = first.rows[(0, "s", "0", "0")]
    assert row.steps_per_s is None and row.busy_pct is None
    second = top.build_frame([text_b], [], ats=[110.0])
    second.rates(first)
    row = second.rows[(0, "s", "0", "0")]
    assert row.steps_per_s == 5.0
    assert row.busy_pct == 40.0


def test_counter_reset_yields_no_rate():
    before = top.build_frame(
        ['accelerator_workload_steps_total{chip="0",worker="",slice=""} 100\n'],
        [], ats=[0.0])
    after = top.build_frame(
        ['accelerator_workload_steps_total{chip="0",worker="",slice=""} 3\n'],
        [], ats=[10.0])
    after.rates(before)
    assert after.rows[(0, "", "", "0")].steps_per_s is None


def test_render_table_shows_every_chip_and_pod():
    text = rendered().replace('pod=""', 'pod="train-abc"').replace(
        'namespace=""', 'namespace="ml"')
    frame = top.build_frame([text], [], ats=[0.0])
    out = top.render_table(frame)
    assert "CHIP" in out and "DUTY%" in out
    assert "0/w0" in out and "1/w0" in out
    assert "tpu-v5p" in out
    assert "ml/train-abc" in out
    assert "chips: 2 (2 up)" in out


def test_render_json_frame():
    frame = top.build_frame([rendered()], [], ats=[0.0])
    parsed = json.loads(top.render_json(frame))
    assert len(parsed["chips"]) == 2
    chip = parsed["chips"][0]
    assert chip["chip"] == "0" and chip["slice"] == "v5p-16"
    assert chip["up"] == 1.0 and "steps_per_s" in chip
    assert "mem_peak" in chip
    # Round-5 counters ride the JSON view: energy for accounting,
    # restarts for bounce triage (mock exports power, so energy exists;
    # one tick in, its integral is still 0).
    assert chip["energy_total"] is not None
    assert chip["restarts_total"] == 0.0


def test_process_open_counts_holders_excluding_overflow_fold():
    text = (
        'accelerator_process_open{chip="0",worker="",slice="",pid="1",comm="a"} 1\n'
        'accelerator_process_open{chip="0",worker="",slice="",pid="2",comm="b"} 1\n'
        'accelerator_process_open{chip="0",worker="",slice="",pid="",comm="_overflow"} 7\n'
    )
    frame = top.build_frame([text], [], ats=[0.0])
    assert frame.rows[(0, "", "", "0")].holders == 2


def test_identical_labels_from_two_targets_stay_distinct():
    """Two dev-VM embedded exporters with empty topology labels must not
    fold into one chimera row — the target index keys them apart."""
    text = 'accelerator_up{chip="0",worker="",slice="",accel_type="tpu-v5e"} 1\n'
    frame = top.build_frame([text, text], [], ats=[0.0, 0.0])
    assert len(frame.rows) == 2
    assert {k[0] for k in frame.rows} == {0, 1}


def test_validate_accepts_embedded_exposition():
    """The embedded exporter's own output (incl. the workload step
    histogram) must pass the schema validator it ships next to."""
    from kube_gpu_stats_tpu import validate

    text = rendered() + (
        'accelerator_workload_step_duration_seconds_bucket{le="0.001"} 2\n'
        'accelerator_workload_step_duration_seconds_bucket{le="+Inf"} 3\n'
        'accelerator_workload_step_duration_seconds_sum 1.5\n'
        'accelerator_workload_step_duration_seconds_count 3\n'
    )
    assert validate.check(text) == []


def test_main_once_against_prom_file(tmp_path, capsys):
    prom = tmp_path / "snap.prom"
    prom.write_text(rendered())
    assert top.main([str(prom), "--once"]) == 0
    out = capsys.readouterr().out
    assert "0/w0" in out and "DUTY%" in out


def test_main_once_json_against_http(tmp_path, capsys):
    from kube_gpu_stats_tpu.exposition import MetricsServer

    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, deadline=5.0)
    loop.tick()
    loop.stop()
    server = MetricsServer(reg, host="127.0.0.1", port=0)
    server.start()
    try:
        rc = top.main([f"http://127.0.0.1:{server.port}/metrics",
                       "--once", "--json"])
    finally:
        server.stop()
    assert rc == 0
    parsed = json.loads(capsys.readouterr().out)
    assert len(parsed["chips"]) == 1


def test_main_once_unreachable_target_exits_2(capsys):
    assert top.main(["http://127.0.0.1:1/metrics", "--once"]) == 2
    assert "!" in capsys.readouterr().err


def test_cli_dispatches_top(tmp_path, capsys):
    from kube_gpu_stats_tpu.cli import main

    prom = tmp_path / "snap.prom"
    prom.write_text(rendered())
    assert main(["top", str(prom), "--once"]) == 0
    assert "CHIP" in capsys.readouterr().out


def test_live_rates_against_ticking_exporter():
    """Integration: two snapshot_frame() rounds against a live HTTP
    exporter whose workload counter advances between them produce a
    positive steps/s — the whole fetch->parse->key->rate pipeline."""
    import time

    from kube_gpu_stats_tpu.collectors import Sample
    from kube_gpu_stats_tpu.exposition import MetricsServer

    class SteppingCollector(MockCollector):
        steps = 0.0

        def sample(self, device):
            s = super().sample(device)
            values = dict(s.values)
            values[schema.WORKLOAD_STEPS.name] = SteppingCollector.steps
            return Sample(device=s.device, values=values,
                          ici_counters=s.ici_counters,
                          collective_ops=s.collective_ops)

    reg = Registry()
    loop = PollLoop(SteppingCollector(num_devices=1), reg, deadline=5.0)
    server = MetricsServer(reg, host="127.0.0.1", port=0)
    server.start()
    url = f"http://127.0.0.1:{server.port}/metrics"
    try:
        loop.tick()
        first = top.snapshot_frame([url], None)
        SteppingCollector.steps = 500.0
        time.sleep(0.05)
        loop.tick()
        second = top.snapshot_frame([url], first)
        (row,) = second.rows.values()
        assert row.steps_per_s is not None and row.steps_per_s > 0
    finally:
        loop.stop()
        server.stop()


def test_transient_fetch_failure_does_not_shift_row_identity():
    """Review finding: rows were keyed by position in the SUCCESSFUL
    fetch list, so one target timing out shifted every later target onto
    a different identity and cross-matched their rate windows. Keys now
    carry the target name."""
    from kube_gpu_stats_tpu.exposition import MetricsServer

    regs = []
    servers = []
    for steps in (1000.0, 50.0):
        reg = Registry()
        builder_loop = PollLoop(MockCollector(num_devices=1), reg,
                                deadline=5.0)
        builder_loop.tick()
        builder_loop.stop()
        regs.append(reg)
        srv = MetricsServer(reg, host="127.0.0.1", port=0)
        srv.start()
        servers.append(srv)
    url_a = f"http://127.0.0.1:{servers[0].port}/metrics"
    url_b = f"http://127.0.0.1:{servers[1].port}/metrics"
    try:
        first = top.snapshot_frame([url_a, url_b], None)
        assert len(first.rows) == 2
        # Target A "goes down": its frame-2 fetch fails.
        servers[0].stop()
        second = top.snapshot_frame([url_a, url_b], first)
        assert any(url_a in e for e in second.errors)
        (key_b,) = second.rows
        assert key_b[0] == url_b  # B keeps ITS identity, not A's slot
        # And B's previous row is matched by name for rates.
        assert key_b in first.rows
    finally:
        for srv in servers[1:]:
            srv.stop()


def test_top_reads_schema_families_it_claims():
    """The column map must reference real schema names only."""
    known = {m.name for m in schema.ALL_METRICS}
    for name in list(top._GAUGES.values()) + list(top._COUNTERS.values()):
        assert name in known


def test_hub_rollup_footer_in_table():
    # Pointing top at a kube-tpu-stats hub: slice_* rollups fold into a
    # footer line (workers, down targets, straggler ratio).
    text = (
        'accelerator_up{chip="0",worker="0",slice="v5p-16"} 1\n'
        'slice_workers{slice="v5p-16"} 3\n'
        'slice_workers_expected 4\n'
        'slice_target_up{target="http://a:9400/metrics"} 1\n'
        'slice_target_up{target="http://b:9400/metrics"} 0\n'
        'slice_straggler_ratio{slice="v5p-16"} 0.75\n'
        'slice_duplicate_series 0\n'
    )
    frame = top.build_frame([text], [], ats=[0.0])
    out = top.render_table(frame)
    assert "hub[v5p-16]:" in out
    assert "workers 3/4" in out
    assert "targets down 1" in out
    assert "straggler ratio 0.75" in out
    assert "DUPLICATE" not in out  # zero duplicates stays quiet


def test_no_rollup_footer_for_plain_exporters():
    frame = top.build_frame([rendered()], [], ats=[0.0])
    assert "hub[" not in top.render_table(frame)


def test_hub_footer_survives_full_outage():
    # A hub with every target down exports no slice-labeled rollups, but
    # the footer must still surface the outage.
    text = (
        'slice_workers_expected 4\n'
        'slice_target_up{target="http://a:9400/metrics"} 0\n'
        'slice_target_up{target="http://b:9400/metrics"} 0\n'
    )
    out = top.render_table(top.build_frame([text], [], ats=[0.0]))
    assert "workers 0/4" in out
    assert "targets down 2" in out


def test_hub_footer_two_hubs_do_not_mix():
    hub_a = (
        'slice_workers{slice="a"} 2\n'
        'slice_workers_expected 2\n'
        'slice_duplicate_series 3\n'
    )
    hub_b = (
        'slice_workers{slice="b"} 8\n'
        'slice_workers_expected 8\n'
        'slice_duplicate_series 0\n'
    )
    out = top.render_table(top.build_frame([hub_a, hub_b], [],
                                           ats=[0.0, 0.0]))
    assert "hub[a]:  workers 2/2  DUPLICATE CHIP IDS 3" in out
    assert "hub[b]:  workers 8/8" in out
    assert "hub[b]:  workers 8/8  DUPLICATE" not in out


def test_hub_footer_multi_slice_expected_not_paired_per_slice():
    # slice_workers_expected is hub config, not a per-slice fact: a hub
    # serving two slices must not claim each slice is short of the total.
    text = (
        'slice_workers{slice="a"} 2\n'
        'slice_workers{slice="b"} 6\n'
        'slice_workers_expected 8\n'
    )
    out = top.render_table(top.build_frame([text], [], ats=[0.0]))
    assert "hub[a]:  workers 2\n" in out + "\n"
    assert "hub[b]:  workers 6\n" in out + "\n"
    assert "hub:  workers 8/8" in out
    assert "2/8" not in out and "6/8" not in out


def test_hub_footer_names_hub_when_several_present():
    hub_a = 'slice_workers{slice="a"} 2\n'
    hub_b = 'slice_workers{slice="a"} 4\n'
    out = top.render_table(top.build_frame(
        [hub_a, hub_b], [], ats=[0.0, 0.0],
        targets=["http://hub-a:9401/metrics", "http://hub-b:9401/metrics"]))
    assert "workers 2  (http://hub-a:9401/metrics)" in out
    assert "workers 4  (http://hub-b:9401/metrics)" in out


def test_top_authenticates_against_hardened_exporter(tmp_path, capsys):
    import hashlib

    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.registry import Registry
    from kube_gpu_stats_tpu.collectors.mock import MockCollector
    from kube_gpu_stats_tpu.poll import PollLoop

    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, deadline=5.0)
    loop.tick()
    server = MetricsServer(
        reg, host="127.0.0.1", port=0, auth_username="viewer",
        auth_password_sha256=hashlib.sha256(b"watchpass").hexdigest())
    server.start()
    url = f"http://127.0.0.1:{server.port}/metrics"
    pw = tmp_path / "pw"
    pw.write_text("watchpass\n")
    try:
        rc = top.main([url, "--once", "--json", "--auth-username", "viewer",
                       "--auth-password-file", str(pw)])
        assert rc == 0
        frame = json.loads(capsys.readouterr().out)
        assert len(frame["chips"]) == 1
        # Without credentials the same target is a 401 error, exit 2.
        rc = top.main([url, "--once", "--json"])
        captured = capsys.readouterr()
        assert rc == 2 and "401" in captured.err
    finally:
        loop.stop()
        server.stop()


def test_top_targets_dns(tmp_path, capsys):
    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.registry import Registry
    from kube_gpu_stats_tpu.collectors.mock import MockCollector
    from kube_gpu_stats_tpu.poll import PollLoop

    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=2), reg, deadline=5.0)
    loop.tick()
    server = MetricsServer(reg, host="127.0.0.1", port=0)
    server.start()
    try:
        rc = top.main(["--targets-dns", f"localhost:{server.port}",
                       "--once", "--json"])
        assert rc == 0
        frame = json.loads(capsys.readouterr().out)
        assert len(frame["chips"]) == 2
        import pytest
        with pytest.raises(SystemExit):  # positional + dns is ambiguous
            top.main(["http://x/metrics", "--targets-dns", "h:1", "--once"])
        capsys.readouterr()
    finally:
        loop.stop()
        server.stop()


def test_top_dns_unresolvable_once_exits_2(capsys, monkeypatch):
    from kube_gpu_stats_tpu import hub as hub_mod

    def boom(endpoint, scheme="http", path="/metrics"):
        raise OSError("dns down")

    monkeypatch.setattr(hub_mod, "resolve_dns_targets", boom)
    rc = top.main(["--targets-dns", "svc:9400", "--once"])
    assert rc == 2
    assert "dns" in capsys.readouterr().err
