"""Shared WAL discipline (wal.py, ISSUE 13 satellite): atomic JSON
state with dual-candidate crash recovery, and the bounded CRC-framed
SegmentRing — torn-write/crash matrix for the code every checkpoint
(energy, ingest sessions, spill queue, exporter shards) now rides."""

import json
import os

import pytest

from kube_gpu_stats_tpu import wal


# -- atomic JSON state -------------------------------------------------------

def test_write_then_load_roundtrip(tmp_path):
    path = str(tmp_path / "state.json")
    assert wal.write_state(path, {"version": 1, "seq": 3, "x": [1, 2]})
    assert wal.load_newest(path, 1) == {"version": 1, "seq": 3, "x": [1, 2]}
    assert not os.path.exists(path + ".wal")  # renamed, not left behind


def test_crash_between_fsync_and_rename_recovers_from_wal(tmp_path):
    """The recovery rule all three checkpoint users share: a newer
    fsynced .wal stranded behind an older main must win."""
    path = str(tmp_path / "state.json")
    wal.write_state(path, {"version": 1, "seq": 5, "value": "old"})
    # Simulate the crash: the NEXT write reached the .wal (fsynced) but
    # died before os.replace.
    (tmp_path / "state.json.wal").write_text(
        json.dumps({"version": 1, "seq": 6, "value": "new"}))
    assert wal.load_newest(path, 1)["value"] == "new"
    assert wal.newest_seq(path, 1) == 6


def test_older_wal_never_shadows_newer_main(tmp_path):
    path = str(tmp_path / "state.json")
    (tmp_path / "state.json.wal").write_text(
        json.dumps({"version": 1, "seq": 2, "value": "stale"}))
    wal.write_state(path, {"version": 1, "seq": 9, "value": "current"})
    # write_state renamed the fresh wal over main; recreate a stale one.
    (tmp_path / "state.json.wal").write_text(
        json.dumps({"version": 1, "seq": 2, "value": "stale"}))
    assert wal.load_newest(path, 1)["value"] == "current"
    assert wal.newest_seq(path, 1) == 9


@pytest.mark.parametrize("garbage", [b"", b"{", b"[1,2]", b"\x00\xff" * 40,
                                     b'{"version": 99, "seq": 1}'])
def test_garbage_and_wrong_version_ignored(tmp_path, garbage):
    path = str(tmp_path / "state.json")
    (tmp_path / "state.json").write_bytes(garbage)
    assert wal.load_newest(path, 1) is None
    assert wal.newest_seq(path, 1) == 0


def test_torn_main_with_good_wal_recovers(tmp_path):
    """A crash mid-rename can leave a truncated main; the .wal copy is
    the fsynced truth."""
    path = str(tmp_path / "state.json")
    (tmp_path / "state.json").write_text('{"version": 1, "se')  # torn
    (tmp_path / "state.json.wal").write_text(
        json.dumps({"version": 1, "seq": 4, "value": "ok"}))
    assert wal.load_newest(path, 1)["value"] == "ok"


def test_unwritable_path_returns_false_not_raise(tmp_path):
    target = tmp_path / "dir"
    target.mkdir()
    # Writing over a directory fails the rename; must be a False, not
    # an exception on the caller's (poll/refresh) thread.
    assert not wal.write_state(str(target), {"version": 1, "seq": 1})


# -- SegmentRing -------------------------------------------------------------

def ring(tmp_path, **kw):
    kw.setdefault("max_bytes", 1 << 20)
    kw.setdefault("segment_bytes", 256)
    kw.setdefault("fsync", False)  # tests don't need the disk flush
    return wal.SegmentRing(str(tmp_path / "ring"), **kw)


def test_ring_fifo_roundtrip(tmp_path):
    r = ring(tmp_path)
    for i in range(10):
        assert r.append(float(i), f"payload-{i}".encode()) == 0
    assert r.records_pending() == 10
    assert r.oldest_ts() == 0.0
    out = []
    while (record := r.peek()) is not None:
        out.append(record)
        r.commit()
    assert [p for _t, p in out] == [f"payload-{i}".encode()
                                    for i in range(10)]
    assert r.records_pending() == 0


def test_ring_survives_restart(tmp_path):
    r = ring(tmp_path)
    for i in range(20):
        r.append(float(i), b"x" * 40)
    # Consume 5, persist the cursor, "crash".
    for _ in range(5):
        r.peek()
        r.commit()
    assert r.save_cursor(force=True)
    r.close()
    r2 = ring(tmp_path)
    assert r2.records_pending() == 15
    assert r2.oldest_ts() == 5.0  # resumes AFTER the consumed prefix
    assert r2.torn_records == 0


def test_ring_unsaved_cursor_resends_at_least_once(tmp_path):
    """A crash between commit and save_cursor re-sends the window — the
    at-least-once half of the contract (never lossy)."""
    r = ring(tmp_path)
    for i in range(4):
        r.append(float(i), b"p%d" % i)
    r.save_cursor(force=True)
    r.peek(), r.commit()  # consumed but cursor not saved
    del r  # crash: no close(), no save
    r2 = ring(tmp_path)
    assert r2.records_pending() == 4  # record 0 comes back, not lost


def test_ring_torn_tail_truncated_not_fatal(tmp_path):
    r = ring(tmp_path)
    for i in range(6):
        r.append(float(i), b"payload-%d" % i)
    r.close()
    # Tear the newest segment mid-record (crash during append).
    segs = sorted((tmp_path / "ring").glob("*.seg"))
    data = segs[-1].read_bytes()
    segs[-1].write_bytes(data[:-3])
    r2 = ring(tmp_path)
    assert r2.torn_records >= 1
    drained = []
    while (record := r2.peek()) is not None:
        drained.append(record[1])
        r2.commit()
    # Everything before the torn tail is CRC-proven intact.
    assert drained == [b"payload-%d" % i for i in range(5)]


def test_ring_orphaned_rewrite_temp_cleaned_on_recovery(tmp_path):
    """A crash between a torn-tail rewrite and its os.replace leaves a
    '<seg>.seg.wal' temp; recovery must delete it (it matches no .seg
    glob, so nothing else ever would) and recover the real segments."""
    r = ring(tmp_path)
    for i in range(4):
        r.append(float(i), b"payload-%d" % i)
    r.close()
    orphan = tmp_path / "ring" / "wal-00000001.seg.wal"
    orphan.write_bytes(b"half-written rewrite temp")
    r2 = ring(tmp_path)
    assert not orphan.exists()
    assert r2.records_pending() == 4
    assert r2.torn_records == 0
    # And the torn bytes never come back on the NEXT recovery.
    r2.close()
    r3 = ring(tmp_path)
    assert r3.torn_records == 0


def test_ring_corrupt_middle_record_stops_at_crc(tmp_path):
    r = ring(tmp_path, segment_bytes=1 << 20)  # one segment
    for i in range(6):
        r.append(float(i), b"payload-%d" % i)
    r.close()
    (seg,) = sorted((tmp_path / "ring").glob("*.seg"))
    data = bytearray(seg.read_bytes())
    data[data.index(b"payload-3")] ^= 0xFF  # corrupt record 3's payload
    seg.write_bytes(bytes(data))
    r2 = ring(tmp_path)
    assert r2.torn_records >= 1
    # The proven prefix survives; the suffix after the bad CRC is gone.
    assert 0 < r2.records_pending() < 6


def test_ring_bounded_evicts_oldest_and_reports(tmp_path):
    r = ring(tmp_path, max_bytes=400, segment_bytes=100)
    evicted = 0
    for i in range(50):
        evicted += r.append(float(i), b"z" * 60)
    assert evicted > 0  # the cap engaged
    assert r.evicted_records == evicted
    assert r.bytes_pending() <= 400 + 100  # bound ~ max + one segment
    # Oldest-first: the survivors are the newest records.
    first = r.peek()
    assert first is not None and first[0] > 0.0
    # Conservation: everything appended is either pending or evicted.
    assert r.records_pending() + evicted == 50


def test_ring_eviction_survives_restart(tmp_path):
    r = ring(tmp_path, max_bytes=300, segment_bytes=100)
    for i in range(30):
        r.append(float(i), b"y" * 50)
    pending = r.records_pending()
    oldest = r.oldest_ts()
    r.close()
    r2 = ring(tmp_path, max_bytes=300, segment_bytes=100)
    assert r2.records_pending() == pending
    assert r2.oldest_ts() == oldest


def test_ring_empty_dir_and_empty_ring(tmp_path):
    r = ring(tmp_path)
    assert r.peek() is None
    assert r.oldest_ts() is None
    assert r.records_pending() == 0
    r.commit()  # commit on empty must be a no-op, not a raise
    status = r.status()
    assert status["records"] == 0 and status["torn_total"] == 0


def test_ring_status_shape(tmp_path):
    r = ring(tmp_path)
    r.append(1.0, b"abc")
    status = r.status()
    for key in ("records", "bytes", "segments", "appended_total",
                "evicted_total", "torn_total", "max_bytes"):
        assert key in status
    assert status["records"] == 1 and status["bytes"] > 3


# -- ported users still behave (energy + ingest on wal.py) -------------------

def test_energy_checkpoint_still_recovers_newer_wal(tmp_path):
    """The energy accountant's monotone-across-restarts guarantee must
    survive the port onto wal.py (the PR 7 review-fix scenario)."""
    from kube_gpu_stats_tpu.energy import EnergyAccountant

    path = str(tmp_path / "energy.json")
    acct = EnergyAccountant(checkpoint_path=path, checkpoint_interval=0.0)
    acct.observe("dev0", "pod-a", "ml", 1.0, 100.0)
    acct.observe("dev0", "pod-a", "ml", 2.0, 100.0)
    assert acct.checkpoint(force=True)
    # Newer fsynced .wal stranded by a crash before rename.
    state = json.loads((tmp_path / "energy.json").read_text())
    state["seq"] += 1
    state["per_pod"] = [["pod-a", "ml", 999.0]]
    (tmp_path / "energy.json.wal").write_text(json.dumps(state))
    acct2 = EnergyAccountant(checkpoint_path=path)
    assert acct2.checkpoint_loaded
    assert acct2.digest()["per_pod"][0][2] == 999.0


def test_ingest_checkpoint_epoch_resumes_past_both_candidates(tmp_path):
    from kube_gpu_stats_tpu.delta import DeltaIngest

    path = str(tmp_path / "ingest.json")
    ingest = DeltaIngest(checkpoint_path=path, checkpoint_interval=0.0)
    from kube_gpu_stats_tpu.delta import decode_frame, encode_full

    ingest.apply(decode_frame(encode_full("src", 1, 1, "m 1\n")), 10)
    assert ingest.checkpoint(force=True)
    main_seq = json.loads((tmp_path / "ingest.json").read_text())["seq"]
    # Strand a higher-seq .wal, then restart: the next write epoch must
    # out-rank BOTH.
    state = json.loads((tmp_path / "ingest.json").read_text())
    state["seq"] = main_seq + 5
    (tmp_path / "ingest.json.wal").write_text(json.dumps(state))
    ingest2 = DeltaIngest(checkpoint_path=path, checkpoint_interval=0.0)
    assert ingest2.checkpoint_loaded
    ingest2.apply(decode_frame(encode_full("src2", 1, 1, "m 2\n")), 10)
    assert ingest2.checkpoint(force=True)
    assert json.loads(
        (tmp_path / "ingest.json").read_text())["seq"] > main_seq + 5


# -- cross-version matrix (ISSUE 14): tolerate the past, quarantine the
# -- future, never corrupt either --------------------------------------------

def test_write_state_refuses_unstamped_dict(tmp_path):
    """Every wal.py writer must version its format — the runtime half
    of the check_wal_versions lint."""
    with pytest.raises(ValueError, match="version"):
        wal.write_state(str(tmp_path / "state.json"), {"seq": 1})


def test_read_state_loads_older_format_with_defaults(tmp_path):
    """An older build wrote fewer keys under a lower stamp: the reader
    accepts any version up to its own."""
    path = tmp_path / "state.json"
    path.write_text(json.dumps({"version": 1, "seq": 4}))
    state = wal.read_state(str(path), 3)
    assert state == {"version": 1, "seq": 4}
    assert path.exists()  # loaded, never touched


def test_read_state_quarantines_future_major_byte_identical(tmp_path):
    """Refuse-don't-corrupt: a future-major checkpoint moves aside
    INTACT (a downgrade can move it back and replay it), the reader
    starts from empty state, and the quarantine is counted."""
    wal.reset_quarantine_stats()
    path = tmp_path / "state.json"
    raw = json.dumps({"version": 7, "seq": 9,
                      "field_from_the_future": [1, 2]}).encode()
    path.write_bytes(raw)
    assert wal.read_state(str(path), 2, label="teststore") is None
    assert not path.exists()  # never truncated IN PLACE...
    aside = tmp_path / "state.json.skew-v7"
    assert aside.read_bytes() == raw  # ...parked byte-identical
    assert wal.quarantine_counts() == {"teststore": 1}
    events = wal.quarantine_events()
    assert events and events[-1]["version"] == 7
    wal.reset_quarantine_stats()


def test_read_state_quarantine_never_overwrites_prior_park(tmp_path):
    """Two rollout accidents in a row must keep BOTH parked files."""
    wal.reset_quarantine_stats()
    path = tmp_path / "state.json"
    for marker in ("first", "second"):
        path.write_text(json.dumps({"version": 9, "m": marker}))
        assert wal.read_state(str(path), 1) is None
    parked = sorted(p.name for p in tmp_path.glob("state.json.skew-v9*"))
    assert len(parked) == 2
    wal.reset_quarantine_stats()


def test_read_state_nonint_version_is_garbage_not_skew(tmp_path):
    """A bogus stamp is a corrupt file, not a future build: ignored in
    place, never quarantined."""
    wal.reset_quarantine_stats()
    path = tmp_path / "state.json"
    for stamp in ("2", None, True, -1, 0):
        path.write_text(json.dumps({"version": stamp}))
        assert wal.read_state(str(path), 2) is None
        assert path.exists()
    assert wal.quarantine_counts() == {}


def test_ring_headerless_legacy_segment_reads_as_v1(tmp_path):
    """A pre-versioning build's segment (no KTSG header) must keep
    reading — a ring legally holds BOTH mid-rollout."""
    import struct
    import zlib as zlib_mod

    directory = tmp_path / "ring"
    directory.mkdir()
    rec = struct.Struct("<dII")
    payload = b"legacy-record"
    with open(directory / "wal-00000001.seg", "wb") as handle:
        handle.write(rec.pack(1.0, len(payload),
                              zlib_mod.crc32(payload)))
        handle.write(payload)
    r = wal.SegmentRing(str(directory), max_bytes=1 << 20,
                        segment_bytes=256, fsync=False,
                        format_version=1)
    assert r.records_pending() == 1
    assert r.peek() == (1.0, payload)
    status = r.status()
    assert status["legacy_segments"] == 1
    assert status["skew_segments_total"] == 0
    # New appends land in a NEW, headered segment; the mixed ring
    # keeps draining oldest-first across the format boundary.
    r.append(2.0, b"new-record")
    r.commit()
    assert r.peek() == (2.0, b"new-record")
    assert r.status()["legacy_segments"] < r.status()["segments"]


def test_ring_future_format_segment_quarantined_whole(tmp_path):
    """A segment stamped with a NEWER payload format (downgrade onto a
    newer build's ring) parks intact as <seg>.skew; recovery continues
    with the rest of the ring."""
    wal.reset_quarantine_stats()
    r = ring(tmp_path, format_version=1)
    r.append(1.0, b"own-record")
    r.close()
    directory = tmp_path / "ring"
    import struct
    import zlib as zlib_mod

    rec = struct.Struct("<dII")
    payload = b"from-the-future"
    future = directory / "wal-00000009.seg"
    raw = (b"KTSG" + bytes((1, 5))
           + rec.pack(2.0, len(payload), zlib_mod.crc32(payload))
           + payload)
    future.write_bytes(raw)
    r2 = ring(tmp_path, format_version=1)
    assert r2.skew_segments == 1
    assert (directory / "wal-00000009.seg.skew").read_bytes() == raw
    assert not future.exists()
    assert r2.records_pending() == 1  # the rest of the ring survives
    assert r2.peek() == (1.0, b"own-record")
    assert r2.status()["skew_segments_total"] == 1
    assert wal.quarantine_counts().get("segment-ring") == 1
    wal.reset_quarantine_stats()


def test_ring_future_container_version_also_quarantined(tmp_path):
    directory = tmp_path / "ring"
    directory.mkdir()
    import struct
    import zlib as zlib_mod

    rec = struct.Struct("<dII")
    payload = b"p"
    (directory / "wal-00000001.seg").write_bytes(
        b"KTSG" + bytes((9, 1))
        + rec.pack(1.0, len(payload), zlib_mod.crc32(payload)) + payload)
    r = wal.SegmentRing(str(directory), max_bytes=1 << 20, fsync=False,
                        format_version=1)
    assert r.skew_segments == 1
    assert r.records_pending() == 0
    wal.reset_quarantine_stats()


def test_ring_torn_legacy_segment_rewritten_headerless(tmp_path):
    """Recovery of a torn LEGACY segment must rewrite it WITHOUT a
    header: stamping it would turn a later downgrade's recovery into a
    full-segment truncation (the old reader sees header bytes as a
    torn first record)."""
    import struct
    import zlib as zlib_mod

    directory = tmp_path / "ring"
    directory.mkdir()
    rec = struct.Struct("<dII")
    payload = b"intact-legacy"
    with open(directory / "wal-00000001.seg", "wb") as handle:
        handle.write(rec.pack(1.0, len(payload),
                              zlib_mod.crc32(payload)))
        handle.write(payload)
        handle.write(b"\x01\x02\x03")  # the torn tail
    r = wal.SegmentRing(str(directory), max_bytes=1 << 20, fsync=False,
                        format_version=1)
    assert r.torn_records == 1
    assert r.peek() == (1.0, payload)
    r.close()
    rewritten = (directory / "wal-00000001.seg").read_bytes()
    assert not rewritten.startswith(b"KTSG")


def test_ring_new_segments_stamp_the_header(tmp_path):
    r = ring(tmp_path, format_version=3)
    r.append(1.0, b"abc")
    r.close()
    segs = sorted((tmp_path / "ring").glob("*.seg"))
    data = segs[-1].read_bytes()
    assert data[:4] == b"KTSG"
    assert data[4] == wal.SEGMENT_CONTAINER_VERSION
    assert data[5] == 3
    # And the same build reads its own stamp back.
    r2 = ring(tmp_path, format_version=3)
    assert r2.records_pending() == 1
    assert r2.skew_segments == 0


def test_ring_cursor_with_pruned_keys_defaults_not_keyerror(tmp_path):
    """An older build's cursor missing keys must default-and-warn on
    the restart path (ISSUE 14 satellite), clamped into reality."""
    r = ring(tmp_path)
    for i in range(3):
        r.append(float(i), b"x")
    r.save_cursor(force=True)
    r.close()
    cursor_path = tmp_path / "ring" / "wal-cursor.json"
    state = json.loads(cursor_path.read_text())
    state.pop("record", None)
    state.pop("seq", None)
    cursor_path.write_text(json.dumps(state))
    r2 = ring(tmp_path)  # must not raise
    assert r2.records_pending() == 3  # defaulted to the oldest record


def test_ring_second_quarantine_of_same_seq_keeps_both(tmp_path):
    """A drained ring restarts its seq numbering, so two downgrade
    accidents can park the SAME segment name — the second must land
    beside the first (.skew.1), never over it."""
    import struct
    import zlib as zlib_mod

    wal.reset_quarantine_stats()
    directory = tmp_path / "ring"
    directory.mkdir()
    rec = struct.Struct("<dII")

    def future_seg(marker: bytes) -> bytes:
        return (b"KTSG" + bytes((1, 5))
                + rec.pack(1.0, len(marker), zlib_mod.crc32(marker))
                + marker)

    first, second = future_seg(b"first"), future_seg(b"second")
    (directory / "wal-00000001.seg").write_bytes(first)
    r = wal.SegmentRing(str(directory), max_bytes=1 << 20, fsync=False,
                        format_version=1)
    r.close()
    (directory / "wal-00000001.seg").write_bytes(second)
    r2 = wal.SegmentRing(str(directory), max_bytes=1 << 20, fsync=False,
                         format_version=1)
    r2.close()
    parked = sorted(p.name for p in directory.glob("*.skew*"))
    assert len(parked) == 2
    contents = {p.read_bytes() for p in directory.glob("*.skew*")}
    assert contents == {first, second}  # both intact, neither clobbered
    wal.reset_quarantine_stats()
