"""accelerator_process_open: procfs fd-scan correctness against a fixture
/proc tree, cardinality bounding, watcher last-good semantics, and the
poll-loop emission path."""

import os

from kube_gpu_stats_tpu import procopen, schema
from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry


def make_proc(root, pids):
    """pids: {pid: (comm, [fd targets])}."""
    for pid, (comm, targets) in pids.items():
        fd_dir = root / str(pid) / "fd"
        fd_dir.mkdir(parents=True)
        (root / str(pid) / "comm").write_text(comm + "\n")
        for i, target in enumerate(targets):
            os.symlink(target, fd_dir / str(i))
    # Non-pid entries a real /proc has; the scanner must skip them.
    (root / "self").mkdir(exist_ok=True)
    (root / "meminfo").write_text("MemTotal: 1 kB\n")


def test_scan_maps_holders_to_devices(tmp_path):
    make_proc(tmp_path, {
        101: ("python3", ["/dev/accel0", "/dev/null", "/dev/accel1"]),
        102: ("libtpu_worker", ["/dev/accel1"]),
        103: ("bash", ["/dev/pts/0"]),
    })
    result = procopen.scan(str(tmp_path), ["/dev/accel0", "/dev/accel1"])
    assert result["/dev/accel0"] == [("101", "python3", "", 1.0)]
    assert result["/dev/accel1"] == [("101", "python3", "", 1.0),
                                     ("102", "libtpu_worker", "", 1.0)]


def test_scan_survives_unreadable_and_vanishing_entries(tmp_path):
    make_proc(tmp_path, {201: ("worker", ["/dev/accel0"])})
    # A pid dir with no fd dir (process exited mid-scan).
    (tmp_path / "202").mkdir()
    # A dangling fd symlink target is still a string match candidate.
    result = procopen.scan(str(tmp_path), ["/dev/accel0"])
    assert result["/dev/accel0"] == [("201", "worker", "", 1.0)]
    # Missing /proc entirely: empty map for every device, no raise.
    assert procopen.scan(str(tmp_path / "nope"), ["/dev/accel0"]) == {
        "/dev/accel0": []
    }
    assert procopen.scan(str(tmp_path), []) == {}


def test_scan_caps_holder_cardinality_with_visible_overflow(tmp_path):
    """Round-1 verdict item 7: 100 fake holders must yield a bounded,
    stable series set — the cap's worth of real holders (lowest pids,
    deterministic) plus ONE overflow series carrying the folded count."""
    make_proc(tmp_path, {
        1000 + i: (f"w{i}", ["/dev/accel0"]) for i in range(100)
    })
    result = procopen.scan(str(tmp_path), ["/dev/accel0"])
    holders = result["/dev/accel0"]
    assert len(holders) == procopen.MAX_HOLDERS_PER_DEVICE + 1
    real, overflow = holders[:-1], holders[-1]
    assert real == [(str(1000 + i), f"w{i}", "", 1.0)
                    for i in range(procopen.MAX_HOLDERS_PER_DEVICE)]
    assert overflow == ("", procopen.OVERFLOW_COMM, "",
                        float(100 - procopen.MAX_HOLDERS_PER_DEVICE))
    # Identity is stable scan-over-scan for a fixed population.
    assert procopen.scan(str(tmp_path), ["/dev/accel0"]) == result
    # A custom cap bounds the same way.
    capped = procopen.scan(str(tmp_path), ["/dev/accel0"], max_holders=5)
    assert len(capped["/dev/accel0"]) == 6
    assert capped["/dev/accel0"][-1] == ("", "_overflow", "", 95.0)


def test_missing_comm_yields_empty_string(tmp_path):
    make_proc(tmp_path, {301: ("x", ["/dev/accel0"])})
    (tmp_path / "301" / "comm").unlink()
    result = procopen.scan(str(tmp_path), ["/dev/accel0"])
    assert result["/dev/accel0"] == [("301", "", "", 1.0)]


def test_watcher_keeps_last_good_map(tmp_path):
    make_proc(tmp_path, {401: ("train", ["/dev/accel0"])})
    watcher = procopen.DeviceProcessWatcher(
        lambda: ["/dev/accel0"], proc_root=str(tmp_path))
    watcher.refresh_once()
    assert watcher.lookup("/dev/accel0") == [("401", "train", "", 1.0)]

    def boom():
        raise RuntimeError("discover broke")

    watcher._paths_fn = boom
    watcher.refresh_once()  # must not raise; keeps the last map
    assert watcher.lookup("/dev/accel0") == [("401", "train", "", 1.0)]
    assert watcher.lookup("/dev/other") == []


def test_poll_loop_emits_process_open_series(tmp_path):
    registry = Registry()
    openers = {"/dev/accel0": [("7", "jax_worker", "", 1.0)], "/dev/accel1": []}
    loop = PollLoop(
        MockCollector(num_devices=2), registry, deadline=5.0,
        process_openers=lambda path: openers.get(path, []),
    )
    loop.tick()
    loop.stop()
    series = [s for s in registry.snapshot().series
              if s.spec.name == schema.PROCESS_OPEN.name]
    assert len(series) == 1
    labels = dict(series[0].labels)
    assert labels["pid"] == "7"
    assert labels["comm"] == "jax_worker"
    assert labels["chip"] == "0"
    assert series[0].value == 1.0
    # Full base label set rides along (exposition contract).
    assert set(schema.ALL_BASE_LABELS) <= set(labels)


def test_daemon_wires_watcher_only_when_enabled(tmp_path):
    from kube_gpu_stats_tpu.config import Config
    from kube_gpu_stats_tpu.daemon import Daemon

    on = Daemon(Config(backend="mock", attribution="off",
                       proc_root=str(tmp_path), listen_port=0))
    try:
        assert on.procwatch is not None
    finally:
        on.collector.close()
    off = Daemon(Config(backend="mock", attribution="off",
                        device_processes="off", listen_port=0))
    try:
        assert off.procwatch is None
    finally:
        off.collector.close()


def test_poll_loop_emits_overflow_series(tmp_path):
    registry = Registry()
    openers = {"/dev/accel0": [("7", "jax_worker", "", 1.0),
                               ("", procopen.OVERFLOW_COMM, "", 68.0)]}
    loop = PollLoop(
        MockCollector(num_devices=1), registry, deadline=5.0,
        process_openers=lambda path: openers.get(path, []),
    )
    loop.tick()
    loop.stop()
    series = {dict(s.labels)["comm"]: s for s in registry.snapshot().series
              if s.spec.name == schema.PROCESS_OPEN.name}
    assert series["jax_worker"].value == 1.0
    overflow = series[procopen.OVERFLOW_COMM]
    assert overflow.value == 68.0
    assert dict(overflow.labels)["pid"] == ""


def test_pod_uid_from_cgroup_both_drivers(tmp_path):
    """The pod UID lands in the holder entry from either kubelet cgroup
    layout; non-pod processes get an empty string."""
    make_proc(tmp_path, {
        501: ("systemd-style", ["/dev/accel0"]),
        502: ("cgroupfs-style", ["/dev/accel0"]),
        503: ("plain-vm", ["/dev/accel0"]),
    })
    (tmp_path / "501" / "cgroup").write_text(
        "0::/kubepods.slice/kubepods-burstable.slice/"
        "kubepods-burstable-pod0a1b2c3d_e4f5_6789_abcd_ef0123456789.slice/"
        "cri-containerd-deadbeef.scope\n")
    (tmp_path / "502" / "cgroup").write_text(
        "11:memory:/kubepods/besteffort/"
        "pod11223344-5566-7788-99aa-bbccddeeff00/deadbeef\n")
    (tmp_path / "503" / "cgroup").write_text("0::/user.slice\n")
    result = procopen.scan(str(tmp_path), ["/dev/accel0"])
    by_pid = {h[0]: h[2] for h in result["/dev/accel0"]}
    assert by_pid["501"] == "0a1b2c3d-e4f5-6789-abcd-ef0123456789"
    assert by_pid["502"] == "11223344-5566-7788-99aa-bbccddeeff00"
    assert by_pid["503"] == ""


def test_pod_uid_label_reaches_exposition(tmp_path):
    registry = Registry()
    openers = {"/dev/accel0": [
        ("7", "jax_worker", "0a1b2c3d-e4f5-6789-abcd-ef0123456789", 1.0)]}
    loop = PollLoop(
        MockCollector(num_devices=1), registry, deadline=5.0,
        process_openers=lambda path: openers.get(path, []),
    )
    loop.tick()
    loop.stop()
    text = registry.snapshot().render()
    assert 'pod_uid="0a1b2c3d-e4f5-6789-abcd-ef0123456789"' in text
    from kube_gpu_stats_tpu import validate
    assert validate.check(text) == []
