"""Attribution: PodResources client + checkpoint fallback + cached provider
(SURVEY.md §4; BASELINE.json configs[2])."""

import json

import pytest

from kube_gpu_stats_tpu.attribution import (
    CachedAttribution,
    build,
    candidate_keys,
    device_probe_keys,
)
from kube_gpu_stats_tpu.attribution.checkpoint import CheckpointSource
from kube_gpu_stats_tpu.attribution.podresources import PodResourcesSource
from kube_gpu_stats_tpu.collectors import Device
from kube_gpu_stats_tpu.proto import podresources as pb

from kube_gpu_stats_tpu.testing.kubelet_server import FakeKubeletServer, tpu_pod


def dev(index, uuid=""):
    return Device(index, str(index), f"/dev/accel{index}", "tpu", uuid)


# -- key normalization (SURVEY.md §7 hard part c) ---------------------------

def test_candidate_keys_plain_index():
    assert candidate_keys("3") == ["3"]


def test_candidate_keys_dev_path():
    assert "/dev/accel2" in candidate_keys("/dev/accel2")
    assert "accel2" in candidate_keys("/dev/accel2")


def test_candidate_keys_accel_name():
    assert "5" in candidate_keys("accel5")


def test_candidate_keys_range():
    keys = candidate_keys("4-7")
    for i in ("4", "5", "6", "7"):
        assert i in keys


def test_device_probe_keys_order_and_dedup():
    keys = device_probe_keys(dev(0, uuid="tpu-uuid-0"))
    assert keys[0] == "0"
    assert "tpu-uuid-0" in keys
    assert "/dev/accel0" in keys
    assert "accel0" in keys
    assert len(keys) == len(set(keys))


# -- PodResources source -----------------------------------------------------

@pytest.fixture
def kubelet(tmp_path):
    socket = str(tmp_path / "kubelet.sock")
    pods = [
        tpu_pod("train-job-abc", "ml", "worker", ["0", "1"]),
        tpu_pod("infer-xyz", "serving", "model", ["/dev/accel2"]),
        tpu_pod("gpu-pod", "other", "cuda", ["GPU-uuid-1"], resource="nvidia.com/gpu"),
        tpu_pod("ignored", "x", "c", ["9"], resource="example.com/fpga"),
    ]
    with FakeKubeletServer(socket, pods) as server:
        yield server


def test_podresources_fetch(kubelet):
    source = PodResourcesSource(kubelet.socket_path)
    table = source.fetch()
    assert table["0"]["pod"] == "train-job-abc"
    assert table["0"]["namespace"] == "ml"
    assert table["1"]["container"] == "worker"
    # /dev/accel2 id answered under both raw and normalized keys.
    assert table["/dev/accel2"]["pod"] == "infer-xyz"
    assert table["accel2"]["pod"] == "infer-xyz"
    # nvidia.com/gpu kept (unified schema C12), unknown resources dropped.
    assert table["GPU-uuid-1"]["pod"] == "gpu-pod"
    assert "9" not in table
    source.close()


def test_cached_attribution_lookup(kubelet):
    cached = CachedAttribution(PodResourcesSource(kubelet.socket_path))
    cached.refresh_once()
    assert cached.lookup(dev(0))["pod"] == "train-job-abc"
    assert cached.lookup(dev(2))["pod"] == "infer-xyz"
    assert cached.lookup(dev(5)) == {}
    cached.stop()


def test_refresh_failure_keeps_last_map(kubelet):
    cached = CachedAttribution(PodResourcesSource(kubelet.socket_path))
    cached.refresh_once()
    kubelet.fail = True
    cached.refresh_once()
    assert cached.consecutive_failures == 1
    assert cached.lookup(dev(0))["pod"] == "train-job-abc"  # stale > empty
    kubelet.fail = False
    cached.refresh_once()
    assert cached.consecutive_failures == 0
    cached.stop()


def test_reallocation_visible_after_refresh(kubelet):
    cached = CachedAttribution(PodResourcesSource(kubelet.socket_path))
    cached.refresh_once()
    kubelet.pods = [tpu_pod("new-owner", "ml2", "c2", ["0"])]
    cached.refresh_once()
    assert cached.lookup(dev(0))["pod"] == "new-owner"
    assert cached.lookup(dev(1)) == {}  # deallocated
    cached.stop()


def test_background_refresh_thread(kubelet):
    cached = CachedAttribution(
        PodResourcesSource(kubelet.socket_path), refresh_interval=0.05
    )
    cached.start()
    import time

    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and kubelet.list_calls < 2:
        time.sleep(0.01)
    cached.stop()
    assert kubelet.list_calls >= 2
    assert cached.lookup(dev(0))["pod"] == "train-job-abc"


# -- checkpoint fallback -----------------------------------------------------

def checkpoint_doc():
    return {
        "Data": {
            "PodDeviceEntries": [
                {
                    "PodUID": "uid-1234",
                    "ContainerName": "worker",
                    "ResourceName": "google.com/tpu",
                    "DeviceIDs": {"-1": ["0", "1"]},
                },
                {
                    "PodUID": "uid-old",
                    "ContainerName": "legacy",
                    "ResourceName": "google.com/tpu",
                    "DeviceIDs": ["2"],  # pre-1.20 flat shape
                },
                {
                    "PodUID": "uid-skip",
                    "ContainerName": "fpga",
                    "ResourceName": "example.com/fpga",
                    "DeviceIDs": {"-1": ["3"]},
                },
            ],
            "RegisteredDevices": {"google.com/tpu": ["0", "1", "2"]},
        },
        "Checksum": 12345,
    }


def test_checkpoint_fetch(tmp_path):
    path = tmp_path / "kubelet_internal_checkpoint"
    path.write_text(json.dumps(checkpoint_doc()))
    table = CheckpointSource(str(path)).fetch()
    assert table["0"] == {"pod": "uid-1234", "namespace": "", "container": "worker"}
    assert table["2"]["container"] == "legacy"
    assert "3" not in table


def test_checkpoint_missing_file_is_refresh_failure(tmp_path):
    cached = CachedAttribution(CheckpointSource(str(tmp_path / "nope")))
    cached.refresh_once()
    assert cached.consecutive_failures == 1
    assert cached.lookup(dev(0)) == {}


# -- factory -----------------------------------------------------------------

def test_build_auto_prefers_podresources(kubelet, tmp_path):
    cached = build(
        mode="auto",
        kubelet_socket=kubelet.socket_path,
        checkpoint_path=str(tmp_path / "nope"),
        refresh_interval=10.0,
    )
    cached.refresh_once()
    assert cached.lookup(dev(0))["pod"] == "train-job-abc"
    cached.stop()


def test_build_auto_falls_back_to_checkpoint(tmp_path):
    path = tmp_path / "kubelet_internal_checkpoint"
    path.write_text(json.dumps(checkpoint_doc()))
    cached = build(
        mode="auto",
        kubelet_socket=str(tmp_path / "missing.sock"),
        checkpoint_path=str(path),
        refresh_interval=10.0,
    )
    cached.refresh_once()
    assert cached.lookup(dev(0))["pod"] == "uid-1234"
    cached.stop()


# -- allocatable cross-check (GetAllocatableResources) ----------------------

def test_podresources_fetch_allocatable(tmp_path):
    socket = str(tmp_path / "kubelet.sock")
    allocatable = [
        pb.ContainerDevices("google.com/tpu", ("0", "1", "2", "3")),
        pb.ContainerDevices("nvidia.com/gpu", ("GPU-a",)),
        pb.ContainerDevices("example.com/fpga", ("f0",)),
    ]
    with FakeKubeletServer(socket, allocatable=allocatable):
        source = PodResourcesSource(socket)
        counts = source.fetch_allocatable()
        assert counts == {"google.com/tpu": 4, "nvidia.com/gpu": 1}
        source.close()


def test_cached_attribution_exposes_allocatable(tmp_path):
    socket = str(tmp_path / "kubelet.sock")
    allocatable = [pb.ContainerDevices("google.com/tpu", ("0", "1"))]
    with FakeKubeletServer(socket, allocatable=allocatable):
        cached = CachedAttribution(PodResourcesSource(socket))
        assert cached.allocatable() == {}
        cached.refresh_once()
        assert cached.allocatable() == {"google.com/tpu": 2}
        cached.stop()


def test_checkpoint_fetch_allocatable(tmp_path):
    path = tmp_path / "kubelet_internal_checkpoint"
    path.write_text(json.dumps(checkpoint_doc()))
    assert CheckpointSource(str(path)).fetch_allocatable() == {
        "google.com/tpu": 3
    }


def test_allocatable_gauge_in_snapshot(tmp_path):
    from kube_gpu_stats_tpu.collectors.mock import MockCollector
    from kube_gpu_stats_tpu.poll import PollLoop
    from kube_gpu_stats_tpu.registry import Registry

    socket = str(tmp_path / "kubelet.sock")
    allocatable = [pb.ContainerDevices("google.com/tpu", ("0", "1", "2", "3"))]
    with FakeKubeletServer(socket, allocatable=allocatable):
        cached = CachedAttribution(PodResourcesSource(socket))
        cached.refresh_once()
        reg = Registry()
        loop = PollLoop(MockCollector(num_devices=4), reg, deadline=5.0,
                        attribution=cached)
        loop.tick()
        series = [
            (dict(s.labels), s.value)
            for s in reg.snapshot().series
            if s.spec.name == "collector_allocatable_devices"
        ]
        assert series == [({"resource": "google.com/tpu"}, 4.0)]
        loop.stop()
        cached.stop()


def test_auto_switches_to_podresources_when_kubelet_appears(tmp_path):
    """Auto mode must pick up a kubelet that starts AFTER the exporter
    (kubelet restart / boot ordering) without a pod restart."""
    path = tmp_path / "kubelet_internal_checkpoint"
    path.write_text(json.dumps(checkpoint_doc()))
    socket = str(tmp_path / "kubelet.sock")
    cached = build(mode="auto", kubelet_socket=socket,
                   checkpoint_path=str(path), refresh_interval=10.0)
    cached.refresh_once()
    assert cached.lookup(dev(0))["pod"] == "uid-1234"  # checkpoint fallback
    with FakeKubeletServer(socket, [tpu_pod("late-pod", "ml", "c", ["0"])]):
        cached.refresh_once()
        assert cached.lookup(dev(0))["pod"] == "late-pod"  # switched
    cached.stop()


def test_auto_falls_back_when_stale_socket_fetch_fails(tmp_path):
    """A crashed kubelet leaves its socket file on disk; auto mode must
    fall back to the checkpoint on fetch failure, not just on absence."""
    path = tmp_path / "kubelet_internal_checkpoint"
    path.write_text(json.dumps(checkpoint_doc()))
    socket = str(tmp_path / "kubelet.sock")
    # Create a stale socket file with nothing listening.
    import socket as pysock

    s = pysock.socket(pysock.AF_UNIX)
    s.bind(socket)
    s.close()  # file remains, no listener
    cached = build(mode="auto", kubelet_socket=socket,
                   checkpoint_path=str(path), refresh_interval=10.0)
    cached.refresh_once()
    assert cached.consecutive_failures == 0
    assert cached.lookup(dev(0))["pod"] == "uid-1234"  # via checkpoint
    cached.stop()


def test_auto_keeps_podresources_identity_after_kubelet_blip(kubelet, tmp_path):
    """Review finding: once PodResources has succeeded, a transient
    kubelet failure must RAISE (cached last-good map with pod NAMES is
    kept) instead of remapping every series to checkpoint pod UIDs."""
    import pytest as _pytest

    from kube_gpu_stats_tpu.attribution import AutoSource

    checkpoint = tmp_path / "kubelet_internal_checkpoint"
    checkpoint.write_text('{"Data":{"PodDeviceEntries":[]},"Checksum":1}')
    source = AutoSource(kubelet.socket_path, str(checkpoint))
    try:
        assert source.fetch()  # PodResources succeeds and latches
        kubelet.stop()
        # Blip hysteresis: the first failures RAISE (cached name-labeled
        # map retained) instead of silently remapping to checkpoint UIDs.
        for _ in range(AutoSource._FALLBACK_AFTER - 1):
            with _pytest.raises(Exception):
                source.fetch()
        # Kubelet genuinely gone: eventually the checkpoint takes over.
        assert source.fetch() == {}
        assert source._cycle_used_checkpoint
    finally:
        source.close()


def test_build_checkpoint_mode_needs_no_grpc(tmp_path, monkeypatch):
    """Review finding: build(mode='checkpoint') imported the grpc-backed
    module unconditionally, so grpcio-less installs silently lost even
    checkpoint attribution."""
    import sys

    from kube_gpu_stats_tpu import attribution

    monkeypatch.setitem(
        sys.modules, "kube_gpu_stats_tpu.attribution.podresources", None)
    checkpoint = tmp_path / "ckpt"
    checkpoint.write_text('{"Data":{"PodDeviceEntries":[]},"Checksum":1}')
    cached = attribution.build(
        mode="checkpoint", kubelet_socket="/nonexistent.sock",
        checkpoint_path=str(checkpoint), refresh_interval=10.0)
    cached.refresh_once()
    cached.stop()


def test_stale_false_while_checkpoint_fallback_serves_fresh():
    """Auto mode with the kubelet breaker open but the checkpoint
    fallback succeeding: lookups serve FRESH (checkpoint) data, so the
    stale marker must stay off — whatever the breaker says."""
    from kube_gpu_stats_tpu.attribution import CachedAttribution
    from kube_gpu_stats_tpu.resilience import CircuitBreaker

    breaker = CircuitBreaker("kubelet", failure_threshold=1)
    breaker.record_failure("socket gone")
    assert breaker.state == "open"

    class CheckpointFallbackSource:
        breaker = None

        def fetch(self):
            return {"0": {"pod": "", "namespace": "", "container": ""}}

        def close(self):
            pass

    source = CheckpointFallbackSource()
    source.breaker = breaker  # AutoSource exposes the PodResources breaker
    cached = CachedAttribution(source, refresh_interval=60.0)
    cached.refresh_once()
    assert cached.consecutive_failures == 0
    assert not cached.stale  # fresh data, just UID/checkpoint-shaped

    # Once refreshes themselves fail, the open breaker marks it stale.
    source.fetch = lambda: (_ for _ in ()).throw(RuntimeError("gone too"))
    cached.refresh_once()
    assert cached.stale
