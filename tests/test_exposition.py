"""HTTP + textfile exposition (SURVEY.md §3 E3, configs[0])."""

import urllib.request

from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.exposition import CONTENT_TYPE, MetricsServer, TextfileWriter
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry


def _served(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as resp:
        return resp.status, resp.headers, resp.read().decode()


def test_http_metrics_roundtrip():
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=2), reg, deadline=5.0)
    loop.tick()
    server = MetricsServer(reg, host="127.0.0.1", port=0)
    server.start()
    try:
        status, headers, body = _served(server.port, "/metrics")
        assert status == 200
        assert headers["Content-Type"] == CONTENT_TYPE
        assert 'accelerator_duty_cycle{accel_type="mock",chip="0"' in body
        assert body == reg.snapshot().render()
        status, _, body = _served(server.port, "/healthz")
        assert (status, body) == (200, "ok\n")
        try:
            _served(server.port, "/nope")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        server.stop()
        loop.stop()


def test_textfile_atomic_write(tmp_path):
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, deadline=5.0)
    loop.tick()
    writer = TextfileWriter(reg, tmp_path)
    writer.write_once()
    text = writer.path.read_text()
    assert text == reg.snapshot().render()
    assert not (tmp_path / "accelerator.prom.tmp").exists()


def test_textfile_follows_publishes(tmp_path):
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, interval=0.02, deadline=5.0)
    writer = TextfileWriter(reg, tmp_path)
    writer.start()
    loop.start()
    try:
        assert reg.wait_for_publish(0, timeout=2)
        deadline_gen = reg.generation + 2
        while reg.generation < deadline_gen:
            assert reg.wait_for_publish(reg.generation, timeout=2)
        # Writer has had at least one publish to chase; file must exist and
        # parse as a full exposition.
        for _ in range(100):
            if writer.path.exists():
                break
            import time

            time.sleep(0.01)
        content = writer.path.read_text()
        assert "accelerator_up" in content
    finally:
        loop.stop()
        writer.stop()


def test_debug_threads_endpoint():
    reg = Registry()
    server = MetricsServer(reg, host="127.0.0.1", port=0)
    server.start()
    try:
        _, _, body = _served(server.port, "/debug/threads")
        assert "--- thread" in body
        assert "MainThread" in body
    finally:
        server.stop()


def test_readyz_transitions(tmp_path):
    from kube_gpu_stats_tpu.registry import SnapshotBuilder

    reg = Registry()
    server = MetricsServer(reg, host="127.0.0.1", port=0)
    server.start()
    try:
        try:
            _served(server.port, "/readyz")
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
        reg.publish(SnapshotBuilder().build())
        status, _, body = _served(server.port, "/readyz")
        assert (status, body) == (200, "ready\n")
    finally:
        server.stop()


def test_official_prometheus_client_parses_our_exposition():
    """Interop: the official prometheus_client text parser must accept the
    full exposition (catches format bugs our own golden tests could share)."""
    from prometheus_client.parser import text_string_to_metric_families

    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=2), reg, deadline=5.0)
    loop.tick()
    loop.tick()
    text = reg.snapshot().render()
    families = {f.name: f for f in text_string_to_metric_families(text)}
    assert "accelerator_duty_cycle" in families
    # Counters: parser strips _total; histogram exposed as one family.
    assert "accelerator_ici_link_traffic_bytes" in families
    assert families["accelerator_ici_link_traffic_bytes"].type == "counter"
    assert "collector_poll_duration_seconds" in families
    assert families["collector_poll_duration_seconds"].type == "histogram"
    sample = families["accelerator_duty_cycle"].samples[0]
    assert set(sample.labels) == set(
        ("accel_type", "chip", "device_path", "uuid", "pod", "namespace",
         "container", "slice", "worker", "topology")
    )
    loop.stop()


def test_openmetrics_negotiation():
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, deadline=5.0)
    loop.tick()
    server = MetricsServer(reg, host="127.0.0.1", port=0)
    server.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/metrics",
            headers={"Accept": "application/openmetrics-text; version=1.0.0"},
        )
        with urllib.request.urlopen(req) as resp:
            assert "openmetrics-text" in resp.headers["Content-Type"]
            body = resp.read().decode()
        assert body.endswith("# EOF\n")
        # Counter family declared without _total; samples keep it.
        assert "# TYPE accelerator_ici_link_traffic_bytes counter" in body
        assert "accelerator_ici_link_traffic_bytes_total{" in body
        # Plain scrape unchanged.
        _, headers, plain = _served(server.port, "/metrics")
        assert "version=0.0.4" in headers["Content-Type"]
        assert "# EOF" not in plain
        assert "# TYPE accelerator_ici_link_traffic_bytes_total counter" in plain
    finally:
        server.stop()
        loop.stop()


def test_openmetrics_parses_with_official_parser():
    from prometheus_client.openmetrics.parser import text_string_to_metric_families

    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=2), reg, deadline=5.0)
    loop.tick()
    loop.tick()
    text = reg.snapshot().render(openmetrics=True)
    families = {f.name: f for f in text_string_to_metric_families(text)}
    assert "accelerator_duty_cycle" in families
    assert families["accelerator_ici_link_traffic_bytes"].type == "counter"
    assert families["collector_poll_duration_seconds"].type == "histogram"
    loop.stop()
