"""HTTP + textfile exposition (SURVEY.md §3 E3, configs[0])."""

import urllib.request

from kube_gpu_stats_tpu.collectors.mock import MockCollector
from kube_gpu_stats_tpu.exposition import CONTENT_TYPE, MetricsServer, TextfileWriter
from kube_gpu_stats_tpu.poll import PollLoop
from kube_gpu_stats_tpu.registry import Registry


def _served(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as resp:
        return resp.status, resp.headers, resp.read().decode()


def test_http_metrics_roundtrip():
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=2), reg, deadline=5.0)
    loop.tick()
    server = MetricsServer(reg, host="127.0.0.1", port=0)
    server.start()
    try:
        status, headers, body = _served(server.port, "/metrics")
        assert status == 200
        assert headers["Content-Type"] == CONTENT_TYPE
        assert 'accelerator_duty_cycle{accel_type="mock",chip="0"' in body
        assert body == reg.snapshot().render()
        status, _, body = _served(server.port, "/healthz")
        assert (status, body) == (200, "ok\n")
        try:
            _served(server.port, "/nope")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        server.stop()
        loop.stop()


def test_textfile_atomic_write(tmp_path):
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, deadline=5.0)
    loop.tick()
    writer = TextfileWriter(reg, tmp_path)
    writer.write_once()
    text = writer.path.read_text()
    assert text == reg.snapshot().render()
    assert not (tmp_path / "accelerator.prom.tmp").exists()


def test_textfile_follows_publishes(tmp_path):
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, interval=0.02, deadline=5.0)
    writer = TextfileWriter(reg, tmp_path)
    writer.start()
    loop.start()
    try:
        assert reg.wait_for_publish(0, timeout=2)
        deadline_gen = reg.generation + 2
        while reg.generation < deadline_gen:
            assert reg.wait_for_publish(reg.generation, timeout=2)
        # Writer has had at least one publish to chase; file must exist and
        # parse as a full exposition.
        for _ in range(100):
            if writer.path.exists():
                break
            import time

            time.sleep(0.01)
        content = writer.path.read_text()
        assert "accelerator_up" in content
    finally:
        loop.stop()
        writer.stop()


def test_debug_threads_endpoint():
    reg = Registry()
    server = MetricsServer(reg, host="127.0.0.1", port=0)
    server.start()
    try:
        _, _, body = _served(server.port, "/debug/threads")
        assert "--- thread" in body
        assert "MainThread" in body
    finally:
        server.stop()


def test_readyz_transitions(tmp_path):
    from kube_gpu_stats_tpu.registry import SnapshotBuilder

    reg = Registry()
    server = MetricsServer(reg, host="127.0.0.1", port=0)
    server.start()
    try:
        try:
            _served(server.port, "/readyz")
            raise AssertionError("expected 503")
        except urllib.error.HTTPError as e:
            assert e.code == 503
        reg.publish(SnapshotBuilder().build())
        status, _, body = _served(server.port, "/readyz")
        assert (status, body) == (200, "ready\n")
    finally:
        server.stop()


def test_official_prometheus_client_parses_our_exposition():
    """Interop: the official prometheus_client text parser must accept the
    full exposition (catches format bugs our own golden tests could share)."""
    from prometheus_client.parser import text_string_to_metric_families

    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=2), reg, deadline=5.0)
    loop.tick()
    loop.tick()
    text = reg.snapshot().render()
    families = {f.name: f for f in text_string_to_metric_families(text)}
    assert "accelerator_duty_cycle" in families
    # Counters: parser strips _total; histogram exposed as one family.
    assert "accelerator_ici_link_traffic_bytes" in families
    assert families["accelerator_ici_link_traffic_bytes"].type == "counter"
    assert "collector_poll_duration_seconds" in families
    assert families["collector_poll_duration_seconds"].type == "histogram"
    sample = families["accelerator_duty_cycle"].samples[0]
    assert set(sample.labels) == set(
        ("accel_type", "chip", "device_path", "uuid", "pod", "namespace",
         "container", "slice", "worker", "topology")
    )
    loop.stop()


def test_openmetrics_negotiation():
    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=1), reg, deadline=5.0)
    loop.tick()
    server = MetricsServer(reg, host="127.0.0.1", port=0)
    server.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/metrics",
            headers={"Accept": "application/openmetrics-text; version=1.0.0"},
        )
        with urllib.request.urlopen(req) as resp:
            assert "openmetrics-text" in resp.headers["Content-Type"]
            body = resp.read().decode()
        assert body.endswith("# EOF\n")
        # Counter family declared without _total; samples keep it.
        assert "# TYPE accelerator_ici_link_traffic_bytes counter" in body
        assert "accelerator_ici_link_traffic_bytes_total{" in body
        # Plain scrape unchanged.
        _, headers, plain = _served(server.port, "/metrics")
        assert "version=0.0.4" in headers["Content-Type"]
        assert "# EOF" not in plain
        assert "# TYPE accelerator_ici_link_traffic_bytes_total counter" in plain
    finally:
        server.stop()
        loop.stop()


def test_openmetrics_parses_with_official_parser():
    from prometheus_client.openmetrics.parser import text_string_to_metric_families

    reg = Registry()
    loop = PollLoop(MockCollector(num_devices=2), reg, deadline=5.0)
    loop.tick()
    loop.tick()
    text = reg.snapshot().render(openmetrics=True)
    families = {f.name: f for f in text_string_to_metric_families(text)}
    assert "accelerator_duty_cycle" in families
    assert families["accelerator_ici_link_traffic_bytes"].type == "counter"
    assert families["collector_poll_duration_seconds"].type == "histogram"
    loop.stop()


def test_scrape_duration_self_metrics_appear_after_first_scrape():
    """Round-1 verdict item 5 (done round 3): the render half of the
    north-star scrape latency. A scrape records render+gzip wall time and
    output bytes into RenderStats; the NEXT tick folds them into the
    snapshot, so the second scrape exposes them."""
    from kube_gpu_stats_tpu.exposition import RenderStats

    reg = Registry()
    stats = RenderStats()
    loop = PollLoop(MockCollector(num_devices=2), reg, deadline=5.0,
                    render_stats=stats.contribute)
    loop.tick()
    server = MetricsServer(reg, host="127.0.0.1", port=0, render_stats=stats)
    server.start()
    try:
        _, _, first = _served(server.port, "/metrics")
        assert "collector_scrape_duration_seconds" not in first
        loop.tick()
        _, _, body = _served(server.port, "/metrics")
        assert ('collector_scrape_duration_seconds_bucket{output="http",'
                'le="0.0001"}') in body
        assert 'collector_scrape_duration_seconds_count{output="http"} 1' in body
        assert 'collector_scrape_duration_seconds_sum{output="http"}' in body
        assert 'collector_rendered_bytes_total{output="http"}' in body
        # One HELP/TYPE header even though more outputs may join the family.
        assert body.count("# TYPE collector_scrape_duration_seconds") == 1
    finally:
        server.stop()
        loop.stop()


def test_textfile_and_pushgateway_renders_observed(tmp_path, monkeypatch):
    import contextlib

    from kube_gpu_stats_tpu.exposition import PushgatewayPusher, RenderStats

    reg = Registry()
    stats = RenderStats()
    loop = PollLoop(MockCollector(num_devices=1), reg, deadline=5.0,
                    render_stats=stats.contribute)
    loop.tick()
    writer = TextfileWriter(reg, tmp_path, render_stats=stats)
    writer.write_once()
    pusher = PushgatewayPusher(reg, "http://127.0.0.1:9", render_stats=stats)
    monkeypatch.setattr("urllib.request.urlopen",
                        lambda *a, **kw: contextlib.nullcontext())
    pusher.push_once()
    loop.tick()
    writer.write_once()
    text = writer.path.read_text()
    assert 'collector_scrape_duration_seconds_count{output="textfile"} 1' in text
    assert 'collector_scrape_duration_seconds_count{output="pushgateway"} 1' in text
    assert 'collector_rendered_bytes_total{output="textfile"}' in text
    assert 'collector_rendered_bytes_total{output="pushgateway"}' in text


def test_render_stats_labeled_histogram_rendered_form():
    """Pin the rendered shape of a multi-output scrape-duration family:
    grouped under one HELP/TYPE, each state carrying its output label on
    every bucket/sum/count line (deterministic golden-style check — wall
    times are injected, not measured)."""
    from kube_gpu_stats_tpu.exposition import RenderStats
    from kube_gpu_stats_tpu.registry import SnapshotBuilder

    stats = RenderStats()
    stats.observe("http", 0.00009, 1000)
    stats.observe("http", 0.002, 1200)
    stats.observe("textfile", 0.03, 500)
    builder = SnapshotBuilder()
    stats.contribute(builder)
    text = builder.build().render()
    assert text.count("# TYPE collector_scrape_duration_seconds histogram") == 1
    assert ('collector_scrape_duration_seconds_bucket{output="http",'
            'le="0.0001"} 1') in text
    assert ('collector_scrape_duration_seconds_bucket{output="http",'
            'le="0.0025"} 2') in text
    assert ('collector_scrape_duration_seconds_bucket{output="http",'
            'le="+Inf"} 2') in text
    assert ('collector_scrape_duration_seconds_bucket{output="textfile",'
            'le="0.05"} 1') in text
    assert 'collector_scrape_duration_seconds_count{output="http"} 2' in text
    assert 'collector_scrape_duration_seconds_count{output="textfile"} 1' in text
    assert 'collector_rendered_bytes_total{output="http"} 2200' in text
    assert 'collector_rendered_bytes_total{output="textfile"} 500' in text
    # Both official parsers accept the labeled-histogram form.
    from prometheus_client.parser import text_string_to_metric_families

    families = {f.name: f for f in text_string_to_metric_families(text)}
    assert families["collector_scrape_duration_seconds"].type == "histogram"
    buckets = [s for s in families["collector_scrape_duration_seconds"].samples
               if s.name.endswith("_bucket")]
    assert {s.labels["output"] for s in buckets} == {"http", "textfile"}


def test_debug_profile_emits_folded_stacks():
    """/debug/profile samples every thread for a bounded window and
    returns flamegraph-ready folded stacks naming the hot function."""
    import re
    import threading
    import time
    import urllib.request

    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.registry import Registry

    stop = threading.Event()

    def recognizable_busy_function():
        while not stop.is_set():
            sum(range(2000))

    worker = threading.Thread(target=recognizable_busy_function,
                              name="busy-worker", daemon=True)
    worker.start()
    srv = MetricsServer(Registry(), host="127.0.0.1", port=0)
    srv.start()
    try:
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/debug/profile?seconds=0.4",
            timeout=15).read().decode()
    finally:
        srv.stop()
        stop.set()
        worker.join(timeout=5)
    assert "recognizable_busy_function" in body
    assert "busy-worker" in body
    # Folded format: every line is "stack... count".
    for line in body.splitlines():
        assert re.fullmatch(r".+ \d+", line), line


def test_debug_profile_seconds_clamped_and_single_flight():
    import threading
    import urllib.error
    import urllib.request

    from kube_gpu_stats_tpu.exposition import MetricsServer
    from kube_gpu_stats_tpu.registry import Registry

    srv = MetricsServer(Registry(), host="127.0.0.1", port=0)
    srv.start()
    url = f"http://127.0.0.1:{srv.port}/debug/profile"
    try:
        # A nonsense duration clamps (0.1s floor) and still answers.
        assert urllib.request.urlopen(
            f"{url}?seconds=banana", timeout=15).status == 200
        codes = []

        def long_profile():
            codes.append(urllib.request.urlopen(
                f"{url}?seconds=1.5", timeout=15).status)

        t = threading.Thread(target=long_profile)
        t.start()
        # Deterministic: wait until the long profile observably HOLDS the
        # lock (a fixed sleep races thread start + connect on loaded CI).
        import time
        deadline = time.monotonic() + 10
        while not srv._profile_lock.locked():
            assert time.monotonic() < deadline, "profile never took the lock"
            time.sleep(0.01)
        try:
            urllib.request.urlopen(f"{url}?seconds=0.1", timeout=15)
            second = 200
        except urllib.error.HTTPError as exc:
            second = exc.code
        t.join(timeout=10)
        assert codes == [200]
        assert second == 409  # single-flight
    finally:
        srv.stop()
