"""Push-delta protocol tests (ISSUE 7): wire codec strictness,
encoder/ingest session semantics under drops/reorders/duplicates/
restarts, the hub's push-serve + pull-fallback composition, federation
re-export, and the byte-identity differential pin — delta-applied hub
state must render identically to the pull-merge oracle fed the same
bodies."""

from __future__ import annotations

import random
import time

import pytest

from kube_gpu_stats_tpu import delta, schema
from kube_gpu_stats_tpu.hub import Hub
from kube_gpu_stats_tpu.registry import Registry, SnapshotBuilder


def make_body(worker: int, duty: float, steps: float = 0.0,
              chips: int = 2, extra_chip: bool = False,
              phase_p50: float = 0.001) -> str:
    """One deterministic worker exposition: per-chip gauges + a counter,
    a workload histogram, and a flight-recorder digest family — every
    ingest surface the hub derives caches from."""
    builder = SnapshotBuilder()
    count = chips + (1 if extra_chip else 0)
    for chip in range(count):
        labels = (
            ("accel_type", "tpu-v5p"), ("chip", str(chip)),
            ("device_path", f"/dev/accel{chip}"), ("uuid", ""),
            ("pod", "train-0"), ("namespace", "ml"), ("container", "w"),
            ("slice", f"s{worker % 2}"), ("worker", str(worker)),
            ("topology", "2x2"))
        builder.add(schema.DEVICE_UP, 1.0, labels)
        builder.add(schema.DUTY_CYCLE, duty + chip, labels)
        builder.add(schema.MEMORY_USED, 1e9 + worker, labels)
        builder.add(schema.POWER, 200.0 + duty, labels)
        builder.add(schema.WORKLOAD_STEPS, steps, labels)
        builder.add(schema.ICI_BANDWIDTH, 1e8 * (1 + chip),
                    labels + (("link", "0"),))
    hist = schema.WORKLOAD_STEP_DURATION
    from kube_gpu_stats_tpu.registry import HistogramState
    state = HistogramState.empty(hist, (0.1, 1.0),
                                 labels=(("worker", str(worker)),))
    state = state.observe(0.05, max(1, int(steps)))
    builder.add_histogram(state)
    builder.add(schema.TICK_PHASE_SECONDS, phase_p50,
                (("phase", "fold"), ("quantile", "p50")))
    return builder.build().render()


# --- wire codec -------------------------------------------------------------

def test_codec_full_roundtrip():
    wire = delta.encode_full("node-a", 7, 3, "accelerator_up 1\n")
    frame = delta.decode_frame(wire)
    assert frame.kind == delta.KIND_FULL
    assert (frame.source, frame.generation, frame.seq) == ("node-a", 7, 3)
    assert frame.body == "accelerator_up 1\n"


def test_codec_delta_roundtrip_gap_encoding():
    changes = [(0, 1.5), (3, -2.0), (4097, 3.25)]
    wire = delta.encode_delta("node-b", 9, 12, changes)
    frame = delta.decode_frame(wire)
    assert frame.kind == delta.KIND_DELTA
    assert list(zip(frame.slots, frame.values)) == changes


def test_codec_rejects_malformed():
    import kube_gpu_stats_tpu.snappy as snappy

    good = delta.encode_full("s", 1, 1, "x 1\n")
    with pytest.raises(ValueError):
        delta.decode_frame(good[:-3])  # truncated snappy stream
    raw = snappy.decompress(good)
    for mutant in (
        snappy.compress(b"NOPE" + raw[4:]),          # bad magic
        snappy.compress(raw[:4] + b"\x63" + raw[5:]),  # bad version
        snappy.compress(raw[:5] + b"\x07" + raw[6:]),  # unknown kind
        snappy.compress(raw[:-2]),                   # body length mismatch
    ):
        with pytest.raises(ValueError):
            delta.decode_frame(mutant)
    with pytest.raises(ValueError):
        delta.encode_delta("s", 1, 1, [(5, 1.0), (2, 1.0)])  # not ascending


def test_decompression_bomb_rejected_before_expanding():
    """A frame DECLARING a huge decompressed size is rejected off the
    preamble, before any decompression work (review finding: the size
    cap ran after snappy.decompress, i.e. after the bomb went off)."""
    bomb = delta._varint(delta.MAX_FRAME_BYTES * 4) + b"\x00" * 64
    with pytest.raises(ValueError, match="size cap"):
        delta.decode_frame(bomb)


def test_empty_source_rejected():
    with pytest.raises(ValueError, match="empty source"):
        delta.decode_frame(delta.encode_full("", 1, 1, "x 1\n"))


# --- encoder ----------------------------------------------------------------

def test_encoder_full_then_delta_then_shape_change():
    encoder = delta.DeltaEncoder("w0", generation=1)
    wire, kind = encoder.encode_next(make_body(0, 10.0))
    assert kind == delta.KIND_FULL
    encoder.ack()
    # Values-only change -> DELTA with exactly the changed slots.
    wire, kind = encoder.encode_next(make_body(0, 12.0))
    assert kind == delta.KIND_DELTA
    frame = delta.decode_frame(wire)
    assert frame.seq == 2
    assert len(frame.slots) > 0
    encoder.ack()
    # Unchanged body -> empty DELTA heartbeat.
    wire, kind = encoder.encode_next(make_body(0, 12.0))
    assert kind == delta.KIND_DELTA
    assert delta.decode_frame(wire).slots == ()
    encoder.ack()
    # Shape change (a chip appears) -> FULL.
    _, kind = encoder.encode_next(make_body(0, 12.0, extra_chip=True))
    assert kind == delta.KIND_FULL
    encoder.ack()
    # nack (failed/uncertain send) promotes the next frame to FULL.
    _, kind = encoder.encode_next(make_body(0, 13.0, extra_chip=True))
    assert kind == delta.KIND_DELTA
    encoder.nack()
    _, kind = encoder.encode_next(make_body(0, 13.0, extra_chip=True))
    assert kind == delta.KIND_FULL


def test_quiet_tick_payload_at_least_10x_smaller():
    """Acceptance pin: a quiet tick's delta payload is >= 10x smaller
    than the full exposition frame."""
    encoder = delta.DeltaEncoder("w0", generation=1)
    full_wire, _ = encoder.encode_next(make_body(0, 10.0, steps=5.0))
    encoder.ack()
    # A quiet tick: one gauge twitches, everything else is unchanged.
    quiet_wire, kind = encoder.encode_next(
        make_body(0, 10.0, steps=5.0, phase_p50=0.0011))
    assert kind == delta.KIND_DELTA
    assert len(quiet_wire) * 10 <= len(full_wire), (
        len(quiet_wire), len(full_wire))


# --- ingest session rules ---------------------------------------------------

def _push_hub(**kwargs) -> Hub:
    kwargs.setdefault("targets_provider", lambda: [])
    kwargs.setdefault("interval", 10.0)
    kwargs.setdefault("push_fence", 1e9)  # tests drive refreshes by hand
    return Hub([], **kwargs)


def _feed(hub: Hub, encoder: delta.DeltaEncoder, body: str,
          deliver: bool = True) -> tuple[int, bytes]:
    wire, _kind = encoder.encode_next(body)
    if not deliver:
        encoder.nack()
        return 0, b""
    code, resp, _hdrs = hub.delta.handle(wire)
    if code == 200:
        encoder.ack()
    else:
        encoder.nack()
    return code, resp


def test_ingest_seq_gap_duplicate_and_reorder_force_resync():
    hub = _push_hub()
    try:
        encoder = delta.DeltaEncoder("w0", generation=5)
        code, _ = _feed(hub, encoder, make_body(0, 10.0))
        assert code == 200
        hub.refresh_once()
        wire2, _ = encoder.encode_next(make_body(0, 11.0))
        assert hub.delta.handle(wire2)[0] == 200
        encoder.ack()
        # Duplicate delivery of the same frame: seq already consumed.
        code, resp, _hdrs = hub.delta.handle(wire2)
        assert code == 409 and b"seq gap" in resp
        # A frame from the future (seq gap; simulates a dropped frame).
        future = delta.encode_delta("w0", 5, 99, [(0, 1.0)])
        assert hub.delta.handle(future)[0] == 409
        # Generation mismatch (worker restarted elsewhere).
        other = delta.encode_delta("w0", 6, 3, [(0, 1.0)])
        assert hub.delta.handle(other)[0] == 409
        assert hub.delta.resyncs_total == 3
        # Unknown source: no session at all.
        orphan = delta.encode_delta("ghost", 1, 1, [(0, 1.0)])
        assert hub.delta.handle(orphan)[0] == 409
        # Out-of-range slot.
        huge = delta.encode_delta("w0", 5, encoder.seq + 1, [(10_000, 1.0)])
        assert hub.delta.handle(huge)[0] == 409
        # Recovery: the nacked encoder promotes to FULL, which is always
        # accepted and re-anchors the chain.
        code, _ = _feed(hub, encoder, make_body(0, 12.0))
        assert code == 200
        hub.refresh_once()
        body = hub.registry.snapshot().render()
        assert 'accelerator_duty_cycle' in body
        line = next(l for l in body.splitlines()
                    if l.startswith("accelerator_duty_cycle")
                    and 'chip="0"' in l)
        assert line.endswith(" 12"), line
    finally:
        hub.stop()


def test_restarted_worker_full_resync_no_stale_chain():
    """A worker restarting with a new generation replaces its session
    wholesale — old-generation deltas can never splice onto it."""
    hub = _push_hub()
    try:
        old = delta.DeltaEncoder("w0", generation=100)
        assert _feed(hub, old, make_body(0, 10.0))[0] == 200
        hub.refresh_once()
        fresh = delta.DeltaEncoder("w0", generation=200)
        assert _feed(hub, fresh, make_body(0, 33.0))[0] == 200
        # Straggler delta from the DEAD incarnation: rejected.
        stale = delta.encode_delta("w0", 100, 2, [(1, 99.0)])
        assert hub.delta.handle(stale)[0] == 409
        hub.refresh_once()
        body = hub.registry.snapshot().render()
        line = next(l for l in body.splitlines()
                    if l.startswith("accelerator_duty_cycle")
                    and 'chip="0"' in l)
        assert line.endswith(" 33"), line
    finally:
        hub.stop()


def test_session_expiry_evicts_target_state():
    """A silent session expires: the target leaves the hub's list and
    its cached entry/breaker/session state is evicted on the same
    refresh path (ISSUE 7 satellite — no stale seq chains)."""
    hub = _push_hub(push_fence=0.05)
    hub.delta._expiry = 0.1
    try:
        encoder = delta.DeltaEncoder("w0", generation=1)
        assert _feed(hub, encoder, make_body(0, 10.0))[0] == 200
        hub.refresh_once()
        assert "w0" in hub._targets
        assert "w0" in hub._parse_cache
        time.sleep(0.15)
        hub.refresh_once()
        assert "w0" not in hub._targets
        assert "w0" not in hub._parse_cache
        assert hub.delta.sources() == []
        # The worker comes back (restart): next delta draws a resync,
        # the FULL re-admits it cleanly.
        late = delta.encode_delta("w0", 1, 2, [(0, 1.0)])
        assert hub.delta.handle(late)[0] == 409
        fresh = delta.DeltaEncoder("w0", generation=2)
        assert _feed(hub, fresh, make_body(0, 20.0))[0] == 200
        hub.refresh_once()
        assert "w0" in hub._targets
    finally:
        hub.stop()


def test_stale_push_session_falls_back_to_pull(tmp_path):
    """Push-unavailable -> pull fallback: a configured target whose push
    session goes stale past the fence is pull-scraped that refresh."""
    target = tmp_path / "w0.prom"
    target.write_text(make_body(0, 77.0))
    hub = Hub([str(target)], interval=10.0, push_fence=0.05)
    try:
        encoder = delta.DeltaEncoder(str(target), generation=1)
        assert _feed(hub, encoder, make_body(0, 10.0))[0] == 200
        hub.refresh_once()
        assert hub._push_served == 1
        line = next(l for l in hub.registry.snapshot().render().splitlines()
                    if l.startswith("accelerator_duty_cycle")
                    and 'chip="0"' in l)
        assert line.endswith(" 10"), line
        time.sleep(0.1)  # past the fence: session stale, file served
        hub.refresh_once()
        assert hub._push_served == 0
        body = hub.registry.snapshot().render()
        line = next(l for l in body.splitlines()
                    if l.startswith("accelerator_duty_cycle")
                    and 'chip="0"' in l)
        assert line.endswith(" 77"), line
        assert f'slice_target_up{{target="{target}"}} 1' in body
        # The pull replaced the pushed entry: the session's next delta
        # draws a resync, and a FULL resumes push service.
        wire, _ = encoder.encode_next(make_body(0, 11.0))
        assert hub.delta.handle(wire)[0] == 409
        encoder.nack()
        assert _feed(hub, encoder, make_body(0, 11.0))[0] == 200
        hub.refresh_once()
        assert hub._push_served == 1
    finally:
        hub.stop()


def test_ingest_metrics_exported():
    hub = _push_hub()
    try:
        encoder = delta.DeltaEncoder("w0", generation=1)
        assert _feed(hub, encoder, make_body(0, 10.0))[0] == 200
        assert _feed(hub, encoder, make_body(0, 11.0))[0] == 200
        hub.refresh_once()
        body = hub.registry.snapshot().render()
        assert 'kts_delta_frames_total{kind="full"} 1' in body
        assert 'kts_delta_frames_total{kind="delta"} 1' in body
        assert "kts_hub_resync_total 0" in body
        assert "kts_delta_push_targets 1" in body
        assert "kts_delta_bytes_total" in body
    finally:
        hub.stop()


# --- sharded lanes (ISSUE 11) -----------------------------------------------

def test_lane_routing_sessions_and_entries_agree():
    """Sources hash to a lane (crc32, PYTHONHASHSEED-stable); the lane's
    session table and the LaneStore entry shard MUST agree on routing —
    a lane locking itself against an entry in another lane's slab would
    be sharding in name only."""
    hub = _push_hub(ingest_lanes=4)
    try:
        assert hub.delta.lanes == 4
        locks = {id(lane.lock) for lane in hub.delta._lanes}
        assert len(locks) == 4  # shared-nothing: one lock per lane
        sources = [f"http://node-{i}:9400/metrics" for i in range(16)]
        for i, source in enumerate(sources):
            encoder = delta.DeltaEncoder(source, generation=i + 1)
            assert _feed(hub, encoder, make_body(i, 10.0))[0] == 200
        used = set()
        for source in sources:
            lane_index = delta.lane_of(source, 4)
            used.add(lane_index)
            assert source in hub.delta._lanes[lane_index].sessions
            assert source in hub._parse_cache.shards[lane_index]
            for other in range(4):
                if other != lane_index:
                    assert source not in hub.delta._lanes[other].sessions
                    assert source not in hub._parse_cache.shards[other]
        assert len(used) > 1  # 16 sources actually spread over lanes
        # sources() reports fleet-wide ADMISSION order, lane-independent
        # — the hub's target order (and first-wins dedup) must be
        # indistinguishable from the single-table era.
        assert hub.delta.sources() == sources
    finally:
        hub.stop()


def test_lane_self_metrics_exported():
    hub = _push_hub(ingest_lanes=2)
    try:
        encoder = delta.DeltaEncoder("w0", generation=1)
        assert _feed(hub, encoder, make_body(0, 10.0))[0] == 200
        assert _feed(hub, encoder, make_body(0, 11.0))[0] == 200
        hub.refresh_once()
        body = hub.registry.snapshot().render()
        assert "kts_ingest_lanes 2" in body
        lane = delta.lane_of("w0", 2)
        assert (f'kts_ingest_lane_sessions{{lane="{lane}"}} 1'
                in body), body
        assert f'kts_ingest_lane_frames_total{{lane="{lane}"}} 2' in body
        apply_line = next(
            l for l in body.splitlines()
            if l.startswith("kts_ingest_lane_apply_seconds_total")
            and f'lane="{lane}"' in l)
        assert float(apply_line.rsplit(" ", 1)[1]) > 0.0, apply_line
        assert "kts_ingest_native" in body
    finally:
        hub.stop()


def test_resync_storm_concurrent_fulls_no_drops_no_healthy_evictions():
    """ISSUE 11 satellite: N sessions 409→FULL at once — concurrent
    handler threads firing FULL resyncs (new generations, the
    fleet-restart shape) while the OTHER half of the fleet keeps
    pushing ordinary deltas — must leave every session alive, every
    restart re-anchored, and every healthy session's chain unbroken
    (no convoy turning into timeouts, no healthy session evicted)."""
    import threading

    hub = _push_hub(ingest_lanes=4)
    try:
        n = 64
        sources = [f"http://node-{i:03d}:9400/metrics" for i in range(n)]
        encoders = []
        for i, source in enumerate(sources):
            encoder = delta.DeltaEncoder(source, generation=i + 1)
            assert _feed(hub, encoder, make_body(i, 10.0))[0] == 200
            encoders.append(encoder)
        hub.refresh_once()
        # Half the fleet "restarts": pre-encode one FULL each under a
        # new generation. The other half pre-encodes a delta chain.
        restart_wires = [
            delta.encode_full(sources[i], 1000 + i, 1, make_body(i, 44.0))
            for i in range(0, n, 2)]
        delta_wires = []
        for i in range(1, n, 2):
            wire, kind = encoders[i].encode_next(make_body(i, 20.0 + i))
            assert kind == delta.KIND_DELTA
            delta_wires.append(wire)
            encoders[i].ack()
        failures: list = []

        def fire(wires) -> None:
            for wire in wires:
                code, resp, _hdrs = hub.delta.handle(wire)
                if code != 200:
                    failures.append((code, resp))

        threads = [threading.Thread(target=fire, args=(restart_wires[k::4],))
                   for k in range(4)]
        threads += [threading.Thread(target=fire, args=(delta_wires[k::4],))
                    for k in range(4)]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join(timeout=30)
        assert not failures, failures[:5]
        assert len(hub.delta.sources()) == n  # nobody dropped
        hub.refresh_once()
        assert hub._push_served == n
        body = hub.registry.snapshot().render()
        # A restarted worker serves its post-restart FULL...
        line = next(l for l in body.splitlines()
                    if l.startswith("accelerator_duty_cycle")
                    and 'worker="0"' in l and 'chip="0"' in l)
        assert line.endswith(" 44"), line
        # ...and a healthy worker's concurrent delta landed.
        line = next(l for l in body.splitlines()
                    if l.startswith("accelerator_duty_cycle")
                    and 'worker="1"' in l and 'chip="0"' in l)
        assert line.endswith(" 21"), line
        # The restarts journaled as generation replacements, not
        # resyncs: a FULL is always accepted.
        assert hub.delta.full_frames_total == n + len(restart_wires)
    finally:
        hub.stop()


def test_expired_session_reestablishes_cleanly_on_drain():
    """ISSUE 13 satellite: a publisher offline past the hub's session
    expiry must re-establish on its spill drain with ONE FULL — no 409
    loop, no duplicate-counted frames — and continue deltas off it."""
    hub = _push_hub()
    try:
        encoder = delta.DeltaEncoder("node-a", generation=7)
        assert _feed(hub, encoder, make_body(0, 10.0))[0] == 200
        assert _feed(hub, encoder, make_body(0, 20.0))[0] == 200
        # The partition outlives the expiry: the hub evicts the session
        # AND its entry on the churn path (worker presumed gone).
        hub.delta.evict(set())
        del hub._parse_cache["node-a"]
        # Drain: the publisher nacked on its first failed send, so the
        # first post-partition frame is a FULL — accepted outright into
        # a fresh session (no 409 needed at all).
        encoder.nack()
        full_before = hub.delta.full_frames_total
        resyncs_before = hub.delta.resyncs_total
        code, _resp = _feed(hub, encoder, make_body(0, 30.0))
        assert code == 200
        # The rest of the backlog rides deltas off the re-anchored
        # session — never more FULLs, never a resync.
        for duty in (31.0, 32.0, 33.0):
            wire, kind = encoder.encode_next(make_body(0, duty))
            assert kind == delta.KIND_DELTA
            assert hub.delta.handle(wire)[0] == 200
            encoder.ack()
        assert hub.delta.full_frames_total == full_before + 1
        assert hub.delta.resyncs_total == resyncs_before
        assert hub.delta.duplicate_frames_total == 0
        hub.refresh_once()
        line = next(l for l in hub.registry.snapshot().render().splitlines()
                    if l.startswith("accelerator_duty_cycle")
                    and 'chip="0"' in l)
        assert line.endswith(" 33"), line
    finally:
        hub.stop()


def test_full_retransmit_not_double_counted():
    """ISSUE 13 satellite: a FULL whose response was lost (flaky link
    mid-drain) is re-sent with the SAME generation+seq; the hub applies
    it idempotently but counts it once — the record stays exactly-once
    even when the wire is at-least-once."""
    hub = _push_hub()
    try:
        wire = delta.encode_full("node-a", 5, 1, make_body(0, 10.0))
        assert hub.delta.handle(wire)[0] == 200
        assert hub.delta.handle(wire)[0] == 200  # retransmit: still ok
        assert hub.delta.full_frames_total == 1
        assert hub.delta.duplicate_frames_total == 1
        assert hub.delta.stats()["duplicate_frames"] == 1
        # A retransmit with a FRESHER body (the publisher re-rendered
        # before re-sending) must win — dedup is about counting, never
        # about serving stale values.
        fresher = delta.encode_full("node-a", 5, 1, make_body(0, 99.0))
        assert hub.delta.handle(fresher)[0] == 200
        hub.refresh_once()
        line = next(l for l in hub.registry.snapshot().render().splitlines()
                    if l.startswith("accelerator_duty_cycle")
                    and 'chip="0"' in l)
        assert line.endswith(" 99"), line
        # The chain continues from the retransmitted seq.
        encoder = delta.DeltaEncoder("node-a", generation=5)
        encoder.seq = 1
        encoder._keys = None
        wire2 = delta.encode_full("node-a", 5, 2, make_body(0, 50.0))
        assert hub.delta.handle(wire2)[0] == 200
        assert hub.delta.full_frames_total == 2
    finally:
        hub.stop()


# --- federation -------------------------------------------------------------

def leaf_rollup_body() -> str:
    builder = SnapshotBuilder()
    builder.add(schema.HUB_CHIPS, 8.0, (("slice", "s-a"),))
    builder.add(schema.HUB_DUTY_MEAN, 61.5, (("slice", "s-a"),))
    builder.add(schema.HUB_TARGET_UP, 1.0,
                (("target", "http://node-1:9400/metrics"),))
    builder.add(schema.HUB_WORKER_STEPS, 3.5,
                (("slice", "s-a"), ("worker", "w1")))
    builder.add(schema.HUB_TARGETS, 4.0)  # unlabeled: NOT re-exported
    return builder.build().render()


def test_federation_root_reexports_slice_rollups():
    hub = _push_hub(federate=True)
    try:
        encoder = delta.DeltaEncoder("leaf-a", generation=1)
        assert _feed(hub, encoder, leaf_rollup_body())[0] == 200
        hub.refresh_once()
        body = hub.registry.snapshot().render()
        assert 'slice_chips{slice="s-a"} 8' in body
        assert 'slice_duty_cycle_mean{slice="s-a"} 61.5' in body
        assert ('slice_worker_steps_per_second{slice="s-a",worker="w1"} 3.5'
                in body)
        assert 'slice_target_up{target="http://node-1:9400/metrics"} 1' \
            in body
        # The leaf's unlabeled self-gauge is NOT forwarded; the root
        # exports its own (1 target: the leaf).
        assert "slice_targets 1" in body
        # Delta-patching a re-exported rollup updates it in place.
        patched = leaf_rollup_body().replace(
            'slice_chips{slice="s-a"} 8', 'slice_chips{slice="s-a"} 6')
        assert _feed(hub, encoder, patched)[0] == 200
        hub.refresh_once()
        assert 'slice_chips{slice="s-a"} 6' in \
            hub.registry.snapshot().render()
    finally:
        hub.stop()


def test_federate_rollups_only_still_serves_leaf_rollups():
    """--federate --rollups-only: the per-chip series are silenced but
    the leaves' slice_* re-export must keep flowing (review finding:
    emit=None silenced both)."""
    hub = _push_hub(federate=True, rollups_only=True)
    try:
        encoder = delta.DeltaEncoder("leaf-a", generation=1)
        assert _feed(hub, encoder, leaf_rollup_body())[0] == 200
        chips = delta.DeltaEncoder("worker-x", generation=2)
        assert _feed(hub, chips, make_body(0, 10.0))[0] == 200
        hub.refresh_once()
        body = hub.registry.snapshot().render()
        assert 'slice_chips{slice="s-a"} 8' in body
        assert not any(line.startswith("accelerator_duty_cycle")
                       for line in body.splitlines())
    finally:
        hub.stop()


def test_non_federate_hub_drops_leaf_rollups():
    hub = _push_hub(federate=False)
    try:
        encoder = delta.DeltaEncoder("leaf-a", generation=1)
        assert _feed(hub, encoder, leaf_rollup_body())[0] == 200
        hub.refresh_once()
        body = hub.registry.snapshot().render()
        assert 'slice_chips{slice="s-a"}' not in body
    finally:
        hub.stop()


# --- HTTP ingest endpoint ---------------------------------------------------

def test_ingest_endpoint_auth_and_errors():
    import base64
    import hashlib
    import urllib.error
    import urllib.request

    from kube_gpu_stats_tpu.exposition import MetricsServer

    hub = _push_hub()
    password = "hunter2"
    server = MetricsServer(
        hub.registry, host="127.0.0.1", port=0,
        auth_username="admin",
        auth_password_sha256=hashlib.sha256(password.encode()).hexdigest(),
        ingest_provider=hub.delta.handle)
    server.start()
    url = f"http://127.0.0.1:{server.port}/ingest/delta"
    try:
        encoder = delta.DeltaEncoder("w0", generation=1)
        wire, _ = encoder.encode_next(make_body(0, 10.0))
        request = urllib.request.Request(url, data=wire, method="POST")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(request, timeout=5)
        assert err.value.code == 401
        token = base64.b64encode(f"admin:{password}".encode()).decode()
        request = urllib.request.Request(
            url, data=wire, method="POST",
            headers={"Authorization": f"Basic {token}"})
        with urllib.request.urlopen(request, timeout=5) as resp:
            assert resp.status == 200
        # Garbage frame -> 400, authed.
        request = urllib.request.Request(
            url, data=b"not a frame", method="POST",
            headers={"Authorization": f"Basic {token}"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(request, timeout=5)
        assert err.value.code == 400
        # Unknown POST path -> 404.
        request = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/ingest/other", data=b"x",
            method="POST", headers={"Authorization": f"Basic {token}"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(request, timeout=5)
        assert err.value.code == 404
    finally:
        server.stop()
        hub.stop()


def test_daemon_ingest_404():
    """A server with no ingest provider (daemons) answers POST 404."""
    import urllib.error
    import urllib.request

    from kube_gpu_stats_tpu.exposition import MetricsServer

    registry = Registry()
    server = MetricsServer(registry, host="127.0.0.1", port=0)
    server.start()
    try:
        request = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/ingest/delta",
            data=b"x", method="POST")
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(request, timeout=5)
        assert err.value.code == 404
    finally:
        server.stop()


# --- publisher over HTTP ----------------------------------------------------

def test_publisher_end_to_end_with_resync_recovery():
    from kube_gpu_stats_tpu.exposition import MetricsServer

    worker = Registry()

    def publish(duty: float) -> None:
        builder = SnapshotBuilder()
        labels = (("accel_type", "tpu-v5p"), ("chip", "0"),
                  ("device_path", "/dev/accel0"), ("uuid", ""))
        builder.add(schema.DEVICE_UP, 1.0, labels)
        builder.add(schema.DUTY_CYCLE, duty, labels)
        worker.publish(builder.build())

    publish(10.0)
    hub = _push_hub()
    server = MetricsServer(hub.registry, host="127.0.0.1", port=0,
                           ingest_provider=hub.delta.handle)
    server.start()
    publisher = delta.DeltaPublisher(
        worker, f"http://127.0.0.1:{server.port}", source="node-a")
    try:
        publisher.push_once()
        assert publisher.pushes_total == 1
        publish(20.0)
        publisher.push_once()
        assert publisher.pushes_total == 2
        assert publisher.last_frame_kind == delta.KIND_DELTA
        # Hub loses the session (restart/eviction): the publisher's
        # next push recovers inside ONE push_once via 409 -> FULL.
        hub.delta.evict(set())
        publish(30.0)
        publisher.push_once()
        assert publisher.resyncs_total == 1
        assert publisher.failures_total == 0
        assert publisher.last_frame_kind == delta.KIND_FULL
        hub.refresh_once()
        line = next(l for l in hub.registry.snapshot().render().splitlines()
                    if l.startswith("accelerator_duty_cycle"))
        assert line.endswith(" 30"), line
        # Hub gone entirely: failures count, telemetry keeps flowing by
        # pull (not exercised here), and nothing raises.
        server.stop()
        publish(40.0)
        publisher.push_once()
        assert publisher.failures_total == 1
    finally:
        publisher.stop()
        hub.stop()
        server.stop()


def _shed_hub_and_server():
    """Push-only hub whose DELTA bucket is effectively empty (FULLs
    still sail through — rate shedding never touches them), fronted by
    a real MetricsServer so the 429 + Retry-After rides real HTTP."""
    from kube_gpu_stats_tpu.exposition import MetricsServer

    hub = Hub([], targets_provider=lambda: [], interval=10.0,
              push_fence=1e9, ingest_lanes=1, ingest_delta_rate=1e-6)
    server = MetricsServer(hub.registry, host="127.0.0.1", port=0,
                           ingest_provider=hub.delta.handle)
    server.start()
    return hub, server


def _worker_registry():
    worker = Registry()

    def publish(duty: float) -> None:
        builder = SnapshotBuilder()
        labels = (("accel_type", "tpu-v5p"), ("chip", "0"),
                  ("device_path", "/dev/accel0"), ("uuid", ""))
        builder.add(schema.DEVICE_UP, 1.0, labels)
        builder.add(schema.DUTY_CYCLE, duty, labels)
        worker.publish(builder.build())

    return worker, publish


def test_publisher_honors_shed_as_its_own_retry_class():
    """ISSUE 12 satellite: a 429/503 + Retry-After is neither a
    failure (no backoff-interval scaling, no supervisor alarm) nor a
    resync (no FULL promotion — under shed that would AMPLIFY load).
    The publisher defers, then the next push re-diffs as a DELTA."""
    worker, publish = _worker_registry()
    publish(10.0)
    hub, server = _shed_hub_and_server()
    publisher = delta.DeltaPublisher(
        worker, f"http://127.0.0.1:{server.port}", source="node-a",
        rng=random.Random(7))
    try:
        publisher.push_once()  # session FULL: never rate-shed
        assert publisher.pushes_total == 1
        publish(20.0)
        publisher.push_once()  # DELTA: the empty bucket sheds it
        assert publisher.shed_honored_total == 1
        assert publisher.pushes_total == 1
        assert publisher.failures_total == 0
        assert publisher.resyncs_total == 0
        assert publisher.consecutive_failures == 0
        assert publisher._shed_until > time.monotonic()
        # While deferring, push_once is a no-op: no render, no POST.
        frames_before = hub.delta.stats()["delta_frames"]
        publish(30.0)
        publisher.push_once()
        assert hub.delta.stats()["delta_frames"] == frames_before
        assert publisher.shed_honored_total == 1
        # Pressure lifts (bucket removed) + the deferral window passes:
        # the next frame is a DELTA off the still-valid acked state —
        # never a FULL — and the seq chain continues unbroken.
        for lane in hub.delta._lanes:
            lane.bucket = None
        publisher._shed_until = 0.0
        publisher.push_once()
        assert publisher.pushes_total == 2
        assert publisher.last_frame_kind == delta.KIND_DELTA
        assert hub.delta.resyncs_total == 0
        hub.refresh_once()
        line = next(l for l in hub.registry.snapshot().render().splitlines()
                    if l.startswith("accelerator_duty_cycle"))
        assert line.endswith(" 30"), line
    finally:
        publisher.stop()
        server.stop()
        hub.stop()


def test_publisher_shed_backoff_spreads_with_decorrelated_jitter():
    """ISSUE 12 satellite pin: 8 publishers shed by one hub must NOT
    re-arrive in lockstep — each defers a decorrelated-jitter draw
    from [Retry-After, 3x] (the AWS recipe re-based on the hub's
    hint), so the spread across seeds is wide and deterministic."""
    worker, publish = _worker_registry()
    publish(10.0)
    hub, server = _shed_hub_and_server()
    publishers = [
        delta.DeltaPublisher(
            worker, f"http://127.0.0.1:{server.port}",
            source=f"node-{i}", rng=random.Random(i))
        for i in range(8)
    ]
    try:
        for publisher in publishers:
            publisher.push_once()
            assert publisher.pushes_total == 1
        publish(20.0)
        now = time.monotonic()
        delays = []
        for publisher in publishers:
            publisher.push_once()
            assert publisher.shed_honored_total == 1
            delays.append(publisher._shed_until - now)
        # The hub's hint is capped at 300s by retry_after_seconds (the
        # empty bucket quotes an absurd horizon); the first decorrelated
        # draw is uniform(base, 3*base) = [300, 900).
        assert all(299.0 < d < 901.0 for d in delays), delays
        assert max(delays) - min(delays) > 30.0, delays  # no lockstep
        assert len({round(d, 1) for d in delays}) == len(delays), delays
    finally:
        for publisher in publishers:
            publisher.stop()
        server.stop()
        hub.stop()


# --- the differential pin ---------------------------------------------------

_EXCLUDED_FAMILIES = (
    # Wall-clock-derived rates: both hubs compute them from their OWN
    # refresh timestamps, so they are equal in shape but not in digits.
    "slice_worker_steps_per_second",
    "slice_straggler_ratio",
    # Fetch wall time: the push hub never fetches (reports 0.0).
    "slice_target_fetch_seconds",
)


def _data_lines(rendered: str) -> list[str]:
    out = []
    for line in rendered.splitlines():
        if line.startswith(("accelerator_", "slice_")) and not \
                line.startswith(_EXCLUDED_FAMILIES):
            out.append(line)
    return out


def test_differential_delta_vs_pull_oracle_under_churn(tmp_path):
    """The acceptance pin: after randomized value churn, shape changes,
    worker restarts, dropped/duplicated frames and forced resyncs, the
    push hub's merged data series are byte-identical to a pull hub fed
    the same bodies."""
    rng = random.Random(1234)
    workers = 5
    paths = [tmp_path / f"w{i}.prom" for i in range(workers)]
    duties = [10.0 * (i + 1) for i in range(workers)]
    steps = [float(i) for i in range(workers)]
    extra = [False] * workers
    generations = [i + 1 for i in range(workers)]

    def body(i: int) -> str:
        return make_body(i, duties[i], steps=steps[i], extra_chip=extra[i])

    for i, path in enumerate(paths):
        path.write_text(body(i))

    oracle = Hub([str(p) for p in paths], interval=10.0,
                 delta_ingest=False)
    push = _push_hub()
    encoders = [delta.DeltaEncoder(str(paths[i]), generation=generations[i])
                for i in range(workers)]
    try:
        for encoder, path in zip(encoders, paths):
            assert _feed(push, encoder, path.read_text())[0] == 200
        oracle.refresh_once()
        push.refresh_once()
        for round_no in range(8):
            for i in range(workers):
                event = rng.random()
                if event < 0.5:
                    duties[i] += rng.choice([0.0, 1.0, 2.5])
                    steps[i] += rng.randint(0, 3)
                elif event < 0.65:
                    extra[i] = not extra[i]  # shape change -> FULL
                elif event < 0.75:
                    # Worker restart: counters reset, new generation.
                    generations[i] += 100
                    encoders[i] = delta.DeltaEncoder(
                        str(paths[i]), generation=generations[i])
                    steps[i] = 0.0
                paths[i].write_text(body(i))
                fault = rng.random()
                if fault < 0.15:
                    # Dropped frame: never delivered; encoder nacks.
                    # The push hub serves last-known state until the
                    # settle pass below recovers with a FULL — freshness
                    # lag by design, never corruption.
                    _feed(push, encoders[i], body(i), deliver=False)
                elif fault < 0.25:
                    # Duplicate delivery: second copy must 409 without
                    # corrupting state; encoder recovers via FULL.
                    wire, _ = encoders[i].encode_next(body(i))
                    code, _resp, _hdrs = push.delta.handle(wire)
                    if code == 200:
                        encoders[i].ack()
                        assert push.delta.handle(wire)[0] == 409
                    else:
                        encoders[i].nack()
                        assert _feed(push, encoders[i], body(i))[0] == 200
                else:
                    code, _resp = _feed(push, encoders[i], body(i))
                    if code == 409:  # e.g. after an earlier fault
                        assert _feed(push, encoders[i], body(i))[0] == 200
            # Settle pass: every session converges on the current body
            # (a dropped frame's nack makes this a FULL resync) — the
            # differential compares CONVERGED state, the protocol's
            # post-recovery guarantee.
            for i in range(workers):
                code, _resp = _feed(push, encoders[i], body(i))
                if code != 200:
                    assert _feed(push, encoders[i], body(i))[0] == 200
            oracle.refresh_once()
            push.refresh_once()
            oracle_lines = _data_lines(oracle.registry.snapshot().render())
            push_lines = _data_lines(push.registry.snapshot().render())
            assert oracle_lines == push_lines, (
                f"round {round_no}: delta-applied state diverged from "
                f"the pull oracle:\n"
                + "\n".join(l for l in oracle_lines if l not in push_lines)
                [:2000])
    finally:
        oracle.stop()
        push.stop()


def test_differential_includes_histograms_and_rates_shape(tmp_path):
    """Histogram merges ride the differential too: the step-duration
    family folded from pushed state equals the pull oracle's fold."""
    path = tmp_path / "w0.prom"
    path.write_text(make_body(0, 10.0, steps=7.0))
    oracle = Hub([str(path)], interval=10.0, delta_ingest=False)
    push = _push_hub()
    encoder = delta.DeltaEncoder(str(path), generation=1)
    try:
        assert _feed(push, encoder, path.read_text())[0] == 200
        oracle.refresh_once()
        push.refresh_once()
        path.write_text(make_body(0, 10.0, steps=9.0))
        assert _feed(push, encoder, path.read_text())[0] == 200
        oracle.refresh_once()
        push.refresh_once()

        def hist_lines(hub):
            return [l for l in hub.registry.snapshot().render().splitlines()
                    if l.startswith(schema.WORKLOAD_STEP_DURATION.name)]

        assert hist_lines(oracle) == hist_lines(push)
        assert hist_lines(push)  # the family actually merged
    finally:
        oracle.stop()
        push.stop()


# --- transport hardening (ISSUE 8 satellite) --------------------------------

def test_publisher_sends_auth_headers_and_handles_401(tmp_path):
    """End-to-end authed push: a publisher with the configured
    credentials lands frames behind the hub's basic-auth gate; bad (or
    missing) credentials get a clean 401 counted as an auth failure,
    never a crash or a silent drop."""
    import base64
    import hashlib

    from kube_gpu_stats_tpu.delta import push_headers_provider
    from kube_gpu_stats_tpu.exposition import MetricsServer

    worker = Registry()
    builder = SnapshotBuilder()
    builder.add(schema.DEVICE_UP, 1.0, (("chip", "0"),))
    worker.publish(builder.build())

    password_file = tmp_path / "hub-pass"
    password_file.write_text("hunter2\n")
    hub = _push_hub()
    server = MetricsServer(
        hub.registry, host="127.0.0.1", port=0,
        auth_username="pusher",
        auth_password_sha256=hashlib.sha256(b"hunter2").hexdigest(),
        ingest_provider=hub.delta.handle)
    server.start()
    url = f"http://127.0.0.1:{server.port}"
    good = delta.DeltaPublisher(
        worker, url, source="node-good",
        headers_provider=push_headers_provider("pusher",
                                               str(password_file)))
    bad = delta.DeltaPublisher(worker, url, source="node-bad")
    try:
        good.push_once()
        assert good.pushes_total == 1 and good.failures_total == 0
        bad.push_once()
        assert bad.pushes_total == 0
        assert bad.failures_total == 1
        assert bad.auth_failures_total == 1
        # Only the authed source holds a session.
        assert hub.delta.sources() == ["node-good"]
        # Rotation: the password file is re-read per push.
        password_file.write_text("rotated\n")
        good.push_once()
        assert good.auth_failures_total == 1  # old password now rejected
    finally:
        good.stop()
        bad.stop()
        server.stop()
        hub.stop()


def test_push_headers_provider_none_without_username():
    from kube_gpu_stats_tpu.delta import push_headers_provider

    assert push_headers_provider("", "") is None
    provider = push_headers_provider("u", "/nonexistent-password-file")
    # Unreadable file degrades to no header (the hub's 401 is the
    # visible failure), never a crash inside the push thread.
    assert provider() == {}


def test_publisher_https_tls_knobs_shape():
    """ca_file/insecure_tls reach the shared opener cache; a https URL
    with insecure_tls builds an opener whose HTTPS handler skips
    verification (the handshake itself needs a live TLS server, which
    the federation sim covers with real sockets for the authed hop)."""
    import ssl

    from kube_gpu_stats_tpu.validate import _opener

    publisher = delta.DeltaPublisher(
        Registry(), "https://hub.example:9401", source="n",
        insecure_tls=True)
    assert publisher._https and publisher._insecure_tls
    opener = _opener(True, "", True, True)
    https_handlers = [h for h in opener.handlers
                      if h.__class__.__name__ == "HTTPSHandler"]
    context = https_handlers[0]._context
    assert context.verify_mode == ssl.CERT_NONE
    publisher.stop()


# --- root-side slice dedup (ISSUE 8 satellite) ------------------------------

def test_federation_dup_slice_counted_and_journaled():
    """Two leaves sharing a slice label: first-wins drops the second
    leaf's rollups — the drop must be visible as kts_hub_dup_slice_total
    plus a delta_dup_slice journal event naming the slice."""
    from kube_gpu_stats_tpu.tracing import reset_log_marks

    reset_log_marks()
    hub = _push_hub(federate=True)
    try:
        leaf_a = delta.DeltaEncoder("leaf-a", generation=1)
        leaf_b = delta.DeltaEncoder("leaf-b", generation=2)
        assert _feed(hub, leaf_a, leaf_rollup_body())[0] == 200
        assert _feed(hub, leaf_b, leaf_rollup_body())[0] == 200
        hub.refresh_once()
        body = hub.registry.snapshot().render()
        # One copy of the colliding rollups survives (first leaf wins),
        # and the drop is counted.
        assert body.count('slice_chips{slice="s-a"}') == 1
        dup_line = next(l for l in body.splitlines()
                        if l.startswith("kts_hub_dup_slice_total"))
        assert float(dup_line.rsplit(" ", 1)[1]) == 4.0  # 4 shared series
        events = hub.tracer.events()["events"]
        dup_events = [e for e in events if e["kind"] == "delta_dup_slice"]
        # One event per colliding identity group: the 3 slice="s-a"
        # rollups, plus the target-labeled slice_target_up both leaves
        # re-exported.
        by_slice = {e["attrs"]["slice"]: e["attrs"]["dropped"]
                    for e in dup_events}
        assert by_slice["s-a"] == 3
        assert sum(by_slice.values()) == 4
    finally:
        hub.stop()


def test_dup_slice_absent_on_healthy_federation():
    hub = _push_hub(federate=True)
    try:
        leaf_a = delta.DeltaEncoder("leaf-a", generation=1)
        assert _feed(hub, leaf_a, leaf_rollup_body())[0] == 200
        hub.refresh_once()
        body = hub.registry.snapshot().render()
        assert "kts_hub_dup_slice_total 0" in body
        assert not [e for e in hub.tracer.events()["events"]
                    if e["kind"] == "delta_dup_slice"]
    finally:
        hub.stop()


def test_dup_slice_family_absent_on_non_federate_hub():
    hub = _push_hub()
    try:
        encoder = delta.DeltaEncoder("w0", generation=1)
        assert _feed(hub, encoder, make_body(0, 10.0))[0] == 200
        hub.refresh_once()
        assert "kts_hub_dup_slice_total" not in \
            hub.registry.snapshot().render()
    finally:
        hub.stop()


# --- push-aware fleet fetch signal (ISSUE 8 satellite) ----------------------

def test_frame_gap_tracked_per_session(monkeypatch):
    clock = {"t": 100.0}
    monkeypatch.setattr(time, "monotonic", lambda: clock["t"])
    hub = _push_hub()
    try:
        encoder = delta.DeltaEncoder("w0", generation=1)
        assert _feed(hub, encoder, make_body(0, 10.0))[0] == 200
        assert hub.delta.frame_gaps() == {"w0": 0.0}  # first frame: no gap
        clock["t"] = 101.5
        assert _feed(hub, encoder, make_body(0, 11.0))[0] == 200
        assert hub.delta.frame_gaps() == {"w0": 1.5}
    finally:
        hub.stop()


def test_fleet_lens_scores_frame_gap_for_push_targets(monkeypatch):
    """A push-served target's fetch signal is the delta-frame
    inter-arrival gap, not the pull path's 0.0 — a publisher falling
    behind its cadence moves the scored signal."""
    clock = {"t": 100.0}
    monkeypatch.setattr(time, "monotonic", lambda: clock["t"])
    hub = _push_hub(fleet_lens=True)
    try:
        encoder = delta.DeltaEncoder("w0", generation=1)
        assert _feed(hub, encoder, make_body(0, 10.0))[0] == 200
        clock["t"] = 102.0
        assert _feed(hub, encoder, make_body(0, 11.0))[0] == 200
        hub.refresh_once()
        state = hub.fleet.rollup()["targets"]["w0"]
        assert state["signals"]["fetch"]["value"] == 2.0
        # The exported slice_target_fetch_seconds stays 0.0: the HUB
        # paid no fetch — only the lens's freshness signal changes.
        body = hub.registry.snapshot().render()
        assert 'slice_target_fetch_seconds{target="w0"} 0' in body
    finally:
        hub.stop()


# -- version skew (ISSUE 14): versioned wire, hello negotiation, 426 ---------

def test_codec_v2_roundtrip_with_caps_and_build():
    body = make_body(1, 0.5)
    wire = delta.encode_full("src", 7, 0, body, proto=2,
                             caps=delta.CAP_BUILD_INFO, build="9.9.9")
    frame = delta.decode_frame(wire)
    assert (frame.proto, frame.caps, frame.build) == (
        2, delta.CAP_BUILD_INFO, "9.9.9")
    assert frame.body == body
    wire = delta.encode_delta("src", 7, 1, [(0, 1.5), (3, 2.5)],
                              proto=2, caps=delta.CAP_BUILD_INFO,
                              build="9.9.9")
    frame = delta.decode_frame(wire)
    assert frame.proto == 2 and frame.build == "9.9.9"
    assert frame.slots == (0, 3) and frame.values == (1.5, 2.5)


def test_codec_v1_frames_carry_no_extensions():
    """The v1 layout is byte-frozen: a capability build talking v1 is
    indistinguishable from an old build (that IS the downgrade)."""
    wire = delta.encode_full("src", 7, 0, "m 1\n", proto=1,
                             caps=delta.CAP_BUILD_INFO, build="9.9.9")
    frame = delta.decode_frame(wire)
    assert (frame.proto, frame.caps, frame.build) == (1, 0, "")


def test_codec_unknown_extension_tags_skipped_forward_tolerant():
    """A v2.x publisher may append blocks a v2.0 receiver never heard
    of: skipped whole by length, never an error."""
    from kube_gpu_stats_tpu import snappy

    wire = delta.encode_full("src", 7, 0, "m 1\n", proto=2,
                             caps=delta.CAP_BUILD_INFO, build="b1")
    raw = snappy.decompress(wire)
    raw += delta._varint(200) + delta._varint(4) + b"\x00\x01\x02\x03"
    frame = delta.decode_frame(snappy.compress(raw))
    assert frame.build == "b1" and frame.body == "m 1\n"
    # But a block lying about its length IS malformed.
    truncated = snappy.decompress(wire) + delta._varint(200) \
        + delta._varint(99) + b"zz"
    with pytest.raises(ValueError, match="truncated extension"):
        delta.decode_frame(snappy.compress(truncated))


def test_decode_out_of_range_version_is_distinct_skew_error():
    from kube_gpu_stats_tpu import snappy

    wire = delta.encode_full("src", 7, 0, "m 1\n")
    raw = bytearray(snappy.decompress(wire))
    raw[4] = 9
    with pytest.raises(delta.FrameVersionSkew) as exc:
        delta.decode_frame(snappy.compress(bytes(raw)))
    assert exc.value.version == 9
    assert isinstance(exc.value, ValueError)  # still catchable broadly


def test_ingest_answers_426_plus_hello_never_quarantine():
    """An out-of-range frame is a healthy peer from another rollout
    wave: 426 + this hub's advertised range, counted + journaled once,
    NEVER a malformed-frame quarantine strike."""
    from kube_gpu_stats_tpu import snappy
    from kube_gpu_stats_tpu.tracing import Tracer

    tracer = Tracer()
    hub = _push_hub(tracer=None)
    ingest = hub.delta
    ingest._tracer = tracer
    wire = delta.encode_full("src-future", 7, 0, "m 1\n")
    raw = bytearray(snappy.decompress(wire))
    raw[4] = 9
    future = snappy.compress(bytes(raw))
    for _ in range(3):
        code, body, headers = ingest.handle(future, peer="10.0.0.9")
        assert code == 426
        assert headers[delta.HELLO_PROTO_MIN] == str(delta.PROTO_MIN)
        assert headers[delta.HELLO_PROTO_MAX] == str(delta.PROTO_MAX)
        assert "Retry-After" in headers
    assert ingest.skew_refused_total == 3
    assert ingest.quarantined == 0  # not a hostile-frame strike
    status = ingest.skew_status()
    assert "10.0.0.9" in status["refused_peers"]
    assert status["refused_peers"]["10.0.0.9"]["version"] == 9
    events = [e for e in tracer.events()["events"]
              if e["kind"] == "skew_refused"]
    assert len(events) == 1  # journaled on first sight, not per frame


def test_ingest_window_refuses_decodable_but_gated_version():
    """--ingest-proto-min floor (census-gated rollout): a DECODABLE v1
    frame below the floor draws 426 keyed on the honest source name."""
    hub = _push_hub(ingest_proto_min=2)
    wire = delta.encode_full("http://old-node/metrics", 7, 0, "m 1\n",
                             proto=1)
    code, _body, headers = hub.delta.handle(wire)
    assert code == 426
    assert "http://old-node/metrics" in \
        hub.delta.skew_status()["refused_peers"]


def test_ingest_hello_rides_200_and_409():
    hub = _push_hub()
    encoder = delta.DeltaEncoder("src", generation=1)
    wire, _ = encoder.encode_next(make_body(0, 0.1))
    code, _body, headers = hub.delta.handle(wire)
    assert code == 200
    assert headers[delta.HELLO_PROTO_MAX] == str(delta.PROTO_MAX)
    # A delta with no session draws a 409 WITH the hello: the refused
    # peer renegotiates on the very response that triggers its FULL.
    orphan = delta.encode_delta("nobody", 3, 5, [(0, 1.0)])
    code, _body, headers = hub.delta.handle(orphan)
    assert code == 409
    assert delta.HELLO_PROTO_MAX in headers


def test_session_census_tracks_proto_caps_and_build():
    hub = _push_hub()
    v1 = delta.DeltaEncoder("old-node", generation=1)
    _feed(hub, v1, make_body(0, 0.1))
    v2 = delta.DeltaEncoder("new-node", generation=2, build="7.7.7")
    v2.set_wire(2, delta.CAP_BUILD_INFO)
    _feed(hub, v2, make_body(1, 0.2))
    census = hub.delta.fleet_versions()
    assert census == {"wire-v1": 1, "7.7.7": 1}
    status = hub.delta.skew_status()
    assert [row["source"] for row in status["downgraded_sessions"]] \
        == ["old-node"]


def test_encoder_announces_build_on_first_frame_after_upgrade():
    """The census must not wait for the next FULL: the first frame —
    even a DELTA — after set_wire carries the build extension, then
    stops paying the bytes."""
    hub = _push_hub()
    encoder = delta.DeltaEncoder("node", generation=1, build="8.8.8")
    _feed(hub, encoder, make_body(0, 0.1))  # v1 FULL opener
    assert hub.delta.fleet_versions() == {"wire-v1": 1}
    assert encoder.set_wire(2, delta.CAP_BUILD_INFO)
    wire, kind = encoder.encode_next(make_body(0, 0.2))
    assert kind == delta.KIND_DELTA
    frame = delta.decode_frame(wire)
    assert frame.build == "8.8.8"  # the announce-once delta
    code, _b, _h = hub.delta.handle(wire)
    assert code == 200
    encoder.ack()
    assert hub.delta.fleet_versions() == {"8.8.8": 1}
    # Announced and acked: the NEXT delta drops the extension bytes.
    wire, _ = encoder.encode_next(make_body(0, 0.3))
    assert delta.decode_frame(wire).build == ""
    assert hub.delta.handle(wire)[0] == 200
    encoder.ack()
    # A v1<->v2 mixed chain is legal: session state keys on (gen, seq).
    encoder.set_wire(1, 0)
    wire, _ = encoder.encode_next(make_body(0, 0.4))
    code, _b, _h = hub.delta.handle(wire)
    assert code == 200


def test_publisher_negotiates_up_off_hello_and_stays_within_cap():
    """End to end over real HTTP: opens at v1, the 200's hello raises
    the session to the common max; a capped publisher never leaves v1;
    a census-gated hub 426s the capped one and doctor names it."""
    from kube_gpu_stats_tpu.exposition import MetricsServer

    registry, publish = _worker_registry()
    hub = _push_hub()
    server = MetricsServer(registry=hub.registry, host="127.0.0.1",
                           port=0, ingest_provider=hub.delta.handle)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}"
        pub = delta.DeltaPublisher(registry, url, source="n1",
                                   min_interval=0.0, timeout=2.0)
        publish(0.1)
        pub.push_once()
        assert pub.negotiated_proto == delta.PROTO_MAX
        assert pub.proto_upgrades_total == 1
        capped = delta.DeltaPublisher(registry, url, source="n2",
                                      min_interval=0.0, timeout=2.0,
                                      proto_max=1)
        capped.push_once()
        assert capped.negotiated_proto == 1
        assert capped.skew_refused_total == 0
        status = pub.skew_status()
        assert status["hub"]["proto_max"] == delta.PROTO_MAX
        assert status["negotiated_proto"] == delta.PROTO_MAX
    finally:
        server.stop()


def test_publisher_refused_by_gated_hub_counts_and_defers():
    from kube_gpu_stats_tpu.exposition import MetricsServer

    registry, publish = _worker_registry()
    hub = _push_hub(ingest_proto_min=2)
    server = MetricsServer(registry=hub.registry, host="127.0.0.1",
                           port=0, ingest_provider=hub.delta.handle)
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}"
        pub = delta.DeltaPublisher(registry, url, source="n-old",
                                   min_interval=0.0, timeout=2.0,
                                   proto_max=1)
        publish(0.1)
        pub.push_once()
        assert pub.pushes_total == 0
        assert pub.skew_refused_total >= 1
        # Refused-not-failed: the diff base survived (defer), so when
        # the window opens the next frame needs no resync.
        assert pub.failures_total == 0
    finally:
        server.stop()


def test_checkpoint_v1_records_load_with_defaults(tmp_path):
    """Cross-version checkpoint (ISSUE 14 satellite): an old build's
    v1 file — 5-field session records, pruned keys — must warm-restore
    without a KeyError; the wire state defaults to unknown until the
    publisher's next frame."""
    import json as json_mod

    path = tmp_path / "ingest.json"
    path.write_text(json_mod.dumps({
        "version": 1,
        "seq": 3,
        "sessions": [
            ["old-src", 11, 4, 1, "m 1\n"],       # v1: five fields
            ["bad-record"],                        # tolerated: skipped
        ],
    }))
    hub = _push_hub(ingest_checkpoint=str(path))
    ingest = hub.delta
    assert ingest.checkpoint_loaded
    assert ingest.warm_restart_pending == 1
    # The v1 record replays: its DELTA applies with no resync.
    wire = delta.encode_delta("old-src", 11, 5, [])
    code, _b, _h = ingest.handle(wire)
    assert code == 200
    assert ingest.fleet_versions() == {"wire-v1": 1}


def test_checkpoint_roundtrips_session_wire_state(tmp_path):
    """A v2 checkpoint carries (proto, caps, build) so the census
    survives a hub restart."""
    path = tmp_path / "ingest.json"
    hub = _push_hub(ingest_checkpoint=str(path),
                    ingest_checkpoint_interval=0.0)
    encoder = delta.DeltaEncoder("node", generation=1, build="6.6.6")
    encoder.set_wire(2, delta.CAP_BUILD_INFO)
    wire, _ = encoder.encode_next("m 1\n")
    assert hub.delta.handle(wire)[0] == 200
    assert hub.delta.checkpoint(force=True)
    hub2 = _push_hub(ingest_checkpoint=str(path))
    hub2.delta.start_replay()
    deadline = time.monotonic() + 5.0
    while hub2.delta.warm_restart_pending and \
            time.monotonic() < deadline:
        time.sleep(0.01)
    assert hub2.delta.fleet_versions() == {"6.6.6": 1}


def test_skew_refusals_throttled_before_decode(monkeypatch):
    """From the SECOND refusal in a window, a skewed peer re-draws its
    426 from the record — no decompress. The first retry after a
    refusal always decodes (the publisher's in-push renegotiated frame
    may now be in range), and the window expires from the last DECODED
    refusal so an upgraded peer recovers within one window."""
    from kube_gpu_stats_tpu import snappy

    hub = _push_hub()
    ingest = hub.delta
    wire = delta.encode_full("src-future", 7, 0, "m 1\n")
    raw = bytearray(snappy.decompress(wire))
    raw[4] = 9
    future = snappy.compress(bytes(raw))
    assert ingest.handle(future, peer="10.0.0.9")[0] == 426
    # First retry decodes (the in-push recovery contract)...
    assert ingest.handle(future, peer="10.0.0.9")[0] == 426
    calls = []
    real = delta.decode_frame
    monkeypatch.setattr(delta, "decode_frame",
                        lambda w: calls.append(1) or real(w))
    # ...the third within the window comes off the record.
    assert ingest.handle(future, peer="10.0.0.9")[0] == 426
    assert calls == []  # throttled: dict lookup, no decode
    assert ingest.skew_refused_total == 3  # still counted honestly
    # A different (healthy) peer is never throttled.
    ok = delta.encode_full("src-ok", 7, 0, "m 1\n")
    assert ingest.handle(ok, peer="10.0.0.8")[0] == 200
    # Window expiry: age the record past the throttle and the frame
    # is decoded again (an upgraded peer recovers within one window).
    with ingest._skew_lock:
        ingest._skew_peers["10.0.0.9"]["last_wall"] -= \
            ingest.SKEW_THROTTLE_SECONDS + 1
    assert ingest.handle(wire, peer="10.0.0.9")[0] == 200
    assert calls  # decoded this time


def test_inpush_renegotiated_retry_not_throttled():
    """The publisher's renegotiated re-POST lands milliseconds after
    its 426 — the throttle must decode it (one-round-trip recovery),
    not replay the cached refusal."""
    hub = _push_hub(ingest_proto_min=2)
    v1 = delta.encode_full("src-roll", 7, 0, "m 1\n", proto=1)
    assert hub.delta.handle(v1, peer="10.0.0.7")[0] == 426
    v2 = delta.encode_full("src-roll", 7, 0, "m 1\n", proto=2)
    assert hub.delta.handle(v2, peer="10.0.0.7")[0] == 200


def test_census_clears_build_when_peer_rolls_back_to_v1():
    """A publisher rolled back to a pre-capability build must not stay
    listed under its new-build census entry (the operator could never
    confirm the rollback landed)."""
    hub = _push_hub()
    encoder = delta.DeltaEncoder("node", generation=1, build="9.9.9")
    encoder.set_wire(2, delta.CAP_BUILD_INFO)
    _feed(hub, encoder, make_body(0, 0.1))
    assert hub.delta.fleet_versions() == {"9.9.9": 1}
    # The rollback: an old build restarts with a new generation and
    # opens with a plain v1 FULL.
    old = delta.DeltaEncoder("node", generation=2)
    _feed(hub, old, make_body(0, 0.2))
    assert hub.delta.fleet_versions() == {"wire-v1": 1}


def test_spillq_reencode_counted_once_across_retried_drains(tmp_path):
    """reencoded_total counts DELIVERIES (commit), not peeks — a drain
    stalled on a down hub re-peeks the same head every probe cycle."""
    from kube_gpu_stats_tpu.spillq import SpillQueue

    q = SpillQueue(str(tmp_path / "spill"), fsync=False)
    q._ring.append(1.0, delta.encode_full("src", 9, 0, "m 7\n"))
    for _ in range(5):  # five failed drain cycles re-peek the head
        assert q.peek() == (1.0, "m 7\n")
    assert q.reencoded_total == 0
    q.commit()
    assert q.reencoded_total == 1
    q.close()


# --- native DELTA slot decode differential (ISSUE 17) -----------------------

def _decode_both(data: bytes):
    """decode_frame_raw once natively and once with the Python loop
    forced (the differential harness): (verdict, payload) pairs."""
    import pytest

    from kube_gpu_stats_tpu.native import load_delta_decode

    if load_delta_decode() is None:
        pytest.skip("wirefast extension not built")
    results = []
    saved = (delta._NATIVE_DECODE, delta._NATIVE_FRAME,
             delta._NATIVE_DECODE_LOADED)
    try:
        for native in (True, False):
            if native:
                (delta._NATIVE_DECODE, delta._NATIVE_FRAME,
                 delta._NATIVE_DECODE_LOADED) = saved
            else:
                delta._NATIVE_DECODE = None
                delta._NATIVE_FRAME = None
                delta._NATIVE_DECODE_LOADED = True
            try:
                frame = delta.decode_frame_raw(data)
            except ValueError as exc:
                results.append((type(exc).__name__, str(exc)))
            else:
                results.append(("ok", frame))
    finally:
        (delta._NATIVE_DECODE, delta._NATIVE_FRAME,
         delta._NATIVE_DECODE_LOADED) = saved
    return results


def test_native_decode_matches_python_loop_fuzz():
    """Randomized well-formed / truncated / corrupted DELTA frames must
    draw identical frames or identical error strings from the native
    slot walk and the inlined Python loop — including the varint-length
    and truncation verdicts the quarantine scoring keys on."""
    import struct as struct_mod

    rng = random.Random(0xDEC0DE)
    for trial in range(400):
        by_slot = {rng.randrange(0, 1 << rng.choice((4, 10, 20))):
                   rng.uniform(-1e9, 1e9)
                   for _ in range(rng.randrange(0, 30))}
        changes = sorted(by_slot.items())
        # Half the trials ride the v2 header (caps varint + trailing
        # build extension) so the whole-frame native decode's extension
        # walk differentials too, not just the v1 common case.
        if trial % 2:
            wire = delta.encode_delta(
                "w", 3, trial, changes, proto=2,
                caps=delta.CAP_BUILD_INFO,
                build=f"v9.{trial}" if trial % 4 == 1 else "")
        else:
            wire = delta.encode_delta("w", 3, trial, changes)
        raw = bytearray(delta.snappy.decompress(wire))
        mode = rng.random()
        if mode < 0.25 and len(raw) > 8:
            raw = raw[:rng.randrange(6, len(raw))]  # truncate
        elif mode < 0.5 and len(raw) > 8:
            raw[rng.randrange(6, len(raw))] ^= 1 << rng.randrange(8)
        native_result, python_result = _decode_both(bytes(raw))
        assert native_result[0] == python_result[0], (trial, native_result,
                                                      python_result)
        if native_result[0] == "ok":
            assert native_result[1] == python_result[1]
        else:
            assert native_result[1] == python_result[1]


def test_native_decode_adversarial_varints_match_python():
    """Hand-built adversarial tails: max-length varints, shift-63
    overflows ("varint too long"), giant gaps that punt the C walk back
    to Python (unbounded-int slots), truncated float windows."""
    header = delta.MAGIC + bytes([1, delta.KIND_DELTA])
    header += delta._varint(1) + b"w" + delta._varint(1) + delta._varint(0)

    def frame(count: int, tail: bytes) -> bytes:
        return header + delta._varint(count) + tail

    cases = [
        frame(1, b"\x80" * 10 + b"\x01" + b"\x00" * 8),  # shift > 63
        frame(1, b"\xff" * 9 + b"\x01" + b"\x00" * 8),   # 2^63-ish gap
        frame(1, b"\x7f" + b"\x00" * 7),                 # short value
        frame(2, b"\x01" + b"\x00" * 8 + b"\x80"),       # truncated varint
        frame(1, b""),                                   # empty tail
        frame(3, b"\x01" + b"\x00" * 8
              + b"\xfe\xff\xff\xff\xff\xff\xff\xff\x7f" + b"\x11" * 8
              + b"\x01" + b"\x22" * 8),                  # huge mid-gap
    ]
    for i, raw in enumerate(cases):
        native_result, python_result = _decode_both(raw)
        assert native_result == python_result, (i, native_result,
                                                python_result)
